"""Aggregator — exemplar-based dataset reduction.

Reference: hex/aggregator/Aggregator.java (SURVEY.md §2b C17): reduce a
frame to ~target_num_exemplars representative rows by single-pass
radius clustering — each row joins the first exemplar within a radius
(scaled per dimension) or becomes a new exemplar; exemplars carry
member counts. The output is the exemplar frame plus a `counts` column.

TPU design: distance evaluation is the hot op and runs on device — the
candidate batch × exemplar matrix distances are one [b,F]x[F,m] matmul
(MXU). Exemplar admission is inherently sequential, so the driver loop
is host-side over batches (like the reference's chunk loop), with the
radius adapted by bisection to land near the target exemplar count.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..frame import Frame, Vec
from .base import Model, resolve_x
from .datainfo import build_datainfo


@dataclass
class AggregatorParams:
    target_num_exemplars: int = 100
    rel_tol_num_exemplars: float = 0.5
    transform: str = "STANDARDIZE"
    seed: int = 0


@jax.jit
def _dist2(B, E):
    """Squared distances [b, cap] between batch rows and exemplars."""
    return ((B * B).sum(1)[:, None] - 2.0 * B @ E.T
            + (E * E).sum(1)[None, :])


def _pad_exemplars(E: np.ndarray, m: int) -> np.ndarray:
    """Pad the exemplar matrix to a power-of-two capacity so the jitted
    distance matmul sees a handful of shapes, not one per admission
    (padding rows sit at +inf → never the nearest exemplar)."""
    cap = 1
    while cap < m:
        cap *= 2
    if E.shape[0] == cap:
        return E
    pad = np.full((cap - E.shape[0], E.shape[1]), np.inf,
                  dtype=E.dtype)
    return np.concatenate([E, pad], axis=0)


def _aggregate(Xs: np.ndarray, radius2: float,
               batch: int = 4096) -> tuple[np.ndarray, np.ndarray]:
    """Single pass: returns (exemplar_row_indices, member_counts)."""
    n = Xs.shape[0]
    ex_idx: list[int] = [0]
    counts = np.ones(1, dtype=np.int64)
    E = Xs[0:1]
    i = 1
    while i < n:
        B = Xs[i: i + batch]
        Ep = _pad_exemplars(E, len(ex_idx))
        d2 = np.asarray(_dist2(jnp.asarray(B), jnp.asarray(Ep)))
        d2 = d2[:, : len(ex_idx)]
        near = d2.min(axis=1) <= radius2
        assign = d2.argmin(axis=1)
        # rows inside the radius of an existing exemplar join it; the
        # FIRST row outside becomes a new exemplar, then the batch is
        # re-examined against the grown set (sequential admission,
        # batched distance math)
        out = np.flatnonzero(~near)
        upto = out[0] if len(out) else len(B)
        np.add.at(counts, assign[:upto], 1)   # vectorized member tally
        if len(out):
            new = i + out[0]
            ex_idx.append(new)
            counts = np.append(counts, 1)
            E = np.concatenate([E, Xs[new: new + 1]], axis=0)
            i = new + 1
        else:
            i += len(B)
    return np.asarray(ex_idx), counts


class AggregatorModel(Model):
    algo = "aggregator"

    def __init__(self, data, params, frame, ex_idx, counts):
        super().__init__(data)
        self.params = params
        self._frame = frame
        self._ex_idx = ex_idx
        self._counts = counts
        self.nclasses = 1

    @property
    def aggregated_frame(self) -> Frame:
        out = self._frame.select_rows(self._ex_idx)
        out["counts"] = Vec.from_numpy(
            self._counts.astype(np.float32), "counts")
        return out

    def num_exemplars(self) -> int:
        return len(self._ex_idx)

    def _score_matrix(self, X):
        raise NotImplementedError("Aggregator has no predict; use "
                                  "aggregated_frame")


class Aggregator:
    """H2OAggregatorEstimator analog."""

    def __init__(self, **kw):
        from .cv import CVArgs

        CVArgs.pop(kw)
        self.params = AggregatorParams(**kw)

    def train(self, training_frame: Frame,
              x: Sequence[str] | None = None,
              ignored_columns: Sequence[str] | None = None,
              y: str | None = None) -> AggregatorModel:
        p = self.params
        if p.target_num_exemplars < 1:
            raise ValueError("target_num_exemplars must be >= 1")
        ignored = list(ignored_columns or [])
        if y is not None:
            ignored.append(y)
        data = resolve_x(training_frame, x, ignored)
        dinfo = build_datainfo(data, training_frame,
                               standardize=p.transform == "STANDARDIZE",
                               drop_first=False)
        Xe = np.asarray(dinfo.expand(data.X))[
            : training_frame.nrows, :-1]
        n, F = Xe.shape
        target = min(p.target_num_exemplars, n)
        lo_ok = max(1, int(target * (1 - p.rel_tol_num_exemplars)))
        hi_ok = int(np.ceil(target * (1 + p.rel_tol_num_exemplars)))

        # bisect the radius until the exemplar count lands in tolerance
        # (the reference adapts its radius_scale the same way)
        lo, hi = 0.0, float(4.0 * F)
        best, best_gap = None, np.inf
        for _ in range(20):
            mid = (lo + hi) / 2
            ex_idx, counts = _aggregate(Xe, mid)
            m = len(ex_idx)
            gap = abs(m - target)
            if gap < best_gap:          # keep the CLOSEST attempt, not
                best, best_gap = (ex_idx, counts), gap   # the last one
            if lo_ok <= m <= hi_ok:
                break
            if m > hi_ok:      # too many exemplars → widen the radius
                lo = mid
            else:
                hi = mid
        ex_idx, counts = best
        return AggregatorModel(data, p, training_frame, ex_idx, counts)
