"""CoxPH — Cox proportional hazards with Efron tie handling.

Reference: hex/coxph/CoxPH.java (SURVEY.md §2b C17): Newton-Raphson on
the partial log-likelihood, accumulating per-iteration sufficient
statistics (risk-set sums of w·exp(η), x·w·exp(η), xxᵀ·w·exp(η)) in an
MRTask over the chunks, Efron or Breslow approximation at tied event
times.

TPU design: rows are sorted by stop time ONCE on the host (the
reference keeps a time-ordered index too); the per-iteration risk-set
sums then become reverse cumulative sums over the time axis — one
jitted program per Newton step (cumsum + segment reductions on device),
with the [P,P] Hessian solved on device. The host loop is Newton (few
iterations), matching the reference's driver."""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..frame import Frame
from .base import Model, resolve_x


@dataclass
class CoxPHParams:
    stop_column: str = ""              # event/censoring time
    event_column: str = ""             # 1 = event, 0 = censored
    ties: str = "efron"                # efron | breslow
    max_iterations: int = 20
    tolerance: float = 1e-8
    seed: int = 0


@functools.partial(jax.jit, static_argnums=(3, 5))
def _cox_step(X, ev, grp, ngrp, beta, ties: str):
    """One Newton step's (loglik, gradient, Hessian).

    X: [n, P] time-DESCENDING covariates; ev: [n] event flag;
    grp: [n] tie-group id in the same order (0 = latest time).
    Risk set of group g = all rows with group id <= g's position, i.e.
    a plain prefix sum in the descending ordering.
    """
    eta = X @ beta
    mx = jnp.max(eta)
    r = jnp.exp(eta - mx)   # stabilized; ratios cancel it, the ll gets
    #                         the constant added back below
    # prefix sums over time-descending order = risk-set sums
    S0 = jnp.cumsum(r)
    S1 = jnp.cumsum(r[:, None] * X, axis=0)
    # event-only sums per tie group
    re = r * ev
    d_g = jax.ops.segment_sum(ev, grp, ngrp)            # events per group
    s0e_g = jax.ops.segment_sum(re, grp, ngrp)
    s1e_g = jax.ops.segment_sum(re[:, None] * X, grp, ngrp)
    xe_g = jax.ops.segment_sum(ev[:, None] * X, grp, ngrp)
    eta_e_g = jax.ops.segment_sum(ev * eta, grp, ngrp)
    # risk-set sums at each group's last row (prefix max index per group)
    last = jax.ops.segment_max(jnp.arange(X.shape[0]), grp, ngrp)
    S0_g = S0[last]
    S1_g = S1[last]

    # Efron's correction loops l = 0..d-1 over tied events; d is data-
    # dependent, so the scan runs to a static cap (train() validates)
    L_CAP = 32

    # S2 (the [P,P] risk-set second moment) — [n,P,P] cumsum; CoxPH's P
    # is small (the reference's use case too), so this stays modest
    P_ = X.shape[1]
    S2 = jnp.cumsum(r[:, None, None] * X[:, :, None] * X[:, None, :],
                    axis=0)
    S2_g = S2[last]
    s2e_g = jax.ops.segment_sum(
        re[:, None, None] * X[:, :, None] * X[:, None, :], grp, ngrp)

    def body2(carry, l_idx):
        ll_acc, g_acc, h_acc = carry
        d = d_g
        is_efron = 1.0 if ties == "efron" else 0.0
        frac = is_efron * jnp.where(d > 0, l_idx / jnp.maximum(d, 1.0),
                                    0.0)
        active = (l_idx < d) if ties == "efron" else \
            (l_idx < jnp.minimum(d, 1.0))
        # Breslow: one denominator per group, weighted by d events
        weight = jnp.where(active, 1.0, 0.0) if ties == "efron" else \
            jnp.where(active, d, 0.0)
        # inactive slots can drive phi0 to the clamp floor → inf terms;
        # weight 0 × inf = NaN, so mask BEFORE weighting
        phi0 = jnp.maximum(S0_g - frac * s0e_g, 1e-30)
        phi1 = S1_g - frac[:, None] * s1e_g
        phi2 = S2_g - frac[:, None, None] * s2e_g
        ll_acc += jnp.where(active, weight * -jnp.log(phi0), 0.0).sum()
        mean = jnp.where(active[:, None], phi1 / phi0[:, None], 0.0)
        g_acc += (weight[:, None] * -mean).sum(axis=0)
        h_term = jnp.where(active[:, None, None],
                           phi2 / phi0[:, None, None], 0.0) - \
            mean[:, :, None] * mean[:, None, :]
        h_acc += (weight[:, None, None] * h_term).sum(axis=0)
        return (ll_acc, g_acc, h_acc), None

    init = (jnp.float32(0.0), jnp.zeros(P_), jnp.zeros((P_, P_)))
    (ll_den, g_den, H), _ = jax.lax.scan(body2, init,
                                         jnp.arange(L_CAP, dtype=jnp.float32))
    # each of the Σd denominator terms carries a -mx from the scaling
    ll = eta_e_g.sum() + ll_den - mx * d_g.sum()
    grad = xe_g.sum(axis=0) + g_den
    return ll, grad, H


class CoxPHModel(Model):
    algo = "coxph"

    def __init__(self, data, params, dinfo, beta, names, loglik,
                 loglik_null, n_events):
        super().__init__(data)
        self.params = params
        self.dinfo = dinfo
        self.beta = beta
        self._names = names
        self.loglik = loglik
        self.loglik_null = loglik_null
        self.n_events = n_events
        self.nclasses = 1

    def coef(self) -> dict[str, float]:
        return dict(zip(self._names, np.asarray(self.beta,
                                                dtype=np.float64)))

    def hazard_ratios(self) -> dict[str, float]:
        return {k: float(np.exp(v)) for k, v in self.coef().items()}

    def _score_matrix(self, X):
        """Linear predictor (log partial hazard), the h2o predict."""
        Xe = self.dinfo.expand(X)[:, :-1]
        return Xe @ self.beta

    def concordance(self, frame: Frame) -> float:
        """Harrell's c-index on (stop, event) vs the risk score."""
        p = self.params
        risk = np.asarray(self.predict_raw(frame))[: frame.nrows]
        t = frame.vec(p.stop_column).to_numpy()
        e = frame.vec(p.event_column).to_numpy()
        conc = disc = 0
        ev_idx = np.flatnonzero(e > 0)
        for i in ev_idx:
            later = t > t[i]
            conc += int(np.sum(risk[i] > risk[later]))
            disc += int(np.sum(risk[i] < risk[later]))
        return conc / max(conc + disc, 1)


class CoxPH:
    """H2OCoxProportionalHazardsEstimator analog."""

    def __init__(self, **kw):
        from .cv import CVArgs

        CVArgs.pop(kw)
        self.params = CoxPHParams(**kw)

    def train(self, training_frame: Frame,
              x: Sequence[str] | None = None,
              ignored_columns: Sequence[str] | None = None,
              y: str | None = None) -> CoxPHModel:
        p = self.params
        if not p.stop_column or not p.event_column:
            raise ValueError("CoxPH needs stop_column and event_column")
        if p.ties not in ("efron", "breslow"):
            raise ValueError(f"unknown ties '{p.ties}'")
        ignored = list(ignored_columns or []) + [p.stop_column,
                                                p.event_column]
        data = resolve_x(training_frame, x, ignored)
        # categorical covariates one-hot expand through DataInfo (the
        # reference does the same in hex/coxph) — raw enum codes fitted
        # as a single slope would be meaningless
        from .datainfo import build_datainfo

        dinfo = build_datainfo(data, training_frame, standardize=False,
                               drop_first=True)
        t = training_frame.vec(p.stop_column).to_numpy().astype(np.float64)
        e = training_frame.vec(p.event_column).to_numpy().astype(np.float64)
        n = training_frame.nrows
        Xraw = np.asarray(data.X)[:n]
        ok = ~(np.isnan(t) | np.isnan(e) | np.isnan(Xraw).any(axis=1))
        t, e = t[ok], e[ok]
        Xe = np.asarray(dinfo.expand(
            jnp.asarray(Xraw[ok])))[:, :-1].astype(np.float64)
        X = Xe
        coef_names = dinfo.coef_names[:-1]
        # standardize for conditioning; de-standardize beta at the end
        mu, sd = X.mean(axis=0), X.std(axis=0) + 1e-12
        Xs = (X - mu) / sd
        order = np.argsort(-t, kind="stable")     # time-descending
        Xs, e_o, t_o = Xs[order], e[order], t[order]
        # tie groups on identical stop times (descending)
        grp = np.zeros(len(t_o), dtype=np.int32)
        if len(t_o) > 1:
            grp[1:] = np.cumsum(t_o[1:] != t_o[:-1])
        ngrp = int(grp.max()) + 1 if len(grp) else 1
        if e_o.sum() == 0:
            raise ValueError("no events in the training frame")
        d_max = int(np.bincount(grp[e_o > 0]).max()) if e_o.sum() else 1
        if d_max > 32 and p.ties == "efron":
            raise ValueError(
                f"{d_max} tied events exceed the Efron cap (32); use "
                "ties='breslow'")

        Xj = jnp.asarray(Xs, dtype=jnp.float32)
        ej = jnp.asarray(e_o, dtype=jnp.float32)
        gj = jnp.asarray(grp)
        P_ = Xj.shape[1]
        beta = jnp.zeros(P_)
        ll_prev = -np.inf
        ll0 = None
        for _ in range(p.max_iterations):
            ll, g, H = _cox_step(Xj, ej, gj, ngrp, beta, p.ties)
            if ll0 is None:
                ll0 = float(ll)   # beta starts at 0 → this IS the null
            delta = jnp.linalg.solve(H + 1e-8 * jnp.eye(P_), g)
            beta = beta + delta
            llf = float(ll)
            if abs(llf - ll_prev) < p.tolerance * (abs(llf) + 1e-10):
                break
            ll_prev = llf
        ll_final = float(_cox_step(Xj, ej, gj, ngrp, beta, p.ties)[0])
        beta_orig = np.asarray(beta, dtype=np.float64) / sd
        return CoxPHModel(data, p, dinfo,
                          jnp.asarray(beta_orig, dtype=jnp.float32),
                          coef_names, ll_final, ll0, int(e.sum()))
