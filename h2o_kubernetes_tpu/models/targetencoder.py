"""Target encoding — the H2OTargetEncoderEstimator analog.

Reference: ai/h2o/targetencoding/TargetEncoder* (h2o-automl) and
h2o-py's H2OTargetEncoderEstimator [U3]: replace a categorical column
with the per-level mean of the response, with three leakage-handling
modes (none / leave_one_out / k_fold), optional blending toward the
global prior (lambda = 1/(1+exp(-(n-k)/f))), and optional uniform
noise on training transforms.

TPU-first design: per-level (Σy, n) are dense [card] accumulators from
one segment-sum pass per column (the same doall shape as GroupBy);
fold-out statistics are the totals minus the fold's own accumulator, so
k_fold needs one [nfolds, card] segment-sum, not nfolds passes. The
transform is a device gather through the level→encoding table. This is
the reference's answer to high-cardinality categoricals (which
histogram binning rejects beyond 255 levels): encode first, then feed
the numeric column to any estimator.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Sequence

import numpy as np

from ..frame import Frame, Vec

__all__ = ["TargetEncoder"]

_MODES = ("none", "leave_one_out", "k_fold")


@dataclass
class TargetEncoderParams:
    data_leakage_handling: str = "none"   # see _MODES
    blending: bool = False
    inflection_point: float = 10.0        # k in lambda(n) = σ((n-k)/f)
    smoothing: float = 20.0               # f
    noise: float = 0.01                   # uniform(±noise) on as_training
    fold_column: str | None = None        # required for k_fold
    seed: int = 0


class TargetEncoderModel:
    """Fitted encoder: per-column level→encoding tables."""

    algo = "targetencoder"

    def __init__(self, params: TargetEncoderParams, y: str,
                 columns: list[str], prior: float,
                 tables: dict[str, dict]):
        self.params = params
        self.y = y
        self.columns = columns
        self.prior = prior
        # per column: {"domain": [...], "sum": [card], "cnt": [card],
        #              "fold_sum": [F, card]|None, "fold_cnt": ...}
        self.tables = tables

    def _encode(self, sums: np.ndarray, cnts: np.ndarray) -> np.ndarray:
        safe = np.maximum(cnts, 1.0)
        mean = sums / safe
        if self.params.blending:
            lam = 1.0 / (1.0 + np.exp(
                -(cnts - self.params.inflection_point)
                / max(self.params.smoothing, 1e-12)))
            enc = lam * mean + (1.0 - lam) * self.prior
        else:
            enc = mean
        return np.where(cnts > 0, enc, self.prior)

    def transform(self, frame: Frame, as_training: bool = False,
                  noise: float | None = None) -> Frame:
        """Return a frame with `<col>_te` columns appended.

        as_training=True applies the fitted leakage handling (fold-out /
        LOO statistics) plus noise; False (scoring, the default) uses
        the full-data encoding with no noise.
        """
        p = self.params
        rng = np.random.default_rng(p.seed)
        noise = p.noise if noise is None else noise
        out = Frame({n: frame.vec(n) for n in frame.names})
        mode = p.data_leakage_handling if as_training else "none"
        fold = None
        if mode == "k_fold":
            fv = frame.vec(p.fold_column).to_numpy()
            fold = np.nan_to_num(fv).astype(np.int64)
        yv = None
        if mode == "leave_one_out":
            if self.y not in frame.names:
                # silently falling back to full-data means would inject
                # exactly the leakage this mode exists to prevent
                raise ValueError(
                    "leave_one_out training transform needs the "
                    f"response column '{self.y}' in the frame")
            yraw = frame.vec(self.y)
            if yraw.is_enum():
                c = yraw.to_numpy()
                # NA codes -> NaN so the subtraction below skips them
                # (they were never counted in the fitted stats)
                yv = np.where(c < 0, np.nan,
                              (c == 1).astype(np.float64))
            else:
                yv = yraw.to_numpy().astype(np.float64)
        for col in self.columns:
            t = self.tables[col]
            v = frame.vec(col)
            codes = self._codes_for(v, t["domain"])
            sums = np.asarray(t["sum"], dtype=np.float64)
            cnts = np.asarray(t["cnt"], dtype=np.float64)
            if mode == "k_fold":
                fs = np.asarray(t["fold_sum"])
                fc = np.asarray(t["fold_cnt"])
                nf = fs.shape[0]
                fidx = np.clip(fold, 0, nf - 1)
                s_out = sums[None, :] - fs            # [F, card]
                c_out = cnts[None, :] - fc
                enc_tab = np.stack([self._encode(s_out[f], c_out[f])
                                    for f in range(nf)])  # [F, card]
                enc = enc_tab[fidx, np.maximum(codes, 0)]
            elif mode == "leave_one_out" and yv is not None:
                s_row = sums[np.maximum(codes, 0)]
                c_row = cnts[np.maximum(codes, 0)]
                ok = ~np.isnan(yv)
                s_loo = s_row - np.where(ok, yv, 0.0)
                c_loo = c_row - ok.astype(np.float64)
                enc = np.asarray(self._encode(s_loo, c_loo))
            else:
                enc_tab = self._encode(sums, cnts)
                enc = enc_tab[np.maximum(codes, 0)]
            enc = np.where(codes >= 0, enc, self.prior)
            if as_training and noise > 0:
                enc = enc + rng.uniform(-noise, noise, size=enc.shape)
            out[f"{col}_te"] = Vec.from_numpy(
                enc.astype(np.float32), f"{col}_te")
        return out

    @staticmethod
    def _codes_for(v: Vec, domain: list[str]) -> np.ndarray:
        """Map a column's codes onto the TRAINING domain (unseen → -1)."""
        if not v.is_enum():
            raise ValueError(f"'{v.name}' is not categorical")
        codes = v.to_numpy().astype(np.int64)
        if list(v.domain or []) == list(domain):
            return codes
        pos = {d: i for i, d in enumerate(domain)}
        lut = np.array([pos.get(d, -1) for d in (v.domain or [])] + [-1],
                       dtype=np.int64)
        return lut[np.where(codes < 0, len(lut) - 1, codes)]


class TargetEncoder:
    """H2OTargetEncoderEstimator analog (fit on train, then transform)."""

    def __init__(self, **kw):
        self.params = TargetEncoderParams(**kw)
        if self.params.data_leakage_handling not in _MODES:
            raise ValueError(
                f"unknown data_leakage_handling "
                f"'{self.params.data_leakage_handling}' "
                f"(supported: {', '.join(_MODES)})")

    def train(self, y: str, training_frame: Frame,
              x: Sequence[str] | None = None) -> TargetEncoderModel:
        p = self.params
        if p.data_leakage_handling == "k_fold" and not p.fold_column:
            raise ValueError("k_fold leakage handling needs fold_column")
        yv = training_frame.vec(y)
        if yv.is_enum():
            if yv.cardinality() != 2:
                raise ValueError("target encoding needs a numeric or "
                                 "binary response")
            yn = (yv.to_numpy() == 1).astype(np.float64)
            yna = yv.to_numpy() < 0
        else:
            raw = yv.to_numpy().astype(np.float64)
            yna = np.isnan(raw)
            yn = np.nan_to_num(raw)
        cols = list(x) if x is not None else [
            n for n in training_frame.names
            if n not in (y, p.fold_column)
            and training_frame.vec(n).is_enum()]
        if not cols:
            raise ValueError("no categorical columns to encode")
        ok = ~yna
        prior = float(yn[ok].mean()) if ok.any() else 0.0
        fold = None
        nf = 0
        if p.data_leakage_handling == "k_fold":
            fv = training_frame.vec(p.fold_column).to_numpy()
            fold = np.nan_to_num(fv).astype(np.int64)
            nf = int(fold.max()) + 1 if fold.size else 1
        tables: dict[str, dict] = {}
        for col in cols:
            v = training_frame.vec(col)
            if not v.is_enum():
                raise ValueError(f"column '{col}' is not categorical")
            card = v.cardinality()
            codes = v.to_numpy().astype(np.int64)
            live = ok & (codes >= 0)
            s = np.bincount(codes[live], weights=yn[live],
                            minlength=card).astype(np.float64)
            c = np.bincount(codes[live], minlength=card).astype(
                np.float64)
            t = {"domain": list(v.domain or []), "sum": s, "cnt": c,
                 "fold_sum": None, "fold_cnt": None}
            if fold is not None:
                flat = fold[live] * card + codes[live]
                fs = np.bincount(flat, weights=yn[live],
                                 minlength=nf * card)
                fc = np.bincount(flat, minlength=nf * card)
                t["fold_sum"] = fs.reshape(nf, card)
                t["fold_cnt"] = fc.reshape(nf, card).astype(np.float64)
            tables[col] = t
        return TargetEncoderModel(p, y, cols, prior, tables)
