from .drf import DRF, DRFModel
from .gbm import GBM, GBMModel, GBMParams
from .deeplearning import DeepLearning, DeepLearningModel
from .glm import GLM, GLMModel, GLMParams
from .word2vec import Word2Vec, Word2VecModel
from .xgboost import XGBoost, XGBoostModel

__all__ = ["DRF", "DRFModel", "DeepLearning", "DeepLearningModel",
           "GBM", "GBMModel", "GBMParams", "GLM", "GLMModel", "GLMParams",
           "Word2Vec", "Word2VecModel", "XGBoost", "XGBoostModel"]
