from .aggregator import Aggregator, AggregatorModel
from .coxph import CoxPH, CoxPHModel
from .drf import DRF, DRFModel
from .gbm import GBM, GBMModel, GBMParams
from .deeplearning import DeepLearning, DeepLearningModel
from .glm import GLM, GLMModel, GLMParams
from .glrm import GLRM, GLRMModel
from .isolationforest import IsolationForest, IsolationForestModel
from .kmeans import KMeans, KMeansModel
from .naivebayes import NaiveBayes, NaiveBayesModel
from .pca import PCA, PCAModel
from .stackedensemble import StackedEnsemble, StackedEnsembleModel
from .targetencoder import TargetEncoder, TargetEncoderModel
from .word2vec import Word2Vec, Word2VecModel
from .xgboost import XGBoost, XGBoostModel

__all__ = ["Aggregator", "AggregatorModel", "CoxPH", "CoxPHModel",
           "GLRM", "GLRMModel", "DRF", "DRFModel", "DeepLearning", "DeepLearningModel",
           "GBM", "GBMModel", "GBMParams", "GLM", "GLMModel", "GLMParams",
           "IsolationForest", "IsolationForestModel",
           "KMeans", "KMeansModel", "NaiveBayes", "NaiveBayesModel",
           "PCA", "PCAModel",
           "StackedEnsemble", "StackedEnsembleModel",
           "TargetEncoder", "TargetEncoderModel",
           "Word2Vec", "Word2VecModel", "XGBoost", "XGBoostModel"]
