from .drf import DRF, DRFModel
from .gbm import GBM, GBMModel, GBMParams

__all__ = ["DRF", "DRFModel", "GBM", "GBMModel", "GBMParams"]
