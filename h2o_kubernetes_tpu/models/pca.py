"""PCA — GramSVD over sharded rows.

Reference: hex/pca/PCA.java (SURVEY.md §2b C17), default method GramSVD:
an MRTask accumulates the Gram matrix XᵀX over all chunks (the same
pattern as GLM's Gram, SURVEY.md §3.5), the driver eigendecomposes it,
and scores are X·V. Transform options mirror the reference's
(NONE/DEMEAN/DESCALE/STANDARDIZE); categoricals one-hot via DataInfo.

TPU design: per-shard Gram is ONE [F,r]x[r,F] matmul on the MXU,
`psum` across shards, `eigh` on the replicated [F,F] result — a single
jitted call, no per-iteration traffic.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from ..frame import Frame
from ..runtime.mesh import ROWS, global_mesh
from .base import Model, resolve_x
from .datainfo import build_datainfo


@dataclass
class PCAParams:
    k: int = 3
    transform: str = "STANDARDIZE"   # NONE|DEMEAN|DESCALE|STANDARDIZE
    pca_method: str = "GramSVD"
    use_all_factor_levels: bool = False
    seed: int = 0


@functools.partial(jax.jit, static_argnums=(2,))
def _gram_psum(Xe, w, mesh):
    def body(xs, ws):
        xw = xs * ws[:, None]
        return (lax.psum(xs.T @ xw, ROWS),      # [F,F] MXU
                lax.psum(jnp.sum(ws), ROWS))

    return jax.shard_map(body, mesh=mesh, in_specs=(P(ROWS), P(ROWS)),
                         out_specs=(P(), P()))(Xe, w)


class PCAModel(Model):
    algo = "pca"

    def __init__(self, data, params, dinfo, eigvec, eigval, n_obs):
        super().__init__(data)
        self.params = params
        self.dinfo = dinfo
        self.eigenvectors = eigvec       # [F, k] (expanded space)
        self.eigenvalues = eigval        # [k] variances
        self.n_obs = n_obs
        self.nclasses = 1

    @property
    def std_deviation(self) -> np.ndarray:
        return np.sqrt(np.maximum(np.asarray(self.eigenvalues), 0.0))

    def pve(self) -> np.ndarray:
        """Proportion of variance explained per component."""
        ev = np.maximum(np.asarray(self.eigenvalues), 0.0)
        return ev / self._total_var

    def _score_matrix(self, X):
        Xe = self.dinfo.expand(X)[:, :-1]
        return Xe @ self.eigenvectors

    def predict(self, frame: Frame) -> Frame:
        out = self.predict_raw(frame)
        return Frame.from_arrays(
            {f"PC{i+1}": out[:, i] for i in range(out.shape[1])})

    def model_performance(self, frame=None, y=None) -> dict:
        return {"std_deviation": self.std_deviation.tolist(),
                "pve": self.pve().tolist()}


_TRANSFORM = {"NONE": (False, False), "DEMEAN": (True, False),
              "DESCALE": (False, True), "STANDARDIZE": (True, True)}


class PCA:
    """H2OPrincipalComponentAnalysisEstimator analog."""

    def __init__(self, **kw):
        from .cv import CVArgs

        CVArgs.pop(kw)
        self.params = PCAParams(**kw)

    def train(self, training_frame: Frame, x: Sequence[str] | None = None,
              ignored_columns: Sequence[str] | None = None,
              y: str | None = None) -> PCAModel:
        p = self.params
        t = p.transform.upper()
        if t not in _TRANSFORM:
            raise ValueError(f"unknown transform '{p.transform}'")
        demean, descale = _TRANSFORM[t]
        ignored = list(ignored_columns or [])
        if y is not None:
            ignored.append(y)
        data = resolve_x(training_frame, x, ignored)
        # DataInfo standardization = STANDARDIZE; for the other transforms
        # adjust the means/stds it would apply
        dinfo = build_datainfo(data, training_frame, standardize=descale,
                               drop_first=not p.use_all_factor_levels)
        if not demean:
            dinfo.means = np.zeros_like(dinfo.means)
        Xe = dinfo.expand(data.X)[:, :-1]
        F = Xe.shape[1]
        if p.k > F:
            raise ValueError(f"k={p.k} > {F} expanded features")

        mesh = global_mesh()
        G, n_obs = _gram_psum(Xe, data.w, mesh)
        # demean in Gram space when DEMEAN/STANDARDIZE: DataInfo already
        # centered numerics; one-hot cols keep their raw frequencies,
        # matching the reference (it also centers only numerics)
        vals, vecs = jnp.linalg.eigh(G / jnp.maximum(n_obs - 1.0, 1.0))
        order = jnp.argsort(-vals)
        vals = vals[order][: p.k]
        vecs = vecs[:, order][:, : p.k]
        # sign convention: largest-|loading| coordinate positive
        sign = jnp.sign(vecs[jnp.argmax(jnp.abs(vecs), axis=0),
                             jnp.arange(p.k)])
        vecs = vecs * sign[None, :]

        model = PCAModel(data, p, dinfo, vecs, vals, float(n_obs))
        model._total_var = float(jnp.trace(G) /
                                 jnp.maximum(n_obs - 1.0, 1.0))
        model.cv = None
        return model
