"""DataInfo — design-matrix expansion shared by GLM and DeepLearning.

Analog of hex/DataInfo.java (SURVEY.md §2b C11): numeric features are
mean-imputed and optionally standardized; categorical features expand to
one-hot (optional NA level; drop-first for unpenalized identifiability);
an intercept/bias column is appended last.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ..frame import Frame
from .base import TrainData


@functools.partial(jax.jit, static_argnums=(3, 4, 5))
def _expand_jit(X, means, stds, numeric_idx: tuple,
                enum_specs: tuple, drop_first: bool):
    """Pure expansion kernel, cached at MODULE level: a per-train
    ``jax.jit(dinfo.expand)`` would key the jit cache on the fresh
    bound-method object and recompile on EVERY train() call — AutoML
    and CV pay that once per model (measured: the only warm-train
    recompile left). Same schema + shape now hits the cache."""
    cols = []
    for j, i in enumerate(numeric_idx):
        c = X[:, i]
        c = jnp.where(jnp.isnan(c), means[j], c)    # mean imputation
        cols.append((c - means[j]) / stds[j])
    out = [jnp.stack(cols, axis=1)] if cols else []
    for (i, L, has_na, mode) in enum_specs:
        c = X[:, i]
        code = jnp.where(jnp.isnan(c), L, c).astype(jnp.int32)
        if not has_na:
            # no NA level was trained: impute NA/unseen to the modal
            # level (the categorical analog of numeric mean-imputation)
            # rather than silently encoding as the dropped base level
            code = jnp.where(code >= L, mode, code)
        lo = 1 if drop_first else 0
        width = L - lo + (1 if has_na else 0)
        levels = jnp.arange(lo, lo + width)
        out.append((code[:, None] == levels[None, :]).astype(jnp.float32))
    ones = jnp.ones((X.shape[0], 1), dtype=jnp.float32)
    out.append(ones)                       # intercept last
    return jnp.concatenate(out, axis=1)


# -- DataInfo: design-matrix expansion --------------------------------------

@dataclass
class DataInfo:
    """Expanded design layout (analog of hex/DataInfo.java)."""

    coef_names: list[str]
    numeric_idx: list[int]            # columns of X that are numeric
    # per enum: (X col, n_levels, has_na, mode_level)
    enum_specs: list[tuple[int, int, bool, int]]
    means: np.ndarray                 # per expanded col (standardization)
    stds: np.ndarray
    n_expanded: int
    drop_first: bool

    def expand(self, X: jax.Array) -> jax.Array:
        """[R, F] raw matrix → [R, P] standardized expanded matrix."""
        return _expand_jit(X, jnp.asarray(self.means),
                           jnp.asarray(self.stds),
                           tuple(self.numeric_idx),
                           tuple(tuple(s) for s in self.enum_specs),
                           self.drop_first)


def build_datainfo(data: TrainData, frame: Frame, standardize: bool,
                   drop_first: bool) -> DataInfo:
    numeric_idx, enum_specs, coef_names = [], [], []
    means, stds = [], []
    for i, name in enumerate(data.feature_names):
        dom = data.feature_domains.get(name)
        if dom is None:
            numeric_idx.append(i)
            r = frame.vec(name).rollups()
            mu = 0.0 if np.isnan(r["mean"]) else r["mean"]
            sd = r["sigma"] if standardize and r["sigma"] > 0 else 1.0
            means.append(mu)
            stds.append(sd)
            coef_names.append(name)
    for i, name in enumerate(data.feature_names):
        dom = data.feature_domains.get(name)
        if dom is not None:
            has_na = frame.vec(name).nacnt() > 0
            L = len(dom)
            codes = frame.vec(name).to_numpy()
            mode = int(np.bincount(codes[codes >= 0],
                                   minlength=L).argmax()) if L else 0
            enum_specs.append((i, L, has_na, mode))
            lo = 1 if drop_first else 0
            coef_names += [f"{name}.{d}" for d in dom[lo:]]
            if has_na:
                coef_names.append(f"{name}.missing(NA)")
    coef_names.append("Intercept")
    n_expanded = len(coef_names)
    return DataInfo(coef_names, numeric_idx, enum_specs,
                    np.array(means, dtype=np.float32),
                    np.array(stds, dtype=np.float32),
                    n_expanded, drop_first)


