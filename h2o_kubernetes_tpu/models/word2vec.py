"""Word2Vec — skip-gram embeddings with model-averaging allreduce.

Reference: hex/word2vec (SURVEY.md §2b C13): skip-gram trained by
per-node SGD over local text with periodic weight averaging across the
cluster (the same parameter-averaging pattern as DeepLearning). The
reference optimizes with hierarchical softmax; here we use negative
sampling — the accelerator-standard equivalent objective (HS descends a
per-word Huffman path, which is sequential and branchy; NS is two
matmul-shaped gathers + a sigmoid, i.e. MXU work). Corpus positions
shard over the ROWS axis; every iteration ends in `psum(params)/n`.

Input convention (as the reference): a Frame with ONE string/enum
column of words, sentences separated by NA rows.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from ..frame import Frame
from ..runtime.mesh import ROWS, global_mesh, n_row_shards
from ..runtime.mrtask import shard_rows


@dataclass
class Word2VecParams:
    vec_size: int = 100
    window_size: int = 5
    min_word_freq: int = 5
    negative_samples: int = 5
    epochs: int = 5
    init_learning_rate: float = 0.025
    batch_per_shard: int = 8192
    seed: int = 0


def _w2v_loss(params, centers, contexts, negs, valid):
    Win, Wout = params
    v = Win[centers]                      # [B, D]
    u = Wout[contexts]                    # [B, D]
    un = Wout[negs]                       # [B, k, D]
    pos = jax.nn.log_sigmoid(jnp.sum(v * u, axis=1))
    neg = jnp.sum(jax.nn.log_sigmoid(
        -jnp.einsum("bd,bkd->bk", v, un)), axis=1)
    return -jnp.sum(valid * (pos + neg)) / (jnp.sum(valid) + 1e-9)


_w2v_grad = jax.grad(_w2v_loss)


def _w2v_local_epoch(params, corp, sent, ns_cdf, key, lr, *,
                     batch, window, k_neg, steps, n_shards):
    """One epoch of per-shard SGD steps, ending in the model-averaging
    psum (the reference's per-node train + periodic averaging)."""
    key = jax.random.fold_in(key, lax.axis_index(ROWS))
    L = corp.shape[0]

    def step(params, k):
        kc, ko, kn = jax.random.split(k, 3)
        ci = jax.random.randint(kc, (batch,), 0, L)
        off = jax.random.randint(ko, (batch,), 1, window + 1)
        sign = jax.random.bernoulli(kn, 0.5, (batch,))
        oi = jnp.clip(ci + jnp.where(sign, off, -off), 0, L - 1)
        centers = corp[ci]
        contexts = corp[oi]
        valid = (centers >= 0) & (contexts >= 0) & \
            (sent[ci] == sent[oi]) & (ci != oi)
        kneg = jax.random.fold_in(kn, 1)
        # inverse-CDF draw from the unigram^0.75 table: O(B·k·log V).
        # (jax.random.categorical materializes a [B, k, V] Gumbel
        # tensor — at V=2000 that is 10M floats PER STEP and was ~95%
        # of the r04 word2vec wall; word2vec's classic unigram-table
        # lookup is exactly this inverse-CDF, just discretized)
        u = jax.random.uniform(kneg, (batch, k_neg))
        negs = jnp.searchsorted(ns_cdf, u).astype(jnp.int32)
        g = _w2v_grad(params, jnp.maximum(centers, 0),
                      jnp.maximum(contexts, 0), negs,
                      valid.astype(jnp.float32))
        params = jax.tree.map(lambda a, b: a - lr * b, params, g)
        return params, None

    keys = jax.random.split(key, steps)
    params, _ = lax.scan(step, params, keys)
    return jax.tree.map(lambda a: lax.psum(a, ROWS) / n_shards, params)


@functools.partial(
    jax.jit, donate_argnums=(0,),
    static_argnames=("batch", "window", "k_neg", "steps", "n_shards",
                     "mesh"))
def _w2v_train(params, corpus_dev, sent_dev, ns_cdf, key, lrs, *,
               batch, window, k_neg, steps, n_shards, mesh):
    """The WHOLE training run in one compiled dispatch: scan over
    epochs (each its own lr from the [E] schedule) of shard-mapped
    local SGD + averaging. Module-level jit: a second train() with the
    same shapes compiles NOTHING — the round-4 suite measured 279
    tokens/s because the per-call jit recompiled the scan inside the
    timed call."""
    epoch = jax.shard_map(
        functools.partial(_w2v_local_epoch, batch=batch, window=window,
                          k_neg=k_neg, steps=steps, n_shards=n_shards),
        mesh=mesh,
        in_specs=(P(), P(ROWS), P(ROWS), P(), P(), P()),
        out_specs=P())

    def body(params, klr):
        k, lr = klr
        return epoch(params, corpus_dev, sent_dev, ns_cdf, k, lr), None

    E = lrs.shape[0]
    keys = jax.random.split(key, E)
    params, _ = lax.scan(body, params, (keys, lrs))
    return params


class Word2VecModel:
    algo = "word2vec"

    def __init__(self, params: Word2VecParams, vocab: list[str],
                 counts: np.ndarray, W: np.ndarray):
        self.params = params
        self.vocab = vocab
        self.word_index = {w: i for i, w in enumerate(vocab)}
        self.counts = counts
        self.W = W                    # [V, D] input embeddings

    def find_synonyms(self, word: str, count: int = 10) -> dict[str, float]:
        i = self.word_index.get(word)
        if i is None:
            return {}
        Wn = self.W / (np.linalg.norm(self.W, axis=1, keepdims=True) + 1e-9)
        sims = Wn @ Wn[i]
        order = np.argsort(-sims)
        out = {}
        for j in order:
            if j == i:
                continue
            out[self.vocab[j]] = float(sims[j])
            if len(out) >= count:
                break
        return out

    def to_frame(self) -> Frame:
        cols = {"Word": np.array(self.vocab)}
        for d in range(self.W.shape[1]):
            cols[f"V{d + 1}"] = self.W[:, d]
        return Frame.from_arrays(cols)

    def transform(self, words_frame: Frame,
                  aggregate_method: str = "NONE") -> np.ndarray:
        """Map words to vectors; AVERAGE pools per NA-separated sentence."""
        col = words_frame.vec(words_frame.names[0])
        codes = col.to_numpy()
        dom = col.domain or []
        remap = np.array([self.word_index.get(w, -1) for w in dom] + [-1],
                         dtype=np.int64)
        idx = remap[np.where(codes < 0, len(dom), codes)]
        vecs = np.where((idx >= 0)[:, None],
                        self.W[np.maximum(idx, 0)], np.nan)
        if aggregate_method.upper() == "NONE":
            return vecs
        # AVERAGE: sentences delimited by NA rows
        sent_id = np.cumsum(codes < 0)
        out = []
        for s in np.unique(sent_id[codes >= 0]):
            rows = vecs[(sent_id == s) & (codes >= 0) & (idx >= 0)]
            out.append(rows.mean(axis=0) if len(rows) else
                       np.full(self.W.shape[1], np.nan))
        return np.stack(out) if out else np.empty((0, self.W.shape[1]))


class Word2Vec:
    """H2OWord2vecEstimator analog."""

    def __init__(self, **kw):
        self.params = Word2VecParams(**kw)

    def train(self, training_frame: Frame) -> Word2VecModel:
        p = self.params
        mesh = global_mesh()
        n_shards = n_row_shards(mesh)

        col = training_frame.vec(training_frame.names[0])
        if not col.is_enum():
            raise ValueError("word2vec needs a single string/enum column")
        codes = col.to_numpy()
        dom = list(col.domain)

        # vocab: words with freq >= min_word_freq, ordered by frequency
        freq = np.bincount(codes[codes >= 0], minlength=len(dom))
        keep = np.where(freq >= p.min_word_freq)[0]
        keep = keep[np.argsort(-freq[keep])]
        vocab = [dom[i] for i in keep]
        V = len(vocab)
        if V == 0:
            raise ValueError("no words meet min_word_freq")
        remap = np.full(len(dom) + 1, -1, dtype=np.int32)
        remap[keep] = np.arange(V, dtype=np.int32)
        corpus = remap[np.where(codes < 0, len(dom), codes)]
        sent_id = np.cumsum(codes < 0).astype(np.int32)
        counts = freq[keep].astype(np.float64)

        # negative-sampling distribution: unigram^0.75, as a cumulative
        # table for inverse-CDF draws
        pw = counts ** 0.75
        ns_cdf = jnp.asarray(np.cumsum(pw / pw.sum()), dtype=jnp.float32)

        corpus_dev = shard_rows(corpus.astype(np.int32), pad_value=-1)
        sent_dev = shard_rows(sent_id, pad_value=-2)
        n_pos = len(corpus)
        D, W_len = p.vec_size, p.window_size

        key = jax.random.key(p.seed)
        key, k1, k2 = jax.random.split(key, 3)
        Win = jax.random.uniform(k1, (V, D), minval=-0.5 / D,
                                 maxval=0.5 / D)
        Wout = jnp.zeros((V, D))

        # batch capped by the per-shard corpus: a big batch on a small
        # corpus collapses an epoch into one SGD update and the
        # embeddings stop converging — small data keeps many small
        # steps, big data gets the wide dispatch-amortizing batches
        batch = int(min(p.batch_per_shard,
                        max(512, n_pos // max(n_shards, 1))))
        # one epoch ≈ every (center, one-of-2W contexts) pair seen once
        steps_per_iter = max(
            1, n_pos * 2 * W_len // (batch * n_shards))
        lrs = jnp.asarray(
            [p.init_learning_rate * max(1.0 - e / p.epochs, 1e-3)
             for e in range(p.epochs)], dtype=jnp.float32)

        params = _w2v_train(
            (Win, Wout), corpus_dev, sent_dev, ns_cdf, key, lrs,
            batch=batch, window=W_len,
            k_neg=p.negative_samples, steps=steps_per_iter,
            n_shards=n_shards, mesh=mesh)

        return Word2VecModel(p, vocab, counts,
                             np.asarray(params[0], dtype=np.float32))
