"""GBM — gradient boosting with the shared histogram tree core.

Reference behavior: hex/tree/gbm/GBM.java driving SharedTree
(SURVEY.md §3.4): per tree, score-and-update residuals, then per level
one full-cluster histogram MRTask + split finding. Here the whole tree
builds in one jitted shard_map (models/tree/core.py); the outer loop
over trees is host-side Python, as in the reference's Driver.

Distributions (hex/genmodel DistributionFamily analogs):
  gaussian     g = f - y,            h = 1
  bernoulli    g = p - y,            h = p(1-p)       (logit link)
  multinomial  K trees/iter, softmax gradient
  poisson      g = exp(f) - y,       h = exp(f)        (log link)
  gamma        g = 1 - y·exp(-f),    h = y·exp(-f)      (log link)
  tweedie      compound-poisson deviance at power 1.5   (log link)
  laplace      g = sign(f - y),      h = 1              (L1 loss)
"""

from __future__ import annotations

import contextlib
import functools
import os
from dataclasses import dataclass, field
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..frame import Frame
from ..runtime.health import device_dispatch, require_healthy
from ..runtime.mesh import global_mesh
from .base import Model, TrainData, resolve_xy
from .tree.binning import (BinSpec, apply_bins, apply_bins_jit, fit_bins,
                           fused_binning_enabled, fused_fit_bins)
from .tree.core import (BoostParams, FlatTrees, Tree, TreeParams,
                        _grad_hess, boost_trees, boost_trees_drf,
                        boost_trees_multi, descend_tree, drf_group_size,
                        flat_margin, flatten_cover, flatten_trees,
                        goss_round_keys, predict_tree)


@dataclass
class GBMParams:
    ntrees: int = 50
    max_depth: int = 5
    learn_rate: float = 0.1
    min_rows: float = 10.0
    nbins: int = 256
    sample_rate: float = 1.0
    col_sample_rate_per_tree: float = 1.0
    mtries: int = -1                     # per-node feature sampling (DRF)
    distribution: str = "auto"
    reg_lambda: float = 0.0
    reg_alpha: float = 0.0
    min_child_weight: float = 0.0        # XGBoost-style hessian-mass floor
    min_split_improvement: float = 1e-5  # H2O default
    seed: int = 0
    score_every: int = 0                 # 0 = score only at end
    # continue training from a previous model (reference SharedTree
    # checkpoint semantics, SURVEY.md §5.4): ntrees is the TOTAL count
    checkpoint: object = None
    # histogram kernel selection (ops/histogram: auto|segment|pallas)
    _hist_impl: str = "auto"
    # DRF mode: no shrinkage on margins, trees vote/average
    _drf_mode: bool = False


# module-level jitted transforms: a fresh jax.jit per call would
# retrace every scoring event (the jit-inside-a-loop antipattern), and
# an eager sharded op risks the XLA:CPU rendezvous flake
_jit_sigmoid = jax.jit(jax.nn.sigmoid)
_jit_softmax = jax.jit(functools.partial(jax.nn.softmax, axis=1))
_jit_exp = jax.jit(jnp.exp)
_jit_min_pos = jax.jit(
    lambda y, w: jnp.nanmin(jnp.where(w > 0, y, jnp.inf)))
# max histogram work units (rows·F·nbins·2^depth summed over a chunk's
# trees) per compiled dispatch — see the chunking comment in train()
_DISPATCH_BUDGET = 3e12

# h ≡ 1 losses accumulate 2-channel histograms (1/3 fewer MXU passes +
# smaller psums) — the ONE membership list _make_tree_params keys on
_UNIT_HESS_DISTS = ("gaussian", "laplace", "quantile", "huber")


def _make_tree_params(p: "GBMParams", distribution: str) -> TreeParams:
    """GBMParams + resolved distribution -> the TreeParams the boost
    dispatch is traced with — shared by train() and compile-ahead
    (compile_ahead_lowerings), so a pre-lowered executable's static
    config cannot drift from the one train() dispatches."""
    return TreeParams(max_depth=p.max_depth, n_bins=p.nbins,
                      min_rows=p.min_rows, reg_lambda=p.reg_lambda,
                      reg_alpha=p.reg_alpha,
                      gamma=p.min_split_improvement, mtries=p.mtries,
                      min_child_weight=p.min_child_weight,
                      hist_impl=p._hist_impl,
                      unit_hess=(p._drf_mode or
                                 distribution in _UNIT_HESS_DISTS))


def goss_params(p: "GBMParams", distribution: str) -> tuple[float, float]:
    """(top_a, rand_b) of GOSS gradient-based one-side sampling
    (arXiv:1809.04559) — (0.0, 0.0) when off. THE one env reader:
    H2O_TPU_GOSS=1 activates it for the boosted-tree growers (GBM +
    XGBoost-hist pointwise objectives); DRF stays bagged/unsampled and
    the lambdarank host loop is excluded. Knobs are read at train
    time, so AutoML plan entries and CV folds inherit them uniformly."""
    if p._drf_mode or distribution.startswith("rank:"):
        return 0.0, 0.0
    if os.environ.get("H2O_TPU_GOSS", "0") != "1":
        return 0.0, 0.0
    a = float(os.environ.get("H2O_TPU_GOSS_TOP_A", "0.1"))
    b = float(os.environ.get("H2O_TPU_GOSS_RAND_B", "0.1"))
    if not (0.0 <= a < 1.0 and 0.0 < b <= 1.0 and a + b <= 1.0):
        raise ValueError(
            f"bad GOSS knobs: H2O_TPU_GOSS_TOP_A={a} / "
            f"H2O_TPU_GOSS_RAND_B={b} — need 0 <= a < 1, 0 < b, "
            "a + b <= 1")
    return a, b


def _make_boost_params(p: "GBMParams", distribution: str) -> BoostParams:
    """The BoostParams twin of _make_tree_params (same no-drift rule)."""
    goss_a, goss_b = goss_params(p, distribution)
    return BoostParams(
        distribution=distribution,
        learn_rate=1.0 if p._drf_mode else p.learn_rate,
        sample_rate=p.sample_rate,
        col_sample_rate_per_tree=p.col_sample_rate_per_tree,
        drf_mode=p._drf_mode,
        goss_a=goss_a, goss_b=goss_b)


def _chunk_sizes(p: "GBMParams", padded: int, F: int, K: int,
                 start_t: int = 0) -> list[int]:
    """Tree counts of the compiled dispatches the in-HBM boost loop
    will issue — shared by _boost_in_hbm and compile-ahead, so the
    pre-lowered key shapes match the dispatched ones exactly."""
    per_round = padded * max(F, 1) * p.nbins * (2 ** p.max_depth) * K
    budget_chunk = max(1, int(_DISPATCH_BUDGET // per_round))
    score = p.score_every if (p.score_every and not p._drf_mode) else 0
    out: list[int] = []
    t = start_t
    while t < p.ntrees:
        n = min(budget_chunk, p.ntrees - t)
        if score:
            # stop at score boundaries, but never let the budget
            # densify the scoring cadence (each scoring event is
            # a blocking host sync)
            n = min(n, score - (t - start_t) % score)
        out.append(n)
        t += n
    return out


@functools.partial(jax.jit, static_argnums=(3, 4))
def _init_margin(y, w, off, dist: str, K: int):
    """(init score, starting margin) fully ON DEVICE — the round-2 path
    transferred the prior sums to the host before the first boost
    dispatch, a blocking tunnel round trip per train() that AutoML pays
    per model. The host reads `init` back only after the boosting
    chunks are enqueued. Pad/NA rows carry y=0, w=0 (resolve_xy).

    ``off`` is the per-row offset margin (zeros when none): the margin
    starts at init + off and the init prior is the intercept MLE GIVEN
    the offset (hex/tree/gbm GBM getInitialValue solves the same
    offset-aware prior [U3]) — closed form for gaussian/poisson/gamma,
    3 Newton steps from logit(ȳ) for bernoulli."""
    w_sum = jnp.sum(w)
    if dist == "bernoulli":
        p1 = jnp.clip(jnp.sum(y * w) / w_sum, 1e-6, 1 - 1e-6)
        init0 = jnp.log(p1 / (1 - p1))

        def newton(_, b):
            p = jax.nn.sigmoid(b + off)
            num = jnp.sum(w * (y - p))
            den = jnp.clip(jnp.sum(w * p * (1.0 - p)), 1e-10, None)
            return b + num / den

        init = lax.fori_loop(0, 3, newton, init0)
        return init, init + off
    if dist == "multinomial":
        cls_w = jax.ops.segment_sum(
            w, jnp.where(w > 0, y, K).astype(jnp.int32),
            num_segments=K + 1)[:K]
        init = jnp.log(jnp.clip(cls_w / w_sum, 1e-8, None)).astype(
            jnp.float32)
        return init, jnp.broadcast_to(init[None, :], (y.shape[0], K))
    if dist in ("poisson", "tweedie"):
        # intercept MLE with log link + offset: e^b = Σwy / Σw·e^off
        init = jnp.log(jnp.clip(
            jnp.sum(y * w) /
            jnp.clip(jnp.sum(w * jnp.exp(off)), 1e-10, None), 1e-8, None))
        return init, init + off
    if dist == "gamma":
        # gamma deviance MLE: e^b = Σ w·y·e^{-off} / Σw
        init = jnp.log(jnp.clip(
            jnp.sum(y * w * jnp.exp(-off)) / w_sum, 1e-8, None))
        return init, init + off
    init = jnp.sum((y - off) * w) / w_sum              # gaussian mean
    return init, init + off


def _margin_metrics(dist: str, margin, y, w, model=None) -> dict:
    """Training metrics from the CURRENT boosting margin (no re-predict).

    Fully device-side with w-masking (pads/holdouts carry w=0): the
    round-1 version round-tripped the 1M-row margin through the host,
    which cost multiple seconds per call when the chip sits behind a
    network tunnel."""
    from .. import metrics as M

    if dist == "bernoulli":
        p1 = _jit_sigmoid(margin)
        return {"train_logloss": M.logloss(y, p1, w=w),
                "train_auc": M.roc_auc(y, p1, w=w)}
    if dist == "multinomial":
        pr = _jit_softmax(margin)
        return {"train_logloss": M.multinomial_logloss(y, pr, w=w)}
    if dist in ("poisson", "gamma", "tweedie"):
        return {"train_rmse": M.rmse(y, _jit_exp(margin), w=w)}
    return {"train_rmse": M.rmse(y, margin, w=w)}


@functools.partial(jax.jit, static_argnums=(2, 3))
def _stack_predict(trees: Tree, binned, max_depth: int, n_bins: int):
    """Sum of leaf values over a stacked [T, ...] Tree pytree."""

    def body(acc, tree):
        return acc + predict_tree(tree, binned, max_depth, n_bins), None

    init = jnp.zeros(binned.shape[0], dtype=jnp.float32)
    total, _ = lax.scan(body, init, trees)
    return total


@functools.partial(jax.jit, static_argnums=(2, 3))
def _stack_leaf_nodes(trees: Tree, binned, max_depth: int, n_bins: int):
    """[T, rows] resting heap node index per tree (leaf assignment) —
    shares descend_tree with predict so split semantics can't drift."""

    def body(_, tree):
        return None, descend_tree(tree, binned, max_depth, n_bins)

    _, nodes = lax.scan(body, None, trees)
    return nodes


class GBMModel(Model):
    algo = "gbm"
    _serving_jit = True     # predict routes through the jitted-scorer cache

    def __init__(self, data: TrainData, params: GBMParams,
                 bin_spec: BinSpec, trees, init_score, varimp):
        super().__init__(data)
        self.params = params
        self.bin_spec = bin_spec
        # stacked pytree: leaves have leading tree axis [T(*K), N];
        # accepts an already-stacked Tree (the fused boost_trees /
        # boost_trees_multi output) or a list of single trees (the
        # XGBoost lambdarank host loop)
        if isinstance(trees, Tree):
            self.trees = trees
            self.ntrees = int(trees.value.shape[0])
        else:
            # stack on HOST: an eager 90-operand jnp.stack on committed
            # multi-device arrays is exactly the dispatch shape that
            # trips XLA:CPU's flaky rendezvous (device_get transfers
            # never do)
            self.trees = jax.tree.map(
                lambda *xs: jnp.asarray(
                    np.stack([np.asarray(x) for x in xs])), *trees)
            self.ntrees = len(trees)
        self.init_score = init_score
        self.margin_scale = 1.0       # laplace robust scaling (train sets)
        self._varimp = varimp
        self._edges = jnp.asarray(bin_spec.edges_matrix())
        self._enum_mask = jnp.asarray(np.array(bin_spec.is_enum))

    def _flat(self) -> FlatTrees:
        """The ONE flattening of this ensemble (serving scorer + MOJO
        export share it): compact reachable-node arrays with raw-
        feature thresholds, built lazily and cached on the model."""
        ft = self.__dict__.get("_flat_trees")
        if ft is None:
            ft = flatten_trees(self.trees, np.asarray(self._edges),
                               np.asarray(self._enum_mask),
                               self.params.max_depth)
            ft = FlatTrees(*(jnp.asarray(a) for a in ft))
            self._flat_trees = ft
        return ft

    # base._cached_score calls this before tracing the jitted scorer
    _serving_prepare = _flat

    def _margins(self, X: jax.Array,
                 offset: jax.Array | None = None) -> jax.Array:
        """Raw boosting margins via the flattened serving scorer — no
        re-binning at score time; bitwise-equal to `_margins_binned`
        (the heap re-descent kept as the parity reference)."""
        K = self.nclasses if self.nclasses > 2 else 1
        p = self.params
        lv = flat_margin(self._flat(), X, self._enum_mask, p.max_depth,
                         K)                               # [K, rows]
        if K == 1:
            m = lv[0]
            if p._drf_mode:
                m = m / self.ntrees
            base = self.init_score if offset is None \
                else self.init_score + offset
            return base + getattr(self, "margin_scale", 1.0) * m
        if p._drf_mode:
            lv = lv / (self.ntrees // K)
        return (jnp.asarray(self.init_score)[:, None] + lv).T

    def _margins_binned(self, X: jax.Array,
                        offset: jax.Array | None = None) -> jax.Array:
        """Legacy per-tree heap re-descent over binned codes — the
        training-structure scorer the flat path must match bitwise
        (tests/test_flat_scorer.py, tools/kernel_gate.py)."""
        binned = apply_bins(X, self._edges, self._enum_mask,
                            self.bin_spec.na_bin)
        K = self.nclasses if self.nclasses > 2 else 1
        p = self.params
        if K == 1:
            m = _stack_predict(self.trees, binned, p.max_depth, p.nbins)
            if p._drf_mode:
                m = m / self.ntrees
            base = self.init_score if offset is None \
                else self.init_score + offset
            return base + getattr(self, "margin_scale", 1.0) * m
        # multinomial: trees interleaved [T*K]; de-interleave per class
        outs = []
        for k in range(K):
            tk = jax.tree.map(lambda a: a[k::K], self.trees)
            mk = _stack_predict(tk, binned, p.max_depth, p.nbins)
            if p._drf_mode:
                mk = mk / (self.ntrees // K)
            outs.append(self.init_score[k] + mk)
        return jnp.stack(outs, axis=1)

    def _score_matrix(self, X: jax.Array,
                      offset: jax.Array | None = None) -> jax.Array:
        m = self._margins(X, offset)
        d = self.distribution
        if d == "bernoulli":
            p1 = jnp.clip(m, 0.0, 1.0) if self.params._drf_mode \
                else jax.nn.sigmoid(m)
            return jnp.stack([1.0 - p1, p1], axis=1)
        if d == "multinomial":
            if self.params._drf_mode:
                m = jnp.clip(m, 0.0, None)
                return m / (jnp.sum(m, axis=1, keepdims=True) + 1e-10)
            return jax.nn.softmax(m, axis=1)
        if d in ("poisson", "gamma", "tweedie"):
            return jnp.exp(m)
        return m

    def predict_leaf_node_assignment(self, frame: Frame,
                                     type: str = "Path") -> Frame:
        """Per-row resting leaf per tree (h2o predict_leaf_node_assignment
        [U3]): one column per tree (`T1..Tk`, class-suffixed for
        multinomial). `Path` (the default, matching h2o) gives the L/R
        descent string from the root; `Node_ID` gives dense-heap
        indices."""
        from ..frame.frame import Vec

        if type not in ("Node_ID", "Path"):
            raise ValueError("type must be 'Node_ID' or 'Path'")
        X = self._design_matrix(frame)
        binned = apply_bins_jit(X, self._edges, self._enum_mask,
                                self.bin_spec.na_bin)
        p = self.params
        nodes = np.asarray(_stack_leaf_nodes(
            self.trees, binned, p.max_depth, p.nbins))[:, : frame.nrows]
        K = self.nclasses if self.nclasses > 2 else 1
        out = Frame()
        for t in range(nodes.shape[0]):
            name = f"T{t // K + 1}" if K == 1 else \
                f"T{t // K + 1}.C{t % K + 1}"
            if type == "Node_ID":
                out[name] = Vec.from_numpy(
                    nodes[t].astype(np.float32), name)
                continue
            # heap index -> root path string (L/R per level, h2o style);
            # only the ~2^depth unique leaves touch Python — per-row
            # work stays vectorized via the unique-inverse remap
            uniq, inv = np.unique(nodes[t], return_inverse=True)
            paths = [_heap_path(int(i)) for i in uniq]
            dom = sorted(set(paths))
            pos = {s: j for j, s in enumerate(dom)}
            remap = np.array([pos[s] for s in paths], dtype=np.int32)
            out[name] = Vec.from_numpy(remap[inv], name, domain=dom)
        return out

    def contrib_support(self) -> str | None:
        """TreeSHAP preconditions — THE one gate shared by the host
        ``predict_contributions``, the serving entry ``contrib_numpy``,
        and the REST contributions route (which turns a non-None
        reason into a clean 400, never a 500 traceback)."""
        if self.nclasses > 2:
            return ("predict_contributions supports binomial "
                    "and regression models only")
        if getattr(self, "offset_column", None):
            # a per-row offset is not attributable to any feature, so
            # SHAP columns could not sum to the margin
            return ("predict_contributions is not supported "
                    "for models trained with an offset")
        cov = getattr(self.trees, "cover", None)
        if cov is None or np.isnan(np.asarray(cov)).any():
            # .any(), not .all(): checkpoint continuation from a
            # pre-cover model mixes NaN-backfilled trees with real ones
            return (
                "this model contains trees saved by a build without "
                "per-node cover (pre-0.2); TreeSHAP needs it — retrain "
                "with this build")
        return None

    def _contrib_scale_init(self) -> tuple[float, float]:
        """(scale, init) applied to the raw kernel/recursion output —
        one formula for the host and device paths."""
        scale = float(getattr(self, "margin_scale", 1.0))
        if self.params._drf_mode:
            scale /= self.ntrees
        init = self.init_score if np.ndim(self.init_score) == 0 \
            else float(np.asarray(self.init_score).ravel()[0])
        return scale, float(init)

    def _shap_sources(self):
        """(flat arrays, slot-aligned cover) for the TreeSHAP path
        tables — the SAME flattening the serving scorer descends."""
        flat = self._flat()
        return (FlatTrees(*(np.asarray(a) for a in flat)),
                flatten_cover(self.trees, self.params.max_depth))

    def _contrib_enum_mask(self):
        return self._enum_mask

    def predict_contributions(self, frame: Frame) -> Frame:
        """Per-row TreeSHAP feature contributions (h2o
        predict_contributions, h2o-genmodel TreeSHAP [U3]): one column
        per feature plus BiasTerm, additive to the raw margin
        prediction. Binomial and regression only, like the reference.

        This is the in-process HOST path (float64 recursion over the
        heap trees) — the parity reference; serving traffic rides the
        compiled device kernel via ``contrib_numpy`` / the REST
        contributions route (docs/SERVING.md "Explainable serving")."""
        reason = self.contrib_support()
        if reason:
            raise ValueError(reason)
        from .tree.shap import ensemble_shap

        X = self._design_matrix(frame)
        binned = np.asarray(apply_bins_jit(
            X, self._edges, self._enum_mask,
            self.bin_spec.na_bin))[: frame.nrows]
        trees_np = {f: np.asarray(getattr(self.trees, f))
                    for f in ("split_feat", "split_bin", "na_left",
                              "is_split", "value", "cover")}
        scale, init = self._contrib_scale_init()
        phi = ensemble_shap(trees_np, binned,
                            len(self.feature_names),
                            self.bin_spec.na_bin, scale=scale)
        phi[:, -1] += init
        cols = {name: phi[:, i].astype(np.float32)
                for i, name in enumerate(self.feature_names)}
        cols["BiasTerm"] = phi[:, -1].astype(np.float32)
        return Frame.from_arrays(cols)

    def varimp(self) -> dict[str, float]:
        """Relative importance: per-feature summed split gain, scaled."""
        v = self._varimp
        top = max(v.values()) if v else 1.0
        return {k: val / top if top > 0 else 0.0
                for k, val in sorted(v.items(), key=lambda kv: -kv[1])}


@contextlib.contextmanager
def legacy_scoring_path(model: GBMModel):
    """Route `model.predict()` through the PRE-flattening path —
    binned heap re-descent, eager op dispatch, no scorer cache — for
    the duration of the block.  The serving benchmarks (bench.py score
    mode, bench_suite's gbm_score_rows_per_sec) use this as the ONE
    definition of the legacy baseline; everything else should never
    need it."""
    model._margins = model._margins_binned
    model._serving_jit = False
    try:
        yield model
    finally:
        del model._margins, model._serving_jit


class GBM:
    """H2OGradientBoostingEstimator analog."""

    model_cls = GBMModel

    def __init__(self, **kw):
        from .cv import CVArgs

        self.cv_args = CVArgs.pop(kw)
        if "nbins" not in kw:               # env/config default tier
            from ..config import get_config

            kw["nbins"] = get_config("nbins")
        self.params = GBMParams(**kw)

    def train(self, y: str, training_frame: Frame,
              x: Sequence[str] | None = None,
              ignored_columns: Sequence[str] | None = None,
              weights_column: str | None = None,
              validation_frame: Frame | None = None,
              offset_column: str | None = None) -> GBMModel:
        p = self.params
        if p.ntrees < 1:
            raise ValueError(f"ntrees must be >= 1, got {p.ntrees}")
        if not 4 <= p.nbins <= 256:
            # fit_bins validates this too; checking up front keeps the
            # error first whichever binning path (classic/fused) runs
            raise ValueError(f"n_bins must be in [4, 256] (uint8 bin "
                             f"codes), got {p.nbins}")
        if offset_column and p._drf_mode:
            # the reference rejects offsets for DRF too (trees vote —
            # there is no additive margin for an offset to join)
            raise ValueError("offset_column is not supported for DRF")
        if self.cv_args.fold_column:
            ignored_columns = list(ignored_columns or []) + \
                [self.cv_args.fold_column]
        # materialize_x=False: the tree learners never touch a full
        # [n, F] float32 design matrix — binning happens column-block-
        # wise straight from the Frame columns (Frame.binned), and
        # gradients come from the y/weights/offset columns alone. The
        # uint8 binned matrix is the only full-width training-resident
        # array (docs/SCALING.md).
        data = resolve_xy(training_frame, y, x, ignored_columns,
                          weights_column, p.distribution, offset_column,
                          materialize_x=False)
        if offset_column and data.distribution in ("multinomial",
                                                   "laplace"):
            raise ValueError("offset_column is not supported for "
                             f"{data.distribution} GBM")
        if data.distribution in ("gamma", "tweedie", "poisson"):
            ymin = float(_jit_min_pos(data.y, data.w))
            if data.distribution == "gamma" and ymin <= 0:
                raise ValueError(
                    "gamma distribution needs a strictly positive "
                    "response")
            if ymin < 0:
                raise ValueError(f"{data.distribution} distribution "
                                 "needs a non-negative response")
        margin_scale = 1.0
        ckpt = p.checkpoint
        if ckpt is not None:
            if self.cv_args.enabled:
                # H2O forbids checkpoint+CV: fold models would inherit
                # trees that already saw their holdout rows
                raise ValueError(
                    "checkpoint cannot be combined with cross-validation")
            if ckpt.feature_names != data.feature_names:
                raise ValueError(
                    "checkpoint model was trained on different features "
                    f"({ckpt.feature_names} vs {data.feature_names})")
            if ckpt.distribution != data.distribution:
                raise ValueError("checkpoint distribution mismatch")
            if ckpt.nclasses != data.nclasses or \
                    (ckpt.response_domain or []) != \
                    (data.response_domain or []):
                raise ValueError(
                    "checkpoint response mismatch: "
                    f"{ckpt.nclasses} classes {ckpt.response_domain} vs "
                    f"{data.nclasses} classes {data.response_domain}")
            K0 = ckpt.nclasses if ckpt.nclasses > 2 else 1
            if p.ntrees * K0 <= len(ckpt.trees.value):
                raise ValueError(
                    f"ntrees={p.ntrees} must exceed the checkpoint's "
                    f"{len(ckpt.trees.value) // K0} trees")
            bin_spec = ckpt.bin_spec     # same binning → trees compose
        else:
            bin_spec = None              # fit below, fused when eligible

        K = data.nclasses if data.nclasses > 2 else 1
        tp = _make_tree_params(p, data.distribution)
        key = jax.random.key(p.seed)
        F = len(data.feature_names)

        # GOSS (H2O_TPU_GOSS): validated up front so a bad knob or a
        # conflicting sample_rate fails before any binning work; the
        # per-round key stream is derived OUTSIDE the dispatch-chunk
        # key schedule (goss_round_keys) so the fused in-HBM path and
        # the ooc stream draw identical keep patterns at one seed
        goss_a, goss_b = goss_params(p, data.distribution)
        if goss_b > 0 and p.sample_rate < 1.0:
            raise ValueError(
                "H2O_TPU_GOSS replaces row subsampling — train with "
                f"sample_rate=1.0 (got {p.sample_rate}) or disable "
                "the GOSS knob")
        goss_keys = goss_round_keys(key, p.ntrees) if goss_b > 0 \
            else None

        # Exclusive Feature Bundling (models/tree/efb.py,
        # docs/SCALING.md "Wide sparse frames"): on wide frames
        # dominated by one-hot / near-empty columns, mutually
        # exclusive sparse features pack into single bundle columns at
        # bin time, so the binned matrix, every per-level scatter-add,
        # and the cross-shard histogram psum all run at the bundled
        # width.  Splits decode back to ORIGINAL (feature, bin) before
        # tree emission — bin_spec/trees/artifacts/serving are
        # bundle-free.  H2O_TPU_EFB=0 kills it; plan-less frames fall
        # through to the fused prologue unchanged.
        from .tree import efb as efb_mod

        efb_plan = None
        efb = None
        F_eff = F
        if bin_spec is None and efb_mod.efb_eligible(F, ckpt):
            spec_efb, efb_plan = efb_mod.fit_plan_cached(
                training_frame, data.feature_names, p.nbins)
            # reuse the fitted spec either way: when the plan is
            # rejected (shrink gate / no exclusive sets) re-fitting
            # through the fused prologue would just duplicate the
            # quantile fit this pass already paid
            bin_spec = spec_efb
            if efb_plan is not None:
                efb = efb_plan.device_luts()
                F_eff = efb_plan.fb

        # deep-tree memory validation: the dense heap's per-level
        # histogram working set is O(2^d·F·B·C) — the SAME accounting
        # (core.level_hist_bytes) the multinomial vmap branch and the
        # grouped-DRF G sizing use, so this validator and the actual
        # branch decisions cannot drift. The reference reaches depth 20
        # via dynamic row partitions; here ANY depth whose level
        # histograms fit the budget trains fine (e.g. depth 16 with 4
        # features × 16 bins is ~25 MB), and one that cannot fit fails
        # HERE with sizing guidance instead of an opaque device OOM
        # mid-boost.
        from .tree.core import level_hist_bytes, multi_grow_vmapped

        # histogram accounting at the width histograms actually have:
        # the BUNDLED width when EFB engaged (the memory win is exactly
        # what buys deeper trees / more grouped-DRF parallelism on
        # wide sparse frames)
        hist_bytes = level_hist_bytes(tp, F_eff)
        if K > 1 and multi_grow_vmapped(tp, F_eff, K):
            # validate the memory that will actually be live: K× only
            # when the grower really vmaps (past its budget it falls
            # to lax.map with one class's histograms live)
            hist_bytes *= K
        budget = float(os.environ.get("H2O_TPU_HIST_BYTES_BUDGET",
                                      2 ** 30))
        if hist_bytes > budget:
            need_mb = hist_bytes / 2 ** 20
            raise ValueError(
                f"max_depth={p.max_depth} with {F_eff} histogram "
                f"columns x {p.nbins} bins needs ~{need_mb:.0f} MiB of "
                f"level histograms (> budget "
                f"{budget / 2 ** 20:.0f} MiB). "
                "Lower max_depth or nbins, drop features, or raise "
                "H2O_TPU_HIST_BYTES_BUDGET if the device has room.")

        # out-of-core mode: when the uint8 binned matrix would not fit
        # the headroom the histogram budget leaves, keep it host-
        # resident in chunks and stream per boosting iteration
        # (models/tree/ooc.py). `binned` is only materialized on device
        # for the in-HBM path.
        ooc_chunk = _ooc_chunk_rows(p, data, K, F_eff, hist_bytes,
                                    budget, ckpt)
        binned = None
        # the bin phase is a telemetry span (h2o_train_phase_seconds
        # {phase="bin"} + /3/Timeline): the prologue whose blocking
        # quantile sync PR 5 removed stays observable in production
        from ..runtime.telemetry import phase_span

        with phase_span("bin", rows=data.y.shape[0], features=F_eff):
            if efb_plan is not None:
                # bundled training matrix [padded, Fb] (host-built
                # during planning, device-cached on the plan); the
                # out-of-core branch slices the same host matrix into
                # its chunk grid
                if ooc_chunk is None:
                    binned = efb_plan.binned_device()
            elif bin_spec is None:
                # fresh fit: on the in-HBM path the quantile fit and
                # the bin apply fuse into ONE dispatch with no host
                # sync in between (binning.fused_fit_bins;
                # H2O_TPU_FUSED_BINNING=0 restores the two-dispatch
                # path) — the out-of-core path keeps the classic fit
                # (its apply streams host chunks)
                if ooc_chunk is None and fused_binning_enabled():
                    bin_spec, binned = fused_fit_bins(
                        training_frame, data.feature_names,
                        n_bins=p.nbins)
                else:
                    bin_spec = fit_bins(training_frame,
                                        data.feature_names,
                                        n_bins=p.nbins)
            if ooc_chunk is None and binned is None:
                binned = training_frame.binned(bin_spec)

        off = data.offset if data.offset is not None \
            else jnp.zeros_like(data.y)
        if ckpt is not None:
            if ckpt.params.nbins != p.nbins or \
                    ckpt.params.max_depth != p.max_depth:
                raise ValueError(
                    "checkpoint nbins/max_depth must match "
                    f"({ckpt.params.nbins}/{ckpt.params.max_depth} vs "
                    f"{p.nbins}/{p.max_depth})")
            if (getattr(ckpt, "offset_column", None) or None) != \
                    (offset_column or None):
                raise ValueError(
                    "checkpoint offset_column mismatch: "
                    f"{getattr(ckpt, 'offset_column', None)!r} vs "
                    f"{offset_column!r}")
            init = ckpt.init_score
            if p._drf_mode:
                margin = jnp.zeros((data.y.shape[0], K)) if K > 1 \
                    else jnp.zeros_like(data.y)
            elif K == 1:
                margin = init + off + _stack_predict(
                    ckpt.trees, binned, p.max_depth, p.nbins)
            else:
                outs = [init[k] + _stack_predict(
                    jax.tree.map(lambda a: a[k::K], ckpt.trees),
                    binned, p.max_depth, p.nbins) for k in range(K)]
                margin = jnp.stack(outs, axis=1)
        elif p._drf_mode:
            # DRF: no boosting — leaves are in-leaf target means, init 0
            init = np.zeros(K, dtype=np.float32) if K > 1 else 0.0
            margin = jnp.zeros((data.y.shape[0], K)) if K > 1 \
                else jnp.zeros_like(data.y)
        elif data.distribution == "laplace":
            # L1 leaf steps are bounded by learn_rate, so fit in
            # median/MAD-scaled space: |y-f| is scale-equivariant and
            # the minimizer is unchanged; predictions rescale on read
            yv = np.asarray(data.y)[np.asarray(data.w) > 0]
            init = float(np.median(yv)) if len(yv) else 0.0
            mad = float(np.median(np.abs(yv - init))) if len(yv) else 1.0
            # MAD degenerates to 0 on zero-inflated data (>=50% of y at
            # one value) — only then fall back to the non-robust std,
            # otherwise keep the outlier-insensitive scale
            if mad * 1.4826 > 1e-8:
                margin_scale = mad * 1.4826
            else:
                std = float(np.std(yv)) if len(yv) else 1.0
                margin_scale = max(std, 1e-8)
            import dataclasses

            data = dataclasses.replace(
                data, y=(data.y - init) / margin_scale)
            margin = jnp.zeros_like(data.y)
        else:
            # bernoulli/multinomial/poisson/gamma/tweedie/gaussian:
            # init + margin in one device dispatch, no host sync before
            # the first boost chunk (init is read back at model build)
            init, margin = _init_margin(data.y, data.w, off,
                                        data.distribution, K)

        if ckpt is not None and data.distribution == "laplace":
            # continuation must reuse the checkpoint's robust scaling or
            # the new trees' leaf units would not compose; the working
            # margin lives in SCALED units (tree leaves), so drop the
            # init the generic ckpt branch added above
            init = ckpt.init_score
            margin_scale = getattr(ckpt, "margin_scale", 1.0)
            import dataclasses

            data = dataclasses.replace(
                data, y=(data.y - init) / margin_scale)
            margin = margin - init

        start_t = 0
        if ckpt is not None:
            start_t = len(ckpt.trees.value) // K
        history: list[dict] = []
        # fused loop: all boosting rounds of a chunk build inside ONE
        # compiled shard_map (scan over rounds; for K>2 classes the K
        # trees of a round grow via vmap inside the scan) — the margin
        # never leaves the device and the host dispatches once per chunk
        # instead of >=3 times per tree (VERDICT r1: the per-tree Python
        # loop dominated wall-clock; r2 left multinomial on it)
        bp = _make_boost_params(p, data.distribution)
        if ooc_chunk is not None:
            # chunk-streamed boosting: host-pinned binned chunks,
            # double-buffered device_put per level, chunk-accumulated
            # histograms (models/tree/ooc.py). Metrics land once at
            # the end — models with a score_every cadence never reach
            # this branch (_ooc_chunk_rows gates them in-HBM).
            from ..runtime.mrtask import shard_rows
            from .tree.ooc import boost_trees_chunked, make_chunks

            require_healthy()
            with device_dispatch("gbm out-of-core boost"), \
                    phase_span("boost", mode="ooc", trees=p.ntrees):
                cks = make_chunks(training_frame, bin_spec, data.y,
                                  data.w, margin, ooc_chunk,
                                  plan=efb_plan)
                margin_np, trees, goss_dropped = boost_trees_chunked(
                    cks, key, p.ntrees, tp, bp, efb=efb,
                    goss_keys=goss_keys)
            _warn_goss_overflow(goss_dropped)
            margin = shard_rows(margin_np)
        else:
            with phase_span("boost", mode="in_hbm", trees=p.ntrees):
                trees, margin, history = self._boost_in_hbm(
                    p, tp, bp, data, binned, margin, key, K, F_eff,
                    ckpt, start_t, history, efb=efb,
                    goss_keys=goss_keys)
        if isinstance(init, jax.Array):
            # read the device init back AFTER the boost chunks are
            # enqueued (async dispatch: this blocks only on the tiny
            # init computation, not on training)
            init = jax.device_get(init)
            init = init if init.ndim else float(init)
            if not np.all(np.isfinite(np.atleast_1d(init))):
                # 0/0 on device (every row weight zero / every response
                # NA) must surface as an error, not a silently-NaN model
                raise ValueError(
                    "no rows with positive weight and a non-NA response "
                    "— cannot fit a prior")
        model = self.model_cls(data, p, bin_spec, trees,
                               init_score=init, varimp=None)
        model.margin_scale = margin_scale
        model.offset_column = offset_column
        model._varimp = _stacked_varimp(model.trees, data.feature_names)
        if p._drf_mode:
            perf = model.model_performance(training_frame, y)
            history.append({"ntrees": p.ntrees,
                            **{f"train_{k}": v for k, v in perf.items()}})
        elif not (history and history[-1].get("ntrees") == p.ntrees):
            # (when score_every divides ntrees the loop already scored
            # the final round — don't duplicate the row)
            history.append({"ntrees": p.ntrees, **_margin_metrics(
                data.distribution, margin, data.y, data.w)})
        if margin_scale != 1.0 and history:
            # report rmse in ORIGINAL units, not MAD units
            for hrow in history:
                if "train_rmse" in hrow:
                    hrow["train_rmse"] *= margin_scale
        model.scoring_history = history
        from .cv import finalize_train

        return finalize_train(
            self, model, y, training_frame,
            {"x": x, "ignored_columns": ignored_columns,
             "weights_column": weights_column,
             "offset_column": offset_column},
            validation_frame)

    def _boost_in_hbm(self, p, tp, bp, data, binned, margin, key, K, F,
                      ckpt, start_t, history, efb=None, goss_keys=None):
        """The fused in-HBM boosting loop (all rows device-resident).
        ``F`` is the HISTOGRAM width (the bundled width under EFB) —
        it sizes the dispatch-budget chunks to the actual work.
        ``goss_keys`` ([ntrees] rows, indexed by GLOBAL tree number)
        is sliced per dispatch chunk so the per-round GOSS draw never
        depends on the _DISPATCH_BUDGET chunk schedule."""
        chunks: list[Tree] = [] if ckpt is None else [ckpt.trees]
        goss_overflow: list = []      # per-dispatch device scalars
        # cap ONE compiled dispatch's work: the TPU worker (behind
        # its RPC deadline) kills executions that run for minutes —
        # observed: 25 depth-12 trees on 1M rows crash the worker,
        # 10 pass. Work/round ~ rows·F·nbins·2^depth·K (deepest level
        # dominates with sibling subtraction); the budget keeps a
        # dispatch around ~10s on v5e and leaves shallow/bench
        # shapes in a single dispatch. The chunk schedule lives in
        # _chunk_sizes — compile-ahead pre-lowers exactly these shapes.
        score = p.score_every if (p.score_every and not p._drf_mode) \
            else 0
        t = start_t
        for n in _chunk_sizes(p, data.y.shape[0], F, K, start_t):
            require_healthy()        # fail fast on a dead mesh (§5.3)
            key, kc = jax.random.split(key)
            # the boost dispatch runs under the device guard: a chip
            # halting AT dispatch marks the cluster unhealthy and
            # raises ClusterHealthError (locked-cloud protocol) — this
            # loop dispatches shard_map directly, bypassing doall's
            # guard. Deliberately NOT block_until_ready: chunk
            # pipelining is the loop's perf design, so a mid-EXECUTION
            # device error instead surfaces at the metrics/model read
            # and is escalated to the same locked-cloud failure by
            # AutoML's step_failed device-error check
            gk = None if goss_keys is None else goss_keys[t: t + n]
            with device_dispatch("gbm boost dispatch"):
                if K == 1 and p._drf_mode:
                    # independent forest trees grow in vmapped GROUPS
                    # (the class-flattening kernel rule): G× fuller MXU
                    # M at shallow levels, G× fewer sequential steps
                    margin, tchunk = boost_trees_drf(
                        binned, data.y, data.w, margin, kc, n, tp, bp,
                        efb=efb)
                elif K == 1:
                    out = boost_trees(
                        binned, data.y, data.w, margin, kc, n, tp, bp,
                        efb=efb, goss_keys=gk)
                    margin, tchunk = out[0], out[1]
                    if gk is not None:
                        goss_overflow.append(out[2])
                else:
                    out = boost_trees_multi(
                        binned, data.y, data.w, margin, kc, n, K, tp,
                        bp, efb=efb, goss_keys=gk)
                    margin, tchunk = out[0], out[1]
                    if gk is not None:
                        goss_overflow.append(out[2])
                    # [n, K, ...] -> interleaved [n*K, ...] (class
                    # fastest), the layout _margins de-interleaves with
                    # a[k::K]
                    tchunk = jax.tree.map(
                        lambda a: a.reshape((-1,) + a.shape[2:]), tchunk)
            chunks.append(tchunk)
            t += n
            if score and (t - start_t) % score == 0:
                history.append({"ntrees": t, **_margin_metrics(
                    data.distribution, margin, data.y, data.w)})
        trees = jax.tree.map(
            lambda *xs: jnp.concatenate(xs), *chunks) \
            if len(chunks) > 1 else chunks[0]
        if goss_overflow:
            _warn_goss_overflow(
                int(sum(int(jax.device_get(o)) for o in goss_overflow)))
        return trees, margin, history

    # -- compile-ahead (runtime/scheduler.py) ---------------------------

    def compile_ahead_lowerings(self, y: str, frame: Frame,
                                x: Sequence[str] | None = None) -> list:
        """Zero-arg thunks that AOT-lower+compile the fused boost
        programs ``train(y, frame, x)`` will dispatch — run on the
        compile-ahead stream while the device token is busy with an
        earlier model, so the device stream's later dispatch is a
        compile-cache hit (in-process executable cache + the
        persistent XLA cache: a fill on a cold run, a no-op warm).

        Shape reconstruction mirrors train() from column METADATA only
        (padded_len, kinds, cardinality — no device dispatch, the
        compile stream never touches the device token). Coverage is
        the in-HBM pointwise tree path: the final fit's full-frame
        shape plus, under modulo CV (AutoML's fold assignment), the
        fold shapes — identical to the full shape in weights-masked
        share mode, the complement sizes in sliced mode. Ineligible
        configs (checkpoint continuation, out-of-core engagement,
        offset/weights columns, non-modulo folds) return [] and train
        compiles on-demand exactly as before.  Drift between this
        mirror and train() is pinned by tests/test_scheduler.py."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..runtime import mesh as meshlib
        from ..runtime.mrtask import _padded_len
        from .tree import core as _core
        from .tree.core import level_hist_bytes, multi_grow_vmapped

        p = self.params
        if p.checkpoint is not None or self.cv_args.fold_column:
            return []
        if y not in frame:
            return []
        ignored = {y}
        names = list(x) if x else [
            n for n in frame.names if n not in ignored and
            frame.vec(n).kind in ("numeric", "enum", "time")]
        if not names or ignored.intersection(names):
            return []
        from .tree import efb as efb_mod

        if efb_mod.efb_eligible(len(names), None):
            # EFB may rebundle this frame to a DATA-dependent width —
            # pre-lowering F-width executables would be dead compile
            # work burning the compile stream while train() compiles
            # the bundled shapes on demand anyway
            return []
        for n in names:
            if n not in frame or frame.vec(n).kind not in (
                    "numeric", "enum", "time"):
                return []
        yv = frame.vec(y)
        nclasses = yv.cardinality() if yv.is_enum() else 1
        dist = p.distribution
        if dist == "auto":
            dist = "bernoulli" if nclasses == 2 else \
                "multinomial" if nclasses > 2 else "gaussian"
        if dist.startswith("rank:"):
            return []       # the lambdarank host loop, not this path
        K = nclasses if nclasses > 2 else 1
        tp = _make_tree_params(p, dist)
        try:
            bp = _make_boost_params(p, dist)
        except ValueError:
            return []       # bad GOSS knobs: train() raises, on the
            #                 driver thread with the real message
        if bp.goss_b > 0 and p.sample_rate < 1.0:
            return []       # train() rejects the combination up front
        hist_bytes = level_hist_bytes(tp, len(names))
        if K > 1 and multi_grow_vmapped(tp, len(names), K):
            hist_bytes *= K
        budget = float(os.environ.get("H2O_TPU_HIST_BYTES_BUDGET",
                                      2 ** 30))
        if hist_bytes > budget:
            return []                       # train() raises up front
        mesh = meshlib.global_mesh()
        shards = mesh.shape[meshlib.ROWS]
        rows_shard = NamedSharding(mesh, P(meshlib.ROWS))
        F = len(names)
        n = frame.nrows

        # the shapes train() will see: the final fit's padded length,
        # plus the modulo-CV fold lengths — full-frame in share mode
        # (models/cv.py weights-masked folds), complement sizes sliced
        padded_sizes = {frame.vec(names[0]).padded_len}
        cv = self.cv_args
        if cv.enabled and cv.nfolds >= 2 and \
                cv.fold_assignment.lower() == "modulo":
            env = os.environ.get("H2O_TPU_CV_SHAPE_SHARE_ROWS")
            if env is not None:
                share = n <= int(env)
            else:
                share = jax.default_backend() == "tpu" and n <= 1_000_000
            if "_cv_mask_w_" in frame.names:
                share = False
            if not share:
                for k in range(cv.nfolds):
                    hold = n // cv.nfolds + (1 if k < n % cv.nfolds
                                             else 0)
                    padded_sizes.add(_padded_len(n - hold, shards))

        # mirror the out-of-core gate per shape (ooc streams its own
        # per-level programs; the fused boost lowering would be wasted)
        class _Shim:           # just .y.shape[0] / .distribution for
            pass               # _ooc_chunk_rows — zero logic duplicated

        keydt = jax.eval_shape(lambda: jax.random.key(0)).dtype
        thunks: list = []
        for padded in sorted(padded_sizes):
            shim = _Shim()
            shim.distribution = dist
            shim.y = jax.ShapeDtypeStruct((padded,), jnp.float32)
            if _ooc_chunk_rows(p, shim, K, F, hist_bytes, budget,
                               None) is not None:
                continue
            binned_s = jax.ShapeDtypeStruct((padded, F), jnp.uint8,
                                            sharding=rows_shard)
            row_s = jax.ShapeDtypeStruct((padded,), jnp.float32,
                                         sharding=rows_shard)
            if p._drf_mode:
                # train()'s DRF margin is an eager jnp.zeros
                # (uncommitted) — mirror its unspecified sharding or
                # the executable key misses
                margin_s = jax.ShapeDtypeStruct(
                    (padded,) if K == 1 else (padded, K), jnp.float32)
            else:
                margin_s = row_s if K == 1 else jax.ShapeDtypeStruct(
                    (padded, K), jnp.float32, sharding=rows_shard)
            if not p._drf_mode and dist != "laplace":
                thunks.append(functools.partial(
                    _aot, _init_margin, row_s, row_s, row_s, dist, K))
            for nt in sorted(set(_chunk_sizes(p, padded, F, K))):
                # efb=None mirrors train(): compile-ahead covers the
                # unbundled dispatch shapes (EFB plans are
                # data-dependent, and the auto gate keeps narrow
                # frames — everything this mirror serves — unbundled)
                if K == 1 and p._drf_mode:
                    G, rounds = drf_group_size(nt, tp, F)
                    keys_s = jax.ShapeDtypeStruct((rounds, G), keydt)
                    thunks.append(functools.partial(
                        _aot, _core._boost_drf_jit, binned_s, row_s,
                        row_s, margin_s, keys_s, None, tp, bp, G, mesh))
                    continue
                keys_s = jax.ShapeDtypeStruct((nt,), keydt)
                if bp.goss_b > 0:
                    # GOSS scans a (round keys, goss keys) pair —
                    # mirror boost_trees' operand structure exactly
                    keys_s = (keys_s,
                              jax.ShapeDtypeStruct((nt,), keydt))
                if K == 1:
                    thunks.append(functools.partial(
                        _aot, _core._boost_jit, binned_s, row_s, row_s,
                        margin_s, keys_s, None, tp, bp, mesh))
                else:
                    thunks.append(functools.partial(
                        _aot, _core._boost_multi_jit, binned_s, row_s,
                        row_s, margin_s, keys_s, None, tp, bp, K, mesh))
        return thunks


def _warn_goss_overflow(dropped: int) -> None:
    """Loud (never silent) notice that GOSS compaction truncated: the
    static per-shard capacity is sized for the EXPECTED a+b selected
    fraction, but a frame whose row ORDER correlates with |gradient|
    (sorted by target or residual) can cluster far more selected rows
    into one shard — and the truncated rows are exactly the
    high-gradient ones GOSS exists to keep (it also breaks the
    in-HBM↔ooc same-seed equivalence, since the two layouts truncate
    different segments). The model still trains; the operator should
    shuffle the rows or raise a+b."""
    if dropped <= 0:
        return
    from ..diagnostics import log

    log.warning(
        "GOSS compaction overflow: %d selected row contributions were "
        "dropped because one or more shards selected more rows than "
        "the static capacity (sized for the expected a+b fraction). "
        "The row order likely correlates with |gradient| — shuffle "
        "the training frame, or raise H2O_TPU_GOSS_TOP_A/"
        "H2O_TPU_GOSS_RAND_B so the capacity covers the clustering.",
        dropped)


def _aot(jitted, *args) -> None:
    """Lower + compile one jitted program ahead of use (compile-ahead
    stream). The executable lands in jax's compilation caches (and the
    persistent XLA cache), so the training-time dispatch of the same
    (program, shapes, statics) is a cache hit instead of a compile."""
    jitted.lower(*args).compile()


def _ooc_chunk_rows(p: GBMParams, data: TrainData, K: int, F: int,
                    hist_bytes: int, budget: float,
                    ckpt) -> int | None:
    """Rows per host-pinned chunk when out-of-core mode engages, None
    for the in-HBM path.

    Trigger: H2O_TPU_OOC=1 forces it (where eligible), =0 disables;
    otherwise it engages when the uint8 binned matrix would exceed the
    headroom H2O_TPU_HIST_BYTES_BUDGET leaves after the level
    histograms. Eligibility is pointwise single-output boosting —
    multinomial, DRF voting, huber (global residual quantile per
    round), checkpoint continuation, a scoring cadence
    (score_every: the stream scores once at the end, and a parameter
    must never be dropped silently), and row/column/per-node feature
    sampling (sample_rate / col_sample_rate_per_tree < 1, mtries > 0:
    the streamed key schedule differs from the fused core's, so the
    MODEL would depend on the chunk-size perf knob or on which path
    engaged) stay in-HBM
    (docs/SCALING.md). Multi-host (DCN) meshes stay in-HBM too:
    the chunk staging `device_put` cannot target other processes'
    devices (same guard as Vec.select_rows).
    """
    env = os.environ.get("H2O_TPU_OOC", "auto")
    if env == "0":
        return None
    if K != 1 or p._drf_mode or ckpt is not None or \
            data.distribution == "huber" or p.score_every or \
            p.sample_rate < 1.0 or p.col_sample_rate_per_tree < 1.0 \
            or p.mtries > 0:
        return None
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ..runtime import mesh as meshlib

    sharding = NamedSharding(meshlib.global_mesh(), P(meshlib.ROWS))
    if not sharding.is_fully_addressable:
        return None
    binned_bytes = data.y.shape[0] * F
    if env != "1" and binned_bytes <= max(budget - hist_bytes, 0):
        return None
    from .tree.ooc import chunk_rows_for

    return chunk_rows_for(data.y.shape[0], F, budget, hist_bytes)


def _heap_path(i: int) -> str:
    """Dense-heap index -> 'LRL...' root descent (root itself = '')."""
    bits = []
    while i > 0:
        bits.append("L" if i % 2 == 1 else "R")   # odd = left child
        i = (i - 1) // 2
    return "".join(reversed(bits))


def _gain_by_feat(tree: Tree, F: int) -> np.ndarray:
    feat = np.asarray(tree.split_feat)
    gain = np.asarray(tree.gain)
    out = np.zeros(F, dtype=np.float64)
    sel = feat >= 0
    np.add.at(out, feat[sel], gain[sel])
    return out


def _stacked_varimp(trees: Tree, names: list[str]) -> dict[str, float]:
    """Varimp from a stacked [T, N] Tree pytree in ONE host transfer —
    a per-tree np.asarray would force a device sync every boosting
    iteration, which dominates wall-clock when the chip sits behind a
    network tunnel. The ravel happens host-side (np) — an eager jnp op
    on the committed tree arrays is a multi-device dispatch."""
    flat = Tree(*(np.asarray(x).ravel() for x in trees))
    return dict(zip(names, _gain_by_feat(flat, len(names))))
