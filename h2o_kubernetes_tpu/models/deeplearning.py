"""DeepLearning — MLP / autoencoder with model-averaging allreduce.

Reference: hex/deeplearning (SURVEY.md §2b C12): each node runs
asynchronous ("Hogwild") SGD over its LOCAL rows, and every
`train_samples_per_iteration` samples an MRTask reduce AVERAGES the
per-node weights — parameter-averaging data parallelism, not gradient
allreduce. The TPU translation keeps those semantics exactly: each
shard runs `local_steps` minibatch SGD steps on its local rows inside
`shard_map`, then `psum(params)/n_shards` — the model-averaging
allreduce on ICI (BASELINE.json:5 names this move explicitly).

Differences from the reference, by design: minibatches instead of
per-row updates (MXU efficiency), and optax adam instead of ADADELTA
as the default adaptive rate (both are per-weight adaptive schemes;
`adaptive_rate=False` gives plain momentum SGD like the reference's
manual-rate mode).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax import lax
from jax.sharding import PartitionSpec as P

from ..frame import Frame
from ..runtime.mesh import ROWS, global_mesh, n_row_shards
from ..runtime.health import require_healthy
from .base import Model, TrainData, resolve_xy
from .datainfo import build_datainfo


@dataclass
class DeepLearningParams:
    hidden: tuple = (200, 200)
    activation: str = "rectifier"     # rectifier | tanh
    epochs: float = 10.0
    mini_batch_size: int = 32
    train_samples_per_iteration: int = -2   # -2: auto (one avg per epoch)
    adaptive_rate: bool = True        # adam; else momentum sgd
    rate: float = 0.005
    momentum_start: float = 0.9
    l1: float = 0.0
    l2: float = 0.0
    input_dropout_ratio: float = 0.0
    hidden_dropout_ratios: tuple | None = None
    autoencoder: bool = False
    standardize: bool = True
    seed: int = 0
    distribution: str = "auto"
    # continue training from a previous model (reference DeepLearning
    # checkpoint semantics, SURVEY.md §5.4): `epochs` is the TOTAL
    # target and must exceed the checkpoint's, mirroring GBM's ntrees
    checkpoint: object = None


def _act(name):
    return jnp.tanh if name == "tanh" else jax.nn.relu


def _init_params(key, sizes):
    params = []
    for i in range(len(sizes) - 1):
        key, k = jax.random.split(key)
        scale = np.sqrt(2.0 / sizes[i])
        params.append({
            "w": jax.random.normal(k, (sizes[i], sizes[i + 1])) * scale,
            "b": jnp.zeros(sizes[i + 1]),
        })
    return params


def _forward(params, x, act, dropout_keys=None, in_drop=0.0, hid_drop=None):
    h = x
    if dropout_keys is not None and in_drop > 0:
        keep = jax.random.bernoulli(dropout_keys[0], 1 - in_drop, h.shape)
        h = h * keep / (1 - in_drop)
    for i, layer in enumerate(params[:-1]):
        h = act(h @ layer["w"] + layer["b"])
        if dropout_keys is not None and hid_drop and hid_drop[i] > 0:
            keep = jax.random.bernoulli(dropout_keys[i + 1],
                                        1 - hid_drop[i], h.shape)
            h = h * keep / (1 - hid_drop[i])
    out = h @ params[-1]["w"] + params[-1]["b"]
    return out


def _loss_fn(params, xb, yb, wb, act, loss_kind, l1, l2, key, in_drop,
             hid_drop):
    nkeys = len(params) + 1
    dkeys = jax.random.split(key, nkeys) if (in_drop or hid_drop) else None
    out = _forward(params, xb, act, dkeys, in_drop, hid_drop)
    if loss_kind == "ce":
        logp = jax.nn.log_softmax(out, axis=1)
        nll = -jnp.take_along_axis(
            logp, yb.astype(jnp.int32)[:, None], axis=1)[:, 0]
        loss = jnp.sum(wb * nll) / (jnp.sum(wb) + 1e-10)
    else:  # mse (regression & autoencoder)
        err = out - (yb if yb.ndim == 2 else yb[:, None])
        loss = jnp.sum(wb[:, None] * err * err) / (jnp.sum(wb) + 1e-10) \
            / err.shape[1]
    reg = sum(jnp.sum(jnp.abs(p["w"])) for p in params) * l1 + \
        sum(jnp.sum(p["w"] ** 2) for p in params) * l2
    return loss + reg


class DeepLearningModel(Model):
    algo = "deeplearning"
    _serving_jit = True     # predict routes through the jitted-scorer cache

    def __init__(self, data: TrainData, params: DeepLearningParams,
                 dinfo, net_params, loss_kind: str):
        super().__init__(data)
        self.params = params
        self.dinfo = dinfo
        self.net = net_params
        self.loss_kind = loss_kind

    def _score_matrix(self, X: jax.Array,
                      offset: jax.Array | None = None) -> jax.Array:
        Xe = self.dinfo.expand(X)[:, :-1]     # drop intercept col (bias
        act = _act(self.params.activation)    # lives in the layers)
        out = _forward(self.net, Xe, act)
        if self.loss_kind == "ce":
            return jax.nn.softmax(out, axis=1)
        if self.params.autoencoder:
            return out
        if offset is not None:
            # regression offset: the net was fit to y - offset (MSE is
            # shift-equivariant), so predictions add it back
            return out[:, 0] + offset
        return out[:, 0]

    def predict(self, frame: Frame) -> Frame:
        if self.params.autoencoder:
            # reconstruction frame, one column per expanded input feature
            # (reference: DeepLearningModel scoring returns reconstr_*)
            rec = self.predict_raw(frame)
            names = self.dinfo.coef_names[:-1]  # minus intercept
            return Frame.from_arrays(
                {f"reconstr_{n}": rec[:, i] for i, n in enumerate(names)})
        return super().predict(frame)

    def model_performance(self, frame: Frame, y: str | None = None) -> dict:
        if self.params.autoencoder:
            return {"mse": float(np.mean(self.anomaly(frame)))}
        return super().model_performance(frame, y)

    def anomaly(self, frame: Frame) -> np.ndarray:
        """Autoencoder per-row reconstruction MSE (anomaly score)."""
        if not self.params.autoencoder:
            raise ValueError("anomaly() requires autoencoder=True")
        X = self._design_matrix(frame)
        Xe = self.dinfo.expand(X)[:, :-1]
        act = _act(self.params.activation)
        rec = _forward(self.net, Xe, act)
        mse = jnp.mean((rec - Xe) ** 2, axis=1)
        return np.asarray(mse)[: frame.nrows]

    def deepfeatures(self, frame: Frame, layer: int) -> np.ndarray:
        """Hidden-layer activations (reference: DeepFeatures scoring)."""
        X = self._design_matrix(frame)
        Xe = self.dinfo.expand(X)[:, :-1]
        act = _act(self.params.activation)
        h = Xe
        for lyr in self.net[: layer + 1]:
            h = act(h @ lyr["w"] + lyr["b"])
        return np.asarray(h)[: frame.nrows]


class DeepLearning:
    """H2ODeepLearningEstimator analog."""

    def __init__(self, **kw):
        from .cv import CVArgs

        self.cv_args = CVArgs.pop(kw)
        self.params = DeepLearningParams(**kw)

    def train(self, y: str | None = None, training_frame: Frame = None,
              x: Sequence[str] | None = None,
              ignored_columns: Sequence[str] | None = None,
              weights_column: str | None = None,
              validation_frame: Frame | None = None,
              offset_column: str | None = None) -> DeepLearningModel:
        p = self.params
        if p.autoencoder and self.cv_args.enabled:
            raise ValueError(
                "cross-validation is not supported for autoencoders")
        if offset_column and p.autoencoder:
            raise ValueError(
                "offset_column is not supported for autoencoders")
        if self.cv_args.fold_column:
            ignored_columns = list(ignored_columns or []) + \
                [self.cv_args.fold_column]
        mesh = global_mesh()
        n_shards = n_row_shards(mesh)

        if p.autoencoder:
            if y is None:
                # unsupervised: fabricate a constant response for resolve_xy
                y = "__ae_const__"
                training_frame = Frame(dict(training_frame._vecs))
                from ..frame import Vec
                training_frame[y] = Vec.from_numpy(
                    np.zeros(training_frame.nrows, dtype=np.float32), y)
            data = resolve_xy(training_frame, y, x, ignored_columns,
                              weights_column, "gaussian")
        else:
            data = resolve_xy(training_frame, y, x, ignored_columns,
                              weights_column, p.distribution,
                              offset_column)
        if offset_column and data.nclasses >= 2:
            # a shared per-row offset on every softmax logit is
            # invariant — only the regression (mse) head can honor it
            raise ValueError("offset_column is only supported for "
                             "regression DeepLearning")

        if p.checkpoint is not None:
            ck = p.checkpoint
            if self.cv_args.enabled:
                raise ValueError(
                    "checkpoint cannot be combined with cross-validation")
            if ck.feature_names != data.feature_names or \
                    ck.feature_domains != data.feature_domains:
                raise ValueError(
                    "checkpoint model was trained on different features/"
                    "domains")
            if (getattr(ck, "offset_column", None) or None) != \
                    (offset_column or None):
                # continuing a no-offset net against y - off (or vice
                # versa) silently shifts every prediction (same gate as
                # GBM's checkpoint offset check)
                raise ValueError(
                    "checkpoint offset_column mismatch: "
                    f"{getattr(ck, 'offset_column', None)!r} vs "
                    f"{offset_column!r}")
            # reuse the checkpoint's standardization stats: recomputing
            # them on the continuation frame would silently rescale every
            # input the restored weights were fit to
            dinfo = ck.dinfo
        else:
            dinfo = build_datainfo(data, training_frame, p.standardize,
                                   drop_first=False)
        Xe = dinfo.expand(data.X)[:, :-1]   # bias is in layers
        Pn = Xe.shape[1]
        K = data.nclasses
        if p.autoencoder:
            loss_kind, out_dim = "mse", Pn
        elif K >= 2:
            loss_kind, out_dim = "ce", K
        else:
            loss_kind, out_dim = "mse", 1

        sizes = (Pn,) + tuple(p.hidden) + (out_dim,)
        key = jax.random.key(p.seed)
        key, kinit = jax.random.split(key)
        if p.checkpoint is not None:
            ck = p.checkpoint
            got = tuple(l["w"].shape[0] for l in ck.net) + \
                (ck.net[-1]["w"].shape[1],)
            if got != sizes:
                raise ValueError(f"checkpoint layer sizes {got} != {sizes}")
            # deep copy: train_iter donates its buffers, and an aliased
            # checkpoint net would be deleted out from under ck
            net = jax.tree.map(lambda a: jnp.array(a, copy=True), ck.net)
        else:
            net = _init_params(kinit, sizes)

        rows_per_shard = Xe.shape[0] // n_shards
        batch = min(p.mini_batch_size, rows_per_shard)
        # non-positive (incl. the reference's -2 "auto") → one model
        # average per epoch of samples
        samples_per_iter = p.train_samples_per_iteration \
            if p.train_samples_per_iteration > 0 else data.nrows
        local_steps = max(1, samples_per_iter // (batch * n_shards))
        if p.checkpoint is not None:
            prev_epochs = p.checkpoint.params.epochs
            if p.epochs <= prev_epochs:
                raise ValueError(
                    f"epochs ({p.epochs}) must exceed the checkpoint "
                    f"model's ({prev_epochs}) — epochs is the total "
                    f"training target, not an increment")
            total_samples = (p.epochs - prev_epochs) * data.nrows
        else:
            total_samples = p.epochs * data.nrows
        n_iters = max(1, int(round(total_samples /
                                   (local_steps * batch * n_shards))))

        if p.adaptive_rate:
            opt = optax.adam(p.rate)
        else:
            opt = optax.sgd(p.rate, momentum=p.momentum_start)
        opt_state = opt.init(net)

        act = _act(p.activation)
        hid_drop = p.hidden_dropout_ratios
        y_dev = Xe if p.autoencoder else data.y     # AE reconstructs input
        if data.offset is not None and not p.autoencoder:
            # fit the net to y - offset: exactly equivalent for the
            # shift-equivariant mse loss; scoring adds the offset back
            y_dev = y_dev - data.offset

        grad_fn = jax.grad(_loss_fn)

        def local_round(net, opt_state, xs, ys, ws, key):
            """`local_steps` minibatch SGD steps on this shard's rows."""
            key = jax.random.fold_in(key, lax.axis_index(ROWS))

            def step(carry, k):
                net, opt_state = carry
                kidx, kdrop = jax.random.split(k)
                idx = jax.random.randint(kidx, (batch,), 0, xs.shape[0])
                xb = xs[idx]
                yb = ys[idx]
                wb = ws[idx]
                g = grad_fn(net, xb, yb, wb, act, loss_kind, p.l1, p.l2,
                            kdrop, p.input_dropout_ratio, hid_drop)
                updates, opt_state = opt.update(g, opt_state, net)
                net = optax.apply_updates(net, updates)
                return (net, opt_state), None

            keys = jax.random.split(key, local_steps)
            (net, opt_state), _ = lax.scan(step, (net, opt_state), keys)
            # the model-averaging allreduce (ICI psum / n)
            net = jax.tree.map(lambda a: lax.psum(a, ROWS) / n_shards, net)
            opt_state = jax.tree.map(
                lambda a: lax.psum(a, ROWS) / n_shards
                if jnp.issubdtype(a.dtype, jnp.floating) else a, opt_state)
            return net, opt_state

        @functools.partial(jax.jit, donate_argnums=(0, 1))
        def train_iter(net, opt_state, key):
            fn = jax.shard_map(
                functools.partial(local_round),
                mesh=mesh,
                in_specs=(P(), P(), P(ROWS), P(ROWS), P(ROWS), P()),
                out_specs=P(),
            )
            return fn(net, opt_state, Xe, y_dev, data.w, key)

        for i in range(n_iters):
            require_healthy()        # fail fast on a dead mesh (§5.3)
            key, ki = jax.random.split(key)
            net, opt_state = train_iter(net, opt_state, ki)

        model = DeepLearningModel(data, p, dinfo, net, loss_kind)
        model.offset_column = offset_column
        if p.autoencoder:
            model.nclasses = 1
            model.cv = None
            if validation_frame is not None:
                # validation reconstruction error (H2O scores AEs the
                # same way: MSE of reconstruction on the valid frame)
                model.validation_metrics = model.model_performance(
                    validation_frame)
            return model
        def _off_has_na():
            # NA offsets make NaN predictions by design (training
            # dropped those rows) and would poison frame-level metrics
            # — skip the history row ONLY for that case;
            # legitimately-NaN metrics on degenerate frames
            # (constant-response r2 etc.) still record. Slice to nrows:
            # as_float() keeps shard-pad rows, which are NaN by design.
            if offset_column is None:
                return False
            off = np.asarray(
                training_frame.vec(offset_column).as_float(),
                dtype=np.float32)[: data.nrows]
            return bool(np.isnan(off).any())

        if data.nrows <= 100_000 and not _off_has_na():
            # final-epoch training metrics (H2O's DL scores a SAMPLE at
            # intervals — score_training_samples defaults to 10k; here
            # one full-frame row at train end, skipped past 100k rows
            # where the extra scoring pass would be felt)
            perf = model.model_performance(training_frame, y)
            model.scoring_history = [{
                "epochs": p.epochs,
                **{f"train_{k}": v for k, v in perf.items()}}]
        from .cv import finalize_train

        return finalize_train(
            self, model, y, training_frame,
            {"x": x, "ignored_columns": ignored_columns,
             "weights_column": weights_column,
             "offset_column": offset_column},
            validation_frame)
