"""KMeans — Lloyd's algorithm as sharded matmuls + ICI psum.

Reference: hex/kmeans/KMeans.java (SURVEY.md §2b C17): k-means++
("PlusPlus") init, then Lloyd iterations where one MRTask per iteration
assigns every row to its closest center and accumulates per-cluster
sums/counts, reduced across the node ring; the driver recomputes
centers and checks movement.

TPU design: the whole Lloyd loop runs in ONE jitted shard_map —
distances via a single [r,F]x[F,k] matmul (MXU), per-cluster sums via a
one-hot [k,r]x[r,F] matmul (MXU again, no scatter), `lax.psum` for the
cross-shard reduce, `lax.while_loop` for convergence — no per-iteration
host round trip (the reference pays one MRTask latency per iteration).
Categoricals are one-hot expanded by DataInfo exactly as the reference
expands them for KMeans.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from ..frame import Frame
from ..runtime.mesh import ROWS, global_mesh
from .base import Model, resolve_x
from .datainfo import build_datainfo


@dataclass
class KMeansParams:
    k: int = 8
    max_iterations: int = 10
    init: str = "PlusPlus"            # PlusPlus | Random | Furthest
    standardize: bool = True
    seed: int = 0
    estimate_k: bool = False          # reserved (reference feature)


def _pairwise_sqdist(X, C):
    """[r,F],[k,F] -> [r,k] squared distances via matmul (MXU path)."""
    x2 = jnp.sum(X * X, axis=1, keepdims=True)
    c2 = jnp.sum(C * C, axis=1)[None, :]
    return x2 - 2.0 * (X @ C.T) + c2


def _lloyd_shard(Xe, w, C0, max_iter: int, tol: float):
    """Runs under shard_map; returns (C, assignments, withinss)."""
    k = C0.shape[0]

    def assign_stats(C):
        d = _pairwise_sqdist(Xe, C)                       # [r,k]
        a = jnp.argmin(d, axis=1)
        onehot = (a[:, None] == jnp.arange(k)[None, :])
        onehot = onehot.astype(jnp.float32) * w[:, None]  # [r,k]
        sums = lax.psum(onehot.T @ Xe, ROWS)              # [k,F] MXU
        cnts = lax.psum(jnp.sum(onehot, axis=0), ROWS)    # [k]
        wss = lax.psum(
            jnp.sum(jnp.min(d, axis=1) * w), ROWS)
        return a, sums, cnts, wss

    def cond(carry):
        it, C, move, _ = carry
        return (it < max_iter) & (move > tol)

    def body(carry):
        it, C, _, _ = carry
        _, sums, cnts, wss = assign_stats(C)
        newC = jnp.where(cnts[:, None] > 0,
                         sums / jnp.maximum(cnts[:, None], 1.0), C)
        move = jnp.max(jnp.sum((newC - C) ** 2, axis=1))
        return it + 1, newC, move, wss

    it, C, _, _ = lax.while_loop(cond, body,
                                 (0, C0, jnp.inf, jnp.float32(0)))
    a, _, cnts, wss = assign_stats(C)
    return C, a, cnts, wss, it


@functools.partial(jax.jit, static_argnums=(3, 5))
def _lloyd_jit(Xe, w, C0, max_iter, tol, mesh):
    fn = jax.shard_map(
        functools.partial(_lloyd_shard, max_iter=max_iter, tol=tol),
        mesh=mesh,
        in_specs=(P(ROWS), P(ROWS), P()),
        out_specs=(P(), P(ROWS), P(), P(), P()))
    return fn(Xe, w, C0)


def _plusplus_init(Xe_np, w_np, k, rng):
    """k-means++ seeding on the host over the (valid-row) matrix."""
    valid = np.flatnonzero(w_np > 0)
    X = Xe_np[valid]
    n = X.shape[0]
    centers = [X[rng.integers(n)]]
    d2 = np.full(n, np.inf, dtype=np.float64)
    for _ in range(1, k):
        c = centers[-1]
        d2 = np.minimum(d2, ((X - c) ** 2).sum(axis=1))
        tot = d2.sum()
        probs = d2 / tot if tot > 0 else np.full(n, 1.0 / n)
        centers.append(X[rng.choice(n, p=probs)])
    return np.stack(centers).astype(np.float32)


class KMeansModel(Model):
    algo = "kmeans"

    def __init__(self, data, params, dinfo, centers, counts,
                 withinss, iterations):
        super().__init__(data)
        self.params = params
        self.dinfo = dinfo
        self.centers_std = centers           # in standardized space
        self.size = counts
        self.tot_withinss = withinss
        self.iterations = iterations
        self.nclasses = 1

    def centers(self) -> np.ndarray:
        """Cluster centers in the ORIGINAL feature space (numeric part
        de-standardized; one-hot coordinates stay as level frequencies,
        as in the reference's standardized-centers output)."""
        C = np.asarray(self.centers_std, dtype=np.float64).copy()
        nn = len(self.dinfo.numeric_idx)
        C[:, :nn] = C[:, :nn] * self.dinfo.stds[None, :] + \
            self.dinfo.means[None, :]
        return C

    def _score_matrix(self, X):
        Xe = self.dinfo.expand(X)[:, :-1]
        d = _pairwise_sqdist(Xe, self.centers_std)
        return jnp.argmin(d, axis=1).astype(jnp.float32)

    def predict(self, frame: Frame) -> Frame:
        out = self.predict_raw(frame).astype(np.int32)
        return Frame.from_arrays({"predict": out})

    def model_performance(self, frame=None, y=None) -> dict:
        return {"tot_withinss": float(self.tot_withinss),
                "iterations": int(self.iterations)}


class KMeans:
    """H2OKMeansEstimator analog."""

    def __init__(self, **kw):
        from .cv import CVArgs

        CVArgs.pop(kw)                 # accepted, unused (no CV for kmeans)
        self.params = KMeansParams(**kw)

    def train(self, training_frame: Frame, x: Sequence[str] | None = None,
              ignored_columns: Sequence[str] | None = None,
              y: str | None = None) -> KMeansModel:
        p = self.params
        if p.k < 1:
            raise ValueError(f"k must be >= 1, got {p.k}")
        ignored = list(ignored_columns or [])
        if y is not None:
            ignored.append(y)
        data = resolve_x(training_frame, x, ignored)
        dinfo = build_datainfo(data, training_frame, p.standardize,
                               drop_first=False)
        Xe = dinfo.expand(data.X)[:, :-1]   # no intercept col
        rng = np.random.default_rng(p.seed)

        Xe_np = np.asarray(Xe)
        w_np = np.asarray(data.w)
        if p.init.lower() in ("plusplus", "kmeans++", "auto"):
            C0 = _plusplus_init(Xe_np, w_np, p.k, rng)
        elif p.init.lower() == "random":
            valid = np.flatnonzero(w_np > 0)
            C0 = Xe_np[rng.choice(valid, size=p.k, replace=False)]
        elif p.init.lower() == "furthest":
            C0 = _furthest_init(Xe_np, w_np, p.k, rng)
        else:
            raise ValueError(f"unknown init '{p.init}'")

        mesh = global_mesh()
        C, a, cnts, wss, iters = _lloyd_jit(
            Xe, data.w, jnp.asarray(C0), p.max_iterations,
            jnp.float32(1e-6), mesh)
        model = KMeansModel(data, p, dinfo, C, np.asarray(cnts),
                            float(wss), int(iters))
        model.cv = None
        return model


def _furthest_init(Xe_np, w_np, k, rng):
    valid = np.flatnonzero(w_np > 0)
    X = Xe_np[valid]
    centers = [X[rng.integers(X.shape[0])]]
    d2 = np.full(X.shape[0], np.inf)
    for _ in range(1, k):
        d2 = np.minimum(d2, ((X - centers[-1]) ** 2).sum(axis=1))
        centers.append(X[int(d2.argmax())])
    return np.stack(centers).astype(np.float32)
