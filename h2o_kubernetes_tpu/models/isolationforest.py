"""IsolationForest — random isolation trees on the dense-heap layout.

Reference: hex/tree/isofor/IsolationForest.java (SURVEY.md §2b C17):
each tree trains on a row subsample (sample_size, default 256); at each
node a RANDOM feature and a RANDOM split value within the node's
[min, max] of that feature are chosen (no histograms, no gain); a row's
anomaly score derives from its mean path length over the forest,
normalized as 2^(-E[h]/c(n)) (Liu et al.'s standard isolation score).

TPU design mirrors models/tree/core.py: dense per-row relative node
ids, per-level `segment_min`/`segment_max` for node feature ranges
(psum-free — `lax.pmin/pmax` across row shards), random choices drawn
from a replicated key so every shard agrees, trees padded to max_depth
so nothing recompiles as the forest grows.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import NamedTuple, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from ..frame import Frame
from ..runtime.mesh import ROWS, global_mesh
from .base import Model, resolve_x


@dataclass(frozen=True)      # hashable: passed as a static jit argument
class IsolationForestParams:
    ntrees: int = 50
    sample_size: int = 256
    max_depth: int = 8              # reference default: ceil(log2(256))
    seed: int = 0


class IsoTree(NamedTuple):
    split_feat: jax.Array   # int32 [N]
    split_val: jax.Array    # f32   [N] raw-value threshold (go left if <)
    is_split: jax.Array     # bool  [N]
    count: jax.Array        # f32   [N] training rows that reached the node


def _avg_path(n):
    """c(n): average BST unsuccessful-search path length (Liu et al.)."""
    n = jnp.maximum(n, 2.0)
    H = jnp.log(n - 1.0) + 0.5772156649
    return 2.0 * H - 2.0 * (n - 1.0) / n


def _seg_stat(vals, seg, n_seg, combine):
    """Per-(node,feature) reduce of row values: [r,F] -> [n_seg,F]."""
    fn = {"min": jax.ops.segment_min, "max": jax.ops.segment_max}[combine]
    return jax.vmap(lambda col: fn(col, seg, num_segments=n_seg),
                    in_axes=1, out_axes=1)(vals)


def _grow_iso_shard(X, live0, key, p: IsolationForestParams):
    F = X.shape[1]
    N = 2 ** (p.max_depth + 1) - 1
    split_feat = jnp.full(N, -1, dtype=jnp.int32)
    split_val = jnp.zeros(N, dtype=jnp.float32)
    is_split = jnp.zeros(N, dtype=bool)
    count = jnp.zeros(N, dtype=jnp.float32)

    Xf = jnp.nan_to_num(X)                    # NAs take value 0 (go left-ish)
    rel = jnp.where(live0, 0, -1)

    for d in range(p.max_depth + 1):
        n_nodes = 2 ** d
        off = n_nodes - 1
        seg = jnp.where(rel >= 0, rel, n_nodes)
        big = jnp.float32(3.4e38)
        vmin = _seg_stat(jnp.where((rel >= 0)[:, None], Xf, big), seg,
                         n_nodes + 1, "min")[:n_nodes]
        vmax = _seg_stat(jnp.where((rel >= 0)[:, None], Xf, -big), seg,
                         n_nodes + 1, "max")[:n_nodes]
        vmin = lax.pmin(vmin, ROWS)
        vmax = lax.pmax(vmax, ROWS)
        cnt = lax.psum(jax.ops.segment_sum(
            (rel >= 0).astype(jnp.float32), seg,
            num_segments=n_nodes + 1)[:n_nodes], ROWS)

        kf, kv = jax.random.split(jax.random.fold_in(key, d))
        # random feature among those with spread; if none, node is a leaf
        spread_ok = vmax > vmin                       # [n, F]
        r = jax.random.uniform(kf, (n_nodes, F))
        r = jnp.where(spread_ok, r, -1.0)
        feat = jnp.argmax(r, axis=1).astype(jnp.int32)
        any_ok = jnp.any(spread_ok, axis=1)
        u = jax.random.uniform(kv, (n_nodes,))
        fmin = jnp.take_along_axis(vmin, feat[:, None], 1)[:, 0]
        fmax = jnp.take_along_axis(vmax, feat[:, None], 1)[:, 0]
        val = fmin + u * (fmax - fmin)
        can = any_ok & (cnt > 1.0)
        if d == p.max_depth:
            can = jnp.zeros_like(can)

        idx = off + jnp.arange(n_nodes)
        split_feat = split_feat.at[idx].set(jnp.where(can, feat, -1))
        split_val = split_val.at[idx].set(val)
        is_split = is_split.at[idx].set(can)
        count = count.at[idx].set(cnt)
        if d == p.max_depth:
            break

        live = rel >= 0
        safe = jnp.where(live, rel, 0)
        rowval = jnp.take_along_axis(
            Xf, feat[safe][:, None], axis=1)[:, 0]
        go_right = rowval >= val[safe]
        child = 2 * rel + go_right.astype(jnp.int32)
        rel = jnp.where(live & can[safe], child, -1)

    return IsoTree(split_feat, split_val, is_split, count)


@functools.partial(jax.jit, static_argnums=(2, 3))
def _grow_iso_jit(X, live, p: IsolationForestParams, mesh, key):
    fn = jax.shard_map(
        functools.partial(_grow_iso_shard, p=p),
        mesh=mesh, in_specs=(P(ROWS), P(ROWS), P()), out_specs=P())
    return fn(X, live, key)


def _path_length(tree: IsoTree, X, max_depth: int):
    """Per-row path length h(x) incl. c(leaf_count) adjustment."""
    Xf = jnp.nan_to_num(X)
    node = jnp.zeros(X.shape[0], dtype=jnp.int32)
    depth = jnp.zeros(X.shape[0], dtype=jnp.float32)
    for _ in range(max_depth):
        f = tree.split_feat[node]
        v = tree.split_val[node]
        sp = tree.is_split[node]
        rowval = jnp.take_along_axis(
            Xf, jnp.maximum(f, 0)[:, None], axis=1)[:, 0]
        child = 2 * node + 1 + (rowval >= v).astype(jnp.int32)
        node = jnp.where(sp, child, node)
        depth = depth + sp.astype(jnp.float32)
    leaf_n = tree.count[node]
    return depth + jnp.where(leaf_n > 1.0, _avg_path(leaf_n), 0.0)


@functools.partial(jax.jit, static_argnums=(2,))
def _forest_path(trees: IsoTree, X, max_depth: int):
    def body(acc, tree):
        return acc + _path_length(tree, X, max_depth), None

    init = jnp.zeros(X.shape[0], dtype=jnp.float32)
    total, _ = lax.scan(body, init, trees)
    return total


class IsolationForestModel(Model):
    algo = "isolationforest"

    def __init__(self, data, params, trees: list[IsoTree],
                 sample_size_effective: int):
        super().__init__(data)
        self.params = params
        self.trees = jax.tree.map(lambda *xs: jnp.stack(xs), *trees)
        self.ntrees = len(trees)
        self.nclasses = 1
        # normalizer uses the ACTUAL per-tree sample (clamped to valid
        # rows), not the requested one, or small frames inflate scores
        self.sample_size_effective = sample_size_effective

    def _score_matrix(self, X):
        mean_len = _forest_path(self.trees, X,
                                self.params.max_depth) / self.ntrees
        c = _avg_path(jnp.float32(self.sample_size_effective))
        score = jnp.exp2(-mean_len / c)
        return jnp.stack([score, mean_len], axis=1)

    def predict(self, frame: Frame) -> Frame:
        out = self.predict_raw(frame)
        return Frame.from_arrays({"predict": out[:, 0],
                                  "mean_length": out[:, 1]})

    def model_performance(self, frame=None, y=None) -> dict:
        return {"ntrees": self.ntrees}


class IsolationForest:
    """H2OIsolationForestEstimator analog."""

    def __init__(self, **kw):
        from .cv import CVArgs

        CVArgs.pop(kw)
        self.params = IsolationForestParams(**kw)

    def train(self, training_frame: Frame,
              x: Sequence[str] | None = None,
              ignored_columns: Sequence[str] | None = None,
              y: str | None = None) -> IsolationForestModel:
        p = self.params
        ignored = list(ignored_columns or [])
        if y is not None:
            ignored.append(y)
        data = resolve_x(training_frame, x, ignored)
        mesh = global_mesh()
        key = jax.random.key(p.seed)
        n = data.X.shape[0]
        rows_valid = np.asarray(data.w) > 0
        rng = np.random.default_rng(p.seed)
        trees = []
        sample = min(p.sample_size, int(rows_valid.sum()))
        valid_idx = np.flatnonzero(rows_valid)
        for t in range(p.ntrees):
            key, kt = jax.random.split(key)
            pick = rng.choice(valid_idx, size=sample, replace=False)
            live = np.zeros(n, dtype=bool)
            live[pick] = True
            trees.append(_grow_iso_jit(data.X, jnp.asarray(live), p,
                                       mesh, kt))
        model = IsolationForestModel(data, p, trees, sample)
        model.cv = None
        return model
