"""ModelBuilder/Model base plumbing shared by every algorithm.

The analog of the reference's hex.ModelBuilder + hex.Model pair
(h2o-core hex/ModelBuilder.java — parameter validation, response
handling, training dispatch; SURVEY.md §2b C9/C10): resolves feature/
response columns from a Frame, infers the distribution, and gives every
model a uniform predict / model_performance surface.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .. import metrics as M
from ..frame import Frame, Vec
from ..runtime import mesh as meshlib

# jitted single-column overwrite for partial_plot sweeps: an EAGER
# .at[].set on a committed multi-device array is the XLA:CPU rendezvous
# flake pattern the fused train paths were purged of
_set_col_jit = jax.jit(
    lambda X, j, v: X.at[:, j].set(v), static_argnums=1)


@dataclass
class TrainData:
    """Device-ready training inputs resolved from a Frame.

    ``X`` is None when resolved with ``materialize_x=False`` — the
    histogram tree learners bin straight from the Frame columns
    (Frame.binned) and never touch a full float32 design matrix;
    gradients come from y/w/offset alone."""

    feature_names: list[str]
    X: jax.Array | None          # [padded, F] float32, NA→NaN, sharded
    y: jax.Array                 # [padded] float32 (class id for enums)
    w: jax.Array                 # [padded] float32 weights, 0 on padding
    nrows: int
    nclasses: int                # 1 for regression
    response_domain: list[str] | None
    distribution: str            # gaussian | bernoulli | multinomial | ...
    feature_domains: dict[str, list[str]] = field(default_factory=dict)
    offset: jax.Array | None = None   # [padded] float32, 0 on padding/NA


def _feature_names(frame: Frame, x: Sequence[str] | None,
                   ignored: set[str]) -> list[str]:
    """Resolve + validate feature columns (shared by resolve_xy/resolve_x)."""
    names = list(x) if x else [n for n in frame.names if n not in ignored]
    if x:
        # an explicit x must not smuggle back a column the caller set
        # aside: the response leaks the label, a weights/offset column
        # double-counts, a fold column encodes holdout membership
        clash = ignored.intersection(names)
        if clash:
            raise ValueError(
                f"column(s) {sorted(clash)} are the response/weights/"
                "offset/fold or ignored_columns and cannot also be "
                "features (remove them from x)")
    for n in names:
        if n not in frame:
            raise ValueError(f"feature column '{n}' not in frame")
        if frame.vec(n).kind not in ("numeric", "enum", "time"):
            raise ValueError(f"column '{n}' of kind {frame.vec(n).kind} "
                             "cannot be a feature")
    return names


def resolve_xy(frame: Frame, y: str, x: Sequence[str] | None = None,
               ignored: Sequence[str] | None = None,
               weights_column: str | None = None,
               distribution: str = "auto",
               offset_column: str | None = None,
               materialize_x: bool = True) -> TrainData:
    from ..runtime.health import require_healthy

    require_healthy()   # fail fast before training on a broken cloud
    if y not in frame:
        raise ValueError(f"response column '{y}' not in frame")
    ignored = set(ignored or [])
    ignored.add(y)
    if weights_column:
        ignored.add(weights_column)
    if offset_column:
        # offset is a fixed per-row margin term, never a feature
        # (hex/ModelBuilder offset_column handling [U3])
        if offset_column not in frame:
            raise ValueError(
                f"offset column '{offset_column}' not in frame")
        if frame.vec(offset_column).is_enum():
            raise ValueError(
                f"offset column '{offset_column}' must be numeric")
        ignored.add(offset_column)
    names = _feature_names(frame, x, ignored)
    yv = frame.vec(y)
    nclasses, domain = 1, None
    if yv.is_enum():
        domain = yv.domain
        nclasses = yv.cardinality()
        if nclasses < 2:
            raise ValueError(f"response '{y}' has {nclasses} classes")
    if distribution == "auto":
        if nclasses == 2:
            distribution = "bernoulli"
        elif nclasses > 2:
            distribution = "multinomial"
        else:
            distribution = "gaussian"
    if distribution in ("bernoulli", "multinomial") and nclasses == 1:
        raise ValueError(f"{distribution} needs a categorical response; "
                         f"'{y}' is numeric (use .asfactor()-style enum)")

    X = frame.to_matrix(names) if materialize_x else None
    y_arr = yv.as_float()
    w = frame.valid_mask()
    if weights_column:
        w = w * frame.vec(weights_column).as_float()
    # response NAs are dropped by zeroing their weight (reference drops
    # such rows during ModelBuilder init)
    w = jnp.where(jnp.isnan(y_arr), 0.0, w)
    y_arr = jnp.nan_to_num(y_arr)
    off = None
    if offset_column:
        off = frame.vec(offset_column).as_float()
        # NA offset rows cannot contribute a defined margin — dropped
        # like NA responses
        w = jnp.where(jnp.isnan(off), 0.0, w)
        off = jnp.nan_to_num(off)
    fdoms = {n: list(frame.vec(n).domain) for n in names
             if frame.vec(n).is_enum()}
    return TrainData(names, X, y_arr, w, frame.nrows, nclasses, domain,
                     distribution, fdoms, off)


def resolve_x(frame: Frame, x: Sequence[str] | None = None,
              ignored: Sequence[str] | None = None) -> TrainData:
    """Unsupervised variant of resolve_xy: features only, y is a dummy.

    Returned TrainData has y=0, nclasses=1 — usable with build_datainfo
    for one-hot expansion/standardization (KMeans/PCA do the same via
    DataInfo in the reference, hex/kmeans & hex/pca)."""
    from ..runtime.health import require_healthy

    require_healthy()   # same fail-fast gate as the supervised path
    ignored = set(ignored or [])
    names = _feature_names(frame, x, ignored)
    X = frame.to_matrix(names)
    w = frame.valid_mask()
    fdoms = {n: list(frame.vec(n).domain) for n in names
             if frame.vec(n).is_enum()}
    zeros = jnp.zeros(X.shape[0], dtype=jnp.float32)
    return TrainData(names, X, zeros, w, frame.nrows, 1, None,
                     "gaussian", fdoms)


# ---------------------------------------------------------------------------
# Jitted-scorer cache (the compiled serving fast path)
# ---------------------------------------------------------------------------
#
# Serving traffic scores the SAME model at a handful of batch shapes
# thousands of times.  Each model carries one pair of jitted scorer
# callables (plain / with-offset) on the instance (dropped from pickles),
# and warm shapes are tracked per (model key, input schema, padded batch
# shape) so a warm call is zero-compile and zero-retrace: jax.jit keys
# its executable cache on the callable identity + input shapes, batch
# sizes are bucketed to powers of two (score_numpy pads), and compiles
# land in the round-4 persistent XLA cache (runtime/backend.py) so even
# a fresh process warm-starts from disk.
#
# Multi-tenant residency (docs/SERVING.md "Multi-tenant serving"): the
# cache is BYTE-budgeted, not count-capped.  Every resident model is
# charged its live trace + LUT + flat-array device bytes
# (_serving_resident_bytes); past H2O_TPU_SCORER_CACHE_BYTES the
# least-recently-scored model's executables AND device arrays are
# dropped (_serving_evict) while its host-side state (heap trees /
# artifact arrays) stays loaded.  The next score re-promotes: the
# re-trace recompiles the SAME HLO (same constants rebuilt from the
# same host arrays), so with the persistent XLA cache enabled an
# eviction costs a disk cache-hit, never a cold compile — and scores
# are bitwise-identical across evict→promote (tests/test_multitenant).

_SCORE_MIN_BATCH = 128          # smallest padded-batch bucket

_SCORER_STATS = {"hits": 0, "misses": 0, "models": 0, "evictions": 0,
                 "promotions": 0}
# guards cache-entry/jit creation + stats: an HTTP handler thread and
# the REST micro-batcher thread can first-score one model concurrently
_SCORER_LOCK = threading.Lock()

# LRU over models holding a live jitted-scorer cache, plus each
# resident model's byte charge. Without a budget a long-lived REST
# server serving a tenant population grows the set of per-model jitted
# callables (and the flat constant arrays each executable embeds)
# without bound; evicting the least-recently-scored model frees its
# executables + device arrays while the model itself stays loaded.
import collections
import os
import weakref

_SCORER_LRU: "collections.OrderedDict[int, weakref.ref]" = \
    collections.OrderedDict()
_SCORER_BYTES: dict[int, int] = {}      # id(model) -> charged bytes

# per-executable overhead beyond embedded constants + I/O buffers:
# generated code, thunk schedules, jax bookkeeping. Deliberately a
# round conservative constant — the accounting is a budget, not a
# profiler.
_TRACE_OVERHEAD = 64 * 1024
_LUT_BYTES_PER_ENTRY = 80       # dict slot + boxed float + key str


def _scorer_cache_cap() -> int:
    """H2O_TPU_SCORER_CACHE_MAX — optional resident-model COUNT cap on
    top of the byte budget (<= 0 = off, the default since the byte
    budget took over residency control). Read per call so a live
    server can be re-tuned without a restart."""
    try:
        cap = int(os.environ.get("H2O_TPU_SCORER_CACHE_MAX", "0"))
    except ValueError:
        cap = 0
    return max(0, cap)


def _scorer_cache_budget() -> int:
    """H2O_TPU_SCORER_CACHE_BYTES (default 1 GiB) — the resident-bytes
    budget over every model's live serving state; <= 0 = unbounded."""
    try:
        b = int(float(os.environ.get("H2O_TPU_SCORER_CACHE_BYTES",
                                     str(2 ** 30))))
    except ValueError:
        b = 2 ** 30
    return b


def scorer_cache_stats() -> dict[str, int]:
    """Shape-level cache counters: a `miss` is a (model, schema, padded
    batch) triple seen for the first time — i.e. an expected XLA
    trace/compile; warm traffic must add only `hits` (the bench's
    recompile check asserts exactly that). `promotions` is the subset
    of misses that re-traced a shape a previous eviction dropped —
    expected churn under a byte budget, not an SLO violation (the
    /3/Stats warm_cache_misses contract subtracts them). `evictions`
    counts models whose live serving state was dropped by the byte
    budget (H2O_TPU_SCORER_CACHE_BYTES) or the optional count cap
    (H2O_TPU_SCORER_CACHE_MAX); `models` counts cache CREATIONS (the
    historical total), while `resident` counts models holding live
    executables right now, charged `resident_bytes` against
    `budget_bytes`."""
    with _SCORER_LOCK:
        out = dict(_SCORER_STATS)
        resident, rbytes = 0, 0
        for vid, ref in _SCORER_LRU.items():
            # skip GC'd models' stale charges: a re-pushed model_id's
            # old instance may linger in _SCORER_BYTES until the next
            # _cached_score purge, and counting it could report
            # resident_bytes over budget for models that no longer
            # exist (a spurious budget_exceeded in the drills)
            if ref() is not None:
                resident += 1
                rbytes += _SCORER_BYTES.get(vid, 0)
        out["resident"] = resident
        out["resident_bytes"] = rbytes
        out["budget_bytes"] = _scorer_cache_budget()
    return out


def model_scorer_counters(model) -> dict[str, int]:
    """Per-model cache counters (hits/misses/promotions). They live on
    the MODEL (host-side) and survive eviction, so /3/Stats can report
    warm_cache_misses = (misses - promotions) - warm-up baseline: a
    re-trace caused by byte-budget eviction re-baselines out instead
    of reading as an SLO-violating first-request compile."""
    return dict(model.__dict__.get("_scorer_counters")
                or {"hits": 0, "misses": 0, "promotions": 0})


def evict_scorer_cache(model=None) -> int:
    """Ops/test hook: drop one model's live serving state (or EVERY
    resident model's when ``model`` is None) exactly as the byte
    budget would — executables + device arrays go, host-side state
    stays, the next score re-promotes through the persistent XLA
    cache. Returns the number of models evicted."""
    with _SCORER_LOCK:
        victims = []
        if model is None:
            for vid, ref in list(_SCORER_LRU.items()):
                del _SCORER_LRU[vid]
                _SCORER_BYTES.pop(vid, None)
                m = ref()
                if m is not None:
                    victims.append(m)
        elif _SCORER_LRU.pop(id(model), None) is not None:
            _SCORER_BYTES.pop(id(model), None)
            victims.append(model)
        for m in victims:
            m._serving_evict()
            _SCORER_STATS["evictions"] += 1
    return len(victims)


# the scorer cache registers with the process-wide metrics registry
# where it lives: /3/Stats and GET /metrics both render this group
# (runtime/telemetry.py — the fleet-telemetry single source of truth)
from ..runtime.telemetry import register_group as _register_tel_group

_register_tel_group("scorer_cache", scorer_cache_stats)


def _batch_bucket(n: int) -> int:
    """Next power-of-two batch size >= max(n, _SCORE_MIN_BATCH)."""
    b = _SCORE_MIN_BATCH
    while b < n:
        b *= 2
    return b


class Model:
    """Base trained model: predict() + model_performance()."""

    algo = "base"
    # True on models whose _score_matrix is end-to-end jittable
    # (GBM/DRF/XGBoost/GLM/DeepLearning): predict/score_numpy route
    # through the jitted-scorer cache instead of eager op dispatch
    _serving_jit = False

    def __init__(self, data: TrainData):
        self.feature_names = data.feature_names
        self.feature_domains = data.feature_domains
        self.nclasses = data.nclasses
        self.response_domain = data.response_domain
        self.distribution = data.distribution
        self.scoring_history: list[dict[str, Any]] = []
        self.cv = None                    # CVResult when trained with nfolds
        self.validation_metrics: dict[str, float] | None = None
        self.offset_column: str | None = None   # set by offset-aware trains

    # -- h2o-py-style CV accessors (H2OEstimator.cross_validation_*) -------

    def cross_validation_models(self):
        return self.cv.models if self.cv else None

    def cross_validation_holdout_predictions(self):
        return self.cv.holdout_predictions if self.cv else None

    def cross_validation_metrics(self) -> dict[str, float] | None:
        return self.cv.metrics if self.cv else None

    def cross_validation_metrics_summary(self):
        return self.cv.metrics_summary if self.cv else None

    # subclasses implement: _score_matrix(X) -> margin/probs array
    def _score_matrix(self, X: jax.Array) -> jax.Array:
        raise NotImplementedError

    # -- compiled serving fast path -----------------------------------------

    def __getstate__(self):
        # jitted scorer callables are process-local, and the flattened
        # ensemble is derivable from the trees (GBMModel._flat rebuilds
        # it lazily): pickling either would bloat artifacts and make
        # save-before-predict vs save-after-predict differ
        d = dict(self.__dict__)
        d.pop("_scorer_cache", None)
        d.pop("_flat_trees", None)
        d.pop("_serving_luts", None)    # rest.py enum-code LUT cache
        d.pop("_scorer_counters", None)  # process-local accounting
        d.pop("_evicted_shapes", None)
        d.pop("_shap_tables", None)      # device TreeSHAP path tables
        d.pop("_shap_tables_np", None)   # (host caches; rebuildable)
        d.pop("_shap_ctab", None)
        d.pop("_shap_ctab_np", None)
        return d

    def _serving_prepare(self) -> None:
        """Hook: materialize host-built serving state (e.g. the GBM
        flattened ensemble) OUTSIDE the jit trace — device constants
        created while tracing would leak as tracers."""

    def _serving_evict(self) -> None:
        """Drop every piece of serving state that is rebuildable from
        this model's host-side state: the jitted executables, the
        device-resident flat arrays, and the enum-code LUTs. The warm
        shape set is remembered (host-side) so the re-trace on the next
        score is accounted a `promotion`, not a fresh miss."""
        ent = self.__dict__.pop("_scorer_cache", None)
        if ent is not None and ent.get("shapes"):
            self.__dict__.setdefault(
                "_evicted_shapes", set()).update(ent["shapes"])
        self.__dict__.pop("_flat_trees", None)
        self.__dict__.pop("_serving_luts", None)
        # device TreeSHAP tables go too (host _shap_*_np stays, like
        # the heap trees: the re-promote rebuilds the SAME device
        # constants -> same HLO -> a persistent-cache hit)
        self.__dict__.pop("_shap_tables", None)
        self.__dict__.pop("_shap_ctab", None)

    def _serving_resident_bytes(self) -> int:
        """Estimated bytes this model's live serving state pins:
        device flat arrays + enum-code LUTs + one executable per
        traced shape. XLA:CPU embeds closed-over constants per
        compiled executable, so each traced batch bucket is charged
        its own copy of the flat arrays plus its padded I/O buffers —
        deliberately conservative: the budget is for capacity
        planning, not byte-exact profiling."""
        flat = 0
        ft = self.__dict__.get("_flat_trees")
        if ft is not None:
            for leaf in jax.tree_util.tree_leaves(ft):
                flat += int(getattr(leaf, "nbytes", 0) or 0)
        for name in ("_shap_tables", "_shap_ctab"):
            st = self.__dict__.get(name)
            if st is not None:
                # contributions executables embed the path/pattern
                # tables as closed-over constants, like the flat arrays
                for leaf in jax.tree_util.tree_leaves(st):
                    flat += int(getattr(leaf, "nbytes", 0) or 0)
        total = flat
        for lut in (self.__dict__.get("_serving_luts") or {}).values():
            total += _LUT_BYTES_PER_ENTRY * len(lut)
        ent = self.__dict__.get("_scorer_cache")
        if ent:
            K = max(int(getattr(self, "nclasses", 1) or 1), 1)
            for F, batch, _off in ent["shapes"]:
                total += flat + 4 * batch * (F + K) + _TRACE_OVERHEAD
        return total

    def _cached_score(self, X: jax.Array,
                      offset: jax.Array | None = None) -> jax.Array:
        return self._cached_apply(X, offset, "score")

    def _cached_apply(self, X: jax.Array, offset: jax.Array | None,
                      kind: str) -> jax.Array:
        """Dispatch through this model's jitted serving executables,
        tracking warm shapes per (model, schema, padded batch,
        offset?/kind) key and charging this model's resident bytes
        against the cache budget. ``kind`` selects the program:
        "score" -> _score_matrix, "contrib" -> _contrib_matrix (the
        TreeSHAP serving kernel) — both live in the ONE per-model
        cache entry, so eviction/promotion/byte accounting treat a
        model's whole serving footprint as a unit."""
        self._serving_prepare()
        if kind == "contrib":
            self._contrib_prepare()
        with _SCORER_LOCK:
            ent = self.__dict__.get("_scorer_cache")
            if ent is None:
                ent = {"shapes": set()}
                self._scorer_cache = ent
                _SCORER_STATS["models"] += 1
            ctr = self.__dict__.get("_scorer_counters")
            if ctr is None:
                ctr = {"hits": 0, "misses": 0, "promotions": 0}
                self._scorer_counters = ctr
            mid = id(self)
            _SCORER_LRU[mid] = weakref.ref(self)
            _SCORER_LRU.move_to_end(mid)
            skey = (X.shape[1], X.shape[0],
                    "contrib" if kind == "contrib"
                    else offset is not None)
            if skey in ent["shapes"]:
                _SCORER_STATS["hits"] += 1
                ctr["hits"] += 1
            else:
                ent["shapes"].add(skey)
                _SCORER_STATS["misses"] += 1
                ctr["misses"] += 1
                ev = self.__dict__.get("_evicted_shapes")
                if ev and skey in ev:
                    # re-trace of a shape a byte-budget eviction
                    # dropped: a PROMOTION — with the persistent XLA
                    # cache on, its compile is a disk hit (the same
                    # constants rebuilt from the same host arrays
                    # lower to the same HLO), never a cold compile
                    ev.discard(skey)
                    _SCORER_STATS["promotions"] += 1
                    ctr["promotions"] += 1
                # byte accounting + eviction on the MISS branch only:
                # a model's charge changes only when a new shape is
                # traced (device arrays + LUTs are in place before the
                # first score), so the warm hit path pays none of this
                # O(resident models + traced shapes) work under the
                # one lock every scoring thread shares. Purge GC'd
                # models, re-charge this model, then evict least-
                # recently-scored models until the population fits
                # the byte budget (and the optional count cap). The
                # model being scored is never its own victim — a
                # single over-budget model keeps serving.
                for vid in [v for v, r in _SCORER_LRU.items()
                            if r() is None]:
                    del _SCORER_LRU[vid]
                    _SCORER_BYTES.pop(vid, None)
                _SCORER_BYTES[mid] = self._serving_resident_bytes()
                cap = _scorer_cache_cap()
                budget = _scorer_cache_budget()
                while len(_SCORER_LRU) > 1 and (
                        (cap and len(_SCORER_LRU) > cap)
                        or (budget > 0
                            and sum(_SCORER_BYTES.values()) > budget)):
                    vid, ref = next(iter(_SCORER_LRU.items()))
                    if vid == mid:
                        break
                    del _SCORER_LRU[vid]
                    _SCORER_BYTES.pop(vid, None)
                    victim = ref()
                    if victim is None:
                        continue  # model already GC'd: just reclaim
                    victim._serving_evict()
                    _SCORER_STATS["evictions"] += 1
            key = "fn_contrib" if kind == "contrib" else \
                ("fn_off" if offset is not None else "fn")
            fn = ent.get(key)
            if fn is None:
                if kind == "contrib":
                    fn = jax.jit(lambda X: self._contrib_matrix(X))
                elif offset is not None:
                    fn = jax.jit(
                        lambda X, off: self._score_matrix(X, offset=off))
                else:
                    fn = jax.jit(lambda X: self._score_matrix(X))
                ent[key] = fn
        # the (possibly multi-second) trace/compile happens OUTSIDE the
        # lock — jax's own caches are thread-safe; only our bookkeeping
        # needs mutual exclusion
        if kind != "contrib" and offset is not None:
            return fn(X, offset)
        return fn(X)

    def _score(self, X: jax.Array,
               offset: jax.Array | None = None) -> jax.Array:
        """Eager _score_matrix — in-process predict() numerics never
        depend on serving state (a jitted scorer can fuse float ops
        differently, so flipping paths mid-process would let invisible
        REST traffic perturb low-order bits of predict()).

        The jitted-scorer cache belongs to the SERVING entry only
        (score_numpy, which the REST routes ride): one model, many
        requests — worth a per-model trace.  Training-time scoring (CV
        folds, AutoML candidates, validation rounds: many models, a
        call or two each) stays here, where eager tree scoring still
        rides the MODULE-level flat_margin jit that same-shaped fold
        models share."""
        if offset is not None:
            return self._score_matrix(X, offset=offset)
        return self._score_matrix(X)

    # -- compiled TreeSHAP serving (predict_contributions fast path) --------

    def contrib_support(self) -> "str | None":
        """None when this model can serve per-row TreeSHAP
        contributions, else the actionable precondition message — THE
        shared gate for ``predict_contributions``, the serving entry
        ``contrib_numpy``, and the REST route's clean 400 (tree models
        override with the real precondition list)."""
        return (f"model '{self.algo}' does not support "
                "predict_contributions (tree ensembles only)")

    def _shap_sources(self):
        """Hook: (FlatTrees numpy, flat cover numpy) for the TreeSHAP
        path tables — GBMModel flattens its heap trees, a registry
        FlatTreeScorer reads its kept artifact parts."""
        raise NotImplementedError

    def _contrib_enum_mask(self):
        """Hook: the device enum mask the contributions kernel
        canonicalizes NAs with."""
        raise NotImplementedError

    def _contrib_scale_init(self) -> tuple[float, float]:
        """Hook: (scale, init) applied to the raw kernel output."""
        raise NotImplementedError

    def _contrib_prepare(self):
        """Materialize the device TreeSHAP state OUTSIDE the jit
        trace: per-leaf path tables (models/tree/shap.py) plus — when
        it fits the byte gate — the per-pattern contribution table
        that turns the kernel into bit-tests + one gather. Host numpy
        copies are cached separately so a byte-budget eviction (which
        drops only the device arrays) re-promotes with identical
        constants: same HLO, a persistent-cache hit, bitwise-identical
        output."""
        st = self.__dict__.get("_shap_tables")
        ct = self.__dict__.get("_shap_ctab")
        if st is not None and ct is not None:
            return st, ct
        stn = self.__dict__.get("_shap_tables_np")
        if stn is None:
            from .tree.shap import (_PATTERN_TABLE_MAX_BYTES,
                                    build_shap_table_groups,
                                    pattern_table)

            flat, cover = self._shap_sources()
            stn = build_shap_table_groups(flat, cover)
            self._shap_tables_np = stn
            # per-group pattern tables against ONE shared per-model
            # byte budget (a group past the remainder runs the DP
            # kernel) — the tables become per-executable jit constants,
            # so an unbounded total would pin arbitrary device bytes
            # the scorer cache cannot partially evict
            remaining = _PATTERN_TABLE_MAX_BYTES
            ctabs = []
            for g in stn:
                c = pattern_table(g, budget=remaining)
                if c is not None:
                    remaining -= c.nbytes
                ctabs.append(c)
            self._shap_ctab_np = ctabs
        from .tree.shap import ShapTables

        st = [ShapTables(*(jnp.asarray(a) for a in g)) for g in stn]
        ct = [None if c is None else jnp.asarray(c)
              for c in self.__dict__["_shap_ctab_np"]]
        self._shap_tables = st
        self._shap_ctab = ct
        # RETURN the locals (FlatTreeScorer._serving_prepare contract):
        # a concurrent byte-budget eviction may pop the attributes
        # between this return and the caller's read mid-trace
        return st, ct

    def _contrib_matrix(self, X: jax.Array) -> jax.Array:
        """[rows, F+1] contributions on raw features via the jitted
        path-enumeration TreeSHAP kernel (the pattern-table fast path
        when the ensemble is shallow enough for it) — the serving twin
        of ``predict_contributions``, which keeps the f64 host
        recursion as the parity oracle the way predict() stays
        eager."""
        from ..ops.shap_kernel import (flat_shap_tab_kernel, kernel_fits,
                                       resolve_impl)
        from .tree.shap import flat_shap, flat_shap_tab

        groups, ctabs = self._contrib_prepare()
        em = self._contrib_enum_mask()
        # impl resolves at TRACE time (H2O_TPU_SHAP_KERNEL, same
        # semantics as hist_impl): the executable cached under this
        # model's scorer key keeps its impl until evict/re-promote.
        use_kernel = resolve_impl() == "pallas"
        rows = int(X.shape[0])
        phi = None
        for g, ct in zip(groups, ctabs):
            if ct is None:
                p = flat_shap(g, X, em)
            elif use_kernel and kernel_fits(g, ct, rows):
                p = flat_shap_tab_kernel(g, ct, X, em)
            else:
                p = flat_shap_tab(g, ct, X, em)
            phi = p if phi is None else phi + p
        scale, init = self._contrib_scale_init()
        phi = phi * jnp.float32(scale)
        return phi.at[:, -1].add(jnp.float32(init))

    def _contrib_chunk(self) -> int:
        """Rows per TreeSHAP device dispatch. The kernel's working set
        is O(rows · leaves · depth), so deep/wide ensembles shrink the
        chunk to keep transients bounded; H2O_TPU_CONTRIB_CHUNK caps
        it (default 16384, floored to a power of two so every full
        chunk shares ONE trace key)."""
        try:
            cap = int(float(os.environ.get("H2O_TPU_CONTRIB_CHUNK",
                                           "16384")))
        except ValueError:
            cap = 16384
        cap = max(_SCORE_MIN_BATCH, cap)
        c = _SCORE_MIN_BATCH
        while c * 2 <= cap:
            c *= 2
        cap = c
        stn = self.__dict__.get("_shap_tables_np")
        if stn:
            ld = max(g.feat.shape[1] * g.feat.shape[2] for g in stn)
            fit = max((1 << 24) // max(ld, 1), _SCORE_MIN_BATCH)
            while cap > _SCORE_MIN_BATCH and cap > fit:
                cap //= 2
        return cap

    def contrib_numpy(self, X) -> np.ndarray:
        """Serving entry for per-row TreeSHAP contributions: raw
        [n, F] ndarray (training value space, enum codes / NaN NAs)
        -> [n, F+1] float32 contributions, last column the bias term
        (per-tree expectations + init) — additive to the raw margin.

        Same serving discipline as ``score_numpy``: pow2 batch
        padding into the per-model jitted cache (warm traffic is
        zero-retrace), the circuit breaker + device guard around the
        dispatch, and the ``score.dispatch`` fault point. Large
        batches are chunked to ``_contrib_chunk()`` rows so the
        kernel's [rows × leaves × depth] transients stay bounded —
        every full chunk reuses one executable."""
        from ..runtime.health import device_dispatch, require_healthy
        from ..runtime.lifecycle import breaker_guard

        reason = self.contrib_support()
        if reason:
            raise ValueError(reason)
        require_healthy(fault_site=None)
        X = np.asarray(X, dtype=np.float32)
        if X.ndim != 2 or X.shape[1] != len(self.feature_names):
            raise ValueError(
                f"contrib_numpy expects [n, {len(self.feature_names)}] "
                f"(features {self.feature_names}), got {X.shape}")
        n = X.shape[0]
        if n == 0:
            raise ValueError("contrib_numpy: empty batch")
        from ..runtime import faults

        with breaker_guard("contributions scoring"), \
                device_dispatch("contributions scoring", locking=False):
            faults.fire("score.dispatch")
            self._contrib_prepare()
            chunk = self._contrib_chunk()
            outs = []
            for s in range(0, n, chunk):
                xs = X[s:s + chunk]
                b = _batch_bucket(xs.shape[0])
                if b != xs.shape[0]:
                    Xp = np.zeros((b, X.shape[1]), dtype=np.float32)
                    Xp[: xs.shape[0]] = xs
                else:
                    Xp = xs
                out = self._cached_apply(jnp.asarray(Xp), None,
                                         "contrib")
                outs.append(np.asarray(out)[: xs.shape[0]])
        return outs[0] if len(outs) == 1 else np.concatenate(outs)

    def warm_up(self, buckets=None, contributions: bool = False
                ) -> list[int]:
        """Pre-trace the jitted serving scorer at the given batch
        buckets (padded to the pow2 buckets score_numpy actually
        dispatches), so the FIRST real request after a replica goes
        ready pays zero compiles — the operator warm-up contract
        (docs/OPERATOR.md): a scorer-pool replica runs this before its
        ``/readyz`` flips, and warm traffic at any batch size <= the
        largest warmed bucket then adds only cache `hits`.

        The whole pow2 ladder up to the LARGEST requested bucket is
        traced (128, 256, ... top): score_numpy pads any batch to its
        own bucket, so a skipped rung would be a first-request compile
        for batches in that range. ``buckets=None`` reads
        ``H2O_TPU_POOL_WARM_BUCKETS`` (default ``128,1024``). Compiles
        land in the persistent XLA cache (runtime/backend.py), so
        sibling replicas on the same host warm from disk instead of
        recompiling. Returns the bucket sizes warmed, ascending."""
        if not self._serving_jit:
            raise ValueError(
                f"model '{self.algo}' has no jitted serving scorer to "
                "warm (score it through predict() instead)")
        if buckets is None:
            raw = os.environ.get("H2O_TPU_POOL_WARM_BUCKETS", "128,1024")
            buckets = [b for b in raw.replace(" ", "").split(",") if b]
        elif isinstance(buckets, (str, bytes)):
            # a JSON string like "512" would otherwise iterate as the
            # DIGITS ('5','1','2' — top bucket 128) and silently warm
            # the wrong ladder, breaking the zero-miss contract the
            # route then advertises
            raise ValueError(
                f"warm-up buckets must be a list of ints, got the "
                f"string {buckets!r}")
        try:
            top = max(_batch_bucket(int(b)) for b in buckets)
            if min(int(b) for b in buckets) < 1:
                raise ValueError
        except (TypeError, ValueError):
            raise ValueError(
                f"bad warm-up bucket list {buckets!r} (want positive "
                "ints, e.g. 128,1024)") from None
        # the FULL pow2 ladder up to the largest requested bucket:
        # score_numpy pads any n to its own bucket, so skipping a rung
        # would leave batches in that range paying a first-request
        # compile — exactly what the contract forbids
        padded, b = [], _SCORE_MIN_BATCH
        while b <= top:
            padded.append(b)
            b *= 2
        F = len(self.feature_names)
        need_off = bool(getattr(self, "offset_column", None))
        for b in padded:
            # zeros are valid everywhere: enum code 0 is a real level,
            # numerics are finite — the VALUES don't matter, only the
            # (schema, padded-batch, offset?) trace key
            X = np.zeros((b, F), dtype=np.float32)
            off = np.zeros(b, dtype=np.float32) if need_off else None
            self.score_numpy(X, offset=off)
        if contributions:
            # pre-trace the contributions executables too — the ladder
            # is capped at the model's chunk size (contrib_numpy never
            # dispatches a bigger bucket: larger batches split into
            # full chunks + one tail bucket, all <= chunk)
            reason = self.contrib_support()
            if reason:
                raise ValueError(reason)
            done: set[int] = set()
            for b in padded:
                be = min(b, self._contrib_chunk())
                if be in done:
                    continue
                done.add(be)
                self.contrib_numpy(np.zeros((be, F), dtype=np.float32))
        return padded

    def score_numpy(self, X, offset=None) -> np.ndarray:
        """Serving entry: raw [n, F] ndarray (training value space,
        enum codes / NaN NAs) -> [n, K] probabilities or [n]
        predictions, skipping Frame/rollup construction entirely.

        Rows are padded to a power-of-two bucket so warm traffic at
        ANY batch size <= the bucket reuses one compiled executable
        (zero retrace); output is trimmed back to n rows.

        The dispatch runs under the serving circuit breaker
        (runtime/lifecycle.py): consecutive device-dispatch errors trip
        it open and every call is then rejected instantly with
        CircuitOpenError (503 over REST) until the half-open probe
        succeeds — a persistently failing device gets a cooldown, not
        the full brunt of serving traffic."""
        from ..runtime.health import device_dispatch, require_healthy
        from ..runtime.lifecycle import breaker_guard

        require_healthy(fault_site=None)   # fail fast on a locked cloud
        X = np.asarray(X, dtype=np.float32)
        if X.ndim != 2 or X.shape[1] != len(self.feature_names):
            raise ValueError(
                f"score_numpy expects [n, {len(self.feature_names)}] "
                f"(features {self.feature_names}), got {X.shape}")
        n = X.shape[0]
        if n == 0:
            raise ValueError("score_numpy: empty batch")
        if getattr(self, "offset_column", None) and offset is None:
            raise ValueError(
                f"this model was trained with offset_column="
                f"'{self.offset_column}'; pass offset= per row")
        b = _batch_bucket(n)
        if b != n:
            Xp = np.zeros((b, X.shape[1]), dtype=np.float32)
            Xp[:n] = X
        else:
            Xp = X
        offp = None
        if offset is not None:
            offset = np.asarray(offset, dtype=np.float32).reshape(-1)
            if offset.shape[0] != n:
                raise ValueError(
                    f"offset has {offset.shape[0]} rows, X has {n}")
            offp = np.zeros(b, dtype=np.float32)
            offp[:n] = offset
            offp = jnp.asarray(offp)
        from ..runtime import faults

        with breaker_guard("model scoring"), \
                device_dispatch("model scoring", locking=False):
            # the one rehearsable serving fault point: dispatch_error
            # here feeds the breaker without locking the cloud
            faults.fire("score.dispatch")
            if self._serving_jit:
                out = self._cached_score(jnp.asarray(Xp), offp)
            else:
                out = self._score(jnp.asarray(Xp), offp)
            return np.asarray(out)[:n]

    def _design_matrix(self, frame: Frame) -> jax.Array:
        """[padded, F] float32 in TRAINING value space.

        Enum codes from a scoring frame are remapped to the training
        domain (unseen levels → NA); the reference does the same domain
        adaptation in Model.adaptTestForTrain (hex/Model.java).
        """
        cols = []
        for name in self.feature_names:
            v = frame.vec(name)
            tdom = self.feature_domains.get(name)
            if tdom is not None:
                if not v.is_enum():
                    raise ValueError(
                        f"column '{name}' was categorical at training time "
                        f"but is {v.kind} in the scoring frame")
                if list(v.domain) == tdom:
                    cols.append(v.as_float())
                else:
                    lut = {d: i for i, d in enumerate(tdom)}
                    perm = np.array(
                        [lut.get(d, -1) for d in v.domain] + [-1],
                        dtype=np.int32)  # trailing slot = NA code
                    idx = jnp.where(v.data < 0, len(perm) - 1, v.data)
                    remap = jnp.asarray(perm)[idx]
                    cols.append(jnp.where(remap < 0, jnp.nan,
                                          remap.astype(jnp.float32)))
            else:
                if v.is_enum():
                    raise ValueError(
                        f"column '{name}' was numeric at training time "
                        "but is categorical in the scoring frame")
                cols.append(v.as_float())
        return jnp.stack(cols, axis=1)

    def _predict_raw_device(self, frame: Frame) -> jax.Array:
        """Device half of predict_raw: the [padded(, K)] scoring array
        BEFORE the host transfer, dispatched under the device guard.

        The CV fold pipeline (models/cv.py) consumes the transfer on
        its host stream so fold f+1's train can dispatch while fold
        f's holdout predictions come back — JAX dispatch is async, so
        returning the un-transferred array is exactly the overlap
        point."""
        from ..runtime.health import device_dispatch, require_healthy

        # scoring is not a training chunk boundary: it must never
        # consume an armed train.step fault's skip/count budget
        require_healthy(fault_site=None)
        # the guard covers the design-matrix build too: it dispatches
        # per-column device ops, so a chip halting there must surface
        # the same way as one halting mid-score (ValueErrors from the
        # validation below pass through the guard untouched)
        with device_dispatch("model scoring"):
            X = self._design_matrix(frame)
            off = self._frame_offset(frame)
            if off is not None:
                return self._score(X, off)
            return self._score(X)

    def predict_raw(self, frame: Frame) -> np.ndarray:
        """[n, K] class probabilities, or [n] regression predictions.

        Scoring fails fast on a locked cloud (same gate as training)
        and runs its dispatch under the device guard: a runtime error
        escaping the mesh mid-predict (halted chip, dead ICI link)
        surfaces as ClusterHealthError with the locked-cloud recovery
        message, not a raw XLA traceback."""
        from ..runtime.health import device_dispatch

        out_dev = self._predict_raw_device(frame)
        # the transfer stays under the guard too: an async-dispatched
        # device error surfaces HERE, at the first read
        with device_dispatch("model scoring"):
            return np.asarray(out_dev)[: frame.nrows]

    def _frame_offset(self, frame: Frame) -> jax.Array | None:
        """Validated per-row offset column for an offset-trained model
        (None otherwise) — the ONE offset contract, shared by
        predict_raw and the REST micro-batcher path.

        A model trained with an offset needs it at scoring time too
        (hex/Model.adaptTestForTrain errors likewise [U3]); NA offsets
        propagate: a row with no defined base margin has no defined
        prediction (training likewise drops such rows via w=0) —
        coercing to 0 would return a confident number for a row the
        model cannot score."""
        if not getattr(self, "offset_column", None):
            return None
        if self.offset_column not in frame:
            raise ValueError(
                f"this model was trained with offset_column="
                f"'{self.offset_column}' which is missing from "
                "the scoring frame")
        return frame.vec(self.offset_column).as_float()

    def predict(self, frame: Frame) -> Frame:
        """H2O-style prediction frame: `predict` (+ per-class probs)."""
        return self._prediction_frame(self.predict_raw(frame))

    def _prediction_frame(self, out: np.ndarray) -> Frame:
        """Raw predictions -> the H2O-style frame (shared by predict()
        and the REST micro-batcher, which scores raw matrices)."""
        if self.nclasses > 1:
            labels = out.argmax(axis=1).astype(np.int32)
            cols: dict[str, Any] = {"predict": labels}
            dom = self.response_domain or [str(i) for i in
                                           range(self.nclasses)]
            pf = Frame.from_arrays(cols, domains={"predict": dom})
            for k, name in enumerate(dom):
                pf[f"p{name}"] = Vec.from_numpy(out[:, k])
            return pf
        return Frame.from_arrays({"predict": out})

    def partial_plot(self, frame: Frame, cols: Sequence[str],
                     nbins: int = 20, plot: bool = False
                     ) -> list[Frame]:
        """Partial dependence (h2o model.partial_plot, hex/PartialDependence
        [U3]): per column, sweep a value grid, overwrite the column for
        EVERY row, and record the mean (+sd, +std-error) of the model's
        response — positive-class probability for binomial, prediction
        for regression. Returns one Frame per column; `plot` is accepted
        for h2o-py signature parity and ignored (no display surface)."""
        if self.nclasses > 2:
            raise ValueError("partial_plot supports binomial and "
                             "regression models only")
        del plot
        out_frames = []
        n = frame.nrows
        # one design-matrix build; each grid step overwrites a single
        # column on device instead of re-sharding the whole frame
        X = self._design_matrix(frame)
        # PD means must average the model as it actually predicts —
        # scoring at offset 0 would disagree with predict() on the
        # same frame
        off = self._frame_offset(frame)
        for col in cols:
            if col not in self.feature_names:
                raise ValueError(
                    f"partial_plot: '{col}' is not a model feature")
            j = self.feature_names.index(col)
            v = frame.vec(col)
            tdom = self.feature_domains.get(col)
            if tdom is not None:
                # grid/labels in TRAINING domain space — the design
                # matrix is remapped to it, so sweeping the scoring
                # frame's codes would mislabel every row when domains
                # differ
                grid = list(range(len(tdom)))
                labels = list(tdom)
            else:
                x = v.to_numpy()
                finite = x[~np.isnan(x)]
                if finite.size == 0:
                    raise ValueError(f"partial_plot: '{col}' is all-NA")
                # quantile-spaced grid like the reference's default
                grid = list(np.unique(np.quantile(
                    finite, np.linspace(0, 1, nbins))))
                labels = None
            means, sds, sems = [], [], []
            for gv in grid:
                Xg = _set_col_jit(X, j, float(gv))
                pred = np.asarray(self._score(Xg, off))[:n]
                resp = pred[:, 1] if self.nclasses == 2 else pred
                means.append(float(np.mean(resp)))
                sds.append(float(np.std(resp, ddof=1))
                           if n > 1 else 0.0)
                sems.append(sds[-1] / np.sqrt(n))
            pd_out = Frame()
            if labels is not None:
                pd_out[col] = Vec.from_numpy(
                    np.arange(len(grid), dtype=np.int32), col,
                    domain=labels)
            else:
                pd_out[col] = Vec.from_numpy(
                    np.asarray(grid, dtype=np.float32), col)
            pd_out["mean_response"] = Vec.from_numpy(
                np.asarray(means, dtype=np.float32), "mean_response")
            pd_out["stddev_response"] = Vec.from_numpy(
                np.asarray(sds, dtype=np.float32), "stddev_response")
            pd_out["std_error_mean_response"] = Vec.from_numpy(
                np.asarray(sems, dtype=np.float32),
                "std_error_mean_response")
            out_frames.append(pd_out)
        return out_frames

    def confusion_matrix(self, frame: Frame, y: str,
                         threshold: float | None = None) -> np.ndarray:
        """Confusion matrix (rows actual, cols predicted). Binomial:
        2x2 at `threshold` (F1-optimal when None, like the reference's
        default); multinomial: KxK argmax counts."""
        yv = frame.vec(y)
        preds = self.predict_raw(frame)
        if self.nclasses == 2:
            codes = yv.to_numpy()
            ok = codes >= 0 if yv.is_enum() else ~np.isnan(codes)
            return M.confusion_matrix(codes[ok], preds[ok][:, 1],
                                      threshold=threshold)
        if self.nclasses > 2:
            codes = yv.to_numpy()
            ok = codes >= 0
            lab = preds[ok].argmax(axis=1)
            K = self.nclasses
            cm = np.zeros((K, K))
            np.add.at(cm, (codes[ok].astype(int), lab), 1.0)
            return cm
        raise ValueError("confusion_matrix needs a classification model")

    def model_performance(self, frame: Frame, y: str) -> dict[str, float]:
        yv = frame.vec(y)
        out = self.predict_raw(frame)
        ok = ~np.isnan(yv.as_float().__array__()[: frame.nrows]) \
            if not yv.is_enum() else yv.to_numpy() >= 0
        return score_predictions(self.nclasses, self.distribution,
                                 yv.to_numpy()[ok], out[ok])


def score_predictions(nclasses: int, distribution: str,
                      y_true: np.ndarray, preds: np.ndarray
                      ) -> dict[str, float]:
    """Metric dispatch shared by model_performance and CV scoring.

    y_true: class codes (classification) or numeric response; preds:
    [n, K] probabilities or [n] regression predictions — NA rows
    already filtered by the caller.
    """
    if len(y_true) == 0:
        raise ValueError("cannot score an empty holdout "
                         "(no rows with a valid response)")
    if nclasses == 2:
        p1 = preds[:, 1]
        out = {
            "auc": M.roc_auc(y_true, p1),
            "logloss": M.logloss(y_true, p1),
            "rmse": M.rmse(y_true, p1),
        }
        try:
            # threshold table metrics (ModelMetricsBinomial surface);
            # degenerate single-class holdouts keep the basic metrics
            stats = M.binomial_stats(y_true, p1)
            out.update({k: stats[k] for k in
                        ("pr_auc", "gini", "f1", "max_f1_threshold",
                         "mean_per_class_error")})
        except ValueError:
            pass
        return out
    if nclasses > 2:
        lab = preds.argmax(axis=1)
        yc = np.asarray(y_true).astype(int)
        # mean per-class error (reference ModelMetricsMultinomial):
        # average of 1 - recall_k over classes present in the holdout
        errs = [float((lab[yc == k] != k).mean())
                for k in range(nclasses) if np.any(yc == k)]
        # macro one-vs-rest AUC (reference multinomial auc_type=MACRO_OVR)
        aucs = [M.roc_auc((yc == k).astype(np.float32), preds[:, k])
                for k in range(nclasses) if np.any(yc == k)]
        return {
            "logloss": M.multinomial_logloss(y_true, preds),
            "accuracy": M.accuracy(y_true, lab),
            "mean_per_class_error": float(np.mean(errs)) if errs
            else float("nan"),
            "auc": float(np.mean(aucs)) if aucs else float("nan"),
        }
    dist = "poisson" if distribution == "poisson" else "gaussian"
    return {
        "rmse": M.rmse(y_true, preds),
        "mae": M.mae(y_true, preds),
        "r2": M.r2(y_true, preds),
        "mean_residual_deviance": M.mean_residual_deviance(
            y_true, preds, dist),
    }
