"""GLRM — generalized low-rank models via alternating minimization.

Reference: hex/glrm/GLRM.java (SURVEY.md §2b C17): factor a frame as
X ≈ U·Vᵀ (U the [n,k] row representation, V the [d,k] archetypes) by
alternating proximal-gradient updates over per-column losses and
regularizers; missing cells are simply dropped from the loss, which is
what makes GLRM an imputation/compression tool.

TPU design: U is row-sharded over the mesh ROWS axis alongside the
data; V is replicated. One jitted shard_map runs the WHOLE alternating
loop (`lax.fori_loop`): the U-step is per-shard (rows are independent
given V), the V-step accumulates the [d,k] gradient and the [k,k]
Hessian-ish Gram per shard and `psum`s them — the exact MRTask shape
of the reference's update tasks. Losses: quadratic (numeric); the
proximal step implements l2/l1/non-negative regularizers.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from ..frame import Frame
from ..runtime.mesh import ROWS, global_mesh
from ..runtime.mrtask import shard_rows
from .base import Model, resolve_x
from .datainfo import build_datainfo


@dataclass(frozen=True)
class GLRMParams:
    k: int = 2
    loss: str = "quadratic"            # quadratic (per-column losses TBD)
    regularization_x: str = "none"     # none | l2 | l1 | non_negative
    regularization_y: str = "none"
    gamma_x: float = 0.0
    gamma_y: float = 0.0
    max_iterations: int = 100
    learn_rate: float = 1.0   # prox-grad step scale; the Frobenius
    #                           Lipschitz bounds below overestimate the
    #                           true curvature, so 1/L-style steps at
    #                           scale 1.0 remain stable
    transform: str = "STANDARDIZE"     # NONE|DEMEAN|DESCALE|STANDARDIZE
    seed: int = 0


def _expand_mask(dinfo, X, n) -> jax.Array:
    """Observed-cell mask in the EXPANDED column layout (mirrors
    DataInfo.expand minus the intercept): NaN numeric cells and NA enum
    cells are unobserved; rows past `n` are shard padding."""
    cols = [~jnp.isnan(X[:, i]) for i in dinfo.numeric_idx]
    out = [jnp.stack(cols, axis=1)] if cols else []
    for (i, L, has_na, mode) in dinfo.enum_specs:
        ok = ~jnp.isnan(X[:, i])
        width = L - (1 if dinfo.drop_first else 0) + (1 if has_na else 0)
        out.append(jnp.broadcast_to(ok[:, None], (X.shape[0], width)))
    M = jnp.concatenate(out, axis=1)
    live = (jnp.arange(X.shape[0]) < n)[:, None]
    return (M & live).astype(jnp.float32)


def _prox(Z, reg: str, step_gamma):
    if reg == "l2":
        return Z / (1.0 + 2.0 * step_gamma)
    if reg == "l1":
        return jnp.sign(Z) * jnp.maximum(jnp.abs(Z) - step_gamma, 0.0)
    if reg == "non_negative":
        return jnp.maximum(Z, 0.0)
    return Z


def _glrm_shard(A, M, U0, V0, p: GLRMParams):
    """Alternating prox-gradient on one row shard; V updates psum'd."""
    n_tot = lax.psum(jnp.sum(M), ROWS) + 1e-10

    def step(_, carry):
        U, V = carry
        # U-step: rows independent given V (per-shard, no collective)
        R = (U @ V.T - A) * M                        # [r, d] masked resid
        gU = R @ V                                   # [r, k]
        LU = jnp.sum(V * V) + 1e-6                   # Lipschitz-ish bound
        U = _prox(U - (p.learn_rate / LU) * gU,
                  p.regularization_x, p.gamma_x * p.learn_rate / LU)
        # V-step: gradient accumulated across shards (MRTask reduce)
        R = (U @ V.T - A) * M
        gV = lax.psum(R.T @ U, ROWS)                 # [d, k]
        LV = lax.psum(jnp.sum(U * U), ROWS) + 1e-6   # global ||U||² bound
        V = _prox(V - (p.learn_rate / LV) * gV,
                  p.regularization_y, p.gamma_y * p.learn_rate / LV)
        return U, V

    U, V = lax.fori_loop(0, p.max_iterations, step, (U0, V0))
    obj = lax.psum(jnp.sum(((U @ V.T - A) * M) ** 2), ROWS) / n_tot
    return U, V, obj


@functools.partial(jax.jit, static_argnums=(4, 5))
def _glrm_fit(A, M, U0, V0, p: GLRMParams, mesh):
    fn = jax.shard_map(
        functools.partial(_glrm_shard, p=p), mesh=mesh,
        in_specs=(P(ROWS), P(ROWS), P(ROWS), P()),
        out_specs=(P(ROWS), P(), P()))
    return fn(A, M, U0, V0)


class GLRMModel(Model):
    algo = "glrm"

    def __init__(self, data, params, dinfo, U, V, objective, nrows):
        super().__init__(data)
        self.params = params
        self.dinfo = dinfo
        self.U = U                       # [n_pad, k] row factors
        self.V = V                       # [d, k] archetypes
        self.objective = objective
        self.nclasses = 1
        self._nrows = nrows

    def archetypes(self) -> np.ndarray:
        """[k, d] archetype matrix in the transformed space (h2o's
        `archetypes` accessor on the Y frame)."""
        return np.asarray(self.V.T)

    def x_frame(self) -> Frame:
        """The U factors as a Frame (h2o's representation frame)."""
        U = np.asarray(self.U)[: self._nrows]
        return Frame.from_arrays(
            {f"Arch{i+1}": U[:, i] for i in range(U.shape[1])})

    def _solve_u(self, X) -> jax.Array:
        """Per-row ridge solve of U for fixed V on fresh rows. The
        missing mask comes from the RAW matrix — expand() mean-imputes,
        so masking the expanded matrix would treat every cell as
        observed and drag sparse rows toward the column means."""
        Xe = self.dinfo.expand(X)[:, :-1]
        mask = _expand_mask(self.dinfo, X, X.shape[0])
        Xz = jnp.nan_to_num(Xe) * mask
        V = self.V
        G = V.T @ V + 1e-6 * jnp.eye(V.shape[1])
        return Xz @ V @ jnp.linalg.inv(G)

    def reconstruct(self, frame: Frame) -> Frame:
        """Impute/reconstruct a frame through the low-rank model
        (h2o predict → reconstructed columns)."""
        X = self._design_matrix(frame)
        rec = self._solve_u(X) @ self.V.T
        names = self.dinfo.coef_names[:-1]
        out = np.asarray(rec)[: frame.nrows]
        return Frame.from_arrays(
            {f"reconstr_{n}": out[:, i] for i, n in enumerate(names)})

    def _score_matrix(self, X):
        return self._solve_u(X)


class GLRM:
    """H2OGeneralizedLowRankEstimator analog."""

    def __init__(self, **kw):
        from .cv import CVArgs

        CVArgs.pop(kw)
        self.params = GLRMParams(**kw)

    def train(self, training_frame: Frame, x: Sequence[str] | None = None,
              ignored_columns: Sequence[str] | None = None,
              y: str | None = None) -> GLRMModel:
        p = self.params
        if p.loss != "quadratic":
            raise ValueError("only loss='quadratic' is implemented")
        from .pca import _TRANSFORM

        t = p.transform.upper()
        if t not in _TRANSFORM:
            raise ValueError(f"unknown transform '{p.transform}'")
        demean, descale = _TRANSFORM[t]
        ignored = list(ignored_columns or [])
        if y is not None:
            ignored.append(y)
        data = resolve_x(training_frame, x, ignored)
        dinfo = build_datainfo(data, training_frame, standardize=descale,
                               drop_first=False)
        if not demean:
            dinfo.means = np.zeros_like(dinfo.means)
        mesh = global_mesh()
        Xe = dinfo.expand(data.X)[:, :-1]     # drop intercept
        n = training_frame.nrows
        # the loss mask comes from the RAW matrix: expand() mean-imputes
        # NaN, but GLRM's whole point is that missing cells drop out of
        # the objective (hex/glrm loss skips NAs); pad rows mask fully
        M = _expand_mask(dinfo, data.X, n)
        A = jnp.nan_to_num(Xe)
        d = Xe.shape[1]
        if p.k > min(n, d):
            raise ValueError(f"k={p.k} exceeds min(rows, cols)="
                             f"{min(n, d)}")
        key = jax.random.key(p.seed)
        k1, k2 = jax.random.split(key)
        U0 = shard_rows(np.asarray(
            jax.random.normal(k1, (Xe.shape[0], p.k)) * 0.1))
        V0 = jax.random.normal(k2, (d, p.k)) * 0.1
        U, V, obj = _glrm_fit(A, M, U0, V0, p, mesh)
        return GLRMModel(data, p, dinfo, U, V, float(obj), n)
