"""DRF — distributed random forest on the shared histogram tree core.

Reference: hex/tree/drf/DRF.java (SURVEY.md §2b C10) — SharedTree with
bootstrap row sampling, per-split feature sampling (`mtries`), and no
boosting: trees fit the raw target independently and predictions
average across trees. With g = -y, h = 1 the shared core's leaf value
-G/H is exactly the in-leaf mean of y (CART variance-reduction splits),
so classification leaves hold P(class) directly — no link function.

Depth note: the reference allows max_depth up to 20 via dynamic row
partitions; the dense-heap TPU layout is per-level O(2^d · F · B), so
the practical default here is 12 with 64 bins (XRT-style capped depth).
"""

from __future__ import annotations

from dataclasses import replace
from typing import Sequence

import numpy as np

from ..frame import Frame
from .base import resolve_xy
from .gbm import GBM, GBMModel, GBMParams


class DRFModel(GBMModel):
    algo = "drf"


class DRF(GBM):
    """H2ORandomForestEstimator analog."""

    model_cls = DRFModel

    def __init__(self, ntrees: int = 50, max_depth: int = 12,
                 nbins: int = 64, sample_rate: float = 0.632,
                 mtries: int = -1, min_rows: float = 1.0, **kw):
        kw.setdefault("min_split_improvement", 1e-5)
        super().__init__(ntrees=ntrees, max_depth=max_depth, nbins=nbins,
                         sample_rate=sample_rate, min_rows=min_rows, **kw)
        self.params._drf_mode = True
        self.params.learn_rate = 1.0
        self._mtries_arg = mtries

    def _resolve_mtries(self, y: str, training_frame: Frame,
                        x: Sequence[str] | None,
                        ignored_columns=None, weights_column=None
                        ) -> None:
        """Resolve the mtries default into self.params — sqrt(F) for
        classification, F/3 for regression (reference DRF defaults) —
        from column names only, without materializing the design
        matrix twice.  Shared by train() and compile-ahead so the
        pre-lowered TreeParams carry the same mtries the dispatch
        will."""
        ignored = set(ignored_columns or [])
        ignored.add(y)
        if self.cv_args.fold_column:
            ignored.add(self.cv_args.fold_column)
        if weights_column:
            ignored.add(weights_column)
        names = list(x) if x else [
            n for n in training_frame.names
            if n not in ignored and
            training_frame.vec(n).kind in ("numeric", "enum", "time")]
        F = len(names)
        classification = training_frame.vec(y).is_enum()
        # H2O semantics: -1 → sqrt(F) classification / F/3 regression
        # (the default), -2 → all features, >0 → that many
        if self._mtries_arg == -1:
            m = int(np.sqrt(F)) if classification else max(F // 3, 1)
            self.params.mtries = max(m, 1)
        elif self._mtries_arg == -2:
            self.params.mtries = -1          # TreeParams: <=0 disables
        elif self._mtries_arg > 0:
            self.params.mtries = self._mtries_arg
        else:
            raise ValueError(f"mtries must be -1, -2 or > 0, "
                             f"got {self._mtries_arg}")

    def train(self, y: str, training_frame: Frame,
              x: Sequence[str] | None = None, **kw) -> DRFModel:
        self._resolve_mtries(y, training_frame, x,
                             kw.get("ignored_columns"),
                             kw.get("weights_column"))
        return super().train(y=y, training_frame=training_frame, x=x, **kw)

    def compile_ahead_lowerings(self, y: str, training_frame: Frame,
                                x: Sequence[str] | None = None) -> list:
        try:
            self._resolve_mtries(y, training_frame, x)
        except (ValueError, KeyError):
            return []                 # train() will raise it properly
        return super().compile_ahead_lowerings(y, training_frame, x)
