"""NaiveBayes — class-conditional stats in one MRTask pass.

Reference: hex/naivebayes/NaiveBayes.java (SURVEY.md §2b C17): one pass
accumulates per-class counts, per-(class, numeric feature) mean/sd and
per-(class, categorical level) frequencies; prediction scores
log-priors + gaussian/frequency log-likelihoods. Laplace smoothing for
categorical probabilities, min_sdev floor for numeric sdevs.

TPU design: all accumulations are one-hot matmuls ([K,r]x[r,F] on the
MXU) inside a single `doall` (runtime/mrtask.py) — the reference's
MRTask.map/reduce — with NA-aware masking so missing cells drop out of
their feature's statistics only.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..frame import Frame
from ..runtime.mrtask import doall
from .base import Model, resolve_xy


@dataclass
class NaiveBayesParams:
    laplace: float = 0.0
    min_sdev: float = 1e-3
    seed: int = 0


class NaiveBayesModel(Model):
    algo = "naivebayes"

    def __init__(self, data, params, priors, num_mean, num_sd,
                 enum_tables, enum_cols, num_cols):
        super().__init__(data)
        self.params = params
        self.priors = priors            # [K]
        self.num_mean = num_mean        # [K, Fnum]
        self.num_sd = num_sd            # [K, Fnum]
        self.enum_tables = enum_tables  # per enum col: [K, L] probs
        self.enum_cols = enum_cols      # X column indices of enums
        self.num_cols = num_cols        # X column indices of numerics

    def _score_matrix(self, X):
        K = self.nclasses
        ll = jnp.log(self.priors)[None, :]             # [r, K]
        ll = jnp.broadcast_to(ll, (X.shape[0], K))
        if self.num_cols:
            Xn = X[:, jnp.asarray(self.num_cols)]      # [r, Fn]
            mu = self.num_mean                          # [K, Fn]
            sd = self.num_sd
            z = (Xn[:, None, :] - mu[None, :, :]) / sd[None, :, :]
            lp = -0.5 * z * z - jnp.log(sd)[None, :, :]
            lp = jnp.where(jnp.isnan(Xn)[:, None, :], 0.0, lp)  # NA drops
            ll = ll + jnp.sum(lp, axis=2)
        for ci, tab in zip(self.enum_cols, self.enum_tables):
            c = X[:, ci]
            L = tab.shape[1]
            code = jnp.where(jnp.isnan(c), 0, c).astype(jnp.int32)
            code = jnp.clip(code, 0, L - 1)
            lp = jnp.log(tab.T)[code]                  # [r, K]
            lp = jnp.where(jnp.isnan(c)[:, None], 0.0, lp)
            ll = ll + lp
        m = jnp.max(ll, axis=1, keepdims=True)
        p = jnp.exp(ll - m)
        return p / jnp.sum(p, axis=1, keepdims=True)


class NaiveBayes:
    """H2ONaiveBayesEstimator analog (classification only)."""

    def __init__(self, **kw):
        from .cv import CVArgs

        self.cv_args = CVArgs.pop(kw)
        self.params = NaiveBayesParams(**kw)

    def train(self, y: str, training_frame: Frame,
              x: Sequence[str] | None = None,
              ignored_columns: Sequence[str] | None = None,
              weights_column: str | None = None,
              validation_frame: Frame | None = None) -> NaiveBayesModel:
        p = self.params
        if self.cv_args.fold_column:
            ignored_columns = list(ignored_columns or []) + \
                [self.cv_args.fold_column]
        data = resolve_xy(training_frame, y, x, ignored_columns,
                          weights_column, "auto")
        if data.nclasses < 2:
            raise ValueError("NaiveBayes needs a categorical response")
        K = data.nclasses
        num_cols = [i for i, n in enumerate(data.feature_names)
                    if n not in data.feature_domains]
        enum_cols = [i for i, n in enumerate(data.feature_names)
                     if n in data.feature_domains]
        enum_L = [len(data.feature_domains[data.feature_names[i]])
                  for i in enum_cols]

        ni = jnp.asarray(num_cols, dtype=jnp.int32) if num_cols else None

        def map_fn(X, yv, w):
            yoh = (yv[:, None] == jnp.arange(K)[None, :]) * w[:, None]
            out = {"class_w": jnp.sum(yoh, axis=0)}       # [K]
            if ni is not None:
                Xn = X[:, ni]
                val = (~jnp.isnan(Xn)).astype(jnp.float32)
                Xn0 = jnp.nan_to_num(Xn)
                out["n_sum"] = yoh.T @ Xn0                # [K,Fn] MXU
                out["n_sumsq"] = yoh.T @ (Xn0 * Xn0)
                out["n_cnt"] = yoh.T @ (val * 1.0)
            for j, (ci, L) in enumerate(zip(enum_cols, enum_L)):
                c = X[:, ci]
                code = jnp.where(jnp.isnan(c), L, c).astype(jnp.int32)
                coh = (code[:, None] == jnp.arange(L)[None, :]) * 1.0
                out[f"e{j}"] = yoh.T @ coh                # [K,L]
            return out

        stats = doall(map_fn, data.X, data.y, data.w, reduce="sum")
        cw = np.asarray(stats["class_w"], dtype=np.float64)
        priors = cw / cw.sum()
        if num_cols:
            cnt = np.maximum(np.asarray(stats["n_cnt"]), 1.0)
            mean = np.asarray(stats["n_sum"]) / cnt
            var = np.asarray(stats["n_sumsq"]) / cnt - mean ** 2
            sd = np.sqrt(np.maximum(var, 0.0))
            sd = np.maximum(sd, p.min_sdev)
        else:
            mean = sd = np.zeros((K, 0), dtype=np.float32)
        tables = []
        for j, L in enumerate(enum_L):
            t = np.asarray(stats[f"e{j}"], dtype=np.float64) + p.laplace
            denom = t.sum(axis=1, keepdims=True)
            denom = np.where(denom > 0, denom, 1.0)
            tab = np.maximum(t / denom, 1e-10)            # avoid log(0)
            tables.append(jnp.asarray(tab.astype(np.float32)))

        model = NaiveBayesModel(
            data, p, jnp.asarray(priors.astype(np.float32)),
            jnp.asarray(mean.astype(np.float32)),
            jnp.asarray(sd.astype(np.float32)),
            tables, enum_cols, num_cols)
        from .cv import finalize_train

        return finalize_train(
            self, model, y, training_frame,
            {"x": x, "ignored_columns": ignored_columns,
             "weights_column": weights_column},
            validation_frame)
