"""N-fold cross-validation shared by every supervised estimator.

The analog of the reference's ModelBuilder CV plumbing
(hex/ModelBuilder.java computeCrossValidation — fold assignment, one
cv-model per fold trained on the complement, holdout predictions kept
for metrics and for Stacked Ensembles; SURVEY.md §2b C15/C16):

- fold assignment schemes mirror H2O's ``fold_assignment`` enum:
  AUTO(→Random), Random, Modulo, Stratified, plus an explicit
  ``fold_column``;
- each fold model trains on the out-of-fold rows and predicts the
  in-fold rows; the concatenated holdout predictions are scored once
  ("combined holdout metrics", H2O's main CV metric surface) and are
  exactly what StackedEnsemble consumes as level-one data;
- per-fold metrics are summarised mean ± std (H2O's
  cross_validation_metrics_summary).

Estimators opt in by constructing with ``nfolds=...`` (and optionally
``fold_assignment=`` / ``fold_column=``), exactly like h2o-py.
"""

from __future__ import annotations

import copy
from dataclasses import dataclass, field
from typing import Any

import numpy as np

from ..frame import Frame

_CV_KEYS = ("nfolds", "fold_assignment", "fold_column",
            "keep_cross_validation_predictions",
            "keep_cross_validation_models")


@dataclass
class CVArgs:
    """CV knobs popped off an estimator's **kwargs (h2o-py surface)."""

    nfolds: int = 0
    fold_assignment: str = "auto"     # auto | random | modulo | stratified
    fold_column: str | None = None
    keep_cross_validation_predictions: bool = True
    keep_cross_validation_models: bool = True

    @classmethod
    def pop(cls, kw: dict) -> "CVArgs":
        args = {k: kw.pop(k) for k in _CV_KEYS if k in kw}
        out = cls(**args)
        if out.fold_assignment.lower() not in (
                "auto", "random", "modulo", "stratified"):
            raise ValueError(
                f"unknown fold_assignment '{out.fold_assignment}'")
        return out

    @property
    def enabled(self) -> bool:
        return self.nfolds >= 2 or self.fold_column is not None


@dataclass
class CVResult:
    """Attached to a model as .cross_validation_* (h2o-py accessors)."""

    fold_ids: np.ndarray
    models: list | None
    holdout_predictions: np.ndarray | None   # [n, K] probs or [n] preds
    metrics: dict[str, float]                # combined-holdout metrics
    metrics_summary: dict[str, dict[str, float]]  # per-metric mean/std
    fold_metrics: list[dict[str, float]] = field(default_factory=list)


def fold_ids(n: int, nfolds: int, scheme: str = "auto",
             y: np.ndarray | None = None, seed: int = 0) -> np.ndarray:
    """Per-row fold index in [0, nfolds) under an H2O assignment scheme."""
    scheme = scheme.lower()
    if scheme == "modulo":
        return (np.arange(n) % nfolds).astype(np.int32)
    rng = np.random.default_rng(seed if seed >= 0 else None)
    if scheme in ("auto", "random"):
        return rng.integers(0, nfolds, size=n).astype(np.int32)
    if scheme == "stratified":
        if y is None:
            raise ValueError("stratified fold assignment needs a "
                             "categorical response")
        out = np.empty(n, dtype=np.int32)
        start = 0
        for cls_val in np.unique(y):
            idx = np.flatnonzero(y == cls_val)
            rng.shuffle(idx)
            # round-robin within the class, rotating the starting fold
            # across classes so small classes don't all land in fold 0
            out[idx] = (np.arange(len(idx)) + start) % nfolds
            start += len(idx)
        return out
    raise ValueError(f"unknown fold_assignment '{scheme}'")


def _combined_metrics(model, y_true_codes, is_enum, preds,
                      dist: str) -> dict[str, float]:
    """Score concatenated holdout predictions (H2O's headline CV metric)."""
    from .base import score_predictions

    ok = (y_true_codes >= 0) if is_enum else ~np.isnan(y_true_codes)
    return score_predictions(model.nclasses, dist, y_true_codes[ok],
                             preds[ok])


def cross_validate(est, y: str, frame: Frame, cv: CVArgs,
                   train_kw: dict[str, Any], seed: int = 0) -> CVResult:
    """Train one model per fold; returns holdout preds + metric summary.

    ``est`` is the configured estimator; each fold trains a deep copy
    with CV disabled (the reference likewise clones the builder per
    fold, ModelBuilder.cv_makeFramesAndBuilders).
    """
    n = frame.nrows
    yv = frame.vec(y)
    if cv.fold_column is not None:
        fv = frame.vec(cv.fold_column)
        fc = fv.to_numpy()
        has_na = (fc < 0).any() if fv.is_enum() else \
            np.isnan(np.asarray(fc, dtype=np.float64)).any()
        if has_na:
            raise ValueError(f"fold_column '{cv.fold_column}' has NAs")
        codes = np.unique(fc)
        folds = np.searchsorted(codes, fc).astype(np.int32)
        nfolds = len(codes)
        if nfolds < 2:
            raise ValueError("fold_column must define >= 2 folds")
    else:
        nfolds = cv.nfolds
        if nfolds > n:
            raise ValueError(f"nfolds={nfolds} > {n} rows")
        scheme = cv.fold_assignment.lower()
        if scheme == "auto":
            scheme = "random"
        if scheme == "stratified" and not yv.is_enum():
            raise ValueError("stratified folds need a categorical response")
        folds = fold_ids(n, nfolds, scheme,
                         yv.to_numpy() if yv.is_enum() else None, seed)
    counts = np.bincount(folds, minlength=nfolds)
    if (counts == 0).any():
        # the reference rejects degenerate fold maps up front
        # (ModelBuilder.cv_init) rather than training on a full frame
        raise ValueError(
            f"fold assignment left fold(s) "
            f"{np.flatnonzero(counts == 0).tolist()} empty "
            f"(nfolds={nfolds}, nrows={n})")

    tkw = dict(train_kw)
    tkw.pop("validation_frame", None)
    fold_col_ignore = [cv.fold_column] if cv.fold_column else []
    if fold_col_ignore:
        ignored = list(tkw.get("ignored_columns") or []) + fold_col_ignore
        tkw["ignored_columns"] = ignored

    # SHAPE-SHARED fold training (compile-dominated regime): instead of
    # slicing per-fold frames (each a new row shape → every jitted
    # program recompiles per fold AND for the final fit), train each
    # fold model on the FULL frame with the holdout rows' weights
    # zeroed. All fold fits + the final fit then share one row shape,
    # one binned matrix and one set of XLA executables — the dominant
    # share of a cold AutoML's compile count (232 → 166 measured,
    # AUTOML_R04SHAPE_r05.json). Holdout rows still carry zero
    # loss/histogram/Gram weight (w=0 is the established dead-row
    # convention); frame-global statistics (quantile bin edges, mean
    # imputation, standardization) see the holdout feature
    # distributions — the same global-binning semantics LightGBM's cv
    # uses, and label-free. The trade: each fold model computes over
    # all n rows (n/(nfolds-1)·nfolds extra FLOPs) — a clear win on
    # TPU, where a fold fit is milliseconds and every avoided compile
    # is a REMOTE round trip, and a measured loss on the CPU mesh
    # (+22% wall at 30k rows on 1 core), so it gates on the backend.
    # Above the row threshold the classic sliced-frame CV runs either
    # way (at 10M rows fold FLOPs dwarf compiles). Env overrides:
    # H2O_TPU_CV_SHAPE_SHARE_ROWS=0 disables, =N forces the threshold
    # on any backend.
    import os

    import jax

    _thresh_env = os.environ.get("H2O_TPU_CV_SHAPE_SHARE_ROWS")
    if _thresh_env is not None:
        share = n <= int(_thresh_env)
    else:
        share = jax.default_backend() == "tpu" and n <= 1_000_000
    wcol = tkw.get("weights_column")
    mask_col = "_cv_mask_w_"
    if mask_col in frame.names:       # collision: fall back, stay correct
        share = False
    if share:
        from ..frame import Vec

        base_w = (np.asarray(frame.vec(wcol).as_float())[:n]
                  if wcol else np.ones(n, dtype=np.float32))
        tkw_share = dict(tkw)
        tkw_share["weights_column"] = mask_col
        if wcol:
            # the original weights column is folded into the mask; it
            # must stay EXCLUDED from features (resolve_xy only ignores
            # the active weights_column)
            tkw_share["ignored_columns"] = list(
                tkw.get("ignored_columns") or []) + [wcol]

    y_codes_all = yv.to_numpy() if yv.is_enum() else \
        np.asarray(yv.as_float())[:n]

    # -- fold pipelining (runtime/scheduler.py kill switch) -----------
    # JAX dispatch is async, so fold f's holdout-prediction TRANSFER +
    # metric extraction (host work) can ride a one-worker host stream
    # while fold f+1's train dispatches on the main thread; in sliced
    # mode the same worker also prefetches fold f+1's frame slices when
    # they take select_rows' HOST-gather path (the device-gather path
    # stays on the main thread: only the device-token holder may
    # dispatch device programs — tests/conftest.py rendezvous rule).
    # Results are deterministic either way: tasks run on ONE worker in
    # submission order and every fold's metrics are a pure function of
    # its predictions. H2O_TPU_AUTOML_PIPELINE=0 restores the serial
    # loop bit-for-bit.
    from ..runtime import scheduler as _sched

    pipe = nfolds >= 2 and _sched.pipeline_enabled()
    if not pipe:
        models, fold_metrics = [], []
        preds = None
        for k in range(nfolds):
            hold = folds == k
            clone = copy.deepcopy(est)
            clone.cv_args = CVArgs()        # fold models never recurse
            if share:
                wk = np.where(hold, 0.0, base_w).astype(np.float32)
                vecs = {nm: frame.vec(nm) for nm in frame.names}
                vecs[mask_col] = Vec.from_numpy(wk, mask_col)
                m = clone.train(y=y, training_frame=Frame(vecs),
                                **tkw_share)
                pk_full = m.predict_raw(frame)  # full shape: shared
                pk = pk_full[hold]              # program
            else:
                m = clone.train(y=y,
                                training_frame=frame.select_rows(~hold),
                                **tkw)
                pk = m.predict_raw(frame.select_rows(hold))
            if preds is None:
                preds = np.zeros((n,) + pk.shape[1:], dtype=pk.dtype)
            preds[hold] = pk
            # fold metrics straight from pk — a model_performance()
            # call would rebuild the design matrix and re-score
            fold_metrics.append(_combined_metrics(
                m, y_codes_all[hold], yv.is_enum(), pk, m.distribution))
            models.append(m)
    else:
        models, fold_metrics, preds = _cross_validate_pipelined(
            est, y, frame, folds, nfolds, share,
            tkw_share if share else tkw,
            base_w if share else None, mask_col, y_codes_all, yv, n)

    keys = fold_metrics[0].keys()
    summary = {key: {"mean": float(np.mean([fm[key] for fm in fold_metrics])),
                     "std": float(np.std([fm[key] for fm in fold_metrics]))}
               for key in keys}
    combined = _combined_metrics(models[0], y_codes_all, yv.is_enum(),
                                 preds, models[0].distribution)
    return CVResult(
        fold_ids=folds,
        models=models if cv.keep_cross_validation_models else None,
        holdout_predictions=(preds if
                             cv.keep_cross_validation_predictions else None),
        metrics=combined, metrics_summary=summary,
        fold_metrics=fold_metrics)


def _cross_validate_pipelined(est, y, frame: Frame, folds, nfolds: int,
                              share: bool, tkw: dict, base_w,
                              mask_col: str, y_codes_all, yv, n: int):
    """The pipelined fold loop — numerics identical to the serial one
    (same train calls in the same order on the main thread, same
    per-fold metric computation), with the holdout transfer + metric
    extraction (and eligible slice prefetches) on a one-worker host
    stream. Returns (models, fold_metrics, preds)."""
    from concurrent.futures import ThreadPoolExecutor

    from ..frame import Vec
    from ..frame.frame import _device_gather_min
    from ..runtime.health import device_dispatch
    from ..runtime.mesh import ROWS, global_mesh

    models: list = [None] * nfolds
    fold_metrics: list = [None] * nfolds
    box: dict = {}                      # {"preds": ndarray} once known

    def extract(k, m, hold, out_dev, hold_n):
        # the transfer stays under the device guard, like predict_raw:
        # an async-dispatched device error surfaces at this first read
        with device_dispatch("model scoring"):
            arr = np.asarray(out_dev)
        if share:
            pk = arr[:n][hold]
        else:
            pk = arr[:hold_n]
        if "preds" not in box:
            box["preds"] = np.zeros((n,) + pk.shape[1:], dtype=pk.dtype)
        box["preds"][hold] = pk
        fold_metrics[k] = _combined_metrics(
            m, y_codes_all[hold], yv.is_enum(), pk, m.distribution)

    # slice prefetch rides the worker ONLY on select_rows' host-gather
    # path; past the device-gather threshold the gather is a device
    # program and belongs to the main (device-token) thread
    from jax.sharding import NamedSharding, PartitionSpec as P

    sharding = NamedSharding(global_mesh(), P(ROWS))
    prefetch_ok = (not share) and (
        n < _device_gather_min() or not sharding.is_fully_addressable)

    def make_slices(hold):
        return frame.select_rows(~hold), frame.select_rows(hold)

    pool = ThreadPoolExecutor(max_workers=1,
                              thread_name_prefix="h2o-cv-host")
    slice_futs: list = [None] * nfolds
    metric_futs: list = [None] * nfolds
    try:
        for k in range(nfolds):
            # fail fast like the serial loop: a COMPLETED earlier
            # fold's extraction error surfaces before the next train
            # dispatches (done() keeps the check non-blocking, so the
            # pipeline overlap is untouched)
            for fut in metric_futs[:k]:
                if fut is not None and fut.done():
                    fut.result()
            hold = folds == k
            clone = copy.deepcopy(est)
            clone.cv_args = CVArgs()        # fold models never recurse
            if share:
                wk = np.where(hold, 0.0, base_w).astype(np.float32)
                vecs = {nm: frame.vec(nm) for nm in frame.names}
                vecs[mask_col] = Vec.from_numpy(wk, mask_col)
                tr_frame, hold_frame = Frame(vecs), frame
            elif slice_futs[k] is not None:
                tr_frame, hold_frame = slice_futs[k].result()
            else:
                tr_frame, hold_frame = make_slices(hold)
            if prefetch_ok and k + 1 < nfolds:
                # submitted BEFORE the train so it overlaps fold k's
                # device work (FIFO worker: it runs after fold k-1's
                # metric extraction)
                slice_futs[k + 1] = pool.submit(make_slices,
                                                folds == (k + 1))
            m = clone.train(y=y, training_frame=tr_frame, **tkw)
            models[k] = m
            out_dev = m._predict_raw_device(hold_frame)
            metric_futs[k] = pool.submit(extract, k, m, hold, out_dev,
                                         hold_frame.nrows)
        for fut in metric_futs:
            fut.result()            # re-raise fold task errors in order
    finally:
        pool.shutdown(wait=True)
    return models, fold_metrics, box["preds"]


def finalize_train(est, model, y: str, training_frame: Frame,
                   train_kw: dict[str, Any],
                   validation_frame: Frame | None = None):
    """Post-train hook every supervised estimator calls: validation
    metrics + optional CV. Returns the (annotated) model."""
    if validation_frame is not None:
        model.validation_metrics = model.model_performance(
            validation_frame, y)
    cv = getattr(est, "cv_args", None)
    if cv is not None and cv.enabled:
        seed = int(getattr(est.params, "seed", 0) or 0)
        model.cv = cross_validate(est, y, training_frame, cv, train_kw,
                                  seed=seed)
    else:
        model.cv = None
    return model
