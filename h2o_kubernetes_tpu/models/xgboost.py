"""XGBoost-hist semantics on the shared TPU histogram tree core.

The reference bundles native XGBoost behind a JNI extension
(h2o-extensions/xgboost: XGBoost.java converts Frame→DMatrix and drives
xgboost4j with tree_method=hist/gpu_hist; Rabit allreduces histograms —
SURVEY.md §2b C14). The TPU rebuild needs no foreign library: the same
regularized-gain hist algorithm runs on the shared tree core
(models/tree/core.py), whose per-level psum over the ROWS mesh axis IS
the Rabit allreduce, now on ICI.

XGBoost-specific semantics implemented here, distinct from H2O GBM:
- split gain regularized by `reg_lambda` (default 1.0), `reg_alpha`,
  `gamma` (min loss reduction), `min_child_weight` on hessian mass;
- objective aliases (reg:squarederror, binary:logistic, multi:softprob,
  count:poisson) and base_score-style flat init;
- learning-to-rank: rank:pairwise and rank:ndcg (LambdaMART) over a
  query `group_column`, the reference's MSLR-WEB30K lambdarank config
  (BASELINE.json:9). Pairwise lambda gradients are computed in a dense
  [groups, max_docs] layout in fixed-size group batches (lax.map), so
  the whole objective stays jittable with static shapes.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .. import metrics as M
from ..frame import Frame
from ..runtime.health import require_healthy
from .base import resolve_xy
from .gbm import GBM, GBMModel, _stacked_varimp
from .tree.binning import fit_bins
from .tree.core import TreeParams

_OBJECTIVE_ALIASES = {
    "reg:squarederror": "gaussian",
    "reg:linear": "gaussian",
    "binary:logistic": "bernoulli",
    "multi:softprob": "multinomial",
    "multi:softmax": "multinomial",
    "count:poisson": "poisson",
    "rank:pairwise": "rank:pairwise",
    "rank:ndcg": "rank:ndcg",
}


class XGBoostModel(GBMModel):
    algo = "xgboost"
    _group_column: str | None = None

    def _score_matrix(self, X: jax.Array,
                      offset: jax.Array | None = None) -> jax.Array:
        if self.distribution.startswith("rank:"):
            return self._margins(X, offset)  # raw ranking scores
        return super()._score_matrix(X, offset)

    def model_performance(self, frame: Frame, y: str,
                          group_column: str | None = None,
                          k: int = 10) -> dict[str, float]:
        if self.distribution.startswith("rank:"):
            gcol = group_column or self._group_column
            score = self.predict_raw(frame)
            yv = frame.vec(y).to_numpy()
            g = frame.vec(gcol).to_numpy()
            return {f"ndcg@{k}": M.ndcg(yv, score, g, k=k)}
        return super().model_performance(frame, y)


# ---------------------------------------------------------------------------
# LambdaMART gradients
# ---------------------------------------------------------------------------

class _GroupLayout:
    """Host-side query-group layout: row-order ↔ dense [G, M] mapping."""

    def __init__(self, group_ids: np.ndarray, padded_len: int):
        uniq, inv = np.unique(group_ids, return_inverse=True)
        self.n_groups = len(uniq)
        sizes = np.bincount(inv, minlength=self.n_groups)
        self.max_docs = int(sizes.max()) if len(sizes) else 1
        G, Mx = self.n_groups, self.max_docs
        order = np.argsort(inv, kind="stable")       # rows grouped together
        starts = np.concatenate([[0], np.cumsum(sizes)[:-1]])
        slot = np.arange(len(inv)) - starts[inv[order]]  # within-group slot
        idx = np.full(G * Mx, -1, dtype=np.int32)
        pos = np.full(padded_len, -1, dtype=np.int32)
        flat = inv[order] * Mx + slot
        idx[flat] = order.astype(np.int32)
        pos[order] = flat.astype(np.int32)
        idx = idx.reshape(G, Mx)
        self.idx = jnp.asarray(idx)          # [G, M] row index or -1
        self.pos = jnp.asarray(pos)          # [padded] flat dense pos or -1
        self.mask = jnp.asarray(idx >= 0)    # [G, M]


def _dense_layout(y, idx, mask):
    """Row-sharded y → [G, M] dense group layout + ideal DCG, in one
    compiled program (no eager sharded gathers on the hot setup path)."""
    y_dense = jnp.where(mask, y[jnp.maximum(idx, 0)], 0.0)
    return y_dense, _ideal_dcg(y_dense, mask)


_dense_layout_jit = jax.jit(_dense_layout)


def _ideal_dcg(y_dense: jax.Array, mask: jax.Array) -> jax.Array:
    """Max DCG per group over the full list (LambdaMART normalizer)."""
    gains = jnp.where(mask, 2.0 ** y_dense - 1.0, 0.0)
    srt = jnp.sort(gains, axis=1)[:, ::-1]
    disc = 1.0 / jnp.log2(jnp.arange(2, gains.shape[1] + 2))
    return jnp.sum(srt * disc[None, :], axis=1)


def _lambda_grads_batch(f, y, mask, maxdcg, use_ndcg: bool):
    """Pairwise lambda gradients for one batch of groups.

    f, y, mask: [B, M]; maxdcg: [B]. Returns (g, h): [B, M] each.
    For each in-group pair with y_i > y_j: cross-entropy on the score
    difference, weighted by |ΔNDCG| when use_ndcg (Burges LambdaRank).
    """
    fm = jnp.where(mask, f, -jnp.inf)
    # current 1-based rank of each doc within its group (desc by score)
    order = jnp.argsort(-fm, axis=1, stable=True)
    rank = jnp.argsort(order, axis=1) + 1
    diff = f[:, :, None] - f[:, None, :]               # [B, M, M]
    rho = jax.nn.sigmoid(-diff)
    pair = ((y[:, :, None] - y[:, None, :]) > 0) \
        & mask[:, :, None] & mask[:, None, :]
    if use_ndcg:
        gain = 2.0 ** y - 1.0
        disc = 1.0 / jnp.log2(1.0 + rank.astype(jnp.float32))
        dgain = jnp.abs(gain[:, :, None] - gain[:, None, :])
        ddisc = jnp.abs(disc[:, :, None] - disc[:, None, :])
        w = dgain * ddisc / jnp.maximum(maxdcg, 1e-10)[:, None, None]
    else:
        w = 1.0
    A = jnp.where(pair, w * rho, 0.0)
    Hh = jnp.where(pair, w * rho * (1.0 - rho), 0.0)
    g = -jnp.sum(A, axis=2) + jnp.sum(A, axis=1)
    h = jnp.sum(Hh, axis=2) + jnp.sum(Hh, axis=1)
    return g, h


@functools.partial(jax.jit, static_argnums=(9, 10, 11, 12, 13, 14, 15))
def _rank_round(binned, margin, y_dense, maxdcg, idx, pos, mask, w, key,
                tp: TreeParams, use_ndcg: bool, batch: int, lr: float,
                sample_rate: float, col_rate: float, mesh=None):
    """ONE compiled program per boosting round: lambda gradients → row
    sampling → tree growth → margin update.

    The round-1/round-2 suite hangs (and the SIGABRTs before the
    rendezvous timeout was raised) were all in EAGER multi-device
    dispatch inside this loop — an eager op on sharded arrays
    occasionally deadlocks XLA:CPU's collective rendezvous. Keeping the
    whole round inside one jit removes every eager sharded dispatch
    from the hot path (the fused GBM loop got the same treatment via
    boost_trees)."""
    from .tree.core import _grow_tree_jit, predict_tree

    g, h = _lambda_grads(margin, idx, pos, mask, use_ndcg, batch,
                         y_dense=y_dense, maxdcg=maxdcg)
    k_row, k_col, k_tree = jax.random.split(key, 3)
    w_t = w
    if sample_rate < 1.0:
        w_t = w * (jax.random.uniform(k_row, w.shape) < sample_rate)
    F = binned.shape[1]
    col_mask = jnp.ones(F, dtype=bool)
    if col_rate < 1.0:
        col_mask = jax.random.uniform(k_col, (F,)) < col_rate
    # lambdarank stays on the ORIGINAL-space binned matrix (efb=None):
    # its margin update re-descends `binned` via predict_tree, which
    # reads original (feature, bin) splits
    tree = _grow_tree_jit(binned, g, h, w_t, col_mask, k_tree, None,
                          tp, mesh)
    tree = tree._replace(value=lr * tree.value)
    margin = margin + predict_tree(tree, binned, tp.max_depth, tp.n_bins)
    return margin, tree


@functools.partial(jax.jit, static_argnums=(4, 5))
def _lambda_grads(margin, layout_idx, layout_pos, layout_mask,
                  use_ndcg: bool, batch: int, y_dense=None, maxdcg=None):
    """Row-layout margins → row-layout (g, h) via the dense group layout."""
    G, Mx = layout_idx.shape
    f_dense = jnp.where(layout_mask, margin[jnp.maximum(layout_idx, 0)], 0.0)
    nb = -(-G // batch)
    pad = nb * batch - G

    def pad_g(a, fill=0.0):
        return jnp.concatenate(
            [a, jnp.full((pad,) + a.shape[1:], fill, a.dtype)]) \
            if pad else a

    fb = pad_g(f_dense).reshape(nb, batch, Mx)
    yb = pad_g(y_dense).reshape(nb, batch, Mx)
    mb = pad_g(layout_mask, False).reshape(nb, batch, Mx)
    db = pad_g(maxdcg).reshape(nb, batch)
    g, h = lax.map(lambda t: _lambda_grads_batch(*t, use_ndcg), (fb, yb, mb, db))
    g = g.reshape(-1, Mx).reshape(-1)[: G * Mx]
    h = h.reshape(-1, Mx).reshape(-1)[: G * Mx]
    ok = layout_pos >= 0
    safe = jnp.maximum(layout_pos, 0)
    return jnp.where(ok, g[safe], 0.0), jnp.where(ok, h[safe], 0.0)


# ---------------------------------------------------------------------------
# Estimator
# ---------------------------------------------------------------------------

class XGBoost(GBM):
    """H2OXGBoostEstimator analog (tree_method=hist on TPU).

    XGBoost defaults differ from H2O GBM: eta .3, depth 6, lambda 1,
    min_child_weight 1 (hessian mass, not row count).
    """

    model_cls = XGBoostModel

    def __init__(self, ntrees: int = 50, max_depth: int = 6,
                 learn_rate: float = 0.3, eta: float | None = None,
                 reg_lambda: float = 1.0, reg_alpha: float = 0.0,
                 gamma: float = 0.0, min_child_weight: float = 1.0,
                 subsample: float = 1.0,
                 colsample_bytree: float = 1.0,
                 nbins: int = 256, objective: str | None = None,
                 booster: str = "gbtree", tree_method: str = "hist",
                 ndcg_group_batch: int = 16, **kw):
        if booster != "gbtree":
            raise ValueError(f"only booster=gbtree is supported: {booster}")
        if tree_method not in ("hist", "gpu_hist", "approx", "auto"):
            raise ValueError(f"unknown tree_method {tree_method}")
        # H2O-side spellings map onto the XGBoost-native ones (the
        # reference's XGBoostV3 schema does the same aliasing)
        if "min_rows" in kw:
            min_child_weight = kw.pop("min_rows")
        if "sample_rate" in kw:
            subsample = kw.pop("sample_rate")
        if "col_sample_rate_per_tree" in kw:
            colsample_bytree = kw.pop("col_sample_rate_per_tree")
        dist = kw.pop("distribution", "auto")
        if objective is not None:
            if objective not in _OBJECTIVE_ALIASES:
                raise ValueError(f"unknown objective {objective}")
            dist = _OBJECTIVE_ALIASES[objective]
        super().__init__(
            ntrees=ntrees, max_depth=max_depth,
            learn_rate=eta if eta is not None else learn_rate,
            reg_lambda=reg_lambda, reg_alpha=reg_alpha,
            min_split_improvement=gamma,
            min_child_weight=min_child_weight,
            sample_rate=subsample,
            col_sample_rate_per_tree=colsample_bytree,
            nbins=nbins, min_rows=1.0,
            distribution=dist, **kw)
        self._ndcg_group_batch = ndcg_group_batch

    def train(self, y: str, training_frame: Frame,
              x: Sequence[str] | None = None,
              group_column: str | None = None, **kw) -> XGBoostModel:
        if self.params.distribution.startswith("rank:"):
            if group_column is None:
                raise ValueError("ranking objectives need group_column")
            if self.cv_args.enabled:
                raise ValueError(
                    "cross-validation with rank:* objectives needs "
                    "group-aware folds; not supported yet")
            return self._train_rank(y, training_frame, x, group_column, **kw)
        ignored = list(kw.pop("ignored_columns", None) or [])
        if group_column:
            ignored.append(group_column)
        model = super().train(y=y, training_frame=training_frame, x=x,
                              ignored_columns=ignored, **kw)
        model._group_column = group_column
        return model

    def _train_rank(self, y: str, frame: Frame, x, group_column: str,
                    ignored_columns: Sequence[str] | None = None,
                    weights_column: str | None = None,
                    validation_frame: Frame | None = None,
                    offset_column: str | None = None) -> XGBoostModel:
        p = self.params
        if offset_column:
            # a base margin is meaningful for pointwise objectives only;
            # LambdaMART gradients come from pairwise score differences
            raise ValueError(
                "offset_column is not supported for rank:* objectives")
        ignored = list(ignored_columns or []) + [group_column]
        # no full f32 design matrix: the ranker bins straight from the
        # Frame columns like the pointwise tree paths (Frame.binned)
        data = resolve_xy(frame, y, x, ignored, weights_column,
                          distribution="gaussian", materialize_x=False)
        data.distribution = p.distribution   # rank:* carried through
        # graded relevance stored as an enum: codes ARE the grades —
        # score as a single-output ranker, never the multinomial path
        data.nclasses = 1
        data.response_domain = None
        use_ndcg = p.distribution == "rank:ndcg"

        gv = frame.vec(group_column)
        gids = gv.to_numpy()
        # padded rows get fresh singleton group ids → they pair with
        # nothing and receive zero gradients
        padded = data.y.shape[0]
        real = np.asarray(gids).astype(np.int64)
        gfull = np.empty(padded, dtype=np.int64)
        gfull[: frame.nrows] = real
        top = int(real.max()) + 1 if len(real) else 0
        gfull[frame.nrows:] = top + np.arange(padded - frame.nrows)
        layout = _GroupLayout(gfull, padded)

        bin_spec = fit_bins(frame, data.feature_names, n_bins=p.nbins)
        binned = frame.binned(bin_spec)

        y_dense, maxdcg = _dense_layout_jit(data.y, layout.idx,
                                            layout.mask)

        tp = TreeParams(max_depth=p.max_depth, n_bins=p.nbins,
                        min_rows=p.min_rows, reg_lambda=p.reg_lambda,
                        reg_alpha=p.reg_alpha,
                        gamma=p.min_split_improvement, mtries=p.mtries,
                        min_child_weight=p.min_child_weight)
        key = jax.random.key(p.seed)
        F = len(data.feature_names)
        margin = jnp.zeros_like(data.y)
        trees, history = [], []
        batch = min(self._ndcg_group_batch, layout.n_groups)
        from ..runtime.mesh import global_mesh

        mesh = global_mesh()
        for t in range(p.ntrees):
            require_healthy()        # fail fast on a dead mesh (§5.3)
            key, kt = jax.random.split(key)
            margin, tree = _rank_round(
                binned, margin, y_dense, maxdcg, layout.idx, layout.pos,
                layout.mask, data.w, kt, tp, use_ndcg, batch,
                p.learn_rate, p.sample_rate, p.col_sample_rate_per_tree,
                mesh)
            trees.append(tree)
            if p.score_every and (t + 1) % p.score_every == 0:
                sc = np.asarray(margin)[: frame.nrows]
                yt = np.asarray(data.y)[: frame.nrows]
                history.append({"ntrees": t + 1,
                                "train_ndcg@10": M.ndcg(yt, sc, gids, k=10)})

        model = self.model_cls(data, p, bin_spec, trees, init_score=0.0,
                               varimp=None)
        model._varimp = _stacked_varimp(model.trees, data.feature_names)
        model._group_column = group_column
        sc = np.asarray(margin)[: frame.nrows]
        yt = np.asarray(data.y)[: frame.nrows]
        history.append({"ntrees": p.ntrees,
                        "train_ndcg@10": M.ndcg(yt, sc, gids, k=10)})
        model.scoring_history = history
        if validation_frame is not None:
            vy = validation_frame.vec(y)
            vscore = model.predict_raw(validation_frame)
            vg = validation_frame.vec(group_column).to_numpy()
            model.validation_metrics = {
                "ndcg@10": M.ndcg(vy.to_numpy(), vscore, vg, k=10)}
        return model
