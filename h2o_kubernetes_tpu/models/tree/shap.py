"""TreeSHAP — per-row feature contributions for the tree ensembles.

Reference: H2O's `predict_contributions` on GBM/DRF/XGBoost
(h2o-genmodel TreeSHAP implementation, SURVEY.md §2b C18), which is the
path-dependent TreeSHAP algorithm of Lundberg et al. 2018: exact
Shapley values under the tree's own cover-weighted conditional
expectations, computed by carrying a path of
(feature, zero_fraction, one_fraction, weight) down the recursion.

Two implementations share this module:

1. ``ensemble_shap`` — the HOST reference: numpy recursion over the
   dense heap, vectorized over rows, float64. The recursion walks the
   tree ONCE; one_fractions and path weights are [rows] vectors while
   zero_fractions stay scalars. O(leaves · depth² · rows) per tree.
   In-process ``predict_contributions`` stays here (f64, the parity
   oracle) exactly as ``predict()`` stays eager while serving jits.

2. ``flat_shap`` / ``flat_shap_tab`` — the COMPILED serving kernels
   (ISSUE 10 tentpole): the path-enumeration form of the same
   algorithm over per-leaf path tables precomputed from the flattened
   serving arrays (``build_shap_tables`` /
   ``build_shap_table_groups``). Per (row, leaf) the DP kernel runs
   the EXTEND dynamic program once and an UNWIND-sum per path slot —
   a dense ``[rows × leaves × depth]`` computation with no recursion,
   no host sync, and a fixed f32 accumulation order (scan over
   trees), the per-tree-parallel dispatch shape of arXiv:1706.08359.
   Duplicate features on a root→leaf path are MERGED host-side
   (cover-fraction products, conjunction of hot conditions — exactly
   what the recursion's unwind/re-extend computes), and every path is
   padded to its group depth with (one=1, zero=1) entries, which are
   provably neutral to the Shapley subset weights: appending such an
   element to the feature set U maps each subset S ⊆ U\\{i} to the
   pair {S, S∪{e}} whose factorial weights sum to S's original
   weight. That makes the whole kernel static-shaped — no per-leaf
   lengths. Three throughput levers on top (docs/SERVING.md
   "Explainable serving"): one_fractions are BINARY, so each leaf's
   whole weight computation collapses to a D-bit hot pattern indexing
   a precomputed f64-built table (``pattern_table`` →
   ``flat_shap_tab``, the default for shallow ensembles); everything
   runs rows-minor (transposed), so feature gathers are contiguous
   column slices and the scatter is per-slot vector adds; and leaves
   pool ACROSS trees into virtual trees bucketed by their own merged
   depth (TreeSHAP is additive over leaves — bias included, as each
   leaf carries its v·P share), so total work is exactly
   Σ_leaf depth_leaf rather than leaves × max-depth.

Additivity invariant (tested, both paths): sum_f phi[:, f] +
phi[:, bias] equals the raw margin prediction of the ensemble.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

__all__ = ["ensemble_shap", "ShapTables", "build_shap_tables",
           "build_shap_table_groups", "flat_shap", "flat_shap_tab",
           "pattern_table"]


def _tree_shap_one(sf, sb, nl, sp, val, cov, binned, na_bin, phi):
    """Accumulate one tree's contributions into phi [rows, F+1].

    sf/sb/nl/sp/val/cov: dense-heap arrays [N]; binned: [rows, F] bin
    codes; the last phi column is the bias term.
    """
    rows = binned.shape[0]

    def recurse(j, ds, zs, os_, ws, pz, po, pd):
        # EXTEND the path with (pd, pz, po)
        L = len(ds)
        ds = ds + [pd]
        zs = zs + [pz]
        os_ = os_ + [po]
        ws = [w.copy() for w in ws]
        ws.append(np.full(rows, 1.0 if L == 0 else 0.0))
        for i in range(L - 1, -1, -1):
            ws[i + 1] += os_[L] * ws[i] * ((i + 1) / (L + 1))
            ws[i] = zs[L] * ws[i] * ((L - i) / (L + 1))

        if not sp[j]:                                   # leaf
            leaf = float(val[j])
            l = len(ds) - 1
            for i in range(1, l + 1):
                # sum of UNWIND(m, i) weights
                w_sum = _unwind_sum(zs, os_, ws, i, l)
                phi[:, ds[i]] += w_sum * (os_[i] - zs[i]) * leaf
            return

        d = int(sf[j])
        rowbin = binned[:, d]
        is_na = rowbin == na_bin
        go_right = np.where(is_na, ~nl[j], rowbin > sb[j])
        hot_left = ~go_right                            # [rows] bool
        lc, rc = 2 * j + 1, 2 * j + 2
        cj = max(float(cov[j]), 1e-12)
        iz, io = 1.0, np.ones(rows)
        # a feature reappearing on the path: undo its previous entry
        k = next((i for i in range(1, len(ds)) if ds[i] == d), None)
        if k is not None:
            iz, io = zs[k], os_[k]
            ds, zs, os_, ws = _unwind(ds, zs, os_, ws, k)
        recurse(lc, ds, zs, os_, ws,
                iz * float(cov[lc]) / cj, io * hot_left, d)
        recurse(rc, ds, zs, os_, ws,
                iz * float(cov[rc]) / cj, io * go_right, d)

    recurse(0, [], [], [], [], 1.0, np.ones(rows), -1)
    # bias: cover-weighted expectation of the tree = recurse with no
    # conditioning; equals the sum of leaf value · P(leaf), which the
    # caller accounts for via the ensemble init instead — the path
    # algorithm already attributes E[f] shifts to features, so the
    # remaining bias per tree is E[f] itself:
    phi[:, -1] += _tree_expectation(sp, val, cov, 0)


def _tree_expectation(sp, val, cov, j):
    if not sp[j]:
        return float(val[j])
    cj = max(float(cov[j]), 1e-12)
    return (float(cov[2 * j + 1]) / cj
            * _tree_expectation(sp, val, cov, 2 * j + 1)
            + float(cov[2 * j + 2]) / cj
            * _tree_expectation(sp, val, cov, 2 * j + 2))


def _unwind(ds, zs, os_, ws, i):
    """Remove path entry i (inverse of EXTEND) — the shap reference's
    unwind_path, with the o==0 / o!=0 branch selected per row.

    Weights are recomputed over the WHOLE path (indices l-1..0); the
    (d, z, o) triples shift down from i while pweights keep their
    recomputed positions 0..l-1 — exactly the C implementation's
    asymmetric shift."""
    l = len(ds) - 1
    ws = [w.copy() for w in ws]
    oi, zi = os_[i], zs[i]
    nonzero = oi != 0
    oi_safe = np.where(nonzero, oi, 1.0)
    zi_safe = zi if zi != 0 else 1e-12
    n = ws[l].copy()
    for j in range(l - 1, -1, -1):
        t = ws[j].copy()
        w_nz = n * (l + 1) / ((j + 1) * oi_safe)
        n = t - w_nz * zi * ((l - j) / (l + 1))
        w_z = t * (l + 1) / (zi_safe * (l - j))
        ws[j] = np.where(nonzero, w_nz, w_z)
    return (ds[:i] + ds[i + 1:], zs[:i] + zs[i + 1:],
            os_[:i] + os_[i + 1:], ws[:l])


def _unwind_sum(zs, os_, ws, i, l):
    """Σ of UNWIND(m, i) pweights without materializing the unwind —
    the shap reference's unwound_path_sum, per-row [rows]."""
    oi, zi = os_[i], zs[i]
    nonzero = oi != 0
    oi_safe = np.where(nonzero, oi, 1.0)
    zi_safe = zi if zi != 0 else 1e-12
    n = ws[l].copy()
    total = np.zeros_like(n)
    for j in range(l - 1, -1, -1):
        tmp = n * (l + 1) / ((j + 1) * oi_safe)
        n = ws[j] - tmp * zi * ((l - j) / (l + 1))
        w_z = ws[j] * (l + 1) / (zi_safe * (l - j))
        total += np.where(nonzero, tmp, w_z)
    return total


def ensemble_shap(trees_np: dict, binned: np.ndarray, n_features: int,
                  na_bin: int, scale: float = 1.0) -> np.ndarray:
    """Contributions [rows, F+1] for a stacked ensemble of dense trees.

    trees_np: {"split_feat": [T,N], "split_bin", "na_left", "is_split",
    "value", "cover"}; the last output column is the per-tree expected
    value (bias); `scale` multiplies every tree (DRF's 1/T averaging).
    """
    T = trees_np["split_feat"].shape[0]
    rows = binned.shape[0]
    phi = np.zeros((rows, n_features + 1), dtype=np.float64)
    for t in range(T):
        _tree_shap_one(trees_np["split_feat"][t],
                       trees_np["split_bin"][t],
                       trees_np["na_left"][t],
                       trees_np["is_split"][t],
                       trees_np["value"][t],
                       trees_np["cover"][t],
                       binned, na_bin, phi)
    return phi * scale


# ---------------------------------------------------------------------------
# Compiled TreeSHAP serving: per-leaf path tables + the device kernel
# ---------------------------------------------------------------------------

class ShapTables(NamedTuple):
    """Per-leaf root→leaf path tables over the flattened serving
    ensemble — the dense operand of ``flat_shap``. All arrays are
    [T, L, D] (trees × max leaves × max unique path features) except
    ``leaf_val``/``bias``; hot conditions live in RAW feature space
    (the same thresholds ``flat_margin`` descends), so a registry
    ``FlatTreeScorer`` can build them from artifact bytes alone.

    Padding is self-neutralizing: dummy slots carry (feat=-1,
    lo=-inf, hi=NaN, na_ok=True, z=1) — their one_fraction is 1 for
    every row, so (o - z) = 0 and the Shapley weights are provably
    unchanged (see the module docstring); padded leaves carry
    leaf_val=0."""

    feat: jax.Array      # int32 [T, L, D]; -1 = padding slot
    lo: jax.Array        # f32: hot needs x >= lo (-inf = no lower bound)
    hi: jax.Array        # f32: hot needs NOT x >= hi (NaN = no upper
    #                      bound — x >= NaN is False for EVERY x, so
    #                      the negation is True without a sentinel
    #                      check; -inf = branch unreachable for non-NA
    #                      rows, since x >= -inf holds for every x)
    na_ok: jax.Array     # bool: NA rows of `feat` follow this path
    zfrac: jax.Array     # f32: merged cover-fraction product (TreeSHAP
    #                      zero_fraction; 1.0 on padding)
    leaf_val: jax.Array  # f32 [T, L]; 0 on padded leaves
    bias: jax.Array      # f32 [T]: per-tree expectation Σ v_l · P(l)


def _enumerate_paths(flat, cover: np.ndarray) -> list[list]:
    """Per tree, the merged per-leaf path entries: a list of
    (merged {feat -> {lo, hi, na, z}}, leaf_value, P_leaf) triples.

    Per leaf, the root→leaf path is walked once; splits on the SAME
    feature merge into one slot — zero_fractions multiply (the
    recursion's unwind/re-extend computes exactly this product) and
    the hot condition becomes the interval conjunction of the split
    decisions: `x >= thresh` for every right turn (=> lo = max), the
    negation for every left turn (=> hi = min over finite thresholds;
    a NaN threshold is the always-left cut, so a left turn there binds
    nothing and a right turn marks the branch dead for non-NA rows,
    encoded hi = -inf). NA routing stays per-feature via ``na``
    (conjunction of the learned na_left directions)."""
    sf = np.asarray(flat.split_feat)
    th = np.asarray(flat.thresh).astype(np.float64)
    lf = np.asarray(flat.left)
    nl = np.asarray(flat.na_left).astype(bool)
    val = np.asarray(flat.value).astype(np.float64)
    cov = np.asarray(cover).astype(np.float64)
    T = sf.shape[0]
    per_tree: list[list] = []
    for t in range(T):
        leaves = []
        stack: list[tuple[int, list]] = [(0, [])]
        while stack:
            node, path = stack.pop()
            if len(path) > 64:
                raise ValueError(
                    "malformed flat tree: root→leaf path exceeds 64 "
                    "nodes (cyclic left pointers?)")
            f = int(sf[t, node])
            if f < 0:
                merged: dict[int, dict] = {}
                P = 1.0
                for (d, thr, right, naleft, ratio) in path:
                    P *= ratio
                    e = merged.get(d)
                    if e is None:
                        e = merged[d] = {"lo": -np.inf, "hi": np.nan,
                                         "na": True, "z": 1.0}
                    e["z"] *= ratio
                    e["na"] = e["na"] and \
                        ((not naleft) if right else naleft)
                    if right:
                        if np.isnan(thr):
                            # right past the always-left cut: no non-NA
                            # row can take this branch
                            e["hi"] = -np.inf
                        else:
                            e["lo"] = max(e["lo"], thr)
                    elif not np.isnan(thr):
                        e["hi"] = thr if np.isnan(e["hi"]) \
                            else min(e["hi"], thr)
                leaves.append((merged, float(val[t, node]), P))
                continue
            left = int(lf[t, node])
            cj = max(cov[t, node], 1e-12)
            thr = float(th[t, node])
            naleft = bool(nl[t, node])
            stack.append((left, path + [(f, thr, False, naleft,
                                         float(cov[t, left]) / cj)]))
            stack.append((left + 1, path + [(f, thr, True, naleft,
                                             float(cov[t, left + 1])
                                             / cj)]))
        per_tree.append(leaves)
    return per_tree


def _pack_tables(per_tree: list[list]) -> ShapTables:
    """Pad a group of enumerated trees to its own (L, D) and pack the
    dense arrays (numpy leaves; callers device_put)."""
    T = len(per_tree)
    L = max(max(len(lv) for lv in per_tree), 1)
    D = max(max((len(m) for m, _, _ in lv), default=0)
            for lv in per_tree)
    D = max(D, 1)
    feat = np.full((T, L, D), -1, dtype=np.int32)
    lo = np.full((T, L, D), -np.inf, dtype=np.float32)
    hi = np.full((T, L, D), np.nan, dtype=np.float32)
    na_ok = np.ones((T, L, D), dtype=bool)
    z = np.ones((T, L, D), dtype=np.float32)
    leaf_val = np.zeros((T, L), dtype=np.float32)
    bias = np.zeros(T, dtype=np.float32)
    for t, leaves in enumerate(per_tree):
        b = 0.0
        for li, (merged, v, P) in enumerate(leaves):
            leaf_val[t, li] = v
            b += v * P
            for si, (d, e) in enumerate(merged.items()):
                feat[t, li, si] = d
                lo[t, li, si] = e["lo"]
                hi[t, li, si] = e["hi"]
                na_ok[t, li, si] = e["na"]
                z[t, li, si] = e["z"]
        bias[t] = b
    return ShapTables(feat, lo, hi, na_ok, z, leaf_val, bias)


def build_shap_tables(flat, cover: np.ndarray) -> ShapTables:
    """Host-side path enumeration: flattened arrays (+ slot-aligned
    per-node cover, core.flatten_cover / the MOJO ``flat_cover`` part)
    -> ONE padded ShapTables bundle over the whole ensemble (see
    ``_enumerate_paths`` for the merge semantics). The serving path
    uses ``build_shap_table_groups`` instead, which buckets trees by
    their own (leaves, depth) so a shallow tree never pays the
    deepest tree's padding."""
    return _pack_tables(_enumerate_paths(flat, cover))


# leaves per VIRTUAL tree in the serving groups: one scan step's
# working set is [_VLEAVES, D, chunk_rows] — 32 keeps it cache-resident
# at the default 16k-row chunk (measured optimum on the CPU mesh)
_VLEAVES = 32


def build_shap_table_groups(flat, cover: np.ndarray
                            ) -> list[ShapTables]:
    """Bucketed table bundles for the serving kernel. TreeSHAP is
    additive over LEAVES (each leaf contributes its per-slot terms
    plus its v·P share of the bias), so tree identity is irrelevant to
    the sum: all leaves of the ensemble pool together, bucket by their
    OWN merged path depth D, and pack into virtual trees of _VLEAVES
    leaves each. The kernel's work is O(rows · leaves · D), so this
    makes the total exactly Σ_leaf D_leaf — no leaf ever pays the
    deepest path's padding (a global pad costs ~30% extra on the
    bench ensemble: early trees saturate depth while shrinkage-era
    leaves stay shallow). Group order is deterministic (ascending D),
    so the cross-group f32 sum order is fixed and evict→promote stays
    bitwise."""
    per_tree = _enumerate_paths(flat, cover)
    buckets: dict[int, list] = {}
    for leaves in per_tree:
        for leaf in leaves:
            D_l = max(len(leaf[0]), 1)
            buckets.setdefault(D_l, []).append(leaf)
    groups = []
    for D_l in sorted(buckets):
        leaves = buckets[D_l]
        Lv = 1
        while Lv < min(len(leaves), _VLEAVES):
            Lv *= 2
        groups.append(_pack_tables(
            [leaves[i:i + Lv] for i in range(0, len(leaves), Lv)]))
    return groups


def _one_fractions(XT, feat, lo, hi, na_ok):
    """[L, D, rows] bool hot indicators from the interval tables —
    shared by both kernels. ``XT`` is the TRANSPOSED [F, rows]
    canonicalized feature matrix: with rows as the minor axis, the
    per-slot feature gather is a contiguous column slice and every
    later op is rows-contiguous — the layout is what makes the kernel
    stream at memory bandwidth on XLA:CPU instead of scalar-gathering
    a [rows, L, D] cube. The sentinel encoding needs NO bound-side
    isnan: `x >= NaN` is False for every x (so `~(x >= hi)` with the
    NaN no-bound sentinel is unconditionally True, +inf rows
    included), and a NaN feature value fails both comparisons, so the
    NA branch is a plain OR."""
    x = XT[jnp.maximum(feat, 0)]                      # [L, D, rows]
    hot = (x >= lo[..., None]) & ~(x >= hi[..., None])
    return (jnp.isnan(x) & na_ok[..., None]) | hot


@jax.jit
def flat_shap(tables: ShapTables, X, enum_mask):
    """[rows, F+1] path-dependent TreeSHAP contributions on RAW
    features (last column = bias term, the sum of per-tree expected
    values — the caller scales and adds init_score).

    Per tree (ordered lax.scan, so f32 accumulation is deterministic
    and bitwise-reproducible across evict→promote): one_fractions are
    evaluated for every (row, leaf, slot) from the interval tables,
    the EXTEND recurrence runs once per (row, leaf) over the D padded
    slots, and each slot's UNWIND-sum uses the binary-one_fraction
    simplification (o ∈ {0,1} ⇒ the nonzero branch's divisor is 1).
    Numerically equivalent to ``ensemble_shap`` (the f64 host
    recursion) to float32 tolerance — pinned by tests/test_contrib.py
    and the kernel gate's ``shap_parity`` check."""
    # negative enum codes are NA — same canonicalization as flat_margin
    Xc = jnp.where(enum_mask[None, :] & (X < 0), jnp.float32(jnp.nan), X)
    XT = Xc.T                                         # [F, rows]
    F = X.shape[1]
    D = tables.feat.shape[2]

    def one_tree(phi, tb):                            # phi [F+1, rows]
        feat, lo, hi, na_ok, z, leaf_val, bias = tb
        ob = _one_fractions(XT, feat, lo, hi, na_ok).astype(
            jnp.float32)                              # [L, D, rows]
        Lv, rows = ob.shape[0], ob.shape[2]
        # per-slot [L, rows] one_fractions x [L, 1] zero-fractions
        # through THE shared weight recurrence (_weight_sums), then
        # scatter leaf_val·(o-z)·Σ to each slot's feature column.
        # Padding slots contribute exactly 0 ((o - z) = 0) and scatter
        # into the bias column harmlessly.
        o = [ob[:, j, :] for j in range(D)]
        zb = [z[:, j, None] for j in range(D)]
        totals = _weight_sums(jnp, o, zb,
                              jnp.ones((Lv, rows), dtype=jnp.float32))
        contrib = jnp.stack(
            [leaf_val[:, None] * (o[i] - zb[i]) * totals[i]
             for i in range(D)], axis=1)              # [L, D, rows]
        tgt = jnp.where(feat < 0, F, feat)            # [L, D]
        # rows-minor scatter: 160 contiguous [rows] vector adds
        phi = phi.at[tgt].add(contrib)
        phi = phi.at[F].add(bias)
        return phi, None

    init = jnp.zeros((F + 1, X.shape[0]), dtype=jnp.float32)
    phi, _ = lax.scan(one_tree, init, tables)
    return phi.T


# total pattern-table budget PER MODEL, across all depth groups: a
# group that would push the model past it runs the DP kernel instead
# (deep trees: a table is T·L·2^D·D floats — depth-5 GBMs are ~400KB
# total, a depth-12 DRF would be GBs). Callers thread the remaining
# budget through `pattern_table(budget=)` (models/base._contrib_prepare)
_PATTERN_TABLE_MAX_BYTES = 64 << 20


def _weight_sums(xp, o, z, w0) -> list:
    """EXTEND + per-slot UNWIND-sum Shapley weight recurrence over a
    padded path — THE one implementation, shared by the f32 device DP
    kernel (``flat_shap``, xp=jnp) and the f64 host pattern-table
    builder (``pattern_table``, xp=np) so the fast path can never
    drift from the fallback. ``o``/``z`` are length-D sequences of
    per-slot arrays broadcastable against the all-ones ``w0`` (which
    fixes the working shape and dtype); the path starts as [bias
    entry] (w = [w0]), step j extends at pre-extend length j+1
    (matching the host recursion's (i+1)/(L+1), (L-i)/(L+1) factors),
    and each slot's unwound sum uses the binary-one_fraction
    simplification (o ∈ {0,1} ⇒ the nonzero branch's divisor is 1).
    Returns the per-slot weight sums; callers apply
    leaf_val · (o_i − z_i)."""
    D = len(o)
    w = [w0]
    for j in range(D):
        Ln = j + 1
        oj, zj = o[j], z[j]
        nxt = []
        for i in range(j + 2):
            v = None
            if i <= j:
                v = zj * w[i] * ((Ln - i) / (Ln + 1))
            if i >= 1:
                up = oj * w[i - 1] * (i / (Ln + 1))
                v = up if v is None else v + up
            nxt.append(v)
        w = nxt
    totals = []
    for i in range(D):
        oi, zi = o[i], z[i]
        nonzero = oi != 0
        zi_safe = xp.where(zi == 0, 1e-12, zi)
        n = w[D]
        total = xp.zeros_like(w0)
        for jj in range(D - 1, -1, -1):
            tmp = n * ((D + 1) / (jj + 1))
            n = w[jj] - tmp * zi * ((D - jj) / (D + 1))
            w_z = w[jj] * ((D + 1) / (D - jj)) / zi_safe
            total = total + xp.where(nonzero, tmp, w_z)
        totals.append(total)
    return totals


def pattern_table(tables: ShapTables,
                  budget: "int | None" = None) -> "np.ndarray | None":
    """[T, L, D, 2^D] float32 precomputed per-slot contributions
    ``leaf_val · (o_i − z_i) · G_i(pattern)`` for EVERY possible hot
    pattern of a leaf's D slots — the key throughput lever of the
    serving kernel: one_fractions are binary, so a (row, leaf)'s whole
    Shapley weight computation collapses to a D-bit pattern index and
    a table gather. Built host-side in float64 (row-independent — the
    same extend/unwind DP as the kernel, batched over [L, 2^D]), so
    the fast path is slightly MORE precise than the in-kernel f32 DP.
    Returns None when the table would exceed ``budget`` (default
    _PATTERN_TABLE_MAX_BYTES; deep groups keep the direct DP
    kernel)."""
    feat = np.asarray(tables.feat)
    T, L, D = feat.shape
    P = 1 << D
    if budget is None:
        budget = _PATTERN_TABLE_MAX_BYTES
    # D > 14 would overflow the kernel's int16 pattern accumulator
    # (and its table would be enormous anyway) — DP kernel instead
    if D > 14 or T * L * P * D * 4 > budget:
        return None
    z64 = np.asarray(tables.zfrac).astype(np.float64)
    val64 = np.asarray(tables.leaf_val).astype(np.float64)
    pats = np.arange(P)
    obits = ((pats[:, None] >> np.arange(D)[None, :]) & 1).astype(
        np.float64)                                   # [P, D]
    out = np.zeros((T, L, D, P), dtype=np.float32)
    for t in range(T):
        # [L, 1] zero-fractions x [1, P] hot bits -> [L, P] work shape
        o = [obits[:, i][None, :] for i in range(D)]
        zb = [z64[t][:, i][:, None] for i in range(D)]
        totals = _weight_sums(np, o, zb, np.ones((L, P)))
        for i in range(D):
            out[t, :, i, :] = (val64[t][:, None] * (o[i] - zb[i])
                               * totals[i]).astype(np.float32)
    return out


@jax.jit
def flat_shap_tab(tables: ShapTables, ctab, X, enum_mask):
    """The pattern-table fast path of ``flat_shap`` (same contract,
    same [rows, F+1] output): per (row, leaf) the kernel computes only
    the D hot bits, folds them into a pattern index, and gathers the
    precomputed per-slot contributions — O(rows·leaves·depth) simple
    rows-contiguous ops instead of the O(depth²) weight DP per
    element, with the scatter reduced to per-slot [rows] vector adds
    in the transposed accumulator.

    This lowered-XLA form is ALSO the bitwise reference for its
    chip-native twin ``ops/shap_kernel.flat_shap_tab_kernel`` (the
    Pallas hand-placement of the same fold/gather/scatter loop, picked
    on TPU by ``resolve_impl``/H2O_TPU_SHAP_KERNEL in
    ``Model._contrib_matrix``); any semantic change here must keep the
    kernel's ordered accumulation in lockstep or the
    ``shap_kernel_parity`` gate and tier-1 bitwise pins will fail."""
    Xc = jnp.where(enum_mask[None, :] & (X < 0), jnp.float32(jnp.nan), X)
    XT = Xc.T                                           # [F, rows]
    F = X.shape[1]
    D = tables.feat.shape[2]
    # int16 MAC: 2x the SIMD width of int32, and the pattern-table
    # gate caps D well under 15 bits
    pow2 = jnp.asarray([1 << i for i in range(D)], dtype=jnp.int16)

    def one_tree(phi, tb):                              # phi [F+1, rows]
        (feat, lo, hi, na_ok, _z, _lv, bias), ct = tb   # ct [L, D, P]
        o = _one_fractions(XT, feat, lo, hi, na_ok)     # [L, D, rows]
        pat = jnp.sum(o.astype(jnp.int16) * pow2[None, :, None],
                      axis=1).astype(jnp.int32)         # [L, rows]
        contrib = jnp.take_along_axis(
            ct, pat[:, None, :], axis=2)                # [L, D, rows]
        tgt = jnp.where(feat < 0, F, feat)              # [L, D]
        phi = phi.at[tgt].add(contrib)
        phi = phi.at[F].add(bias)
        return phi, None

    init = jnp.zeros((F + 1, X.shape[0]), dtype=jnp.float32)
    phi, _ = lax.scan(one_tree, init, (tables, ctab))
    return phi.T
