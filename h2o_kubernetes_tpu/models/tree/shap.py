"""TreeSHAP — per-row feature contributions for the tree ensembles.

Reference: H2O's `predict_contributions` on GBM/DRF/XGBoost
(h2o-genmodel TreeSHAP implementation, SURVEY.md §2b C18), which is the
path-dependent TreeSHAP algorithm of Lundberg et al. 2018: exact
Shapley values under the tree's own cover-weighted conditional
expectations, computed by carrying a path of
(feature, zero_fraction, one_fraction, weight) down the recursion.

Design: host-side numpy, vectorized over ROWS. The recursion walks the
tree ONCE (not per row); one_fractions and path weights are [rows]
vectors (hot/cold branching differs per row) while zero_fractions stay
scalars (cover ratios are row-independent). Work is
O(leaves · depth² · rows) per tree with numpy inner ops — contributions
are a scoring-time feature on modest frames, not a training hot loop,
so the device kernel budget stays on training (ops/histogram).

Additivity invariant (tested): sum_f phi[:, f] + phi[:, bias] equals
the raw margin prediction of the ensemble.
"""

from __future__ import annotations

import numpy as np

__all__ = ["ensemble_shap"]


def _tree_shap_one(sf, sb, nl, sp, val, cov, binned, na_bin, phi):
    """Accumulate one tree's contributions into phi [rows, F+1].

    sf/sb/nl/sp/val/cov: dense-heap arrays [N]; binned: [rows, F] bin
    codes; the last phi column is the bias term.
    """
    rows = binned.shape[0]

    def recurse(j, ds, zs, os_, ws, pz, po, pd):
        # EXTEND the path with (pd, pz, po)
        L = len(ds)
        ds = ds + [pd]
        zs = zs + [pz]
        os_ = os_ + [po]
        ws = [w.copy() for w in ws]
        ws.append(np.full(rows, 1.0 if L == 0 else 0.0))
        for i in range(L - 1, -1, -1):
            ws[i + 1] += os_[L] * ws[i] * ((i + 1) / (L + 1))
            ws[i] = zs[L] * ws[i] * ((L - i) / (L + 1))

        if not sp[j]:                                   # leaf
            leaf = float(val[j])
            l = len(ds) - 1
            for i in range(1, l + 1):
                # sum of UNWIND(m, i) weights
                w_sum = _unwind_sum(zs, os_, ws, i, l)
                phi[:, ds[i]] += w_sum * (os_[i] - zs[i]) * leaf
            return

        d = int(sf[j])
        rowbin = binned[:, d]
        is_na = rowbin == na_bin
        go_right = np.where(is_na, ~nl[j], rowbin > sb[j])
        hot_left = ~go_right                            # [rows] bool
        lc, rc = 2 * j + 1, 2 * j + 2
        cj = max(float(cov[j]), 1e-12)
        iz, io = 1.0, np.ones(rows)
        # a feature reappearing on the path: undo its previous entry
        k = next((i for i in range(1, len(ds)) if ds[i] == d), None)
        if k is not None:
            iz, io = zs[k], os_[k]
            ds, zs, os_, ws = _unwind(ds, zs, os_, ws, k)
        recurse(lc, ds, zs, os_, ws,
                iz * float(cov[lc]) / cj, io * hot_left, d)
        recurse(rc, ds, zs, os_, ws,
                iz * float(cov[rc]) / cj, io * go_right, d)

    recurse(0, [], [], [], [], 1.0, np.ones(rows), -1)
    # bias: cover-weighted expectation of the tree = recurse with no
    # conditioning; equals the sum of leaf value · P(leaf), which the
    # caller accounts for via the ensemble init instead — the path
    # algorithm already attributes E[f] shifts to features, so the
    # remaining bias per tree is E[f] itself:
    phi[:, -1] += _tree_expectation(sp, val, cov, 0)


def _tree_expectation(sp, val, cov, j):
    if not sp[j]:
        return float(val[j])
    cj = max(float(cov[j]), 1e-12)
    return (float(cov[2 * j + 1]) / cj
            * _tree_expectation(sp, val, cov, 2 * j + 1)
            + float(cov[2 * j + 2]) / cj
            * _tree_expectation(sp, val, cov, 2 * j + 2))


def _unwind(ds, zs, os_, ws, i):
    """Remove path entry i (inverse of EXTEND) — the shap reference's
    unwind_path, with the o==0 / o!=0 branch selected per row.

    Weights are recomputed over the WHOLE path (indices l-1..0); the
    (d, z, o) triples shift down from i while pweights keep their
    recomputed positions 0..l-1 — exactly the C implementation's
    asymmetric shift."""
    l = len(ds) - 1
    ws = [w.copy() for w in ws]
    oi, zi = os_[i], zs[i]
    nonzero = oi != 0
    oi_safe = np.where(nonzero, oi, 1.0)
    zi_safe = zi if zi != 0 else 1e-12
    n = ws[l].copy()
    for j in range(l - 1, -1, -1):
        t = ws[j].copy()
        w_nz = n * (l + 1) / ((j + 1) * oi_safe)
        n = t - w_nz * zi * ((l - j) / (l + 1))
        w_z = t * (l + 1) / (zi_safe * (l - j))
        ws[j] = np.where(nonzero, w_nz, w_z)
    return (ds[:i] + ds[i + 1:], zs[:i] + zs[i + 1:],
            os_[:i] + os_[i + 1:], ws[:l])


def _unwind_sum(zs, os_, ws, i, l):
    """Σ of UNWIND(m, i) pweights without materializing the unwind —
    the shap reference's unwound_path_sum, per-row [rows]."""
    oi, zi = os_[i], zs[i]
    nonzero = oi != 0
    oi_safe = np.where(nonzero, oi, 1.0)
    zi_safe = zi if zi != 0 else 1e-12
    n = ws[l].copy()
    total = np.zeros_like(n)
    for j in range(l - 1, -1, -1):
        tmp = n * (l + 1) / ((j + 1) * oi_safe)
        n = ws[j] - tmp * zi * ((l - j) / (l + 1))
        w_z = ws[j] * (l + 1) / (zi_safe * (l - j))
        total += np.where(nonzero, tmp, w_z)
    return total


def ensemble_shap(trees_np: dict, binned: np.ndarray, n_features: int,
                  na_bin: int, scale: float = 1.0) -> np.ndarray:
    """Contributions [rows, F+1] for a stacked ensemble of dense trees.

    trees_np: {"split_feat": [T,N], "split_bin", "na_left", "is_split",
    "value", "cover"}; the last output column is the per-tree expected
    value (bias); `scale` multiplies every tree (DRF's 1/T averaging).
    """
    T = trees_np["split_feat"].shape[0]
    rows = binned.shape[0]
    phi = np.zeros((rows, n_features + 1), dtype=np.float64)
    for t in range(T):
        _tree_shap_one(trees_np["split_feat"][t],
                       trees_np["split_bin"][t],
                       trees_np["na_left"][t],
                       trees_np["is_split"][t],
                       trees_np["value"][t],
                       trees_np["cover"][t],
                       binned, na_bin, phi)
    return phi * scale
