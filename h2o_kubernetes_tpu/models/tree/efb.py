"""Exclusive Feature Bundling (EFB) for wide sparse frames.

Wide CTR/NLP-featurized frames are dominated by one-hot / near-empty
columns, yet the histogram hot loop (ops/histogram.py) pays the full
``rows x F`` scatter-add per tree level and the multi-chip path psums
a full-width histogram.  LightGBM's EFB (the technique benchmarked
across GBDT implementations in arXiv:1809.04559) packs mutually
exclusive sparse features — features whose non-default rows never
overlap — into single columns, so the binned matrix, the per-level
scatter-add, AND the cross-shard histogram psum all run at the bundled
width ``Fb`` instead of ``F``.

Design (docs/SCALING.md "Wide sparse frames"):

- The bundled matrix is a TRAINING-ONLY representation.  Split finding
  decodes every winning bundle slot back to the ORIGINAL
  ``(feature, bin)`` pair before tree emission
  (core._find_splits_efb), so grown ``Tree``s, ``flatten_trees`` raw-
  feature thresholds, MOJO-v2 artifacts and the whole serving stack
  are byte-identical in format to the unbundled path and never see a
  bundle.
- Each bundle column's bin space: slot 0 = "every member at its
  default bin"; each member owns a contiguous run of slots — one slot
  per non-default body bin seen in the data, one (row-empty) slot for
  the member's default bin so the ``t = default`` threshold stays a
  candidate, and one NA slot (original NA routing is learned per
  member exactly as unbundled).  Bin ``B-1`` is left unused in bundle
  columns so the node-total formula matches the unbundled one.
- Dense features pass through untouched (their column in the bundled
  matrix carries the ORIGINAL bin codes), which keeps their split
  gains bitwise-identical to the unbundled path.
- A member's default-bin mass is reconstructed as
  ``node_total - member_mass`` (exact set identity under zero
  conflicts).  The f32 reassociation this introduces is the same
  caveat the out-of-core chunk streamer documents: sums that are
  exact (integer counts, dyadic gradients — e.g. any DRF forest on a
  0/1 response, or the first gaussian round) are BITWISE equal to the
  unbundled path; general multi-round boosting agrees to float
  tolerance with identical split structure (tests/test_efb.py pins
  both).
- Conflict budget ``H2O_TPU_EFB_CONFLICT`` (fraction of rows, default
  0 = exact exclusivity): rows claimed by two members resolve
  first-member-wins at apply time; the plan is verified against the
  FULL data during apply and any member whose true conflicts exceed
  the budget is demoted to a passthrough column, so a sample-built
  plan can never silently drop rows.

Kill switch: ``H2O_TPU_EFB=0``.  Default ``auto`` plans only when the
frame is wide (>= H2O_TPU_EFB_MIN_F features, default 64) and keeps
the bundling only when it meaningfully shrinks the matrix
(Fb <= H2O_TPU_EFB_MIN_SHRINK * F, default 0.75).  ``H2O_TPU_EFB=1``
forces planning at any width and keeps any shrink.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

# sample rows the greedy planner sees (the full data re-verifies at
# apply time, demoting any member the sample mis-judged)
_PLAN_SAMPLE = 1 << 16
# a feature is bundle-eligible only when its non-default rows are at
# most this fraction of the sample (sparsity gate) ...
_MAX_DENSITY = 0.3
# ... and its slot need (non-default body bins + default slot + NA
# slot) leaves room for >= 4 members per bundle
_MAX_SLOT_FRAC = 4
# open bundles the greedy pass probes per feature before opening a new
# one (LightGBM caps its search the same way)
_MAX_BUNDLE_TRIES = 64

_POPCNT8 = np.unpackbits(
    np.arange(256, dtype=np.uint8)[:, None], axis=1).sum(1)


def efb_mode() -> str:
    """H2O_TPU_EFB: '0' off, '1' force, anything else (default) auto."""
    v = os.environ.get("H2O_TPU_EFB", "auto")
    return v if v in ("0", "1") else "auto"


def conflict_budget_frac() -> float:
    """H2O_TPU_EFB_CONFLICT: allowed conflict-ROW fraction per bundle
    (LightGBM's max_conflict_rate analog). Default 0 = exact
    exclusivity, the parity-gated configuration."""
    try:
        return max(0.0, float(os.environ.get("H2O_TPU_EFB_CONFLICT", "0")))
    except ValueError:
        return 0.0


def efb_eligible(n_features: int, checkpoint) -> bool:
    """Whether train() should even attempt a bundling plan.

    Checkpoint continuation is out (the continued trees descend the
    checkpoint's original-space binned matrix); in auto mode narrow
    frames skip the planning pass entirely so the fused no-host-sync
    prologue keeps the narrow-frame train path exactly as before."""
    mode = efb_mode()
    if mode == "0" or checkpoint is not None:
        return False
    if mode == "1":
        return n_features >= 2
    min_f = int(os.environ.get("H2O_TPU_EFB_MIN_F", "64"))
    return n_features >= max(min_f, 2)


def _keep_plan(F: int, fb: int) -> bool:
    if fb >= F:
        return False
    if efb_mode() == "1":
        return True
    try:
        shrink = float(os.environ.get("H2O_TPU_EFB_MIN_SHRINK", "0.75"))
    except ValueError:
        shrink = 0.75
    return fb <= shrink * F


class EFBLuts(NamedTuple):
    """Device LUTs the tree core descends/decodes bundles with.

    All are dense arrays (a pytree operand, replicated P() under
    shard_map).  ``B`` is the bin count, ``Fb`` the bundled width,
    ``F`` the original width; S = B-1 candidate slots per column."""

    slot_feat: jax.Array    # int32 [Fb, B]  original feature per slot, -1 none
    slot_bin: jax.Array     # int32 [Fb, B]  original bin per slot (B-1 = NA)
    na_slot: jax.Array      # int32 [Fb, B]  slot of the member's NA slot
    mstart: jax.Array       # int32 [Fb, B]  member's first body slot
    mend: jax.Array         # int32 [Fb, B]  member's last body slot
    has_rem: jax.Array      # bool  [Fb, B]  default-remainder applies (bundled)
    dbin: jax.Array         # int32 [Fb, B]  member's default original bin
    perm: jax.Array         # int32 [Fb*(B-1)] candidate rank -> flat slot,
    #                         ordered by (orig feature, orig bin) so argmax
    #                         tie-breaking matches the unbundled flat order
    feat_col: jax.Array     # int32 [F] bundled column of each original feature
    feat_default: jax.Array  # int32 [F] default original bin (0 for dense)


@dataclass
class _Member:
    feat: int
    default_bin: int
    slot_of_code: np.ndarray       # [B] uint8 code -> slot id (255 = unmapped)
    body: list                     # [(slot, orig_bin)] ascending orig bin
    na_slot: int


@dataclass
class EFBPlan:
    """Host-side bundling plan + the bundled binned matrix."""

    n_features: int
    n_bins: int
    cols: list                      # ("pass", feat) | ("bundle", [_Member])
    binned_host: np.ndarray         # [padded, Fb] uint8
    conflicts: int                  # total first-wins-resolved rows
    demoted: list = field(default_factory=list)   # feats that failed verify
    _luts: EFBLuts | None = None
    _binned_dev: object = None

    @property
    def fb(self) -> int:
        return len(self.cols)

    def device_luts(self) -> EFBLuts:
        if self._luts is None:
            self._luts = _build_luts(self)
        return self._luts

    def binned_device(self):
        """The row-sharded device bundled matrix (built lazily, cached
        on the plan — AutoML/CV repeats on the same frame pay once).
        The host copy is RELEASED on upload: the unbundled in-HBM path
        has no host-side binned matrix either, and keeping both would
        double residency for the frame-cache lifetime."""
        if self._binned_dev is None:
            from ...runtime.mrtask import shard_rows

            self._binned_dev = shard_rows(self.binned_host)
            self.binned_host = None
        return self._binned_dev

    def host_matrix(self) -> np.ndarray:
        """[padded, Fb] uint8 on host — from the retained host copy,
        or fetched back from the device copy (only possible after an
        in-HBM train already placed it there)."""
        if self.binned_host is not None:
            return self.binned_host
        return np.asarray(self._binned_dev)

    def __getstate__(self):
        d = dict(self.__dict__)
        if d["binned_host"] is None:    # rematerialize: device arrays
            d["binned_host"] = self.host_matrix()    # never pickle
        d["_binned_dev"] = None
        return d


def _build_luts(plan: EFBPlan) -> EFBLuts:
    B = plan.n_bins
    Fb = plan.fb
    F = plan.n_features
    slot_feat = np.full((Fb, B), -1, dtype=np.int32)
    slot_bin = np.full((Fb, B), B - 1, dtype=np.int32)
    na_slot = np.full((Fb, B), B - 1, dtype=np.int32)
    mstart = np.zeros((Fb, B), dtype=np.int32)
    mend = np.full((Fb, B), B - 2, dtype=np.int32)
    has_rem = np.zeros((Fb, B), dtype=bool)
    dbin = np.zeros((Fb, B), dtype=np.int32)
    feat_col = np.zeros(F, dtype=np.int32)
    feat_default = np.zeros(F, dtype=np.int32)
    for c, col in enumerate(plan.cols):
        kind, payload = col
        if kind == "pass":
            f = payload
            feat_col[f] = c
            slot_feat[c, :] = f
            slot_bin[c, :] = np.arange(B)
            # mstart 0 / mend B-2 / na B-1 / no remainder: the column
            # IS the original feature, gains reduce to the unbundled
            # cumsum bitwise
            continue
        for m in payload:
            feat_col[m.feat] = c
            feat_default[m.feat] = m.default_bin
            slots = [s for s, _ in m.body] + [m.na_slot]
            lo = m.body[0][0]
            hi = m.body[-1][0]
            for s in slots:
                slot_feat[c, s] = m.feat
                na_slot[c, s] = m.na_slot
                mstart[c, s] = lo
                mend[c, s] = hi
                has_rem[c, s] = True
                dbin[c, s] = m.default_bin
            for s, ob in m.body:
                slot_bin[c, s] = ob
            slot_bin[c, m.na_slot] = B - 1
    # candidate permutation: rank candidates (slots s < B-1) by
    # (orig feature, orig bin, column, slot); invalid slots sort last.
    # argmax over the permuted gains then picks the same winner — and
    # the same TIE winner — as the unbundled feat-major/bin-minor flat
    # argmax.
    S = B - 1
    sf = slot_feat[:, :S].ravel()
    sb = slot_bin[:, :S].ravel()
    valid = (sf >= 0) & (sb < B - 1)
    key_feat = np.where(valid, sf, F)
    key_bin = np.where(valid, sb, B)
    perm = np.lexsort((np.arange(Fb * S), key_bin, key_feat))
    return EFBLuts(
        slot_feat=jnp.asarray(slot_feat), slot_bin=jnp.asarray(slot_bin),
        na_slot=jnp.asarray(na_slot), mstart=jnp.asarray(mstart),
        mend=jnp.asarray(mend), has_rem=jnp.asarray(has_rem),
        dbin=jnp.asarray(dbin), perm=jnp.asarray(perm.astype(np.int32)),
        feat_col=jnp.asarray(feat_col),
        feat_default=jnp.asarray(feat_default))


# ---------------------------------------------------------------------------
# Planning + apply (host, column-at-a-time)
# ---------------------------------------------------------------------------

# columns binned per device dispatch in the planning/apply passes — a
# per-COLUMN dispatch + host pull would cost F serial round trips on
# exactly the wide frames EFB targets; a 128-column block of a 64k
# sample is ~32 MB f32 transient
_CODES_BLOCK = 128


def _host_codes_block(frame, spec, js, rows: int | None = None
                      ) -> np.ndarray:
    """Original bin codes of features ``js`` as a host uint8
    [rows, len(js)] block — bounded-width device transients (the
    bin_frame_host_chunks discipline), so a 10k-wide frame never
    materializes a dense [rows, F] float32 OR uint8 matrix."""
    from .binning import _bin_block_jit

    edges = jnp.asarray(spec.edges_matrix())
    enum = np.array(spec.is_enum)
    outs = []
    for lo in range(0, len(js), _CODES_BLOCK):
        blk = list(js[lo: lo + _CODES_BLOCK])
        cols = []
        for j in blk:
            c = frame.vec(spec.names[j]).as_float()
            cols.append(c[:rows] if rows is not None else c)
        outs.append(np.asarray(_bin_block_jit(
            tuple(cols), edges[np.asarray(blk)], spec.na_bin,
            jnp.asarray(enum[np.asarray(blk)]))))
    return outs[0] if len(outs) == 1 else np.concatenate(outs, axis=1)


def _pack(mask: np.ndarray) -> np.ndarray:
    return np.packbits(mask)


def _overlap(packed_a: np.ndarray, packed_b: np.ndarray) -> int:
    return int(_POPCNT8[np.bitwise_and(packed_a, packed_b)].sum())


def _feature_stats(stats, j: int, codes: np.ndarray, B: int, ns: int,
                   cap_slots: int) -> None:
    """Bundle-eligibility stats of one sampled column: dominant body
    bin (the default), non-default row mask, used-bin slot need."""
    counts = np.bincount(codes, minlength=B)
    default = int(np.argmax(counts[: B - 1]))          # body bins only
    if counts[default] <= 0:
        return                                          # all-NA column
    nnd = codes != default
    n_nnd = int(nnd.sum())
    if n_nnd > _MAX_DENSITY * ns:
        return
    used_body = np.nonzero(counts[: B - 1])[0]
    used_body = used_body[used_body != default]
    # default slot + NA slot + one per used non-default body bin
    if len(used_body) + 2 > cap_slots:
        return
    stats[j] = (default, used_body, n_nnd, _pack(nnd))


def plan_bundles(frame, spec, nrows: int | None = None):
    """Greedy graph-coloring bundler + bundled bin apply.

    Returns an ``EFBPlan`` or ``None`` when bundling would not pay
    (no exclusive sets found, or the shrink gate rejects the plan).

    Two passes over the columns, both one-column-at-a-time:
    1. sample pass (<= _PLAN_SAMPLE real rows): per-feature bin usage,
       default bin, non-default row masks; greedy packing of eligible
       features into open bundles under the conflict budget.
    2. full apply pass: bin each member over ALL rows, verify the
       conflict budget and the slot map against the full data (demote
       violators to passthrough), scatter slots into the bundled
       matrix.
    """
    F = len(spec.names)
    B = spec.n_bins
    padded = frame.vec(spec.names[0]).padded_len
    n_real = frame.nrows if nrows is None else nrows
    ns = min(n_real, _PLAN_SAMPLE)
    if ns < 1 or F < 2:
        return None
    cap_slots = max(2, (B - 2) // _MAX_SLOT_FRAC)

    # -- pass 1: sample stats + greedy packing --------------------------
    stats = {}           # feat -> (default_bin, used_body, nnd_count, packed)
    for lo in range(0, F, _CODES_BLOCK):
        js = list(range(lo, min(lo + _CODES_BLOCK, F)))
        codes_blk = _host_codes_block(frame, spec, js, rows=ns)
        for bi, j in enumerate(js):
            _feature_stats(stats, j, codes_blk[:, bi], B, ns, cap_slots)
    if len(stats) < 2:
        return None
    budget = int(conflict_budget_frac() * ns)
    order = sorted(stats, key=lambda j: (-stats[j][2], j))
    bundles = []     # dicts: members [feat], slots_used, claimed, conflicts
    for j in order:
        default, used_body, n_nnd, packed = stats[j]
        need = len(used_body) + 2
        placed = False
        for b in bundles[:_MAX_BUNDLE_TRIES]:
            if b["slots_used"] + need > B - 2:
                continue
            ov = _overlap(b["claimed"], packed)
            if b["conflicts"] + ov > budget:
                continue
            b["members"].append(j)
            b["slots_used"] += need
            b["conflicts"] += ov
            b["claimed"] = np.bitwise_or(b["claimed"], packed)
            placed = True
            break
        if not placed:
            bundles.append({"members": [j], "slots_used": 1 + need,
                            "claimed": packed.copy(), "conflicts": 0})
    bundles = [b for b in bundles if len(b["members"]) >= 2]
    if not bundles:
        return None

    # -- pass 2: full-data apply + verification ------------------------
    full_budget = int(conflict_budget_frac() * n_real)
    built = []        # ("bundle", members, buf)
    demoted: list[int] = []
    bundled_feats: set[int] = set()
    total_conflicts = 0
    for b in bundles:
        buf = np.zeros(padded, dtype=np.uint8)        # slot 0 = default
        members: list[_Member] = []
        next_slot = 1
        conflicts = 0
        codes_blk = _host_codes_block(frame, spec, b["members"])
        for mi, j in enumerate(b["members"]):
            default, used_body, _, _ = stats[j]
            codes = codes_blk[:, mi]
            # slot map: used non-default body bins ascending, the
            # (row-empty) default-candidate slot in sorted position,
            # then the NA slot at the end of the member's run
            bins_sorted = np.sort(
                np.concatenate([used_body, [default]])).astype(np.int64)
            slot_of_code = np.full(B, 255, dtype=np.uint8)
            body = []
            for k, ob in enumerate(bins_sorted):
                body.append((next_slot + k, int(ob)))
                slot_of_code[ob] = next_slot + k
            na_slot = next_slot + len(bins_sorted)
            slot_of_code[B - 1] = na_slot
            real = codes[:n_real]
            nnd_full = real != default
            unmapped = int((slot_of_code[real] == 255).sum())
            ov = int((nnd_full & (buf[:n_real] != 0)).sum())
            if unmapped > 0 or conflicts + ov > full_budget:
                # the sample mis-judged this member (unseen bins or
                # true conflicts past budget): demote to passthrough,
                # never drop rows silently
                demoted.append(j)
                continue
            write = nnd_full & (buf[:n_real] == 0)    # first member wins
            buf[:n_real][write] = slot_of_code[real[write]]
            conflicts += ov
            members.append(_Member(feat=j, default_bin=default,
                                   slot_of_code=slot_of_code, body=body,
                                   na_slot=na_slot))
            next_slot = na_slot + 1
        if len(members) >= 2:
            built.append((members, buf))
            bundled_feats.update(m.feat for m in members)
            total_conflicts += conflicts
        else:
            demoted.extend(m.feat for m in members)
    if not built:
        return None

    fb = (F - len(bundled_feats)) + len(built)
    if not _keep_plan(F, fb):
        return None

    # passthrough columns first in ORIGINAL feature order (so an
    # all-dense prefix keeps node totals bitwise-identical to the
    # unbundled path), bundles after, ordered by smallest member
    cols: list = [("pass", j) for j in range(F) if j not in bundled_feats]
    built.sort(key=lambda mb: min(m.feat for m in mb[0]))
    out = np.zeros((padded, fb), dtype=np.uint8)
    if cols:
        out[:, : len(cols)] = _host_codes_block(
            frame, spec, [j for _, j in cols])
    plan_cols = list(cols)
    for members, buf in built:
        out[:, len(plan_cols)] = buf
        plan_cols.append(("bundle", members))
    return EFBPlan(n_features=F, n_bins=B, cols=plan_cols,
                   binned_host=out, conflicts=total_conflicts,
                   demoted=sorted(demoted))


def fit_plan_cached(frame, feature_names, n_bins: int):
    """(BinSpec, EFBPlan | None) with the frame-level cache the fused
    prologue uses: keyed on (names, nbins, content version, conflict
    budget) so every AutoML candidate / share-mode CV fold after the
    first pays neither the quantile fit, the planning pass, nor the
    bundled apply."""
    from .binning import fit_bins

    cache = frame.__dict__.setdefault("_binned_cache", {})
    # every gate knob is in the key — changing H2O_TPU_EFB* mid-process
    # applies on the next train like every other read-at-use knob
    key = ("efb", tuple(feature_names), n_bins,
           frame.__dict__.get("_version", 0), conflict_budget_frac(),
           efb_mode(),
           os.environ.get("H2O_TPU_EFB_MIN_SHRINK", "0.75"))
    hit = cache.pop(key, None)
    if hit is not None:
        cache[key] = hit
        return hit
    spec = fit_bins(frame, list(feature_names), n_bins=n_bins)
    plan = plan_bundles(frame, spec)
    while len(cache) >= 2:
        cache.pop(next(iter(cache)))
    cache[key] = (spec, plan)
    return spec, plan


def chunk_plan_host(plan: EFBPlan, chunk_rows: int) -> list[np.ndarray]:
    """Slice the bundled host matrix into the out-of-core chunk grid
    (same row mapping as binning.bin_frame_host_chunks: chunk c =
    rows [c*chunk_rows, (c+1)*chunk_rows), the last chunk padded with
    dead rows)."""
    host = plan.host_matrix()
    padded, fb = host.shape
    n_chunks = -(-padded // chunk_rows)
    bufs = []
    for c in range(n_chunks):
        lo = c * chunk_rows
        hi = min(lo + chunk_rows, padded)
        buf = np.zeros((chunk_rows, fb), dtype=np.uint8)
        buf[: hi - lo] = host[lo:hi]
        bufs.append(buf)
    return bufs
