"""Shared histogram tree-growing core for GBM / DRF / XGBoost-hist.

This is the TPU redesign of the reference's SharedTree driver +
ScoreBuildHistogram2 MRTask + DTree split finding (hex/tree/SharedTree,
DHistogram, ScoreBuildHistogram2 — SURVEY.md §3.4): per level, every
row's (grad, hess, count) is accumulated into a per-node per-feature
per-bin histogram, histograms are all-reduced across row shards, and the
best split per node is an argmax over (feature, bin).

TPU-first choices (SURVEY.md §7 "hard parts"):
- dense per-row relative node ids instead of dynamic row partitions;
  dead rows carry id -1 and are masked out of histograms;
- the whole tree builds inside ONE jitted shard_map: local segment-sum
  histograms + `lax.psum` over the ROWS axis per level (the MRTask
  reduce), split finding replicated on every shard;
- trees are dense heaps padded to max_depth — no recompilation as the
  tree grows.

Split semantics: `bin <= split_bin` goes left. The NA bin is the last
bin; `na_left` per node records the learned NA direction (both
directions are scored, XGBoost-style).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from ...runtime.mesh import ROWS, global_mesh


class TreeParams(NamedTuple):
    max_depth: int = 5
    n_bins: int = 256
    min_rows: float = 10.0          # min rows per leaf (on weighted counts)
    reg_lambda: float = 0.0         # H2O GBM has no L2 penalty; XGB uses 1.0
    reg_alpha: float = 0.0
    gamma: float = 0.0              # min split gain improvement
    mtries: int = -1                # per-node feature subsampling (DRF); -1=all
    min_child_weight: float = 0.0   # min hessian mass per child (XGBoost)
    hist_impl: str = "auto"         # auto | segment | pallas (ops/histogram)
    unit_hess: bool = False         # h ≡ 1 loss: 2-channel histograms


class Tree(NamedTuple):
    """Dense heap tree: node i has children 2i+1, 2i+2. [N]=2^(d+1)-1."""

    split_feat: jax.Array   # int32 [N], -1 for leaves
    split_bin: jax.Array    # int32 [N]
    na_left: jax.Array      # bool  [N] NA direction
    is_split: jax.Array     # bool  [N]
    value: jax.Array        # f32   [N] leaf value (valid where not split)
    gain: jax.Array         # f32   [N] split gain (varimp attribution)
    # f32 [N] training weight mass reaching the node (global, psum'd) —
    # TreeSHAP's r_j. Defaulted so binary models pickled BEFORE this
    # field existed (6-tuple Trees) still unpickle; load_model backfills
    # the None (persist.py) and predict_contributions rejects it.
    cover: jax.Array = None


def _soft_thresh(g, alpha):
    return jnp.sign(g) * jnp.maximum(jnp.abs(g) - alpha, 0.0)


def _leaf_value(G, H, p: TreeParams):
    return -_soft_thresh(G, p.reg_alpha) / (H + p.reg_lambda + 1e-10)


def _gain_term(G, H, p: TreeParams):
    return _soft_thresh(G, p.reg_alpha) ** 2 / (H + p.reg_lambda + 1e-10)


# histogram accumulation lives in ops/histogram.py (segment_sum on CPU,
# the Pallas one-hot-matmul kernel on TPU)
from ...ops.histogram import build_histogram as _build_histogram_op
from ...ops.histogram import expand_unit_hess as _expand_unit_hess
from ...ops.histogram import resolve_impl as _resolve_impl


def _split_gains(left, tot4, p: TreeParams):
    """Split gain of every candidate's left stats [..., 3] against the
    node totals ``tot4`` [n, 1, 1, 3] — THE one gain formula, shared
    by `_find_splits` and `_find_splits_efb` so the EFB exactness
    contract (identical gains for identical left stats) cannot drift."""
    right = tot4 - left
    Gl, Hl, Cl = left[..., 0], left[..., 1], left[..., 2]
    Gr, Hr, Cr = right[..., 0], right[..., 1], right[..., 2]
    parent = _gain_term(tot4[..., 0], tot4[..., 1], p)
    raw = _gain_term(Gl, Hl, p) + _gain_term(Gr, Hr, p) - parent
    ok = (Cl >= p.min_rows) & (Cr >= p.min_rows)
    if p.min_child_weight > 0:
        ok &= (Hl >= p.min_child_weight) & (Hr >= p.min_child_weight)
    return jnp.where(ok, raw, -jnp.inf)


def _find_splits(hist, p: TreeParams, feat_ok=None, efb=None):
    """Best split per node from a [n_nodes, F, B, 3] histogram.

    With ``efb`` (an efb.EFBLuts pytree) the histogram is in BUNDLED
    column space and split finding dispatches to ``_find_splits_efb``,
    which decodes the winner back to the ORIGINAL (feature, bin) pair
    — downstream (tree emission, flattening, MOJO, serving) never sees
    a bundle.

    Scores every (feature, threshold-bin) cut with the NA bin (last)
    assigned to each side in turn, XGBoost-style learned NA direction.
    `feat_ok`: optional [n_nodes, F] bool mask of allowed features
    (per-tree column sampling and DRF per-node mtries) — always in
    ORIGINAL feature space, whatever the histogram width.
    Returns (feat, bin, na_left, can_split, node_value, best_gain,
    cover, left, right) per node — cover is the node's total weight
    mass (TreeSHAP's r_j); left/right are the chosen split's side
    totals [n, 3] (== the children's node totals, NA side applied),
    which the grower uses as the final level's leaf stats.
    """
    if efb is not None:
        return _find_splits_efb(hist, p, efb, feat_ok)
    nb = hist.shape[2]
    na = hist[:, :, nb - 1, :]                 # [n, F, 3]
    body = hist[:, :, : nb - 1, :]
    cum = jnp.cumsum(body, axis=2)             # left stats, NA excluded
    tot = cum[:, :, -1, :] + na                # [n, F, 3] node totals
    totn = tot[:, 0:1, :]                      # same for every feature

    tot4 = totn[:, :, None, :]                 # [n, 1, 1, 3]

    gain_na_r = _split_gains(cum, tot4, p)              # NA goes right
    gain_na_l = _split_gains(cum + na[:, :, None, :], tot4, p)  # NA left
    na_left_better = gain_na_l > gain_na_r
    gain = jnp.maximum(gain_na_l, gain_na_r)            # [n, F, B-1]
    if feat_ok is not None:
        gain = jnp.where(feat_ok[:, :, None], gain, -jnp.inf)

    n_nodes, F = gain.shape[0], gain.shape[1]
    flat = gain.reshape(n_nodes, F * (nb - 1))
    best = jnp.argmax(flat, axis=1)
    best_gain = jnp.take_along_axis(flat, best[:, None], 1)[:, 0]
    feat = (best // (nb - 1)).astype(jnp.int32)
    bin_ = (best % (nb - 1)).astype(jnp.int32)
    na_l = jnp.take_along_axis(
        na_left_better.reshape(n_nodes, -1), best[:, None], 1)[:, 0]

    # (G, H, C) of the chosen split's LEFT side (NA routed per na_l):
    # these ARE the left child's node totals, and right = parent-left —
    # the grower derives the final level's leaf stats from them instead
    # of paying one more full-row histogram pass per tree
    def pick(left4):                                   # [n, F, B-1, 3]
        return jnp.take_along_axis(
            left4.reshape(n_nodes, F * (nb - 1), 3),
            best[:, None, None], 1)[:, 0]              # [n, 3]
    left = jnp.where(na_l[:, None], pick(cum + na[:, :, None, :]),
                     pick(cum))
    right = totn[:, 0, :] - left

    G, H, C = totn[:, 0, 0], totn[:, 0, 1], totn[:, 0, 2]
    can_split = (best_gain > p.gamma) & (C >= 2 * p.min_rows) & \
        jnp.isfinite(best_gain)
    value = _leaf_value(G, H, p)
    return (feat, bin_, na_l, can_split, value, best_gain, C,
            left, right)


def _find_splits_efb(hist, p: TreeParams, efb, feat_ok):
    """EFB split finding: the histogram is [n_nodes, Fb, B, 3] in
    BUNDLED column space (models/tree/efb.py); every candidate slot is
    scored as the ORIGINAL (feature, threshold-bin) cut it encodes and
    the winner is returned decoded.

    Exactness contract (docs/SCALING.md "Wide sparse frames"): the
    candidate set and the tie-break order (original feat-major /
    bin-minor via ``efb.perm``) match `_find_splits` exactly;
    passthrough (dense) columns' gains are computed by the identical
    masked-cumsum program and are bitwise-equal; bundled members'
    default-bin mass is reconstructed as ``node_total - member_mass``
    — an exact set identity under zero conflicts whose f32
    reassociation is bitwise-neutral whenever the sums are exact
    (integer counts, dyadic gradients) and float-tolerance otherwise,
    the same caveat ooc.py documents for chunk-boundary sums."""
    nb = hist.shape[2]
    n, Fb = hist.shape[0], hist.shape[1]
    S = nb - 1
    sf = efb.slot_feat[:, :S]                    # [Fb, S]
    sb = efb.slot_bin[:, :S]
    body_mask = (efb.slot_feat >= 0) & (efb.slot_bin < nb - 1)  # [Fb, nb]
    body = hist[:, :, :S, :] * body_mask[None, :, :S, None]
    cum = jnp.cumsum(body, axis=2)               # [n, Fb, S, 3]
    # node totals from column 0: body cumsum tail + the non-body mass
    # (default slot, member NA slots; zeros only for a passthrough
    # column, where this reduces to the unbundled cum[-1] + na)
    nonbody0 = ~body_mask[0]
    totn = cum[:, 0, -1, :] + jnp.sum(
        hist[:, 0, :, :] * nonbody0[None, :, None], axis=1)     # [n, 3]
    tot4 = totn[:, None, None, :]
    # per-candidate member stats: NA mass, member-local prefix (left
    # stats excluding default/NA), member total (body + NA)
    na_idx = jnp.broadcast_to(efb.na_slot[None, :, :S, None],
                              (n, Fb, S, 3))
    na_c = jnp.take_along_axis(hist, na_idx, axis=2)            # [n,Fb,S,3]
    mstart = efb.mstart[:, :S]
    pre_idx = jnp.broadcast_to(
        jnp.maximum(mstart - 1, 0)[None, :, :, None], cum.shape)
    pre = jnp.take_along_axis(cum, pre_idx, axis=2)
    started = (mstart > 0)[None, :, :, None]
    mleft = jnp.where(started, cum - pre, cum)
    end_idx = jnp.broadcast_to(efb.mend[None, :, :S, None], cum.shape)
    mtot = jnp.take_along_axis(cum, end_idx, axis=2)
    mtot = jnp.where(started, mtot - pre, mtot)
    has_rem = efb.has_rem[:, :S]
    # default-bin remainder: every node row not in this member's own
    # slots sits at the member's default bin (zero-conflict identity)
    rem = jnp.where(has_rem[None, :, :, None],
                    tot4 - (mtot + na_c), 0.0)
    add_rem = has_rem & (sb >= efb.dbin[:, :S])
    left = mleft + jnp.where(add_rem[None, :, :, None], rem, 0.0)

    gain_na_r = _split_gains(left, tot4, p)          # NA goes right
    gain_na_l = _split_gains(left + na_c, tot4, p)   # NA goes left
    na_left_better = gain_na_l > gain_na_r
    gain = jnp.maximum(gain_na_l, gain_na_r)     # [n, Fb, S]
    cand = body_mask[:, :S]
    if feat_ok is None:
        feat_ok = jnp.ones((n, efb.feat_col.shape[0]), dtype=bool)
    fok = feat_ok[:, jnp.maximum(sf, 0).reshape(-1)].reshape(n, Fb, S)
    gain = jnp.where(cand[None, :, :] & fok, gain, -jnp.inf)
    flat = gain.reshape(n, Fb * S)[:, efb.perm]  # (feat, bin) order
    best_rank = jnp.argmax(flat, axis=1)
    best_gain = jnp.take_along_axis(flat, best_rank[:, None], 1)[:, 0]
    best = efb.perm[best_rank]                   # flat (col, slot) index
    feat = jnp.maximum(sf.reshape(-1)[best], 0).astype(jnp.int32)
    bin_ = jnp.clip(sb.reshape(-1)[best], 0, nb - 2).astype(jnp.int32)
    na_l = jnp.take_along_axis(
        na_left_better.reshape(n, -1), best[:, None], 1)[:, 0]

    def pick(l4):
        return jnp.take_along_axis(
            l4.reshape(n, Fb * S, 3), best[:, None, None], 1)[:, 0]
    left_w = jnp.where(na_l[:, None], pick(left + na_c), pick(left))
    right_w = totn - left_w

    G, H, C = totn[:, 0], totn[:, 1], totn[:, 2]
    can_split = (best_gain > p.gamma) & (C >= 2 * p.min_rows) & \
        jnp.isfinite(best_gain)
    value = _leaf_value(G, H, p)
    return (feat, bin_, na_l, can_split, value, best_gain, C,
            left_w, right_w)


def row_orig_bins(binned, f, efb):
    """Per-row ORIGINAL-space bin of (per-row) feature ``f`` — the ONE
    decode both the fused grower and the out-of-core descent use.
    Unbundled: a plain column gather. Bundled: gather the row's bundle
    slot from feature f's column, then LUT-decode (rows whose slot
    belongs to another member sit at f's default bin; a member NA slot
    decodes to the NA bin, preserving learned NA routing)."""
    if efb is None:
        return jnp.take_along_axis(
            binned, f[:, None].astype(jnp.int32), axis=1)[:, 0].astype(
            jnp.int32)
    col = efb.feat_col[f]
    s = jnp.take_along_axis(
        binned, col[:, None].astype(jnp.int32), axis=1)[:, 0].astype(
        jnp.int32)
    sf = efb.slot_feat[col, s]
    sb = efb.slot_bin[col, s]
    return jnp.where(sf == f, sb, efb.feat_default[f]).astype(jnp.int32)


def _grow_tree_shard(binned, g, h, w, col_mask, key, p: TreeParams,
                     efb=None):
    """Per-shard tree build (runs under shard_map; histograms psum'd).

    Returns (Tree, leaf_node): `leaf_node` is each row's final absolute
    heap index — the grower already walks each row to its resting node,
    so the boost loop reads `tree.value[leaf_node]` instead of paying a
    second full heap descent per tree (predict_tree).

    ``efb``: optional bundle LUTs (models/tree/efb.py) — ``binned`` is
    then the BUNDLED matrix, histograms/psums run at bundled width,
    and splits/descents are decoded to original feature space.
    """
    F = col_mask.shape[0]       # ORIGINAL feature count (== binned
    #                             width only when efb is None)
    N = 2 ** (p.max_depth + 1) - 1
    split_feat = jnp.full(N, -1, dtype=jnp.int32)
    split_bin = jnp.zeros(N, dtype=jnp.int32)
    na_left = jnp.zeros(N, dtype=bool)
    is_split = jnp.zeros(N, dtype=bool)
    value = jnp.zeros(N, dtype=jnp.float32)
    gain = jnp.zeros(N, dtype=jnp.float32)
    cover = jnp.zeros(N, dtype=jnp.float32)

    rel = jnp.zeros(binned.shape[0], dtype=jnp.int32)   # relative node @ lvl
    abs_node = jnp.zeros(binned.shape[0], dtype=jnp.int32)

    hist_prev = None        # parent histograms for sibling subtraction
    can_prev = None
    for d in range(p.max_depth + 1):
        n_nodes = 2 ** d
        off = n_nodes - 1
        if d == p.max_depth:
            # final level: every node is a forced leaf, and its
            # (G, H, C) totals are EXACTLY the parent's chosen-split
            # side stats (same rows, NA routing included) — already in
            # hand from _find_splits at the previous level. Rounds 2-3
            # built a histogram here (full at first — half the tree's
            # matmul work — then single-bin); now it costs NOTHING:
            # no row-stream pass, no psum.
            if d == 0:
                # depth-0 stump: no parent level exists — one
                # single-bin pass for the root totals
                zero_bin = jnp.zeros((binned.shape[0], 1),
                                     dtype=binned.dtype)
                tot = _build_histogram_op(zero_bin, rel, g, h, w, 1, 1,
                                          impl=p.hist_impl,
                                          unit_hess=p.unit_hess)
                tot = lax.psum(tot, ROWS)
                if p.unit_hess:
                    tot = _expand_unit_hess(tot)
                tot = tot[:, 0, 0, :]
            else:
                tot = jnp.where(can_prev[:, None, None],
                                jnp.stack([left_prev, right_prev],
                                          axis=1),
                                0.0).reshape(n_nodes, 3)  # child order
            idx = off + jnp.arange(n_nodes)
            value = value.at[idx].set(
                _leaf_value(tot[:, 0], tot[:, 1], p))
            cover = cover.at[idx].set(tot[:, 2])
            break
        if d == 0:
            hist = _build_histogram_op(binned, rel, g, h, w, 1,
                                       p.n_bins, impl=p.hist_impl,
                                       unit_hess=p.unit_hess)
            hist = lax.psum(hist, ROWS)                 # MRTask reduce
            if p.unit_hess:
                hist = _expand_unit_hess(hist)
        else:
            # sibling subtraction (the XGBoost/LightGBM trick): histogram
            # only LEFT children, derive right = parent - left. Halves
            # the hot-loop FLOPs and the psum payload at every level.
            # Valid because every live row of a split parent lands in
            # exactly one child; children of non-split parents are
            # zeroed so _find_splits can't fabricate splits from the
            # stale parent mass.
            left_rel = jnp.where((rel >= 0) & (rel % 2 == 0), rel // 2, -1)
            hist_l = _build_histogram_op(binned, left_rel, g, h, w,
                                         n_nodes // 2, p.n_bins,
                                         impl=p.hist_impl,
                                         unit_hess=p.unit_hess)
            hist_l = lax.psum(hist_l, ROWS)
            if p.unit_hess:
                hist_l = _expand_unit_hess(hist_l)
            parent = jnp.where(can_prev[:, None, None, None], hist_prev,
                               0.0)
            hist_l = jnp.where(can_prev[:, None, None, None], hist_l, 0.0)
            hist_r = parent - hist_l
            hist = jnp.stack([hist_l, hist_r], axis=1).reshape(
                n_nodes, binned.shape[1], p.n_bins, 3)
        feat_ok = jnp.broadcast_to(col_mask[None, :], (n_nodes, F))
        if p.mtries > 0 and p.mtries < F:
            # DRF: exactly mtries features per node (reference: DTree
            # per-split feature sampling with mtries, SURVEY.md §2b C10)
            r = jax.random.uniform(jax.random.fold_in(key, d), (n_nodes, F))
            r = jnp.where(feat_ok, r, jnp.inf)
            kth = jnp.sort(r, axis=1)[:, p.mtries - 1: p.mtries]
            feat_ok = feat_ok & (r <= kth)
        (feat, bin_, na_l, can, val, g_best, cov, left_ch,
         right_ch) = _find_splits(hist, p, feat_ok, efb)
        idx = off + jnp.arange(n_nodes)
        split_feat = split_feat.at[idx].set(jnp.where(can, feat, -1))
        split_bin = split_bin.at[idx].set(bin_)
        na_left = na_left.at[idx].set(na_l)
        is_split = is_split.at[idx].set(can)
        value = value.at[idx].set(val)
        gain = gain.at[idx].set(jnp.where(can, g_best, 0.0))
        cover = cover.at[idx].set(cov)
        hist_prev, can_prev = hist, can
        left_prev, right_prev = left_ch, right_ch
        # descend rows: dead rows stay dead; rows in non-split nodes die
        live = rel >= 0
        safe_rel = jnp.where(live, rel, 0)
        f = feat[safe_rel]
        b = bin_[safe_rel]
        nl = na_l[safe_rel]
        rowbin = row_orig_bins(binned, f, efb)
        is_na = rowbin == p.n_bins - 1
        go_right = jnp.where(is_na, ~nl, rowbin > b)
        child = 2 * rel + go_right.astype(jnp.int32)  # rel index at d+1
        moved = live & can[safe_rel]
        rel = jnp.where(moved, child, -1)
        abs_node = jnp.where(moved, (2 ** (d + 1) - 1) + child, abs_node)

    return Tree(split_feat, split_bin, na_left, is_split, value, gain,
                cover), abs_node


def grow_tree(binned, g, h, w, p: TreeParams, col_mask=None, key=None,
              mesh=None, efb=None) -> Tree:
    """Build one tree over row-sharded inputs. Tree is replicated."""
    if col_mask is None:
        n_feat = efb.feat_col.shape[0] if efb is not None \
            else binned.shape[1]
        col_mask = jnp.ones(n_feat, dtype=bool)
    if key is None:
        key = jax.random.key(0)
    return _grow_tree_jit(binned, g, h, w, col_mask, key, efb, p,
                          mesh or global_mesh())


def _grad_hess(distribution: str, margin, y):
    """Gradient/hessian of the boosting loss at the current margin
    (hex/genmodel DistributionFamily analogs — see models/gbm.py)."""
    if distribution == "gaussian":
        return margin - y, jnp.ones_like(margin)
    if distribution == "bernoulli":
        p = jax.nn.sigmoid(margin)
        return p - y, p * (1.0 - p)
    if distribution == "poisson":
        mu = jnp.exp(margin)
        return mu - y, mu
    if distribution == "gamma":
        # gamma deviance, log link: g = 1 - y·e^{-f}, h = y·e^{-f}
        ye = y * jnp.exp(-margin)
        return 1.0 - ye, jnp.clip(ye, 1e-10, None)
    if distribution == "tweedie":
        pw = 1.5                      # variance power (fixed, like H2O's
        a = y * jnp.exp((1.0 - pw) * margin)      # default 1.5)
        b = jnp.exp((2.0 - pw) * margin)
        return b - a, jnp.clip((2.0 - pw) * b - (1.0 - pw) * a,
                               1e-10, None)
    if distribution == "laplace":
        return jnp.sign(margin - y), jnp.ones_like(margin)
    raise ValueError(distribution)


class BoostParams(NamedTuple):
    """Static config of the fused boosting loop (hashable for jit)."""

    distribution: str = "gaussian"
    learn_rate: float = 0.1
    sample_rate: float = 1.0
    col_sample_rate_per_tree: float = 1.0
    drf_mode: bool = False
    quantile_alpha: float = 0.5     # quantile distribution's τ
    huber_alpha: float = 0.9        # huber δ = this quantile of |resid|
    # GOSS (gradient-based one-side sampling, arXiv:1809.04559):
    # goss_b > 0 activates it — keep the top-`goss_a` fraction of rows
    # by |gradient| plus a seeded `goss_b` fraction of the rest,
    # amplified by (1-a)/b so split gains stay unbiased. 0.0 = off
    # (the H2O_TPU_GOSS kill-switch path traces byte-identically to a
    # build without the feature). models/gbm.goss_params is the ONE
    # env reader.
    goss_a: float = 0.0
    goss_b: float = 0.0


def _boost_grad_hess(bp: BoostParams, margin, y, w):
    """Per-round (g, h) including the distributions whose gradients
    need BoostParams state (quantile's τ, huber's per-round δ); plain
    families delegate to _grad_hess.

    huber re-derives δ every round as the huber_alpha quantile of the
    CURRENT absolute residuals (hex/tree/gbm GBM.java recomputes δ per
    scoring pass [U3]); under shard_map the quantile is computed per
    shard and pmean'd over ROWS — a distributed approximation of the
    global order statistic (exact would need an all-gather sort).
    """
    if bp.distribution == "quantile":
        a = bp.quantile_alpha
        g = jnp.where(margin < y, -a, 1.0 - a)
        return g, jnp.ones_like(y)
    if bp.distribution == "huber":
        r = y - margin
        absr = jnp.where(w > 0, jnp.abs(r), jnp.nan)
        delta = lax.pmean(jnp.nanquantile(absr, bp.huber_alpha), ROWS)
        g = jnp.where(jnp.abs(r) <= delta, -r, -delta * jnp.sign(r))
        return g, jnp.ones_like(y)
    return _grad_hess(bp.distribution, margin, y)


def _round_sampling(bp: BoostParams, w, F: int, k_row, k_col):
    """Shard-level row/column sampling for one boosting round →
    (w_t, col_mask). Shared by ``_boost_shard`` and
    ``_boost_shard_multi``; ``models/xgboost.py::_rank_round`` applies
    the same scheme host-side (outside shard_map) — keep the semantics
    in sync."""
    w_t = w
    if bp.sample_rate < 1.0:
        # fold in the shard index: every shard holds different rows
        # and must draw an independent keep-pattern
        k_row_s = jax.random.fold_in(k_row, lax.axis_index(ROWS))
        keep = jax.random.uniform(k_row_s, w.shape) < bp.sample_rate
        w_t = w * keep
    col_mask = jnp.ones(F, dtype=bool)
    if bp.col_sample_rate_per_tree < 1.0:
        # same key on every shard → consistent replicated mask
        col_mask = jax.random.uniform(
            k_col, (F,)) < bp.col_sample_rate_per_tree
    return w_t, col_mask


# ---------------------------------------------------------------------------
# GOSS — gradient-based one-side sampling (arXiv:1809.04559)
# ---------------------------------------------------------------------------
#
# Per boosting round, keep the top-`a` fraction of rows by |gradient|
# outright plus a seeded random draw of the rest, and amplify every
# sampled small-gradient row's (g·w, h·w, w) histogram contribution by
# (1-a)/b so split gains stay unbiased. Everything below is STATIC
# SHAPE: the selected rows are compacted per shard into a fixed-
# capacity buffer (goss_cap_rows) and only THAT buffer streams through
# the per-level histogram kernels — the 3-5× row reduction is real
# compute, not just masking; unfilled slots carry w=0 and contribute
# nothing (the same dead-row discipline as the rel == -1 mask).
#
# Layout invariance (the in-HBM mesh layout and the ooc chunk grid
# must select the SAME rows at the same seed, or the two paths would
# train different models): every per-row decision is a pure function
# of (a) GLOBAL ranking stats that are exactly associative — the max
# of |g| and an int32 count histogram of |g| bins, both order-
# independent under psum / cross-chunk adds — and (b) a per-row
# threefry hash of (round key, GLOBAL row id). No sort, no per-shard
# quantile, no draw whose value depends on how rows are sharded.
#
# Tie handling: the top set is "bins strictly above the threshold bin
# T" plus a per-row hash draw with probability frac_T inside bin T, so
# the kept-outright fraction hits `a` in expectation even when |g| is
# massively tied (round-1 bernoulli has exactly two |g| values). Rows
# that lose the bin-T draw fall through to the random-`b` rule, so
# every row's expected weight is exactly its true weight:
#   bin > T:   1
#   bin == T:  frac_T·1 + (1-frac_T)·q·amp = frac_T + (1-frac_T) = 1
#   bin < T:   q·amp = 1          (q = b/(1-a), amp = (1-a)/b = 1/q)

_GOSS_BINS = 2048       # |g|-ranking histogram resolution
_GOSS_SLACK = 1.25      # compaction capacity over the expected a+b rows
_GOSS_KEY_TAG = 0x9055  # fold_in tag of the path-invariant key stream


def goss_round_keys(key, n_trees: int):
    """Per-round GOSS key stream, derived from the estimator seed key
    OUTSIDE the per-dispatch key schedule — the fused in-HBM chunks
    and the ooc stream index it by global tree number, so both paths
    draw identical per-row keep patterns at the same seed."""
    return jax.random.split(jax.random.fold_in(key, _GOSS_KEY_TAG),
                            n_trees)


def goss_cap_rows(rows: int, a: float, b: float) -> int:
    """Static per-shard capacity of the compacted row buffer: the
    expected selected fraction is exactly a+b (see the tie-handling
    note above), so 1.25× slack + a 64-row floor absorbs the binomial
    fluctuation at any realistic shard size. Overflow (possible only
    far past the slack) drops the latest selected rows of the segment
    — a documented approximation, never an error."""
    cap = int(rows * (a + b) * _GOSS_SLACK) + 64
    cap = -(-cap // 8) * 8
    return min(rows, cap)


def goss_rank_stat(g, w):
    """Per-row |gradient| ranking stat masked to live (w>0) rows;
    multi-output [K, rows] gradients rank by the class L1 norm."""
    absg = jnp.abs(g) if g.ndim == 1 else jnp.sum(jnp.abs(g), axis=0)
    return jnp.where(w > 0, absg, 0.0)


def _goss_bin_ids(absg, m):
    scale = _GOSS_BINS / jnp.maximum(m, 1e-30)
    return jnp.clip((absg * scale).astype(jnp.int32), 0, _GOSS_BINS - 1)


def goss_local_counts(absg, live, m):
    """(int32 [GOSS_BINS] counts, int32 live count) for this segment —
    integer sums are exactly associative, so psum over shards and adds
    over ooc chunks give the SAME global histogram in any order."""
    bins = _goss_bin_ids(absg, m)
    counts = jnp.zeros(_GOSS_BINS, jnp.int32).at[bins].add(
        live.astype(jnp.int32))
    return counts, jnp.sum(live.astype(jnp.int32))


def goss_threshold(counts, total, a: float):
    """(T, frac_T) from the GLOBAL count histogram: rows in bins > T
    are kept outright; a row in bin T is kept outright when its hash
    draw lands under frac_T — together the top-`a` fraction in
    expectation, whatever the tie structure."""
    suffix = jnp.cumsum(counts[::-1])[::-1].astype(jnp.float32)
    k_top = jnp.float32(a) * total.astype(jnp.float32)
    T = jnp.sum((suffix >= k_top).astype(jnp.int32)) - 1
    T = jnp.clip(T, 0, _GOSS_BINS - 1)
    cnt_T = counts[T].astype(jnp.float32)
    above = suffix[T] - cnt_T                  # count(bin > T)
    frac = jnp.clip((k_top - above) / jnp.maximum(cnt_T, 1.0), 0.0, 1.0)
    return T, frac


def goss_row_factor(absg, live, m, T, frac_T, kg, row_ids,
                    a: float, b: float):
    """f32 per-row GOSS weight factor in {0, 1, (1-a)/b}. The two
    uniforms per row come from a threefry hash of (kg, global row id)
    — layout-invariant by construction."""
    q = b / (1.0 - a)              # rest-row keep probability
    amp = (1.0 - a) / b            # rest-row amplification = 1/q
    keys = jax.vmap(jax.random.fold_in, in_axes=(None, 0))(kg, row_ids)
    u = jax.vmap(lambda k: jax.random.uniform(k, (2,)))(keys)
    bins = _goss_bin_ids(absg, m)
    top = (bins > T) | ((bins == T) & (u[:, 0] < frac_T))
    factor = jnp.where(top, jnp.float32(1.0),
                       jnp.where(u[:, 1] < q, jnp.float32(amp),
                                 jnp.float32(0.0)))
    return jnp.where(live, factor, 0.0)


def goss_amplified_w(g, w, kg, bp: BoostParams):
    """Runs UNDER shard_map (the in-HBM fused path): global ranking
    stats via pmax/psum over ROWS, then the per-row amplified weight
    w·factor for this shard's rows."""
    a, b = bp.goss_a, bp.goss_b
    absg = goss_rank_stat(g, w)
    live = w > 0
    m = lax.pmax(jnp.max(absg), ROWS)
    counts, nlive = goss_local_counts(absg, live, m)
    counts = lax.psum(counts, ROWS)
    total = lax.psum(nlive, ROWS)
    T, frac = goss_threshold(counts, total, a)
    rows_local = w.shape[0]
    row_ids = lax.axis_index(ROWS) * rows_local + \
        jnp.arange(rows_local, dtype=jnp.int32)
    return w * goss_row_factor(absg, live, m, T, frac, kg, row_ids,
                               a, b)


def goss_compact(binned, g, h, w_amp, cap: int):
    """Per-shard static-capacity compaction of the selected
    (w_amp > 0) rows, in ascending row order. Unfilled slots gather
    row 0 with w=0 — zero histogram contribution, exactly the dead-row
    semantics of the rel == -1 mask. g may be [rows] or [K, rows].

    Returns (binned, g, h, w, dropped): ``dropped`` is this segment's
    overflow count max(nsel - cap, 0) — the cap is sized for the
    EXPECTED a+b fraction, but the top-a set follows the data layout,
    so a frame whose row ORDER correlates with |gradient| (sorted by
    target/residual) can cluster far more than (a+b)·rows into one
    shard. The count is psum'd/summed by the callers and surfaced as
    a loud warning (models/gbm) — a silent drop of exactly the
    highest-gradient rows must never be silent."""
    sel = w_amp > 0
    idx = jnp.nonzero(sel, size=cap, fill_value=0)[0].astype(jnp.int32)
    nsel = jnp.sum(sel.astype(jnp.int32))
    valid = jnp.arange(cap, dtype=jnp.int32) < nsel
    wC = jnp.where(valid, w_amp[idx], 0.0)
    if g.ndim == 1:
        gC, hC = g[idx], h[idx]
    else:
        gC, hC = g[:, idx], h[:, idx]
    dropped = jnp.maximum(nsel - cap, 0)
    return binned[idx], gC, hC, wC, dropped


def _boost_shard(binned, y, w, margin, keys, efb=None, *,
                 p: TreeParams, bp: BoostParams):
    """Scan over trees INSIDE one shard_map: grad/hess → grow → local
    margin update, with histograms psum'd per level.

    This replaces the reference's per-tree driver round trips
    (SharedTree.Driver.computeImpl's outer loop, SURVEY.md §3.4) with a
    single compiled program — the margin never leaves the device and
    the host dispatches once per chunk of trees instead of ≥3 times per
    tree.
    """
    F = efb.feat_col.shape[0] if efb is not None else binned.shape[1]
    goss = bp.goss_b > 0.0

    def body(margin, kt):
        if goss:
            kt, kg = kt
        k_row, k_col, k_tree = jax.random.split(kt, 3)
        w_t, col_mask = _round_sampling(bp, w, F, k_row, k_col)
        if bp.drf_mode:
            g, h = -y, jnp.ones_like(y)
        else:
            g, h = _boost_grad_hess(bp, margin, y, w)
        if goss:
            # GOSS: amplified weights → static-cap compaction → the
            # grower streams only the sampled rows. The margin update
            # re-descends the FULL binned matrix through the grown
            # tree (the grower's leaf walk only covers sampled rows).
            w_amp = goss_amplified_w(g, w_t, kg, bp)
            cap = goss_cap_rows(binned.shape[0], bp.goss_a, bp.goss_b)
            bC, gC, hC, wC, dropped = goss_compact(binned, g, h,
                                                   w_amp, cap)
            tree, _ = _grow_tree_shard(bC, gC, hC, wC, col_mask,
                                       k_tree, p, efb)
            tree = tree._replace(value=bp.learn_rate * tree.value)
            if not bp.drf_mode:
                margin = margin + tree.value[descend_tree(
                    tree, binned, p.max_depth, p.n_bins, efb)]
            return margin, (tree, lax.psum(dropped, ROWS))
        tree, leaf = _grow_tree_shard(binned, g, h, w_t, col_mask,
                                      k_tree, p, efb)
        tree = tree._replace(value=bp.learn_rate * tree.value)
        if not bp.drf_mode:
            # the grower already walked each row to its leaf: one gather
            # replaces a full predict_tree heap re-descent per tree
            margin = margin + tree.value[leaf]
        return margin, tree

    if goss:
        margin, (trees, dropped) = lax.scan(body, margin, keys)
        return margin, trees, jnp.sum(dropped)
    margin, trees = lax.scan(body, margin, keys)
    return margin, trees


# live histogram bytes allowed for the vmapped K-class grow (per shard,
# deepest level) before _boost_shard_multi drops to sequential lax.map
_MULTI_HIST_BUDGET = 2 ** 30


def level_hist_bytes(p: TreeParams, F: int) -> int:
    """Peak live histogram bytes for ONE tree's deepest level: the ×5
    covers hist_prev, hist_l, hist_r (2^(d-1) nodes each) and the
    stacked level (2^d nodes) live at once. THE single accounting used
    by the up-front budget validation (models/gbm.py), the multinomial
    vmap-vs-lax.map branch, and grouped-DRF G sizing — one formula so
    the validator and the branch decisions cannot drift."""
    C = 2 if p.unit_hess else 3
    return 5 * (2 ** max(p.max_depth - 1, 0)) * F * p.n_bins * C * 4


def multi_grow_vmapped(p: TreeParams, F: int, K: int) -> bool:
    """True when the K-class grow vmaps (K× histograms live); False
    when it falls to lax.map with one class's histograms live."""
    return K * level_hist_bytes(p, F) <= _MULTI_HIST_BUDGET


def _boost_shard_multi(binned, y, w, margin, keys, efb=None, *,
                       p: TreeParams, bp: BoostParams, K: int):
    """Multinomial analog of ``_boost_shard``: K class trees grow per
    boosting round via ``vmap`` over the class axis (per-level psums
    batch across classes), inside the same scan-over-rounds shard_map.

    Replaces the round-2 host loop (K ``grow_tree`` + K predict
    dispatches per iteration — the exact dispatch-latency failure mode
    PROFILE.md documents for round-1 binomial). Margin is [rows, K] and
    never leaves the device; one dispatch covers a whole chunk of
    boosting rounds. Reference: hex/tree/gbm/GBM.java grows the K class
    trees of an iteration from shared softmax probs (SURVEY.md §3.4).
    """
    F = efb.feat_col.shape[0] if efb is not None else binned.shape[1]
    goss = bp.goss_b > 0.0

    def body(margin, kt):
        if goss:
            kt, kg = kt
        k_row, k_col, k_tree = jax.random.split(kt, 3)
        # one row-sample per ROUND, shared by its K trees (the
        # reference samples per iteration, not per class tree)
        w_t, col_mask = _round_sampling(bp, w, F, k_row, k_col)
        # NaN responses (w=0 pad rows) compare False for every class
        yk = (y[:, None] == jnp.arange(K, dtype=y.dtype)[None, :]
              ).astype(jnp.float32)                      # [rows, K]
        if bp.drf_mode:
            g = -yk.T
            h = jnp.ones_like(g)
        else:
            probs = jax.nn.softmax(margin, axis=1)
            g = (probs - yk).T                           # [K, rows]
            h = (probs * (1.0 - probs)).T
        if goss:
            # one GOSS draw per ROUND (rows ranked by the class-L1
            # gradient norm), shared by its K class trees — the same
            # per-iteration discipline as the row sample above
            w_amp = goss_amplified_w(g, w_t, kg, bp)
            cap = goss_cap_rows(binned.shape[0], bp.goss_a, bp.goss_b)
            bC, gC, hC, wC, dropped = goss_compact(binned, g, h,
                                                   w_amp, cap)
        else:
            bC, gC, hC, wC = binned, g, h, None

        def grow_one(gk, hk, kk):
            return _grow_tree_shard(bC, gk, hk,
                                    wC if goss else w_t, col_mask, kk,
                                    p, efb)

        keys_k = jax.random.split(k_tree, K)
        # vmap multiplies per-level histogram memory by K; past a VMEM/
        # HBM budget grow classes sequentially INSIDE the dispatch
        # (lax.map: 1/K the live histogram footprint, still one compile).
        # The decision uses the HISTOGRAM width (binned.shape[1] — the
        # bundled width under EFB), matching gbm.py's validator, which
        # also means bundling buys back the K-vmapped growth on wide
        # sparse frames
        if multi_grow_vmapped(p, binned.shape[1], K):
            trees, leaf = jax.vmap(grow_one)(gC, hC, keys_k)
        else:
            trees, leaf = lax.map(lambda a: grow_one(*a),
                                  (gC, hC, keys_k))
        trees = trees._replace(value=bp.learn_rate * trees.value)
        if not bp.drf_mode:
            if goss:
                # sampled grow → full-row leaf values by re-descent
                upd = jax.vmap(lambda tr: tr.value[descend_tree(
                    tr, binned, p.max_depth, p.n_bins, efb)])(trees)
            else:
                upd = jax.vmap(lambda v, lf: v[lf])(trees.value, leaf)
            margin = margin + upd.T
        if goss:
            return margin, (trees, lax.psum(dropped, ROWS))
        return margin, trees

    if goss:
        margin, (trees, dropped) = lax.scan(body, margin, keys)
        return margin, trees, jnp.sum(dropped)
    margin, trees = lax.scan(body, margin, keys)
    return margin, trees


def _boost_shard_drf(binned, y, w, margin, keys, efb=None, *,
                     p: TreeParams, bp: BoostParams, G: int):
    """DRF grouped growth: forest trees are INDEPENDENT (no margin
    coupling), so G trees grow per scan step via vmap — the
    class-flattening custom_vmap rule relabels tree g's rows to nodes
    [g·n_nodes, (g+1)·n_nodes) and ONE kernel call covers the group.
    Two wins over the sequential scan: the MXU M dimension (channels ×
    hi-slots) is G× fuller at shallow tree levels (PROFILE.md names
    sub-128 M as a main MFU lever), and the per-level sequencing
    overhead amortizes over G trees. keys: [rounds, G]."""
    F = efb.feat_col.shape[0] if efb is not None else binned.shape[1]
    g0 = -y
    h0 = jnp.ones_like(y)

    def body(carry, kt_group):
        def grow_one(kt):
            k_row, k_col, k_tree = jax.random.split(kt, 3)
            w_t, col_mask = _round_sampling(bp, w, F, k_row, k_col)
            tree, _ = _grow_tree_shard(binned, g0, h0, w_t, col_mask,
                                       k_tree, p, efb)
            return tree

        return carry, jax.vmap(grow_one)(kt_group)

    _, trees = lax.scan(body, 0, keys)
    # [rounds, G, N] -> [rounds*G, N]
    return margin, jax.tree.map(
        lambda a: a.reshape((-1,) + a.shape[2:]), trees)


@functools.partial(jax.jit, static_argnums=(6, 7, 8, 9))
def _boost_drf_jit(binned, y, w, margin, keys, efb, p: TreeParams,
                   bp: BoostParams, G: int, mesh):
    fn = jax.shard_map(
        functools.partial(_boost_shard_drf, p=p, bp=bp, G=G),
        mesh=mesh,
        in_specs=(P(ROWS), P(ROWS), P(ROWS), P(ROWS), P(), P()),
        out_specs=(P(ROWS), P()),
        check_vma=_resolve_impl(p.hist_impl) == "segment")
    return fn(binned, y, w, margin, keys, efb)


def drf_group_size(n_trees: int, p: TreeParams, F: int) -> tuple[int, int]:
    """(G, rounds) for the grouped DRF grow — the ONE sizing used by
    boost_trees_drf and by compile-ahead (models/gbm.py), so the
    pre-lowered executable's key shape cannot drift from the dispatch.

    Same live-histogram accounting as the multinomial path: vmap
    multiplies per-level histogram memory by G. Grouping only pays on
    the MXU (fuller M, fewer kernel launches); under the segment impl
    (CPU mesh) it just multiplies live memory on a shared host — and
    the virtual-device mesh multiplies it again by the shard count —
    so grow sequentially there."""
    hist_bytes = level_hist_bytes(p, F)
    if _resolve_impl(p.hist_impl) != "pallas":
        G = 1
    else:
        # the user's histogram-memory budget (gbm.py validates single-
        # tree fit against it) also caps the GROUP's live memory — a
        # grouped grow must not exceed what the validation promised
        import os as _os

        budget = min(_MULTI_HIST_BUDGET,
                     int(float(_os.environ.get(
                         "H2O_TPU_HIST_BYTES_BUDGET", 2 ** 30))))
        G = int(max(1, min(n_trees, 16, budget // hist_bytes)))
    rounds = -(-n_trees // G)
    # rebalance: n_trees=20, G=16 would grow 2 rounds x 16 = 32 trees
    # and throw 12 away; G = ceil(n_trees / rounds) keeps the same
    # round count (and stays under the old G, hence under budget) with
    # minimal padded work
    return -(-n_trees // rounds), rounds


def boost_trees_drf(binned, y, w, margin, key, n_trees: int,
                    p: TreeParams, bp: BoostParams, mesh=None,
                    efb=None):
    """Grouped DRF forest growth: n_trees independent trees in ONE
    dispatch, vmapped in groups sized to the histogram memory budget
    (drf_group_size). Returns (margin unchanged, trees [n_trees, N]).
    Group sizing uses the HISTOGRAM width — the bundled width under
    EFB, which is the whole point: more trees fit a group."""
    assert bp.drf_mode
    F = binned.shape[1]
    G, rounds = drf_group_size(n_trees, p, F)
    keys = jax.random.split(key, rounds * G).reshape(rounds, G)
    margin, trees = _boost_drf_jit(binned, y, w, margin, keys, efb,
                                   p, bp, G, mesh or global_mesh())
    if rounds * G != n_trees:       # drop the last group's padding
        trees = jax.tree.map(lambda a: a[:n_trees], trees)
    return margin, trees


@functools.partial(jax.jit, static_argnums=(6, 7, 8, 9))
def _boost_multi_jit(binned, y, w, margin, keys, efb, p: TreeParams,
                     bp: BoostParams, K: int, mesh):
    out_specs = (P(ROWS), P(), P()) if bp.goss_b > 0 \
        else (P(ROWS), P())
    fn = jax.shard_map(
        functools.partial(_boost_shard_multi, p=p, bp=bp, K=K),
        mesh=mesh,
        in_specs=(P(ROWS), P(ROWS), P(ROWS), P(ROWS), P(), P()),
        out_specs=out_specs,
        check_vma=_resolve_impl(p.hist_impl) == "segment")
    return fn(binned, y, w, margin, keys, efb)


def boost_trees_multi(binned, y, w, margin, key, n_trees: int, K: int,
                      p: TreeParams, bp: BoostParams, mesh=None,
                      efb=None, goss_keys=None):
    """Fused multinomial boosting: n_trees rounds × K class trees in ONE
    compiled dispatch. Returns (margin [rows, K], trees [T, K, N]) —
    plus the GOSS overflow scalar when sampling is active (see
    boost_trees)."""
    keys = jax.random.split(key, n_trees)
    if bp.goss_b > 0.0:
        if goss_keys is None:
            goss_keys = goss_round_keys(key, n_trees)
        keys = (keys, goss_keys)
    return _boost_multi_jit(binned, y, w, margin, keys, efb, p, bp, K,
                            mesh or global_mesh())


@functools.partial(jax.jit, static_argnums=(6, 7, 8))
def _boost_jit(binned, y, w, margin, keys, efb, p: TreeParams,
               bp: BoostParams, mesh):
    out_specs = (P(ROWS), P(), P()) if bp.goss_b > 0 \
        else (P(ROWS), P())
    fn = jax.shard_map(
        functools.partial(_boost_shard, p=p, bp=bp),
        mesh=mesh,
        in_specs=(P(ROWS), P(ROWS), P(ROWS), P(ROWS), P(), P()),
        out_specs=out_specs,
        check_vma=_resolve_impl(p.hist_impl) == "segment")
    return fn(binned, y, w, margin, keys, efb)


def boost_trees(binned, y, w, margin, key, n_trees: int, p: TreeParams,
                bp: BoostParams, mesh=None, efb=None, goss_keys=None):
    """Fused boosting: n_trees rounds in ONE compiled dispatch.

    Returns (margin, trees) with trees a stacked Tree pytree [T, N] —
    plus a third ``overflow`` device scalar (total compaction-dropped
    row count, see goss_compact) when GOSS is active. ``goss_keys``
    ([n_trees] key rows of the path-invariant goss_round_keys stream)
    rides along as a second scanned key array when GOSS is active;
    with GOSS off the scanned operand is the plain key array,
    byte-identical to a build without the feature.
    """
    keys = jax.random.split(key, n_trees)
    if bp.goss_b > 0.0:
        if goss_keys is None:
            goss_keys = goss_round_keys(key, n_trees)
        keys = (keys, goss_keys)
    return _boost_jit(binned, y, w, margin, keys, efb, p, bp,
                      mesh or global_mesh())


@functools.partial(jax.jit, static_argnums=(7, 8))
def _grow_tree_jit(binned, g, h, w, col_mask, key, efb, p: TreeParams,
                   mesh) -> Tree:
    def body(binned, g, h, w, col_mask, key, efb=None):
        tree, _ = _grow_tree_shard(binned, g, h, w, col_mask, key, p,
                                   efb)
        return tree

    fn = jax.shard_map(
        body,
        mesh=mesh,
        in_specs=(P(ROWS), P(ROWS), P(ROWS), P(ROWS), P(), P(), P()),
        out_specs=P(),
        # pallas_call's interpret mode can't thread vma through its
        # internal slices (jax 0.9 limitation) — disable the check here
        check_vma=_resolve_impl(p.hist_impl) == "segment")
    return fn(binned, g, h, w, col_mask, key, efb)


def descend_tree(tree: Tree, binned, max_depth: int, n_bins: int,
                 efb=None):
    """Per-row resting heap node by iterative descent (jittable) — the
    ONE implementation of split semantics at scoring time (NA bin
    routing via na_left, `bin > split_bin` goes right). With ``efb``
    the binned matrix is in BUNDLED column space and per-row bins
    decode through the shared row_orig_bins LUT gather."""
    node = jnp.zeros(binned.shape[0], dtype=jnp.int32)
    for _ in range(max_depth):
        f = tree.split_feat[node]
        b = tree.split_bin[node]
        nl = tree.na_left[node]
        sp = tree.is_split[node]
        rowbin = row_orig_bins(binned, jnp.maximum(f, 0), efb)
        is_na = rowbin == n_bins - 1
        go_right = jnp.where(is_na, ~nl, rowbin > b)
        child = 2 * node + 1 + go_right.astype(jnp.int32)
        node = jnp.where(sp, child, node)
    return node


def predict_tree(tree: Tree, binned, max_depth: int, n_bins: int,
                 efb=None):
    """Per-row leaf value (descend + gather)."""
    return tree.value[descend_tree(tree, binned, max_depth, n_bins,
                                   efb)]


# ---------------------------------------------------------------------------
# Compiled serving fast path: flattened ensemble scorer
# ---------------------------------------------------------------------------
#
# The MOJO idea (h2o-genmodel SharedTreeMojoModel [U3]): scoring needs
# none of the training structures.  flatten_trees packs the dense heap
# into compact per-tree node arrays — only REACHABLE nodes, explicit
# left-child slots — and converts every split's bin id into a RAW
# FEATURE threshold, so serving never re-bins: with right-searchsorted
# binning, `bin(x) <= b  <=>  x < edges[b]`, hence descending right on
# `x >= thresh` reproduces the heap descent decision bitwise.  These
# arrays are the single flattening shared by the in-process scorer
# (flat_margin) and the MOJO artifact (mojo.py serializes them).

class FlatTrees(NamedTuple):
    """Compact serving ensemble: [T, M] node arrays, M = max reachable
    nodes per tree (BFS slot order, root = slot 0, right = left + 1)."""

    split_feat: jax.Array   # int32 [T, M]; -1 marks a leaf
    thresh: jax.Array       # f32   [T, M]; go RIGHT iff x >= thresh
    left: jax.Array         # int32 [T, M]; left-child slot
    na_left: jax.Array      # bool  [T, M]; NaN feature goes left
    value: jax.Array        # f32   [T, M]; leaf value (0 on splits)


def _reach_slots(isp: np.ndarray, max_depth: int
                 ) -> tuple[np.ndarray, np.ndarray, int]:
    """(reach [T, N] bool, slot [T, N] int, M) — the reachable-node set
    and its BFS slot assignment, shared by ``flatten_trees`` and
    ``flatten_cover`` so every per-node companion array (cover, for the
    TreeSHAP path tables) lands on exactly the slots the serving
    descent reads."""
    T, N = isp.shape
    reach = np.zeros((T, N), dtype=bool)
    reach[:, 0] = True
    for d in range(max_depth):
        lo, hi = 2 ** d - 1, 2 ** (d + 1) - 1
        if hi > N:
            break
        par = reach[:, lo:hi] & isp[:, lo:hi]
        idx = np.arange(lo, hi)
        reach[:, 2 * idx + 1] |= par
        reach[:, 2 * idx + 2] |= par
    # BFS slot order == heap-index order among reachable nodes (FIFO
    # BFS emits each level in parent order, i.e. ascending heap index)
    slot = reach.cumsum(axis=1) - 1                       # [T, N]
    M = int(reach.sum(axis=1).max())
    return reach, slot, M


def flatten_cover(trees: Tree, max_depth: int) -> np.ndarray:
    """[T, M] per-FLAT-NODE training weight mass (TreeSHAP's r_j),
    slot-aligned with ``flatten_trees``' arrays — the optional MOJO-v2
    ``flat_cover`` part and the input to the per-leaf path tables
    (models/tree/shap.py::build_shap_tables)."""
    isp = np.asarray(trees.is_split).astype(bool)
    cov = np.asarray(trees.cover).astype(np.float32)
    reach, slot, M = _reach_slots(isp, max_depth)
    out = np.zeros((isp.shape[0], M), dtype=np.float32)
    tt, hh = np.nonzero(reach)
    out[tt, slot[tt, hh]] = cov[tt, hh]
    return out


def flatten_trees(trees: Tree, edges_matrix: np.ndarray,
                  enum_mask: np.ndarray, max_depth: int) -> FlatTrees:
    """Host-side flattening of a stacked [T, N] heap Tree pytree.

    Threshold semantics (bitwise-equal to the binned heap descent,
    models/tree/binning.py `apply_bins`):
      numeric feature, split_bin b < n_edges: thresh = edges[f, b]
        (searchsorted(e, x, "right") > b  <=>  x >= e[b], +inf pads
        included — a padded edge sends every finite x left both ways);
      numeric, b == n_edges (cut past the last body bin): thresh = NaN
        — `x >= NaN` is False, so every non-NA row goes left, exactly
        like `bin <= b` when b is the max body bin;
      categorical (code IS the bin): thresh = b + 1, since
        `clip(code) > b  <=>  code >= b + 1` for integer codes.
    NA routing stays explicit via na_left (callers canonicalize
    negative enum codes to NaN before descending — apply_bins sends
    those to the NA bin)."""
    sf = np.asarray(trees.split_feat)
    sb = np.asarray(trees.split_bin)
    nl = np.asarray(trees.na_left).astype(bool)
    isp = np.asarray(trees.is_split).astype(bool)
    val = np.asarray(trees.value).astype(np.float32)
    edges_matrix = np.asarray(edges_matrix)
    enum_mask = np.asarray(enum_mask).astype(bool)
    T, N = sf.shape
    # reachable set + BFS slots (shared with flatten_cover)
    reach, slot, M = _reach_slots(isp, max_depth)
    out_feat = np.full((T, M), -1, dtype=np.int32)
    out_thresh = np.zeros((T, M), dtype=np.float32)
    out_left = np.zeros((T, M), dtype=np.int32)
    out_nal = np.zeros((T, M), dtype=bool)
    out_val = np.zeros((T, M), dtype=np.float32)
    tt, hh = np.nonzero(reach)
    ss = slot[tt, hh]
    sm = isp[tt, hh]                                      # split mask
    f = np.where(sm, sf[tt, hh], 0)
    b = sb[tt, hh]
    width = edges_matrix.shape[1]
    b_safe = np.minimum(b, width - 1)
    with np.errstate(invalid="ignore"):
        th = np.where(
            enum_mask[f], (b + 1).astype(np.float32),
            np.where(b < width, edges_matrix[f, b_safe].astype(np.float32),
                     np.float32(np.nan)))
    lh = np.minimum(2 * hh + 1, N - 1)                    # guarded gather
    out_feat[tt, ss] = np.where(sm, sf[tt, hh], -1)
    out_thresh[tt, ss] = np.where(sm, th, 0.0)
    out_left[tt, ss] = np.where(sm, slot[tt, lh], 0)
    out_nal[tt, ss] = nl[tt, hh] & sm
    out_val[tt, ss] = np.where(sm, 0.0, val[tt, hh])
    return FlatTrees(out_feat, out_thresh, out_left, out_nal, out_val)


@functools.partial(jax.jit, static_argnums=(3, 4))
def flat_margin(flat: FlatTrees, X, enum_mask, levels: int, K: int):
    """[K, rows] per-class leaf-value sums over an interleaved [T*K]
    flat ensemble, scored on RAW float features (no binning).

    Accumulation is an ordered scan over boosting rounds — the same
    per-class f32 addition order as the binned `_stack_predict` path,
    so predictions are bitwise-identical, not merely close."""
    # negative enum codes are NA (apply_bins sends them to the NA bin);
    # canonicalize to NaN once so the descent needs only isnan
    Xc = jnp.where(enum_mask[None, :] & (X < 0), jnp.float32(jnp.nan), X)
    TK = flat.split_feat.shape[0]
    per_round = jax.tree.map(
        lambda a: a.reshape((TK // K, K) + a.shape[1:]), flat)

    def descend(sf, th, lf, nl, val):
        node = jnp.zeros(Xc.shape[0], dtype=jnp.int32)
        for _ in range(levels):
            f = sf[node]
            x = jnp.take_along_axis(
                Xc, jnp.maximum(f, 0)[:, None], axis=1)[:, 0]
            go_r = jnp.where(jnp.isnan(x), ~nl[node], x >= th[node])
            node = jnp.where(f >= 0, lf[node] + go_r.astype(jnp.int32),
                             node)
        return val[node]

    def body(acc, tr):
        return acc + jax.vmap(descend)(*tr), None

    init = jnp.zeros((K, Xc.shape[0]), dtype=jnp.float32)
    total, _ = lax.scan(body, init, tuple(per_round))
    return total
