from .binning import BinSpec, apply_bins, fit_bins
from .core import Tree, TreeParams, grow_tree, predict_tree

__all__ = ["BinSpec", "apply_bins", "fit_bins", "Tree", "TreeParams",
           "grow_tree", "predict_tree"]
