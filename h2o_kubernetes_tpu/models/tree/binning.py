"""Feature binning for histogram tree learners.

The reference bins feature values per split via DHistogram min/max +
equal-width bins recomputed every level (hex/tree/DHistogram.java,
SURVEY.md §2b C10); the bundled XGBoost path uses global quantile
sketches (tree_method=hist). On TPU, global quantile binning wins: it is
done ONCE per frame, turns every feature into a uint8 code, and makes
the per-level hot loop a pure integer scatter-add — static shapes, no
data-dependent rebinning. This follows the GBDT-on-accelerator
literature (PAPERS.md: XGBoost GPU, Booster) rather than the Java design.

Layout: B total bins per feature. Bin B-1 is reserved for NA. Numeric
features use quantile edges (≤ B-2 finite bins); categorical features
use their codes directly; past B-1 levels, contiguous code ranges share
bins (the reference's DHistogram grouping past nbins_cats [U3]).

Wide sparse frames additionally go through Exclusive Feature Bundling
at bin time (models/tree/efb.py, docs/SCALING.md "Wide sparse
frames"): mutually exclusive sparse features pack into single uint8
bundle columns, reusing this module's per-column `_bin_block_jit`
apply so the dense [rows, F] matrix — float32 OR uint8 — never
materializes; the fused prologue below stays the unbundled fast path
(narrow frames never pay the planning pass).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np

NA_BIN_OFFSET = 1  # last bin is NA


@dataclass
class BinSpec:
    """Binning model: per-feature quantile edges.

    `edges_dev` (the fast path, round 3) keeps the [F, B-2] edge matrix
    ON DEVICE — `fit_bins` no longer round-trips the quantiles through
    the host before the first training dispatch (AutoML/CV pay that
    per fold-model). `edges` remains for models saved by older builds
    (and pickles to host numpy either way via the persist layer)."""

    names: list[str]
    edges: list[np.ndarray] | None   # host per-feature edges (legacy)
    is_enum: list[bool]
    n_bins: int = 256                # total incl. NA bin
    edges_dev: object = None         # [F, B-2] device matrix (+inf pad)

    @property
    def na_bin(self) -> int:
        return self.n_bins - 1

    def edges_matrix(self):
        """[F, B-2] edge matrix padded with +inf (for device binning)."""
        dev = getattr(self, "edges_dev", None)   # absent in old pickles
        if dev is not None:
            return dev
        if self.edges is None:
            raise ValueError(
                "BinSpec has neither edges_dev nor host edges — exactly "
                "one must be set (fit_bins sets edges_dev)")
        F = len(self.edges)
        width = self.n_bins - 2
        m = np.full((F, width), np.inf, dtype=np.float32)
        for i, e in enumerate(self.edges):
            m[i, : len(e)] = e
        return m


import functools

# above this many rows, quantile edges come from a fixed-key uniform
# row sample instead of a full-column sort. The reference's own hist
# path (XGBoost tree_method=hist; PAPERS.md GBDT-on-accelerator
# entries) bins from APPROXIMATE quantile sketches, not exact
# order statistics — a 64k sample gives ~256 draws per bin edge at
# n_bins=256, far inside the noise of where a split lands, while the
# per-column sort cost drops ~16x at 1M rows (fit_bins was ~200 ms of
# the 2.6 s bench train; sorts dominate it).
_QUANTILE_SAMPLE = 1 << 16


@functools.partial(jax.jit, static_argnums=(1,))
def _device_quantiles(Xn: jax.Array, n_q: int) -> jax.Array:
    """Per-column quantile edges on device: [n, Fn] → [Fn, n_q].

    Device-side (round 3: no host round-trip before the first training
    dispatch). Sampling is the CALLER's job: fit_bins feeds this the
    `_sampled_feature_matrix` gather (≤ _QUANTILE_SAMPLE rows), the
    one place the fixed-key sample draw lives."""
    qs = jnp.linspace(0.0, 1.0, n_q + 2)[1:-1]
    return jax.vmap(lambda c: jnp.nanquantile(c, qs))(Xn.T)


# per-column sample gather for the sketch path: fit_bins used to stack
# the FULL [n, Fn] f32 matrix just to sample 64k rows from it inside
# _device_quantiles — at 10M rows that transient alone is ~1.1 GB and
# was one of the ~5x-working-set peaks the chunked training path
# removes. Gathering the sample per column keeps the peak at O(sample).
_col_sample_jit = jax.jit(lambda c, idx: c[idx])


def _sampled_feature_matrix(num_cols: list) -> jax.Array:
    """Stack numeric columns into the [min(n, S), Fn] matrix
    _device_quantiles sees — bitwise the same rows the old full-matrix
    path sampled (same fixed key, same with-replacement index draw
    over the PADDED length), without ever materializing [n, Fn]. The
    ONLY sample-draw site — edges for a given shape stay
    deterministic."""
    n = num_cols[0].shape[0]
    if n > _QUANTILE_SAMPLE:
        idx = jax.random.randint(jax.random.key(0x51BB),
                                 (_QUANTILE_SAMPLE,), 0, n)
        num_cols = [_col_sample_jit(c, idx) for c in num_cols]
    return jnp.stack(num_cols, axis=1)


def _classify_features(frame, feature_names: list[str], n_bins: int
                       ) -> tuple[list[bool], list[int], list, np.ndarray]:
    """(is_enum, num_idx, num_cols, base_M) — the ONE feature-kind
    classification shared by fit_bins and the fused path.

    ``base_M`` is the host [F, B-2] +inf edge matrix with the
    high-cardinality range-grouping edges already filled: past B-1
    levels, contiguous CODE RANGES share bins — the same range grouping
    the reference's DHistogram applies to categoricals past nbins_cats
    ([U3] hex/tree/DHistogram). Expressed through the numeric
    searchsorted path (is_enum=False + synthetic edges between ranges);
    NA codes arrive as NaN from as_float and land in the NA bin as
    usual.  Enum rows never consult edges (apply_bins clips the code),
    so their rows stay at the +inf padding."""
    is_enum: list[bool] = []
    num_idx: list[int] = []
    num_cols = []
    base = np.full((len(feature_names), n_bins - 2), np.inf,
                   dtype=np.float32)
    for name in feature_names:
        v = frame.vec(name)
        if v.is_enum():
            card = v.cardinality()
            if card > n_bins - 1:
                # n_bins-3 edges split the code space [0, card) into
                # n_bins-2 near-equal ranges; the -0.5 puts each edge
                # BETWEEN codes (airlines Origin/Dest is ~300 levels)
                e = (np.arange(1, n_bins - 2, dtype=np.float32)
                     * card / (n_bins - 2)) - 0.5
                base[len(is_enum), : n_bins - 3] = e
                is_enum.append(False)
                continue
            is_enum.append(True)
            continue
        num_idx.append(len(is_enum))
        num_cols.append(v.as_float())
        is_enum.append(False)
    return is_enum, num_idx, num_cols, base


def fit_bins(frame, feature_names: list[str],
             n_bins: int = 256) -> BinSpec:
    """Compute quantile edges per numeric feature, fully device-side.

    The edge matrix never visits the host: NaN quantiles (all-NA
    columns) become +inf on device, and duplicate quantiles (heavily
    tied columns) are kept — duplicated edges only produce empty bins,
    which is semantically identical to the round-2 host-side
    `np.unique` dedup (bin ids are labels; MOJO scoring uses the SAME
    matrix, so artifacts stay consistent)."""
    if not 4 <= n_bins <= 256:
        raise ValueError(f"n_bins must be in [4, 256] (uint8 bin codes), "
                         f"got {n_bins}")
    is_enum, num_idx, num_cols, base = _classify_features(
        frame, feature_names, n_bins)
    M = jnp.asarray(base)
    if num_cols:
        Q = _device_quantiles(_sampled_feature_matrix(num_cols),
                              n_bins - 3)
        Q = jnp.where(jnp.isnan(Q), jnp.inf, Q.astype(jnp.float32))
        M = M.at[jnp.asarray(num_idx, dtype=jnp.int32),
                 : n_bins - 3].set(Q)
    return BinSpec(names=list(feature_names), edges=None,
                   is_enum=is_enum, n_bins=n_bins, edges_dev=M)


def apply_bins(X: jax.Array, edges_matrix: jax.Array, enum_mask: jax.Array,
               na_bin: int) -> jax.Array:
    """Bin a [rows, F] float matrix → [rows, F] uint8 codes (jittable).

    Numeric: searchsorted into that feature's quantile edges.
    Enum: the code IS the bin. NaN (or negative enum code) → NA bin.
    """

    def bin_feature(col, e, is_enum):
        num = jnp.searchsorted(e, col, side="right").astype(jnp.int32)
        cat = jnp.clip(col, 0, na_bin - 1).astype(jnp.int32)
        b = jnp.where(is_enum, cat, num)
        return jnp.where(jnp.isnan(col) | (col < 0) & is_enum, na_bin, b)

    binned = jax.vmap(bin_feature, in_axes=(1, 0, 0), out_axes=1)(
        X, edges_matrix, enum_mask)
    return binned.astype(jnp.uint8)


# module-level jitted form: a fresh jax.jit per train() call would
# retrace the binning program on every model fit (grid search / AutoML
# build many models per process)
apply_bins_jit = jax.jit(apply_bins, static_argnums=3)


# ---------------------------------------------------------------------------
# Binning straight from Frame columns (the chunked training data path)
# ---------------------------------------------------------------------------
#
# The round-5 tree train paths materialized the full [n, F] float32
# design matrix (data.X) only to bin it to uint8 — a transient ~5x the
# binned working set at 10M rows. `bin_frame` applies the bins
# column-BLOCK-wise directly from the Frame's device columns, so the
# largest float32 transient is one block; the uint8 matrix is the only
# full-width array that survives. Bitwise-identical to
# `apply_bins_jit(frame.to_matrix(names), ...)`: apply_bins is
# per-feature independent (vmap over columns), so blocking the column
# axis cannot change a single bin code.

import os as _os

# f32 bytes one column block may occupy while being binned
_BIN_BLOCK_BYTES = 256 << 20


def _bin_block_cols(padded_rows: int, F: int) -> int:
    env = _os.environ.get("H2O_TPU_BIN_BLOCK_COLS")
    if env:
        return max(1, min(int(env), F))
    return max(1, min(F, _BIN_BLOCK_BYTES // max(padded_rows * 4, 1)))


@functools.partial(jax.jit, static_argnums=(2,))
def _bin_block_jit(cols: tuple, edges_block, na_bin: int, enum_block):
    return apply_bins(jnp.stack(cols, axis=1), edges_block, enum_block,
                      na_bin)


_concat_blocks_jit = jax.jit(
    lambda *blocks: jnp.concatenate(blocks, axis=1))


def bin_frame(frame, bin_spec: BinSpec) -> jax.Array:
    """[padded, F] uint8 bin codes from Frame columns, block-wise.

    All device dispatches are jitted (an eager op over committed
    multi-device arrays is the XLA:CPU rendezvous flake pattern)."""
    names = bin_spec.names
    edges = jnp.asarray(bin_spec.edges_matrix())
    enum_mask = jnp.asarray(np.array(bin_spec.is_enum))
    padded = frame.vec(names[0]).padded_len
    F = len(names)
    block = _bin_block_cols(padded, F)
    out = []
    for lo in range(0, F, block):
        hi = min(lo + block, F)
        cols = tuple(frame.vec(n).as_float() for n in names[lo:hi])
        out.append(_bin_block_jit(cols, edges[lo:hi], bin_spec.na_bin,
                                  enum_mask[lo:hi]))
    return out[0] if len(out) == 1 else _concat_blocks_jit(*out)


# ---------------------------------------------------------------------------
# Fused first-dispatch binning (fit + apply in ONE program)
# ---------------------------------------------------------------------------
#
# The two-dispatch train prologue (fit_bins → Frame.binned) hides a
# blocking host round trip: Frame.binned fingerprints the EDGE BYTES
# for its cache key, so `np.asarray(edges)` must wait out the quantile
# computation and transfer it to the host before the bin apply can even
# dispatch — ~100 ms per train() on the tunneled chip (PROFILE.md
# "What's next" #2), paid once per AutoML candidate and per CV fold.
# `fused_fit_bins` folds both halves into the frame's first training
# dispatch: one jitted program computes the quantile edges AND the
# first column block's codes, nothing touches the host, and the binned
# cache is keyed by (names, n_bins, frame content version) — valid
# because the edges are a pure function of the frame's content (the
# version counter bumps on Frame.__setitem__).  Bit-parity with the
# two-dispatch path (same sample gather, same quantile program, same
# apply_bins) is asserted by tests/test_scheduler.py.


def fused_binning_enabled() -> bool:
    """H2O_TPU_FUSED_BINNING != "0" (the two-dispatch escape hatch)."""
    return _os.environ.get("H2O_TPU_FUSED_BINNING", "1") != "0"


@functools.partial(jax.jit, static_argnums=(5,))
def _fused_fit_bin_jit(base_M, num_idx, sample, cols: tuple,
                       enum_block, na_bin: int):
    """ONE dispatch: quantile edges from the sampled matrix + the bin
    codes of the first column block.  ``sample=None`` (no numeric
    features) skips the quantile half at trace time."""
    M = base_M
    if sample is not None:
        n_q = M.shape[1] - 1                      # n_bins - 3
        qs = jnp.linspace(0.0, 1.0, n_q + 2)[1:-1]
        Q = jax.vmap(lambda c: jnp.nanquantile(c, qs))(sample.T)
        Q = jnp.where(jnp.isnan(Q), jnp.inf, Q.astype(jnp.float32))
        M = M.at[num_idx, : n_q].set(Q)
    binned = apply_bins(jnp.stack(cols, axis=1), M[: len(cols)],
                        enum_block, na_bin)
    return M, binned


def fused_fit_bins(frame, feature_names: list[str],
                   n_bins: int = 256) -> tuple[BinSpec, jax.Array]:
    """(BinSpec, [padded, F] uint8 codes) in one fused first dispatch.

    Cache: hits the owning frame's ``_binned_cache`` under a
    content-version fit key WITHOUT any device sync, so a second model
    on the same frame/nbins (every AutoML plan entry after the first)
    pays neither the quantile fit nor the bin apply.  The classic
    fingerprint path (Frame.binned) remains for specs that did not come
    from fitting THIS frame (checkpoint continuation)."""
    if not 4 <= n_bins <= 256:
        raise ValueError(f"n_bins must be in [4, 256] (uint8 bin codes), "
                         f"got {n_bins}")
    cache = frame.__dict__.setdefault("_binned_cache", {})
    key = ("fitbin", tuple(feature_names), n_bins,
           frame.__dict__.get("_version", 0))
    hit = cache.pop(key, None)
    if hit is not None:
        cache[key] = hit              # true LRU: a hit refreshes recency
        return hit
    is_enum, num_idx, num_cols, base = _classify_features(
        frame, feature_names, n_bins)
    F = len(feature_names)
    padded = frame.vec(feature_names[0]).padded_len
    sample = _sampled_feature_matrix(num_cols) if num_cols else None
    block = _bin_block_cols(padded, F)
    enum_arr = np.array(is_enum)
    cols0 = tuple(frame.vec(nm).as_float()
                  for nm in feature_names[:block])
    M, first = _fused_fit_bin_jit(
        jnp.asarray(base), jnp.asarray(num_idx, dtype=jnp.int32),
        sample, cols0, jnp.asarray(enum_arr[:block]), n_bins - 1)
    outs = [first]
    for lo in range(block, F, block):
        hi = min(lo + block, F)
        cols = tuple(frame.vec(nm).as_float()
                     for nm in feature_names[lo:hi])
        outs.append(_bin_block_jit(cols, M[lo:hi], n_bins - 1,
                                   jnp.asarray(enum_arr[lo:hi])))
    binned = outs[0] if len(outs) == 1 else _concat_blocks_jit(*outs)
    spec = BinSpec(names=list(feature_names), edges=None,
                   is_enum=is_enum, n_bins=n_bins, edges_dev=M)
    while len(cache) >= 2:                  # tiny LRU: drop oldest
        cache.pop(next(iter(cache)))
    cache[key] = (spec, binned)
    return spec, binned


def bin_frame_host_chunks(frame, bin_spec: BinSpec,
                          chunk_rows: int) -> list[np.ndarray]:
    """Row-chunked HOST-resident uint8 binned matrix (out-of-core mode).

    Bins one column at a time on device (peak device transient: one f32
    column + one uint8 column), fetches it, and scatters the bytes into
    per-chunk [chunk_rows, F] buffers. Rows past the padded length in
    the final chunk get the NA bin and are dead (w=0) downstream.
    Chunk c's rows are EXACTLY rows [c*chunk_rows, (c+1)*chunk_rows) of
    `bin_frame`'s output — the chunk-parity tests rely on it."""
    names = bin_spec.names
    edges = jnp.asarray(bin_spec.edges_matrix())
    enum_mask = np.array(bin_spec.is_enum)
    padded = frame.vec(names[0]).padded_len
    F = len(names)
    n_chunks = -(-padded // chunk_rows)
    bufs = [np.full((chunk_rows, F), bin_spec.na_bin, dtype=np.uint8)
            for _ in range(n_chunks)]
    for j, name in enumerate(names):
        col = frame.vec(name).as_float()
        b = np.asarray(_bin_block_jit(
            (col,), edges[j: j + 1], bin_spec.na_bin,
            jnp.asarray(enum_mask[j: j + 1])))[:, 0]
        for c in range(n_chunks):
            lo = c * chunk_rows
            hi = min(lo + chunk_rows, padded)
            bufs[c][: hi - lo, j] = b[lo:hi]
    return bufs
