"""Out-of-core chunk-streamed boosting (the 10M-row training path).

When the uint8 binned matrix itself exceeds the device-memory headroom
left by `H2O_TPU_HIST_BYTES_BUDGET` (models/gbm.py derives the
trigger), training switches from the fused all-rows-resident
`core.boost_trees` scan to this driver: the binned matrix lives as
HOST-resident row chunks and is streamed to device per tree level with
double-buffered `device_put` (the upload of chunk c+1 overlaps the
histogram build of chunk c), exactly the compressed-stream design of
the GBDT-on-accelerator literature (PAPERS.md: *Out-of-Core GPU
Gradient Boosting*, arXiv:2005.09148; *XGBoost: Scalable GPU
Accelerated Learning*, arXiv:1806.11248 §"out-of-core").

Only the per-row COLUMNS stay device-resident full-length-equivalent —
y, weights and the boosting margin, each chunked alongside the binned
chunks (12 B/row total) — so the device working set is
O(chunk · F + rows · 12 B + level histograms).

Numerics: per-level histograms are accumulated over chunks in FIXED
chunk order with f32 adds, and every split/leaf computation reuses the
shared `core._find_splits` / `core._leaf_value` code paths — so the
streamed (host-chunk) and resident (device-chunk) modes are
bitwise-identical (tests/test_chunked_path.py asserts it; the
`H2O_TPU_OOC_RESIDENT=1` debug mode exists for exactly that test).
Versus the monolithic fused path the only difference is the f32
reassociation at chunk boundaries: sums that are exact (e.g. the
first gaussian round on a ±0.5-gradient response) are bitwise equal,
general multi-tree models agree to float tolerance.

Scope: pointwise single-output boosting (GBM/XGBoost gaussian,
bernoulli, poisson, gamma, tweedie, laplace, quantile) at
sample_rate=1 with no scoring cadence. GOSS gradient-based sampling
(H2O_TPU_GOSS, docs/SCALING.md "Gradient-based sampling") IS
stream-eligible: its per-round selection is a pure function of
exactly-associative global stats plus a per-row (key, global row id)
hash, so the chunk grid picks the same rows the fused in-HBM path
picks at the same seed. Multinomial (K margins), DRF
voting, huber (needs a global residual quantile per round),
checkpoint continuation, score_every (the stream scores once at the
end — a requested cadence must not be dropped silently), row/column
subsampling (the streamed key schedule differs from the fused
core's, so sampled models would depend on which path engaged or on
the chunk-size knob) and multi-host meshes stay on the in-HBM path —
models/gbm._ooc_chunk_rows is the single gate; docs/SCALING.md
documents the matrix.
"""

from __future__ import annotations

import functools
import os
from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from ...ops.histogram import build_histogram as _build_histogram_op
from ...ops.histogram import expand_unit_hess as _expand_unit_hess
from ...ops.histogram import resolve_impl as _resolve_impl
from ...runtime import telemetry
from ...runtime.mesh import ROWS, global_mesh
from .core import (BoostParams, Tree, TreeParams, _boost_grad_hess,
                   _find_splits, _leaf_value, descend_tree,
                   goss_cap_rows, goss_compact, goss_local_counts,
                   goss_rank_stat, goss_round_keys, goss_row_factor,
                   goss_threshold, row_orig_bins)


# ---------------------------------------------------------------------------
# Chunk container
# ---------------------------------------------------------------------------

@dataclass
class BinnedChunks:
    """Row-chunked training set: binned uint8 chunks (host numpy in
    streamed mode, device arrays in resident mode) plus aligned
    per-chunk device columns. All chunks share one shape so every
    jitted per-chunk program compiles once per tree level."""

    binned: list                    # [chunk_rows, F] uint8 (np or jax)
    y: list                         # [chunk_rows] f32 device
    w: list                         # [chunk_rows] f32 device
    margin: list                    # [chunk_rows] f32 device
    chunk_rows: int
    padded_rows: int                # logical padded length (pre-chunking)
    streamed: bool                  # True: host chunks, device_put per use

    @property
    def n_chunks(self) -> int:
        return len(self.binned)

    @property
    def n_features(self) -> int:
        return self.binned[0].shape[1]


def chunk_rows_for(padded_rows: int, n_features: int, budget: float,
                   hist_bytes: int, mesh=None) -> int:
    """Rows per chunk: a quarter of the histogram-budget headroom (two
    staging buffers + the device copy in flight + slack), floored at
    1 MiB of uint8 codes, aligned to the mesh row axis, capped at the
    table. ``H2O_TPU_OOC_CHUNK_ROWS`` overrides (tests force tiny
    chunks with it)."""
    mesh = mesh or global_mesh()
    shards = mesh.shape[ROWS]
    env = os.environ.get("H2O_TPU_OOC_CHUNK_ROWS")
    if env:
        rows = int(env)
    else:
        headroom = max(budget - hist_bytes, 1 << 20)
        rows = int(max(headroom // 4, 1 << 20) // max(n_features, 1))
    rows = max(shards, (rows // shards) * shards)
    return min(rows, ((padded_rows + shards - 1) // shards) * shards)


def make_chunks(frame, bin_spec, y, w, margin, chunk_rows: int,
                mesh=None, plan=None) -> BinnedChunks:
    """Build the chunked training set from a Frame + resolved columns.

    ``y``/``w``/``margin`` are the full [padded] device columns from
    resolve_xy/_init_margin; they are fetched once and re-sharded per
    chunk. Binned chunks come from `binning.bin_frame_host_chunks`
    (one column on device at a time — the full f32 matrix never
    exists), or from the EFB ``plan``'s bundled host matrix when
    bundling engaged (models/tree/efb.py — the chunks then carry
    BUNDLED slot codes at width Fb). ``H2O_TPU_OOC_RESIDENT=1`` keeps
    the binned chunks device-resident (the bitwise
    streamed-vs-resident test harness)."""
    from .binning import bin_frame_host_chunks

    mesh = mesh or global_mesh()
    sharding = NamedSharding(mesh, P(ROWS))
    if plan is not None:
        from .efb import chunk_plan_host

        bufs = chunk_plan_host(plan, chunk_rows)
    else:
        bufs = bin_frame_host_chunks(frame, bin_spec, chunk_rows)
    n_chunks = len(bufs)
    total = n_chunks * chunk_rows

    def _cols(full, fill):
        a = np.asarray(full)
        out = np.full(total, fill, dtype=np.float32)
        out[: a.shape[0]] = a
        return [jax.device_put(out[c * chunk_rows:(c + 1) * chunk_rows],
                               sharding) for c in range(n_chunks)]

    streamed = os.environ.get("H2O_TPU_OOC_RESIDENT", "0") != "1"
    if not streamed:
        bufs = [jax.device_put(b, sharding) for b in bufs]
    return BinnedChunks(binned=bufs, y=_cols(y, 0.0), w=_cols(w, 0.0),
                        margin=_cols(margin, 0.0),
                        chunk_rows=chunk_rows,
                        padded_rows=np.asarray(y).shape[0],
                        streamed=streamed)


def _stream(chunks: BinnedChunks, mesh):
    """Yield device binned chunks with one-ahead prefetch: the
    (asynchronous) ``device_put`` of chunk c+1 is issued before chunk c
    is consumed, double-buffering host→device transfer against the
    histogram build. Resident chunks pass through untouched.

    Each streamed pass reports its upload/compute split to the fleet
    telemetry registry (``ooc_stream_account``): time blocked inside
    ``device_put`` vs time the CONSUMER held the generator suspended —
    the overlap-efficiency gauge (compute/(compute+upload) → 1.0 when
    every upload hides under the histogram build) the SCALING docs
    previously estimated by hand. The timestamps are host clock reads
    around calls already on this path — no extra device syncs."""
    if not chunks.streamed:
        yield from chunks.binned
        return
    import time

    sharding = NamedSharding(mesh, P(ROWS))
    upload_s = compute_s = 0.0
    t0 = time.monotonic()
    t = t0
    nxt = jax.device_put(chunks.binned[0], sharding)
    upload_s += time.monotonic() - t
    for c in range(chunks.n_chunks):
        cur = nxt
        if c + 1 < chunks.n_chunks:
            t = time.monotonic()
            nxt = jax.device_put(chunks.binned[c + 1], sharding)
            upload_s += time.monotonic() - t
        t = time.monotonic()
        yield cur
        compute_s += time.monotonic() - t
    telemetry.ooc_stream_account(upload_s, compute_s,
                                 time.monotonic() - t0)


# ---------------------------------------------------------------------------
# Per-chunk jitted programs
# ---------------------------------------------------------------------------

def _shard_hist(binned, rel, g, h, w, n_nodes, p: TreeParams, mesh):
    def body(b, r, g_, h_, w_):
        hh = _build_histogram_op(b, r, g_, h_, w_, n_nodes, p.n_bins,
                                 impl=p.hist_impl, unit_hess=p.unit_hess)
        return lax.psum(hh, ROWS)

    fn = jax.shard_map(
        body, mesh=mesh, in_specs=(P(ROWS),) * 5, out_specs=P(),
        check_vma=_resolve_impl(p.hist_impl) == "segment")
    return fn(binned, rel, g, h, w)


@functools.partial(jax.jit, static_argnums=(3,))
def _chunk_grads_jit(margin, y, w, bp: BoostParams):
    """Per-chunk (g, h) for one boosting round. No row sampling here:
    sample_rate < 1 is OOC-ineligible (a per-chunk keep-draw would tie
    the model to the chunk grid — models/gbm._ooc_chunk_rows)."""
    return _boost_grad_hess(bp, margin, y, w)


@functools.partial(jax.jit, static_argnums=(5, 6, 7))
def _chunk_root_hist_jit(binned, g, h, w, rel0, n_bins_full: bool,
                         p: TreeParams, mesh):
    """Level-0 histogram for one chunk: full bins (tree root), or a
    single zero bin (the depth-0 stump's root totals)."""
    if n_bins_full:
        return _shard_hist(binned, rel0, g, h, w, 1, p, mesh)
    zero_bin = jnp.zeros((binned.shape[0], 1), dtype=binned.dtype)
    p1 = p._replace(n_bins=1)
    return _shard_hist(zero_bin, rel0, g, h, w, 1, p1, mesh)


def _descend(binned, rel, absn, feat, bin_, nal, can, d: int,
             n_bins: int, efb=None):
    """Move every row from level ``d`` to ``d+1`` given level-``d``
    splits — the exact row-walk of core._grow_tree_shard (bundle slots
    decoded through the shared core.row_orig_bins LUT gather)."""
    live = rel >= 0
    safe_rel = jnp.where(live, rel, 0)
    f = feat[safe_rel]
    b = bin_[safe_rel]
    nl = nal[safe_rel]
    rowbin = row_orig_bins(binned, f, efb)
    is_na = rowbin == n_bins - 1
    go_right = jnp.where(is_na, ~nl, rowbin > b)
    child = 2 * rel + go_right.astype(jnp.int32)
    moved = live & can[safe_rel]
    rel = jnp.where(moved, child, -1)
    absn = jnp.where(moved, (2 ** (d + 1) - 1) + child, absn)
    return rel, absn


@functools.partial(jax.jit, static_argnums=(10, 11, 12))
def _chunk_desc_hist_jit(binned, rel, absn, g, h, w, feat, bin_, nal,
                         can, d: int, p: TreeParams, mesh, efb=None):
    """ONE streamed pass of a chunk for level d+1: descend the rows
    from level d's splits, then build the LEFT-child histogram (sibling
    subtraction happens after cross-chunk accumulation). Fusing the
    descent into the histogram pass is what keeps the stream at one
    read of the binned chunk per level."""
    rel, absn = _descend(binned, rel, absn, feat, bin_, nal, can, d,
                         p.n_bins, efb)
    left_rel = jnp.where((rel >= 0) & (rel % 2 == 0), rel // 2, -1)
    hist_l = _shard_hist(binned, left_rel, g, h, w, 2 ** d, p, mesh)
    return rel, absn, hist_l


_add_jit = jax.jit(jnp.add)
_expand_unit_hess_jit = jax.jit(_expand_unit_hess)


@functools.partial(jax.jit, static_argnums=(4, 5))
def _level_logic_jit(hist_l2, hist_prev, can_prev, col_key,
                     p: TreeParams, d: int, efb=None):
    """Sibling subtraction + split finding for level d >= 1 — the same
    math core._grow_tree_shard runs inside the fused scan."""
    if p.unit_hess:
        hist_l2 = _expand_unit_hess(hist_l2)
    parent = jnp.where(can_prev[:, None, None, None], hist_prev, 0.0)
    hist_l = jnp.where(can_prev[:, None, None, None], hist_l2, 0.0)
    hist_r = parent - hist_l
    n_nodes = 2 ** d
    F = hist_l.shape[1]
    hist = jnp.stack([hist_l, hist_r], axis=1).reshape(
        n_nodes, F, p.n_bins, 3)
    return hist, _splits_with_mask(hist, col_key, p, d, efb)


@functools.partial(jax.jit, static_argnums=(2, 3))
def _root_logic_jit(hist, col_key, p: TreeParams, d: int, efb=None):
    if p.unit_hess:
        hist = _expand_unit_hess(hist)
    return hist, _splits_with_mask(hist, col_key, p, d, efb)


def _splits_with_mask(hist, col_key, p: TreeParams, d: int, efb=None):
    n_nodes = hist.shape[0]
    col_mask, key = col_key
    F = col_mask.shape[0]        # ORIGINAL feature count under EFB
    feat_ok = jnp.broadcast_to(col_mask[None, :], (n_nodes, F))
    if p.mtries > 0 and p.mtries < F:
        # same per-node draw as core (key folded with the level)
        r = jax.random.uniform(jax.random.fold_in(key, d), (n_nodes, F))
        r = jnp.where(feat_ok, r, jnp.inf)
        kth = jnp.sort(r, axis=1)[:, p.mtries - 1: p.mtries]
        feat_ok = feat_ok & (r <= kth)
    return _find_splits(hist, p, feat_ok, efb)


@functools.partial(jax.jit, static_argnums=(3,))
def _final_leaves_jit(can_prev, left_prev, right_prev, p: TreeParams):
    """Final-level leaf values/covers from the previous level's chosen
    split side stats — zero extra row passes, like the fused core."""
    n_nodes = can_prev.shape[0] * 2
    tot = jnp.where(can_prev[:, None, None],
                    jnp.stack([left_prev, right_prev], axis=1),
                    0.0).reshape(n_nodes, 3)
    return _leaf_value(tot[:, 0], tot[:, 1], p), tot[:, 2]


@functools.partial(jax.jit, static_argnums=(9, 10))
def _chunk_finish_jit(binned, rel, absn, margin, feat, bin_, nal, can,
                      value_scaled, d: int, p: TreeParams, efb=None):
    """Last streamed pass of a tree: descend the final level's rows and
    fold the (already learn-rate-scaled) leaf values into the margin."""
    rel, absn = _descend(binned, rel, absn, feat, bin_, nal, can, d,
                         p.n_bins, efb)
    margin = margin + value_scaled[absn]
    return rel, absn, margin


# ---------------------------------------------------------------------------
# GOSS per-chunk programs (models/tree/core.py "GOSS" — the selection
# rule is a pure function of exactly-associative GLOBAL stats plus a
# per-row hash, so the chunk grid and the in-HBM mesh layout pick the
# SAME rows at one seed; docs/SCALING.md "Gradient-based sampling")
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnums=(2,))
def _chunk_goss_max_jit(g, w, mesh):
    """Replicated per-chunk max |g| over live rows (pmax over shards;
    the cross-chunk max is exact whatever the chunk order)."""
    def body(g_, w_):
        return lax.pmax(jnp.max(goss_rank_stat(g_, w_)), ROWS)

    fn = jax.shard_map(body, mesh=mesh, in_specs=(P(ROWS), P(ROWS)),
                       out_specs=P())
    return fn(g, w)


@functools.partial(jax.jit, static_argnums=(3,))
def _chunk_goss_counts_jit(g, w, m, mesh):
    """Replicated per-chunk int32 |g|-bin counts + live count (int
    sums are exactly associative — cross-chunk adds are order-free)."""
    def body(g_, w_, m_):
        absg = goss_rank_stat(g_, w_)
        counts, nlive = goss_local_counts(absg, w_ > 0, m_)
        return lax.psum(counts, ROWS), lax.psum(nlive, ROWS)

    fn = jax.shard_map(body, mesh=mesh,
                       in_specs=(P(ROWS), P(ROWS), P()),
                       out_specs=(P(), P()))
    return fn(g, w, m)


@functools.partial(jax.jit, static_argnums=(2,))
def _goss_threshold_jit(counts, total, a: float):
    return goss_threshold(counts, total, a)


@functools.partial(jax.jit, static_argnums=(9, 10, 11))
def _chunk_goss_compact_jit(binned, g, h, w, m, T, frac, kg, row0,
                            cap_local: int, bp: BoostParams, mesh):
    """ONE streamed read of a binned chunk per round: per-row GOSS
    factor from the global stats + the (round key, global row id)
    hash, then per-shard static-cap compaction — the compacted buffers
    stay DEVICE-resident for every level of this round's tree, so the
    stream pays one upload per ROUND instead of one per level."""
    def body(bc, g_, h_, w_, m_, T_, f_, kg_, r0_):
        rows_local = w_.shape[0]
        row_ids = (r0_ + lax.axis_index(ROWS) * rows_local +
                   jnp.arange(rows_local, dtype=jnp.int32))
        absg = goss_rank_stat(g_, w_)
        factor = goss_row_factor(absg, w_ > 0, m_, T_, f_, kg_,
                                 row_ids, bp.goss_a, bp.goss_b)
        bC, gC, hC, wC, dropped = goss_compact(bc, g_, h_,
                                               w_ * factor, cap_local)
        return bC, gC, hC, wC, lax.psum(dropped, ROWS)

    fn = jax.shard_map(body, mesh=mesh,
                       in_specs=(P(ROWS),) * 4 + (P(),) * 5,
                       out_specs=(P(ROWS),) * 4 + (P(),))
    return fn(binned, g, h, w, m, T, frac, kg, row0)


@functools.partial(jax.jit, static_argnums=(3,))
def _chunk_goss_margin_jit(binned, margin, tree: Tree, p: TreeParams,
                           efb=None):
    """Full re-descent margin update for one chunk: the sampled grow
    only walked the compacted rows, so every row re-descends the grown
    tree (shared core.descend_tree — split semantics cannot drift).
    tree.value is already learn-rate-scaled."""
    node = descend_tree(tree, binned, p.max_depth, p.n_bins, efb)
    return margin + tree.value[node]


_max_jit = jax.jit(jnp.maximum)


# ---------------------------------------------------------------------------
# Driver
# ---------------------------------------------------------------------------

def _grow_tree_chunked(chunks: BinnedChunks, gs, hs, wts, col_key,
                       p: TreeParams, mesh, efb=None):
    """Grow one tree over the chunk stream. Returns (Tree of host
    arrays, per-chunk final abs leaf nodes) — margin update is the
    caller's (it owns the learn-rate scaling)."""
    C = chunks.n_chunks
    N = 2 ** (p.max_depth + 1) - 1
    sf = np.full(N, -1, dtype=np.int32)
    sb = np.zeros(N, dtype=np.int32)
    nl = np.zeros(N, dtype=bool)
    isp = np.zeros(N, dtype=bool)
    val = np.zeros(N, dtype=np.float32)
    gn = np.zeros(N, dtype=np.float32)
    cov = np.zeros(N, dtype=np.float32)

    zeros = jnp.zeros(chunks.chunk_rows, dtype=jnp.int32)
    rel = [zeros] * C
    absn = [zeros] * C
    hist_prev = can_prev = left_prev = right_prev = None
    feat_d = bin_d = nal_d = can_d = None

    for d in range(p.max_depth + 1):
        n_nodes = 2 ** d
        off = n_nodes - 1
        if d == p.max_depth:
            if d == 0:
                # depth-0 stump: root totals via a single-bin pass
                tot = None
                for ci, bc in enumerate(_stream(chunks, mesh)):
                    t = _chunk_root_hist_jit(bc, gs[ci], hs[ci],
                                             wts[ci], rel[ci], False,
                                             p, mesh)
                    tot = t if tot is None else _add_jit(tot, t)
                if p.unit_hess:
                    # jitted: an eager op over the committed
                    # replicated total is the XLA:CPU rendezvous flake
                    tot = _expand_unit_hess_jit(tot)
                t3 = np.asarray(tot)[:, 0, 0, :]
                vals_np = np.asarray(
                    _leaf_value(jnp.asarray(t3[:, 0]),
                                jnp.asarray(t3[:, 1]), p))
                covs_np = t3[:, 2]
            else:
                vals_l, covs_l = _final_leaves_jit(
                    can_prev, left_prev, right_prev, p)
                vals_np, covs_np = np.asarray(vals_l), np.asarray(covs_l)
            idx = off + np.arange(n_nodes)
            val[idx] = vals_np
            cov[idx] = covs_np
            break
        # phase spans (h2o_train_phase_seconds + /3/Timeline): the
        # per-level chunk-accumulated histogram build vs the split
        # search — the level-by-level attribution behind any ooc
        # wall-clock claim (host-observable on this path because each
        # level is a host loop over chunk programs)
        if d == 0:
            hist2 = None
            with telemetry.phase_span("level_hist", depth=d):
                for ci, bc in enumerate(_stream(chunks, mesh)):
                    hc = _chunk_root_hist_jit(bc, gs[ci], hs[ci],
                                              wts[ci], rel[ci], True,
                                              p, mesh)
                    hist2 = hc if hist2 is None \
                        else _add_jit(hist2, hc)
            with telemetry.phase_span("split_find", depth=d):
                hist, found = _root_logic_jit(hist2, col_key, p, d,
                                              efb)
        else:
            hist_l2 = None
            with telemetry.phase_span("level_hist", depth=d):
                for ci, bc in enumerate(_stream(chunks, mesh)):
                    rel[ci], absn[ci], hc = _chunk_desc_hist_jit(
                        bc, rel[ci], absn[ci], gs[ci], hs[ci],
                        wts[ci], feat_d, bin_d, nal_d, can_d, d - 1,
                        p, mesh, efb)
                    hist_l2 = hc if hist_l2 is None \
                        else _add_jit(hist_l2, hc)
            with telemetry.phase_span("split_find", depth=d):
                hist, found = _level_logic_jit(hist_l2, hist_prev,
                                               can_prev, col_key, p,
                                               d, efb)
        (feat_d, bin_d, nal_d, can_d, val_d, gain_d, cov_d,
         left_prev, right_prev) = found
        idx = off + np.arange(n_nodes)
        can_np = np.asarray(can_d)
        sf[idx] = np.where(can_np, np.asarray(feat_d), -1)
        sb[idx] = np.asarray(bin_d)
        nl[idx] = np.asarray(nal_d)
        isp[idx] = can_np
        val[idx] = np.asarray(val_d)
        gn[idx] = np.where(can_np, np.asarray(gain_d), 0.0)
        cov[idx] = np.asarray(cov_d)
        hist_prev, can_prev = hist, can_d

    tree = Tree(sf, sb, nl, isp, val, gn, cov)
    return tree, (feat_d, bin_d, nal_d, can_d), rel, absn


def _goss_round_chunked(chunks: BinnedChunks, gs, hs, wts, kg, col_key,
                        cap_local: int, p: TreeParams, bp: BoostParams,
                        mesh, efb=None):
    """One GOSS boosting round over the chunk stream: global ranking
    stats (device scalars, combined lazily — the host never blocks),
    one compaction stream pass, grow over the device-resident
    compacted chunks, one margin-update stream pass. Returns the
    learn-rate-scaled host Tree + the round's compaction-overflow
    device scalar (goss_compact)."""
    m = None
    for ci in range(chunks.n_chunks):
        mc = _chunk_goss_max_jit(gs[ci], wts[ci], mesh)
        m = mc if m is None else _max_jit(m, mc)
    counts = total = None
    for ci in range(chunks.n_chunks):
        cc, nc = _chunk_goss_counts_jit(gs[ci], wts[ci], m, mesh)
        counts = cc if counts is None else _add_jit(counts, cc)
        total = nc if total is None else _add_jit(total, nc)
    T, frac = _goss_threshold_jit(counts, total, bp.goss_a)
    bufsC, gsC, hsC, wtsC = [], [], [], []
    dropped = None
    for ci, bc in enumerate(_stream(chunks, mesh)):
        bC, gC, hC, wC, dc = _chunk_goss_compact_jit(
            bc, gs[ci], hs[ci], wts[ci], m, T, frac, kg,
            ci * chunks.chunk_rows, cap_local, bp, mesh)
        bufsC.append(bC)
        gsC.append(gC)
        hsC.append(hC)
        wtsC.append(wC)
        dropped = dc if dropped is None else _add_jit(dropped, dc)
    shards = mesh.shape[ROWS]
    comp = BinnedChunks(binned=bufsC, y=[], w=[], margin=[],
                        chunk_rows=cap_local * shards,
                        padded_rows=chunks.padded_rows,
                        streamed=False)
    tree, _, _, _ = _grow_tree_chunked(comp, gsC, hsC, wtsC, col_key,
                                       p, mesh, efb)
    # scale leaves once (f32, same IEEE multiply as the fused core)
    scaled = (tree.value
              * np.float32(bp.learn_rate)).astype(np.float32)
    tree = tree._replace(value=scaled)
    tree_dev = Tree(*(jnp.asarray(x) for x in tree))
    for ci, bc in enumerate(_stream(chunks, mesh)):
        chunks.margin[ci] = _chunk_goss_margin_jit(
            bc, chunks.margin[ci], tree_dev, p, efb)
    return tree, dropped


def boost_trees_chunked(chunks: BinnedChunks, key, n_trees: int,
                        p: TreeParams, bp: BoostParams, mesh=None,
                        efb=None, goss_keys=None):
    """n_trees boosting rounds over the chunk stream.

    Returns (margin [padded_rows] numpy, [Tree] with host arrays,
    goss_dropped int — total GOSS compaction-overflow contributions,
    0 when sampling is off; models/gbm surfaces it as a warning) —
    the margin is reassembled once at the end for final metrics; it
    never leaves the device during boosting (each chunk's slice stays
    a sharded device column).

    GOSS (bp.goss_b > 0) composes with the stream WITHOUT a host
    sync: per round, the global |g| ranking stats combine across
    chunks as device scalars (max + int32 adds — exactly associative,
    so they equal the in-HBM psum bit for bit), one streamed pass
    compacts each chunk's sampled rows into a device-resident buffer,
    every tree level then builds from the compacted buffers (no
    per-level streaming at 1/(a+b)-ish of the rows), and a final
    streamed pass re-descends the full chunks for the margin update —
    2 uploads per round instead of max_depth+2."""
    assert not bp.drf_mode, "OOC mode is pointwise boosting only"
    assert bp.sample_rate >= 1.0 and \
        bp.col_sample_rate_per_tree >= 1.0 and p.mtries <= 0, \
        "OOC requires sample_rate=col_sample_rate_per_tree=1, no " \
        "mtries (gated in models/gbm — streamed keys differ from " \
        "the fused core's)"
    mesh = mesh or global_mesh()
    # col_mask lives in ORIGINAL feature space (chunks.n_features is
    # the BUNDLED width when EFB engaged)
    F = efb.feat_col.shape[0] if efb is not None else chunks.n_features
    trees: list[Tree] = []
    # every stochastic option (sample_rate, col_sample_rate_per_tree,
    # mtries) is gated OFF this path in models/gbm._ooc_chunk_rows —
    # the key below is plumbed only for _splits_with_mask's signature
    col_mask = jnp.ones(F, dtype=bool)
    goss = bp.goss_b > 0.0
    goss_dropped = None
    if goss:
        if goss_keys is None:       # same fallback as core.boost_trees
            goss_keys = goss_round_keys(key, n_trees)
        shards = mesh.shape[ROWS]
        cap_local = goss_cap_rows(chunks.chunk_rows // shards,
                                  bp.goss_a, bp.goss_b)
    for t in range(n_trees):
        key, k_tree = jax.random.split(key)
        gs, hs, wts = [], [], []
        for ci in range(chunks.n_chunks):
            g, h = _chunk_grads_jit(
                chunks.margin[ci], chunks.y[ci], chunks.w[ci], bp)
            gs.append(g)
            hs.append(h)
            wts.append(chunks.w[ci])
        if goss:
            tree, dc = _goss_round_chunked(chunks, gs, hs, wts,
                                           goss_keys[t],
                                           (col_mask, k_tree),
                                           cap_local, p, bp, mesh,
                                           efb)
            goss_dropped = dc if goss_dropped is None \
                else _add_jit(goss_dropped, dc)
            trees.append(tree)
            continue
        tree, last_split, rel, absn = _grow_tree_chunked(
            chunks, gs, hs, wts, (col_mask, k_tree), p, mesh, efb)
        # scale leaves once (f32, same IEEE multiply as the fused
        # core's tree._replace(value=lr*value)) and fold into margins
        scaled = (tree.value
                  * np.float32(bp.learn_rate)).astype(np.float32)
        tree = tree._replace(value=scaled)
        value_dev = jnp.asarray(scaled)
        if p.max_depth > 0:
            feat_d, bin_d, nal_d, can_d = last_split
            for ci, bc in enumerate(_stream(chunks, mesh)):
                _, _, chunks.margin[ci] = _chunk_finish_jit(
                    bc, rel[ci], absn[ci], chunks.margin[ci], feat_d,
                    bin_d, nal_d, can_d, value_dev,
                    p.max_depth - 1, p, efb)
        else:
            for ci in range(chunks.n_chunks):
                chunks.margin[ci] = _add_root_jit(chunks.margin[ci],
                                                  value_dev)
        trees.append(tree)
    margin = np.concatenate([np.asarray(m) for m in chunks.margin])
    dropped_total = 0 if goss_dropped is None \
        else int(np.asarray(goss_dropped))
    return margin[: chunks.padded_rows], trees, dropped_total


_add_root_jit = jax.jit(lambda m, v: m + v[0])
