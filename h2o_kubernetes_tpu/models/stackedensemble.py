"""Stacked Ensembles — metalearner over base-model CV holdout predictions.

Reference: hex/ensemble/StackedEnsemble.java + StackedEnsembleModel
(SURVEY.md §2b C15): the level-one frame is each base model's
cross-validation holdout predictions (class-1 probability for binomial,
all K probabilities for multinomial, raw prediction for regression),
the metalearner (GLM by default, as in the reference) trains on it, and
scoring runs every base model then the metalearner on their outputs.

Requirements mirrored from the reference's checks: every base model
must have been trained with CV holdout predictions kept, on the same
response, with the SAME fold assignment (verified via the stored
per-row fold ids, like StackedEnsembleModel.checkAndInheritModelProperties).
"""

from __future__ import annotations

import numpy as np

from ..frame import Frame
from .base import Model


def _level_one_columns(m, preds: np.ndarray, tag: str) -> dict[str, np.ndarray]:
    """Columns a base model contributes to the level-one frame."""
    if m.nclasses == 2:
        return {tag: preds[:, 1]}
    if m.nclasses > 2:
        return {f"{tag}_p{k}": preds[:, k] for k in range(m.nclasses)}
    return {tag: preds}


class StackedEnsembleModel(Model):
    algo = "stackedensemble"

    def __init__(self, data, base_models: list, metalearner,
                 base_tags: list[str]):
        super().__init__(data)
        self.base_models = base_models
        self.metalearner = metalearner
        self.base_tags = base_tags

    def _level_one_frame(self, frame: Frame) -> Frame:
        cols: dict[str, np.ndarray] = {}
        for m, tag in zip(self.base_models, self.base_tags):
            cols.update(_level_one_columns(m, m.predict_raw(frame), tag))
        return Frame.from_arrays(cols)

    def predict_raw(self, frame: Frame) -> np.ndarray:
        # the inherited predict()/model_performance() route through this
        # override, so the ensemble needs nothing else
        return self.metalearner.predict_raw(self._level_one_frame(frame))


class StackedEnsemble:
    """H2OStackedEnsembleEstimator analog."""

    def __init__(self, base_models: list,
                 metalearner_algorithm: str = "glm",
                 metalearner_params: dict | None = None,
                 metalearner_nfolds: int = 0):
        if not base_models:
            raise ValueError("base_models must be non-empty")
        self.base_models = list(base_models)
        self.metalearner_algorithm = metalearner_algorithm
        self.metalearner_params = dict(metalearner_params or {})
        self.metalearner_nfolds = metalearner_nfolds

    def train(self, y: str, training_frame: Frame) -> StackedEnsembleModel:
        models = self.base_models
        ref = models[0]
        fold_ref = None
        for i, m in enumerate(models):
            if m.cv is None or m.cv.holdout_predictions is None:
                raise ValueError(
                    f"base model #{i} ({m.algo}) was not trained with "
                    "nfolds >= 2 and keep_cross_validation_predictions")
            if m.nclasses != ref.nclasses:
                raise ValueError("base models disagree on the response "
                                 f"({m.nclasses} vs {ref.nclasses} classes)")
            if m.cv.holdout_predictions.shape[0] != training_frame.nrows:
                raise ValueError(
                    f"base model #{i} was trained on a different frame "
                    f"({m.cv.holdout_predictions.shape[0]} rows vs "
                    f"{training_frame.nrows})")
            if fold_ref is None:
                fold_ref = m.cv.fold_ids
            elif not np.array_equal(m.cv.fold_ids, fold_ref):
                raise ValueError(
                    f"base model #{i} used a different fold assignment; "
                    "train all base models with the same fold_column or "
                    "(fold_assignment, seed)")

        tags = []
        seen: dict[str, int] = {}
        for m in models:
            tag = m.algo
            seen[tag] = seen.get(tag, 0) + 1
            tags.append(f"{tag}{seen[tag]}" if seen[tag] > 1 else tag)

        cols: dict[str, np.ndarray] = {}
        for m, tag in zip(models, tags):
            cols.update(_level_one_columns(m, m.cv.holdout_predictions, tag))
        lone = Frame.from_arrays(cols)
        lone[y] = training_frame.vec(y)

        cvkw = {"nfolds": self.metalearner_nfolds,
                "fold_assignment": "modulo"} \
            if self.metalearner_nfolds >= 2 else {}
        if self.metalearner_algorithm == "glm":
            from .glm import GLM

            params = dict(self.metalearner_params)
            if ref.nclasses == 2:
                params.setdefault("family", "binomial")
            elif ref.nclasses == 1:
                params.setdefault("family", "gaussian")
            else:
                # multinomial metalearning falls back to a DRF metalearner
                # until GLM grows a multinomial family
                from .drf import DRF

                meta = DRF(ntrees=50, seed=0, **cvkw).train(
                    y=y, training_frame=lone)
                return self._finish(meta, models, tags, training_frame, y)
            meta = GLM(**params, **cvkw).train(y=y, training_frame=lone)
        elif self.metalearner_algorithm in ("drf", "gbm"):
            from .drf import DRF
            from .gbm import GBM

            cls = DRF if self.metalearner_algorithm == "drf" else GBM
            meta = cls(**self.metalearner_params, **cvkw).train(
                y=y, training_frame=lone)
        else:
            raise ValueError(
                f"unknown metalearner '{self.metalearner_algorithm}'")
        return self._finish(meta, models, tags, training_frame, y)

    def _finish(self, meta, models, tags, training_frame, y):
        from .base import resolve_xy

        # reuse resolve_xy only for response metadata (features come
        # from the base models, not the frame)
        data = resolve_xy(training_frame, y,
                          x=models[0].feature_names[:1])
        data.feature_names = []
        model = StackedEnsembleModel(data, models, meta, tags)
        # the metalearner's CV (over the level-one holdout frame) is the
        # honest generalization estimate for the whole ensemble
        model.cv = meta.cv
        return model
