"""GLM — generalized linear models with IRLSM and L-BFGS solvers.

Reference: hex/glm/GLM.java + GLMTask.GLMIterationTask + gram/Gram +
optimization/ADMM (SURVEY.md §2b C11, §3.5): each IRLS iteration is one
MRTask over all chunks accumulating the weighted Gram XᵀWX and XᵀWz,
reduced over the node ring, then a Cholesky solve on the driver (ADMM
wrap for L1). Here the Gram accumulation is a per-shard fused matmul
(MXU work) + `psum` over the ROWS axis, and the [P,P] solve runs
replicated on device — the exact §3.5 correspondence.

DataInfo analog: numeric features are mean-imputed + standardized;
categorical features expand to one-hot (with optional NA level and
drop-first when unpenalized), all device-side.

Families: gaussian (identity), binomial (logit), poisson (log).
Solvers: IRLSM (+ ADMM proximal loop for elastic-net L1), L_BFGS
(optax.lbfgs on the penalized deviance). lambda_search fits a warm-
started descending λ path.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from ..frame import Frame
from ..runtime.mesh import COLS, ROWS, global_mesh
from .base import Model, TrainData, resolve_xy
from .datainfo import DataInfo, build_datainfo


@dataclass
class GLMParams:
    family: str = "gaussian"          # gaussian | binomial | poisson
    solver: str = "IRLSM"             # IRLSM | L_BFGS
    alpha: float = 0.5                # elastic-net mixing (1 = lasso)
    lambda_: float | None = None      # None → 0 unless lambda_search
    lambda_search: bool = False
    nlambdas: int = 30
    lambda_min_ratio: float = 1e-4
    standardize: bool = True
    use_all_factor_levels: bool = False
    max_iterations: int = 50
    objective_epsilon: float = 1e-6
    beta_epsilon: float = 1e-4
    seed: int = 0


# -- link/family math --------------------------------------------------------

def _linkinv(family, eta):
    if family == "binomial":
        return jax.nn.sigmoid(eta)
    if family == "poisson":
        return jnp.exp(jnp.clip(eta, -30, 30))
    return eta


def _family_deviance(family, y, mu, w):
    if family == "binomial":
        mu = jnp.clip(mu, 1e-7, 1 - 1e-7)
        ll = y * jnp.log(mu) + (1 - y) * jnp.log1p(-mu)
        return -2.0 * jnp.sum(w * ll)
    if family == "poisson":
        mu = jnp.clip(mu, 1e-10, None)
        t = jnp.where(y > 0, y * jnp.log(y / mu), 0.0)
        return 2.0 * jnp.sum(w * (t - (y - mu)))
    return jnp.sum(w * (y - mu) ** 2)


def _irls_weights(family, eta, mu, y):
    """(working weight, working response z) for one IRLS step."""
    if family == "binomial":
        wk = jnp.clip(mu * (1 - mu), 1e-10, None)
        z = eta + (y - mu) / wk
        return wk, z
    if family == "poisson":
        wk = jnp.clip(mu, 1e-10, None)
        z = eta + (y - mu) / wk
        return wk, z
    return jnp.ones_like(eta), y


# -- distributed accumulations (the GLMIterationTask analogs) ---------------

@functools.partial(jax.jit, static_argnums=(4,))
def _gram_task(Xe, wk, z, w, mesh):
    """Distributed Gram accumulate: G=XᵀWX [P,P], b=XᵀWz [P].

    Rows shard over ROWS (the MRTask reduce, psum on ICI) and the
    EXPANDED FEATURE axis shards over COLS — the wide-feature TP analog
    (SURVEY.md §5.7): GLM's categorical expansion can reach 10⁴–10⁶
    features, at which point the [P,P] Gram dominates.  Each COLS shard
    computes only its [P/c, P] row-block of G with a fused matmul, so
    Gram FLOPs and result memory split c ways; G comes back
    feature-sharded over COLS (out_specs P(COLS)), the psum over ROWS
    acting as a reduce-scatter across the mesh as a whole.  c == 1
    degenerates to the plain row-sharded Gram.
    """
    c = mesh.shape[COLS]
    Pn = Xe.shape[1]
    blk = -(-Pn // c)
    pad = blk * c - Pn
    Xp = jnp.pad(Xe, ((0, 0), (0, pad))) if pad else Xe

    def body(xs, wks, zs, ws):
        ci = lax.axis_index(COLS)
        ww = (wks * ws)[:, None]
        xb = lax.dynamic_slice_in_dim(xs, ci * blk, blk, axis=1)
        G = xb.T @ (ww * xs)                    # [blk, P] block of G
        b = xb.T @ (ww[:, 0] * zs)              # [blk] block of b
        return lax.psum(G, ROWS), lax.psum(b, ROWS)

    G, b = jax.shard_map(body, mesh=mesh,
                         in_specs=(P(ROWS), P(ROWS), P(ROWS), P(ROWS)),
                         out_specs=(P(COLS, None), P(COLS)))(Xp, wk, z, w)
    return G[:Pn, :Pn], b[:Pn]


@functools.partial(jax.jit, static_argnums=(3, 4))
def _eta_dev_task(Xe, beta, yw, family, mesh):
    """Per-shard eta + deviance psum → (dev, eta). yw: [R,2] (y, w).

    Returning eta (row-sharded) lets the IRLS loop reuse this matmul for
    the next iteration's working weights instead of recomputing Xe@beta.
    """

    def body(xs, yws, b):
        eta = xs @ b
        mu = _linkinv(family, eta)
        dev = _family_deviance(family, yws[:, 0], mu, yws[:, 1])
        return lax.psum(dev, ROWS), eta

    return jax.shard_map(body, mesh=mesh,
                         in_specs=(P(ROWS), P(ROWS), P()),
                         out_specs=(P(), P(ROWS)))(Xe, yw, beta)


def _soft(x, k):
    return jnp.sign(x) * jnp.maximum(jnp.abs(x) - k, 0.0)


@functools.partial(jax.jit, static_argnums=(4,))
def _admm_solve(G, b, lam_l1, lam_l2, n_iter: int = 100):
    """minimize ½βᵀGβ - bᵀβ + λ₁|β|₁ + ½λ₂|β|² (intercept unpenalized)."""
    Pn = G.shape[0]
    pen_mask = jnp.ones(Pn).at[Pn - 1].set(0.0)   # intercept last
    rho = jnp.maximum(lam_l1, 1e-3)
    A = G + (lam_l2 * pen_mask + rho * pen_mask)[:, None] * jnp.eye(Pn) \
        + 1e-6 * jnp.eye(Pn)
    L = jax.scipy.linalg.cho_factor(A)

    def step(carry, _):
        zb, u = carry
        beta = jax.scipy.linalg.cho_solve(
            L, b + rho * pen_mask * (zb - u))
        zb_new = _soft(beta + u, lam_l1 / rho) * pen_mask + \
            (beta + u) * (1 - pen_mask)
        u_new = u + beta - zb_new
        return (zb_new, u_new), None

    (zb, _), _ = lax.scan(step, (jnp.zeros(Pn), jnp.zeros(Pn)), None,
                          length=n_iter)
    return zb


@jax.jit
def _chol_solve(G, b, lam_l2):
    Pn = G.shape[0]
    pen = jnp.ones(Pn).at[Pn - 1].set(0.0) * lam_l2
    A = G + jnp.diag(pen) + 1e-6 * jnp.eye(Pn)
    return jax.scipy.linalg.cho_solve(jax.scipy.linalg.cho_factor(A), b)


# -- model ------------------------------------------------------------------

class GLMModel(Model):
    algo = "glm"

    def __init__(self, data: TrainData, params: GLMParams, dinfo: DataInfo,
                 beta: jax.Array, lambda_used: float,
                 null_deviance: float, residual_deviance: float,
                 n_iterations: int):
        super().__init__(data)
        self.params = params
        self.dinfo = dinfo
        self.beta = beta
        self.lambda_used = lambda_used
        self.null_deviance = null_deviance
        self.residual_deviance = residual_deviance
        self.n_iterations = n_iterations

    def coef(self) -> dict[str, float]:
        """De-standardized coefficients in original units."""
        b = np.asarray(self.beta, dtype=np.float64)
        names = self.dinfo.coef_names
        out = dict(zip(names, b))
        icpt = out["Intercept"]
        nnum = len(self.dinfo.numeric_idx)
        for j in range(nnum):
            name = names[j]
            out[name] = b[j] / self.dinfo.stds[j]
            icpt -= b[j] * self.dinfo.means[j] / self.dinfo.stds[j]
        out["Intercept"] = icpt
        return out

    def coef_norm(self) -> dict[str, float]:
        """Coefficients on the standardized scale (as solved)."""
        return dict(zip(self.dinfo.coef_names,
                        np.asarray(self.beta, dtype=np.float64)))

    def _score_matrix(self, X: jax.Array) -> jax.Array:
        Xe = self.dinfo.expand(X)
        eta = Xe @ self.beta
        mu = _linkinv(self.params.family, eta)
        if self.params.family == "binomial":
            return jnp.stack([1 - mu, mu], axis=1)
        return mu


class GLM:
    """H2OGeneralizedLinearEstimator analog."""

    def __init__(self, **kw):
        from .cv import CVArgs

        self.cv_args = CVArgs.pop(kw)
        self.params = GLMParams(**kw)

    def _fit_beta(self, Xe, data, dinfo, lam, beta0, mesh):
        p = self.params
        Pn = dinfo.n_expanded
        lam_l1 = lam * p.alpha
        lam_l2 = lam * (1 - p.alpha)
        n_obs = float(jnp.sum(data.w))
        beta = beta0
        yw = jnp.stack([data.y, data.w], axis=1)
        dev0, eta = _eta_dev_task(Xe, beta, yw, p.family, mesh)
        dev_prev = float(dev0)
        it = 0
        for it in range(1, p.max_iterations + 1):
            mu = _linkinv(p.family, eta)       # eta reused from last solve
            wk, z = _irls_weights(p.family, eta, mu, data.y)
            G, b = _gram_task(Xe, wk, z, data.w, mesh)
            G = G / n_obs
            b = b / n_obs
            if lam_l1 > 0:
                beta_new = _admm_solve(G, b, lam_l1, lam_l2)
            else:
                beta_new = _chol_solve(G, b, lam_l2)
            dev_new, eta = _eta_dev_task(Xe, beta_new, yw, p.family, mesh)
            dev = float(dev_new)
            db = float(jnp.max(jnp.abs(beta_new - beta)))
            beta = beta_new
            if p.family == "gaussian" and lam_l1 == 0:
                break                      # exact one-shot solve
            if abs(dev_prev - dev) < p.objective_epsilon * \
                    (abs(dev_prev) + 1e-10) or db < p.beta_epsilon:
                dev_prev = dev
                break
            dev_prev = dev
        return beta, dev_prev, it

    def train(self, y: str, training_frame: Frame,
              x: Sequence[str] | None = None,
              ignored_columns: Sequence[str] | None = None,
              weights_column: str | None = None,
              validation_frame: Frame | None = None) -> GLMModel:
        p = self.params
        if self.cv_args.fold_column:
            ignored_columns = list(ignored_columns or []) + \
                [self.cv_args.fold_column]
        if p.family not in ("gaussian", "binomial", "poisson"):
            raise ValueError(f"unknown family '{p.family}' (supported: "
                             "gaussian, binomial, poisson)")
        if p.solver not in ("IRLSM", "L_BFGS"):
            raise ValueError(f"unknown solver '{p.solver}' (supported: "
                             "IRLSM, L_BFGS)")
        mesh = global_mesh()
        fam_dist = {"binomial": "bernoulli"}.get(p.family, p.family)
        data = resolve_xy(training_frame, y, x, ignored_columns,
                          weights_column, fam_dist)
        if p.family == "binomial" and data.nclasses != 2:
            raise ValueError("binomial family needs a 2-class response")
        if p.family != "binomial" and data.nclasses > 1:
            raise ValueError(
                f"family='{p.family}' needs a numeric response; "
                f"'{y}' is categorical")
        dinfo = build_datainfo(data, training_frame, p.standardize,
                               drop_first=not p.use_all_factor_levels)
        Xe = jax.jit(dinfo.expand)(data.X)
        Pn = dinfo.n_expanded
        n_obs = float(jnp.sum(data.w))
        yw = jnp.stack([data.y, data.w], axis=1)

        # null deviance (intercept-only model)
        ybar = float(jnp.sum(data.y * data.w)) / n_obs
        if p.family == "binomial":
            ybar = min(max(ybar, 1e-7), 1 - 1e-7)
            b0 = np.log(ybar / (1 - ybar))
        elif p.family == "poisson":
            b0 = np.log(max(ybar, 1e-10))
        else:
            b0 = ybar
        beta_null = jnp.zeros(Pn).at[Pn - 1].set(b0)
        null_dev = float(_eta_dev_task(Xe, beta_null, yw, p.family,
                                         mesh)[0])

        if p.lambda_search:
            # λ_max: smallest λ zeroing all coefs (from null-model gradient)
            eta0 = Xe @ beta_null
            mu0 = _linkinv(p.family, eta0)
            grad = np.asarray(jnp.abs(
                Xe.T @ ((mu0 - data.y) * data.w))) / n_obs
            lam_max = float(grad[:-1].max()) / max(p.alpha, 1e-3)
            lams = np.logspace(np.log10(lam_max),
                               np.log10(lam_max * p.lambda_min_ratio),
                               p.nlambdas)
        else:
            lams = [p.lambda_ if p.lambda_ is not None else 0.0]

        if p.solver == "L_BFGS":
            beta, dev, iters = self._fit_lbfgs(Xe, data, dinfo,
                                               float(lams[-1]), beta_null,
                                               mesh)
            lam_used = float(lams[-1])
        else:
            beta = beta_null
            dev, iters = null_dev, 0
            for lam in lams:               # warm-started λ path
                beta, dev, its = self._fit_beta(Xe, data, dinfo,
                                                float(lam), beta, mesh)
                iters += its
            lam_used = float(lams[-1])

        model = GLMModel(data, p, dinfo, beta, lam_used, null_dev, dev,
                         iters)
        from .cv import finalize_train

        return finalize_train(
            self, model, y, training_frame,
            {"x": x, "ignored_columns": ignored_columns,
             "weights_column": weights_column},
            validation_frame)

    def _fit_lbfgs(self, Xe, data, dinfo, lam, beta0, mesh):
        import optax

        p = self.params
        n_obs = float(jnp.sum(data.w))
        lam_l2 = lam * (1 - p.alpha)
        lam_l1 = lam * p.alpha
        Pn = dinfo.n_expanded
        pen_mask = jnp.ones(Pn).at[Pn - 1].set(0.0)
        yw = jnp.stack([data.y, data.w], axis=1)

        def obj(beta):
            def body(xs, yws, b):
                eta = xs @ b
                mu = _linkinv(p.family, eta)
                return lax.psum(
                    _family_deviance(p.family, yws[:, 0], mu, yws[:, 1]),
                    ROWS)

            dev = jax.shard_map(body, mesh=mesh,
                                in_specs=(P(ROWS), P(ROWS), P()),
                                out_specs=P())(Xe, yw, beta)
            penal = 0.5 * lam_l2 * jnp.sum((pen_mask * beta) ** 2) + \
                lam_l1 * jnp.sum(jnp.abs(pen_mask * beta))  # subgradient
            return 0.5 * dev / n_obs + penal

        opt = optax.lbfgs()
        state = opt.init(beta0)
        beta = beta0
        value_and_grad = jax.jit(jax.value_and_grad(obj))

        @jax.jit
        def step(beta, state):
            value, grad = value_and_grad(beta)
            updates, state = opt.update(
                grad, state, beta, value=value, grad=grad,
                value_fn=obj)
            return optax.apply_updates(beta, updates), state, value

        prev = np.inf
        it = 0
        for it in range(1, p.max_iterations + 1):
            beta, state, value = step(beta, state)
            v = float(value)
            if abs(prev - v) < p.objective_epsilon * (abs(prev) + 1e-10):
                break
            prev = v
        dev = float(_eta_dev_task(Xe, beta, yw, p.family, mesh)[0])
        return beta, dev, it
