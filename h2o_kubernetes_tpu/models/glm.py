"""GLM — generalized linear models with IRLSM and L-BFGS solvers.

Reference: hex/glm/GLM.java + GLMTask.GLMIterationTask + gram/Gram +
optimization/ADMM (SURVEY.md §2b C11, §3.5): each IRLS iteration is one
MRTask over all chunks accumulating the weighted Gram XᵀWX and XᵀWz,
reduced over the node ring, then a Cholesky solve on the driver (ADMM
wrap for L1). Here the Gram accumulation is a per-shard fused matmul
(MXU work) + `psum` over the ROWS axis, and the [P,P] solve runs
replicated on device — the exact §3.5 correspondence.

DataInfo analog: numeric features are mean-imputed + standardized;
categorical features expand to one-hot (with optional NA level and
drop-first when unpenalized), all device-side.

Families (hex/glm/GLMModel.GLMParameters.Family [U3]): gaussian
(identity), binomial (logit), poisson (log), gamma (inverse|log),
tweedie (log, variance power in (1,2)), negativebinomial (log, theta),
multinomial (softmax; IRLSM cycles classes with per-class
Fisher scoring like the reference, L_BFGS runs the full-matrix path). Solvers: IRLSM (+ ADMM proximal
loop for elastic-net L1), L_BFGS (optax.lbfgs on the penalized
deviance), COORDINATE_DESCENT (glmnet-style cyclic CD on the weighted
Gram inside the IRLS loop). lambda_search fits a warm-started
descending λ path. compute_p_values adds std errors / z / p per
coefficient from the inverse information matrix (λ=0, IRLSM only —
the reference's restriction).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import Any, NamedTuple, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from ..frame import Frame
from ..runtime.mesh import COLS, ROWS, global_mesh
from ..runtime.health import require_healthy
from .base import Model, TrainData, resolve_xy
from .datainfo import DataInfo, build_datainfo


_FAMILIES = ("gaussian", "binomial", "poisson", "gamma", "tweedie",
             "negativebinomial", "multinomial")
_SOLVERS = ("IRLSM", "L_BFGS", "COORDINATE_DESCENT")
_DEFAULT_LINK = {"gaussian": "identity", "binomial": "logit",
                 "poisson": "log", "gamma": "inverse", "tweedie": "log",
                 "negativebinomial": "log", "multinomial": "multinomial"}


@dataclass
class GLMParams:
    family: str = "gaussian"          # see _FAMILIES
    solver: str = "IRLSM"             # see _SOLVERS
    link: str | None = None           # None → family default
    alpha: float = 0.5                # elastic-net mixing (1 = lasso)
    lambda_: float | None = None      # None → 0 unless lambda_search
    lambda_search: bool = False
    nlambdas: int = 30
    lambda_min_ratio: float = 1e-4
    standardize: bool = True
    use_all_factor_levels: bool = False
    max_iterations: int = 50
    objective_epsilon: float = 1e-6
    beta_epsilon: float = 1e-4
    tweedie_variance_power: float = 1.5   # p in (1,2)
    theta: float = 1.0                    # negativebinomial dispersion
    compute_p_values: bool = False
    seed: int = 0


# -- link/family math --------------------------------------------------------

class FamSpec(NamedTuple):
    """Hashable (family, link, extras) bundle — a jit static argument."""

    family: str
    link: str
    tvp: float = 1.5      # tweedie variance power
    theta: float = 1.0    # negativebinomial dispersion


def _linkinv(fam, eta):
    if fam.link == "logit":
        return jax.nn.sigmoid(eta)
    if fam.link == "log":
        return jnp.exp(jnp.clip(eta, -30, 30))
    if fam.link == "inverse":
        # keep eta away from 0 preserving sign (reference GLM link inverse)
        e = jnp.where(jnp.abs(eta) < 1e-6,
                      jnp.where(eta < 0, -1e-6, 1e-6), eta)
        return 1.0 / e
    return eta


def _linkfun(fam, mu):
    if fam.link == "logit":
        return jnp.log(mu / (1.0 - mu))
    if fam.link == "log":
        return jnp.log(mu)
    if fam.link == "inverse":
        return 1.0 / mu
    return mu


def _dmu_deta(fam, eta, mu):
    if fam.link == "logit":
        return mu * (1.0 - mu)
    if fam.link == "log":
        return mu
    if fam.link == "inverse":
        return -(mu * mu)
    return jnp.ones_like(eta)


def _variance_fn(fam, mu):
    f = fam.family
    if f == "binomial":
        return mu * (1.0 - mu)
    if f == "poisson":
        return mu
    if f == "gamma":
        return mu * mu
    if f == "tweedie":
        return jnp.power(jnp.clip(mu, 1e-10, None), fam.tvp)
    if f == "negativebinomial":
        return mu + fam.theta * mu * mu
    return jnp.ones_like(mu)


def _family_deviance(fam, y, mu, w):
    f = fam.family
    if f == "binomial":
        mu = jnp.clip(mu, 1e-7, 1 - 1e-7)
        ll = y * jnp.log(mu) + (1 - y) * jnp.log1p(-mu)
        return -2.0 * jnp.sum(w * ll)
    if f == "poisson":
        mu = jnp.clip(mu, 1e-10, None)
        t = jnp.where(y > 0, y * jnp.log(y / mu), 0.0)
        return 2.0 * jnp.sum(w * (t - (y - mu)))
    if f == "gamma":
        mu = jnp.clip(mu, 1e-10, None)
        ys = jnp.clip(y, 1e-10, None)
        return 2.0 * jnp.sum(w * ((y - mu) / mu - jnp.log(ys / mu)))
    if f == "tweedie":
        p_ = fam.tvp
        mu = jnp.clip(mu, 1e-10, None)
        ys = jnp.clip(y, 0.0, None)
        t1 = jnp.where(ys > 0,
                       jnp.power(jnp.clip(ys, 1e-10, None), 2 - p_) /
                       ((1 - p_) * (2 - p_)), 0.0)
        return 2.0 * jnp.sum(w * (
            t1 - ys * jnp.power(mu, 1 - p_) / (1 - p_)
            + jnp.power(mu, 2 - p_) / (2 - p_)))
    if f == "negativebinomial":
        th = fam.theta
        mu = jnp.clip(mu, 1e-10, None)
        t1 = jnp.where(y > 0, y * jnp.log(jnp.clip(y, 1e-10, None) / mu),
                       0.0)
        t2 = (y + 1.0 / th) * jnp.log((1 + th * y) / (1 + th * mu))
        return 2.0 * jnp.sum(w * (t1 - t2))
    return jnp.sum(w * (y - mu) ** 2)


def _irls_weights(fam, eta, mu, y):
    """(working weight, working response z) for one IRLS step:
    wk = (dμ/dη)²/V(μ), z = η + (y-μ)/(dμ/dη) — the standard Fisher
    scoring construction, matching GLMIterationTask's per-row math."""
    if fam.family == "gaussian" and fam.link == "identity":
        return jnp.ones_like(eta), y
    d = _dmu_deta(fam, eta, mu)
    V = _variance_fn(fam, mu)
    safe_d = jnp.where(jnp.abs(d) < 1e-10,
                       jnp.where(d < 0, -1e-10, 1e-10), d)
    wk = jnp.clip(d * d / jnp.clip(V, 1e-10, None), 1e-10, None)
    z = eta + (y - mu) / safe_d
    return wk, z


# -- distributed accumulations (the GLMIterationTask analogs) ---------------

@functools.partial(jax.jit, static_argnums=(4,))
def _gram_task(Xe, wk, z, w, mesh):
    """Distributed Gram accumulate: G=XᵀWX [P,P], b=XᵀWz [P].

    Rows shard over ROWS (the MRTask reduce, psum on ICI) and the
    EXPANDED FEATURE axis shards over COLS — the wide-feature TP analog
    (SURVEY.md §5.7): GLM's categorical expansion can reach 10⁴–10⁶
    features, at which point the [P,P] Gram dominates.  Each COLS shard
    computes only its [P/c, P] row-block of G with a fused matmul, so
    Gram FLOPs and result memory split c ways; G comes back
    feature-sharded over COLS (out_specs P(COLS)), the psum over ROWS
    acting as a reduce-scatter across the mesh as a whole.  c == 1
    degenerates to the plain row-sharded Gram.
    """
    c = mesh.shape[COLS]
    Pn = Xe.shape[1]
    blk = -(-Pn // c)
    pad = blk * c - Pn
    Xp = jnp.pad(Xe, ((0, 0), (0, pad))) if pad else Xe

    def body(xs, wks, zs, ws):
        ci = lax.axis_index(COLS)
        ww = (wks * ws)[:, None]
        xb = lax.dynamic_slice_in_dim(xs, ci * blk, blk, axis=1)
        G = xb.T @ (ww * xs)                    # [blk, P] block of G
        b = xb.T @ (ww[:, 0] * zs)              # [blk] block of b
        return lax.psum(G, ROWS), lax.psum(b, ROWS)

    G, b = jax.shard_map(body, mesh=mesh,
                         in_specs=(P(ROWS), P(ROWS), P(ROWS), P(ROWS)),
                         out_specs=(P(COLS, None), P(COLS)))(Xp, wk, z, w)
    return G[:Pn, :Pn], b[:Pn]


@functools.partial(jax.jit, static_argnums=(4,))
def _softmax_irls_task(Xe, B, yw, k, mesh):
    """Per-class IRLS working (wk, z) from the multinomial softmax at
    the current [P, K] coefficients — the class-k block of the
    block-diagonal Fisher update (reference: GLM.java solves
    multinomial under IRLSM by cycling classes, SURVEY.md §2b C11).
    `k` is TRACED (one compile serves every class — K static variants
    would recompile the shard_map per class)."""

    def body(xs, yws, b, kk):
        eta = xs @ b                               # [r, K]
        pk = jnp.take(jax.nn.softmax(eta, axis=1), kk, axis=1)
        pk = jnp.clip(pk, 1e-10, 1.0 - 1e-10)
        wk = jnp.clip(pk * (1.0 - pk), 1e-10, None)
        yk = (yws[:, 0] == kk).astype(jnp.float32)
        z = jnp.take(eta, kk, axis=1) + (yk - pk) / wk
        return wk, z

    return jax.shard_map(body, mesh=mesh,
                         in_specs=(P(ROWS), P(ROWS), P(), P()),
                         out_specs=(P(ROWS), P(ROWS)))(
        Xe, yw, B, jnp.asarray(k, dtype=jnp.int32))


@functools.partial(jax.jit, static_argnums=(3, 4))
def _eta_dev_task(Xe, beta, yw, fam, mesh):
    """Per-shard eta + deviance psum → (dev, eta).

    yw: [R,3] (y, w, offset). The returned eta is the TOTAL linear
    predictor Xe@beta + offset (row-sharded), which the IRLS loop
    reuses for the next iteration's working weights instead of
    recomputing the matmul; the fixed offset term rides along
    (hex/glm GLMTask applies the row offset to eta identically [U3]).
    """

    def body(xs, yws, b):
        eta = xs @ b + yws[:, 2]
        mu = _linkinv(fam, eta)
        dev = _family_deviance(fam, yws[:, 0], mu, yws[:, 1])
        return lax.psum(dev, ROWS), eta

    return jax.shard_map(body, mesh=mesh,
                         in_specs=(P(ROWS), P(ROWS), P()),
                         out_specs=(P(), P(ROWS)))(Xe, yw, beta)


def _ywo(data: TrainData) -> jax.Array:
    """[R,3] (y, w, offset) stack shared by every GLM task."""
    off = data.offset if data.offset is not None \
        else jnp.zeros_like(data.y)
    return jnp.stack([data.y, data.w, off], axis=1)


def _soft(x, k):
    return jnp.sign(x) * jnp.maximum(jnp.abs(x) - k, 0.0)


@functools.partial(jax.jit, static_argnums=(4,))
def _admm_solve(G, b, lam_l1, lam_l2, n_iter: int = 100):
    """minimize ½βᵀGβ - bᵀβ + λ₁|β|₁ + ½λ₂|β|² (intercept unpenalized)."""
    Pn = G.shape[0]
    pen_mask = jnp.ones(Pn).at[Pn - 1].set(0.0)   # intercept last
    rho = jnp.maximum(lam_l1, 1e-3)
    A = G + (lam_l2 * pen_mask + rho * pen_mask)[:, None] * jnp.eye(Pn) \
        + 1e-6 * jnp.eye(Pn)
    L = jax.scipy.linalg.cho_factor(A)

    def step(carry, _):
        zb, u = carry
        beta = jax.scipy.linalg.cho_solve(
            L, b + rho * pen_mask * (zb - u))
        zb_new = _soft(beta + u, lam_l1 / rho) * pen_mask + \
            (beta + u) * (1 - pen_mask)
        u_new = u + beta - zb_new
        return (zb_new, u_new), None

    (zb, _), _ = lax.scan(step, (jnp.zeros(Pn), jnp.zeros(Pn)), None,
                          length=n_iter)
    return zb


@jax.jit
def _chol_solve(G, b, lam_l2):
    Pn = G.shape[0]
    pen = jnp.ones(Pn).at[Pn - 1].set(0.0) * lam_l2
    A = G + jnp.diag(pen) + 1e-6 * jnp.eye(Pn)
    return jax.scipy.linalg.cho_solve(jax.scipy.linalg.cho_factor(A), b)


def _solve_gram(G, b, beta0, lam_l1, lam_l2, solver: str):
    """ONE solver-selection policy for every IRLS loop (binomial
    _fit_beta and the multinomial per-class sweep): CD when requested,
    ADMM when L1 is active, else the direct Cholesky solve. Host-side
    dispatch — the solvers themselves are jitted."""
    if solver == "COORDINATE_DESCENT":
        return _cd_solve(G, b, beta0, lam_l1, lam_l2)
    if lam_l1 > 0:
        return _admm_solve(G, b, lam_l1, lam_l2)
    return _chol_solve(G, b, lam_l2)


@functools.partial(jax.jit, static_argnums=(5,))
def _cd_solve(G, b, beta0, lam_l1, lam_l2, n_sweeps: int = 50):
    """Cyclic coordinate descent on ½βᵀGβ - bᵀβ + λ₁|β|₁ + ½λ₂|β|²
    (glmnet covariance updates — the reference's COORDINATE_DESCENT
    solver, hex/glm GLM.Solver.COORDINATE_DESCENT [U3]). Operates on
    the same normalized Gram as the Cholesky/ADMM paths; the intercept
    (last coordinate) is unpenalized."""
    Pn = G.shape[0]
    pen = jnp.ones(Pn).at[Pn - 1].set(0.0)
    diag = jnp.diagonal(G)

    def coord(j, beta):
        gj = b[j] - G[j] @ beta + diag[j] * beta[j]
        bj = _soft(gj, lam_l1 * pen[j]) / \
            (diag[j] + lam_l2 * pen[j] + 1e-10)
        return beta.at[j].set(bj)

    def sweep(beta, _):
        return lax.fori_loop(0, Pn, coord, beta), None

    beta, _ = lax.scan(sweep, beta0, None, length=n_sweeps)
    return beta


def _famspec(p: GLMParams) -> FamSpec:
    return FamSpec(p.family, p.link or _DEFAULT_LINK[p.family],
                   p.tweedie_variance_power, p.theta)


# -- model ------------------------------------------------------------------

class GLMModel(Model):
    algo = "glm"
    _serving_jit = True     # predict routes through the jitted-scorer cache

    def __init__(self, data: TrainData, params: GLMParams, dinfo: DataInfo,
                 beta: jax.Array, lambda_used: float,
                 null_deviance: float, residual_deviance: float,
                 n_iterations: int):
        super().__init__(data)
        self.params = params
        self.dinfo = dinfo
        self.beta = beta
        self.lambda_used = lambda_used
        self.null_deviance = null_deviance
        self.residual_deviance = residual_deviance
        self.n_iterations = n_iterations

    def coef(self) -> dict:
        """De-standardized coefficients in original units.

        Multinomial: {class_label: {coef_name: value}} (h2o-py returns
        a per-class table; a dict-of-dicts is the Python-first shape).
        """
        b = np.asarray(self.beta, dtype=np.float64)
        names = self.dinfo.coef_names
        if b.ndim == 2:
            out = {}
            doms = self.response_domain or [str(k)
                                            for k in range(b.shape[1])]
            for k, lbl in enumerate(doms):
                sub = GLMModel.__new__(GLMModel)
                sub.beta = self.beta[:, k]
                sub.dinfo = self.dinfo
                out[lbl] = GLMModel.coef(sub)
            return out
        out = dict(zip(names, b))
        icpt = out["Intercept"]
        nnum = len(self.dinfo.numeric_idx)
        for j in range(nnum):
            name = names[j]
            out[name] = b[j] / self.dinfo.stds[j]
            icpt -= b[j] * self.dinfo.means[j] / self.dinfo.stds[j]
        out["Intercept"] = icpt
        return out

    def coef_norm(self) -> dict[str, float]:
        """Coefficients on the standardized scale (as solved)."""
        return dict(zip(self.dinfo.coef_names,
                        np.asarray(self.beta, dtype=np.float64)))

    def _score_matrix(self, X: jax.Array,
                      offset: jax.Array | None = None) -> jax.Array:
        Xe = self.dinfo.expand(X)
        eta = Xe @ self.beta
        if offset is not None:
            eta = eta + offset
        if self.params.family == "multinomial":
            return jax.nn.softmax(eta, axis=1)
        mu = _linkinv(_famspec(self.params), eta)
        if self.params.family == "binomial":
            return jnp.stack([1 - mu, mu], axis=1)
        return mu

    # -- inference statistics (compute_p_values) ----------------------------

    def _fit_inference(self, Xe, data, fam, mesh) -> None:
        """Std errors / z / p from the inverse Fisher information
        XᵀWX⁻¹·φ at the fitted β (hex/glm computePValues [U3]),
        de-standardized through the same affine map as coef()."""
        eta = Xe @ self.beta
        if data.offset is not None:
            eta = eta + data.offset
        mu = _linkinv(fam, eta)
        wk, _ = _irls_weights(fam, eta, mu, data.y)
        G, _ = _gram_task(Xe, wk, jnp.zeros_like(eta), data.w, mesh)
        n = float(jnp.sum(data.w))
        Pn = G.shape[0]
        k = Pn  # parameters incl. intercept
        if fam.family in ("gaussian", "gamma", "tweedie"):
            # moment estimate of the dispersion φ (Pearson X²/(n-k))
            V = _variance_fn(fam, mu)
            pearson = float(jnp.sum(
                data.w * (data.y - mu) ** 2 / jnp.clip(V, 1e-10, None)))
            phi = pearson / max(n - k, 1.0)
        else:
            phi = 1.0
        cov = np.linalg.inv(np.asarray(G, dtype=np.float64)
                            + 1e-10 * np.eye(Pn)) * phi
        # de-standardization is linear: coef_orig = A @ coef_std
        A = np.eye(Pn)
        nnum = len(self.dinfo.numeric_idx)
        for j in range(nnum):
            A[j, j] = 1.0 / self.dinfo.stds[j]
            A[Pn - 1, j] = -self.dinfo.means[j] / self.dinfo.stds[j]
        cov_o = A @ cov @ A.T
        se = np.sqrt(np.clip(np.diag(cov_o), 0, None))
        names = self.dinfo.coef_names
        coefs = self.coef()
        b = np.array([coefs[nm] for nm in names])
        with np.errstate(invalid="ignore", divide="ignore"):
            z = b / se
        from math import erfc
        # normal two-sided tail via erfc — no scipy dependency
        pv = np.array([erfc(abs(zz) / np.sqrt(2.0)) if zz == zz else
                       np.nan for zz in z])
        self._std_errs = dict(zip(names, se))
        self._z_values = dict(zip(names, z))
        self._p_values = dict(zip(names, pv))

    def std_errs(self) -> dict[str, float]:
        return self._require_inference("_std_errs")

    def zvalues(self) -> dict[str, float]:
        return self._require_inference("_z_values")

    def pvalues(self) -> dict[str, float]:
        return self._require_inference("_p_values")

    def _require_inference(self, attr):
        if not hasattr(self, attr):
            raise ValueError(
                "train with compute_p_values=True to get inference stats")
        return getattr(self, attr)


class GLM:
    """H2OGeneralizedLinearEstimator analog."""

    def __init__(self, **kw):
        from .cv import CVArgs

        self.cv_args = CVArgs.pop(kw)
        self.params = GLMParams(**kw)

    def _fit_beta(self, Xe, data, dinfo, lam, beta0, mesh,
                  history=None):
        """history: optional list collecting one row per IRLS
        iteration ({iteration, lambda, deviance}) — the GLMScoringInfo
        analog; the per-iteration deviance float already syncs for the
        convergence check, so recording it is free."""
        p = self.params
        fam = _famspec(p)
        Pn = dinfo.n_expanded
        lam_l1 = lam * p.alpha
        lam_l2 = lam * (1 - p.alpha)
        n_obs = float(jnp.sum(data.w))
        beta = beta0
        yw = _ywo(data)
        dev0, eta = _eta_dev_task(Xe, beta, yw, fam, mesh)
        dev_prev = float(dev0)
        it = 0
        for it in range(1, p.max_iterations + 1):
            require_healthy()   # fail fast on a dead mesh (§5.3)
            mu = _linkinv(fam, eta)            # eta reused from last solve
            wk, z = _irls_weights(fam, eta, mu, data.y)
            # eta (and hence z) carries the fixed offset; the Gram
            # solves for the LINEAR part only, so the working response
            # is z - offset (the reference subtracts the offset from z
            # in GLMIterationTask the same way)
            z = z - yw[:, 2]
            G, b = _gram_task(Xe, wk, z, data.w, mesh)
            G = G / n_obs
            b = b / n_obs
            beta_new = _solve_gram(G, b, beta, lam_l1, lam_l2, p.solver)
            dev_new, eta = _eta_dev_task(Xe, beta_new, yw, fam, mesh)
            dev = float(dev_new)
            db = float(jnp.max(jnp.abs(beta_new - beta)))
            beta = beta_new
            if history is not None:
                history.append({"iteration": len(history) + 1,
                                "lambda": lam, "deviance": dev})
            if fam.family == "gaussian" and fam.link == "identity" \
                    and lam_l1 == 0 and p.solver == "IRLSM":
                break                      # exact one-shot solve
            if abs(dev_prev - dev) < p.objective_epsilon * \
                    (abs(dev_prev) + 1e-10) or db < p.beta_epsilon:
                dev_prev = dev
                break
            dev_prev = dev
        return beta, dev_prev, it

    def train(self, y: str, training_frame: Frame,
              x: Sequence[str] | None = None,
              ignored_columns: Sequence[str] | None = None,
              weights_column: str | None = None,
              validation_frame: Frame | None = None,
              offset_column: str | None = None) -> GLMModel:
        p = self.params
        if offset_column and p.family == "multinomial":
            # a shared per-row offset added to every class eta is
            # softmax-invariant — accepting it would silently train an
            # identical model
            raise ValueError(
                "offset_column is not supported for multinomial")
        if self.cv_args.fold_column:
            ignored_columns = list(ignored_columns or []) + \
                [self.cv_args.fold_column]
        if p.family not in _FAMILIES:
            raise ValueError(f"unknown family '{p.family}' (supported: "
                             f"{', '.join(_FAMILIES)})")
        if p.solver not in _SOLVERS:
            raise ValueError(f"unknown solver '{p.solver}' (supported: "
                             f"{', '.join(_SOLVERS)})")
        fam = _famspec(p)
        if p.family == "tweedie" and not 1.0 < p.tweedie_variance_power < 2.0:
            raise ValueError("tweedie_variance_power must be in (1, 2)")
        if p.compute_p_values:
            # reference restriction (GLM.java): p-values need the exact
            # information matrix — IRLSM, no regularization
            if p.solver != "IRLSM":
                raise ValueError("compute_p_values requires solver='IRLSM'")
            if p.lambda_search or (p.lambda_ or 0.0) > 0:
                raise ValueError("compute_p_values requires lambda=0")
            if p.family == "multinomial":
                raise ValueError(
                    "compute_p_values is not supported for multinomial")
        if p.family == "multinomial" and p.lambda_search:
            # neither multinomial solver implements the warm-started λ
            # path yet; silently fitting one unpenalized model would
            # masquerade as a searched path
            raise ValueError(
                "lambda_search is not supported for multinomial; pass "
                "an explicit lambda_")
        mesh = global_mesh()
        fam_dist = {"binomial": "bernoulli", "gamma": "gaussian",
                    "tweedie": "gaussian", "negativebinomial": "poisson",
                    }.get(p.family, p.family)
        data = resolve_xy(training_frame, y, x, ignored_columns,
                          weights_column, fam_dist, offset_column)
        if p.family == "binomial" and data.nclasses != 2:
            raise ValueError("binomial family needs a 2-class response")
        if p.family == "multinomial" and data.nclasses < 2:
            raise ValueError(
                "multinomial family needs a categorical response")
        if p.family not in ("binomial", "multinomial") and data.nclasses > 1:
            raise ValueError(
                f"family='{p.family}' needs a numeric response; "
                f"'{y}' is categorical")
        ymin = float(jnp.nanmin(data.y)) if p.family in (
            "gamma", "tweedie", "poisson", "negativebinomial") else 0.0
        if p.family == "gamma" and ymin <= 0:
            raise ValueError("gamma family needs a strictly positive "
                             "response")
        if p.family in ("tweedie", "poisson", "negativebinomial") \
                and ymin < 0:
            raise ValueError(f"{p.family} family needs a non-negative "
                             "response")
        dinfo = build_datainfo(data, training_frame, p.standardize,
                               drop_first=not p.use_all_factor_levels)
        Xe = dinfo.expand(data.X)
        Pn = dinfo.n_expanded
        n_obs = float(jnp.sum(data.w))

        if p.family == "multinomial":
            return self._train_multinomial(
                y, training_frame, x, ignored_columns, weights_column,
                validation_frame, data, dinfo, Xe, mesh)
        yw = _ywo(data)

        # null deviance (intercept-only model: intercept = link(ȳ))
        ybar = float(jnp.sum(data.y * data.w)) / n_obs
        if p.family == "binomial":
            ybar = min(max(ybar, 1e-7), 1 - 1e-7)
        elif fam.link in ("log", "inverse"):
            ybar = max(ybar, 1e-10)
        b0 = float(_linkfun(fam, jnp.float32(ybar)))
        if data.offset is not None:
            # with an offset link(ȳ) is no longer the intercept MLE —
            # fit the intercept-only model through the same IRLS
            # machinery on a ones design (cheap: Gram is 1x1).
            # shard_rows, not jnp.ones: the design must be placed like
            # Xe or the shard_map can't shard it on a multi-host mesh
            from ..runtime.mrtask import shard_rows

            ones = shard_rows(np.ones((Xe.shape[0], 1), np.float32),
                              mesh=mesh)
            b_null, _, _ = self._fit_beta(
                ones, data, dinfo, 0.0, jnp.asarray([b0]), mesh)
            b0 = float(b_null[0])
        beta_null = jnp.zeros(Pn).at[Pn - 1].set(b0)
        null_dev = float(_eta_dev_task(Xe, beta_null, yw, fam,
                                         mesh)[0])

        if p.lambda_search:
            # λ_max: smallest λ zeroing all coefs (from null-model gradient)
            eta0 = Xe @ beta_null + yw[:, 2]
            mu0 = _linkinv(fam, eta0)
            grad = np.asarray(jnp.abs(
                Xe.T @ ((mu0 - data.y) * data.w))) / n_obs
            lam_max = float(grad[:-1].max()) / max(p.alpha, 1e-3)
            lams = np.logspace(np.log10(lam_max),
                               np.log10(lam_max * p.lambda_min_ratio),
                               p.nlambdas)
        else:
            lams = [p.lambda_ if p.lambda_ is not None else 0.0]

        history: list[dict] = []
        if p.solver == "L_BFGS":
            beta, dev, iters = self._fit_lbfgs(Xe, data, dinfo,
                                               float(lams[-1]), beta_null,
                                               mesh, history)
            lam_used = float(lams[-1])
        else:
            beta = beta_null
            dev, iters = null_dev, 0
            for lam in lams:               # warm-started λ path
                beta, dev, its = self._fit_beta(Xe, data, dinfo,
                                                float(lam), beta, mesh,
                                                history)
                iters += its
            lam_used = float(lams[-1])

        model = GLMModel(data, p, dinfo, beta, lam_used, null_dev, dev,
                         iters)
        model.scoring_history = history
        model.offset_column = offset_column
        if p.compute_p_values:
            model._fit_inference(Xe, data, fam, mesh)
        from .cv import finalize_train

        return finalize_train(
            self, model, y, training_frame,
            {"x": x, "ignored_columns": ignored_columns,
             "weights_column": weights_column,
             "offset_column": offset_column},
            validation_frame)

    def _train_multinomial(self, y, training_frame, x, ignored_columns,
                           weights_column, validation_frame, data, dinfo,
                           Xe, mesh):
        """Softmax regression: β is [P, K]; the deviance is the
        multinomial -2·loglik psum'd over row shards. IRLSM (and
        COORDINATE_DESCENT) cycle classes with per-class Fisher scoring
        through the distributed Gram — the reference's multinomial
        IRLSM shape (GLM.java [U3]); L_BFGS runs full-matrix optax
        L-BFGS on the softmax objective."""
        import optax

        p = self.params
        K = data.nclasses
        Pn = dinfo.n_expanded
        n_obs = float(jnp.sum(data.w))
        pen_mask = jnp.ones(Pn).at[Pn - 1].set(0.0)[:, None]
        lam = p.lambda_ if p.lambda_ is not None else 0.0
        lam_l2 = lam * (1 - p.alpha)
        lam_l1 = lam * p.alpha
        yw = jnp.stack([data.y, data.w], axis=1)
        history: list[dict] = []

        def dev_fn(B):
            def body(xs, yws, b):
                eta = xs @ b                       # [r, K]
                logp = jax.nn.log_softmax(eta, axis=1)
                yk = yws[:, 0].astype(jnp.int32)
                ll = jnp.take_along_axis(logp, yk[:, None], axis=1)[:, 0]
                return lax.psum(-2.0 * jnp.sum(yws[:, 1] * ll), ROWS)

            return jax.shard_map(body, mesh=mesh,
                                 in_specs=(P(ROWS), P(ROWS), P()),
                                 out_specs=P())(Xe, yw, B)

        def obj(B):
            penal = 0.5 * lam_l2 * jnp.sum((pen_mask * B) ** 2) + \
                lam_l1 * jnp.sum(jnp.abs(pen_mask * B))
            return 0.5 * dev_fn(B) / n_obs + penal

        # class priors as the intercept init (the null model)
        pri = np.zeros(K, dtype=np.float32)
        for k in range(K):
            pri[k] = float(jnp.sum((data.y == k) * data.w)) / n_obs
        B = jnp.zeros((Pn, K)).at[Pn - 1].set(
            jnp.log(jnp.clip(jnp.asarray(pri), 1e-8, None)))
        null_dev = float(dev_fn(B))

        if p.solver in ("IRLSM", "COORDINATE_DESCENT"):
            # cyclic per-class Fisher scoring: class k's working
            # (wk, z) from the current softmax, one distributed Gram
            # solve per class per sweep (the reference's multinomial
            # IRLSM; the cross-class Hessian blocks are dropped, which
            # is exactly its block-diagonal approximation)
            prev = null_dev
            it = 0
            for it in range(1, p.max_iterations + 1):
                require_healthy()   # fail fast on a dead mesh (§5.3)
                for k in range(K):
                    wk, z = _softmax_irls_task(Xe, B, yw, k, mesh)
                    G, b = _gram_task(Xe, wk, z, data.w, mesh)
                    G = G / n_obs
                    b = b / n_obs
                    B = B.at[:, k].set(
                        _solve_gram(G, b, B[:, k], lam_l1, lam_l2,
                                    p.solver))
                v = float(dev_fn(B))
                history.append({"iteration": it, "lambda": lam,
                                "deviance": v})
                if abs(prev - v) < p.objective_epsilon * \
                        (abs(prev) + 1e-10):
                    prev = v
                    break
                prev = v
            dev = prev
        else:
            opt = optax.lbfgs()
            state = opt.init(B)
            value_and_grad = jax.value_and_grad(obj)

            @jax.jit
            def step(B, state):
                value, grad = value_and_grad(B)
                updates, state = opt.update(grad, state, B, value=value,
                                            grad=grad, value_fn=obj)
                return optax.apply_updates(B, updates), state, value

            prev, it = np.inf, 0
            for it in range(1, p.max_iterations + 1):
                require_healthy()   # fail fast on a dead mesh (§5.3)
                B, state, value = step(B, state)
                v = float(value)
                history.append({"iteration": it, "lambda": lam,
                                "objective": v})
                if abs(prev - v) < p.objective_epsilon * \
                        (abs(prev) + 1e-10):
                    break
                prev = v
            dev = float(dev_fn(B))

        model = GLMModel(data, p, dinfo, B, lam, null_dev, dev, it)
        model.scoring_history = history
        from .cv import finalize_train

        return finalize_train(
            self, model, y, training_frame,
            {"x": x, "ignored_columns": ignored_columns,
             "weights_column": weights_column},
            validation_frame)

    def _fit_lbfgs(self, Xe, data, dinfo, lam, beta0, mesh,
                   history=None):
        import optax

        p = self.params
        fam = _famspec(p)
        n_obs = float(jnp.sum(data.w))
        lam_l2 = lam * (1 - p.alpha)
        lam_l1 = lam * p.alpha
        Pn = dinfo.n_expanded
        pen_mask = jnp.ones(Pn).at[Pn - 1].set(0.0)
        yw = _ywo(data)

        def obj(beta):
            def body(xs, yws, b):
                eta = xs @ b + yws[:, 2]
                mu = _linkinv(fam, eta)
                return lax.psum(
                    _family_deviance(fam, yws[:, 0], mu, yws[:, 1]),
                    ROWS)

            dev = jax.shard_map(body, mesh=mesh,
                                in_specs=(P(ROWS), P(ROWS), P()),
                                out_specs=P())(Xe, yw, beta)
            penal = 0.5 * lam_l2 * jnp.sum((pen_mask * beta) ** 2) + \
                lam_l1 * jnp.sum(jnp.abs(pen_mask * beta))  # subgradient
            return 0.5 * dev / n_obs + penal

        opt = optax.lbfgs()
        state = opt.init(beta0)
        beta = beta0
        value_and_grad = jax.jit(jax.value_and_grad(obj))

        @jax.jit
        def step(beta, state):
            value, grad = value_and_grad(beta)
            updates, state = opt.update(
                grad, state, beta, value=value, grad=grad,
                value_fn=obj)
            return optax.apply_updates(beta, updates), state, value

        prev = np.inf
        it = 0
        for it in range(1, p.max_iterations + 1):
            require_healthy()   # fail fast on a dead mesh (§5.3)
            beta, state, value = step(beta, state)
            v = float(value)
            if history is not None:
                history.append({"iteration": len(history) + 1,
                                "lambda": lam, "objective": v})
            if abs(prev - v) < p.objective_epsilon * (abs(prev) + 1e-10):
                break
            prev = v
        dev = float(_eta_dev_task(Xe, beta, yw, fam, mesh)[0])
        return beta, dev, it
