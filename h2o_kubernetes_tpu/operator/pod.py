"""Scorer-pool replica entry: ``python -m h2o_kubernetes_tpu.operator.pod``.

The pod the reconciler provisions: the existing rest.py serving stack
(micro-batcher, admission queue, breaker, SIGTERM drain — PR 2/4)
plus the two replica-specific pieces:

- the **model-registry readiness gate**: ``/readyz`` stays 503 until
  an artifact has been pushed over ``POST /3/ModelRegistry/load`` AND
  its pow2 batch buckets pre-traced (``Model.warm_up``) — a Service
  can never route traffic to a replica that would compile on its
  first request;
- the **persistent XLA compile cache** is enabled up front, so the
  warm-up traces of replica N+1 are disk hits from replica N's
  compiles instead of fresh multi-second compiles.

``/healthz`` is live from server start (the reconciler uses it as the
"process is up, push the artifact now" signal); SIGTERM runs the PR-4
drain (flush in-flight scoring, settle jobs, exit 0) inside
``H2O_TPU_DRAIN_TIMEOUT``.
"""

from __future__ import annotations

import argparse
import os
import sys
import time


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--port", type=int, required=True)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--pool", default=None,
                    help="owning pool name (identity on /3/Stats)")
    ap.add_argument("--rid", default=None,
                    help="replica id assigned by the reconciler")
    ap.add_argument("--manifest", default=None,
                    help="pid/port manifest path — rewritten with "
                    "this process's authoritative pid so a restarted "
                    "operator can adopt the pod (it also marks this "
                    "pod ADOPTABLE to the run_tests preflight reaper)")
    args = ap.parse_args(argv)

    # replica identity BEFORE any jax/package import reads env
    os.environ.setdefault("H2O_TPU_POOL_REPLICA", "1")
    from ..runtime.backend import enable_persistent_compile_cache

    # threshold 0: tenant models compile in well under the default
    # 0.5 s on CPU, and the byte-budget cache's evict→promote contract
    # needs EVERY serving compile persisted so a promotion is a disk
    # hit, never a cold compile (H2O_TPU_PCACHE_MIN_SECS overrides;
    # parsed tolerantly — a typo'd knob must not crash-loop every
    # replica the reconciler spawns)
    try:
        mcs = float(os.environ.get("H2O_TPU_PCACHE_MIN_SECS") or 0.0)
    except ValueError:
        mcs = 0.0
    enable_persistent_compile_cache(min_compile_secs=mcs)
    from ..runtime import lifecycle, make_mesh, set_global_mesh

    set_global_mesh(make_mesh())
    from .. import rest

    rest.install_pool_replica_gate()
    # identity fields on /3/Stats: the adoption probe of a restarted
    # operator verifies pool/rid/pid before trusting a manifest —
    # a recycled port cannot masquerade as this replica
    rest.IDENTITY.update({
        "pool": args.pool, "replica": args.rid,
        "pid": os.getpid(), "port": args.port,
        "started_at": time.time()})
    if args.manifest:
        # rewrite the controller-dropped manifest with the pid this
        # process actually has (authoritative), atomically
        import json

        doc = {"rid": args.rid, "pool": args.pool,
               "pid": os.getpid(), "port": args.port,
               "created_at": time.time()}
        try:
            with open(args.manifest) as f:
                old = json.load(f)
            for k in ("version", "model_key"):
                if k in old:
                    doc[k] = old[k]
        except (OSError, ValueError):
            pass
        os.makedirs(os.path.dirname(args.manifest), exist_ok=True)
        tmp = args.manifest + f".pod{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, args.manifest)
    rest.start_server(args.port, host=args.host, background=True,
                      install_signals=True)
    print(f"POD_UP port={args.port} pid={os.getpid()}", flush=True)
    # sleep is signal-interruptible; the SIGTERM drain thread
    # os._exit(0)s when the drain completes, so this loop only ends
    # via terminated() on an in-process drain
    while not lifecycle.terminated():
        time.sleep(0.2)
    return 0


if __name__ == "__main__":
    sys.exit(main())
