"""Operator-provisioned scorer fleets — the layer above the pod.

The source project IS a Kubernetes operator that provisions H2O
clusters (PAPER.md §1a); PRs 2 and 4 built the single-pod serving
primitives (flattened MOJO-v2 scorer + jitted cache, lifecycle/
breaker/drain), and this package is the controller that turns those
pods into a FLEET:

- ``spec``      — the ``H2OScorerPool`` spec model + a dict-backed
  in-process "API server" (``PoolStore``): spec generations, status,
  and a bounded event log — the CRD/etcd analog, swappable for a real
  kubeconfig-backed store later without touching the reconciler.
- ``registry``  — the model registry: versioned MOJO-v2 artifacts
  persisted through persist.py backends, pushed to replicas over
  ``POST /3/ModelRegistry/load``, and a jitted ``FlatTreeScorer``
  built from the flat arrays so a replica serves WITHOUT the training
  stack.
- ``reconcile`` — the level-triggered reconcile loop: observes real
  subprocess pods (the rest.py serving entry with its own lifecycle
  state machine), converges observed state to spec on replica death,
  spec resize, and artifact change, and rolls artifact updates
  surge-one with warm-up-gated readiness (zero 5xx under load).
- ``autoscale`` — the horizontal scale signal derived from each
  replica's admission-queue depth / shed / deadline counters scraped
  off ``GET /3/Stats``.
- ``pod``       — the replica entry point
  (``python -m h2o_kubernetes_tpu.operator.pod --port N``): mesh +
  persistent XLA cache + the model-registry readiness gate + the
  SIGTERM drain path.
- ``store``     — ``DurablePoolStore``: the persist.py-backed
  PoolStore (atomic JSON per pool, generation-fenced writes) that
  makes the control plane RESTARTABLE — specs, status, rollout state
  and events survive operator death.
- ``run``       — the operator process entry
  (``python -m h2o_kubernetes_tpu.operator.run``): durable store +
  reconciler + pod ADOPTION on restart (live pods found via workdir
  manifests are identity-probed over /3/Stats and inherited, never
  duplicated).
- ``placement`` — rendezvous-hash tenant placement with
  popularity-aware replication (Zipf head on every shard, tail on
  ``tail_replicas``); pure math, stability pinned by property tests.
- ``router``    — the device-free front-door scoring router over a
  sharded fleet: health-swept failover, per-tenant retry budgets with
  Retry-After honoring, optional hedged dispatch, and the typed
  ``placement_pending`` degraded 503.
- ``probe``     — THE replica scrape helper (probe timeout + 3
  attempts before unhealthy) shared by the reconciler's adoption/
  autoscale scrapes and the router's health sweeps.

docs/OPERATOR.md documents the spec schema, reconcile semantics, the
rolling-update contract, and the autoscale signal; tools/chaos.py's
``rolling-update`` and ``replica-kill`` drills rehearse the whole
stack end to end.
"""

from .placement import (PlacementPlan, move_destination,
                        plan_placement, shard_preference)
from .registry import FlatTreeScorer, ModelRegistry, load_artifact
from .reconcile import (AdoptedReplica, Reconciler, ScorerReplica,
                        ShardedPool)
from .router import ScoringRouter, StoreRoutingTable, start_router
from .spec import PoolStore, ScorerPoolSpec, StaleGenerationError
from .store import DurablePoolStore

__all__ = ["ScorerPoolSpec", "PoolStore", "DurablePoolStore",
           "StaleGenerationError", "ModelRegistry", "FlatTreeScorer",
           "load_artifact", "Reconciler", "ScorerReplica",
           "AdoptedReplica", "ShardedPool", "PlacementPlan",
           "plan_placement", "shard_preference", "move_destination",
           "ScoringRouter", "StoreRoutingTable", "start_router"]
