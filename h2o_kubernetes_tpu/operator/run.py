"""Operator process entry: ``python -m h2o_kubernetes_tpu.operator.run``.

The control plane as its own process (what a Deployment would run):
one durable-store-backed Reconciler per pool, reconciling until
SIGTERM. Because the store is durable and replicas drop pid/port
manifests under the workdir, this process is RESTARTABLE: SIGKILL it
mid-rollout, start a fresh one against the same ``--store``/
``--workdir``, and it adopts the live pods, then finishes (or rolls
back) the rollout — the data plane never notices. The
``operator-restart`` chaos drill rehearses exactly that.

Usage::

    python -m h2o_kubernetes_tpu.operator.run \
        --store /var/h2o/poolstore --registry /var/h2o/registry \
        --pool churn-pool --workdir /var/h2o/pools/churn-pool

SIGTERM = graceful: stop reconciling, drain every replica (the PR-4
pod drain path), exit 0. SIGKILL = crash: pods keep serving (own
sessions), manifests stay, the next operator adopts them.
``--leave-pods`` makes SIGTERM leave the data plane running too
(operator handoff: retire THIS controller, keep the fleet).

``--ha`` runs the lease-fenced high-availability mode: N replicas of
this process share one ``--store``/``--workdir``; exactly one (the
``<pool>.lease.json`` holder) reconciles and publishes the routing
table, the others poll the lease as hot standbys. The holder
heartbeats every ``H2O_TPU_LEASE_HEARTBEAT``; standbys take over
within ``H2O_TPU_LEASE_TTL`` of holder death (SIGKILL the holder and
watch), adopt the surviving pods, and RESUME whatever the dead holder
was mid-way through — a rollout continues, it does not restart. A
deposed holder (paused, partitioned, renewal missed) stops
reconciling the moment its fenced writes start bouncing and returns
to standby; its pods are never killed, just inherited.
"""

from __future__ import annotations

import argparse
import os
import signal
import sys
import threading


def _lease_ttl() -> float:
    from ..runtime.retry import _env_float

    return max(0.5, _env_float("H2O_TPU_LEASE_TTL", 5.0))


def _lease_heartbeat(ttl: float) -> float:
    from ..runtime.retry import _env_float

    hb = _env_float("H2O_TPU_LEASE_HEARTBEAT", 0.0)
    return hb if hb > 0.0 else max(0.1, ttl / 3.0)


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--store", required=True,
                    help="DurablePoolStore root (dir or mem://)")
    ap.add_argument("--registry", required=True,
                    help="ModelRegistry root")
    ap.add_argument("--pool", required=True)
    ap.add_argument("--workdir", required=True,
                    help="pool workdir: pod manifests + logs")
    ap.add_argument("--interval", type=float, default=None,
                    help="reconcile interval override (else "
                    "H2O_TPU_POOL_RECONCILE_INTERVAL)")
    ap.add_argument("--leave-pods", action="store_true",
                    help="on SIGTERM, exit WITHOUT draining replicas "
                    "(handoff to a successor operator)")
    ap.add_argument("--ha", action="store_true",
                    help="lease-fenced HA mode: run as one of N "
                    "operator replicas; only the lease holder "
                    "reconciles (ShardedPool control plane)")
    ap.add_argument("--holder-id", default=None,
                    help="lease holder identity (--ha; default "
                    "host-pid)")
    ap.add_argument("--status-port", type=int, default=None,
                    help="bind a tiny /metrics + /healthz listener on "
                    "this port (0 = ephemeral; default: "
                    "H2O_TPU_METRICS_PORT, unset/empty = no listener) "
                    "— the operator's Prometheus scrape surface")
    args = ap.parse_args(argv)

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from .reconcile import Reconciler
    from .registry import ModelRegistry
    from .store import DurablePoolStore

    store = DurablePoolStore(args.store)
    rec = Reconciler(store, ModelRegistry(args.registry), args.pool,
                     workdir=args.workdir)
    stop = threading.Event()

    # status listener: the operator's own /metrics scrape surface
    # (reconcile event counters, build info) — the control plane is a
    # fleet member too, and fleet_top scrapes it like any replica
    status_port = args.status_port
    if status_port is None:
        raw = os.environ.get("H2O_TPU_METRICS_PORT")
        if raw:
            try:
                status_port = int(raw)
            except ValueError:
                print(f"OPERATOR_BAD_METRICS_PORT {raw!r} (ignored)",
                      flush=True)
    status_srv = None
    if status_port is not None:
        from ..runtime.telemetry import start_status_listener

        def _operator_groups():
            try:
                return {"operator": {
                    "pool": args.pool,
                    "status": store.get_status(args.pool) or {}}}
            except Exception:  # noqa: BLE001 — scrape must survive
                return None

        status_srv = start_status_listener(
            status_port, extra_groups=_operator_groups)
        print(f"OPERATOR_METRICS port="
              f"{status_srv.server_address[1]}", flush=True)

    def _sigterm(signum, frame):
        stop.set()

    signal.signal(signal.SIGTERM, _sigterm)
    signal.signal(signal.SIGINT, _sigterm)

    # the store file is the API wire: starting the operator BEFORE a
    # client applies the pool spec is a supported ordering — wait for
    # the spec instead of crashing on a missing pool
    while not stop.is_set():
        try:
            store.get(args.pool)
            break
        except KeyError:
            print(f"OPERATOR_WAITING pool={args.pool} (no spec yet)",
                  flush=True)
            stop.wait(1.0)
    if stop.is_set():
        return 0
    if args.ha:
        rc = _run_ha(args, store, stop)
    else:
        adopted = rec.adopt_existing()
        print(f"OPERATOR_UP pool={args.pool} pid={os.getpid()} "
              f"adopted={adopted}", flush=True)
        rec.run(stop, interval=args.interval)
        if not args.leave_pods:
            rec.shutdown()
        rc = 0
    if status_srv is not None:
        status_srv.shutdown()
        status_srv.server_close()
    print("OPERATOR_DOWN", flush=True)
    return rc


def _run_ha(args, store, stop: threading.Event) -> int:
    """The lease loop: standby-poll -> hold (reconcile + heartbeat) ->
    deposed-or-stopped. Deposition leaves every pod running — the new
    holder adopts them off their manifests; only a user SIGTERM while
    HOLDING drains the fleet (unless --leave-pods)."""
    import socket

    from .reconcile import ShardedPool
    from .registry import ModelRegistry

    holder = args.holder_id or f"{socket.gethostname()}-{os.getpid()}"
    registry = ModelRegistry(args.registry)
    ttl = _lease_ttl()
    heartbeat = _lease_heartbeat(ttl)
    print(f"OPERATOR_HA pool={args.pool} holder={holder} "
          f"ttl={ttl:g} heartbeat={heartbeat:g}", flush=True)
    while not stop.is_set():
        epoch = store.acquire_lease(args.pool, holder, ttl)
        if epoch is None:
            stop.wait(heartbeat)        # hot standby: poll the lease
            continue
        print(f"OPERATOR_LEASE_ACQUIRED pool={args.pool} "
              f"holder={holder} epoch={epoch}", flush=True)
        ctl = ShardedPool(store, registry, args.pool,
                          workdir=args.workdir)
        ctl.lease_epoch = epoch
        ctl_stop = threading.Event()
        t = threading.Thread(target=ctl.run, args=(ctl_stop,),
                             kwargs={"interval": args.interval},
                             name="h2o-ha-reconcile", daemon=True)
        t.start()
        deposed = False
        while not stop.is_set():
            stop.wait(heartbeat)
            if stop.is_set():
                break
            if ctl.deposed or not store.renew_lease(
                    args.pool, holder, epoch):
                deposed = True
                break
        ctl_stop.set()
        t.join(timeout=30.0)
        if deposed:
            # back to standby with the pods untouched; the reconcile
            # thread already stopped (fence or renewal failure)
            ctl.deposed = True
            print(f"OPERATOR_DEPOSED pool={args.pool} "
                  f"holder={holder} epoch={epoch}", flush=True)
            continue
        # user-initiated stop while holding: hand the lease back so a
        # standby takes over on its next poll, not after a TTL
        store.release_lease(args.pool, holder)
        if not args.leave_pods:
            ctl.shutdown()
        return 0
    return 0


if __name__ == "__main__":
    sys.exit(main())
