"""H2OScorerPool spec model + the dict-backed in-process API server.

The reference operator watches an ``H2O`` CRD in the kube API server
and reconciles StatefulSets against it (SURVEY.md §5.6); here the
"API server" is an in-process, thread-safe store with the same
observable semantics — specs carry a monotonically increasing
``generation``, status is written by the controller, and events are a
bounded log — so the reconciler is written against an interface a
kubeconfig-backed store can implement later without changing it.
"""

from __future__ import annotations

import collections
import contextlib
import json
import threading
import time
from dataclasses import dataclass, field, replace

__all__ = ["ScorerPoolSpec", "PoolStore", "StaleGenerationError"]


class StaleGenerationError(RuntimeError):
    """A fenced write carried a generation that is no longer current —
    the writer is a stale controller (or raced another apply) and its
    view of the spec must not overwrite the newer one."""


@dataclass(frozen=True)
class ScorerPoolSpec:
    """Declarative spec of one scorer pool (the CRD analog).

    ``artifact``/``version`` name a model-registry artifact
    (registry.publish's name + version); ``model_key`` is the stable
    REST key replicas serve it under — it stays the same across
    versions so client URLs survive rolling updates.
    """

    name: str                      # pool name (store key)
    artifact: str                  # registry artifact name
    version: int                   # pinned artifact version (rolls on change)
    model_key: str = "model"       # MODELS key on every replica
    replicas: int = 1              # desired serving replicas
    min_replicas: int = 1          # autoscale floor
    max_replicas: int = 8          # autoscale ceiling
    autoscale: bool = False        # reconciler adjusts `replicas` itself
    # pow2 batches pre-traced before readyz; None = let each REPLICA
    # resolve H2O_TPU_POOL_WARM_BUCKETS (default 128,1024) — pinning a
    # tuple here overrides the env knob for this pool
    warm_buckets: tuple | None = None
    # default SLO class for the primary artifact's traffic (rest.py
    # SLO_CLASSES; None = the replica's H2O_TPU_SLO_DEFAULT)
    slo: str | None = None
    # multi-tenant pools: extra (artifact, version, model_key[, slo])
    # tuples pushed to EVERY replica alongside the primary — /readyz
    # holds until ALL of them are loaded + warmed (the replica's
    # required-model readiness set is declared before the first push).
    # The PRIMARY artifact/version still drives rolling updates; a
    # changed extra artifact rides the next primary version bump.
    extra_artifacts: tuple = ()
    # tenant sharding (operator/placement.py + ShardedPool): >1 splits
    # the catalog across this many shard groups (each `replicas` wide)
    # via rendezvous hashing instead of pushing everything everywhere.
    # The catalog order (primary first, then extra_artifacts) is the
    # POPULARITY rank: the first `head_models` keys are replicated on
    # every shard (instant router failover for the Zipf head), the
    # tail lands on exactly `tail_replicas` shards. shards == 1 is the
    # legacy everyone-has-everything pool, bit-for-bit.
    shards: int = 1
    head_models: int = 1           # catalog prefix replicated everywhere
    tail_replicas: int = 1         # shards per tail tenant
    env: dict = field(default_factory=dict)   # extra pod env overrides

    def validate(self) -> "ScorerPoolSpec":
        if not self.name or not self.artifact or not self.model_key:
            raise ValueError("pool spec needs name, artifact and "
                             "model_key")
        if self.version < 1:
            raise ValueError(f"version must be >= 1, got {self.version}")
        if self.replicas < 0:
            raise ValueError(f"replicas must be >= 0, got "
                             f"{self.replicas}")
        if not (1 <= self.min_replicas <= self.max_replicas):
            raise ValueError(
                f"need 1 <= min_replicas ({self.min_replicas}) <= "
                f"max_replicas ({self.max_replicas})")
        if self.warm_buckets is not None and not self.warm_buckets:
            raise ValueError("warm_buckets must name at least one "
                             "batch bucket, or be None to defer to "
                             "the replica's H2O_TPU_POOL_WARM_BUCKETS")
        # SLO classes validate at APPLY time: a typo'd class would
        # otherwise pass here and 400 on every replica's artifact
        # push — the pool wedging in a replace loop instead of the
        # spec being rejected (validate()'s whole job)
        from ..rest import SLO_CLASSES

        def _check_slo(slo, where):
            if slo is not None and slo not in SLO_CLASSES:
                raise ValueError(
                    f"unknown SLO class {slo!r} for {where} "
                    f"(known: {', '.join(sorted(SLO_CLASSES))})")

        _check_slo(self.slo, "the primary artifact")
        keys = [self.model_key]
        for ent in self.extra_artifacts:
            ent = tuple(ent)
            if len(ent) not in (3, 4) or not ent[0] or not ent[2]:
                raise ValueError(
                    "extra_artifacts entries must be (artifact, "
                    f"version, model_key[, slo]) tuples, got {ent!r}")
            if int(ent[1]) < 1:
                raise ValueError(
                    f"extra artifact {ent[0]!r} version must be >= 1")
            if len(ent) > 3:
                _check_slo(ent[3], f"extra artifact {ent[0]!r}")
            keys.append(ent[2])
        if len(set(keys)) != len(keys):
            raise ValueError(
                f"duplicate model_key across the pool's artifacts: "
                f"{sorted(k for k in set(keys) if keys.count(k) > 1)}")
        if self.shards < 1:
            raise ValueError(f"shards must be >= 1, got {self.shards}")
        if not (1 <= self.tail_replicas <= max(1, self.shards)):
            raise ValueError(
                f"need 1 <= tail_replicas ({self.tail_replicas}) <= "
                f"shards ({self.shards})")
        if not (0 <= self.head_models <= len(keys)):
            raise ValueError(
                f"head_models ({self.head_models}) must be within the "
                f"catalog (0..{len(keys)})")
        if self.shards > 1 and self.head_models < 1:
            # every shard's child pool needs the primary artifact (it
            # is the rank-1 head by the catalog-order convention), so
            # a sharded pool replicates at least the primary
            raise ValueError("a sharded pool needs head_models >= 1 "
                             "(the primary model is the rank-1 head "
                             "and lives on every shard)")
        return self

    def all_artifacts(self) -> list[tuple]:
        """Every (artifact, version, model_key, slo) a replica must
        serve, primary first — the push list AND the required-model
        readiness set."""
        items = [(self.artifact, int(self.version), self.model_key,
                  self.slo)]
        for ent in self.extra_artifacts:
            ent = tuple(ent)
            items.append((ent[0], int(ent[1]), ent[2],
                          ent[3] if len(ent) > 3 else None))
        return items


_EVENT_CAP = 256        # bounded: a flapping pool must not grow memory


class PoolStore:
    """Thread-safe dict-backed spec/status/event store (etcd analog).

    Writes accept an optional ``fence`` — the generation the writer
    last observed. A fenced write whose generation is no longer
    current raises :class:`StaleGenerationError` instead of landing
    (optimistic concurrency, the resourceVersion-precondition analog):
    a controller that kept running against an old store snapshot, or
    a second operator racing the first, cannot clobber newer state.
    Subclasses persist by overriding ``_persist``/``_forget`` (called
    under the store lock, so snapshots are never torn)."""

    def __init__(self):
        # RLock: _persist hooks run inside mutators while the lock is
        # held, and a durable subclass may re-read state to snapshot
        self._lock = threading.RLock()
        self._specs: dict[str, ScorerPoolSpec] = {}
        self._gens: dict[str, int] = {}
        self._status: dict[str, dict] = {}
        self._events: dict[str, collections.deque] = {}
        self._routing: dict[str, dict] = {}
        self._leases: dict[str, dict] = {}

    # -- durability hooks (no-ops on the in-memory store) ---------------------
    #
    # Split by WRITER, the spec/status-subresource discipline: specs
    # are written by whoever applies them (client, autoscaler), status
    # + events only by the owning controller — so a durable subclass
    # can keep the two in separate files and a controller status write
    # can never clobber a concurrent client spec update.

    def _persist_spec(self, name: str) -> None:
        """Called under the lock after a spec mutation of `name`."""

    def _persist_state(self, name: str) -> None:
        """Called under the lock after a status/event mutation."""

    def _refresh(self, name: str) -> None:
        """Called under the lock before a read — a durable subclass
        re-reads disk so one process observes another's writes."""

    def _forget(self, name: str) -> None:
        """Called under the lock after `name` is deleted."""

    def _persist_routing(self, name: str) -> None:
        """Called under the lock after a routing-table publish."""

    def _persist_lease(self, name: str) -> None:
        """Called under the lock after a lease mutation (a cleared
        lease persists as 'gone', not as a stale document)."""

    def _lease_guard(self, name: str):
        """Context manager serializing lease read-decide-write cycles
        ACROSS store instances. The in-memory store has exactly one
        instance per universe, so ``self._lock`` already suffices; a
        durable subclass shared by N processes must override this with
        a cross-process lock (flock) or the read-then-bump in
        ``acquire_lease`` races between two expired-lease claimants."""
        return contextlib.nullcontext()

    def _check_fence(self, name: str, fence: int | None) -> None:
        if fence is not None and fence != self._gens.get(name, 0):
            raise StaleGenerationError(
                f"pool '{name}': write fenced at generation {fence} "
                f"but the store is at {self._gens.get(name, 0)} — "
                "stale controller write rejected")

    # -- spec (the declarative side) ------------------------------------------

    def apply(self, spec: ScorerPoolSpec, fence: int | None = None,
              **updates) -> int:
        """Create or update a pool spec; field updates may be passed as
        kwargs against the stored spec (``store.apply(spec)`` or
        ``store.apply_update(name, replicas=3)`` style). Returns the
        new generation. No-op updates still bump the generation — the
        reconciler is level-triggered, so that is harmless. ``fence``
        makes the write conditional on the observed generation."""
        spec = replace(spec, **updates).validate() if updates \
            else spec.validate()
        with self._lock:
            self._refresh(spec.name)
            self._check_fence(spec.name, fence)
            self._specs[spec.name] = spec
            self._gens[spec.name] = self._gens.get(spec.name, 0) + 1
            self._persist_spec(spec.name)
            return self._gens[spec.name]

    def apply_update(self, name: str, fence: int | None = None,
                     **updates) -> int:
        with self._lock:
            self._refresh(name)
            cur = self._specs.get(name)
            if cur is None:
                raise KeyError(f"no pool '{name}'")
            return self.apply(replace(cur, **updates), fence=fence)

    def get(self, name: str) -> tuple[ScorerPoolSpec, int]:
        with self._lock:
            self._refresh(name)
            if name not in self._specs:
                raise KeyError(f"no pool '{name}'")
            return self._specs[name], self._gens[name]

    def pools(self) -> list[str]:
        with self._lock:
            return sorted(self._specs)

    def delete(self, name: str) -> None:
        with self._lock:
            self._specs.pop(name, None)
            self._gens.pop(name, None)
            self._status.pop(name, None)
            self._events.pop(name, None)
            self._routing.pop(name, None)
            self._leases.pop(name, None)
            self._forget(name)

    # -- status + events (the observed side) ----------------------------------

    def set_status(self, name: str, status: dict,
                   fence: int | None = None) -> None:
        with self._lock:
            self._refresh(name)
            self._check_fence(name, fence)
            self._status[name] = dict(status)
            self._persist_state(name)

    def get_status(self, name: str) -> dict:
        with self._lock:
            self._refresh(name)
            return dict(self._status.get(name, {}))

    def record_event(self, name: str, kind: str, msg: str = "") -> None:
        """Append one operator event (bounded ring; the drill
        acceptance reads the replica_died → replica_start →
        replica_ready sequence out of this)."""
        ev = {"t": time.time(), "kind": kind, "msg": msg}
        with self._lock:
            # refresh-then-append: the durable write below persists
            # the WHOLE state doc, so appending onto a stale cache
            # would clobber status/events another process wrote since
            # our last read (single state-writer is the design, but a
            # handoff window must merge, not overwrite)
            self._refresh(name)
            dq = self._events.setdefault(
                name, collections.deque(maxlen=_EVENT_CAP))
            dq.append(ev)
            self._persist_state(name)

    def events(self, name: str) -> list[dict]:
        with self._lock:
            self._refresh(name)
            return list(self._events.get(name, ()))

    # -- routing table (the front-door side) ----------------------------------
    #
    # The sharded controller publishes its routing table through the
    # store so N stateless routers can serve from one source of truth
    # (<pool>.routing.json on a durable root — controller-written,
    # router-read, same single-writer discipline as the state file).
    # ``table_generation`` is store-owned and monotonic, and bumps
    # ONLY when the table content changes: routers reject regressions
    # (a stale controller can never roll a newer table back), and an
    # unchanged republish every reconcile pass costs no churn.

    def publish_routing(self, name: str, table: dict,
                        epoch: int | None = None) -> int:
        """Publish the controller's routing table; returns the
        ``table_generation`` now current. ``epoch`` is the writer's
        lease epoch: when the pool's lease has moved past it, the
        writer was deposed and the publish raises
        :class:`StaleGenerationError` (split-brain fence — a new
        holder's takeover bumps the epoch, so the old holder's queued
        tables lose deterministically, never merge)."""
        # JSON-normalize so the content compare is stable across the
        # durable round-trip (tuples become lists, key order sorts)
        body = json.loads(json.dumps(
            {k: v for k, v in dict(table).items()
             if k != "table_generation"}, sort_keys=True))
        with self._lock:
            self._refresh(name)
            if epoch is not None:
                lease = self._leases.get(name)
                if lease is not None and \
                        int(lease.get("epoch", 0)) > int(epoch):
                    raise StaleGenerationError(
                        f"pool '{name}': routing publish fenced at "
                        f"lease epoch {epoch} but the lease is at "
                        f"epoch {lease.get('epoch')} — deposed "
                        "controller write rejected")
            cur = self._routing.get(name)
            if cur is not None:
                if {k: v for k, v in cur.items()
                        if k != "table_generation"} == body:
                    return int(cur["table_generation"])
            gen = (int(cur["table_generation"]) + 1) if cur else 1
            self._routing[name] = {"table_generation": gen, **body}
            self._persist_routing(name)
            return gen

    def get_routing(self, name: str) -> dict | None:
        """The last published routing doc (with ``table_generation``),
        or None if nothing was ever published."""
        with self._lock:
            self._refresh(name)
            doc = self._routing.get(name)
            return json.loads(json.dumps(doc)) if doc is not None \
                else None

    # -- controller lease (the HA side) ---------------------------------------
    #
    # A wall-clock TTL lease elects exactly one reconciling controller
    # out of N ``operator.run`` replicas. The epoch bumps on every
    # ownership change (takeover OR expired re-acquire), and doubles
    # as the write fence for publish_routing above: holding the lease
    # file is advisory, holding a CURRENT epoch is what lets writes
    # land — so a paused/partitioned holder that misses its heartbeat
    # window is structurally deposed, not just presumed dead.

    @staticmethod
    def _lease_expired(lease: dict, now: float) -> bool:
        return now - float(lease.get("renewed", 0.0)) > \
            float(lease.get("ttl", 0.0))

    def acquire_lease(self, name: str, holder: str,
                      ttl: float) -> int | None:
        """Try to take (or keep) the controller lease. Returns the
        lease epoch on success; None while another holder's lease is
        still live. Re-acquiring one's own live lease renews it
        without an epoch bump; claiming an expired lease bumps it."""
        now = time.time()
        with self._lease_guard(name):
            with self._lock:
                self._refresh(name)
                cur = self._leases.get(name)
                if cur is not None and not self._lease_expired(cur, now):
                    if cur.get("holder") != holder:
                        return None
                    self._leases[name] = dict(cur, renewed=now,
                                              ttl=float(ttl))
                    self._persist_lease(name)
                    return int(cur["epoch"])
                epoch = (int(cur.get("epoch", 0)) + 1) if cur else 1
                self._leases[name] = {
                    "holder": holder, "epoch": epoch,
                    "ttl": float(ttl), "renewed": now, "acquired": now}
                self._persist_lease(name)
                return epoch

    def renew_lease(self, name: str, holder: str, epoch: int) -> bool:
        """Heartbeat. Strict: False when the lease expired, changed
        hands, or the epoch moved — the caller must stop reconciling
        immediately (its routing writes are already fenced off)."""
        now = time.time()
        with self._lease_guard(name):
            with self._lock:
                self._refresh(name)
                cur = self._leases.get(name)
                if (cur is None or cur.get("holder") != holder
                        or int(cur.get("epoch", 0)) != int(epoch)
                        or self._lease_expired(cur, now)):
                    return False
                self._leases[name] = dict(cur, renewed=now)
                self._persist_lease(name)
                return True

    def get_lease(self, name: str) -> dict | None:
        with self._lock:
            self._refresh(name)
            doc = self._leases.get(name)
            return dict(doc) if doc is not None else None

    def release_lease(self, name: str, holder: str) -> None:
        """Voluntary handoff (clean shutdown): clears the lease so a
        standby takes over on its next poll instead of waiting out the
        TTL. Only the current holder's release does anything."""
        with self._lease_guard(name):
            with self._lock:
                self._refresh(name)
                cur = self._leases.get(name)
                if cur is not None and cur.get("holder") == holder:
                    # keep the epoch (monotonic forever): dropping it
                    # would reset the fence and let a long-deposed
                    # holder's writes land again after a release
                    self._leases[name] = {
                        "epoch": int(cur.get("epoch", 0)),
                        "released": True, "ttl": 0.0, "renewed": 0.0}
                    self._persist_lease(name)
