"""Horizontal autoscale signal from per-replica serving stats.

The inputs are exactly what ``GET /3/Stats`` exposes per replica (the
PR-4 overload-control counters, previously process-local): the
admission queue's instantaneous depth, the cumulative load-shed (429)
count, and the deadline-expired (504) count. The policy is
deliberately simple and hysteresis-free at this layer — one step up
on pressure, one step down on proven idleness, clamped to the spec's
[min_replicas, max_replicas] — because the caller (the reconcile
loop) controls the cadence and can add cooldowns without changing the
signal.

Pressure (scale UP by 1) — any of:
- mean queue depth across replicas >= ``H2O_TPU_POOL_QUEUE_HIGH``
  (queued work means latency is already batch-window x depth);
- any load was shed since the previous scrape (a 429 is the queue
  saying "full" — more replicas is the only fix the operator owns);
- any request 504'd on its deadline since the previous scrape.

Idleness (scale DOWN by 1) — all of, since the previous scrape:
- every replica's queue depth is 0,
- zero shed and zero deadline 504s,
- zero new scoring requests (a pool serving ANY traffic holds its
  size — scale-down only reclaims truly idle replicas),
- and no counter went BACKWARDS since the last scrape: a negative
  delta means a replica restart or rolling update zeroed the
  cumulative counters, which is indistinguishable from idleness by
  delta alone — the pool holds for one scrape instead of shrinking
  under live traffic.
"""

from __future__ import annotations

from ..runtime.retry import _env_float
from .spec import ScorerPoolSpec

__all__ = ["desired_replicas", "pressure_by_model"]


def pressure_by_model(samples: list[dict],
                      model_keys: "set | None" = None) -> dict:
    """Cumulative shed + deadline-504 count PER TENANT across replicas
    (/3/Stats ``models``) — the hot-shard rebalance attribution
    signal: the same per-model counters the shard-aware autoscale
    reads, but kept per key so the controller can name WHICH tenant's
    pressure is sustained and move that one, not guess. ``model_keys``
    restricts to the shard's own placed tenants."""
    out: dict = {}
    for s in samples:
        for key, m in (s.get("models") or {}).items():
            if model_keys is not None and key not in model_keys:
                continue
            out[key] = out.get(key, 0) \
                + int(m.get("shed") or 0) \
                + int(m.get("deadline_504") or 0)
    return out


def _totals(samples: list[dict],
            model_keys: "set | None" = None) -> dict:
    """Pressure counters summed across replicas. ``model_keys``
    restricts the cumulative counters to THOSE tenants' per-model
    stats (/3/Stats ``models``) — the shard-aware signal: a sharded
    pool must scale the shard whose own tenants shed, and a re-placed
    tenant's burst must pull up the shard actually serving it, not
    every shard that happens to share a process-global counter."""
    t = {"shed": 0, "deadline_504": 0, "requests": 0}
    for s in samples:
        if model_keys is None:
            b = s.get("batcher") or {}
            c = s.get("counters") or {}
            t["shed"] += int(b.get("shed") or 0)
            t["deadline_504"] += int(c.get("deadline_504") or 0)
            t["requests"] += int(b.get("requests") or 0)
        else:
            for key, m in (s.get("models") or {}).items():
                if key not in model_keys:
                    continue
                t["shed"] += int(m.get("shed") or 0)
                t["deadline_504"] += int(m.get("deadline_504") or 0)
                t["requests"] += int(m.get("requests") or 0)
    return t


def desired_replicas(spec: ScorerPoolSpec, samples: list[dict],
                     prev_totals: dict | None = None,
                     model_keys: "set | None" = None
                     ) -> tuple[int, str, dict]:
    """(desired, reason, totals). ``samples`` are /3/Stats dicts from
    the READY replicas; pass the returned ``totals`` back as
    ``prev_totals`` next scrape so cumulative counters become rates.
    ``model_keys`` (a sharded pool's placed tenant set) attributes the
    cumulative pressure counters to the shard's own tenants. With no
    samples (pool still converging) the signal holds."""
    n = spec.replicas
    totals = _totals(samples, model_keys)
    if not samples:
        return n, "no ready replicas to scrape", totals
    lo, hi = spec.min_replicas, spec.max_replicas
    depths = [int((s.get("batcher") or {}).get("queue_depth") or 0)
              for s in samples]
    queue_high = max(1.0, _env_float("H2O_TPU_POOL_QUEUE_HIGH", 8.0))
    mean_depth = sum(depths) / len(depths)

    shed_d = d504_d = req_d = None
    reset = False
    if prev_totals is not None:
        shed_d = totals["shed"] - prev_totals.get("shed", 0)
        d504_d = totals["deadline_504"] \
            - prev_totals.get("deadline_504", 0)
        req_d = totals["requests"] - prev_totals.get("requests", 0)
        # a counter going BACKWARDS means a replica restarted (or a
        # rolling update swapped the fleet) since the last scrape —
        # the deltas say nothing about load this window. Pressure
        # signals still fire from the instantaneous queue depth, but
        # the idle scale-down must HOLD: zeroed counters on a fresh
        # fleet are indistinguishable from idleness by delta alone.
        reset = shed_d < 0 or d504_d < 0 or req_d < 0
        shed_d, d504_d, req_d = (max(0, shed_d), max(0, d504_d),
                                 max(0, req_d))

    if mean_depth >= queue_high:
        return (min(n + 1, hi),
                f"mean queue depth {mean_depth:.1f} >= "
                f"{queue_high:g}", totals)
    if shed_d:
        return min(n + 1, hi), f"{shed_d} requests shed (429)", totals
    if d504_d:
        return (min(n + 1, hi),
                f"{d504_d} deadline expiries (504)", totals)
    if (prev_totals is not None and not reset
            and max(depths, default=0) == 0
            and shed_d == 0 and d504_d == 0 and req_d == 0):
        return max(n - 1, lo), "pool idle since last scrape", totals
    if reset:
        return n, "counters reset (replica restart) — holding", totals
    return n, "holding", totals
