"""Device-free front-door scoring router over a tenant-sharded fleet.

The Service-with-a-brain the sharded catalog needs: clients keep one
URL and one verb (``POST /3/Predictions/models/{key}`` — the
``/contributions`` suffix rides along), the router resolves the key
through the placement table (``ShardedPool.routing_table()``: each
key's shard preference order — rendezvous order for the tail, every
shard for the Zipf head — plus each shard's live endpoints) and
forwards the request bytes. NO JAX anywhere on this path: the router
process never touches a device, so it can sit in front of the fleet
on the cheapest node there is.

It rides the rest.py machinery rather than reinventing it:
``JsonHttpHandler`` (same JSON/error/Retry-After shapes, same
drain-safe body discard), the ``X-H2O-Deadline-Ms`` contract (parsed
at the front door, the REMAINING budget forwarded so the replica's
batcher sees the client's true deadline), ``X-H2O-SLO`` passthrough,
and the lifecycle drain gate.

The failure half — what makes it a robustness layer, not a proxy:

- **health**: a background sweep reads every replica's ``/3/Stats``
  through the shared probe helper (operator/probe.py: probe timeout +
  3 attempts per sweep, so a scoring burst cannot flap a shard out of
  the ring); a shard serves iff it has a ready replica.
- **failover**: a replicated key whose preferred shard is down (or
  whose dispatch dies mid-flight) moves to the next shard in its
  preference order instantly.
- **retry budget**: every cross-shard retry consumes a token from the
  TENANT's bucket (``H2O_TPU_ROUTER_RETRY_BUDGET`` retries/s, burst =
  1 s; 0 disables retries) — a dying shard cannot amplify its load
  onto the survivors. Replica ``Retry-After`` is honored: a 503's
  cooldown takes the replica out of the candidate set for that long,
  and when the budget (or the candidate list) is exhausted the
  upstream response is relayed WITH its Retry-After so clients back
  off too. Budget accounting is on ``GET /3/Stats``: every granted
  token is counted as a retry at the grant itself, so ``retries`` ==
  ``retry_budget.granted`` holds structurally — hedges included.
- **hedging** (kill switch, default off): ``H2O_TPU_ROUTER_HEDGE_MS``
  arms speculative re-dispatch for the ``interactive`` SLO class —
  when the primary shard has not answered inside the hedge window, a
  second request goes to the next replica shard and the first answer
  wins. Hedges consume retry-budget tokens (they are load
  amplification too).
- **degraded mode**: a tail tenant whose every placed shard is down
  gets a TYPED 503 — ``hint: placement_pending`` — while the
  reconciler re-places its artifact onto a survivor; the routing
  table picks the re-placement up on the next sweep and the window
  closes without the client ever seeing a 5xx that lies about being
  retryable.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.request
from http.server import ThreadingHTTPServer

from ..runtime import lifecycle, telemetry
from ..runtime.retry import _env_float
from .probe import probe_json

__all__ = ["ScoringRouter", "StoreRoutingTable", "start_router"]


def _retry_budget_rate() -> float:
    """Per-tenant cross-shard retry budget, retries/second (burst = 1
    second of budget, min 1). 0 = no retries at all — every failure is
    relayed to the client on the first answer."""
    return max(0.0, _env_float("H2O_TPU_ROUTER_RETRY_BUDGET", 2.0))


def _hedge_ms() -> float:
    """Hedged-dispatch kill switch: 0/unset = off; > 0 arms
    speculative re-dispatch for `interactive` traffic after this many
    milliseconds without a primary answer."""
    return max(0.0, _env_float("H2O_TPU_ROUTER_HEDGE_MS", 0.0))


def _health_interval() -> float:
    return max(0.05, _env_float("H2O_TPU_ROUTER_HEALTH_INTERVAL", 0.5))


def _max_inflight() -> int:
    v = _env_float("H2O_TPU_ROUTER_MAX_INFLIGHT", 256.0)
    import sys

    return sys.maxsize if v <= 0 else max(1, int(v))


def _router_timeout() -> float:
    return max(0.1, _env_float("H2O_TPU_ROUTER_TIMEOUT", 30.0))


def _table_interval() -> float:
    """Extra throttle between STORE reads of the routing table; 0 =
    refresh on every health sweep (the default cadence)."""
    return max(0.0, _env_float("H2O_TPU_ROUTER_TABLE_INTERVAL", 0.0))


class StoreRoutingTable:
    """Store-backed routing-table provider: a zero-arg callable over
    the controller-published ``<pool>.routing.json`` that makes N
    ``start_router`` processes interchangeable — none of them holds
    the table, they all read the one the lease-holding controller
    writes.

    Invariants the front door depends on:

    - **monotonic**: a document whose ``table_generation`` is LOWER
      than the last one served is rejected (``stale_rejected``) — a
      deposed controller's file, or a lagging replica of the store,
      can never roll a router back to an older placement.
    - **last-good**: a store read failure (or a vanished document)
      serves the previous snapshot unchanged (``refresh_errors``) —
      store unavailability degrades table FRESHNESS, never request
      serving.
    - **cold**: before the first document ever lands, the provider
      returns an empty table marked ``cold`` so the router can answer
      a typed degraded 503 instead of 404 — it cannot know the
      catalog yet, so it must not claim a tenant does not exist.
    """

    def __init__(self, store, pool: str):
        self.store = store
        self.pool = pool
        self.generation = 0
        self.stats = {"refreshes": 0, "refresh_errors": 0,
                      "stale_rejected": 0}
        self._lock = threading.Lock()
        self._last: dict | None = None
        self._last_read = 0.0

    def __call__(self) -> dict:
        now = time.monotonic()
        with self._lock:
            iv = _table_interval()
            if self._last is not None and iv > 0.0 and \
                    now - self._last_read < iv:
                return self._last
        try:
            doc = self.store.get_routing(self.pool)
        except Exception:  # noqa: BLE001 — store down: serve last-good
            doc = None
            with self._lock:
                self.stats["refresh_errors"] += 1
        with self._lock:
            self._last_read = now
            if doc is not None:
                gen = int(doc.get("table_generation", 0))
                if gen >= self.generation:
                    self.generation = gen
                    self._last = doc
                    self.stats["refreshes"] += 1
                else:
                    self.stats["stale_rejected"] += 1
            if self._last is not None:
                return self._last
            return {"keys": {}, "shards": {}, "cold": True,
                    "table_generation": 0}

    def snapshot(self) -> dict:
        with self._lock:
            return {"generation": self.generation, **self.stats}


class _Transport(Exception):
    """Connection refused/reset/timeout talking to a replica — the
    failover-eligible failure shape (as opposed to an HTTP answer,
    which is relayed or retried by status)."""


class _BudgetExpired(Exception):
    """The client's X-H2O-Deadline-Ms budget ran out before a dispatch
    could even be sent — the 504 shape (rest.py's contract for the
    identical condition), never a retryable transport failure."""


class ScoringRouter:
    """Routing + health + budget state behind the handler (the handler
    class is built per-server so two routers in one process cannot
    share counters)."""

    def __init__(self, table):
        # table: dict or zero-arg callable ->
        #   {"keys": {model_key: [shard, ...]},   # preference order
        #    "shards": {shard: [replica_url, ...]}}
        self.get_table = table if callable(table) else (lambda: table)
        self._lock = threading.Lock()
        # the table snapshot the REQUEST path reads: rebuilt once per
        # health sweep, not per request — ShardedPool.routing_table()
        # is an O(catalog) dict build plus per-shard locks, which a
        # 1000-tenant catalog must not pay on every forward
        self._table: dict | None = None
        self._ready: dict[str, bool] = {}        # replica url -> ready
        self._cooldown: dict[str, float] = {}    # url -> monotonic until
        self._rr: dict[str, int] = {}            # shard -> round robin
        self._retry_buckets: dict[str, list] = {}
        self._inflight = 0
        self._stop = threading.Event()
        self._health_thread: threading.Thread | None = None
        self.stats = {
            "requests": 0, "forwarded": 0, "retries": 0,
            "retry_denied": 0, "failovers": 0, "hedges": 0,
            "hedge_wins": 0, "degraded_503": 0, "relayed_5xx": 0,
            "transport_errors": 0, "inflight_shed": 0,
            "unknown_model_404": 0,
        }
        self.retry_budget = {"granted": 0, "denied": 0}
        self.by_shard: dict[str, dict] = {}
        # per-TENANT relayed-success counter: incremented exactly ONCE
        # per client request, at the final relay (never at a dispatch
        # attempt) — so a lost hedge or a failover retry can never
        # double-count a tenant's traffic. Bounded like every tenant-
        # labeled series: past 4*top-K keys the coldest roll into
        # `other`.
        self.by_model: dict[str, int] = {}

    # -- health ---------------------------------------------------------------

    def _refresh_table(self) -> dict:
        """Pull a fresh routing-table snapshot from the provider and
        cache it for the request path (one O(catalog) build per
        sweep, not per request)."""
        t = self.get_table()
        with self._lock:
            self._table = t
        gen = t.get("table_generation") if isinstance(t, dict) else None
        if gen is not None:
            telemetry.REGISTRY.gauge(
                "h2o_router_table_generation",
                "routing-table generation this router serves from "
                "(store-backed providers only bump it forward)"
            ).set(float(gen))
        return t

    def table(self) -> dict:
        with self._lock:
            t = self._table
        return t if t is not None else self._refresh_table()

    def sweep_health(self) -> None:
        """One pass over every replica of every shard: ready iff its
        /3/Stats answers with ready=true (readiness + liveness + the
        warm-up gate in one device-free scrape). The shared probe
        helper retries 3x inside the probe timeout, so one missed
        scrape under load cannot drop a shard from the ring, while a
        dead pod (connection refused) classifies in milliseconds.
        Replicas are probed CONCURRENTLY: one wedged pod (accepting
        but unresponsive) costs 3x the probe timeout, and probing
        serially would stall death-detection for every OTHER shard by
        that much per sweep. The sweep also refreshes the cached
        routing-table snapshot the request path reads."""
        table = self._refresh_table()
        seen = []
        for sid, urls in (table.get("shards") or {}).items():
            for url in urls:
                seen.append(url.rstrip("/"))

        def probe_one(url: str) -> None:
            st = probe_json(url, "/3/Stats", retries=3)
            with self._lock:
                self._ready[url] = bool(st and st.get("ready"))

        threads = [threading.Thread(target=probe_one, args=(u,),
                                    daemon=True) for u in seen]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        with self._lock:
            for url in list(self._ready):
                if url not in seen:
                    del self._ready[url]     # replaced replica
            for url in list(self._cooldown):
                if self._cooldown[url] <= time.monotonic():
                    del self._cooldown[url]

    def _health_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.sweep_health()
            except Exception:  # noqa: BLE001 — the sweep must survive
                pass
            self._stop.wait(_health_interval())

    def start(self) -> None:
        self.sweep_health()                   # never serve blind
        self._health_thread = threading.Thread(
            target=self._health_loop, name="h2o-router-health",
            daemon=True)
        self._health_thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._health_thread is not None:
            self._health_thread.join(timeout=5.0)

    def any_shard_healthy(self) -> bool:
        table = self.table()
        with self._lock:
            for urls in (table.get("shards") or {}).values():
                if any(self._ready.get(u.rstrip("/")) for u in urls):
                    return True
        return False

    def shard_health(self) -> dict:
        table = self.table()
        out = {}
        with self._lock:
            for sid, urls in (table.get("shards") or {}).items():
                reps = {u.rstrip("/"): bool(self._ready.get(
                    u.rstrip("/"))) for u in urls}
                out[sid] = {"healthy": any(reps.values()),
                            "replicas": reps}
        return out

    # -- retry budget ---------------------------------------------------------

    def _retry_token(self, model_key: str) -> bool:
        """Take one cross-shard-retry token from the tenant's bucket
        (runtime/retry.bucket_take — the SAME bucket step as rest.py's
        per-tenant rate limit, so the two budgets can never drift).
        Accounting is exact: `granted` counts every token consumed,
        `denied` every refusal — the drill's never-exceeded proof
        reads these off /3/Stats."""
        from ..runtime.retry import bucket_take

        rate = _retry_budget_rate()
        with self._lock:
            if rate <= 0 or bucket_take(self._retry_buckets, model_key,
                                        rate, time.monotonic()) > 0.0:
                self.retry_budget["denied"] += 1
                return False
            self.retry_budget["granted"] += 1
            # counted HERE, not at the call sites: every granted token
            # IS a cross-shard re-dispatch (sequential retry or hedge),
            # so stats["retries"] == retry_budget["granted"] is
            # structural — the drill's never-exceeded audit can never
            # find phantom unaccounted tokens, hedging armed or not
            self.stats["retries"] += 1
            return True

    # -- candidate selection --------------------------------------------------

    def candidates(self, model_key: str):
        """(known, [(shard, [replica_url, ...]), ...]) — every healthy
        shard in the key's preference order, each with its READY
        replicas rotated round-robin (first = this request's primary,
        the rest = INTRA-shard failover order: a replica that dies
        between health sweeps must not 503 a single-shard tail tenant
        while a READY sibling sits next to it). Cooled-down replicas
        are skipped for their Retry-After window. Reads the
        sweep-cached table snapshot — never the O(catalog) provider —
        on the request path."""
        table = self.table()
        prefs = (table.get("keys") or {}).get(model_key)
        if prefs is None:
            return False, []
        shards = table.get("shards") or {}
        now = time.monotonic()
        out = []
        with self._lock:
            for sid in prefs:
                urls = [u.rstrip("/") for u in shards.get(sid, ())]
                live = [u for u in urls if self._ready.get(u)
                        and self._cooldown.get(u, 0.0) <= now]
                if not live:
                    continue
                i = self._rr.get(sid, 0)
                self._rr[sid] = i + 1
                out.append((sid, live[i % len(live):]
                            + live[: i % len(live)]))
        return True, out

    # -- dispatch -------------------------------------------------------------

    def _call_one(self, url: str, path: str, body: bytes,
                  headers: dict, deadline: float | None,
                  tid: str | None = None) -> dict:
        """One upstream POST. Returns {"code", "body", "retry_after"}
        for any HTTP answer; raises _Transport for connection-level
        failures (the failover shape)."""
        timeout = _router_timeout()
        hdrs = {"Content-Type": headers.get("Content-Type",
                                            "application/json")}
        if headers.get("X-H2O-SLO"):
            hdrs["X-H2O-SLO"] = headers["X-H2O-SLO"]
        if tid:
            # trace propagation: the replica records its queue/batch/
            # dispatch spans under the SAME id the router minted, so
            # one GET /3/Trace/{id} per hop reassembles the request
            hdrs["X-H2O-Trace-Id"] = tid
        if deadline is not None:
            # forward the REMAINING budget: the replica's admission
            # and batcher enforce the client's true deadline, minus
            # the time already spent at the front door
            rem_ms = (deadline - time.monotonic()) * 1000.0
            if rem_ms <= 0:
                raise _BudgetExpired("deadline exhausted before "
                                     "dispatch")
            hdrs["X-H2O-Deadline-Ms"] = f"{rem_ms:.1f}"
            timeout = min(timeout, rem_ms / 1000.0 + 1.0)
        req = urllib.request.Request(url + path, data=body,
                                     method="POST", headers=hdrs)
        try:
            with urllib.request.urlopen(req, timeout=timeout) as r:
                return {"code": r.status, "body": r.read(),
                        "retry_after": None}
        except urllib.error.HTTPError as e:
            ra = e.headers.get("Retry-After")
            try:
                ra = float(ra) if ra is not None else None
            except ValueError:
                ra = None
            return {"code": e.code, "body": e.read(), "retry_after": ra}
        except Exception as e:  # noqa: BLE001 — refused/reset/timeout
            raise _Transport(repr(e)[:200]) from None

    def _bump_shard(self, sid: str, field: str) -> None:
        with self._lock:
            rec = self.by_shard.setdefault(
                sid, {"forwarded": 0, "errors": 0, "hedge_won": 0,
                      "hedge_lost": 0, "hedge_cancelled": 0})
            rec[field] += 1

    def _hedge_outcome(self, sid: str, outcome: str) -> None:
        """Per-shard hedge-race accounting: `hedge_won` — the hedge
        leg's answer was relayed; `hedge_lost` — the hedge answered
        but the primary's answer won; `hedge_cancelled` — the primary
        won while the hedge was still in flight (its eventual answer
        is discarded unread). One of the three fires for EVERY fired
        hedge, so won+lost+cancelled == hedges holds structurally."""
        self._bump_shard(sid, f"hedge_{outcome}")
        telemetry.REGISTRY.counter(
            f"h2o_router_hedge_{outcome}_total",
            f"hedged dispatches whose race ended {outcome}, per "
            "shard", label="shard").inc(label_value=sid)

    def _bump_model(self, model_key: str) -> None:
        """The per-tenant relayed-success counter, bounded at
        4x H2O_TPU_METRICS_TOPK named keys: at capacity a newcomer
        evicts a ONE-count resident into `other` (so a flood of cold
        one-request probes cannot permanently squat every named slot),
        else the newcomer itself rolls into `other`. The genuinely
        traffic-ranked top-K view is the registry counter below —
        its series cap demotes by observed traffic."""
        from ..runtime.telemetry import _topk

        local_key = model_key
        with self._lock:
            cap = 4 * _topk()
            named = [k for k in self.by_model if k != "other"]
            if local_key not in self.by_model and len(named) >= cap:
                coldest = min(named, key=self.by_model.get)
                # a single prior request is all a newcomer needs to
                # out-rank a 1-count resident; ties keep the resident
                if self.by_model[coldest] <= 1:
                    self.by_model["other"] = \
                        self.by_model.get("other", 0) \
                        + self.by_model.pop(coldest)
                else:
                    local_key = "other"
            self.by_model[local_key] = \
                self.by_model.get(local_key, 0) + 1
        # the registry counter gets the REAL tenant key — its own
        # traffic-ranked series cap decides the exposed label set,
        # and it can only rank what it observes (feeding it the
        # locally-capped 'other' would lock a late-arriving hot
        # tenant out of a named series forever)
        telemetry.REGISTRY.counter(
            "h2o_router_forwarded_total",
            "requests relayed with a non-5xx answer, per tenant "
            "(top-K + other)", label="model").inc(label_value=model_key)

    def route(self, model_key: str, path: str, body: bytes,
              headers: dict, deadline: float | None,
              slo: str | None, tid: str | None = None
              ) -> tuple[int, bytes, dict]:
        """Resolve + forward with failover/hedging under the retry
        budget; returns (status, body bytes, response headers).
        ``tid`` is the request's trace id: every dispatch attempt is
        recorded as a span under it (outcome + shard + duration), and
        the final relay increments the tenant's forwarded counter
        exactly once — whatever failover/hedging did in between."""
        attempts: list[dict] = []
        t0 = time.monotonic()
        try:
            code, body_out, hdrs = self._route_inner(
                model_key, path, body, headers, deadline, slo, tid,
                attempts)
        except BaseException:
            if tid:
                telemetry.TRACER.record(tid, attempts, model=model_key,
                                        hop="router")
            raise
        dur = time.monotonic() - t0
        telemetry.REGISTRY.histogram(
            "h2o_router_route_seconds",
            "front-door routing latency (resolve + failover + "
            "upstream)").observe(dur)
        if tid:
            telemetry.TRACER.record(
                tid, attempts + [{"name": "route", "outcome": code,
                                  "ms": round(dur * 1000.0, 3)}],
                model=model_key, hop="router")
        if code < 500 and code != 404:
            # relayed non-5xx = the tenant's one forwarded answer
            # (404 excluded: an unknown-model probe must not mint
            # per-tenant series for attacker-chosen keys)
            self._bump_model(model_key)
        return code, body_out, hdrs

    @staticmethod
    def _attempt(attempts: list, sid: str, url: str, outcome: str,
                 t_start: float) -> None:
        attempts.append({
            "name": "dispatch", "shard": sid, "url": url,
            "outcome": outcome,
            "ms": round((time.monotonic() - t_start) * 1000.0, 3)})

    def _route_inner(self, model_key: str, path: str, body: bytes,
                     headers: dict, deadline: float | None,
                     slo: str | None, tid: str | None,
                     attempts: list) -> tuple[int, bytes, dict]:
        with self._lock:
            self.stats["requests"] += 1
        known, cands = self.candidates(model_key)
        if not known:
            if self.table().get("cold"):
                # a store-backed router that has never seen a table
                # cannot distinguish "unknown tenant" from "table not
                # yet published" — a typed degraded 503 keeps the
                # client retrying instead of a 404 that lies about
                # the catalog
                with self._lock:
                    self.stats["degraded_503"] += 1
                return 503, json.dumps(
                    {"__schema": "H2OErrorV3", "http_status": 503,
                     "msg": "router has no routing table yet (store "
                     "cold or controller not elected); retry shortly",
                     "hint": "table_pending",
                     "model": model_key}).encode(), \
                    {"Retry-After": "1"}
            with self._lock:
                self.stats["unknown_model_404"] += 1
            return 404, json.dumps(
                {"__schema": "H2OErrorV3", "http_status": 404,
                 "msg": f"model '{model_key}' is not in this fleet's "
                 "catalog"}).encode(), {}
        if not cands:
            # degraded mode: the tenant exists but no placed shard is
            # serving — a TYPED 503 the client can distinguish from a
            # generic outage: the reconciler is re-placing the
            # artifact; retry shortly and the routing table will have
            # a survivor
            with self._lock:
                self.stats["degraded_503"] += 1
            return 503, json.dumps(
                {"__schema": "H2OErrorV3", "http_status": 503,
                 "msg": f"tenant '{model_key}': every placed shard is "
                 "down; artifact re-placement onto a surviving shard "
                 "is in progress", "hint": "placement_pending",
                 "model": model_key}).encode(), {"Retry-After": "1"}

        hedge_s = _hedge_ms() / 1000.0
        start_i = 0
        last: dict | None = None
        if hedge_s > 0 and slo == "interactive" and len(cands) >= 2:
            h = self._route_hedged(model_key, path, body, headers,
                                   deadline, cands, tid, attempts)
            if h.get("expired"):
                return self._expired_504(model_key)
            if "relay" in h:
                return h["relay"]
            # hedged legs did not produce a success: continue the
            # SEQUENTIAL path from the first un-tried candidate, with
            # the best answered response kept for relay — arming the
            # hedge switch must never give up failover the sequential
            # path would have performed
            start_i = h["resume"]
            last = h.get("last")

        for i in range(start_i, len(cands)):
            sid, urls = cands[i]
            if deadline is not None and \
                    time.monotonic() >= deadline:
                # the client's budget died mid-route: 504 like the
                # replica path (rest.py) for the identical condition,
                # and NO retry tokens burned on dispatches that can
                # never be sent
                return self._expired_504(model_key)
            if i > 0:
                # a cross-shard retry — budget-gated so a dying shard
                # cannot amplify its load onto the survivors (the
                # grant itself increments stats["retries"])
                if not self._retry_token(model_key):
                    with self._lock:
                        self.stats["retry_denied"] += 1
                    break
            res = None
            for j, url in enumerate(urls):
                t_call = time.monotonic()
                try:
                    res = self._call_one(url, path, body, headers,
                                         deadline, tid)
                    break
                except _BudgetExpired:
                    self._attempt(attempts, sid, url,
                                  "budget_expired", t_call)
                    return self._expired_504(model_key)
                except _Transport:
                    self._attempt(attempts, sid, url,
                                  "transport_error", t_call)
                    # INTRA-shard failover on a connection-level
                    # failure is free (nothing was processed, no
                    # duplicated work — and token-gating it would
                    # starve a single-shard tail tenant on one
                    # replica death); each replica is tried at most
                    # once, so it stays bounded
                    with self._lock:
                        self.stats["transport_errors"] += 1
                        if j + 1 < len(urls) or i + 1 < len(cands):
                            self.stats["failovers"] += 1
                    self._bump_shard(sid, "errors")
            if res is None:
                continue        # shard dead at transport level
            if res["code"] >= 500:
                # an answered 5xx (drain 503, breaker open): honor its
                # Retry-After as a replica cooldown so we do not
                # re-dispatch into the same recovering pod, and keep
                # the response to relay if no survivor answers
                if res["retry_after"]:
                    with self._lock:
                        self._cooldown[url] = time.monotonic() + \
                            min(float(res["retry_after"]), 30.0)
                with self._lock:
                    self.stats["relayed_5xx"] += 1
                self._bump_shard(sid, "errors")
                self._attempt(attempts, sid, url, "answered_5xx",
                              t_call)
                last = res
                continue
            # 2xx and 4xx (including a tenant's own 429 rate limit —
            # retrying that on another shard would defeat the limit)
            # relay as-is
            with self._lock:
                self.stats["forwarded"] += 1
            self._bump_shard(sid, "forwarded")
            self._attempt(attempts, sid, url, "forwarded", t_call)
            return self._relay(res)
        if last is not None:
            return self._relay(last)
        with self._lock:
            self.stats["transport_errors"] += 1
        return 503, json.dumps(
            {"__schema": "H2OErrorV3", "http_status": 503,
             "msg": f"tenant '{model_key}': no shard answered (retry "
             "budget or candidates exhausted)"}).encode(), \
            {"Retry-After": "1"}

    def _expired_504(self, model_key: str) -> tuple[int, bytes, dict]:
        return 504, json.dumps(
            {"__schema": "H2OErrorV3", "http_status": 504,
             "msg": f"tenant '{model_key}': X-H2O-Deadline-Ms budget "
             "expired during routing — dropped unscored"}).encode(), {}

    @staticmethod
    def _relay(res: dict) -> tuple[int, bytes, dict]:
        hdrs = {}
        if res.get("retry_after") is not None:
            hdrs["Retry-After"] = str(
                max(1, int(float(res["retry_after"]) + 0.999)))
        return res["code"], res["body"], hdrs

    def _leg_failed(self, result, more_candidates: bool,
                    attempts: list):
        """Sequential-path bookkeeping for one failed hedge leg: a
        5xx answer records its Retry-After cooldown + relayed_5xx (so
        arming the hedge switch never skips the cooldown the
        sequential path applies), a transport failure counts like any
        other. Returns the answered response (for relay-of-last-
        resort) or None."""
        kind, sid, url, res, dur_ms = result
        if kind == "ok":
            if res["retry_after"]:
                with self._lock:
                    self._cooldown[url] = time.monotonic() + \
                        min(float(res["retry_after"]), 30.0)
            with self._lock:
                self.stats["relayed_5xx"] += 1
            self._bump_shard(sid, "errors")
            attempts.append({"name": "dispatch", "shard": sid,
                             "url": url, "outcome": "answered_5xx",
                             "ms": dur_ms})
            return res
        with self._lock:
            self.stats["transport_errors"] += 1
            if more_candidates:
                self.stats["failovers"] += 1
        self._bump_shard(sid, "errors")
        attempts.append({"name": "dispatch", "shard": sid, "url": url,
                         "outcome": "transport_error", "ms": dur_ms})
        return None

    def _route_hedged(self, model_key, path, body, headers, deadline,
                      cands, tid=None, attempts=None) -> dict:
        """Speculative dual-dispatch for interactive traffic: primary
        first; if it has not answered inside the hedge window AND the
        tenant's budget grants a token, fire the next shard and take
        whichever SUCCEEDS first. Returns ``{"relay": response}`` on a
        success, else ``{"resume": i, "last": res|None}`` — the caller
        continues the normal sequential failover from candidate ``i``
        with the best answered (5xx) response kept for relay, so a
        fast-failing primary gets exactly the sequential semantics
        (cooldown, budget-gated failover), never a relayed 5xx that
        a healthy replica shard could have absorbed.

        Race accounting: every fired hedge resolves to exactly one of
        hedge_won / hedge_lost / hedge_cancelled on the HEDGE shard's
        counters (see _hedge_outcome) — and the tenant's forwarded
        counter is untouched here (the route() wrapper increments it
        once on the final relay), so a lost hedge can never
        double-count a request."""
        if attempts is None:
            attempts = []
        results: list = [None, None]
        done = threading.Event()
        hedged = [False]

        def leg(i: int, target) -> None:
            sid, urls = target
            url = urls[0]
            t_call = time.monotonic()

            def dur():
                return round((time.monotonic() - t_call) * 1000.0, 3)

            try:
                results[i] = ("ok", sid, url,
                              self._call_one(url, path, body, headers,
                                             deadline, tid), dur())
            except _BudgetExpired as e:
                results[i] = ("expired", sid, url, e, dur())
            except _Transport as e:
                results[i] = ("transport", sid, url, e, dur())
            done.set()

        def settle_hedge(winner: int) -> None:
            """The race ended with a relayed answer from ``winner``:
            file the hedge leg's outcome (won / lost / cancelled)."""
            if not hedged[0]:
                return
            sid1 = cands[1][0]
            if winner == 1:
                self._hedge_outcome(sid1, "won")
            elif results[1] is not None:
                self._hedge_outcome(sid1, "lost")
            else:
                self._hedge_outcome(sid1, "cancelled")

        def won(i: int):
            """Relay dict when leg i holds a success."""
            kind, sid, url, res, dur_ms = results[i]
            if kind != "ok" or res["code"] >= 500:
                return None
            with self._lock:
                self.stats["forwarded"] += 1
                if i == 1:
                    self.stats["hedge_wins"] += 1
            self._bump_shard(sid, "forwarded")
            attempts.append({"name": "dispatch", "shard": sid,
                             "url": url, "outcome": "forwarded",
                             "ms": dur_ms,
                             **({"hedge_leg": i} if hedged[0]
                                else {})})
            settle_hedge(i)
            return {"relay": self._relay(res)}

        threading.Thread(target=leg, args=(0, cands[0]),
                         daemon=True).start()
        end0 = time.monotonic() + _hedge_ms() / 1000.0
        while results[0] is None and time.monotonic() < end0:
            done.wait(0.005)
            done.clear()
        if results[0] is not None:
            # primary answered INSIDE the hedge window: a success
            # relays, a failure takes the sequential path from
            # candidate 1 — the hedge never fires
            if results[0][0] == "expired":
                return {"expired": True}
            out = won(0)
            if out is not None:
                return out
            last = self._leg_failed(results[0], len(cands) > 1,
                                    attempts)
            return {"resume": 1, "last": last}
        # primary slow: fire the hedge (it is load amplification, so
        # it is budget-gated like any retry)
        if self._retry_token(model_key):
            with self._lock:
                self.stats["hedges"] += 1
            hedged[0] = True
            threading.Thread(target=leg, args=(1, cands[1]),
                             daemon=True).start()
            fired_legs = (0, 1)
        else:
            with self._lock:
                self.stats["retry_denied"] += 1
            fired_legs = (0,)
        # wait for a success from whichever legs are running
        end = time.monotonic() + _router_timeout()
        handled = set()
        last = None
        while time.monotonic() < end:
            for i in fired_legs:
                if results[i] is None or i in handled:
                    continue
                if results[i][0] == "expired":
                    if hedged[0]:
                        # the race ends here too: settle the hedge
                        # leg so won+lost+cancelled == hedges holds
                        # even when the deadline dies mid-race
                        self._hedge_outcome(
                            cands[1][0],
                            "lost" if results[1] is not None
                            else "cancelled")
                    return {"expired": True}
                out = won(i)
                if out is not None:
                    return out
                handled.add(i)
                res = self._leg_failed(results[i],
                                       len(cands) > len(fired_legs),
                                       attempts)
                if res is not None:
                    last = res
            if len(handled) == len(fired_legs):
                break
            done.wait(0.01)
            done.clear()
        if hedged[0]:
            # no leg relayed: the race had no winner — count the
            # hedge leg by what it DID (answered-and-failed = lost,
            # still in flight when we gave up = cancelled) so
            # won+lost+cancelled == hedges stays structural
            self._hedge_outcome(cands[1][0],
                                "lost" if results[1] is not None
                                else "cancelled")
        return {"resume": len(fired_legs), "last": last}

    # -- admission ------------------------------------------------------------

    def admit(self) -> bool:
        with self._lock:
            if self._inflight >= _max_inflight():
                self.stats["inflight_shed"] += 1
                return False
            self._inflight += 1
            return True

    def release(self) -> None:
        with self._lock:
            self._inflight -= 1

    def snapshot(self) -> dict:
        with self._lock:
            stats = dict(self.stats)
            budget = dict(self.retry_budget)
            by_shard = {k: dict(v) for k, v in self.by_shard.items()}
            by_model = dict(self.by_model)
            inflight = self._inflight
        tbl = self.table()
        gen = tbl.get("table_generation") if isinstance(tbl, dict) \
            else None
        out = {"router": True, "stats": stats,
               "retry_budget": {**budget,
                                "rate_per_s": _retry_budget_rate()},
               "by_shard": by_shard, "by_model": by_model,
               "inflight": inflight,
               "hedge_ms": _hedge_ms(),
               "table_generation": gen,
               "shards": self.shard_health(),
               "build": telemetry.build_info()}
        prov = getattr(self.get_table, "snapshot", None)
        if callable(prov):
            out["table_provider"] = prov()
        return out


def _make_handler(router: ScoringRouter):
    # rest.py is imported lazily HERE (not at module import): the
    # handler genuinely reuses the server plumbing, but a router
    # process should not pay the numpy import until it actually serves
    from ..rest import (JsonHttpHandler, _DeadlineExpired,
                        _request_deadline, _request_slo)

    class _RouterHandler(JsonHttpHandler):
        server_version = "h2o-tpu-router/1"

        def do_GET(self):
            import urllib.parse

            path = urllib.parse.urlparse(self.path).path.rstrip("/")
            if path == "/healthz":
                st = lifecycle.status()
                alive = st["state"] != lifecycle.TERMINATED
                return self._json({"alive": alive, "router": True,
                                   **st}, 200 if alive else 503)
            if path == "/readyz":
                ready = router.any_shard_healthy() and \
                    lifecycle.accepting()
                return self._json(
                    {"ready": ready, "router": True},
                    200 if ready else 503)
            if path == "/3/Stats":
                return self._json({"ready":
                                   router.any_shard_healthy(),
                                   **router.snapshot()})
            if path == "/metrics":
                # Prometheus exposition at the front door: the
                # process-wide registry (hedge outcome + forwarded
                # counters, route-latency histogram, build info) plus
                # this router instance's snapshot flattened in.
                # by_model/shards are excluded from the flatten —
                # tenant keys and replica URLs must never become
                # metric NAMES (the capped first-class counters carry
                # them as labels instead).
                snap = router.snapshot()
                extra = {"router": {
                    k: v for k, v in snap.items()
                    if k not in ("by_model", "shards", "build")}}
                telemetry.write_metrics(self, extra)
                return None
            if path.startswith("/3/Trace/"):
                # the router's half of a request trace: one span per
                # dispatch attempt (shard, outcome, duration) + the
                # route total — pair it with the replica's
                # /3/Trace/{id} for the full hop decomposition
                tid = urllib.parse.unquote(path[len("/3/Trace/"):])
                rec = telemetry.TRACER.get(tid)
                if rec is None:
                    return self._error(
                        404, f"trace '{tid}' not in the router's ring")
                return self._json(rec)
            return self._error(404, f"no route for GET {path}")

        def do_POST(self):
            import urllib.parse

            try:
                path = urllib.parse.urlparse(
                    self.path).path.rstrip("/")
                if not lifecycle.accepting():
                    self._discard_body()
                    return self._error(
                        503, f"router {lifecycle.state()}: draining",
                        retry_after=lifecycle.remaining_drain_budget())
                prefix = "/3/Predictions/models/"
                if not path.startswith(prefix):
                    self._discard_body()
                    return self._error(
                        404, f"no route for POST {path} (the router "
                        "forwards scoring + contributions only)")
                rest_part = path[len(prefix):]
                mkey = rest_part
                if rest_part.endswith("/contributions"):
                    mkey = rest_part[: -len("/contributions")]
                mkey = urllib.parse.unquote(mkey)
                # the router MINTS the trace id when the client sent
                # none — from here every hop (forward headers, replica
                # span records, hedge legs) carries the same id
                tid = telemetry.trace_id_from(self.headers)
                try:
                    deadline = _request_deadline(self.headers)
                    slo = _request_slo(self.headers)
                except ValueError as e:
                    self._discard_body()
                    return self._error(400, str(e))
                except _DeadlineExpired as e:
                    # same discard discipline as the 400: the body is
                    # still unread here, and closing with unread bytes
                    # sends RST — which can destroy the buffered 504
                    # client-side
                    self._discard_body()
                    return self._error(504, str(e))
                if not router.admit():
                    self._discard_body()
                    return self._error(
                        429, "router in-flight limit reached "
                        "(H2O_TPU_ROUTER_MAX_INFLIGHT); shed",
                        retry_after=1.0)
                try:
                    n = int(self.headers.get("Content-Length") or 0)
                    body = self.rfile.read(n) if n else b""
                    # self.headers (not dict()): HTTPMessage lookups
                    # are case-insensitive, and proxies en route may
                    # have re-capitalized X-H2O-SLO
                    code, out, hdrs = router.route(
                        mkey, path, body, self.headers,
                        deadline, slo, tid=tid)
                finally:
                    router.release()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(out)))
                self.send_header("X-H2O-Trace-Id", tid)
                for k, v in hdrs.items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(out)
                return None
            except _DeadlineExpired as e:
                return self._error(504, str(e))
            except Exception as e:  # noqa: BLE001
                import traceback

                traceback.print_exc()
                return self._error(500, repr(e))

    return _RouterHandler


def start_router(table, port: int = 0, host: str = "127.0.0.1"
                 ) -> tuple[ThreadingHTTPServer, ScoringRouter]:
    """Start a router over ``table`` (a dict or a zero-arg callable —
    ``ShardedPool.routing_table`` is the intended provider). Returns
    (server, router); ``server.server_address[1]`` is the bound port.
    Tear down with ``router.stop(); server.shutdown()``."""
    router = ScoringRouter(table)
    srv = ThreadingHTTPServer((host, port), _make_handler(router))
    router.start()
    t = threading.Thread(target=srv.serve_forever,
                         name="h2o-tpu-router", daemon=True)
    t.start()
    return srv, router


def main(argv=None) -> int:
    """``python -m h2o_kubernetes_tpu.operator.router --store ROOT
    --pool NAME [--port P]`` — one stateless router process over a
    durable store root. Start N of them behind any TCP balancer: they
    share nothing but the store, so killing any one of them loses
    nothing but its in-flight sockets."""
    import argparse
    import signal

    ap = argparse.ArgumentParser(
        description="store-backed front-door scoring router")
    ap.add_argument("--store", required=True,
                    help="DurablePoolStore root (dir or mem://)")
    ap.add_argument("--pool", required=True, help="pool name")
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--host", default="127.0.0.1")
    args = ap.parse_args(argv)

    from .store import DurablePoolStore

    provider = StoreRoutingTable(DurablePoolStore(args.store),
                                 args.pool)
    srv, router = start_router(provider, port=args.port,
                               host=args.host)
    print(f"ROUTER_UP port={srv.server_address[1]} "
          f"pool={args.pool}", flush=True)
    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.set())
    try:
        while not stop.is_set():
            stop.wait(0.5)
    finally:
        router.stop()
        srv.shutdown()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
