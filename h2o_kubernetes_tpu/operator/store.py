"""Durable PoolStore — specs/status/events that survive operator death.

The in-memory ``PoolStore`` dies with the process that owns it; a real
operator's control plane must not (ROADMAP: "kubeconfig-backed store"
— this is the persist.py-backed step toward it, same observable
semantics). ``DurablePoolStore`` keeps the base class's in-memory view
as a cache and persists through a persist.py root (local dir or
``mem://``), split by WRITER the way kube splits the spec and status
subresources:

    <root>/<pool>.spec.json    {"generation", "spec"}      — client-written
    <root>/<pool>.state.json   {"status", "events"}        — controller-written
    <root>/<pool>.routing.json {"table_generation", ...}   — controller-written
    <root>/<pool>.lease.json   {"holder", "epoch", ...}    — lease-holder-written

so a drill (or a human) applying a spec bump from ONE process and the
operator writing status from ANOTHER can share a root without either
clobbering the other: each file has a single writer. Reads re-load
from disk (``_refresh``), so the operator observes a client's version
bump on its next reconcile pass, and a client polls live status —
the store file IS the API-server wire.

Every write goes through :func:`persist.write_bytes_atomic`
(write-temp + fsync + rename, read-back digest verify): an operator
SIGKILLed mid-write leaves the previous intact document, never a torn
one. The event ring stays bounded (the base class's deque cap), so
the state file cannot grow without bound under a flapping pool.

Generation fencing is inherited from ``PoolStore`` and checked against
the REFRESHED on-disk generation: a stale controller (or a split-brain
second operator) holding an old generation gets
``StaleGenerationError`` on any fenced write — stale writes lose
deterministically.
"""

from __future__ import annotations

import collections
import json
import os
import threading
from dataclasses import asdict

from .. import persist
from .spec import _EVENT_CAP, PoolStore, ScorerPoolSpec

try:                               # not on Windows; lease guard degrades
    import fcntl
except ImportError:                # pragma: no cover
    fcntl = None

__all__ = ["DurablePoolStore"]


# mem:// roots live inside one process, so a module-level lock is a
# real cross-instance guard there (two DurablePoolStores over the same
# mem:// root are two threads, never two processes)
_MEM_LEASE_LOCKS: dict[str, threading.Lock] = {}
_MEM_LEASE_LOCKS_GUARD = threading.Lock()


class _FlockGuard:
    """Cross-process critical section for lease mutations on a
    directory root: N operator replicas share the root but not a
    process lock, and ``acquire_lease``'s read-decide-write must be
    atomic or two standbys racing an expired lease both claim it."""

    def __init__(self, path: str):
        self.path = path
        self._f = None

    def __enter__(self):
        self._f = open(self.path, "a+")
        if fcntl is not None:
            fcntl.flock(self._f.fileno(), fcntl.LOCK_EX)
        return self

    def __exit__(self, *exc):
        try:
            if fcntl is not None:
                fcntl.flock(self._f.fileno(), fcntl.LOCK_UN)
        finally:
            self._f.close()
            self._f = None
        return False


def _spec_from_doc(doc: dict) -> ScorerPoolSpec:
    """JSON round-trip loses tuple-ness; restore the spec's tuple
    fields so a reloaded spec compares equal to the applied one."""
    doc = dict(doc)
    if doc.get("warm_buckets") is not None:
        doc["warm_buckets"] = tuple(doc["warm_buckets"])
    doc["extra_artifacts"] = tuple(
        tuple(ent) for ent in doc.get("extra_artifacts") or ())
    return ScorerPoolSpec(**doc)


class DurablePoolStore(PoolStore):
    """persist.py-backed :class:`PoolStore` (file / mem backends)."""

    def __init__(self, root: str):
        super().__init__()
        self.root = root
        self._load_all()

    def _spec_path(self, name: str) -> str:
        return persist.join_path(self.root, f"{name}.spec.json")

    def _state_path(self, name: str) -> str:
        return persist.join_path(self.root, f"{name}.state.json")

    def _routing_path(self, name: str) -> str:
        return persist.join_path(self.root, f"{name}.routing.json")

    def _lease_path(self, name: str) -> str:
        return persist.join_path(self.root, f"{name}.lease.json")

    @staticmethod
    def _read_doc(path: str) -> dict | None:
        """None = missing, unreadable, or tombstoned — all read as
        'not there'; the atomic writer means torn files cannot exist,
        so anything unparseable is foreign and skipped, not fatal."""
        try:
            doc = json.loads(persist.read_bytes(path))
        except (FileNotFoundError, ValueError, OSError):
            return None
        return doc or None

    # -- durability hooks (called under the store lock) -----------------------

    def _persist_spec(self, name: str) -> None:
        spec = self._specs.get(name)
        if spec is None:
            return
        persist.write_bytes_atomic(
            self._spec_path(name),
            json.dumps({"generation": self._gens.get(name, 0),
                        "spec": asdict(spec)}, indent=1).encode())

    def _persist_state(self, name: str) -> None:
        if name not in self._specs:
            # a deleted pool's state must not be resurrected by a
            # still-running operator's event/status writes — the
            # reconciler's loop keeps erroring (and evented) until
            # its owner stops it, but the files stay gone
            return
        persist.write_bytes_atomic(
            self._state_path(name),
            json.dumps({"status": self._status.get(name, {}),
                        "events": list(self._events.get(name, ()))},
                       indent=1).encode())

    def _persist_routing(self, name: str) -> None:
        doc = self._routing.get(name)
        if doc is None or name not in self._specs:
            return                      # same no-resurrect rule as state
        persist.write_bytes_atomic(
            self._routing_path(name),
            json.dumps(doc, indent=1).encode())

    def _persist_lease(self, name: str) -> None:
        doc = self._leases.get(name)
        path = self._lease_path(name)
        if doc is None:                 # released → file reads as gone
            try:
                if "://" in path:
                    persist.write_bytes(path, b"{}")
                else:
                    os.remove(path)
            except (FileNotFoundError, OSError):
                pass
            return
        persist.write_bytes_atomic(path, json.dumps(doc, indent=1).encode())

    def _lease_guard(self, name: str):
        if "://" in self.root:
            with _MEM_LEASE_LOCKS_GUARD:
                return _MEM_LEASE_LOCKS.setdefault(
                    f"{self.root}|{name}", threading.Lock())
        os.makedirs(self.root, exist_ok=True)
        return _FlockGuard(os.path.join(self.root,
                                        f"{name}.lease.lock"))

    def _refresh(self, name: str) -> None:
        """Re-read `name` from disk into the in-memory cache: the
        writer of a file re-reads its own last (atomic) write, and
        every OTHER process observes it — one store root, N
        processes, no watch machinery needed at this scale."""
        sdoc = self._read_doc(self._spec_path(name))
        if sdoc is None or "spec" not in sdoc:
            self._specs.pop(name, None)
            self._gens.pop(name, None)
        else:
            try:
                self._specs[name] = \
                    _spec_from_doc(sdoc["spec"]).validate()
                self._gens[name] = int(sdoc.get("generation", 1))
            except (TypeError, ValueError):
                pass                     # foreign junk: keep the cache
        tdoc = self._read_doc(self._state_path(name))
        if tdoc is not None:
            self._status[name] = dict(tdoc.get("status") or {})
            self._events[name] = collections.deque(
                tdoc.get("events") or (), maxlen=_EVENT_CAP)
        rdoc = self._read_doc(self._routing_path(name))
        if rdoc is not None and "table_generation" in rdoc:
            self._routing[name] = rdoc
        ldoc = self._read_doc(self._lease_path(name))
        if ldoc is None:
            self._leases.pop(name, None)
        elif "epoch" in ldoc:
            self._leases[name] = ldoc

    def _forget(self, name: str) -> None:
        paths = [self._spec_path(name), self._state_path(name),
                 self._routing_path(name), self._lease_path(name)]
        if "://" not in self.root:
            paths.append(os.path.join(self.root, f"{name}.lease.lock"))
        for path in paths:
            try:
                if "://" in path:
                    # mem:// has no delete verb; tombstone (skipped by
                    # _read_doc and the loader)
                    persist.write_bytes(path, b"{}")
                else:
                    os.remove(path)
            except (FileNotFoundError, OSError):
                pass

    # -- restart path ---------------------------------------------------------

    def _load_all(self) -> None:
        for fname in persist.list_names(self.root):
            if fname.endswith(".spec.json"):
                with self._lock:
                    self._refresh(fname[:-len(".spec.json")])
