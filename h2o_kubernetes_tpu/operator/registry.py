"""Model registry: versioned MOJO-v2 artifacts + the replica scorer.

The training cluster publishes a trained tree ensemble ONCE as a
versioned MOJO-v2 artifact (mojo.py — the flat_* serving arrays ARE
the wire format, PR 2), persisted through any persist.py backend
(local dir, mem://, s3://...). Scorer replicas never see the training
stack: the registry pushes an artifact over ``POST
/3/ModelRegistry/load`` and the replica wraps the flat arrays in a
``FlatTreeScorer`` — a ``Model`` whose ``_score_matrix`` descends the
SAME ``flat_margin`` executable the in-process serving scorer uses,
so predictions are bitwise-identical to the training-side model, and
``score_numpy``/the REST micro-batcher/the jitted-scorer cache all
just work. ``Model.warm_up`` then pre-traces the pow2 batch buckets
through the persistent XLA cache BEFORE the replica's ``/readyz``
flips (the warm-up contract: ``warm_cache_misses == 0`` on the first
real request).

Format-v1 artifacts (pre-flattening: heap trees + bin edges) are
REJECTED — they have no serving arrays to load; re-export with this
build.
"""

from __future__ import annotations

import base64
import hashlib
import io
import json
from typing import Sequence

import numpy as np

from .. import persist
from ..mojo import MOJO_FORMAT, export_mojo, read_mojo_parts
from ..models.base import Model

__all__ = ["ModelRegistry", "FlatTreeScorer", "load_artifact",
           "SERVABLE_ALGOS"]

# the registry serves TREE ensembles (the AutoML leaders that matter
# for throughput); GLM/DL artifact serving rides the same route once a
# flat scorer exists for them
SERVABLE_ALGOS = ("gbm", "drf", "xgboost")


class FlatTreeScorer(Model):
    """Servable model built from a MOJO-v2 tree artifact's flat arrays.

    Mirrors ``GBMModel._margins`` + ``_score_matrix`` op for op on the
    SAME ``flat_margin`` jitted executable (models/tree/core.py), so a
    replica scoring a pushed artifact is bitwise-identical to the
    training-side model serving in-process — pinned by
    tests/test_operator.py's round-trip test."""

    _serving_jit = True

    def __init__(self, meta: dict, arrays: dict):
        # Model.__init__ wants TrainData; a registry scorer has only
        # the artifact metadata — set the serving surface directly.
        # The artifact parts are kept (host numpy) because they ARE
        # this model's persistent state: Model.__getstate__ drops
        # _flat_trees assuming a lazy rebuild from heap trees, which
        # a registry scorer does not have — see __getstate__ below.
        self._artifact_meta = dict(meta)
        keep = ["init_score", "enum_mask", "flat_split_feat",
                "flat_thresh", "flat_left", "flat_na_left",
                "flat_value"]
        if "flat_cover" in arrays:
            # optional MOJO-v2 cover part: enables serving
            # predict_contributions (TreeSHAP path tables); artifacts
            # without it still serve margins
            keep.append("flat_cover")
        self._artifact_arrays = {k: np.asarray(arrays[k]) for k in keep}
        arrays = self._artifact_arrays
        self.algo = meta["algo"]
        self.feature_names = list(meta["feature_names"])
        self.feature_domains = dict(meta.get("feature_domains") or {})
        self.nclasses = int(meta["nclasses"])
        self.response_domain = meta.get("response_domain")
        self.distribution = meta.get("distribution")
        self.offset_column = meta.get("offset_column")
        self.scoring_history: list = []
        self.cv = None
        self.validation_metrics = None
        self.ntrees = int(meta["ntrees"])
        self.max_depth = int(meta["max_depth"])
        self.drf_mode = bool(meta["drf_mode"])
        self.margin_scale = float(meta.get("margin_scale", 1.0))
        self.init_score = np.asarray(arrays["init_score"])
        # device state (_flat_trees, _enum_mask) is built lazily by
        # _serving_prepare from the kept host arrays, so the byte-
        # budgeted scorer cache can evict it and a later score
        # re-promotes — rebuilding the SAME constants means the same
        # HLO, a persistent-cache hit, and bitwise-identical output
        self._serving_prepare()

    def _serving_prepare(self):
        """Build (or fetch) the device arrays; RETURNS them so callers
        hold locals — a concurrent byte-budget eviction may pop the
        attributes between a check and a read (the evict loop runs
        under _SCORER_LOCK, a trace in flight does not), and a
        check-then-self-read would AttributeError mid-score."""
        ft = self.__dict__.get("_flat_trees")
        em = self.__dict__.get("_enum_mask")
        if ft is not None and em is not None:
            return ft, em
        import jax.numpy as jnp

        from ..models.tree.core import FlatTrees

        arrays = self._artifact_arrays
        em = jnp.asarray(np.asarray(arrays["enum_mask"]).astype(bool))
        ft = FlatTrees(
            *(jnp.asarray(arrays[f"flat_{f}"])
              for f in ("split_feat", "thresh", "left", "na_left",
                        "value")))
        self._enum_mask = em
        self._flat_trees = ft
        return ft, em

    def _serving_evict(self) -> None:
        super()._serving_evict()
        self.__dict__.pop("_enum_mask", None)

    # -- compiled TreeSHAP serving -------------------------------------------

    def contrib_support(self) -> "str | None":
        """Mirror of GBMModel.contrib_support for a registry scorer:
        same precondition set, with the cover check against the
        artifact's optional ``flat_cover`` part."""
        if int(self.nclasses) > 2:
            return ("predict_contributions supports binomial "
                    "and regression models only")
        if self.offset_column:
            return ("predict_contributions is not supported "
                    "for models trained with an offset")
        if "flat_cover" not in self._artifact_arrays:
            return (
                "this artifact was exported without per-node cover "
                "(pre-cover build, or a source model trained before "
                "per-node cover existed); TreeSHAP needs it — "
                "re-export the model with this build")
        return None

    def _shap_sources(self):
        """(flat arrays, cover) straight from the kept artifact parts
        — identical numpy values to the training-side model's, so the
        base _contrib_prepare/_contrib_matrix produce the same device
        constants, the same HLO, and bitwise-identical contributions
        (pinned by tests/test_contrib.py)."""
        from ..models.tree.core import FlatTrees

        a = self._artifact_arrays
        flat = FlatTrees(
            *(np.asarray(a[f"flat_{f}"])
              for f in ("split_feat", "thresh", "left", "na_left",
                        "value")))
        return flat, np.asarray(a["flat_cover"])

    def _contrib_enum_mask(self):
        _, em = self._serving_prepare()
        return em

    def _contrib_scale_init(self) -> tuple[float, float]:
        scale = float(self.margin_scale)
        if self.drf_mode:
            scale /= self.ntrees
        return scale, float(np.asarray(self.init_score).ravel()[0])

    def export_artifact(self) -> bytes:
        """Re-serialize this scorer as a MOJO-v2 zip from its kept
        artifact parts — export_mojo cannot walk a registry scorer (no
        params/bin_spec/heap trees), so the REST mojo-download route
        and registry.publish use THIS for FlatTreeScorer instances.
        Semantically identical to the artifact it was loaded from
        (same meta, same arrays); the zip bytes themselves may differ
        (compression/ordering), so it gets its own digest on
        re-publish."""
        import zipfile

        npz = io.BytesIO()
        np.savez_compressed(npz, **self._artifact_arrays)
        buf = io.BytesIO()
        with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as z:
            z.writestr("model.json", json.dumps(self._artifact_meta))
            z.writestr("arrays.npz", npz.getvalue())
        return buf.getvalue()

    def __getstate__(self):
        # the base Model pops _flat_trees (GBMModel rebuilds it lazily
        # from heap trees); this scorer HAS no heap trees — pickle the
        # artifact parts instead and rebuild everything from them
        return {"meta": self._artifact_meta,
                "arrays": self._artifact_arrays}

    def __setstate__(self, state):
        self.__init__(state["meta"], state["arrays"])

    def _score_matrix(self, X, offset=None):
        import jax
        import jax.numpy as jnp

        from ..models.tree.core import flat_margin

        # the eager predict() path reaches here without _cached_score
        # having run _serving_prepare; after an eviction the device
        # arrays must be rebuilt (concrete host→device constants —
        # safe even under a jit trace). LOCALS, not self-reads: a
        # concurrent eviction may pop the attributes mid-score.
        ft, em = self._serving_prepare()
        K = self.nclasses if self.nclasses > 2 else 1
        lv = flat_margin(ft, X, em, self.max_depth, K)      # [K, rows]
        if K == 1:
            m = lv[0]
            if self.drf_mode:
                m = m / self.ntrees
            base = self.init_score if offset is None \
                else self.init_score + offset
            m = base + self.margin_scale * m
        else:
            if self.drf_mode:
                lv = lv / (self.ntrees // K)
            m = (jnp.asarray(self.init_score)[:, None] + lv).T
        d = self.distribution
        if d == "bernoulli":
            p1 = jnp.clip(m, 0.0, 1.0) if self.drf_mode \
                else jax.nn.sigmoid(m)
            return jnp.stack([1.0 - p1, p1], axis=1)
        if d == "multinomial":
            if self.drf_mode:
                m = jnp.clip(m, 0.0, None)
                return m / (jnp.sum(m, axis=1, keepdims=True) + 1e-10)
            return jax.nn.softmax(m, axis=1)
        if d in ("poisson", "gamma", "tweedie"):
            return jnp.exp(m)
        return m


def load_artifact(blob: bytes) -> FlatTreeScorer:
    """MOJO-v2 artifact bytes -> a servable FlatTreeScorer.

    Rejects format-v1 artifacts (no flattened serving arrays — a
    replica would have to re-bin and heap-descend, i.e. carry the
    training stack) and non-tree algos, with actionable messages."""
    meta, arrays, _ = read_mojo_parts(io.BytesIO(blob))
    if meta.get("format") != MOJO_FORMAT:
        raise ValueError(
            f"artifact format {meta.get('format')!r} is not servable "
            f"by a scorer replica (need {MOJO_FORMAT}): format-v1 "
            "artifacts carry heap trees + bin edges, not the flattened "
            "serving arrays — re-export the model with this build")
    if meta.get("algo") not in SERVABLE_ALGOS:
        raise ValueError(
            f"algo '{meta.get('algo')}' is not servable by a scorer "
            f"replica (supported: {', '.join(SERVABLE_ALGOS)})")
    if "flat_split_feat" not in arrays:
        raise ValueError("artifact claims MOJO-v2 but lacks the flat_* "
                         "serving arrays — corrupt or tampered")
    return FlatTreeScorer(meta, arrays)


class ModelRegistry:
    """Versioned artifact store rooted at a persist.py path.

    Layout: ``<root>/index.json`` (name -> {latest, versions}) plus
    ``<root>/<name>-v<N>.mojo`` blobs. Single-writer by design (ONE
    operator process owns a registry root, like one controller owns a
    CRD); replicas only ever read."""

    def __init__(self, root: str):
        self.root = root

    # -- index ----------------------------------------------------------------

    def _index_path(self) -> str:
        return persist.join_path(self.root, "index.json")

    def _load_index(self) -> dict:
        # one read, not exists()+read: on a remote backend an
        # existence probe IS a full GET, so probing first would double
        # every registry operation's round-trips
        try:
            return json.loads(persist.read_bytes(self._index_path()))
        except FileNotFoundError:
            return {}       # fresh registry root

    def _save_index(self, idx: dict) -> None:
        # atomic + read-back-verified: the index is the registry's
        # single point of failure — a publish crashed mid-write must
        # leave the PREVIOUS intact index, never a torn one that
        # breaks every subsequent fetch's digest check
        persist.write_bytes_atomic(self._index_path(),
                                   json.dumps(idx, indent=1).encode())

    # -- publish / fetch ------------------------------------------------------

    def artifact_path(self, name: str, version: int) -> str:
        return persist.join_path(self.root, f"{name}-v{int(version)}.mojo")

    def publish(self, model, name: str) -> int:
        """Export `model` as the next version of artifact `name`;
        returns the new version number. The artifact is the exact
        MOJO-v2 zip export_mojo writes — one flattening code path
        shared with in-process serving and offline MojoModel scoring."""
        if getattr(model, "algo", None) not in SERVABLE_ALGOS:
            raise ValueError(
                f"cannot publish algo '{getattr(model, 'algo', '?')}' "
                f"to a scorer pool (supported: "
                f"{', '.join(SERVABLE_ALGOS)})")
        if hasattr(model, "export_artifact"):
            # re-publishing a loaded FlatTreeScorer (replica-to-replica
            # promotion): it has no heap trees for export_mojo to walk,
            # but its kept artifact parts ARE the artifact
            blob = model.export_artifact()
        else:
            buf = io.BytesIO()
            export_mojo(model, buf)
            blob = buf.getvalue()
        idx = self._load_index()
        ent = idx.setdefault(name, {"latest": 0, "versions": {}})
        version = int(ent["latest"]) + 1
        path = self.artifact_path(name, version)
        # blob first, index second (a crash between the two leaves an
        # unreferenced blob, never an index entry without bytes)
        persist.write_bytes_atomic(path, blob)
        ent["versions"][str(version)] = {
            "path": path,
            "bytes": len(blob),
            "sha256": hashlib.sha256(blob).hexdigest(),
            "algo": model.algo,
        }
        ent["latest"] = version
        self._save_index(idx)
        return version

    def latest(self, name: str) -> int:
        ent = self._load_index().get(name)
        if not ent or not ent["latest"]:
            raise KeyError(f"no artifact '{name}' in registry "
                           f"{self.root}")
        return int(ent["latest"])

    def info(self, name: str, version: int) -> dict:
        ent = self._load_index().get(name) or {"versions": {}}
        try:
            return dict(ent["versions"][str(int(version))])
        except KeyError:
            raise KeyError(f"no artifact '{name}' v{version} in "
                           f"registry {self.root}") from None

    def fetch(self, name: str, version: int) -> bytes:
        blob = persist.read_bytes(self.artifact_path(name, version))
        want = self.info(name, version)["sha256"]
        got = hashlib.sha256(blob).hexdigest()
        if got != want:
            raise IOError(
                f"artifact '{name}' v{version} digest mismatch "
                f"({got[:12]} != indexed {want[:12]}) — refusing to "
                "serve a corrupted model")
        return blob

    # -- push to a replica ----------------------------------------------------

    def push(self, base_url: str, name: str, version: int,
             model_key: str, warm_buckets: Sequence[int] | None = None,
             timeout: float = 300.0, inline: bool | None = None,
             slo: str | None = None) -> dict:
        """POST the artifact to a replica's /3/ModelRegistry/load and
        block until it has loaded AND warmed (the route warms before
        it returns, so success here means the replica's readiness gate
        is satisfied).

        ``warm_buckets=None`` omits the field so the REPLICA resolves
        its own ``H2O_TPU_POOL_WARM_BUCKETS`` — a spec-pinned tuple
        overrides it. ``slo`` sets the model's default SLO class on
        the replica (rest.py SLO_CLASSES; per-request X-H2O-SLO still
        wins). ``inline=None`` sends the artifact PATH when the
        backend is host-visible (local FS / cloud schemes the replica
        can read) and falls back to inline base64 bytes for mem://
        roots, which exist only in THIS process."""
        if inline is None:
            inline = self.root.startswith("mem://")
        body = {"model_id": model_key, "name": name,
                "version": int(version)}
        if warm_buckets is not None:
            body["warm_buckets"] = [int(b) for b in warm_buckets]
        if slo is not None:
            body["slo"] = slo
        if inline:
            body["artifact_b64"] = base64.b64encode(
                self.fetch(name, version)).decode()
        else:
            body["path"] = self.artifact_path(name, version)
            body["sha256"] = self.info(name, version)["sha256"]
        return self._post_json(base_url, "/3/ModelRegistry/load",
                               body, timeout)

    def push_many(self, base_url: str, items: Sequence[Sequence],
                  warm_buckets: Sequence[int] | None = None,
                  timeout: float = 300.0,
                  require: bool = True) -> list[dict]:
        """Push a TENANT SET to one replica: ``items`` is a sequence
        of (artifact, version, model_key[, slo]) entries
        (ScorerPoolSpec.all_artifacts). With ``require`` (the
        default), the replica's required-model readiness set is
        declared FIRST — so ``/readyz`` cannot flip green between
        artifact 1 landing and artifact N, whatever order the pushes
        complete in. Returns the per-artifact load responses."""
        items = [tuple(it) for it in items]
        if require:
            self._post_json(base_url, "/3/ModelRegistry/require",
                            {"model_ids": [it[2] for it in items]},
                            timeout)
        out = []
        for it in items:
            name, version, model_key = it[0], it[1], it[2]
            slo = it[3] if len(it) > 3 else None
            out.append(self.push(base_url, name, version, model_key,
                                 warm_buckets=warm_buckets,
                                 timeout=timeout, slo=slo))
        return out

    @staticmethod
    def _post_json(base_url: str, path: str, body: dict,
                   timeout: float) -> dict:
        """POST with the runtime/retry.py backoff layer on TRANSIENT
        failures (replica 5xx/429, connection reset/refused, timeout):
        one flaky push during a rollout used to surface as
        ``load_failed`` and burn a crash-loop backoff slot on a
        replica that was merely busy. Permanent outcomes (4xx other
        than 429 — bad artifact, digest mismatch) propagate on the
        first attempt unchanged, so the poison-rollback path still
        fails fast. The load route is idempotent, so retrying a push
        whose response was lost is safe."""
        import urllib.error
        import urllib.request

        from ..runtime import retry as _retry

        data = json.dumps(body).encode()

        def attempt() -> dict:
            req = urllib.request.Request(
                base_url.rstrip("/") + path, data=data, method="POST",
                headers={"Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(req, timeout=timeout) as r:
                    return json.loads(r.read())
            except urllib.error.HTTPError as e:
                if e.code == 429 or e.code >= 500:
                    ra = e.headers.get("Retry-After")
                    try:
                        ra = float(ra) if ra is not None else None
                    except ValueError:
                        ra = None
                    detail = e.read()[:200]
                    raise _retry.TransientError(
                        f"replica POST {path}: HTTP {e.code} "
                        f"{detail!r}", retry_after=ra) from None
                raise                       # 4xx: permanent, no retry
            except urllib.error.URLError as e:
                # refused / reset / DNS — the replica is restarting or
                # mid-drain; classic transient
                raise _retry.TransientError(
                    f"replica POST {path}: {e.reason!r}") from None
            except (TimeoutError, ConnectionError, OSError) as e:
                raise _retry.TransientError(
                    f"replica POST {path}: {e!r}") from None

        return _retry.call(attempt,
                           describe=f"registry push {path}")
