"""Shared replica scrape helper: probe timeout + retry-before-unhealthy.

THE one way the control plane (reconciler adoption/autoscale scrapes)
and the data-plane router read a replica's ``/readyz`` / ``/3/Stats``:

- every attempt is capped by ``H2O_TPU_POOL_PROBE_TIMEOUT`` (PR 9 —
  one hung replica must not stall a reconcile pass or a router health
  sweep), and
- a replica is classified unreachable only after ``retries``
  consecutive failed attempts (default 3) in ONE call: a GIL-bound
  scoring burst that makes a replica miss a single scrape must not
  flap it out of the router's ring or make an adopting operator kill
  a healthy pod. A dead replica (connection refused) fails all three
  attempts in milliseconds, so failover detection stays fast.

Returns the parsed JSON, or None when every attempt failed.
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request

from ..runtime.retry import _env_float

__all__ = ["probe_json", "probe_timeout"]


def probe_timeout() -> float:
    """Per-attempt cap on every replica scrape (floored at 0.1 so a
    typo'd knob can never make probes hang-proof-less)."""
    return max(0.1, _env_float("H2O_TPU_POOL_PROBE_TIMEOUT", 2.0))


def probe_json(url: str, path: str = "/3/Stats", retries: int = 3,
               timeout: float | None = None,
               retry_sleep: float = 0.15):
    """GET ``url + path`` and parse JSON, retrying transient failures.

    HTTP error responses that still carry JSON (a 503 from /readyz
    with its reasons) are RETURNED, not retried — "unready" is an
    answer, only "unreachable" gets the retry treatment."""
    t = probe_timeout() if timeout is None else timeout
    for attempt in range(max(1, int(retries))):
        try:
            with urllib.request.urlopen(url.rstrip("/") + path,
                                        timeout=t) as r:
                return json.loads(r.read())
        except urllib.error.HTTPError as e:
            try:
                return json.loads(e.read())
            except Exception:  # noqa: BLE001 — non-JSON error body
                return None
        except Exception:  # noqa: BLE001 — refused/reset/timeout
            if attempt + 1 < retries:
                time.sleep(retry_sleep)
    return None
