"""Level-triggered reconcile loop over real subprocess scorer pods.

The controller pattern of the reference operator (deployment/
controller.rs watches the H2O CRD and converges StatefulSets), applied
to the serving fleet: every pass re-derives actions from OBSERVED
state (live processes, /healthz, /readyz) against the current spec —
no edge memory, so a missed event can never wedge the pool. The loop
converges on:

- **replica death** — a pod whose process exited (OOM-kill, SIGKILL,
  crash) is recorded (``replica_died``) and replaced next pass;
- **spec resize** — ``replicas`` up spawns, down cordons + drains the
  excess (never a hard kill of a serving replica);
- **artifact change** — ``version`` bump rolls surge-one: spawn ONE
  fresh replica on the new artifact, push + warm it (readyz flips only
  after the pow2 buckets are pre-traced), and only once it is READY
  cordon one old-version replica, wait the deregister grace (routers
  drop the endpoint; stragglers still get served — that is how the
  drill holds zero 5xx), then SIGTERM it into the PR-4 drain path.

Pods are REAL subprocesses running the rest.py serving entry via
``python -m h2o_kubernetes_tpu.operator.pod``: own lifecycle state
machine, SIGTERM drain, breaker, admission queue — exactly what a
kubelet would run; swapping the Popen for a pod template against a
kube API server changes ``ScorerReplica`` only.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
import urllib.request

from ..runtime.retry import _env_float
from .registry import ModelRegistry
from .spec import PoolStore, ScorerPoolSpec

__all__ = ["Reconciler", "ScorerReplica", "PENDING", "STARTING",
           "LOADING", "READY", "CORDONED", "DRAINING", "DEAD"]

PENDING = "PENDING"        # created, not yet spawned
STARTING = "STARTING"      # process up, waiting for /healthz
LOADING = "LOADING"        # artifact push + warm-up in flight
READY = "READY"            # /readyz green (artifact warmed)
CORDONED = "CORDONED"      # readiness off, serving stragglers (grace)
DRAINING = "DRAINING"      # SIGTERM sent, PR-4 drain in progress
DEAD = "DEAD"              # process gone (observed or forced)

# states that count toward (future) serving capacity — cordoned and
# draining replicas are on their way OUT and never count
CAPACITY_STATES = (STARTING, LOADING, READY)


def _interval() -> float:
    return max(0.05, _env_float("H2O_TPU_POOL_RECONCILE_INTERVAL", 0.5))


def _startup_deadline() -> float:
    return max(1.0, _env_float("H2O_TPU_POOL_STARTUP_DEADLINE", 180.0))


def _deregister_grace() -> float:
    return max(0.0, _env_float("H2O_TPU_POOL_DEREGISTER_GRACE", 0.75))


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class ScorerReplica:
    """One subprocess scorer pod + this controller's view of it.

    All process/HTTP interaction lives here so the Reconciler is pure
    orchestration — tests drive it with fake replicas implementing
    this surface."""

    def __init__(self, rid: str, version: int, spec: ScorerPoolSpec,
                 log_dir: str | None = None):
        self.rid = rid
        self.version = int(version)
        self.model_key = spec.model_key
        self.artifact = spec.artifact
        # the FULL tenant set this replica must serve (primary pinned
        # to the rollout version + every extra artifact): pushed as
        # one required-set so /readyz can't flip mid-push
        self.artifacts = [(spec.artifact, int(version), spec.model_key,
                           spec.slo)]
        for ent in spec.all_artifacts()[1:]:
            self.artifacts.append(ent)
        # None = the replica resolves H2O_TPU_POOL_WARM_BUCKETS itself
        self.warm_buckets = None if spec.warm_buckets is None \
            else tuple(spec.warm_buckets)
        self.env_overrides = dict(spec.env)
        self.log_dir = log_dir
        self.port = _free_port()
        self.proc: subprocess.Popen | None = None
        self.state = PENDING
        self.created_at = time.monotonic()
        self.cordoned_at = 0.0
        self.drain_at = 0.0
        self._log_f = None
        self._load_thread: threading.Thread | None = None
        self._load_err: str | None = None
        self._load_done = False

    # -- process --------------------------------------------------------------

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def spawn(self) -> None:
        repo = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        env = dict(os.environ)
        env.update(self.env_overrides)
        env["H2O_TPU_POOL_REPLICA"] = "1"
        env.setdefault("JAX_PLATFORMS", "cpu")
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
        out = subprocess.DEVNULL
        if self.log_dir:
            os.makedirs(self.log_dir, exist_ok=True)
            self._log_f = open(os.path.join(
                self.log_dir, f"{self.rid}.log"), "ab")
            out = self._log_f
        self.proc = subprocess.Popen(
            [sys.executable, "-m", "h2o_kubernetes_tpu.operator.pod",
             "--port", str(self.port)],
            env=env, cwd=repo, stdout=out, stderr=out,
            start_new_session=True)
        self.state = STARTING
        self.created_at = time.monotonic()

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def pid(self) -> int | None:
        return self.proc.pid if self.proc is not None else None

    def mark_dead(self) -> None:
        self.state = DEAD
        if self._log_f is not None:
            try:
                self._log_f.close()
            except OSError:
                pass
            self._log_f = None

    # -- HTTP -----------------------------------------------------------------

    def _get_json(self, path: str, timeout: float = 2.0):
        try:
            with urllib.request.urlopen(self.url + path,
                                        timeout=timeout) as r:
                return json.loads(r.read())
        except Exception:  # noqa: BLE001 — down/unready both read None
            return None

    def healthz_ok(self) -> bool:
        out = self._get_json("/healthz")
        return bool(out and out.get("alive"))

    def readyz_ok(self) -> bool:
        out = self._get_json("/readyz")
        return bool(out and out.get("ready"))

    def stats(self) -> dict | None:
        return self._get_json("/3/Stats")

    def loaded_version(self) -> int | None:
        out = self._get_json("/3/ModelRegistry")
        if not out:
            return None
        info = (out.get("models") or {}).get(self.model_key)
        return info.get("version") if info else None

    # -- artifact push (background: warm-up compiles take seconds) -----------

    def start_load(self, registry: ModelRegistry) -> None:
        self.state = LOADING

        def push():
            try:
                # the whole tenant set (primary + extras), required-
                # set declared first: readiness flips only after
                # EVERY artifact is loaded + warmed
                registry.push_many(self.url, self.artifacts,
                                   warm_buckets=self.warm_buckets,
                                   timeout=_startup_deadline())
            except Exception as e:  # noqa: BLE001 — reconciler decides
                self._load_err = repr(e)[:300]
            finally:
                self._load_done = True

        self._load_thread = threading.Thread(
            target=push, name=f"h2o-pool-push-{self.rid}", daemon=True)
        self._load_thread.start()

    def load_finished(self) -> bool:
        return self._load_done

    def load_error(self) -> str | None:
        return self._load_err

    # -- retirement -----------------------------------------------------------

    def cordon(self) -> None:
        """Endpoint removal: readiness off, admission stays open."""
        try:
            req = urllib.request.Request(
                self.url + "/3/Cordon",
                data=json.dumps({"reason": "rollout"}).encode(),
                method="POST",
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=5.0):
                pass
        except Exception:  # noqa: BLE001 — a dead pod cordons itself
            pass
        self.state = CORDONED
        self.cordoned_at = time.monotonic()

    def terminate(self) -> None:
        """SIGTERM → the pod's PR-4 drain path (flush batcher, settle
        jobs, exit 0 inside H2O_TPU_DRAIN_TIMEOUT)."""
        if self.proc is not None and self.proc.poll() is None:
            try:
                self.proc.terminate()
            except ProcessLookupError:
                pass
        self.state = DRAINING
        self.drain_at = time.monotonic()

    def kill(self) -> None:
        if self.proc is not None and self.proc.poll() is None:
            try:
                self.proc.kill()
            except ProcessLookupError:
                pass


class Reconciler:
    """Converge a pool of ScorerReplicas to its ScorerPoolSpec."""

    def __init__(self, store: PoolStore, registry: ModelRegistry,
                 pool: str, log_dir: str | None = None,
                 replica_factory=None):
        self.store = store
        self.registry = registry
        self.pool = pool
        self.log_dir = log_dir
        # injectable for tests: factory(rid, version, spec) -> replica
        self.replica_factory = replica_factory or (
            lambda rid, version, spec: ScorerReplica(
                rid, version, spec, log_dir=self.log_dir))
        self.replicas: list = []
        self._seq = 0
        self._last_totals: dict | None = None   # autoscale deltas
        self._lock = threading.Lock()           # replicas list mutation
        self._stopped = False                   # shutdown() flips it

    # -- events / status ------------------------------------------------------

    def _event(self, kind: str, msg: str = "") -> None:
        self.store.record_event(self.pool, kind, msg)
        from ..diagnostics import log

        log.warning("operator[%s]: %s %s", self.pool, kind, msg)

    def endpoints(self) -> list[str]:
        """Routable endpoint URLs — the Service-endpoints analog.
        Cordoned/draining replicas are OUT the instant they cordon;
        not-yet-ready ones are included (the load generator's
        readiness poller filters on /readyz, like kube-proxy on
        endpoint readiness)."""
        with self._lock:
            return [r.url for r in self.replicas
                    if r.state in CAPACITY_STATES]

    def status(self) -> dict:
        with self._lock:
            reps = list(self.replicas)
        return {
            "replicas": [{"id": r.rid, "state": r.state,
                          "version": r.version, "port": r.port,
                          "pid": r.pid()} for r in reps],
            "ready": sum(1 for r in reps if r.state == READY),
        }

    def converged(self, spec: ScorerPoolSpec | None = None) -> bool:
        if spec is None:
            spec, _ = self.store.get(self.pool)
        with self._lock:
            reps = list(self.replicas)
        # alive() is checked HERE, not just at reconcile time: a
        # replica SIGKILLed an instant ago is still READY in controller
        # state until the next pass observes it, and a wait_converged
        # racing that pass must not declare victory over a dead pod
        current_ready = [r for r in reps if r.state == READY
                         and r.version == spec.version and r.alive()]
        leftovers = [r for r in reps if r.state != DEAD
                     and not (r.state == READY
                              and r.version == spec.version
                              and r.alive())]
        return len(current_ready) == spec.replicas and not leftovers

    # -- the loop -------------------------------------------------------------

    def _spawn(self, version: int, spec: ScorerPoolSpec):
        with self._lock:
            if self._stopped:
                return None
            self._seq += 1
            rid = f"{self.pool}-{self._seq}"
        r = self.replica_factory(rid, version, spec)
        r.spawn()
        with self._lock:
            if self._stopped:
                # shutdown() completed between the check above and the
                # Popen: the torn-down pool must not gain a live pod
                # nothing will ever terminate — kill it right here
                r.kill()
                r.mark_dead()
                return None
            self.replicas.append(r)
        self._event("replica_start",
                    f"{rid} v{version} port={getattr(r, 'port', '?')}")
        return r

    def reconcile_once(self) -> None:
        if self._stopped:
            # shutdown() won the race with a still-running run() loop:
            # reconciling now would re-provision the pool it just tore
            # down and leak pods past the caller's teardown
            return
        spec, gen = self.store.get(self.pool)
        now = time.monotonic()
        deadline = _startup_deadline()
        grace = _deregister_grace()

        # 1. observe process deaths (replica-kill converges from here)
        for r in list(self.replicas):
            if r.state in (DEAD, PENDING):
                continue
            if not r.alive():
                if r.state == DRAINING:
                    self._event("replica_exit",
                                f"{r.rid} drained and exited")
                elif r.state == CORDONED:
                    self._event("replica_exit",
                                f"{r.rid} exited while cordoned")
                else:
                    self._event("replica_died",
                                f"{r.rid} v{r.version} "
                                f"(port {r.port}) exited unexpectedly")
                r.mark_dead()
        with self._lock:
            self.replicas = [r for r in self.replicas
                             if r.state != DEAD]

        # 2. advance startups: healthz → push+warm → readyz
        for r in self.replicas:
            if r.state == STARTING:
                if r.healthz_ok():
                    r.start_load(self.registry)
                    buckets = "env default" if r.warm_buckets is None \
                        else str(list(r.warm_buckets))
                    self._event("replica_load",
                                f"{r.rid} pushing {r.artifact} "
                                f"v{r.version} + warming {buckets}")
                elif now - r.created_at > deadline:
                    self._event("replica_startup_timeout",
                                f"{r.rid} no /healthz after "
                                f"{deadline:.0f}s — replacing")
                    r.kill()
                    r.mark_dead()
            elif r.state == LOADING:
                err = r.load_error()
                if err is not None:
                    self._event("replica_load_failed",
                                f"{r.rid}: {err}")
                    r.kill()
                    r.mark_dead()
                elif r.load_finished() and r.readyz_ok():
                    r.state = READY
                    self._event("replica_ready",
                                f"{r.rid} v{r.version} warmed — "
                                "readyz green")
                elif now - r.created_at > deadline:
                    self._event("replica_startup_timeout",
                                f"{r.rid} not READY after "
                                f"{deadline:.0f}s — replacing")
                    r.kill()
                    r.mark_dead()
        with self._lock:
            self.replicas = [r for r in self.replicas
                             if r.state != DEAD]

        # 3. cordoned replicas past the deregister grace drain now;
        # wedged drains get SIGKILL well past the pod's own budget
        drain_budget = _env_float("H2O_TPU_DRAIN_TIMEOUT", 30.0)
        for r in self.replicas:
            if r.state == CORDONED and now - r.cordoned_at >= grace:
                r.terminate()
                self._event("replica_drain",
                            f"{r.rid} SIGTERM after {grace:.2f}s "
                            "deregister grace")
            elif r.state == DRAINING and \
                    now - r.drain_at > drain_budget + 15.0:
                self._event("replica_drain_wedged",
                            f"{r.rid} still alive "
                            f"{drain_budget + 15:.0f}s after SIGTERM "
                            "— SIGKILL")
                r.kill()

        # 4. converge version + count (surge-one rolling update)
        want = spec.version
        # stale replicas that never went READY are superseded work —
        # kill outright, nothing routes to them
        for r in list(self.replicas):
            if r.version != want and r.state in (STARTING, LOADING):
                self._event("replica_superseded",
                            f"{r.rid} v{r.version} superseded by "
                            f"v{want} before READY")
                r.kill()
                r.mark_dead()
        with self._lock:
            self.replicas = [r for r in self.replicas
                             if r.state != DEAD]
        capacity = [r for r in self.replicas
                    if r.state in CAPACITY_STATES]
        current = [r for r in capacity if r.version == want]
        stale_ready = [r for r in capacity
                       if r.version != want and r.state == READY]
        ready = [r for r in capacity if r.state == READY]

        if len(current) < spec.replicas and \
                len(capacity) < spec.replicas + 1:
            # scale up / replace dead / surge the rollout — one spawn
            # per pass keeps the surge at one
            self._spawn(want, spec)
        elif stale_ready and len(ready) > spec.replicas:
            # a new-version replica is READY beyond the desired count:
            # retire ONE old-version replica — cordon first (routers
            # drop the endpoint), drain after the grace (step 3)
            victim = stale_ready[0]
            victim.cordon()
            self._event("replica_cordon",
                        f"{victim.rid} v{victim.version} cordoned "
                        f"(rollout to v{want})")
        elif not stale_ready and len(current) > spec.replicas:
            # spec resize down: prefer retiring a not-yet-ready spare
            spares = [r for r in current if r.state != READY]
            if spares:
                victim = spares[-1]
                self._event("replica_scaled_down",
                            f"{victim.rid} (not yet ready) stopped — "
                            f"replicas={spec.replicas}")
                victim.kill()
                victim.mark_dead()
            else:
                victim = current[-1]
                victim.cordon()
                self._event("replica_cordon",
                            f"{victim.rid} cordoned (scale down to "
                            f"{spec.replicas})")
        with self._lock:
            self.replicas = [r for r in self.replicas
                             if r.state != DEAD]

        # 5. publish observed status
        st = self.status()
        by_version: dict[str, int] = {}
        for r in st["replicas"]:
            if r["state"] == READY:
                by_version[str(r["version"])] = \
                    by_version.get(str(r["version"]), 0) + 1
        self.store.set_status(self.pool, {
            "generation_observed": gen,
            "desired_replicas": spec.replicas,
            "desired_version": spec.version,
            "ready_by_version": by_version,
            "converged": self.converged(spec),
            **st,
        })

    def run(self, stop: threading.Event,
            interval: float | None = None) -> None:
        """Blocking loop (callers thread it); autoscale piggybacks on
        the same cadence when the spec opts in."""
        while not stop.is_set():
            try:
                self.reconcile_once()
                self.autoscale_once()
            except Exception as e:  # noqa: BLE001 — the loop survives
                self._event("reconcile_error", repr(e)[:300])
            stop.wait(interval if interval is not None else _interval())

    def wait_converged(self, timeout: float = 120.0) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.converged():
                return True
            time.sleep(0.1)
        return self.converged()

    def shutdown(self, timeout: float = 60.0) -> None:
        """Drain every replica (tests/drills teardown): stop
        reconciling first (a racing run() pass must not re-provision
        what this tears down), SIGTERM all, SIGKILL stragglers at the
        deadline."""
        with self._lock:
            # one atomic step: after this, _spawn either sees _stopped
            # (and kills its own pod) or its replica is in this
            # snapshot — no pod can fall between the two
            self._stopped = True
            reps = list(self.replicas)
        for r in reps:
            if r.state not in (DEAD,):
                r.terminate()
        deadline = time.monotonic() + timeout
        for r in reps:
            while r.alive() and time.monotonic() < deadline:
                time.sleep(0.1)
            if r.alive():
                r.kill()
            r.mark_dead()
        with self._lock:
            self.replicas = []

    # -- autoscale ------------------------------------------------------------

    def autoscale_once(self) -> int | None:
        """Scrape /3/Stats off READY replicas and apply the autoscale
        signal to the spec (when ``spec.autoscale``); returns the new
        desired count or None when disabled/unchanged."""
        spec, _ = self.store.get(self.pool)
        if not spec.autoscale:
            return None
        with self._lock:
            ready = [r for r in self.replicas if r.state == READY]
        samples = [s for s in (r.stats() for r in ready) if s]
        from .autoscale import desired_replicas

        desired, why, totals = desired_replicas(
            spec, samples, self._last_totals)
        self._last_totals = totals
        if desired != spec.replicas:
            self.store.apply_update(self.pool, replicas=desired)
            self._event("autoscale",
                        f"replicas {spec.replicas} -> {desired} "
                        f"({why})")
            return desired
        return None
