"""Level-triggered reconcile loop over real subprocess scorer pods.

The controller pattern of the reference operator (deployment/
controller.rs watches the H2O CRD and converges StatefulSets), applied
to the serving fleet: every pass re-derives actions from OBSERVED
state (live processes, /healthz, /readyz) against the current spec —
no edge memory, so a missed event can never wedge the pool. The loop
converges on:

- **replica death** — a pod whose process exited (OOM-kill, SIGKILL,
  crash) is recorded (``replica_died``) and replaced next pass;
- **spec resize** — ``replicas`` up spawns, down cordons + drains the
  excess (never a hard kill of a serving replica);
- **artifact change** — ``version`` bump rolls surge-one: spawn ONE
  fresh replica on the new artifact, push + warm it (readyz flips only
  after the pow2 buckets are pre-traced), and only once it is READY
  cordon one old-version replica, wait the deregister grace (routers
  drop the endpoint; stragglers still get served — that is how the
  drill holds zero 5xx), then SIGTERM it into the PR-4 drain path;
- **operator restart** — replicas drop pid/port manifests under the
  pool workdir; a fresh Reconciler ADOPTS the live pods it finds
  there (identity-probed via /3/Stats) instead of spawning
  duplicates, before its first reconcile pass;
- **crash loops** — respawns of a failing version are exponentially
  backoff-spaced (``H2O_TPU_POOL_BACKOFF_*``), and a rollout whose
  new version keeps failing readiness auto-rolls-back to the pinned
  last-good version (``H2O_TPU_POOL_ROLLOUT_RETRIES``) — old
  replicas are never disturbed.

Pods are REAL subprocesses running the rest.py serving entry via
``python -m h2o_kubernetes_tpu.operator.pod``: own lifecycle state
machine, SIGTERM drain, breaker, admission queue — exactly what a
kubelet would run; swapping the Popen for a pod template against a
kube API server changes ``ScorerReplica`` only.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
import urllib.request

from dataclasses import replace as _dc_replace

from ..runtime.retry import _env_float
from .placement import (PlacementPlan, move_destination,
                        plan_placement, shard_preference)
from .probe import probe_json
from .registry import ModelRegistry
from .spec import PoolStore, ScorerPoolSpec, StaleGenerationError

__all__ = ["Reconciler", "ScorerReplica", "AdoptedReplica",
           "ShardedPool", "PENDING", "STARTING", "LOADING", "READY",
           "CORDONED", "DRAINING", "DEAD"]

PENDING = "PENDING"        # created, not yet spawned
STARTING = "STARTING"      # process up, waiting for /healthz
LOADING = "LOADING"        # artifact push + warm-up in flight
READY = "READY"            # /readyz green (artifact warmed)
CORDONED = "CORDONED"      # readiness off, serving stragglers (grace)
DRAINING = "DRAINING"      # SIGTERM sent, PR-4 drain in progress
DEAD = "DEAD"              # process gone (observed or forced)

# states that count toward (future) serving capacity — cordoned and
# draining replicas are on their way OUT and never count
CAPACITY_STATES = (STARTING, LOADING, READY)


def _interval() -> float:
    return max(0.05, _env_float("H2O_TPU_POOL_RECONCILE_INTERVAL", 0.5))


def _startup_deadline() -> float:
    return max(1.0, _env_float("H2O_TPU_POOL_STARTUP_DEADLINE", 180.0))


def _deregister_grace() -> float:
    return max(0.0, _env_float("H2O_TPU_POOL_DEREGISTER_GRACE", 0.75))


def _probe_timeout() -> float:
    """Per-probe cap on every reconciler health/readyz//3/Stats
    scrape: one hung replica must not stall the whole pass (and with
    it death-detection for its siblings). Shared with the router's
    health sweeps — operator/probe.py is the one implementation."""
    from .probe import probe_timeout

    return probe_timeout()


def _backoff_base() -> float:
    return max(0.0, _env_float("H2O_TPU_POOL_BACKOFF_BASE", 0.5))


def _backoff_cap() -> float:
    return max(0.1, _env_float("H2O_TPU_POOL_BACKOFF_MAX", 30.0))


def _backoff_window() -> float:
    """Seconds a failure stays in the backoff history; a version that
    has run clean this long respawns immediately again."""
    return max(1.0, _env_float("H2O_TPU_POOL_BACKOFF_WINDOW", 120.0))


def _rollout_retries() -> int:
    return max(1, int(_env_float("H2O_TPU_POOL_ROLLOUT_RETRIES", 3)))


def _rebalance_enabled() -> bool:
    """Hot-shard rebalancing kill switch (default OFF: moving tenants
    under load is an operator policy, not a default behavior)."""
    return _env_float("H2O_TPU_REBALANCE", 0.0) > 0


def _rebalance_sustain() -> int:
    """Consecutive pressure passes before a move fires — one shed
    burst must not trigger a tenant migration."""
    return max(1, int(_env_float("H2O_TPU_REBALANCE_SUSTAIN", 3)))


def _rebalance_cooldown() -> float:
    """Seconds between moves, fleet-wide: rebalancing converges one
    tenant at a time, never a thundering migration."""
    return max(0.0, _env_float("H2O_TPU_REBALANCE_COOLDOWN", 30.0))


def _rebalance_retire_s() -> float:
    """make-before-break dwell: how long the SOURCE keeps serving a
    moved tenant after the destination went live (routers refresh
    their table within a health sweep; this must outlast one)."""
    return max(0.0, _env_float("H2O_TPU_REBALANCE_RETIRE_S", 5.0))


def _rebalance_failback_s() -> float:
    """How long a re-placed tenant's home shard must stay healthy
    before the override copies age out (failback hygiene)."""
    return max(0.0, _env_float("H2O_TPU_REBALANCE_FAILBACK_S", 30.0))


def _log_max_bytes() -> int:
    return int(_env_float("H2O_TPU_POOL_LOG_MAX_BYTES", 8 << 20))


def _log_keep() -> int:
    return max(2, int(_env_float("H2O_TPU_POOL_LOG_KEEP", 16)))


def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class ScorerReplica:
    """One subprocess scorer pod + this controller's view of it.

    All process/HTTP interaction lives here so the Reconciler is pure
    orchestration — tests drive it with fake replicas implementing
    this surface."""

    def __init__(self, rid: str, version: int, spec: ScorerPoolSpec,
                 log_dir: str | None = None,
                 manifest_dir: str | None = None,
                 pool: str | None = None, port: int | None = None):
        self.rid = rid
        self.version = int(version)
        self.model_key = spec.model_key
        self.artifact = spec.artifact
        self.pool = pool or spec.name
        self.manifest_dir = manifest_dir
        # the FULL tenant set this replica must serve (primary pinned
        # to the rollout version + every extra artifact): pushed as
        # one required-set so /readyz can't flip mid-push
        self.artifacts = [(spec.artifact, int(version), spec.model_key,
                           spec.slo)]
        for ent in spec.all_artifacts()[1:]:
            self.artifacts.append(ent)
        # None = the replica resolves H2O_TPU_POOL_WARM_BUCKETS itself
        self.warm_buckets = None if spec.warm_buckets is None \
            else tuple(spec.warm_buckets)
        self.env_overrides = dict(spec.env)
        self.log_dir = log_dir
        self.port = _free_port() if port is None else int(port)
        self.proc: subprocess.Popen | None = None
        self.state = PENDING
        self.created_at = time.monotonic()
        self.cordoned_at = 0.0
        self.drain_at = 0.0
        self._log_f = None
        self._load_thread: threading.Thread | None = None
        self._load_err: str | None = None
        self._load_done = False

    # -- process --------------------------------------------------------------

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.port}"

    def manifest_path(self) -> str | None:
        if not self.manifest_dir:
            return None
        return os.path.join(self.manifest_dir, f"{self.rid}.json")

    def _write_manifest(self) -> None:
        """Drop the pidfile/port manifest a restarted operator adopts
        from (docs/OPERATOR.md "Control-plane recovery"). Written by
        the controller at spawn (it knows rid/version) and rewritten
        by the pod itself once up (authoritative pid)."""
        path = self.manifest_path()
        if path is None:
            return
        os.makedirs(self.manifest_dir, exist_ok=True)
        doc = {"rid": self.rid, "pool": self.pool,
               "pid": self.proc.pid, "port": self.port,
               "version": self.version, "model_key": self.model_key,
               "created_at": time.time()}
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)

    def _remove_manifest(self) -> None:
        path = self.manifest_path()
        if path:
            try:
                os.remove(path)
            except OSError:
                pass

    def _rotate_logs(self) -> None:
        """Size cap + rotate-on-respawn: an oversized log from a
        previous life of this rid rolls to `.1` before the fresh
        process reopens it. Dir-wide pruning is the RECONCILER's job
        (it knows which rids are live — see `_prune_logs`)."""
        if not self.log_dir:
            return
        mine = os.path.join(self.log_dir, f"{self.rid}.log")
        try:
            if os.path.getsize(mine) > _log_max_bytes():
                os.replace(mine, mine + ".1")
        except OSError:
            pass

    def spawn(self) -> None:
        repo = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        env = dict(os.environ)
        env.update(self.env_overrides)
        env["H2O_TPU_POOL_REPLICA"] = "1"
        env.setdefault("JAX_PLATFORMS", "cpu")
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
        out = subprocess.DEVNULL
        if self.log_dir:
            os.makedirs(self.log_dir, exist_ok=True)
            self._rotate_logs()
            self._log_f = open(os.path.join(
                self.log_dir, f"{self.rid}.log"), "ab")
            out = self._log_f
        argv = [sys.executable, "-m",
                "h2o_kubernetes_tpu.operator.pod",
                "--port", str(self.port),
                "--pool", self.pool, "--rid", self.rid]
        man = self.manifest_path()
        if man is not None:
            # on the pod's OWN cmdline so (a) it can rewrite the
            # manifest with its authoritative pid, and (b) the
            # run_tests preflight can tell an ADOPTABLE orphan (live
            # manifest) from a leaked one (reap)
            argv += ["--manifest", man]
        self.proc = subprocess.Popen(
            argv, env=env, cwd=repo, stdout=out, stderr=out,
            start_new_session=True)
        if man is not None:
            self._write_manifest()
        self.state = STARTING
        self.created_at = time.monotonic()

    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def pid(self) -> int | None:
        return self.proc.pid if self.proc is not None else None

    def mark_dead(self) -> None:
        self.state = DEAD
        self._remove_manifest()
        if self._log_f is not None:
            try:
                self._log_f.close()
            except OSError:
                pass
            self._log_f = None

    # -- HTTP -----------------------------------------------------------------

    def _get_json(self, path: str, timeout: float | None = None):
        try:
            with urllib.request.urlopen(
                    self.url + path,
                    timeout=_probe_timeout() if timeout is None
                    else timeout) as r:
                return json.loads(r.read())
        except Exception:  # noqa: BLE001 — down/unready both read None
            return None

    def healthz_ok(self) -> bool:
        out = self._get_json("/healthz")
        return bool(out and out.get("alive"))

    def readyz_ok(self) -> bool:
        out = self._get_json("/readyz")
        return bool(out and out.get("ready"))

    def stats(self) -> dict | None:
        # the shared probe helper (3 attempts inside one probe
        # timeout each): an autoscale scrape that lands mid scoring
        # burst must not read a healthy replica as gone
        return probe_json(self.url, "/3/Stats", retries=3)

    def loaded_version(self) -> int | None:
        out = self._get_json("/3/ModelRegistry")
        if not out:
            return None
        info = (out.get("models") or {}).get(self.model_key)
        return info.get("version") if info else None

    # -- artifact push (background: warm-up compiles take seconds) -----------

    def start_load(self, registry: ModelRegistry) -> None:
        self.state = LOADING

        def push():
            try:
                # the whole tenant set (primary + extras), required-
                # set declared first: readiness flips only after
                # EVERY artifact is loaded + warmed
                registry.push_many(self.url, self.artifacts,
                                   warm_buckets=self.warm_buckets,
                                   timeout=_startup_deadline())
            except Exception as e:  # noqa: BLE001 — reconciler decides
                self._load_err = repr(e)[:300]
            finally:
                self._load_done = True

        self._load_thread = threading.Thread(
            target=push, name=f"h2o-pool-push-{self.rid}", daemon=True)
        self._load_thread.start()

    def load_finished(self) -> bool:
        return self._load_done

    def load_error(self) -> str | None:
        return self._load_err

    # -- retirement -----------------------------------------------------------

    def cordon(self) -> None:
        """Endpoint removal: readiness off, admission stays open."""
        try:
            req = urllib.request.Request(
                self.url + "/3/Cordon",
                data=json.dumps({"reason": "rollout"}).encode(),
                method="POST",
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=5.0):
                pass
        except Exception:  # noqa: BLE001 — a dead pod cordons itself
            pass
        self.state = CORDONED
        self.cordoned_at = time.monotonic()

    def terminate(self) -> None:
        """SIGTERM → the pod's PR-4 drain path (flush batcher, settle
        jobs, exit 0 inside H2O_TPU_DRAIN_TIMEOUT)."""
        if self.proc is not None and self.proc.poll() is None:
            try:
                self.proc.terminate()
            except ProcessLookupError:
                pass
        self.state = DRAINING
        self.drain_at = time.monotonic()

    def kill(self) -> None:
        if self.proc is not None and self.proc.poll() is None:
            try:
                self.proc.kill()
            except ProcessLookupError:
                pass


class AdoptedReplica(ScorerReplica):
    """A live pod inherited from a DEAD operator: same HTTP surface,
    but there is no Popen handle — liveness is pid-probed and signals
    go through os.kill. Everything else (push, cordon, the state
    machine) behaves exactly like a spawned replica, so adoptees ride
    the normal convergence path (a stale-version adoptee is cordoned +
    replaced by the standard surge-one rollout)."""

    def __init__(self, manifest: dict, version: int,
                 spec: ScorerPoolSpec, log_dir: str | None = None,
                 manifest_dir: str | None = None):
        super().__init__(manifest["rid"], version, spec,
                         log_dir=log_dir, manifest_dir=manifest_dir,
                         pool=manifest.get("pool"),
                         port=manifest["port"])
        self._pid = int(manifest["pid"])

    def spawn(self) -> None:   # pragma: no cover — adoptees exist
        raise RuntimeError("an adopted replica is already running")

    def alive(self) -> bool:
        try:
            os.kill(self._pid, 0)
            return True
        except ProcessLookupError:
            return False
        except PermissionError:   # pragma: no cover — exists, not ours
            return True

    def pid(self) -> int | None:
        return self._pid

    def terminate(self) -> None:
        import signal

        try:
            os.kill(self._pid, signal.SIGTERM)
        except ProcessLookupError:
            pass
        self.state = DRAINING
        self.drain_at = time.monotonic()

    def kill(self) -> None:
        import signal

        try:
            os.kill(self._pid, signal.SIGKILL)
        except ProcessLookupError:
            pass


class Reconciler:
    """Converge a pool of ScorerReplicas to its ScorerPoolSpec."""

    def __init__(self, store: PoolStore, registry: ModelRegistry,
                 pool: str, log_dir: str | None = None,
                 replica_factory=None, workdir: str | None = None,
                 adopted_factory=None):
        self.store = store
        self.registry = registry
        self.pool = pool
        # workdir: the pool's on-disk anchor — pod manifests (and, by
        # default, logs) live under it so a RESTARTED operator can
        # find its predecessor's pods. No workdir = no adoption
        # (exactly the PR-6 behavior).
        self.workdir = workdir
        self.manifest_dir = os.path.join(workdir, "pods") \
            if workdir else None
        self.log_dir = log_dir if log_dir is not None else (
            os.path.join(workdir, "logs") if workdir else None)
        # injectable for tests: factory(rid, version, spec) -> replica
        self.replica_factory = replica_factory or (
            lambda rid, version, spec: ScorerReplica(
                rid, version, spec, log_dir=self.log_dir,
                manifest_dir=self.manifest_dir, pool=self.pool))
        self.adopted_factory = adopted_factory or (
            lambda manifest, version, spec: AdoptedReplica(
                manifest, version, spec, log_dir=self.log_dir,
                manifest_dir=self.manifest_dir))
        self.replicas: list = []
        self._seq = 0
        self._last_totals: dict | None = None   # autoscale deltas
        # shard-aware autoscale: when set (ShardedPool wires it to the
        # shard's placed tenant set), the cumulative pressure counters
        # come from THOSE tenants' per-model stats — the shard whose
        # tenants shed scales, not whichever shard shares a counter
        self.autoscale_keys: set | None = None
        self._lock = threading.Lock()           # replicas list mutation
        self._stopped = False                   # shutdown() flips it
        self._adopted = False                   # adopt_existing ran
        # crash-loop backoff: version -> recent failure monotonics
        # (windowed — spacing) and cumulative counts (rollback trigger)
        self._failures: dict[int, list[float]] = {}
        self._fail_counts: dict[int, int] = {}
        self._backoff_announced: float = 0.0
        # rollout rollback: failed spec version -> pinned last-good
        self._rollback: dict[int, int] = {}
        self._last_good: int | None = None
        # a restarted operator resumes rollback/last-good state from
        # the durable store's status instead of re-trying a version
        # that already rolled back
        st = store.get_status(pool)
        if st.get("last_good_version") is not None:
            self._last_good = int(st["last_good_version"])
        ro = st.get("rollout") or {}
        if ro.get("failed_version") is not None and \
                ro.get("pinned_version") is not None:
            self._rollback[int(ro["failed_version"])] = \
                int(ro["pinned_version"])

    # -- events / status ------------------------------------------------------

    def _event(self, kind: str, msg: str = "") -> None:
        self.store.record_event(self.pool, kind, msg)
        # re-registered through the fleet-telemetry registry too:
        # the durable store keeps the bounded event ring, /metrics
        # (h2o_operator_events_total{event=...}) keeps the rates
        from ..runtime.telemetry import count_event

        count_event(kind)
        from ..diagnostics import log

        log.warning("operator[%s]: %s %s", self.pool, kind, msg)

    def endpoints(self) -> list[str]:
        """Routable endpoint URLs — the Service-endpoints analog.
        Cordoned/draining replicas are OUT the instant they cordon;
        not-yet-ready ones are included (the load generator's
        readiness poller filters on /readyz, like kube-proxy on
        endpoint readiness)."""
        with self._lock:
            return [r.url for r in self.replicas
                    if r.state in CAPACITY_STATES]

    def status(self) -> dict:
        with self._lock:
            reps = list(self.replicas)
        return {
            "replicas": [{"id": r.rid, "state": r.state,
                          "version": r.version, "port": r.port,
                          "pid": r.pid()} for r in reps],
            "ready": sum(1 for r in reps if r.state == READY),
        }

    def _want_version(self, spec: ScorerPoolSpec) -> int:
        """The version this pool should actually converge on: the
        spec's, unless that version auto-rolled-back — then the pinned
        last-good version until the spec moves to a NEW version."""
        return self._rollback.get(spec.version, spec.version)

    def converged(self, spec: ScorerPoolSpec | None = None) -> bool:
        if spec is None:
            spec, _ = self.store.get(self.pool)
        want = self._want_version(spec)
        with self._lock:
            reps = list(self.replicas)
        # alive() is checked HERE, not just at reconcile time: a
        # replica SIGKILLed an instant ago is still READY in controller
        # state until the next pass observes it, and a wait_converged
        # racing that pass must not declare victory over a dead pod
        current_ready = [r for r in reps if r.state == READY
                         and r.version == want and r.alive()]
        leftovers = [r for r in reps if r.state != DEAD
                     and not (r.state == READY
                              and r.version == want
                              and r.alive())]
        return len(current_ready) == spec.replicas and not leftovers

    # -- adoption (operator restart) ------------------------------------------

    def _probe_stats(self, url: str) -> dict | None:
        """GET /3/Stats off a candidate adoptee — identity fields
        (pool/replica/pid), lifecycle state, and loaded model versions
        in one device-free scrape, through the shared probe helper
        (probe timeout + 3 attempts: one timed-out scrape under a
        scoring burst must not get a healthy pod killed). Injectable
        for tests."""
        return probe_json(url, "/3/Stats", retries=3)

    @staticmethod
    def _pid_alive(pid: int) -> bool:
        try:
            os.kill(int(pid), 0)
            return True
        except ProcessLookupError:
            return False
        except PermissionError:   # pragma: no cover
            return True

    def scan_manifests(self) -> list[dict]:
        """Valid pod manifests under the pool workdir (pidfile/port
        records dropped at spawn). Unparseable files are removed —
        only the atomic writer produces them, so garbage is foreign."""
        if not self.manifest_dir:
            return []
        out = []
        try:
            names = sorted(os.listdir(self.manifest_dir))
        except OSError:
            return []
        for n in names:
            if not n.endswith(".json"):
                continue
            path = os.path.join(self.manifest_dir, n)
            try:
                with open(path) as f:
                    doc = json.load(f)
                if not all(k in doc for k in
                           ("rid", "pool", "pid", "port")):
                    raise ValueError("missing keys")
            except (OSError, ValueError):
                try:
                    os.remove(path)
                except OSError:
                    pass
                continue
            if doc.get("pool") == self.pool:
                out.append(doc)
        return out

    def adopt_existing(self) -> int:
        """Adopt this pool's still-live pods after an operator restart
        instead of spawning duplicates (ISSUE 9 tentpole). For every
        manifest: dead pid → stale, cleaned up; live + identity match
        (pool/rid/pid off /3/Stats) → adopted in its OBSERVED state —
        READY at its loaded version (a stale version is then rolled
        through normal convergence), cordoned stays CORDONED (drains
        after the grace), mid-load orphans restart the push as
        STARTING; identity mismatch → the process is left alone but
        the manifest is dropped (port reuse by a stranger); live but
        unresponsive → killed (it cannot serve and nothing else will
        ever reap it). Returns the number of pods adopted. Runs once,
        BEFORE the first reconcile pass (run() enforces the order)."""
        self._adopted = True
        if not self.manifest_dir:
            return 0
        spec, _ = self.store.get(self.pool)
        want = self._want_version(spec)
        adopted = 0
        with self._lock:
            known = {r.rid for r in self.replicas}
        for man in self.scan_manifests():
            rid, pid, port = man["rid"], man["pid"], man["port"]
            if rid in known:
                continue
            path = os.path.join(self.manifest_dir, f"{rid}.json")
            if not self._pid_alive(pid):
                self._event("adoption_stale",
                            f"{rid} manifest pid {pid} is gone — "
                            "cleaned up")
                try:
                    os.remove(path)
                except OSError:
                    pass
                continue
            # _probe_stats retries internally (the shared probe
            # helper): killing a live pod on ONE timed-out scrape
            # (GIL-bound scoring burst, transient reset) would break
            # the 'data plane never notices' contract adoption exists
            # for
            st = self._probe_stats(f"http://127.0.0.1:{port}")
            ident = (st or {}).get("identity") or {}
            if st is not None and (
                    ident.get("pool") != self.pool
                    or ident.get("replica") != rid
                    or (ident.get("pid") is not None
                        and int(ident["pid"]) != int(pid))):
                self._event("adoption_foreign",
                            f"{rid}: port {port} answers as "
                            f"{ident.get('pool')}/{ident.get('replica')}"
                            " — not ours, manifest dropped")
                try:
                    os.remove(path)
                except OSError:
                    pass
                continue
            if st is None:
                age = time.time() - float(man.get("created_at") or 0)
                if 0 <= age <= _startup_deadline():
                    # live pid, HTTP not up YET: spawned moments
                    # before the old operator died — adopt as
                    # STARTING; the normal startup deadline replaces
                    # it if it never comes up
                    r = self.adopted_factory(man, want, spec)
                    r.created_at = time.monotonic()
                    r.state = STARTING
                    with self._lock:
                        self.replicas.append(r)
                    adopted += 1
                    self._event("replica_adopted",
                                f"{rid} pid {pid} port {port} adopted "
                                "(still booting)")
                    continue
                # live pid, dead HTTP, well past any boot window: it
                # can never serve and no other process will ever reap
                # it — kill, then replace via the normal spawn path
                self._event("adoption_unresponsive",
                            f"{rid} pid {pid} alive but /3/Stats "
                            f"unreachable after {age:.0f}s — killing")
                try:
                    import signal

                    os.kill(int(pid), signal.SIGKILL)
                except OSError:
                    pass
                try:
                    os.remove(path)
                except OSError:
                    pass
                continue
            loaded = ((st.get("registry") or {})
                      .get(spec.model_key) or {}).get("version")
            cordoned = (st.get("cordoned") or
                        any("cordon" in str(rs)
                            for rs in st.get("reasons") or ()))
            if st.get("ready") and loaded is not None:
                r = self.adopted_factory(man, int(loaded), spec)
                r.state = READY
                note = f"READY v{loaded}"
            elif cordoned:
                r = self.adopted_factory(
                    man, int(loaded or man.get("version") or want),
                    spec)
                r.cordoned_at = time.monotonic()
                r.state = CORDONED
                note = "cordoned — resuming drain"
            else:
                # mid-load orphan: its pusher died with the old
                # operator; adopt at the TARGET version and re-drive
                # the push through the normal STARTING path (the load
                # route is idempotent)
                r = self.adopted_factory(man, want, spec)
                r.created_at = time.monotonic()
                r.state = STARTING
                note = "mid-load — re-pushing"
            with self._lock:
                self.replicas.append(r)
            adopted += 1
            self._event("replica_adopted",
                        f"{rid} pid {pid} port {port} adopted "
                        f"({note})")
        # rid sequence must clear every adopted rid or a fresh spawn
        # would collide with a live pod's identity
        with self._lock:
            for r in self.replicas:
                tail = r.rid.rsplit("-", 1)[-1]
                if tail.isdigit():
                    self._seq = max(self._seq, int(tail))
        return adopted

    # -- crash-loop backoff + rollout rollback --------------------------------

    def _record_failure(self, version: int) -> None:
        """One non-graceful replica failure (unexpected exit, load
        failure, startup timeout) of `version`: feeds BOTH the
        windowed backoff history (respawn spacing) and the cumulative
        per-version count (the rollback trigger)."""
        now = time.monotonic()
        window = _backoff_window()
        hist = self._failures.setdefault(int(version), [])
        hist[:] = [t for t in hist if now - t <= window]
        hist.append(now)
        self._fail_counts[int(version)] = \
            self._fail_counts.get(int(version), 0) + 1

    def _backoff_remaining(self, version: int, now: float) -> float:
        """Seconds until a replacement of `version` may spawn. The
        FIRST failure in the window replaces immediately (a one-off
        OOM-kill must not slow recovery — the replica-kill drill's
        contract); from the second on, base·2^(n-2) capped at
        H2O_TPU_POOL_BACKOFF_MAX — a crash loop becomes spaced
        respawns instead of a hot loop."""
        hist = self._failures.get(int(version))
        if not hist:
            return 0.0
        window = _backoff_window()
        hist[:] = [t for t in hist if now - t <= window]
        n = len(hist)
        if n < 2:
            return 0.0
        delay = min(_backoff_cap(), _backoff_base() * (2 ** (n - 2)))
        return max(0.0, hist[-1] + delay - now)

    def _maybe_rollback(self, spec: ScorerPoolSpec) -> None:
        """Auto-rollback: when the rollout's new version has failed
        its warm-up/readiness H2O_TPU_POOL_ROLLOUT_RETRIES times and a
        last-good version exists, pin the pool to last-good. Old
        replicas are never disturbed; the spec stays at the failed
        version (the operator's declared intent is preserved and a
        NEW version bump supersedes the pin)."""
        want = spec.version
        if want in self._rollback or self._last_good is None \
                or self._last_good == want:
            return
        if self._fail_counts.get(want, 0) < _rollout_retries():
            return
        self._rollback = {want: self._last_good}
        self._event("rollout_rolled_back",
                    f"v{want} failed readiness "
                    f"{self._fail_counts[want]} times — pool pinned "
                    f"to last-good v{self._last_good}; push a new "
                    "version to retry")

    # -- the loop -------------------------------------------------------------

    def _prune_logs(self) -> None:
        """Cap the pool log dir so a crash-looping pod cannot fill the
        disk the durable store lives on: keep the newest
        H2O_TPU_POOL_LOG_KEEP files, but NEVER delete a live
        replica's open log (its fd would keep writing to an unlinked
        inode and the crash-diagnosis artifact would be silently
        lost)."""
        if not self.log_dir:
            return
        with self._lock:
            live = {r.rid for r in self.replicas if r.state != DEAD}
        try:
            logs = sorted(
                (os.path.join(self.log_dir, n)
                 for n in os.listdir(self.log_dir)
                 if ".log" in n
                 and n.split(".log", 1)[0] not in live),
                key=lambda p: os.path.getmtime(p))
        except OSError:
            return
        for stale in logs[:max(0, len(logs) - _log_keep())]:
            try:
                os.remove(stale)
            except OSError:
                pass

    def _spawn(self, version: int, spec: ScorerPoolSpec):
        self._prune_logs()
        with self._lock:
            if self._stopped:
                return None
            self._seq += 1
            rid = f"{self.pool}-{self._seq}"
        r = self.replica_factory(rid, version, spec)
        r.spawn()
        with self._lock:
            if self._stopped:
                # shutdown() completed between the check above and the
                # Popen: the torn-down pool must not gain a live pod
                # nothing will ever terminate — kill it right here
                r.kill()
                r.mark_dead()
                return None
            self.replicas.append(r)
        self._event("replica_start",
                    f"{rid} v{version} port={getattr(r, 'port', '?')}")
        return r

    def reconcile_once(self) -> None:
        if self._stopped:
            # shutdown() won the race with a still-running run() loop:
            # reconciling now would re-provision the pool it just tore
            # down and leak pods past the caller's teardown
            return
        spec, gen = self.store.get(self.pool)
        now = time.monotonic()
        deadline = _startup_deadline()
        grace = _deregister_grace()

        # 1. observe process deaths (replica-kill converges from here)
        for r in list(self.replicas):
            if r.state in (DEAD, PENDING):
                continue
            if not r.alive():
                if r.state == DRAINING:
                    self._event("replica_exit",
                                f"{r.rid} drained and exited")
                elif r.state == CORDONED:
                    self._event("replica_exit",
                                f"{r.rid} exited while cordoned")
                else:
                    self._event("replica_died",
                                f"{r.rid} v{r.version} "
                                f"(port {r.port}) exited unexpectedly")
                    self._record_failure(r.version)
                r.mark_dead()
        with self._lock:
            self.replicas = [r for r in self.replicas
                             if r.state != DEAD]

        # 2. advance startups: healthz → push+warm → readyz
        for r in self.replicas:
            if r.state == STARTING:
                if r.healthz_ok():
                    r.start_load(self.registry)
                    buckets = "env default" if r.warm_buckets is None \
                        else str(list(r.warm_buckets))
                    self._event("replica_load",
                                f"{r.rid} pushing {r.artifact} "
                                f"v{r.version} + warming {buckets}")
                elif now - r.created_at > deadline:
                    self._event("replica_startup_timeout",
                                f"{r.rid} no /healthz after "
                                f"{deadline:.0f}s — replacing")
                    self._record_failure(r.version)
                    r.kill()
                    r.mark_dead()
            elif r.state == LOADING:
                err = r.load_error()
                if err is not None:
                    self._event("replica_load_failed",
                                f"{r.rid}: {err}")
                    self._record_failure(r.version)
                    r.kill()
                    r.mark_dead()
                elif r.load_finished() and r.readyz_ok():
                    r.state = READY
                    # the version provably serves: clear its failure
                    # history so one old flake can't feed a later
                    # rollback, and remember it as rollback target
                    self._failures.pop(r.version, None)
                    self._fail_counts.pop(r.version, None)
                    self._event("replica_ready",
                                f"{r.rid} v{r.version} warmed — "
                                "readyz green")
                elif now - r.created_at > deadline:
                    self._event("replica_startup_timeout",
                                f"{r.rid} not READY after "
                                f"{deadline:.0f}s — replacing")
                    self._record_failure(r.version)
                    r.kill()
                    r.mark_dead()
        with self._lock:
            self.replicas = [r for r in self.replicas
                             if r.state != DEAD]

        # 3. cordoned replicas past the deregister grace drain now;
        # wedged drains get SIGKILL well past the pod's own budget
        drain_budget = _env_float("H2O_TPU_DRAIN_TIMEOUT", 30.0)
        for r in self.replicas:
            if r.state == CORDONED and now - r.cordoned_at >= grace:
                r.terminate()
                self._event("replica_drain",
                            f"{r.rid} SIGTERM after {grace:.2f}s "
                            "deregister grace")
            elif r.state == DRAINING and \
                    now - r.drain_at > drain_budget + 15.0:
                self._event("replica_drain_wedged",
                            f"{r.rid} still alive "
                            f"{drain_budget + 15:.0f}s after SIGTERM "
                            "— SIGKILL")
                r.kill()

        # 4. converge version + count (surge-one rolling update).
        # A rollout whose new version keeps failing rolls back to the
        # pinned last-good version; respawns of a crash-looping
        # version are backoff-spaced instead of hot-looped.
        self._maybe_rollback(spec)
        want = self._want_version(spec)
        # stale replicas that never went READY are superseded work —
        # kill outright, nothing routes to them
        for r in list(self.replicas):
            if r.version != want and r.state in (STARTING, LOADING):
                self._event("replica_superseded",
                            f"{r.rid} v{r.version} superseded by "
                            f"v{want} before READY")
                r.kill()
                r.mark_dead()
        with self._lock:
            self.replicas = [r for r in self.replicas
                             if r.state != DEAD]
        capacity = [r for r in self.replicas
                    if r.state in CAPACITY_STATES]
        current = [r for r in capacity if r.version == want]
        stale_ready = [r for r in capacity
                       if r.version != want and r.state == READY]
        ready = [r for r in capacity if r.state == READY]

        backoff_left = 0.0
        if len(current) < spec.replicas and \
                len(capacity) < spec.replicas + 1:
            backoff_left = self._backoff_remaining(want, now)
            if backoff_left <= 0.0:
                # scale up / replace dead / surge the rollout — one
                # spawn per pass keeps the surge at one
                self._spawn(want, spec)
            elif now >= self._backoff_announced:
                n = len(self._failures.get(want, ()))
                self._event("crash_loop_backoff",
                            f"v{want} failed {n}x recently — next "
                            f"respawn in {backoff_left:.2f}s")
                # announce once per wait, not every 0.5s pass
                self._backoff_announced = now + backoff_left
        elif stale_ready and len(ready) > spec.replicas:
            # a new-version replica is READY beyond the desired count:
            # retire ONE old-version replica — cordon first (routers
            # drop the endpoint), drain after the grace (step 3)
            victim = stale_ready[0]
            victim.cordon()
            self._event("replica_cordon",
                        f"{victim.rid} v{victim.version} cordoned "
                        f"(rollout to v{want})")
        elif not stale_ready and len(current) > spec.replicas:
            # spec resize down: prefer retiring a not-yet-ready spare
            spares = [r for r in current if r.state != READY]
            if spares:
                victim = spares[-1]
                self._event("replica_scaled_down",
                            f"{victim.rid} (not yet ready) stopped — "
                            f"replicas={spec.replicas}")
                victim.kill()
                victim.mark_dead()
            else:
                victim = current[-1]
                victim.cordon()
                self._event("replica_cordon",
                            f"{victim.rid} cordoned (scale down to "
                            f"{spec.replicas})")
        with self._lock:
            self.replicas = [r for r in self.replicas
                             if r.state != DEAD]

        # 5. publish observed status (generation-fenced: if another
        # controller bumped the spec since this pass read it, OUR view
        # is stale — drop the write, the next pass re-reads)
        conv = self.converged(spec)
        if conv:
            # every desired replica READY on the effective version:
            # this version provably serves — the rollback target
            self._last_good = want
        st = self.status()
        by_version: dict[str, int] = {}
        for r in st["replicas"]:
            if r["state"] == READY:
                by_version[str(r["version"])] = \
                    by_version.get(str(r["version"]), 0) + 1
        status = {
            "generation_observed": gen,
            "desired_replicas": spec.replicas,
            "desired_version": spec.version,
            "effective_version": want,
            "last_good_version": self._last_good,
            "ready_by_version": by_version,
            "converged": conv,
            **st,
        }
        if spec.version in self._rollback:
            status["rollout"] = {
                "failed_version": spec.version,
                "pinned_version": self._rollback[spec.version],
                "state": "rolled_back",
            }
        if backoff_left > 0.0:
            status["crash_loop"] = {
                "version": want,
                "recent_failures": len(self._failures.get(want, ())),
                "next_spawn_in": round(backoff_left, 3),
            }
        from .spec import StaleGenerationError

        try:
            self.store.set_status(self.pool, status, fence=gen)
        except StaleGenerationError:
            pass

    def run(self, stop: threading.Event,
            interval: float | None = None) -> None:
        """Blocking loop (callers thread it); autoscale piggybacks on
        the same cadence when the spec opts in. Adoption runs FIRST:
        reconciling before the predecessor's pods are adopted would
        spawn duplicates of every live pod."""
        if not self._adopted:
            try:
                self.adopt_existing()
            except Exception as e:  # noqa: BLE001 — loop must start
                self._event("adoption_error", repr(e)[:300])
        while not stop.is_set():
            try:
                self.reconcile_once()
                self.autoscale_once()
            except Exception as e:  # noqa: BLE001 — the loop survives
                self._event("reconcile_error", repr(e)[:300])
            stop.wait(interval if interval is not None else _interval())

    def wait_converged(self, timeout: float = 120.0) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.converged():
                return True
            time.sleep(0.1)
        return self.converged()

    def shutdown(self, timeout: float = 60.0) -> None:
        """Drain every replica (tests/drills teardown): stop
        reconciling first (a racing run() pass must not re-provision
        what this tears down), SIGTERM all, SIGKILL stragglers at the
        deadline."""
        with self._lock:
            # one atomic step: after this, _spawn either sees _stopped
            # (and kills its own pod) or its replica is in this
            # snapshot — no pod can fall between the two
            self._stopped = True
            reps = list(self.replicas)
        for r in reps:
            if r.state not in (DEAD,):
                r.terminate()
        deadline = time.monotonic() + timeout
        for r in reps:
            while r.alive() and time.monotonic() < deadline:
                time.sleep(0.1)
            if r.alive():
                r.kill()
            r.mark_dead()
        with self._lock:
            self.replicas = []

    # -- autoscale ------------------------------------------------------------

    def autoscale_once(self) -> int | None:
        """Scrape /3/Stats off READY replicas and apply the autoscale
        signal to the spec (when ``spec.autoscale``); returns the new
        desired count or None when disabled/unchanged."""
        spec, _ = self.store.get(self.pool)
        if not spec.autoscale:
            return None
        with self._lock:
            ready = [r for r in self.replicas if r.state == READY]
        samples = [s for s in (r.stats() for r in ready) if s]
        from .autoscale import desired_replicas

        desired, why, totals = desired_replicas(
            spec, samples, self._last_totals,
            model_keys=self.autoscale_keys)
        self._last_totals = totals
        if desired != spec.replicas:
            self.store.apply_update(self.pool, replicas=desired)
            self._event("autoscale",
                        f"replicas {spec.replicas} -> {desired} "
                        f"({why})")
            return desired
        return None


# ---------------------------------------------------------------------------
# Sharded pools: placement + re-placement over child reconcilers
# ---------------------------------------------------------------------------


class ShardedPool:
    """A tenant-sharded fleet: one child Reconciler per shard, each
    converging a child pool that holds only the tenants placement put
    there (operator/placement.py — rendezvous hashing, the Zipf head
    replicated on every shard, the tail on ``tail_replicas``), plus
    the failure half:

    - **shard health** is derived from the children's observed state
      (a shard with zero live READY replicas is DOWN);
    - **re-placement**: a tail tenant whose every placed shard is down
      is re-placed onto the next surviving shard in its rendezvous
      preference order — a TARGETED ``registry.push`` of that one
      artifact to the survivor's live replicas (never a full-catalog
      re-push), the survivor's child spec extended so future spawns of
      that shard keep serving it, and the routing table extended so
      the router finds it (the degraded-503 window closes);
    - **shard-aware autoscale**: each child reconciler autoscales its
      OWN shard from its own replicas' /3/Stats, with the pressure
      counters attributed to the shard's placed tenants
      (``Reconciler.autoscale_keys``) — the shard whose tenants shed
      scales, not the pool.

    The level-triggered discipline carries over: every pass re-derives
    placement health from observed state; ``overrides`` (re-placements
    already pushed) are the only memory, and re-deriving them costs an
    idempotent push at worst. The parent pool's spec is the single
    declarative input — child specs are derived, and a parent change
    (version bump, resize) re-derives and re-applies them, so rolling
    updates ride the existing surge-one machinery per shard."""

    def __init__(self, store: PoolStore, registry: ModelRegistry,
                 pool: str, workdir: str | None = None,
                 log_dir: str | None = None, replica_factory=None):
        self.store = store
        self.registry = registry
        self.pool = pool
        self.workdir = workdir
        self.log_dir = log_dir
        self.replica_factory = replica_factory
        self.recs: dict[str, Reconciler] = {}
        self.plan: PlacementPlan | None = None
        # key -> tuple of EXTRA shard ids the tenant was re-placed
        # onto (appended to the plan's preference order for routing)
        self.overrides: dict[str, tuple] = {}
        self._gen_seen: int | None = None
        self._parent_replicas: int | None = None
        self._lock = threading.Lock()
        self._down_since: dict[str, float] = {}
        # run()-managed child reconciler threads, one per shard, each
        # with its OWN stop event so a shard removed by a spec change
        # can be stopped + drained without touching its siblings
        self._child_threads: dict[str, threading.Thread] = {}
        self._child_stops: dict[str, threading.Event] = {}
        # shards that have served at least once: re-placement (and the
        # degraded accounting) applies to shards that were LOST, never
        # to shards still converging toward their first READY replica
        # — re-placing a booting shard's tenants would double-place
        # the whole catalog on every cold start
        self._ever_healthy: set = set()
        # a RESTARTED controller resumes re-placement state from the
        # durable status it published (the PR-9 rollback-pin pattern):
        # without this, the restart would re-derive child specs from
        # the plan alone — clobbering the survivors' extended specs —
        # and a shard that died BEFORE the restart would read as
        # "still converging" forever, leaving its tenants degraded
        # with no recovery path
        # HA: the lease epoch this controller reconciles under (None =
        # not lease-managed, the single-controller mode). Every routing
        # publish is fenced on it; a fence rejection marks the
        # controller DEPOSED — it stops reconciling and leaves its pods
        # for the new holder to adopt (split-brain ends with exactly
        # one writer, and no pod is ever killed by the loser).
        self.lease_epoch: int | None = None
        self.deposed = False
        # hot-shard rebalancing state: key -> {"src", "dst", "t",
        # "state": serving|retired, "retired": [aged-out sources]}.
        # Deliberately SEPARATE from `overrides`: overrides are
        # loss-driven copies that failback removes once the home shard
        # recovers; moves are load-driven placements that persist (a
        # reverse move is the same primitive, not a failback).
        self.moves: dict[str, dict] = {}
        self._tenant_prev: dict[str, dict] = {}   # sid -> per-key totals
        self._pressure_hits: dict[str, int] = {}  # sid -> consecutive
        self._healthy_since: dict[str, float] = {}
        self._last_move_t = 0.0
        st = store.get_status(pool)
        pl = st.get("placement") or {}
        self.overrides = {k: tuple(v) for k, v in
                          (pl.get("overrides") or {}).items()}
        self._ever_healthy = set(pl.get("ever_healthy") or ())
        # moves resume like overrides do: a restarted (or takeover)
        # controller must keep serving moved tenants from their
        # destination, not snap placement back to the plan
        self.moves = {k: dict(v) for k, v in
                      (pl.get("moves") or {}).items()}
        self._ensure_children()

    # -- derivation -----------------------------------------------------------

    def _event(self, kind: str, msg: str = "") -> None:
        self.store.record_event(self.pool, kind, msg)
        # re-registered through the fleet-telemetry registry too:
        # the durable store keeps the bounded event ring, /metrics
        # (h2o_operator_events_total{event=...}) keeps the rates
        from ..runtime.telemetry import count_event

        count_event(kind)
        from ..diagnostics import log

        log.warning("operator[%s]: %s %s", self.pool, kind, msg)

    def shard_ids(self, spec: ScorerPoolSpec | None = None) -> list:
        if spec is None:
            spec, _ = self.store.get(self.pool)
        return [f"{self.pool}-s{i}" for i in range(max(1, spec.shards))]

    @staticmethod
    def _catalog(spec: ScorerPoolSpec) -> dict:
        """model_key -> (artifact, version, model_key, slo), catalog
        (= popularity) order preserved by dict insertion."""
        return {ent[2]: tuple(ent) for ent in spec.all_artifacts()}

    def _derive_plan(self, spec: ScorerPoolSpec) -> PlacementPlan:
        return plan_placement(list(self._catalog(spec)),
                              self.shard_ids(spec),
                              head=spec.head_models,
                              tail_replicas=spec.tail_replicas)

    def _child_spec(self, spec: ScorerPoolSpec, sid: str,
                    plan: PlacementPlan) -> ScorerPoolSpec:
        catalog = self._catalog(spec)
        keys = [k for k in plan.keys_for(sid)]
        for key, extra_sids in self.overrides.items():
            if sid in extra_sids and key not in keys and key in catalog:
                keys.append(key)
        for key, mv in self.moves.items():
            if key not in catalog:
                continue
            if mv.get("dst") == sid and key not in keys:
                keys.append(key)
            if sid in (mv.get("retired") or ()) and key in keys:
                # retired move source: future spawns of this shard no
                # longer carry the tenant — the destination owns it
                keys.remove(key)
        extra = tuple(catalog[k] for k in keys if k != spec.model_key)
        replicas = spec.replicas
        try:
            cur, _ = self.store.get(sid)
            if spec.autoscale or spec.replicas == self._parent_replicas:
                # keep the child's own width when (a) it autoscales
                # itself, or (b) the PARENT's replicas field did not
                # change — a reapply triggered by some other field
                # (version bump, head tweak) or by a re-placement
                # spec extension must not clobber a directly-resized
                # child (an operator's capacity-zero on a lost shard,
                # a survivor scaled up mid-incident). An explicit
                # parent resize still flows into every shard.
                replicas = cur.replicas
        except KeyError:
            pass
        return _dc_replace(
            spec, name=sid, replicas=replicas, extra_artifacts=extra,
            shards=1, head_models=min(1, len(keys) or 1),
            tail_replicas=1)

    def _recs_snapshot(self) -> dict:
        """Stable view of the child map: _ensure_children mutates it
        under the lock when the shard set changes, and the router's
        request path iterates it (routing_table) — iterating the live
        dict would RuntimeError mid-reconfiguration."""
        with self._lock:
            return dict(self.recs)

    def _ensure_children(self) -> None:
        """Derive + apply the child specs and build one Reconciler per
        shard. Re-runs whenever the parent spec generation moved (a
        version bump or resize flows into every child, riding the
        normal per-shard surge-one rollout); a shard REMOVED by the
        change is stopped, drained, and deleted from the store — its
        tenants already live in the re-derived plan of the survivors."""
        spec, gen = self.store.get(self.pool)
        if gen == self._gen_seen and self.recs:
            return
        removed: list = []
        with self._lock:
            if gen == self._gen_seen and self.recs:
                return
            plan = self._derive_plan(spec)
            # a changed shard SET invalidates the overrides (they name
            # shards that may no longer exist); a same-shape reapply
            # keeps them — orphans are re-detected level-triggered
            # either way, re-placement is idempotent
            if self.plan is not None and \
                    self.plan.shards != plan.shards:
                self.overrides.clear()
            self.plan = plan
            want = set(self.shard_ids(spec))
            for sid in sorted(set(self.recs) - want):
                removed.append((sid, self.recs.pop(sid)))
                self._ever_healthy.discard(sid)
                self._down_since.pop(sid, None)
            for sid in self.shard_ids(spec):
                child = self._child_spec(spec, sid, plan)
                self.store.apply(child)
                if sid not in self.recs:
                    wd = os.path.join(self.workdir, sid) \
                        if self.workdir else None
                    ld = os.path.join(self.log_dir, sid) \
                        if self.log_dir else None
                    self.recs[sid] = Reconciler(
                        self.store, self.registry, sid, log_dir=ld,
                        workdir=wd,
                        replica_factory=self.replica_factory)
                self._set_autoscale_keys(sid)
            self._gen_seen = gen
            self._parent_replicas = spec.replicas
        for sid, rec in removed:
            ev = self._child_stops.pop(sid, None)
            if ev is not None:
                ev.set()
            self._child_threads.pop(sid, None)
            self._event("shard_removed",
                        f"{sid} left the shard set — draining")
            # drain outside the lock and off this thread: retiring a
            # shard's pods can take a full drain window and must not
            # stall routing_table() or the surviving shards' loop
            threading.Thread(target=self._retire_child,
                             args=(sid, rec), daemon=True).start()

    def _retire_child(self, sid: str, rec: "Reconciler") -> None:
        try:
            rec.shutdown(timeout=90)
        finally:
            try:
                self.store.delete(sid)
            except Exception:  # noqa: BLE001 — cleanup is best-effort
                pass

    def _set_autoscale_keys(self, sid: str) -> None:
        keys = set(self.plan.keys_for(sid)) if self.plan else set()
        keys.update(k for k, sids in self.overrides.items()
                    if sid in sids)
        for k, mv in self.moves.items():
            if mv.get("dst") == sid:
                keys.add(k)
            if sid in (mv.get("retired") or ()):
                keys.discard(k)
        self.recs[sid].autoscale_keys = keys

    # -- health + re-placement ------------------------------------------------

    def shard_healthy(self, sid: str) -> bool:
        """A shard serves iff it has at least one live READY replica —
        derived from the child's OBSERVED state (the reconciler just
        probed these pods), no extra HTTP."""
        rec = self.recs.get(sid)
        if rec is None:
            return False
        with rec._lock:
            reps = list(rec.replicas)
        return any(r.state == READY and r.alive() for r in reps)

    def _placed_shards(self, key: str) -> tuple:
        base = (self.plan.assignments.get(key, ())
                + self.overrides.get(key, ()))
        mv = self.moves.get(key)
        if mv:
            gone = set(mv.get("retired") or ())
            base = tuple(s for s in base if s not in gone)
            if mv.get("state") == "serving":
                # make-before-break window: the source MUST keep
                # serving (even a source that itself entered via an
                # earlier move and is not in the plan)
                src = mv.get("src")
                if src and src not in base:
                    base = base + (src,)
            dst = mv.get("dst")
            if dst:
                # destination first: preference position 0 is what
                # actually moves the traffic off the hot shard
                base = (dst,) + tuple(s for s in base if s != dst)
        return base

    def _health_maps(self) -> tuple[dict, dict]:
        """(actual, effective) shard health. ``actual`` is the live
        has-a-READY-replica answer (push targets use it); ``effective``
        additionally treats a shard as not-down while it (a) has NEVER
        been healthy — a cold-starting shard is converging, not lost —
        or (b) has not finished pod ADOPTION yet: a restarted
        controller's children inherit live pods on their first pass,
        and judging a shard lost in the window before that pass would
        spuriously re-place a healthy fleet's whole catalog."""
        actual = {sid: self.shard_healthy(sid)
                  for sid in (self.plan.shards if self.plan else ())}
        for sid, ok in actual.items():
            if ok:
                self._ever_healthy.add(sid)
        effective = {}
        for sid, ok in actual.items():
            rec = self.recs.get(sid)
            adopted = bool(rec is not None and rec._adopted)
            effective[sid] = (ok or sid not in self._ever_healthy
                              or not adopted)
        return actual, effective

    def pending_orphans(self) -> list:
        """Tenants currently unservable: every placed shard was lost.
        The router 503s these with the ``placement_pending`` hint
        until re-placement (or shard recovery) closes the gap."""
        if self.plan is None:
            return []
        _, effective = self._health_maps()
        return [k for k in self.plan.assignments
                if not any(effective.get(s) for s in
                           self._placed_shards(k))]

    def _push_tenant(self, key: str, sid: str,
                     spec: ScorerPoolSpec) -> bool:
        """Targeted push of ONE tenant's artifact to every live READY
        replica of ``sid`` (each replica must hold the full shard
        set). Returns False on any failure — the level-triggered loop
        retries next pass. Deliberately does NOT touch the replica's
        required-model set: extending it mid-push would flip a serving
        replica unready; the child-spec update below covers future
        spawns instead."""
        ent = self._catalog(spec).get(key)
        rec = self.recs.get(sid)
        if ent is None or rec is None:
            return False
        with rec._lock:
            targets = [r for r in rec.replicas
                       if r.state == READY and r.alive()]
        if not targets:
            return False
        name, version, model_key, slo = ent
        buckets = None if spec.warm_buckets is None \
            else list(spec.warm_buckets)
        for r in targets:
            try:
                self.registry.push(r.url, name, int(version), model_key,
                                   warm_buckets=buckets, slo=slo)
            except Exception as e:  # noqa: BLE001 — retry next pass
                self._event("tenant_replace_failed",
                            f"'{key}' -> {sid} ({r.rid}): "
                            f"{repr(e)[:200]}")
                return False
        return True

    def _replace_once(self) -> int:
        """One re-placement pass: every orphaned tenant (all placed
        shards down) is pushed onto the first HEALTHY shard in its
        rendezvous preference order. Catalog order = popularity order,
        so the hottest orphans close their degraded window first.
        Returns the number of tenants re-placed this pass."""
        if self.plan is None:
            return 0
        spec, _ = self.store.get(self.pool)
        actual, effective = self._health_maps()
        for sid, down in ((s, not ok) for s, ok in effective.items()):
            if down and sid not in self._down_since:
                self._down_since[sid] = time.monotonic()
                self._event("shard_down",
                            f"{sid} has no live READY replica")
            elif not down and sid in self._down_since:
                dt = time.monotonic() - self._down_since.pop(sid)
                self._event("shard_recovered",
                            f"{sid} serving again after {dt:.1f}s")
        if not any(actual.values()):
            return 0          # nowhere to re-place onto
        moved = 0
        for key in list(self.plan.assignments):
            placed = self._placed_shards(key)
            if any(effective.get(s) for s in placed):
                continue
            # re-check live health before each push: if the home
            # shard recovered mid-loop, the remaining orphans are
            # served again and need no re-placement
            actual, effective = self._health_maps()
            if any(effective.get(s) for s in placed):
                continue
            for sid in shard_preference(key, self.plan.shards):
                if sid in placed or not actual.get(sid):
                    continue
                if self._push_tenant(key, sid, spec):
                    self.overrides[key] = \
                        self.overrides.get(key, ()) + (sid,)
                    moved += 1
                    self._event(
                        "tenant_replaced",
                        f"'{key}' re-placed onto {sid} (home "
                        f"shard(s) {list(placed)} down)")
                    # durable intent: future spawns of the survivor
                    # carry the tenant (same version — no rollout)
                    try:
                        self.store.apply(
                            self._child_spec(spec, sid, self.plan))
                    except Exception as e:  # noqa: BLE001
                        self._event("tenant_replace_spec_error",
                                    repr(e)[:200])
                    self._set_autoscale_keys(sid)
                break
        return moved

    # -- hot-shard rebalancing (make-before-break moves) ----------------------

    def _move_tenant(self, key: str, src: str, dst: str,
                     spec: ScorerPoolSpec) -> bool:
        """Make-before-break move of one tenant: the destination's
        live replicas get the artifact FIRST (``registry.push``
        returns only once loaded AND warmed — that IS the destination
        READY-verification), then the move lands in the routing table
        with the destination in preference position 0 while the source
        still serves, and ``_retire_moves`` drops the source only
        after ``H2O_TPU_REBALANCE_RETIRE_S``. Reversible: a later move
        in the opposite direction is the same primitive."""
        if not self._push_tenant(key, dst, spec):
            return False
        old = self.moves.get(key) or {}
        self.moves[key] = {"src": src, "dst": dst, "t": time.time(),
                           "state": "serving",
                           "retired": list(old.get("retired") or ())}
        self._event("tenant_move",
                    f"'{key}' moving {src} -> {dst} (sustained "
                    "pressure); source keeps serving until retire")
        # durable intent for the destination: future spawns carry the
        # tenant (same artifact version — no rollout rides on a move)
        try:
            self.store.apply(self._child_spec(spec, dst, self.plan))
        except Exception as e:  # noqa: BLE001 — level-triggered retry
            self._event("tenant_move_spec_error", repr(e)[:200])
        self._set_autoscale_keys(dst)
        return True

    def _retire_moves(self) -> int:
        """Deferred break half: a serving move whose dwell elapsed —
        and whose destination still serves — retires its source. The
        source's child spec and autoscale attribution drop the tenant;
        the next routing publish drops it from the table."""
        retired = 0
        spec = None
        for key, mv in list(self.moves.items()):
            if mv.get("state") != "serving":
                continue
            if time.time() - float(mv.get("t") or 0.0) < \
                    _rebalance_retire_s():
                continue
            if not self.shard_healthy(mv.get("dst", "")):
                continue        # never break before make held
            src = mv.get("src")
            mv["state"] = "retired"
            mv["retired"] = list(mv.get("retired") or ()) + [src]
            retired += 1
            self._event("tenant_move_retired",
                        f"'{key}' source {src} retired — "
                        f"{mv['dst']} is the tenant's home now")
            if src in self.recs:
                if spec is None:
                    spec, _ = self.store.get(self.pool)
                try:
                    self.store.apply(
                        self._child_spec(spec, src, self.plan))
                except Exception as e:  # noqa: BLE001
                    self._event("tenant_move_spec_error",
                                repr(e)[:200])
                self._set_autoscale_keys(src)
        return retired

    def _failback_once(self) -> int:
        """Failback hygiene for LOSS-driven re-placements: once every
        home shard of an overridden tenant has been provably healthy
        for ``H2O_TPU_REBALANCE_FAILBACK_S``, the override copies age
        out of the survivor's child spec and the routing table —
        instead of lingering until the next plan rebuild. (Load-driven
        ``moves`` are exempt: they ARE the intended placement.)"""
        if self.plan is None:
            return 0
        now = time.monotonic()
        actual, _ = self._health_maps()
        for sid, ok in actual.items():
            if ok:
                self._healthy_since.setdefault(sid, now)
            else:
                self._healthy_since.pop(sid, None)
        if not self.overrides:
            return 0
        wait = _rebalance_failback_s()
        spec = None
        dropped = 0
        for key in list(self.overrides):
            home = self.plan.assignments.get(key, ())
            if not home or not all(
                    self._healthy_since.get(s) is not None
                    and now - self._healthy_since[s] >= wait
                    for s in home):
                continue
            extras = self.overrides.pop(key)
            dropped += 1
            self._event("tenant_failback",
                        f"'{key}' home shard(s) {list(home)} healthy "
                        f">= {wait:g}s — override copies on "
                        f"{list(extras)} age out")
            if spec is None:
                spec, _ = self.store.get(self.pool)
            for sid in extras:
                if sid in self.recs:
                    try:
                        self.store.apply(
                            self._child_spec(spec, sid, self.plan))
                    except Exception as e:  # noqa: BLE001
                        self._event("tenant_failback_spec_error",
                                    repr(e)[:200])
                    self._set_autoscale_keys(sid)
        return dropped

    def _rebalance_once(self) -> int:
        """Sustained-pressure move trigger (``H2O_TPU_REBALANCE``, off
        by default): per shard, the per-tenant shed/504 deltas of its
        OWN placed tenants (the shard-aware autoscale counters) must
        show pressure for ``H2O_TPU_REBALANCE_SUSTAIN`` consecutive
        passes; then the hottest movable tenant on that shard moves to
        the first healthy non-placed shard in its rendezvous
        preference. One move per cooldown window, fleet-wide."""
        if self.plan is None or not _rebalance_enabled():
            return 0
        from .autoscale import pressure_by_model

        spec, _ = self.store.get(self.pool)
        actual, _ = self._health_maps()
        now = time.monotonic()
        head = set(self.plan.head_keys)
        moved = 0
        for sid, rec in self._recs_snapshot().items():
            with rec._lock:
                ready = [r for r in rec.replicas if r.state == READY]
            samples = [s for s in (r.stats() for r in ready) if s]
            per = pressure_by_model(samples, rec.autoscale_keys)
            prev = self._tenant_prev.get(sid)
            self._tenant_prev[sid] = per
            if prev is None:
                continue
            delta = {k: v - prev.get(k, 0) for k, v in per.items()}
            if any(v < 0 for v in delta.values()):
                continue     # counter reset (replica restart) — hold
            delta = {k: v for k, v in delta.items() if v > 0}
            if not delta:
                self._pressure_hits[sid] = 0
                continue
            hits = self._pressure_hits.get(sid, 0) + 1
            self._pressure_hits[sid] = hits
            if hits < _rebalance_sustain():
                continue
            if now - self._last_move_t < _rebalance_cooldown() and \
                    self._last_move_t > 0.0:
                continue
            for key in sorted(delta, key=delta.get, reverse=True):
                if key in head:
                    continue     # the head is everywhere already
                if self.moves.get(key, {}).get("state") == "serving":
                    continue     # one move at a time per tenant
                placed = self._placed_shards(key)
                if sid not in placed:
                    continue
                dst = move_destination(key, self.plan.shards,
                                       exclude=placed, healthy=actual)
                if dst is None:
                    continue     # nowhere better to go — hold
                if self._move_tenant(key, sid, dst, spec):
                    self._last_move_t = time.monotonic()
                    self._pressure_hits[sid] = 0
                    moved += 1
                break
        return moved

    # -- routing publication (the N-router contract) --------------------------

    def _publish_routing(self) -> None:
        """Publish the routing table through the store, fenced on this
        controller's lease epoch. A fence rejection means a newer
        holder took over: this controller is DEPOSED — it stops
        reconciling and leaves its pods for the new holder to adopt
        (split-brain resolves to exactly one writer; no pod dies)."""
        if self.deposed:
            return
        table = self.routing_table()
        try:
            gen = self.store.publish_routing(self.pool, table,
                                             epoch=self.lease_epoch)
        except StaleGenerationError as e:
            self.deposed = True
            self._event("controller_deposed", repr(e)[:200])
            return
        except Exception as e:  # noqa: BLE001 — publish retries
            self._event("routing_publish_error", repr(e)[:200])
            return
        from ..runtime.telemetry import REGISTRY

        REGISTRY.gauge(
            "h2o_operator_table_generation",
            "routing-table generation last published by this "
            "controller").set(float(gen))

    # -- the loop -------------------------------------------------------------

    def reconcile_once(self) -> None:
        """Test-driving entry: one parent sync + one pass of every
        child + one re-placement sweep + status publish. Adoption
        first, same as Reconciler.run — shard-loss judgment is gated
        on it (_health_maps)."""
        self._ensure_children()
        for rec in self._recs_snapshot().values():
            if not rec._adopted:
                try:
                    rec.adopt_existing()
                except Exception as e:  # noqa: BLE001 — pass must run
                    self._event("adoption_error", repr(e)[:200])
            rec.reconcile_once()
            rec.autoscale_once()
        self._replace_once()
        self._rebalance_once()
        self._retire_moves()
        self._failback_once()
        self._publish_status()
        self._publish_routing()

    def _sync_child_threads(self, interval: float | None) -> None:
        """Every shard in the child map gets a running reconciler
        thread — including shards ADDED by a mid-run spec change (a
        thread list built once before the loop would leave a new
        shard's pods unspawned forever, its tenants 503ing with no
        recovery path). Each thread has its own stop event so shard
        removal stops exactly one."""
        for sid, rec in self._recs_snapshot().items():
            t = self._child_threads.get(sid)
            if t is not None and t.is_alive():
                continue
            ev = self._child_stops.get(sid)
            if ev is None or ev.is_set():
                ev = threading.Event()
                self._child_stops[sid] = ev
            t = threading.Thread(target=rec.run, args=(ev,),
                                 kwargs={"interval": interval},
                                 name=f"h2o-shard-{sid}", daemon=True)
            t.start()
            self._child_threads[sid] = t

    def run(self, stop: threading.Event,
            interval: float | None = None) -> None:
        """Blocking loop: children run on their own threads (each the
        normal Reconciler.run with adoption-first), this thread owns
        parent sync, re-placement, and parent status."""
        self._ensure_children()
        self._sync_child_threads(interval)
        while not stop.is_set():
            try:
                self._ensure_children()
                self._sync_child_threads(interval)
                self._replace_once()
                self._rebalance_once()
                self._retire_moves()
                self._failback_once()
                self._publish_status()
                self._publish_routing()
            except Exception as e:  # noqa: BLE001 — the loop survives
                self._event("shard_loop_error", repr(e)[:300])
            if self.deposed:
                # a newer lease holder owns the fleet: stop
                # reconciling, leave every pod running — the new
                # holder adopts them off their manifests
                break
            stop.wait(interval if interval is not None else _interval())
        for ev in list(self._child_stops.values()):
            ev.set()
        for t in list(self._child_threads.values()):
            t.join(timeout=10)

    def converged(self) -> bool:
        recs = self._recs_snapshot()
        if not recs:
            return False
        return all(rec.converged() for rec in recs.values())

    def wait_converged(self, timeout: float = 240.0) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if self.converged():
                return True
            time.sleep(0.1)
        return self.converged()

    def shutdown(self, timeout: float = 60.0) -> None:
        for ev in list(self._child_stops.values()):
            ev.set()
        threads = [threading.Thread(
            target=rec.shutdown, kwargs={"timeout": timeout},
            daemon=True) for rec in self._recs_snapshot().values()]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=timeout + 10)

    # -- the router's view ----------------------------------------------------

    def routing_table(self) -> dict:
        """The router input: every key's shard preference order (plan
        + re-placement overrides appended) and every shard's current
        endpoint URLs. Device-free and cheap — safe to call per
        health sweep."""
        if self.plan is None:
            return {"keys": {}, "shards": {}}
        return {
            "keys": {k: list(self._placed_shards(k))
                     for k in self.plan.assignments},
            "shards": {sid: rec.endpoints()
                       for sid, rec in self._recs_snapshot().items()},
        }

    def endpoints(self) -> list:
        out = []
        for rec in self._recs_snapshot().values():
            out.extend(rec.endpoints())
        return out

    def _publish_status(self) -> None:
        shards = {}
        for sid, rec in self._recs_snapshot().items():
            st = rec.status()
            shards[sid] = {
                "ready": st["ready"],
                "converged": rec.converged(),
                "healthy": self.shard_healthy(sid),
                "tenants": len(rec.autoscale_keys or ()),
                "replicas": st["replicas"],
            }
        orphans = self.pending_orphans()
        status = {
            "sharded": True,
            "shards": shards,
            "converged": bool(self.recs) and all(
                s["converged"] for s in shards.values()),
            "placement": {
                "catalog": len(self.plan.assignments)
                if self.plan else 0,
                "head": len(self.plan.head_keys) if self.plan else 0,
                # overrides + ever_healthy ARE the re-placement state
                # a restarted controller resumes from (see __init__)
                "overrides": {k: list(v)
                              for k, v in self.overrides.items()},
                "ever_healthy": sorted(self._ever_healthy),
                "moves": {k: dict(v) for k, v in self.moves.items()},
            },
            "lease_epoch": self.lease_epoch,
            "degraded_tenants": orphans[:64],
            "degraded_count": len(orphans),
        }
        try:
            self.store.set_status(self.pool, status)
        except Exception:  # noqa: BLE001 — status is best-effort
            pass
