"""Tenant placement: rendezvous hashing + popularity-aware replication.

The fleet's catalog (one model key per tenant) is placed across pool
SHARDS so no single replica has to hold every tenant (PR 7's
``extra_artifacts`` push put the full catalog on each pod, and the
byte-budgeted scorer cache churns as soon as the catalog outgrows one
node's budget). The placement rules:

- **Rendezvous (HRW) hashing** orders the shards per key by
  ``hash(shard, key)``: deterministic for a fixed (catalog, shard-set)
  input, and minimally disruptive — adding or draining a shard moves
  only ~1/N of the tail keys (each key's winner changes only when the
  NEW shard scores highest for it), never a full reshuffle the way a
  modulo scheme would.
- **Popularity-aware replication**: the catalog order IS the
  popularity rank (the Zipf convention every load shape in this repo
  uses — tools/datasets.zipf_probs). The first ``head`` keys (the
  Zipf head that carries most of the traffic) are placed on EVERY
  shard, so the loss of any one shard never takes down a hot tenant —
  the router fails over to a replica shard instantly. The long tail
  lives on exactly ``tail_replicas`` shards (default 1): the catalog
  scales with the shard count instead of every node holding it.

Pure host-side math — no HTTP, no device; the orchestration lives in
``reconcile.ShardedPool`` and the data path in ``router``.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from typing import Iterable, Sequence

__all__ = ["PlacementPlan", "plan_placement", "shard_preference",
           "hrw_score", "move_destination"]


def hrw_score(key: str, shard: str) -> int:
    """Rendezvous weight of ``shard`` for ``key`` — the highest-scoring
    shard owns the key. sha1 (not Python hash()): stable across
    processes and interpreter runs, which the determinism contract
    (and a restarted operator re-deriving the same plan) requires."""
    h = hashlib.sha1(f"{shard}\x00{key}".encode()).digest()
    return int.from_bytes(h[:8], "big")


def shard_preference(key: str, shards: Iterable[str]) -> list[str]:
    """Every shard ordered by rendezvous weight for ``key`` (winner
    first) — the router's failover order for replicated keys."""
    return sorted(shards, key=lambda s: (hrw_score(key, s), s),
                  reverse=True)


@dataclass(frozen=True)
class PlacementPlan:
    """One catalog's placement over one shard set.

    ``assignments`` maps every model key to the tuple of shard ids
    that must hold its artifact, in failover-preference order (HRW
    order; for head keys that is ALL shards). Frozen: a plan is a pure
    function of its inputs — re-derive, never mutate (the ShardedPool
    layers runtime re-placement on top as overrides)."""

    shards: tuple
    assignments: dict
    head_keys: frozenset

    def shards_for(self, key: str) -> tuple:
        return self.assignments[key]

    def keys_for(self, shard: str) -> list:
        """Keys placed on ``shard``, in catalog (popularity) order."""
        return [k for k, s in self.assignments.items() if shard in s]

    def by_shard(self) -> dict:
        return {s: self.keys_for(s) for s in self.shards}

    def tail_keys(self) -> list:
        return [k for k in self.assignments if k not in self.head_keys]


def move_destination(key: str, shards: Iterable[str],
                     exclude: Iterable[str] = (),
                     healthy: dict | None = None) -> str | None:
    """The make-before-break move target for ``key``: the first shard
    in its rendezvous preference order that is not already placed
    (``exclude``) and — when a health map is given — currently serving.
    Deterministic like everything else here, so a restarted controller
    re-derives the same destination for the same fleet state. None
    when every candidate is excluded or down (the move waits)."""
    exclude = set(exclude)
    for sid in shard_preference(key, shards):
        if sid in exclude:
            continue
        if healthy is not None and not healthy.get(sid):
            continue
        return sid
    return None


def plan_placement(keys: Sequence[str], shards: Sequence[str],
                   head: int = 0,
                   tail_replicas: int = 1) -> PlacementPlan:
    """Place ``keys`` (catalog order = popularity rank, hottest first)
    over ``shards``. The first ``head`` keys go on every shard; the
    rest on their top ``tail_replicas`` HRW shards."""
    shards = tuple(shards)
    if not shards:
        raise ValueError("placement needs at least one shard")
    if len(set(shards)) != len(shards):
        raise ValueError(f"duplicate shard ids: {sorted(shards)}")
    if len(set(keys)) != len(keys):
        dup = sorted({k for k in keys if list(keys).count(k) > 1})
        raise ValueError(f"duplicate model keys in the catalog: {dup}")
    head = max(0, int(head))
    tr = min(len(shards), max(1, int(tail_replicas)))
    assignments: dict = {}
    head_keys = []
    for rank, key in enumerate(keys):
        pref = shard_preference(key, shards)
        if rank < head:
            head_keys.append(key)
            assignments[key] = tuple(pref)       # every shard, HRW order
        else:
            assignments[key] = tuple(pref[:tr])
    return PlacementPlan(shards=shards, assignments=assignments,
                         head_keys=frozenset(head_keys))
