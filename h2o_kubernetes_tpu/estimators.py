"""h2o-py estimator-name compatibility layer.

The reference's Python client exposes estimators under
h2o.estimators.* with H2O-prefixed names (h2o-py/h2o/estimators/*,
SURVEY.md §2b C19). A user migrating from h2o-py can keep their class
names:

    from h2o_kubernetes_tpu.estimators import H2OGradientBoostingEstimator
    H2OGradientBoostingEstimator(ntrees=50).train(y=..., training_frame=...)
"""

from .automl import AutoML as H2OAutoML
from .models import (DRF, GBM, GLM, GLRM, PCA, Aggregator, CoxPH,
                     DeepLearning, IsolationForest, KMeans, NaiveBayes,
                     StackedEnsemble, TargetEncoder, Word2Vec, XGBoost)

H2OGradientBoostingEstimator = GBM
H2ORandomForestEstimator = DRF
H2OGeneralizedLinearEstimator = GLM
H2ODeepLearningEstimator = DeepLearning
H2OXGBoostEstimator = XGBoost
H2OWord2vecEstimator = Word2Vec
H2OStackedEnsembleEstimator = StackedEnsemble
H2OKMeansEstimator = KMeans
H2OPrincipalComponentAnalysisEstimator = PCA
H2ONaiveBayesEstimator = NaiveBayes
H2OIsolationForestEstimator = IsolationForest
H2OGeneralizedLowRankEstimator = GLRM
H2OCoxProportionalHazardsEstimator = CoxPH
H2OAggregatorEstimator = Aggregator
H2OTargetEncoderEstimator = TargetEncoder

__all__ = [
    "H2OAutoML", "H2OGradientBoostingEstimator",
    "H2ORandomForestEstimator", "H2OGeneralizedLinearEstimator",
    "H2ODeepLearningEstimator", "H2OXGBoostEstimator",
    "H2OWord2vecEstimator", "H2OStackedEnsembleEstimator",
    "H2OKMeansEstimator", "H2OPrincipalComponentAnalysisEstimator",
    "H2ONaiveBayesEstimator", "H2OIsolationForestEstimator",
    "H2OGeneralizedLowRankEstimator",
    "H2OCoxProportionalHazardsEstimator", "H2OAggregatorEstimator",
    "H2OTargetEncoderEstimator",
]
