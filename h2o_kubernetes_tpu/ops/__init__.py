from .histogram import build_histogram

__all__ = ["build_histogram"]
