from .histogram import build_histogram
from .shap_kernel import flat_shap_tab_kernel

__all__ = ["build_histogram", "flat_shap_tab_kernel"]
