"""Histogram accumulation kernels for the tree learners' hot loop.

This is THE hot op of the framework (SURVEY.md §3.4: the reference's
ScoreBuildHistogram2 row×column binning loop; BASELINE.json names a
Pallas histogram kernel as the TPU answer). Per tree level every live
row contributes (g·w, h·w, w) to histogram cell [node, feature, bin].

Two implementations:

- `segment`: jax.ops.segment_sum per feature — XLA lowers this to
  scatter-add, which is fine on CPU but serializes on TPU.
- `pallas`: scatter-free MXU formulation. For a row tile, the one-hot
  membership matrix over (node·B + bin) is built in VMEM and multiplied
  against the per-row value rows: histᵀ += valsᵀ @ onehot — a [3,T] x
  [T, NBT] matmul per (feature, bin-block, row-tile) grid cell, so the
  entire histogram build rides the systolic array (the GPU literature's
  shared-memory atomics have no TPU analog; matmul inflation is the
  right trade — see PAPERS.md GBDT-on-accelerator entries).

`build_histogram(..., impl="auto")` picks pallas on TPU, segment
elsewhere. Both run under shard_map (per-shard rows); callers psum the
result across the ROWS mesh axis.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.custom_batching import custom_vmap
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Precision: a plain bf16 multiply loses ~0.4% on the gradient sums, so
# both kernels reproduce f32 products with THREE explicit bf16 mantissa
# terms of the values against the exactly-representable 0/1 one-hot —
# the same arithmetic HIGHEST would emulate, minus the wasted passes on
# the one-hot operand (it is already bf16-exact).
ROW_TILE = 1024     # bin-blocked kernel's row tile (its [T, nbt] one-hot
#                     is VMEM-bounded: 4 MB bf16 at T=1024, nbt=2048)


def _out_struct(shape, dtype, vma) -> jax.ShapeDtypeStruct:
    """ShapeDtypeStruct threading the vma set where the running jax
    supports it; older builds have neither the kwarg nor the vma check
    that needs it (runtime/compat.py disables check_rep there)."""
    try:
        return jax.ShapeDtypeStruct(shape, dtype, vma=vma)
    except TypeError:
        return jax.ShapeDtypeStruct(shape, dtype)


def _fact_row_tile(n_hi: int, rows: int) -> int:
    """Row tile for the factorized kernel. Wider tiles amortize
    per-grid-step overhead (the bench shape runs ~250 steps/level at
    4096 instead of ~1000), but the [3·C·n_hi, T] A operand scales with
    T — stay at 1024 when n_hi is large (VMEM ~16 MB/core) or the rows
    wouldn't fill a wide tile anyway."""
    return 4096 if n_hi <= 64 and rows >= 8192 else 1024


# out-block VMEM budget for the fused-feature kernel: features are
# processed in groups of `fg` per grid step so [fg, C·n_hi, 128] f32
# stays resident; past this budget F is split into 8-aligned groups
_OUT_BUDGET = 3 << 20

# grid dimension_semantics opt-out: a backend-compile regression from
# the annotation must be recoverable without a code change (bench.py
# flips this and retries rather than scoring 0.0 on the round board)
import os as _os

_DIMSEM = _os.environ.get("H2O_TPU_HIST_DIMSEM", "1") != "0"

# mantissa terms for the f32-precision bf16 emulation. 3 (default)
# reproduces f32 products to ~2^-24 (parity-gated at 1e-6 vs the
# segment path). 2 is the throughput mode (~2^-16 product precision —
# the single-precision-histogram regime LightGBM ships): the stacked
# A operand drops from 3·C·n_hi to 2·C·n_hi MXU rows, which at the
# bench shape's deepest level means ONE 128-row M-tile instead of two,
# and the A-build VPU cost falls by a third. Gain argmaxes are robust
# at 2^-16 relative noise; the kernel gate checks the 2-term path at
# its own looser tolerance.
_TERMS = 2 if _os.environ.get("H2O_TPU_HIST_TERMS", "3") == "2" else 3


# renamed TPUCompilerParams -> CompilerParams across pallas releases;
# same dimension_semantics kwarg either way
_COMPILER_PARAMS = getattr(pltpu, "CompilerParams",
                           getattr(pltpu, "TPUCompilerParams", None))


def _dimsem(*sems):
    return _COMPILER_PARAMS(dimension_semantics=sems) \
        if _DIMSEM and _COMPILER_PARAMS is not None else None


def _hist_segment(binned, rel, vals, n_nodes: int, n_bins: int):
    """[r,F] bins + [r] rel + [r,C] vals -> [n_nodes, F, B, C]."""
    live = rel >= 0
    seg_node = jnp.where(live, rel, n_nodes)
    C = vals.shape[1]

    def per_feature(bins_f):
        seg = seg_node * n_bins + bins_f.astype(jnp.int32)
        out = jax.ops.segment_sum(
            vals, seg, num_segments=(n_nodes + 1) * n_bins)
        return out[: n_nodes * n_bins].reshape(n_nodes, n_bins, C)

    return jax.vmap(per_feature, in_axes=1, out_axes=1)(binned)


def _bin_block(n_nodes: int, n_bins: int) -> int:
    """Bin-block width: B times the largest power-of-2 node group that
    keeps the one-hot tile around ~2k lanes (VMEM-bounded). The group
    must divide n_nodes so the grid tiles evenly — n_nodes is 2^d for
    plain trees but K·2^d under the flattened class batching."""
    k = 1
    while k * 2 <= n_nodes and (k * 2) * n_bins <= 2048 \
            and n_nodes % (k * 2) == 0:
        k *= 2
    return k * n_bins


def _mantissa_terms(vals_t, terms: int):
    """Split [n_ch, T] f32 values into `terms` stacked bf16 mantissa
    terms whose products against a 0/1 operand sum back to the f32
    product (to ~2^-8·8·terms relative)."""
    v1 = vals_t.astype(jnp.bfloat16)
    if terms == 1:
        return v1
    r1 = vals_t - v1.astype(jnp.float32)
    v2 = r1.astype(jnp.bfloat16)
    if terms == 2:
        return jnp.concatenate([v1, v2], axis=0)
    v3 = (r1 - v2.astype(jnp.float32)).astype(jnp.bfloat16)
    return jnp.concatenate([v1, v2, v3], axis=0)


def _hist_fact_kernel(binned_ref, rel_ref, vals_ref, out_ref, *, n_bins,
                      n_hi, n_ch, fg, terms):
    """Factorized one-hot histogram matmul (the fast path).

    seg = rel·B + bin is split as seg = hi·128 + lo.  The LHS packs the
    three weighted value channels against the hi one-hot —
    A[c·n_hi + hi, t] = v_c[t]·1[hi_t = hi] — and the RHS is the exact
    lo one-hot [T, 128], so hist[c, seg] = (A @ B)[c·n_hi + hi, lo].
    Against the bin-blocked kernel below this turns the MXU shape from
    [3, T]x[T, ≤2048] (3/128 row occupancy, ≤16 lane passes) into
    [3·n_hi, T]x[T, 128] (full rows for n_hi ≥ 43, ONE lane pass).  A is
    split into three bf16 terms (hi/mid/lo mantissa) so the f32 products
    match the segment path to ~2^-24; B is 0/1 and thus exact in bf16.
    """
    # grid (feature_groups, n_copies, row_blocks): one step covers a
    # whole FEATURE GROUP of fg features for its row block — the
    # row-stream operands (rel, vals, mantissa split) load and compute
    # ONCE per row block instead of once per (feature, row block), and
    # the grid shrinks F× (per-step sequencing overhead, not FLOPs, was
    # the round-2/3 bench bottleneck).
    first = (pl.program_id(1) == 0) & (pl.program_id(2) == 0)

    @pl.when(first)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    rel = rel_ref[:]                                 # [T]
    rel_base = rel * n_bins
    T = rel.shape[0]
    vals_t = vals_ref[:].T                           # [n_ch, T]
    # f32-precision via `terms` bf16 mantissa terms, split on the TINY
    # [n_ch, T] values and masked by the 0/1 one-hot IN bf16 —
    # bit-identical to splitting the big masked A (0/1 masking commutes
    # with rounding) but skips materializing a [n_ch*n_hi, T] f32 A
    # plus two subtract passes over it: the A-build drops from ~6
    # f32-width VPU passes to `terms` bf16-width multiplies.
    V = _mantissa_terms(vals_t, terms)               # [terms·n_ch, T]
    iota_hi = lax.broadcasted_iota(jnp.int32, (n_hi, T), 0)
    iota_lo = lax.broadcasted_iota(jnp.int32, (T, 128), 1)
    dn = (((1,), (0,)), ((), ()))

    # REAL loop over the feature group, not a static unroll: Mosaic
    # stack-allocates every unrolled iteration's [3·n_ch·n_hi, T] A
    # operand separately (fg=10 at T=4096 → 22 MB, past the 16 MB
    # scoped-vmem limit — caught by the on-chip gate), while a
    # fori_loop body's buffers are reused across iterations. The
    # feature index is a LEADING dim of the binned/out blocks so the
    # dynamic index never touches the tiled (sublane, lane) pair.
    def _feature(j, carry):
        bins = binned_ref[j, 0, 0, :]                # [T]
        seg = rel_base + bins
        hi = lax.shift_right_arithmetic(seg, 7)      # floor(seg/128)
        lo = seg - hi * 128                          # seg mod 128, >= 0
        # hi one-hot, transposed [n_hi, T]. Dead rows (rel=-1) have
        # hi < 0 and match no slot; their vals are zeroed upstream.
        oh_hi = (iota_hi == hi[None, :]).astype(jnp.bfloat16)
        B = (iota_lo == lo[:, None]).astype(jnp.bfloat16)
        # ONE matmul with all mantissa terms stacked into M — the
        # MXU's row occupancy multiplies (terms·n_ch·n_hi rows instead
        # of `terms` passes of n_ch·n_hi); the per-term partial sums
        # recombine with one cheap VPU add over [n_ch·n_hi, 128]. Same
        # bf16 products, same f32 accumulation.
        a = jnp.concatenate(
            [oh_hi * V[k][None, :] for k in range(terms * n_ch)],
            axis=0)                             # [terms·n_ch·n_hi, T]
        acc = lax.dot_general(a, B, dimension_numbers=dn,
                              preferred_element_type=jnp.float32)
        acc = acc.reshape(terms, n_ch * n_hi, 128)
        out_ref[0, j] += acc.sum(axis=0)             # [n_ch·n_hi, 128]
        return carry

    lax.fori_loop(0, fg, _feature, 0)


# VMEM cap for the factorized kernel's working set. With the stacked-
# term matmul the peak is the bf16 A [3·n_ch·n_hi, T] (4.7 MB at
# n_hi=256, C=3, T=1024 — _fact_row_tile drops to 1024 past n_hi=64)
# plus the [n_hi, T] hi one-hot, the [T, 128] lo one-hot, the f32
# [3·n_ch·n_hi, 128] dot result (1.2 MB) and the resident out block
# (_OUT_BUDGET) — ~10 MB worst case against ~16 MB/core VMEM. TIGHT:
# the on-chip kernel gate compiles exactly this cap shape as
# `fact_kernel_cap`; if it fails there, lower this cap. Deeper trees
# (n_nodes·n_bins > 2^15) take the bin-blocked kernel below.
_FACT_MAX_NHI = 256


def _hist_pallas_fact(binned, rel, vals, n_nodes: int, n_bins: int,
                      binned_tile: int = 1, row_tile: int | None = None):
    """``binned_tile`` > 1: rel/vals carry ``binned_tile`` consecutive
    copies of the row range (the flattened class batch) while binned is
    stored ONCE — the grid index map re-reads the same bin blocks per
    copy instead of materializing K copies in HBM. Such callers must
    pre-align each copy's rows and pass the ``row_tile`` they aligned
    to (one decision, not two that must agree)."""
    r, F = binned.shape
    C = vals.shape[1]
    nB = n_nodes * n_bins
    n_hi = -(-nB // 128)                             # ceil
    rt_size = row_tile or _fact_row_tile(n_hi, r)
    pad = (-r) % rt_size
    if pad:
        assert binned_tile == 1     # tiled callers pre-align rows
        binned = jnp.pad(binned, ((0, pad), (0, 0)))
        rel = jnp.pad(rel, (0, pad), constant_values=-1)
        vals = jnp.pad(vals, ((0, pad), (0, 0)))
    rp = r + pad
    rbb = rp // rt_size                 # row blocks per binned copy
    # feature grouping: each grid step holds [fg, C·n_hi, 128] f32 of
    # output resident; wide tables split into 8-aligned groups (padded
    # feature columns histogram into junk rows that are sliced away).
    # fg is also capped at 64 outright: the row-stream-reuse win
    # saturates long before that, and the resident out block is the
    # only cost that grows with fg (the kernel's fori_loop reuses one
    # iteration's buffers)
    per_f = C * n_hi * 128 * 4
    fg_cap = min(F, 64, max(1, _OUT_BUDGET // per_f))
    if fg_cap >= F:
        fg, F_pad = F, F
    else:
        fg = max(8, fg_cap // 8 * 8)
        F_pad = -(-F // fg) * fg
        binned = jnp.pad(binned, ((0, 0), (0, F_pad - F)))
    n_fg = F_pad // fg
    # [rp, F_pad] -> [F_pad, row_block, 1, rt]: a (fg, 1, 1, rt) block
    # is a row block's bins for one feature group, with the feature on
    # a LEADING dim — the kernel's fori_loop indexes it dynamically,
    # which is only legal off the tiled (sublane, lane) pair
    binned4 = binned.astype(jnp.int32).T.reshape(
        F_pad, rbb, 1, rt_size)
    rel32 = rel.astype(jnp.int32)
    vma = getattr(jax.typeof(vals), "vma", frozenset()) or frozenset()
    grid = (n_fg, binned_tile, rbb)
    out = pl.pallas_call(
        functools.partial(_hist_fact_kernel, n_bins=n_bins, n_hi=n_hi,
                          n_ch=C, fg=fg, terms=_TERMS),
        out_shape=_out_struct((n_fg, fg, C * n_hi, 128),
                              jnp.float32, vma),
        grid=grid,
        in_specs=[
            pl.BlockSpec((fg, 1, 1, rt_size),
                         lambda g, k, rt: (g, rt, 0, 0)),
            pl.BlockSpec((rt_size,),
                         lambda g, k, rt, rb=rbb: (k * rb + rt,)),
            pl.BlockSpec((rt_size, C),
                         lambda g, k, rt, rb=rbb: (k * rb + rt, 0)),
        ],
        out_specs=pl.BlockSpec((1, fg, C * n_hi, 128),
                               lambda g, k, rt: (g, 0, 0, 0)),
        # feature groups write DISTINCT out blocks (parallel — Mosaic
        # may pipeline them); copies and row blocks ACCUMULATE into the
        # same block (arbitrary = sequential)
        compiler_params=_dimsem("parallel", "arbitrary", "arbitrary"),
        interpret=jax.default_backend() != "tpu",
    )(binned4, rel32, vals)
    # [n_fg, fg, C·n_hi, 128] -> [F, C, n_hi·128] -> [n, F, B, C]
    out = out.reshape(F_pad, C, n_hi * 128)[:F, :, :nB]
    return out.reshape(F, C, n_nodes, n_bins).transpose(2, 0, 3, 1)


def _hist_kernel(binned_ref, rel_ref, vals_ref, out_ref, *, n_bins, nbt,
                 terms):
    nb = pl.program_id(1)
    rt = pl.program_id(2)

    @pl.when(rt == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    bins = binned_ref[:]                             # [T]
    rel = rel_ref[:]                                 # [T]
    seg = rel * n_bins + bins
    base = nb * nbt
    iota = lax.broadcasted_iota(jnp.int32, (bins.shape[0], nbt), 1)
    # dead rows (rel=-1) give seg in [-n_bins, -1], which can never equal
    # a non-negative iota slot — no explicit liveness mask needed (a bool
    # [:, None] broadcast is also unsupported by Mosaic for non-32-bit)
    onehot = ((seg[:, None] - base) == iota).astype(jnp.bfloat16)
    vals_t = vals_ref[:].T                           # [C, T]
    # same f32-precision recipe as the factorized kernel: the one-hot
    # RHS is 0/1 (bf16-exact) and the [C, T] values split into `terms`
    # bf16 mantissa terms — explicit bf16 passes replace the implicit
    # ~6-pass f32 HIGHEST emulation on BOTH operands
    dn = (((1,), (0,)), ((), ()))

    # single matmul with the mantissa terms stacked into M (terms·C
    # rows, one pass) instead of separate C-row passes; the per-term
    # sums recombine with one VPU add — same products, f32 accumulate
    C = vals_t.shape[0]
    V = _mantissa_terms(vals_t, terms)               # [terms·C, T] bf16
    acc = lax.dot_general(V, onehot, dimension_numbers=dn,
                          preferred_element_type=jnp.float32)
    acc = acc.reshape(terms, C, nbt)
    out_ref[0] += acc.sum(axis=0)                    # [C, NBT] on the MXU


def _hist_pallas(binned, rel, vals, n_nodes: int, n_bins: int,
                 binned_tile: int = 1, row_tile: int | None = None):
    r, F = binned.shape
    C = vals.shape[1]
    nB = n_nodes * n_bins
    if -(-nB // 128) <= _FACT_MAX_NHI:
        return _hist_pallas_fact(binned, rel, vals, n_nodes, n_bins,
                                 binned_tile, row_tile)
    if binned_tile > 1:
        # deep-tree (blocked-kernel) shapes are rare for the flattened
        # class batch — materialize the bin copies rather than widen
        # the blocked kernel's grid to 4-D
        binned = jnp.tile(binned, (binned_tile, 1))
        r = binned.shape[0]
    nbt = _bin_block(n_nodes, n_bins)
    if nbt % 128 and nbt != nB:
        # un-tileable bin block (non-power-of-2 n_bins hitting the lane
        # cap mid-range) — Mosaic requires the last block dim be a
        # multiple of 128 or the whole array; fall back off the MXU path
        return _hist_segment(binned, rel, vals, n_nodes, n_bins)
    pad = (-r) % ROW_TILE
    if pad:
        binned = jnp.pad(binned, ((0, pad), (0, 0)))
        rel = jnp.pad(rel, (0, pad), constant_values=-1)
        vals = jnp.pad(vals, ((0, pad), (0, 0)))
    rp = r + pad
    # feature-major flat row stream: 1-D blocks of ROW_TILE satisfy the
    # TPU lane tiling where a (1, ROW_TILE) 2-D block cannot (its
    # sublane dim 1 is neither 8-divisible nor the full axis)
    binned_flat = binned.T.astype(jnp.int32).reshape(F * rp)
    rel32 = rel.astype(jnp.int32)
    rblocks = rp // ROW_TILE

    grid = (F, nB // nbt, rblocks)
    # under shard_map the output varies per shard: propagate the input's
    # varying-mesh-axes set or jax's vma check rejects the call
    vma = getattr(jax.typeof(vals), "vma", frozenset()) or frozenset()
    out = pl.pallas_call(
        functools.partial(_hist_kernel, n_bins=n_bins, nbt=nbt,
                          terms=_TERMS),
        out_shape=_out_struct((F, C, nB), jnp.float32, vma),
        grid=grid,
        in_specs=[
            pl.BlockSpec((ROW_TILE,),
                         lambda f, nb, rt, rb=rblocks: (f * rb + rt,)),
            pl.BlockSpec((ROW_TILE,), lambda f, nb, rt: (rt,)),
            pl.BlockSpec((ROW_TILE, C), lambda f, nb, rt: (rt, 0)),
        ],
        out_specs=pl.BlockSpec((1, C, nbt), lambda f, nb, rt: (f, 0, nb)),
        # features and bin blocks write distinct out blocks; only the
        # row-block axis accumulates
        compiler_params=_dimsem("parallel", "parallel", "arbitrary"),
        interpret=jax.default_backend() != "tpu",
    )(binned_flat, rel32, vals)
    # [F, C, n*B] -> [n, F, B, C]
    return out.reshape(F, C, n_nodes, n_bins).transpose(2, 0, 3, 1)


def _hist_call(binned, rel, vals, n_nodes: int, n_bins: int, impl: str):
    fn = _hist_pallas if impl == "pallas" else _hist_segment
    return fn(binned, rel, vals, n_nodes, n_bins)


def _hist_vmappable(binned, rel, vals, n_nodes: int, n_bins: int,
                    impl: str):
    """Histogram build with a class-batching rule that never vmaps the
    Pallas kernel.

    ``jax.vmap`` of a pallas_call prepends a squeezed batch dim to
    every block spec, and Mosaic rejects that for the rank-1 row-stream
    operands (block (1, T) over a [K, rows] array fails the (8, 128)
    divisibility rule) — the round-4 on-chip kernel gate caught exactly
    this in the fused multinomial boost scan, which grows its K class
    trees under vmap. Instead of batching the kernel, the batch is
    LOWERED AWAY: class k's rows are relabeled to nodes
    [k·n_nodes, (k+1)·n_nodes) and the SAME flat kernel runs once over
    the concatenated row stream. Identical sums, and the MXU M
    dimension (channels × hi-slots) gets K× fuller than K separate
    passes would — batching IMPROVES systolic occupancy here.
    """
    cv = custom_vmap(
        functools.partial(_hist_call, n_nodes=n_nodes, n_bins=n_bins,
                          impl=impl))

    @cv.def_vmap
    def _rule(axis_size, in_batched, binned_b, rel_b, vals_b):
        K = axis_size
        bb, rb, vb = in_batched
        if impl != "pallas":
            # segment_sum vmaps fine as-is — no kernel, no flattening
            fn = functools.partial(_hist_call, n_nodes=n_nodes,
                                   n_bins=n_bins, impl=impl)
            out = jax.vmap(fn, in_axes=(0 if bb else None,
                                        0 if rb else None,
                                        0 if vb else None))(
                binned_b, rel_b, vals_b)
            return out, True

        r = rel_b.shape[1] if rb else rel_b.shape[0]
        # pad each class's rows to the row tile the flat kernel will
        # pick for the MERGED node count (fact kernel when it fits,
        # blocked kernel otherwise)
        n_hi_t = -(-K * n_nodes * n_bins // 128)
        rt = _fact_row_tile(n_hi_t, r) if n_hi_t <= _FACT_MAX_NHI \
            else ROW_TILE
        pad = (-r) % rt
        C = vals_b.shape[-1]
        F = binned_b.shape[-1]
        # per-class row padding BEFORE flattening so each class's rows
        # stay aligned with the (re-read) binned row blocks
        if bb:
            binned_f = jnp.pad(binned_b, ((0, 0), (0, pad), (0, 0))
                               ).reshape(K * (r + pad), F)
            tile = 1
        else:
            binned_f = jnp.pad(binned_b, ((0, pad), (0, 0)))
            tile = K        # binned stored once; grid re-reads it K×
        rel2 = rel_b if rb else jnp.broadcast_to(rel_b[None], (K, r))
        rel2 = jnp.pad(rel2, ((0, 0), (0, pad)), constant_values=-1)
        # class k's rows land in nodes [k·n_nodes, (k+1)·n_nodes)
        rel2 = jnp.where(rel2 >= 0,
                         rel2 + (jnp.arange(K, dtype=jnp.int32)
                                 * n_nodes)[:, None], -1)
        vals2 = vals_b if vb else jnp.broadcast_to(
            vals_b[None], (K, r, C))
        vals2 = jnp.pad(vals2, ((0, 0), (0, pad), (0, 0)))
        out = _hist_pallas(binned_f, rel2.reshape(K * (r + pad)),
                           vals2.reshape(K * (r + pad), C),
                           K * n_nodes, n_bins, binned_tile=tile,
                           row_tile=rt)
        return out.reshape((K, n_nodes) + out.shape[1:]), True

    return cv(binned, rel, vals)


def resolve_impl(impl: str) -> str:
    if impl == "auto":
        from ..config import get_config

        cfg = get_config("hist_impl")     # env/programmatic tier
        if cfg != "auto":
            if cfg not in ("segment", "pallas"):
                # the env tier (H2O_TPU_HIST_IMPL) is unvalidated at
                # load — a typo must not silently demote the kernel
                raise ValueError(
                    f"H2O_TPU_HIST_IMPL/config hist_impl '{cfg}' is not "
                    "one of auto/segment/pallas")
            return cfg
        return "pallas" if jax.default_backend() == "tpu" else "segment"
    if impl not in ("segment", "pallas"):
        raise ValueError(f"unknown histogram impl '{impl}'")
    return impl


def build_histogram(binned, rel, g, h, w, n_nodes: int, n_bins: int,
                    impl: str = "auto", unit_hess: bool = False):
    """Per-shard histogram [n_nodes, F, B, 3] of (Σgw, Σhw, Σw).

    binned: [r, F] uint8 bin codes; rel: [r] int32 node id (-1 dead);
    w: [r] row weight (0 for padding/unsampled rows).

    ``unit_hess``: the caller asserts h ≡ 1 (gaussian/laplace/quantile/
    huber losses and DRF), so Σhw == Σw and the kernels accumulate TWO
    channels [Σgw, Σw] instead of three — 1/3 fewer MXU passes and a
    1/3 smaller psum payload at every tree level. The result is then
    [..., 2]; callers expand back to [..., 3] AFTER their psum with
    ``expand_unit_hess`` (expanding earlier would forfeit the psum
    saving).
    """
    live = (rel >= 0) & (w > 0)
    rel = jnp.where(live, rel, -1)
    impl = resolve_impl(impl)
    # where() (not just *w) so NaN g/h in dead rows can't poison sums
    if unit_hess:
        vals = jnp.where(live[:, None],
                         jnp.stack([g * w, w], axis=1), 0.0)
    else:
        vals = jnp.where(live[:, None],
                         jnp.stack([g * w, h * w, w], axis=1), 0.0)
    return _hist_vmappable(binned, rel, vals, n_nodes, n_bins, impl)


def expand_unit_hess(hist2):
    """[..., 2] (Σgw, Σw) → [..., 3] (Σgw, Σhw=Σw, Σw) — the H channel
    of a unit-hessian histogram IS the weight channel."""
    return jnp.concatenate(
        [hist2[..., 0:1], hist2[..., 1:2], hist2[..., 1:2]], axis=-1)
