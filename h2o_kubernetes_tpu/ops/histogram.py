"""Histogram accumulation kernels for the tree learners' hot loop.

This is THE hot op of the framework (SURVEY.md §3.4: the reference's
ScoreBuildHistogram2 row×column binning loop; BASELINE.json names a
Pallas histogram kernel as the TPU answer). Per tree level every live
row contributes (g·w, h·w, w) to histogram cell [node, feature, bin].

Two implementations:

- `segment`: jax.ops.segment_sum per feature — XLA lowers this to
  scatter-add, which is fine on CPU but serializes on TPU.
- `pallas`: scatter-free MXU formulation. For a row tile, the one-hot
  membership matrix over (node·B + bin) is built in VMEM and multiplied
  against the per-row value rows: histᵀ += valsᵀ @ onehot — a [3,T] x
  [T, NBT] matmul per (feature, bin-block, row-tile) grid cell, so the
  entire histogram build rides the systolic array (the GPU literature's
  shared-memory atomics have no TPU analog; matmul inflation is the
  right trade — see PAPERS.md GBDT-on-accelerator entries).

`build_histogram(..., impl="auto")` picks pallas on TPU, segment
elsewhere. Both run under shard_map (per-shard rows); callers psum the
result across the ROWS mesh axis.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

# Precision: a plain bf16 multiply loses ~0.4% on the gradient sums, so
# both kernels reproduce f32 products with THREE explicit bf16 mantissa
# terms of the values against the exactly-representable 0/1 one-hot —
# the same arithmetic HIGHEST would emulate, minus the wasted passes on
# the one-hot operand (it is already bf16-exact).
ROW_TILE = 1024  # 1-D s32 operands carry XLA layout T(1024): the row
#                  block must match it or Mosaic rejects the layouts


def _hist_segment(binned, rel, vals, n_nodes: int, n_bins: int):
    """[r,F] bins + [r] rel + [r,C] vals -> [n_nodes, F, B, C]."""
    live = rel >= 0
    seg_node = jnp.where(live, rel, n_nodes)
    C = vals.shape[1]

    def per_feature(bins_f):
        seg = seg_node * n_bins + bins_f.astype(jnp.int32)
        out = jax.ops.segment_sum(
            vals, seg, num_segments=(n_nodes + 1) * n_bins)
        return out[: n_nodes * n_bins].reshape(n_nodes, n_bins, C)

    return jax.vmap(per_feature, in_axes=1, out_axes=1)(binned)


def _bin_block(n_nodes: int, n_bins: int) -> int:
    """Bin-block width: B times the largest power-of-2 node group that
    keeps the one-hot tile around ~2k lanes (VMEM-bounded)."""
    k = 1
    while k * 2 <= n_nodes and (k * 2) * n_bins <= 2048:
        k *= 2
    return k * n_bins


def _hist_fact_kernel(binned_ref, rel_ref, vals_ref, out_ref, *, n_bins,
                      n_hi, n_ch):
    """Factorized one-hot histogram matmul (the fast path).

    seg = rel·B + bin is split as seg = hi·128 + lo.  The LHS packs the
    three weighted value channels against the hi one-hot —
    A[c·n_hi + hi, t] = v_c[t]·1[hi_t = hi] — and the RHS is the exact
    lo one-hot [T, 128], so hist[c, seg] = (A @ B)[c·n_hi + hi, lo].
    Against the bin-blocked kernel below this turns the MXU shape from
    [3, T]x[T, ≤2048] (3/128 row occupancy, ≤16 lane passes) into
    [3·n_hi, T]x[T, 128] (full rows for n_hi ≥ 43, ONE lane pass).  A is
    split into three bf16 terms (hi/mid/lo mantissa) so the f32 products
    match the segment path to ~2^-24; B is 0/1 and thus exact in bf16.
    """
    rt = pl.program_id(1)

    @pl.when(rt == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    bins = binned_ref[:]                             # [T]
    rel = rel_ref[:]                                 # [T]
    seg = rel * n_bins + bins
    hi = lax.shift_right_arithmetic(seg, 7)          # floor(seg/128)
    lo = seg - hi * 128                              # seg mod 128, >= 0
    T = bins.shape[0]
    # hi one-hot, transposed: [n_hi, T].  Dead rows (rel=-1) have hi < 0
    # and match no slot; their vals are zeroed upstream anyway.
    iota_hi = lax.broadcasted_iota(jnp.int32, (n_hi, T), 0)
    oh_hi = (iota_hi == hi[None, :]).astype(jnp.bfloat16)
    vals_t = vals_ref[:].T                           # [n_ch, T]
    iota_lo = lax.broadcasted_iota(jnp.int32, (T, 128), 1)
    B = (iota_lo == lo[:, None]).astype(jnp.bfloat16)

    # f32-precision via 3 bf16 mantissa terms, split on the TINY
    # [n_ch, T] values and masked by the 0/1 one-hot IN bf16 —
    # bit-identical to splitting the big masked A (0/1 masking commutes
    # with rounding) but skips materializing a [n_ch*n_hi, T] f32 A
    # plus two subtract passes over it: the A-build drops from ~6
    # f32-width VPU passes to 3 bf16-width multiplies (round-4
    # VPU-bound remainder attack, PROFILE.md "what's next").
    v1 = vals_t.astype(jnp.bfloat16)
    r1 = vals_t - v1.astype(jnp.float32)
    v2 = r1.astype(jnp.bfloat16)
    v3 = (r1 - v2.astype(jnp.float32)).astype(jnp.bfloat16)
    dn = (((1,), (0,)), ((), ()))

    def dg(vk):                                      # [n_ch,T] bf16 term
        a = jnp.concatenate(
            [oh_hi * vk[c][None, :] for c in range(n_ch)],
            axis=0)                                  # [n_ch*n_hi, T]
        return lax.dot_general(a, B, dimension_numbers=dn,
                               preferred_element_type=jnp.float32)

    out_ref[0] += dg(v1) + dg(v2) + dg(v3)           # [n_ch*n_hi, 128]


# VMEM cap for the factorized kernel's working set: A f32 [3*n_hi, T]
# plus its three bf16 split terms and the hi one-hot is ~22 B per A
# element — n_hi=256 is ~9 MB, safely inside v5e VMEM alongside the
# [3*n_hi, 128] accumulator. Deeper trees (n_nodes*n_bins > 2^15) take
# the bin-blocked kernel below.
_FACT_MAX_NHI = 256


def _hist_pallas_fact(binned, rel, vals, n_nodes: int, n_bins: int):
    r, F = binned.shape
    C = vals.shape[1]
    nB = n_nodes * n_bins
    n_hi = -(-nB // 128)                             # ceil
    pad = (-r) % ROW_TILE
    if pad:
        binned = jnp.pad(binned, ((0, pad), (0, 0)))
        rel = jnp.pad(rel, (0, pad), constant_values=-1)
        vals = jnp.pad(vals, ((0, pad), (0, 0)))
    rp = r + pad
    binned_flat = binned.T.astype(jnp.int32).reshape(F * rp)
    rel32 = rel.astype(jnp.int32)
    rblocks = rp // ROW_TILE

    grid = (F, rblocks)
    vma = getattr(jax.typeof(vals), "vma", frozenset()) or frozenset()
    out = pl.pallas_call(
        functools.partial(_hist_fact_kernel, n_bins=n_bins, n_hi=n_hi,
                          n_ch=C),
        out_shape=jax.ShapeDtypeStruct((F, C * n_hi, 128), jnp.float32,
                                       vma=vma),
        grid=grid,
        in_specs=[
            pl.BlockSpec((ROW_TILE,),
                         lambda f, rt, rb=rblocks: (f * rb + rt,)),
            pl.BlockSpec((ROW_TILE,), lambda f, rt: (rt,)),
            pl.BlockSpec((ROW_TILE, C), lambda f, rt: (rt, 0)),
        ],
        out_specs=pl.BlockSpec((1, C * n_hi, 128), lambda f, rt: (f, 0, 0)),
        interpret=jax.default_backend() != "tpu",
    )(binned_flat, rel32, vals)
    # [F, C*n_hi, 128] -> [F, C, n_hi*128] -> [n, F, B, C]
    out = out.reshape(F, C, n_hi * 128)[:, :, :nB]
    return out.reshape(F, C, n_nodes, n_bins).transpose(2, 0, 3, 1)


def _hist_kernel(binned_ref, rel_ref, vals_ref, out_ref, *, n_bins, nbt):
    nb = pl.program_id(1)
    rt = pl.program_id(2)

    @pl.when(rt == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    bins = binned_ref[:]                             # [T]
    rel = rel_ref[:]                                 # [T]
    seg = rel * n_bins + bins
    base = nb * nbt
    iota = lax.broadcasted_iota(jnp.int32, (bins.shape[0], nbt), 1)
    # dead rows (rel=-1) give seg in [-n_bins, -1], which can never equal
    # a non-negative iota slot — no explicit liveness mask needed (a bool
    # [:, None] broadcast is also unsupported by Mosaic for non-32-bit)
    onehot = ((seg[:, None] - base) == iota).astype(jnp.bfloat16)
    vals_t = vals_ref[:].T                           # [3, T]
    # same f32-precision recipe as the factorized kernel: the one-hot
    # RHS is 0/1 (bf16-exact) and the [3, T] values split into three
    # bf16 mantissa terms — 3 explicit bf16 passes replace the implicit
    # ~6-pass f32 HIGHEST emulation on BOTH operands
    v1 = vals_t.astype(jnp.bfloat16)
    r1 = vals_t - v1.astype(jnp.float32)
    v2 = r1.astype(jnp.bfloat16)
    v3 = (r1 - v2.astype(jnp.float32)).astype(jnp.bfloat16)
    dn = (((1,), (0,)), ((), ()))

    def dg(vk):
        return lax.dot_general(vk, onehot, dimension_numbers=dn,
                               preferred_element_type=jnp.float32)

    out_ref[0] += dg(v1) + dg(v2) + dg(v3)           # [C, NBT] on the MXU


def _hist_pallas(binned, rel, vals, n_nodes: int, n_bins: int):
    r, F = binned.shape
    C = vals.shape[1]
    nB = n_nodes * n_bins
    if -(-nB // 128) <= _FACT_MAX_NHI:
        return _hist_pallas_fact(binned, rel, vals, n_nodes, n_bins)
    nbt = _bin_block(n_nodes, n_bins)
    if nbt % 128 and nbt != nB:
        # un-tileable bin block (non-power-of-2 n_bins hitting the lane
        # cap mid-range) — Mosaic requires the last block dim be a
        # multiple of 128 or the whole array; fall back off the MXU path
        return _hist_segment(binned, rel, vals, n_nodes, n_bins)
    pad = (-r) % ROW_TILE
    if pad:
        binned = jnp.pad(binned, ((0, pad), (0, 0)))
        rel = jnp.pad(rel, (0, pad), constant_values=-1)
        vals = jnp.pad(vals, ((0, pad), (0, 0)))
    rp = r + pad
    # feature-major flat row stream: 1-D blocks of ROW_TILE satisfy the
    # TPU lane tiling where a (1, ROW_TILE) 2-D block cannot (its
    # sublane dim 1 is neither 8-divisible nor the full axis)
    binned_flat = binned.T.astype(jnp.int32).reshape(F * rp)
    rel32 = rel.astype(jnp.int32)
    rblocks = rp // ROW_TILE

    grid = (F, nB // nbt, rblocks)
    # under shard_map the output varies per shard: propagate the input's
    # varying-mesh-axes set or jax's vma check rejects the call
    vma = getattr(jax.typeof(vals), "vma", frozenset()) or frozenset()
    out = pl.pallas_call(
        functools.partial(_hist_kernel, n_bins=n_bins, nbt=nbt),
        out_shape=jax.ShapeDtypeStruct((F, C, nB), jnp.float32, vma=vma),
        grid=grid,
        in_specs=[
            pl.BlockSpec((ROW_TILE,),
                         lambda f, nb, rt, rb=rblocks: (f * rb + rt,)),
            pl.BlockSpec((ROW_TILE,), lambda f, nb, rt: (rt,)),
            pl.BlockSpec((ROW_TILE, C), lambda f, nb, rt: (rt, 0)),
        ],
        out_specs=pl.BlockSpec((1, C, nbt), lambda f, nb, rt: (f, 0, nb)),
        interpret=jax.default_backend() != "tpu",
    )(binned_flat, rel32, vals)
    # [F, C, n*B] -> [n, F, B, C]
    return out.reshape(F, C, n_nodes, n_bins).transpose(2, 0, 3, 1)


def resolve_impl(impl: str) -> str:
    if impl == "auto":
        from ..config import get_config

        cfg = get_config("hist_impl")     # env/programmatic tier
        if cfg != "auto":
            if cfg not in ("segment", "pallas"):
                # the env tier (H2O_TPU_HIST_IMPL) is unvalidated at
                # load — a typo must not silently demote the kernel
                raise ValueError(
                    f"H2O_TPU_HIST_IMPL/config hist_impl '{cfg}' is not "
                    "one of auto/segment/pallas")
            return cfg
        return "pallas" if jax.default_backend() == "tpu" else "segment"
    if impl not in ("segment", "pallas"):
        raise ValueError(f"unknown histogram impl '{impl}'")
    return impl


def build_histogram(binned, rel, g, h, w, n_nodes: int, n_bins: int,
                    impl: str = "auto", unit_hess: bool = False):
    """Per-shard histogram [n_nodes, F, B, 3] of (Σgw, Σhw, Σw).

    binned: [r, F] uint8 bin codes; rel: [r] int32 node id (-1 dead);
    w: [r] row weight (0 for padding/unsampled rows).

    ``unit_hess``: the caller asserts h ≡ 1 (gaussian/laplace/quantile/
    huber losses and DRF), so Σhw == Σw and the kernels accumulate TWO
    channels [Σgw, Σw] instead of three — 1/3 fewer MXU passes and a
    1/3 smaller psum payload at every tree level. The result is then
    [..., 2]; callers expand back to [..., 3] AFTER their psum with
    ``expand_unit_hess`` (expanding earlier would forfeit the psum
    saving).
    """
    live = (rel >= 0) & (w > 0)
    rel = jnp.where(live, rel, -1)
    impl = resolve_impl(impl)
    # where() (not just *w) so NaN g/h in dead rows can't poison sums
    if unit_hess:
        vals = jnp.where(live[:, None],
                         jnp.stack([g * w, w], axis=1), 0.0)
        fn = _hist_pallas if impl == "pallas" else _hist_segment
        return fn(binned, rel, vals, n_nodes, n_bins)
    vals = jnp.where(live[:, None],
                     jnp.stack([g * w, h * w, w], axis=1), 0.0)
    if impl == "pallas":
        return _hist_pallas(binned, rel, vals, n_nodes, n_bins)
    return _hist_segment(binned, rel, vals, n_nodes, n_bins)


def expand_unit_hess(hist2):
    """[..., 2] (Σgw, Σw) → [..., 3] (Σgw, Σhw=Σw, Σw) — the H channel
    of a unit-hessian histogram IS the weight channel."""
    return jnp.concatenate(
        [hist2[..., 0:1], hist2[..., 1:2], hist2[..., 1:2]], axis=-1)
