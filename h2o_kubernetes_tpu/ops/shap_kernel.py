"""Chip-native TreeSHAP: Pallas kernel for the `flat_shap_tab` path.

`models/tree/shap.flat_shap_tab` is the pattern-table fast path of the
compiled TreeSHAP server: per virtual-tree leaf it folds a D-bit hot
pattern over the transposed [F, rows] feature block, gathers the
precomputed per-pattern contribution column from `pattern_table`, and
scatter-accumulates each of the D slot rows into phi. Lowered by XLA
those are exactly the shapes the GBDT-on-accelerator literature says
want a hand-placed kernel (Booster, arXiv:2011.02022): contiguous
column-slice gathers plus per-slot [rows] vector-add scatters that the
TPU backend serializes.

This module is the hand-placed version, mirroring `ops/histogram.py`'s
integration pattern end to end:

- grid (rows/row_tile, T): row blocks are "parallel", virtual trees
  "arbitrary" (phi accumulates across the T dimension, initialised at
  t == 0 per row block).
- the per-tree scalar tables (feat/lo/hi/na_ok [L, D], bias) are
  staged in SMEM; the transposed feature block [F, rt] and the
  pattern table [L, D, P] live in VMEM.
- the pattern gather is a one-hot matmul — ct_l [D, P] × onehot [P, rt]
  with Precision.HIGHEST and f32 accumulation — which is EXACT
  selection (0/1 against f32), the same trick the histogram kernel
  rides the MXU with.
- the per-slot scatter keeps the XLA reference's ORDERED f32
  accumulation (leaves outer, depth slots inner — XLA folds duplicate
  scatter indices in row-major update order), so results are
  deterministic and BITWISE-equal to `flat_shap_tab`: the feature row
  is fetched with a dynamic sublane slice (a matmul gather would
  poison on NaN features), and phi rows accumulate one dynamic slice
  at a time in slot order.

`resolve_impl("auto")` picks the kernel on TPU and the lowered-XLA
`flat_shap_tab` elsewhere; `H2O_TPU_SHAP_KERNEL=1/0` forces/kills it
(the kill switch restores the XLA path bitwise — same executable, not
a lookalike). On non-TPU backends the kernel runs in interpret mode,
which is how tier-1 (`tests/test_shap_kernel.py`) and
`kernel_gate.py --check shap_kernel_parity` pin bitwise parity on CPU;
the gate compiles it non-interpret when a chip is attached.

Like `hist_impl`, the knob is read when the serving program is TRACED:
a model's cached contributions executable keeps the impl it was traced
with until the scorer cache is evicted or the model is re-promoted.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .histogram import _COMPILER_PARAMS, _dimsem

__all__ = ["flat_shap_tab_kernel", "kernel_fits", "resolve_impl"]

# default row tile: [F+1, rt] phi + [F, rt] X + [P, rt] one-hot f32
# blocks stay comfortably inside VMEM at serving widths (F ≤ a few
# hundred, P = 2^D ≤ 2^14); pow2 so serving's bucketed batch shapes
# (_batch_bucket, ≥ 128) tile exactly.
_ROW_TILE = 512

# VMEM ceiling for the resident blocks of one grid step. ~16 MB/core
# on current chips; leave headroom for Mosaic's own temporaries.
_VMEM_BUDGET = 12 << 20

_MIN_ROWS = 128        # serving's _SCORE_MIN_BATCH — smaller batches
#                        never reach the device path un-padded


def resolve_impl(impl: str = "auto") -> str:
    """'auto'/'pallas'/'xla' -> 'pallas'|'xla'.

    auto consults H2O_TPU_SHAP_KERNEL (auto/1/0, pallas/xla aliases):
    0 is the kill switch (lowered-XLA `flat_shap_tab`, bitwise the
    pre-kernel path), 1 forces the kernel (interpret mode off-chip),
    auto picks the kernel only on a TPU backend. A typo must not
    silently demote the kernel, so junk values raise."""
    if impl == "auto":
        env = os.environ.get("H2O_TPU_SHAP_KERNEL", "auto")
        if env in ("1", "pallas"):
            return "pallas"
        if env in ("0", "xla"):
            return "xla"
        if env != "auto":
            raise ValueError(
                f"H2O_TPU_SHAP_KERNEL '{env}' is not one of auto/1/0")
        return "pallas" if jax.default_backend() == "tpu" else "xla"
    if impl not in ("pallas", "xla"):
        raise ValueError(f"unknown shap impl '{impl}'")
    return impl


def kernel_fits(tables, ctab, rows: int | None = None) -> bool:
    """Static eligibility of ONE virtual-tree group for the kernel.

    Ineligible groups silently take the XLA path even under =1 — the
    env knob selects an implementation, it must not turn a large-P
    group (or a non-pow2 debug batch) into a trace error."""
    if ctab is None:
        return False
    T, L, D, P = ctab.shape
    if rows is not None:
        if rows < _MIN_ROWS or rows & (rows - 1):
            return False
    rt = _ROW_TILE if rows is None else min(rows, _ROW_TILE)
    # resident f32 blocks of one grid step: ctab [L,D,P] + one-hot
    # [P,rt] + contrib [D,rt] + X [F,rt] + phi [F+1,rt]; F is bounded
    # by the X/phi terms — charge a generous 1024-feature stand-in
    # when the caller doesn't know rows/F yet.
    vmem = 4 * (L * D * P + P * rt + D * rt + 2 * 1024 * rt)
    return vmem <= _VMEM_BUDGET


def _shap_tab_kernel(feat_ref, lo_ref, hi_ref, na_ref, bias_ref,
                     xt_ref, ct_ref, phi_ref):
    """One (row-block, virtual-tree) grid step.

    feat/lo/hi/na: [1, L, D] SMEM scalar tables (one virtual tree);
    bias: [1, 1] SMEM; xt: [F, rt] VMEM transposed canonical features;
    ct: [1, L, D, P] VMEM pattern table; phi: [F+1, rt] accumulator.
    """
    L, D = feat_ref.shape[1], feat_ref.shape[2]
    P = ct_ref.shape[3]
    F = phi_ref.shape[0] - 1
    rt = phi_ref.shape[1]

    @pl.when(pl.program_id(1) == 0)
    def _():
        phi_ref[:] = jnp.zeros_like(phi_ref)

    iota_p = lax.broadcasted_iota(jnp.int32, (P, rt), 0)
    dn = (((1,), (0,)), ((), ()))

    def leaf(l, carry):
        # D-bit hot-pattern fold. Padding slots (feat == -1) carry
        # lo=-inf / hi=NaN / na_ok=True, so x >= -inf is hot for any
        # real value and NaN features take the na_ok branch — the bit
        # is 1 either way, matching `_one_fractions` exactly; the
        # max(fidx, 0) clamp only picks WHICH garbage row is compared.
        pat = jnp.zeros((1, rt), dtype=jnp.int32)
        for d in range(D):
            fidx = feat_ref[0, l, d]
            x = xt_ref[pl.ds(jnp.maximum(fidx, 0), 1), :]
            hot = (x >= lo_ref[0, l, d]) & ~(x >= hi_ref[0, l, d])
            o = (jnp.isnan(x) & (na_ref[0, l, d] != 0)) | hot
            pat = pat + o.astype(jnp.int32) * (1 << d)
        # pattern gather as exact one-hot matmul: [D, P] x [P, rt]
        onehot = (iota_p == pat).astype(jnp.float32)
        contrib = lax.dot_general(ct_ref[0, l], onehot,
                                  dimension_numbers=dn,
                                  preferred_element_type=jnp.float32,
                                  precision=lax.Precision.HIGHEST)
        # ordered per-slot scatter: padding slots target the bias row
        # F (their ct column is identically 0), duplicates fold in
        # slot order — the XLA reference's row-major scatter order.
        for d in range(D):
            fidx = feat_ref[0, l, d]
            tgt = jnp.where(fidx < 0, F, fidx)
            phi_ref[pl.ds(tgt, 1), :] = (phi_ref[pl.ds(tgt, 1), :]
                                         + contrib[d:d + 1, :])
        return carry

    lax.fori_loop(0, L, leaf, 0)
    phi_ref[F:F + 1, :] = phi_ref[F:F + 1, :] + bias_ref[0, 0]


@functools.partial(jax.jit, static_argnames=("row_tile",))
def flat_shap_tab_kernel(tables, ctab, X, enum_mask,
                         row_tile: int = _ROW_TILE):
    """[rows, F] × ShapTables × pattern table -> [rows, F+1] phi.

    Drop-in twin of `models/tree/shap.flat_shap_tab` (same canonical
    NaN-for-negative-enum rewrite, same ordered accumulation, bitwise
    output); caller guarantees `kernel_fits(tables, ctab, rows)`.
    """
    rows, F = X.shape
    T, L, D = tables.feat.shape
    rt = min(rows, row_tile)
    Xc = jnp.where(enum_mask[None, :] & (X < 0), jnp.float32(jnp.nan),
                   X)
    smem = functools.partial(pl.BlockSpec, memory_space=pltpu.SMEM)
    phi = pl.pallas_call(
        _shap_tab_kernel,
        out_shape=jax.ShapeDtypeStruct((F + 1, rows), jnp.float32),
        grid=(rows // rt, T),
        in_specs=[
            smem((1, L, D), lambda r, t: (t, 0, 0)),          # feat
            smem((1, L, D), lambda r, t: (t, 0, 0)),          # lo
            smem((1, L, D), lambda r, t: (t, 0, 0)),          # hi
            smem((1, L, D), lambda r, t: (t, 0, 0)),          # na_ok
            smem((1, 1), lambda r, t: (t, 0)),                # bias
            pl.BlockSpec((F, rt), lambda r, t: (0, r)),       # Xᵀ
            pl.BlockSpec((1, L, D) + ctab.shape[3:],
                         lambda r, t: (t, 0, 0, 0)),          # ctab
        ],
        out_specs=pl.BlockSpec((F + 1, rt), lambda r, t: (0, r)),
        compiler_params=_dimsem("parallel", "arbitrary"),
        interpret=jax.default_backend() != "tpu",
    )(tables.feat.astype(jnp.int32), tables.lo, tables.hi,
      tables.na_ok.astype(jnp.int32), tables.bias.reshape(T, 1),
      Xc.T, ctab)
    return phi.T
