import numpy as np
import pytest

from h2o_kubernetes_tpu import Frame
from h2o_kubernetes_tpu.models import GLM


def _gaussian_data(n=4000, seed=0):
    rng = np.random.default_rng(seed)
    x1 = rng.normal(size=n)
    x2 = rng.normal(size=n)
    g = np.array(["a", "b", "c"])[rng.integers(0, 3, size=n)]
    y = 2.0 * x1 - 1.0 * x2 + 0.5 * (g == "b") + 1.5 * (g == "c") + 3.0 \
        + rng.normal(scale=0.5, size=n)
    fr = Frame.from_arrays({"x1": x1, "x2": x2, "g": g, "y": y})
    return fr, x1, x2, g, y


def test_glm_gaussian_matches_ols(mesh8):
    fr, x1, x2, g, y = _gaussian_data()
    m = GLM(family="gaussian", lambda_=0.0).train(y="y", training_frame=fr)
    coef = m.coef()
    # closed-form check vs sklearn OLS on the same design
    from sklearn.linear_model import LinearRegression

    X = np.stack([x1, x2, (g == "b"), (g == "c")], axis=1).astype(float)
    sk = LinearRegression().fit(X, y)
    np.testing.assert_allclose(coef["x1"], sk.coef_[0], rtol=1e-3)
    np.testing.assert_allclose(coef["x2"], sk.coef_[1], rtol=1e-3)
    np.testing.assert_allclose(coef["g.b"], sk.coef_[2], rtol=2e-2)
    np.testing.assert_allclose(coef["g.c"], sk.coef_[3], rtol=2e-2)
    np.testing.assert_allclose(coef["Intercept"], sk.intercept_, rtol=1e-2)
    assert m.model_performance(fr, "y")["r2"] > 0.9


def test_glm_binomial_matches_sklearn(mesh8):
    rng = np.random.default_rng(1)
    n = 6000
    x1 = rng.normal(size=n)
    x2 = rng.normal(size=n)
    pr = 1 / (1 + np.exp(-(0.8 * x1 - 1.5 * x2 + 0.3)))
    y = (rng.uniform(size=n) < pr).astype(int)
    fr = Frame.from_arrays({"x1": x1, "x2": x2,
                            "y": np.array(["n", "p"])[y]})
    m = GLM(family="binomial", lambda_=0.0).train(y="y", training_frame=fr)
    coef = m.coef()
    from sklearn.linear_model import LogisticRegression

    sk = LogisticRegression(C=np.inf, tol=1e-8).fit(
        np.stack([x1, x2], 1), y)
    np.testing.assert_allclose(coef["x1"], sk.coef_[0][0], rtol=2e-2)
    np.testing.assert_allclose(coef["x2"], sk.coef_[0][1], rtol=2e-2)
    perf = m.model_performance(fr, "y")
    assert perf["auc"] > 0.8
    assert m.null_deviance > m.residual_deviance


def test_glm_poisson(mesh8):
    rng = np.random.default_rng(2)
    n = 5000
    x = rng.normal(size=n)
    lam = np.exp(0.5 * x + 1.0)
    y = rng.poisson(lam).astype(float)
    fr = Frame.from_arrays({"x": x, "y": y})
    m = GLM(family="poisson", lambda_=0.0).train(y="y", training_frame=fr)
    coef = m.coef()
    np.testing.assert_allclose(coef["x"], 0.5, atol=0.05)
    np.testing.assert_allclose(coef["Intercept"], 1.0, atol=0.05)


def test_glm_lasso_sparsifies(mesh8):
    rng = np.random.default_rng(3)
    n = 3000
    X = rng.normal(size=(n, 10))
    y = 2.0 * X[:, 0] - 1.0 * X[:, 1] + rng.normal(scale=0.3, size=n)
    fr = Frame.from_arrays({f"x{i}": X[:, i] for i in range(10)} | {"y": y})
    m = GLM(family="gaussian", alpha=1.0, lambda_=0.1).train(
        y="y", training_frame=fr)
    coef = m.coef()
    noise_coefs = [abs(coef[f"x{i}"]) for i in range(2, 10)]
    assert max(noise_coefs) < 0.02          # noise zeroed by L1
    assert abs(coef["x0"]) > 1.5            # signal survives


def test_glm_lambda_search(mesh8):
    fr, *_ = _gaussian_data(n=2000, seed=4)
    m = GLM(family="gaussian", lambda_search=True, nlambdas=10,
            alpha=0.5).train(y="y", training_frame=fr)
    assert m.lambda_used < 0.01  # path descended far below lambda_max
    assert m.model_performance(fr, "y")["r2"] > 0.85


def test_glm_lbfgs_close_to_irlsm(mesh8):
    rng = np.random.default_rng(5)
    n = 4000
    x1 = rng.normal(size=n)
    x2 = rng.normal(size=n)
    pr = 1 / (1 + np.exp(-(1.0 * x1 - 0.5 * x2)))
    y = (rng.uniform(size=n) < pr).astype(int)
    fr = Frame.from_arrays({"x1": x1, "x2": x2,
                            "y": np.array(["n", "p"])[y]})
    a = GLM(family="binomial", solver="IRLSM", lambda_=0.0,
            max_iterations=50).train(y="y", training_frame=fr)
    b = GLM(family="binomial", solver="L_BFGS", lambda_=0.0,
            max_iterations=200).train(y="y", training_frame=fr)
    ca, cb = a.coef(), b.coef()
    np.testing.assert_allclose(ca["x1"], cb["x1"], atol=0.03)
    np.testing.assert_allclose(ca["x2"], cb["x2"], atol=0.03)


def test_glm_na_imputation(mesh8):
    rng = np.random.default_rng(6)
    n = 2000
    x = rng.normal(size=n)
    y = 2 * x + rng.normal(scale=0.1, size=n)
    x_na = x.copy()
    x_na[::7] = np.nan
    fr = Frame.from_arrays({"x": x_na, "y": y})
    m = GLM(family="gaussian", lambda_=0.0).train(y="y", training_frame=fr)
    assert abs(m.coef()["x"] - 2.0) < 0.2


def test_glm_family_response_validation(mesh8):
    fr = Frame.from_arrays({"x": np.arange(10.0),
                            "y": np.array(["a", "b"] * 5)})
    with pytest.raises(ValueError, match="categorical"):
        GLM(family="gaussian").train(y="y", training_frame=fr)
    fr2 = Frame.from_arrays({"x": np.arange(10.0), "y": np.arange(10.0)})
    with pytest.raises(ValueError, match="categorical|2-class"):
        GLM(family="binomial").train(y="y", training_frame=fr2)


def test_glm_param_validation(mesh8):
    fr = Frame.from_arrays({"x": np.arange(10.0), "y": np.arange(10.0)})
    with pytest.raises(ValueError, match="family"):
        GLM(family="martian").train(y="y", training_frame=fr)
    with pytest.raises(ValueError, match="solver"):
        GLM(solver="NEWTON").train(y="y", training_frame=fr)


def test_glm_enum_na_scoring_mode_imputed(mesh8):
    rng = np.random.default_rng(7)
    n = 2000
    g = np.array(["a", "b", "b", "b"])[rng.integers(0, 4, size=n)]  # b modal
    y = 1.0 * (g == "b") + rng.normal(scale=0.1, size=n)
    fr = Frame.from_arrays({"g": g, "y": y})
    m = GLM(family="gaussian", lambda_=0.0).train(y="y", training_frame=fr)
    # scoring frame with an unseen level: must impute to mode 'b', not 'a'
    sf = Frame.from_arrays({"g": np.array(["zz", "a", "b"])})
    pred = m.predict_raw(sf)
    np.testing.assert_allclose(pred[0], pred[2], atol=0.05)  # zz ≈ b
    assert abs(pred[0] - pred[1]) > 0.5                      # zz != a


def test_glm_cols_axis_mesh_parity(mesh8):
    """Gram sharded over the COLS (wide-feature TP) axis must reproduce
    the row-only result: 4x2 mesh vs the default 8x1 mesh."""
    from h2o_kubernetes_tpu.runtime import make_mesh, use_mesh

    rng = np.random.default_rng(21)
    n = 512
    x = rng.normal(size=(n, 5)).astype(np.float32)
    cat = np.array(["a", "b", "c"])[rng.integers(0, 3, size=n)]
    logit = x[:, 0] - 0.5 * x[:, 1] + (cat == "b") * 0.8
    fr = Frame.from_arrays({
        **{f"x{i}": x[:, i] for i in range(5)},
        "c": cat,
        "y": np.where(logit + rng.normal(scale=0.3, size=n) > 0,
                      "yes", "no")})
    m1 = GLM(family="binomial", lambda_=0.01, alpha=0.5,
             max_iterations=20, seed=0).train(y="y", training_frame=fr)
    with use_mesh(make_mesh(n_rows=4, n_cols=2)):
        m2 = GLM(family="binomial", lambda_=0.01, alpha=0.5,
                 max_iterations=20, seed=0).train(y="y", training_frame=fr)
    np.testing.assert_allclose(np.asarray(m1.beta), np.asarray(m2.beta),
                               rtol=2e-4, atol=2e-5)
    # odd expanded-feature count exercises the padding path on 4x2
    assert m1.dinfo.n_expanded % 2 == 1
