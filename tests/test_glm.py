import numpy as np
import pytest

from h2o_kubernetes_tpu import Frame
from h2o_kubernetes_tpu.models import GLM


def _gaussian_data(n=4000, seed=0):
    rng = np.random.default_rng(seed)
    x1 = rng.normal(size=n)
    x2 = rng.normal(size=n)
    g = np.array(["a", "b", "c"])[rng.integers(0, 3, size=n)]
    y = 2.0 * x1 - 1.0 * x2 + 0.5 * (g == "b") + 1.5 * (g == "c") + 3.0 \
        + rng.normal(scale=0.5, size=n)
    fr = Frame.from_arrays({"x1": x1, "x2": x2, "g": g, "y": y})
    return fr, x1, x2, g, y


def test_glm_gaussian_matches_ols(mesh8):
    fr, x1, x2, g, y = _gaussian_data()
    m = GLM(family="gaussian", lambda_=0.0).train(y="y", training_frame=fr)
    coef = m.coef()
    # closed-form check vs sklearn OLS on the same design
    from sklearn.linear_model import LinearRegression

    X = np.stack([x1, x2, (g == "b"), (g == "c")], axis=1).astype(float)
    sk = LinearRegression().fit(X, y)
    np.testing.assert_allclose(coef["x1"], sk.coef_[0], rtol=1e-3)
    np.testing.assert_allclose(coef["x2"], sk.coef_[1], rtol=1e-3)
    np.testing.assert_allclose(coef["g.b"], sk.coef_[2], rtol=2e-2)
    np.testing.assert_allclose(coef["g.c"], sk.coef_[3], rtol=2e-2)
    np.testing.assert_allclose(coef["Intercept"], sk.intercept_, rtol=1e-2)
    assert m.model_performance(fr, "y")["r2"] > 0.9


def test_glm_binomial_matches_sklearn(mesh8):
    rng = np.random.default_rng(1)
    n = 6000
    x1 = rng.normal(size=n)
    x2 = rng.normal(size=n)
    pr = 1 / (1 + np.exp(-(0.8 * x1 - 1.5 * x2 + 0.3)))
    y = (rng.uniform(size=n) < pr).astype(int)
    fr = Frame.from_arrays({"x1": x1, "x2": x2,
                            "y": np.array(["n", "p"])[y]})
    m = GLM(family="binomial", lambda_=0.0).train(y="y", training_frame=fr)
    coef = m.coef()
    from sklearn.linear_model import LogisticRegression

    sk = LogisticRegression(C=np.inf, tol=1e-8).fit(
        np.stack([x1, x2], 1), y)
    np.testing.assert_allclose(coef["x1"], sk.coef_[0][0], rtol=2e-2)
    np.testing.assert_allclose(coef["x2"], sk.coef_[0][1], rtol=2e-2)
    perf = m.model_performance(fr, "y")
    assert perf["auc"] > 0.8
    assert m.null_deviance > m.residual_deviance


def test_glm_poisson(mesh8):
    rng = np.random.default_rng(2)
    n = 5000
    x = rng.normal(size=n)
    lam = np.exp(0.5 * x + 1.0)
    y = rng.poisson(lam).astype(float)
    fr = Frame.from_arrays({"x": x, "y": y})
    m = GLM(family="poisson", lambda_=0.0).train(y="y", training_frame=fr)
    coef = m.coef()
    np.testing.assert_allclose(coef["x"], 0.5, atol=0.05)
    np.testing.assert_allclose(coef["Intercept"], 1.0, atol=0.05)


def test_glm_lasso_sparsifies(mesh8):
    rng = np.random.default_rng(3)
    n = 3000
    X = rng.normal(size=(n, 10))
    y = 2.0 * X[:, 0] - 1.0 * X[:, 1] + rng.normal(scale=0.3, size=n)
    fr = Frame.from_arrays({f"x{i}": X[:, i] for i in range(10)} | {"y": y})
    m = GLM(family="gaussian", alpha=1.0, lambda_=0.1).train(
        y="y", training_frame=fr)
    coef = m.coef()
    noise_coefs = [abs(coef[f"x{i}"]) for i in range(2, 10)]
    assert max(noise_coefs) < 0.02          # noise zeroed by L1
    assert abs(coef["x0"]) > 1.5            # signal survives


def test_glm_lambda_search(mesh8):
    fr, *_ = _gaussian_data(n=2000, seed=4)
    m = GLM(family="gaussian", lambda_search=True, nlambdas=10,
            alpha=0.5).train(y="y", training_frame=fr)
    assert m.lambda_used < 0.01  # path descended far below lambda_max
    assert m.model_performance(fr, "y")["r2"] > 0.85


def test_glm_lbfgs_close_to_irlsm(mesh8):
    rng = np.random.default_rng(5)
    n = 4000
    x1 = rng.normal(size=n)
    x2 = rng.normal(size=n)
    pr = 1 / (1 + np.exp(-(1.0 * x1 - 0.5 * x2)))
    y = (rng.uniform(size=n) < pr).astype(int)
    fr = Frame.from_arrays({"x1": x1, "x2": x2,
                            "y": np.array(["n", "p"])[y]})
    a = GLM(family="binomial", solver="IRLSM", lambda_=0.0,
            max_iterations=50).train(y="y", training_frame=fr)
    b = GLM(family="binomial", solver="L_BFGS", lambda_=0.0,
            max_iterations=200).train(y="y", training_frame=fr)
    ca, cb = a.coef(), b.coef()
    np.testing.assert_allclose(ca["x1"], cb["x1"], atol=0.03)
    np.testing.assert_allclose(ca["x2"], cb["x2"], atol=0.03)


def test_glm_na_imputation(mesh8):
    rng = np.random.default_rng(6)
    n = 2000
    x = rng.normal(size=n)
    y = 2 * x + rng.normal(scale=0.1, size=n)
    x_na = x.copy()
    x_na[::7] = np.nan
    fr = Frame.from_arrays({"x": x_na, "y": y})
    m = GLM(family="gaussian", lambda_=0.0).train(y="y", training_frame=fr)
    assert abs(m.coef()["x"] - 2.0) < 0.2


def test_glm_family_response_validation(mesh8):
    fr = Frame.from_arrays({"x": np.arange(10.0),
                            "y": np.array(["a", "b"] * 5)})
    with pytest.raises(ValueError, match="categorical"):
        GLM(family="gaussian").train(y="y", training_frame=fr)
    fr2 = Frame.from_arrays({"x": np.arange(10.0), "y": np.arange(10.0)})
    with pytest.raises(ValueError, match="categorical|2-class"):
        GLM(family="binomial").train(y="y", training_frame=fr2)


def test_glm_param_validation(mesh8):
    fr = Frame.from_arrays({"x": np.arange(10.0), "y": np.arange(10.0)})
    with pytest.raises(ValueError, match="family"):
        GLM(family="martian").train(y="y", training_frame=fr)
    with pytest.raises(ValueError, match="solver"):
        GLM(solver="NEWTON").train(y="y", training_frame=fr)


def test_glm_enum_na_scoring_mode_imputed(mesh8):
    rng = np.random.default_rng(7)
    n = 2000
    g = np.array(["a", "b", "b", "b"])[rng.integers(0, 4, size=n)]  # b modal
    y = 1.0 * (g == "b") + rng.normal(scale=0.1, size=n)
    fr = Frame.from_arrays({"g": g, "y": y})
    m = GLM(family="gaussian", lambda_=0.0).train(y="y", training_frame=fr)
    # scoring frame with an unseen level: must impute to mode 'b', not 'a'
    sf = Frame.from_arrays({"g": np.array(["zz", "a", "b"])})
    pred = m.predict_raw(sf)
    np.testing.assert_allclose(pred[0], pred[2], atol=0.05)  # zz ≈ b
    assert abs(pred[0] - pred[1]) > 0.5                      # zz != a


def test_glm_cols_axis_mesh_parity(mesh8):
    """Gram sharded over the COLS (wide-feature TP) axis must reproduce
    the row-only result: 4x2 mesh vs the default 8x1 mesh."""
    from h2o_kubernetes_tpu.runtime import make_mesh, use_mesh

    rng = np.random.default_rng(21)
    n = 512
    x = rng.normal(size=(n, 6)).astype(np.float32)
    cat = np.array(["a", "b", "c"])[rng.integers(0, 3, size=n)]
    logit = x[:, 0] - 0.5 * x[:, 1] + (cat == "b") * 0.8
    fr = Frame.from_arrays({
        **{f"x{i}": x[:, i] for i in range(6)},
        "c": cat,
        "y": np.where(logit + rng.normal(scale=0.3, size=n) > 0,
                      "yes", "no")})
    m1 = GLM(family="binomial", lambda_=0.01, alpha=0.5,
             max_iterations=20, seed=0).train(y="y", training_frame=fr)
    with use_mesh(make_mesh(n_rows=4, n_cols=2)):
        m2 = GLM(family="binomial", lambda_=0.01, alpha=0.5,
                 max_iterations=20, seed=0).train(y="y", training_frame=fr)
    np.testing.assert_allclose(np.asarray(m1.beta), np.asarray(m2.beta),
                               rtol=2e-4, atol=2e-5)
    # odd expanded-feature count exercises the padding path on 4x2
    assert m1.dinfo.n_expanded % 2 == 1


# -- round-2 family/solver breadth (VERDICT #8) ------------------------------

def test_glm_gamma_log_link_matches_sklearn(mesh8):
    rng = np.random.default_rng(5)
    n = 4000
    x1, x2 = rng.normal(size=n), rng.normal(size=n)
    mu = np.exp(0.6 * x1 - 0.4 * x2 + 1.0)
    y = rng.gamma(shape=4.0, scale=mu / 4.0)
    fr = Frame.from_arrays({"x1": x1, "x2": x2, "y": y})
    m = GLM(family="gamma", link="log", lambda_=0.0).train(
        y="y", training_frame=fr)
    from sklearn.linear_model import GammaRegressor

    sk = GammaRegressor(alpha=0.0, tol=1e-8, max_iter=1000).fit(
        np.stack([x1, x2], 1), y)
    coef = m.coef()
    np.testing.assert_allclose(coef["x1"], sk.coef_[0], rtol=2e-2)
    np.testing.assert_allclose(coef["x2"], sk.coef_[1], rtol=2e-2)
    np.testing.assert_allclose(coef["Intercept"], sk.intercept_, rtol=2e-2)
    assert m.null_deviance > m.residual_deviance


def test_glm_gamma_inverse_link_default(mesh8):
    rng = np.random.default_rng(6)
    n = 3000
    x1 = rng.uniform(0.5, 1.5, size=n)
    mu = 1.0 / (0.8 * x1 + 1.2)
    y = rng.gamma(shape=5.0, scale=mu / 5.0)
    fr = Frame.from_arrays({"x1": x1, "y": y})
    m = GLM(family="gamma", lambda_=0.0, standardize=False).train(
        y="y", training_frame=fr)
    coef = m.coef()   # default link is inverse (reference default)
    np.testing.assert_allclose(coef["x1"], 0.8, rtol=0.15)
    np.testing.assert_allclose(coef["Intercept"], 1.2, rtol=0.15)


def test_glm_tweedie_matches_sklearn(mesh8):
    rng = np.random.default_rng(7)
    n = 5000
    x1, x2 = rng.normal(size=n), rng.normal(size=n)
    mu = np.exp(0.5 * x1 + 0.25 * x2)
    # compound poisson-gamma sample (exact zeros + positive mass)
    npois = rng.poisson(mu)
    y = np.array([rng.gamma(sh, 1.0) if sh > 0 else 0.0 for sh in npois])
    fr = Frame.from_arrays({"x1": x1, "x2": x2, "y": y})
    m = GLM(family="tweedie", tweedie_variance_power=1.5,
            lambda_=0.0).train(y="y", training_frame=fr)
    from sklearn.linear_model import TweedieRegressor

    sk = TweedieRegressor(power=1.5, alpha=0.0, link="log", tol=1e-8,
                          max_iter=2000).fit(np.stack([x1, x2], 1), y)
    coef = m.coef()
    np.testing.assert_allclose(coef["x1"], sk.coef_[0], rtol=5e-2)
    np.testing.assert_allclose(coef["x2"], sk.coef_[1], rtol=5e-2)


def test_glm_negativebinomial(mesh8):
    rng = np.random.default_rng(8)
    n = 5000
    x1 = rng.normal(size=n)
    mu = np.exp(0.7 * x1 + 0.5)
    theta = 0.5   # var = mu + theta*mu^2
    y = rng.negative_binomial(1.0 / theta, 1.0 / (1.0 + theta * mu))
    fr = Frame.from_arrays({"x1": x1, "y": y.astype(np.float64)})
    m = GLM(family="negativebinomial", theta=0.5, lambda_=0.0).train(
        y="y", training_frame=fr)
    coef = m.coef()
    np.testing.assert_allclose(coef["x1"], 0.7, rtol=0.1)
    np.testing.assert_allclose(coef["Intercept"], 0.5, atol=0.1)
    assert m.null_deviance > m.residual_deviance


def test_glm_multinomial_matches_sklearn(mesh8):
    rng = np.random.default_rng(9)
    n = 6000
    x1, x2 = rng.normal(size=n), rng.normal(size=n)
    logits = np.stack([0.0 * x1, 1.2 * x1 - 0.4 * x2,
                       -0.8 * x1 + 0.9 * x2], axis=1)
    pr = np.exp(logits) / np.exp(logits).sum(1, keepdims=True)
    yk = np.array([rng.choice(3, p=p) for p in pr])
    fr = Frame.from_arrays({"x1": x1, "x2": x2,
                            "y": np.array(["a", "b", "c"])[yk]})
    m = GLM(family="multinomial", lambda_=0.0, max_iterations=200).train(
        y="y", training_frame=fr)
    from sklearn.linear_model import LogisticRegression
    from sklearn.metrics import accuracy_score

    sk = LogisticRegression(C=np.inf, tol=1e-8, max_iter=2000).fit(
        np.stack([x1, x2], 1), yk)
    pred = m.predict(fr)
    acc = float(np.mean(pred["predict"].to_numpy() == yk))
    sk_acc = accuracy_score(yk, sk.predict(np.stack([x1, x2], 1)))
    assert acc > sk_acc - 0.01
    # softmax coefs are identified up to a per-feature shift: compare
    # class contrasts (b - a), which are shift-invariant
    coef = m.coef()
    contrast = coef["b"]["x1"] - coef["a"]["x1"]
    sk_contrast = sk.coef_[1][0] - sk.coef_[0][0]
    np.testing.assert_allclose(contrast, sk_contrast, rtol=5e-2)


def test_glm_coordinate_descent_matches_cholesky(mesh8):
    fr, x1, x2, g, y = _gaussian_data()
    m_cd = GLM(solver="COORDINATE_DESCENT", lambda_=0.0,
               max_iterations=100).train(y="y", training_frame=fr)
    m_ch = GLM(solver="IRLSM", lambda_=0.0).train(y="y", training_frame=fr)
    c1, c2 = m_cd.coef(), m_ch.coef()
    for k in c1:
        np.testing.assert_allclose(c1[k], c2[k], rtol=1e-3, atol=1e-4)


def test_glm_coordinate_descent_lasso_sparsity(mesh8):
    rng = np.random.default_rng(11)
    n = 2000
    X = rng.normal(size=(n, 6))
    y = 3.0 * X[:, 0] + rng.normal(scale=0.1, size=n)  # only x0 matters
    fr = Frame.from_arrays({f"x{i}": X[:, i] for i in range(6)} | {"y": y})
    m = GLM(solver="COORDINATE_DESCENT", alpha=1.0, lambda_=0.1).train(
        y="y", training_frame=fr)
    coef = m.coef_norm()
    zeros = sum(1 for k, v in coef.items()
                if k not in ("x0", "Intercept") and abs(v) < 1e-6)
    assert zeros >= 4          # noise coefs hard-zeroed by the L1 path
    assert abs(coef["x0"]) > 1.0


def test_glm_p_values_ols_oracle(mesh8):
    rng = np.random.default_rng(12)
    n = 500
    x1, x2 = rng.normal(size=n), rng.normal(size=n)
    y = 1.5 * x1 + 0.0 * x2 + 2.0 + rng.normal(scale=1.0, size=n)
    fr = Frame.from_arrays({"x1": x1, "x2": x2, "y": y})
    m = GLM(family="gaussian", lambda_=0.0, compute_p_values=True).train(
        y="y", training_frame=fr)
    # closed-form OLS standard errors as the oracle
    X = np.stack([x1, x2, np.ones(n)], axis=1)
    b = np.linalg.lstsq(X, y, rcond=None)[0]
    resid = y - X @ b
    s2 = resid @ resid / (n - 3)
    se = np.sqrt(np.diag(np.linalg.inv(X.T @ X)) * s2)
    got = m.std_errs()
    np.testing.assert_allclose(got["x1"], se[0], rtol=2e-2)
    np.testing.assert_allclose(got["x2"], se[1], rtol=2e-2)
    np.testing.assert_allclose(got["Intercept"], se[2], rtol=2e-2)
    assert m.pvalues()["x1"] < 1e-6       # real effect
    assert m.pvalues()["x2"] > 0.05       # null effect
    assert m.zvalues()["x1"] > 10


def test_glm_p_values_requires_irlsm_lambda0(mesh8):
    fr, *_ = _gaussian_data(n=200)
    with pytest.raises(ValueError):
        GLM(compute_p_values=True, lambda_=0.5).train(
            y="y", training_frame=fr)
    with pytest.raises(ValueError):
        GLM(compute_p_values=True, solver="L_BFGS").train(
            y="y", training_frame=fr)


def test_glm_gamma_rejects_nonpositive_response(mesh8):
    fr = Frame.from_arrays({"x": np.arange(10.0),
                            "y": np.arange(10.0) - 5.0})
    with pytest.raises(ValueError):
        GLM(family="gamma").train(y="y", training_frame=fr)


@pytest.mark.slow
def test_glm_multinomial_irlsm_vs_lbfgs(mesh8):
    """Multinomial under IRLSM (cyclic per-class Fisher scoring, the
    reference's shape) must land on the same solution the L-BFGS path
    finds — class contrasts are the identified quantities."""
    rng = np.random.default_rng(15)
    n = 4000
    x1, x2 = rng.normal(size=n), rng.normal(size=n)
    logits = np.stack([0.0 * x1, 1.0 * x1 - 0.5 * x2,
                       -0.7 * x1 + 0.8 * x2], axis=1)
    pr = np.exp(logits) / np.exp(logits).sum(1, keepdims=True)
    yk = np.array([rng.choice(3, p=p) for p in pr])
    fr = Frame.from_arrays({"x1": x1, "x2": x2,
                            "y": np.array(["a", "b", "c"])[yk]})
    mi = GLM(family="multinomial", solver="IRLSM", lambda_=0.0,
             max_iterations=100).train(y="y", training_frame=fr)
    ml = GLM(family="multinomial", solver="L_BFGS", lambda_=0.0,
             max_iterations=300).train(y="y", training_frame=fr)
    ci, cl = mi.coef(), ml.coef()
    for feat in ("x1", "x2"):
        for k in ("b", "c"):
            got = ci[k][feat] - ci["a"][feat]
            want = cl[k][feat] - cl["a"][feat]
            assert abs(got - want) < 0.05, (feat, k, got, want)
    # ridge-penalized cyclic solve also converges
    mr = GLM(family="multinomial", solver="IRLSM", lambda_=0.01,
             alpha=0.0, max_iterations=50).train(y="y", training_frame=fr)
    acc = float(np.mean(mr.predict(fr)["predict"].to_numpy() == yk))
    assert acc > 0.55


def test_glm_scoring_history(mesh8):
    """GLM records one row per solver iteration (GLMScoringInfo
    analog): IRLSM logs deviance, L-BFGS logs objective, and the
    recorded deviance must be non-increasing for a well-posed fit."""
    rng = np.random.default_rng(5)
    n = 2000
    x = rng.normal(size=n).astype(np.float32)
    y = np.where(x + rng.normal(scale=0.8, size=n) > 0, "a", "b")
    fr = Frame.from_arrays({"x": x, "y": y})

    m = GLM(family="binomial", solver="IRLSM", lambda_=0.0).train(
        y="y", training_frame=fr)
    h = m.scoring_history
    assert len(h) == m.n_iterations >= 1
    devs = [r["deviance"] for r in h]
    assert all(b <= a + 1e-6 * abs(a) for a, b in zip(devs, devs[1:]))

    m2 = GLM(family="binomial", solver="L_BFGS", lambda_=0.0,
             max_iterations=25).train(y="y", training_frame=fr)
    assert m2.scoring_history and "objective" in m2.scoring_history[0]

    y3 = np.where(x > 0.5, "p", np.where(x < -0.5, "q", "r"))
    fr3 = Frame.from_arrays({"x": x, "y": y3})
    m3 = GLM(family="multinomial", solver="IRLSM", lambda_=0.0).train(
        y="y", training_frame=fr3)
    assert m3.scoring_history and "deviance" in m3.scoring_history[0]
