"""Fault-injection harness + retry/backoff resilience layer.

Chaos drills (ISSUE 1): prove the failure paths — persist HTTP bursts,
probe hangs, device errors escaping a training step — recover through
the retry layer and the checkpoint-restart protocol, on CPU, without a
real outage. The acceptance test also proves the NEGATIVE: with the
retry layer disabled via env, the same faults break the run (the
harness really exercises the path).
"""

import json
import os
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, HTTPServer

import numpy as np
import pytest

import h2o_kubernetes_tpu as h2o
from h2o_kubernetes_tpu.runtime import faults, health, retry

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _fresh_faults():
    faults.reset()
    health.reset()
    yield
    faults.reset()
    health.reset()


# -- fault-spec grammar ------------------------------------------------------

def test_parse_spec_grammar():
    fs = faults.parse("persist.http:http_503*2;train.step:device_error@3;"
                      "health.probe:hang~0.5, persist.http:http_429*inf~1.5")
    assert [f.site for f in fs] == ["persist.http", "train.step",
                                    "health.probe", "persist.http"]
    assert fs[0].count == 2 and fs[0].skip == 0
    assert fs[1].skip == 3 and fs[1].count == 1
    assert fs[2].param == 0.5
    assert fs[3].count == float("inf") and fs[3].param == 1.5
    # round-trips through .spec()
    assert faults.parse(";".join(f.spec() for f in fs)) == fs


def test_parse_spec_rejects_garbage():
    with pytest.raises(ValueError, match="bad fault clause"):
        faults.parse("persist.http=503")
    with pytest.raises(ValueError, match="bad fault clause"):
        faults.parse("nope")


def test_fire_consumes_skip_then_count():
    with faults.inject("site.a:error*2@1"):
        faults.fire("site.a")                 # skipped
        with pytest.raises(faults.FaultError):
            faults.fire("site.a")
        with pytest.raises(faults.FaultError):
            faults.fire("site.a")
        faults.fire("site.a")                 # exhausted — passes
    faults.fire("site.a")                     # disarmed outside the block


def test_env_activation(monkeypatch):
    monkeypatch.setenv("H2O_TPU_FAULTS", "site.env:error")
    assert "site.env:error" in faults.active()
    with pytest.raises(faults.FaultError):
        faults.fire("site.env")
    faults.fire("site.env")                   # count exhausted
    # a CHANGED env value re-arms fresh counters
    monkeypatch.setenv("H2O_TPU_FAULTS", "site.env:error*1")
    with pytest.raises(faults.FaultError):
        faults.fire("site.env")


# -- retry layer -------------------------------------------------------------

def test_retry_backoff_then_success():
    calls = {"n": 0}
    sleeps: list[float] = []

    def flaky():
        calls["n"] += 1
        if calls["n"] < 4:
            raise retry.TransientError(f"blip {calls['n']}")
        return "ok"

    pol = retry.RetryPolicy(attempts=5, base=0.1, max_delay=10.0,
                            deadline=60.0, jitter=False)
    assert retry.call(flaky, policy=pol, sleep=sleeps.append) == "ok"
    assert calls["n"] == 4
    assert sleeps == [0.1, 0.2, 0.4]          # exponential, no jitter


def test_retry_jitter_bounds():
    pol = retry.RetryPolicy(base=1.0, jitter=True)
    delays = [pol.backoff(1) for _ in range(50)]
    assert all(0.5 <= d <= 1.0 for d in delays)
    assert len(set(delays)) > 1               # actually jittered


def test_retry_honors_retry_after():
    sleeps: list[float] = []
    calls = {"n": 0}

    def throttled():
        calls["n"] += 1
        if calls["n"] == 1:
            raise retry.TransientError("429", retry_after=0.017)
        return "ok"

    pol = retry.RetryPolicy(attempts=3, base=5.0, jitter=False)
    assert retry.call(throttled, policy=pol, sleep=sleeps.append) == "ok"
    assert sleeps == [0.017]                  # server wait, not backoff


def test_retry_exhaustion_raises_last_transient():
    def hopeless():
        raise retry.TransientError("still down")

    pol = retry.RetryPolicy(attempts=3, base=0.0, jitter=False)
    with pytest.raises(IOError, match="still down"):
        retry.call(hopeless, policy=pol, sleep=lambda s: None)


def test_retry_permanent_error_no_retry():
    calls = {"n": 0}

    def broken():
        calls["n"] += 1
        raise FileNotFoundError("gone")

    with pytest.raises(FileNotFoundError):
        retry.call(broken, policy=retry.RetryPolicy(attempts=5),
                   sleep=lambda s: None)
    assert calls["n"] == 1


def test_retry_env_knobs(monkeypatch):
    monkeypatch.setenv("H2O_TPU_RETRY_ATTEMPTS", "7")
    monkeypatch.setenv("H2O_TPU_RETRY_BASE", "0.05")
    pol = retry.policy_from_env()
    assert pol.attempts == 7 and pol.base == 0.05
    monkeypatch.setenv("H2O_TPU_RETRY_DISABLE", "1")
    assert retry.policy_from_env().attempts == 1


# -- persist HTTP path under faults ------------------------------------------

class _FlakyStore(BaseHTTPRequestHandler):
    """Tiny object store whose failure behavior tests steer per-class:
    `fail_codes` is a queue of status codes returned (and consumed)
    before requests succeed; `put_404` makes every PUT 404."""

    store: dict[str, bytes] = {}
    fail_codes: list[int] = []
    put_404: bool = False
    requests: list[str] = []

    def log_message(self, *a):
        pass

    def _maybe_fail(self) -> bool:
        type(self).requests.append(f"{self.command} {self.path}")
        if self.fail_codes:
            code = type(self).fail_codes.pop(0)
            self.send_response(code)
            if code == 429:
                self.send_header("Retry-After", "0.01")
            self.end_headers()
            return True
        return False

    def do_GET(self):
        if self._maybe_fail():
            return
        key = self.path.split("?", 1)[0]
        if key not in self.store:
            self.send_response(404)
            self.end_headers()
            return
        body = self.store[key]
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_PUT(self):
        n = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(n)
        if self._maybe_fail():
            return
        if type(self).put_404:
            self.send_response(404)
            self.end_headers()
            return
        self.store[self.path.split("?", 1)[0]] = body
        self.send_response(200)
        self.end_headers()

    do_POST = do_PUT


@pytest.fixture()
def flaky_store(monkeypatch):
    _FlakyStore.store = {}
    _FlakyStore.fail_codes = []
    _FlakyStore.put_404 = False
    _FlakyStore.requests = []
    srv = HTTPServer(("127.0.0.1", 0), _FlakyStore)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    url = f"http://127.0.0.1:{srv.server_port}"
    monkeypatch.setenv("AWS_ENDPOINT_URL", url)
    monkeypatch.delenv("AWS_ACCESS_KEY_ID", raising=False)
    monkeypatch.delenv("AWS_SECRET_ACCESS_KEY", raising=False)
    # fast, deterministic-enough retries for tests
    monkeypatch.setenv("H2O_TPU_RETRY_BASE", "0.01")
    monkeypatch.setenv("H2O_TPU_RETRY_MAX_DELAY", "0.05")
    yield url
    srv.shutdown()


def test_persist_survives_503_burst_from_server(flaky_store):
    _FlakyStore.fail_codes = [503, 503]
    h2o.persist.write_bytes("s3://bkt/obj.bin", b"payload")
    assert _FlakyStore.store["/bkt/obj.bin"] == b"payload"
    assert len(_FlakyStore.requests) == 3      # 2 failures + 1 success


def test_persist_survives_injected_503_burst(flaky_store):
    """The harness path: the 503s come from the fault layer (no server
    cooperation needed) and the write still lands."""
    with faults.inject("persist.http:http_503*2"):
        h2o.persist.write_bytes("s3://bkt/inj.bin", b"x" * 64)
    assert _FlakyStore.store["/bkt/inj.bin"] == b"x" * 64
    # the two injected failures never reached the wire
    assert len(_FlakyStore.requests) == 1


def test_persist_fails_without_retry_layer(flaky_store, monkeypatch):
    """Negative control: the SAME fault breaks the save when retries
    are disabled — proving the harness exercises the retry path."""
    monkeypatch.setenv("H2O_TPU_RETRY_DISABLE", "1")
    with faults.inject("persist.http:http_503*2"):
        with pytest.raises(IOError, match="503"):
            h2o.persist.write_bytes("s3://bkt/nope.bin", b"x")
    assert "/bkt/nope.bin" not in _FlakyStore.store


def test_persist_429_honors_retry_after(flaky_store):
    _FlakyStore.fail_codes = [429]
    t0 = time.monotonic()
    h2o.persist.write_bytes("s3://bkt/throttled.bin", b"y")
    assert _FlakyStore.store["/bkt/throttled.bin"] == b"y"
    assert time.monotonic() - t0 < 5.0         # waited ~0.01s, not minutes


def test_persist_survives_timeout_and_urlerror(flaky_store):
    with faults.inject("persist.http:timeout;persist.http:urlerror"):
        h2o.persist.write_bytes("s3://bkt/t.bin", b"z")
    assert _FlakyStore.store["/bkt/t.bin"] == b"z"


def test_persist_survives_truncated_transfer(flaky_store):
    with faults.inject("persist.http:truncate"):
        h2o.persist.write_bytes("s3://bkt/trunc.bin", b"w")
    assert _FlakyStore.store["/bkt/trunc.bin"] == b"w"


def test_404_read_is_file_not_found(flaky_store):
    with pytest.raises(FileNotFoundError):
        h2o.persist.read_bytes("s3://bkt/missing.bin")


def test_404_write_is_ioerror_not_file_not_found(flaky_store):
    """ISSUE satellite: a 404 on a WRITE (deleted upload session, stale
    WebHDFS redirect) is a broken write path, not a missing file — a
    FileNotFoundError here would make the AutoML manifest writer treat
    a failed checkpoint save as 'fresh run' and clobber state."""
    _FlakyStore.put_404 = True
    with pytest.raises(IOError) as ei:
        h2o.persist.write_bytes("s3://bkt/w.bin", b"v")
    assert not isinstance(ei.value, FileNotFoundError)
    assert "404" in str(ei.value)


def test_retries_visible_in_timeline(flaky_store):
    from h2o_kubernetes_tpu.diagnostics import timeline

    with faults.inject("persist.http:http_503"):
        h2o.persist.write_bytes("s3://bkt/tl.bin", b"t")
    kinds = [e["kind"] for e in timeline.events()]
    assert "fault_injected" in kinds and "retry" in kinds


# -- heartbeat probe under faults --------------------------------------------

def _probe_threads():
    return [t for t in threading.enumerate()
            if t.name == "h2o-tpu-probe" and t.is_alive()]


def test_probe_hang_detected_and_no_thread_leak(mesh8):
    """ISSUE satellite: a wedged probe must (a) trip unhealthy at the
    deadline, (b) NOT leak one more hung daemon thread per heartbeat
    call while the previous probe is still in flight."""
    for t in _probe_threads():           # drain strays from other tests
        t.join(timeout=5)
    with faults.inject("health.probe:hang~0.7"):
        assert health.heartbeat(timeout=0.1) is False
        assert not health.healthy()
        n0 = len(_probe_threads())
        assert n0 == 1
        # the hung probe is still alive: further heartbeats must skip
        # spawning, log, and return False — not stack up threads
        assert health.heartbeat(timeout=0.1) is False
        assert health.heartbeat(timeout=0.1) is False
        assert len(_probe_threads()) == 1
    # restart semantics: once the wedged probe drains and health is
    # reset, heartbeats succeed again
    deadline = time.monotonic() + 10
    while _probe_threads() and time.monotonic() < deadline:
        time.sleep(0.05)
    assert not _probe_threads()
    health.reset()
    assert health.heartbeat(timeout=120.0) is True


def test_probe_error_trips_unhealthy(mesh8):
    with faults.inject("health.probe:error"):
        assert health.heartbeat(timeout=30.0) is False
    assert not health.healthy()
    with pytest.raises(health.ClusterHealthError):
        health.require_healthy()


# -- device errors escaping a training step ----------------------------------

def _frame(n=200, seed=7):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=n).astype(np.float32)
    y = np.where(x + rng.normal(scale=0.4, size=n) > 0, "p", "n")
    return h2o.Frame.from_arrays({"x": x, "y": y})


def test_device_error_mid_train_then_restart(mesh8):
    """Acceptance: GBM train dies on an injected device error at a
    chunk boundary, the cloud locks, a retry without restart fails
    fast, and after reset() (the restart analog) training succeeds."""
    from h2o_kubernetes_tpu.models import GBM

    fr = _frame()
    # skip the resolve_xy guard; fire at the boost-loop chunk boundary
    with faults.inject("train.step:device_error@1"):
        with pytest.raises(faults.InjectedDeviceError):
            GBM(ntrees=4, max_depth=2, seed=0).train(
                y="y", training_frame=fr)
    assert not health.healthy()
    # locked cloud: retrying WITHOUT a restart fails fast, cleanly
    with pytest.raises(health.ClusterHealthError):
        GBM(ntrees=4, max_depth=2, seed=0).train(y="y", training_frame=fr)
    # restart → train to completion
    health.reset()
    m = GBM(ntrees=4, max_depth=2, seed=0).train(y="y", training_frame=fr)
    assert np.isfinite(m.predict_raw(fr)).all()


def test_doall_device_error_marks_unhealthy(mesh8):
    import jax.numpy as jnp

    from h2o_kubernetes_tpu.runtime.mrtask import doall

    with faults.inject("mrtask.doall:device_error"):
        with pytest.raises(faults.InjectedDeviceError):
            doall(lambda x: {"s": jnp.sum(x)}, jnp.ones(16), reduce="sum")
    assert not health.healthy()
    health.reset()
    out = doall(lambda x: {"s": jnp.sum(x)}, jnp.ones(16), reduce="sum")
    assert float(out["s"]) == 16.0


def test_predict_on_dead_mesh_is_cluster_error(mesh8):
    from h2o_kubernetes_tpu.models import GBM

    fr = _frame()
    m = GBM(ntrees=3, max_depth=2, seed=0).train(y="y", training_frame=fr)
    health.mark_unhealthy("simulated chip loss")
    with pytest.raises(health.ClusterHealthError):
        m.predict(fr)
    health.reset()
    assert m.predict(fr).nrows == fr.nrows


# -- AutoML checkpoint-restart round trip ------------------------------------

def _aml_kwargs(tmp_path=None):
    kw = dict(max_models=2, nfolds=2, seed=11, verbosity=None,
              include_algos=["glm", "deeplearning"],
              project_name="chaos_resume")
    if tmp_path is not None:
        kw["checkpoint_dir"] = str(tmp_path)
    return kw


def test_automl_resume_after_mid_run_device_error(mesh8, tmp_path):
    """ISSUE satellite + acceptance: inject a device error during step
    2 of an AutoML run with a checkpoint_dir; the job fails with the
    locked-cloud error; the manifest holds the completed step; after
    restart the rerun resumes (no retrain of step 1) and its
    leaderboard matches an uninterrupted run."""
    fr = _frame(n=160, seed=12)

    # reference run, no interruptions, no checkpointing
    ref = h2o.AutoML(**_aml_kwargs())
    ref.train(y="y", training_frame=fr)
    ref_rows = {r["model_id"]: r for r in ref.leaderboard.rows}
    assert len(ref_rows) >= 2

    # run 1: step 2 (DeepLearning) hits a device error mid-plan
    a1 = h2o.AutoML(**_aml_kwargs(tmp_path))
    with faults.inject("automl.step:device_error@1"):
        with pytest.raises(health.ClusterHealthError,
                           match="restart and rerun"):
            a1.train(y="y", training_frame=fr)
    assert a1.job.status == "FAILED"
    assert not health.healthy()
    manifest = json.loads((tmp_path / "automl_manifest.json").read_text())
    assert len(manifest) == 1                   # exactly the finished step
    done_id = next(iter(manifest))
    assert "GLM" in done_id

    # restart: reset health (new cluster), rerun with the same dir
    health.reset()
    a2 = h2o.AutoML(**_aml_kwargs(tmp_path))
    a2.train(y="y", training_frame=fr)
    resumed = [m for _, m in a2.event_log if "resumed from checkpoint" in m]
    assert resumed and done_id in resumed[0]
    got_rows = {r["model_id"]: r for r in a2.leaderboard.rows}
    assert set(got_rows) == set(ref_rows)
    metric = a2.leaderboard.sort_metric
    for mid in ref_rows:
        np.testing.assert_allclose(got_rows[mid][metric],
                                   ref_rows[mid][metric], rtol=1e-5,
                                   err_msg=f"{mid} {metric} diverged "
                                   "between resumed and uninterrupted run")
    # resumed leader predicts
    assert a2.leader.predict(fr).nrows == fr.nrows


def test_automl_escalates_real_device_error(mesh8, monkeypatch):
    """A REAL XLA runtime error (not the harness's InjectedDeviceError,
    which flips health itself) escaping a training step must also lock
    the cloud and fail the job — not get logged as a step failure while
    the plan grinds on against a dead mesh."""
    from h2o_kubernetes_tpu import automl as automl_mod
    from h2o_kubernetes_tpu.runtime.health import is_device_error

    try:
        from jax.errors import JaxRuntimeError as XErr
    except ImportError:
        from jaxlib.xla_extension import XlaRuntimeError as XErr
    err = XErr("INTERNAL: device halted (test)")
    assert is_device_error(err)
    fr = _frame(120)

    class Dying(automl_mod._EST["glm"]):
        def train(self, *a, **kw):
            raise err

    monkeypatch.setitem(automl_mod._EST, "glm", Dying)
    a = h2o.AutoML(max_models=2, nfolds=2, include_algos=["glm", "gbm"],
                   verbosity=None, project_name="realdev_t")
    with pytest.raises(health.ClusterHealthError, match="restart and"):
        a.train(y="y", training_frame=fr)
    assert a.job.status == "FAILED"
    assert not health.healthy()


# -- REST graceful degradation -----------------------------------------------

def _free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.fixture
def rest_server(mesh8):
    from h2o_kubernetes_tpu import rest

    port = _free_port()
    srv = rest.start_server(port)
    yield f"http://127.0.0.1:{port}"
    srv.shutdown()
    rest.FRAMES.clear()
    rest.MODELS.clear()
    rest.AUTOML.clear()
    rest.GRIDS.clear()


def test_rest_degrades_to_503_when_unhealthy(rest_server, tmp_path):
    fr = _frame(120)
    csv = tmp_path / "t.csv"
    h2o.export_file(fr, str(csv))
    health.mark_unhealthy("ICI link down (drill)")
    # builds degrade to 503 carrying the health error, not 500/hang
    body = json.dumps({"training_frame": "t", "response_column": "y"})
    req = urllib.request.Request(
        rest_server + "/3/ModelBuilders/gbm", data=body.encode(),
        method="POST", headers={"Content-Type": "application/json"})
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(req, timeout=60)
    assert ei.value.code == 503
    payload = json.loads(ei.value.read())
    assert "ICI link down" in payload["msg"]
    # reads stay served: /3/Cloud reports the unhealthy cloud
    with urllib.request.urlopen(rest_server + "/3/Cloud",
                                timeout=60) as r:
        assert json.loads(r.read())["cloud_healthy"] is False
    # restart → builds work again
    health.reset()
    with urllib.request.urlopen(rest_server + "/3/Cloud",
                                timeout=60) as r:
        assert json.loads(r.read())["cloud_healthy"] is True


def test_rest_job_records_failure_not_running_forever(rest_server,
                                                      tmp_path):
    """A device error during a REST-driven build must land on the Job
    (FAILED + message), and the cluster then degrades to 503 — the job
    must never be left RUNNING for /3/Jobs pollers."""
    from h2o_kubernetes_tpu import rest

    fr = _frame(150, seed=3)
    csv = tmp_path / "train.csv"
    h2o.export_file(fr, str(csv))
    import urllib.parse

    data = urllib.parse.urlencode(
        {"path": str(csv), "destination_frame": "train"}).encode()
    urllib.request.urlopen(
        urllib.request.Request(rest_server + "/3/ImportFiles",
                               data=data, method="POST"),
        timeout=120).read()
    with faults.inject("train.step:device_error"):
        body = json.dumps({"training_frame": "train",
                           "response_column": "y", "ntrees": 3,
                           "max_depth": 2, "model_id": "doomed"})
        req = urllib.request.Request(
            rest_server + "/3/ModelBuilders/gbm", data=body.encode(),
            method="POST", headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=300) as r:
            out = json.loads(r.read())
    assert out["job"]["status"] == "FAILED"
    assert "injected device error" in out["job"]["msg"]
    # the failed dispatch locked the cloud: the next build 503s
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(req, timeout=60)
    assert ei.value.code == 503
    jobs = json.loads(urllib.request.urlopen(
        rest_server + "/3/Jobs", timeout=60).read())["jobs"]
    doomed = [j for j in jobs if j["dest"] == "doomed"]
    assert doomed and doomed[0]["status"] == "FAILED"
    health.reset()
