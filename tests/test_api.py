"""Client-API parity tests: estimator aliases, jobs, timeline,
diagnostics (SURVEY.md §2b C9/C19, §5.1/§5.5)."""

import numpy as np

import h2o_kubernetes_tpu as h2o


def _frame(n=200, seed=31):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=n).astype(np.float32)
    y = np.where(x + rng.normal(scale=0.4, size=n) > 0, "a", "b")
    return h2o.Frame.from_arrays({"x": x, "y": y})


def test_estimator_aliases(mesh8):
    from h2o_kubernetes_tpu.estimators import (
        H2OGradientBoostingEstimator, H2OGeneralizedLinearEstimator)

    fr = _frame()
    m = H2OGradientBoostingEstimator(ntrees=3, max_depth=3).train(
        y="y", training_frame=fr)
    assert m.algo == "gbm"
    g = H2OGeneralizedLinearEstimator(family="binomial").train(
        y="y", training_frame=fr)
    assert g.algo == "glm"


def test_jobs_and_timeline(mesh8):
    fr = _frame()
    h2o.timeline.clear()
    am = h2o.AutoML(max_models=1, nfolds=2, seed=0,
                    include_algos=["glm"], verbosity=None,
                    project_name="jobs_test")
    am.train(y="y", training_frame=fr)
    js = h2o.jobs()
    mine = [j for j in js if j["dest"] == "jobs_test"]
    assert mine and mine[0]["status"] == "DONE"
    kinds = {e["kind"] for e in h2o.timeline.events()}
    assert {"job_start", "job_done"} <= kinds


def test_device_memory_and_cluster_status(mesh8):
    st = h2o.cluster_status()
    assert st["cloud_size"] == 8
    dm = h2o.device_memory()
    assert len(dm) >= 1 and "device" in dm[0]


def test_log_levels():
    h2o.log.setLevel("INFO")
    h2o.log.info("hello from tests")
    h2o.log.setLevel("WARNING")
