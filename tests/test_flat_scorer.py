"""Compiled serving fast path (ISSUE 2 tentpole): the flattened-tree
scorer must match the binned heap re-descent BITWISE across the parity
matrix (NAs, categoricals incl. grouped high-cardinality bins, weights,
offset, multinomial/DRF, laplace margin scaling), the jitted-scorer
cache must be zero-retrace warm, and MOJO export must reuse the SAME
flattened arrays (one flattening code path)."""

import io

import numpy as np
import pytest

import h2o_kubernetes_tpu as h2o
from h2o_kubernetes_tpu.models import DRF, GBM, GLM, DeepLearning, XGBoost
from h2o_kubernetes_tpu.models.base import scorer_cache_stats
from h2o_kubernetes_tpu.mojo import MojoModel, export_mojo


def _rich_frame(n=1200, seed=7, nlevels=100):
    """Numeric-with-NA + low-card enum + HIGH-card enum (grouped code
    ranges at nbins=64) + weights + offset + binary response."""
    rng = np.random.default_rng(seed)
    x0 = rng.normal(size=n).astype(np.float32)
    x0[::17] = np.nan
    x1 = rng.exponential(2.0, size=n).astype(np.float32)
    g = np.array([f"L{i}" for i in range(nlevels)])[
        rng.integers(0, nlevels, n)]
    c = np.array(["a", "b", "c"])[rng.integers(0, 3, n)]
    w = rng.uniform(0.5, 2.0, n).astype(np.float32)
    off = rng.normal(scale=0.1, size=n).astype(np.float32)
    y = np.where(np.nan_to_num(x0) + (c == "a")
                 + rng.normal(scale=0.5, size=n) > 0, "p", "n")
    return h2o.Frame.from_arrays(
        {"x0": x0, "x1": x1, "g": g, "c": c, "w": w, "off": off, "y": y})


def _assert_bitwise(model, frame, offset_col=None):
    X = model._design_matrix(frame)
    off = frame.vec(offset_col).as_float() if offset_col else None
    a = np.asarray(model._margins(X, off) if off is not None
                   else model._margins(X))
    b = np.asarray(model._margins_binned(X, off) if off is not None
                   else model._margins_binned(X))
    assert a.dtype == b.dtype and a.shape == b.shape
    assert np.array_equal(a, b), \
        f"flat scorer diverged: max |d| = {np.abs(a - b).max()}"


def test_flat_parity_binomial_weights_offset_highcard(mesh8):
    fr = _rich_frame()
    m = GBM(ntrees=8, max_depth=4, nbins=64, seed=1).train(
        y="y", training_frame=fr, weights_column="w",
        offset_column="off")
    _assert_bitwise(m, fr, offset_col="off")
    # scoring-frame domain remap path too (fresh frame, same data)
    _assert_bitwise(m, _rich_frame(seed=7), offset_col="off")


def test_flat_parity_gaussian_and_laplace(mesh8):
    rng = np.random.default_rng(3)
    n = 800
    x = rng.normal(size=n).astype(np.float32)
    x[::11] = np.nan
    y = 2.0 * np.nan_to_num(x) + rng.normal(scale=0.3, size=n)
    fr = h2o.Frame.from_arrays(
        {"x": x, "y": y.astype(np.float32)})
    for dist in ("gaussian", "laplace"):
        m = GBM(ntrees=6, max_depth=3, distribution=dist, seed=2).train(
            y="y", training_frame=fr)
        _assert_bitwise(m, fr)   # laplace: margin_scale != 1 path


def test_flat_parity_drf_multinomial(mesh8):
    rng = np.random.default_rng(5)
    n = 900
    x = rng.normal(size=n).astype(np.float32)
    c = np.array(["u", "v"])[rng.integers(0, 2, n)]
    y = np.where(x > 0.5, "A", np.where(x < -0.5, "B", "C"))
    fr = h2o.Frame.from_arrays({"x": x, "c": c, "y": y})
    m = DRF(ntrees=6, max_depth=4, nbins=32, seed=4).train(
        y="y", training_frame=fr)
    _assert_bitwise(m, fr)
    # GBM multinomial (boosted K-interleaved trees)
    m2 = GBM(ntrees=4, max_depth=3, seed=4).train(
        y="y", training_frame=fr)
    _assert_bitwise(m2, fr)


def test_flat_parity_xgboost(mesh8):
    rng = np.random.default_rng(9)
    n = 600
    x0 = rng.normal(size=n).astype(np.float32)
    x1 = rng.normal(size=n).astype(np.float32)
    y = (x0 - x1 + rng.normal(scale=0.4, size=n)).astype(np.float32)
    fr = h2o.Frame.from_arrays({"x0": x0, "x1": x1, "y": y})
    m = XGBoost(ntrees=5, max_depth=4, seed=1).train(
        y="y", training_frame=fr)
    _assert_bitwise(m, fr)


def test_score_numpy_matches_predict_and_is_warm(mesh8):
    fr = _rich_frame(n=700, seed=11)
    m = GBM(ntrees=5, max_depth=3, nbins=64, seed=1).train(
        y="y", training_frame=fr, offset_column="off")
    pr = m.predict_raw(fr)
    X = np.asarray(m._design_matrix(fr))[: fr.nrows]
    off = np.asarray(fr.vec("off").as_float())[: fr.nrows]
    got = m.score_numpy(X, offset=off)
    assert np.array_equal(got, pr)
    # warm repeat: zero new cache misses (miss == new XLA trace key)
    s0 = scorer_cache_stats()
    m.score_numpy(X, offset=off)
    s1 = scorer_cache_stats()
    assert s1["misses"] == s0["misses"]
    assert s1["hits"] == s0["hits"] + 1
    # any batch inside the same power-of-two bucket: still zero miss
    m.score_numpy(X[:100], offset=off[:100])
    m.score_numpy(X[:90], offset=off[:90])
    s2 = scorer_cache_stats()
    assert s2["misses"] == s1["misses"] + 1   # first 128-bucket compile
    assert s2["hits"] == s1["hits"] + 1


def test_score_numpy_validation(mesh8):
    rng = np.random.default_rng(1)
    fr = h2o.Frame.from_arrays(
        {"x": rng.normal(size=300).astype(np.float32),
         "y": rng.normal(size=300).astype(np.float32)})
    m = GBM(ntrees=3, max_depth=2, seed=0).train(
        y="y", training_frame=fr)
    with pytest.raises(ValueError, match="expects"):
        m.score_numpy(np.zeros((5, 3), np.float32))
    with pytest.raises(ValueError, match="empty"):
        m.score_numpy(np.zeros((0, 1), np.float32))


def test_score_numpy_glm_deeplearning(mesh8):
    """GLM and DeepLearning ride the same jitted-scorer cache."""
    rng = np.random.default_rng(2)
    n = 500
    x = rng.normal(size=n).astype(np.float32)
    c = np.array(["a", "b", "c"])[rng.integers(0, 3, n)]
    y = np.where(x + (c == "a") + rng.normal(scale=0.5, size=n) > 0,
                 "p", "n")
    fr = h2o.Frame.from_arrays({"x": x, "c": c, "y": y})
    for est in (GLM(family="binomial"),
                DeepLearning(hidden=[8], epochs=1, seed=1)):
        m = est.train(y="y", training_frame=fr)
        assert m._serving_jit
        pr = m.predict_raw(fr)
        X = np.asarray(m._design_matrix(fr))[: fr.nrows]
        got = m.score_numpy(X)
        np.testing.assert_allclose(got, pr, rtol=1e-6, atol=1e-7)


def test_mojo_shares_flattening(tmp_path, mesh8):
    """MOJO export serializes the SAME flat arrays the serving scorer
    descends — one flattening code path, no edges, no re-binning."""
    fr = _rich_frame(n=600, seed=13)
    m = GBM(ntrees=6, max_depth=4, nbins=64, seed=3).train(
        y="y", training_frame=fr)
    buf = io.BytesIO()
    export_mojo(m, buf)
    buf.seek(0)
    mj = MojoModel(buf)
    flat = m._flat()
    for f in ("split_feat", "thresh", "left", "na_left", "value"):
        assert np.array_equal(mj.arrays[f"flat_{f}"],
                              np.asarray(getattr(flat, f))), f
    assert "edges" not in mj.arrays
    assert "tree_split_feat" not in mj.arrays
    got = mj.predict(fr)
    want = m.predict_raw(fr)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_flat_cache_survives_pickle(tmp_path, mesh8):
    from h2o_kubernetes_tpu.persist import load_model, save_model

    fr = _rich_frame(n=400, seed=17)
    m = GBM(ntrees=4, max_depth=3, nbins=64, seed=5).train(
        y="y", training_frame=fr)
    want = m.predict_raw(fr)       # populates _flat_trees + scorer
    p = str(tmp_path / "m.model")
    save_model(m, p)
    m2 = load_model(p)
    # derivable serving state is NOT pickled (rebuilt lazily): the
    # artifact must not depend on whether the model served first
    assert "_flat_trees" not in m2.__dict__
    assert "_scorer_cache" not in m2.__dict__
    assert np.array_equal(m2.predict_raw(fr), want)
