"""GLRM / CoxPH / Aggregator (SURVEY.md §2b C17 round-2 additions).

Oracles: GLRM with quadratic loss vs sklearn TruncatedSVD (both solve
rank-k least squares on complete data); CoxPH coefficient recovery on
simulated exponential survival data + a hand-checkable no-ties case;
Aggregator invariants (coverage, counts, target tolerance).
"""

import numpy as np
import pytest

import h2o_kubernetes_tpu as h2o
from h2o_kubernetes_tpu.models import GLRM, Aggregator, CoxPH


# -- GLRM --------------------------------------------------------------------

def _lowrank_frame(n=400, d=6, k=2, seed=0, na_frac=0.0):
    rng = np.random.default_rng(seed)
    U = rng.normal(size=(n, k))
    V = rng.normal(size=(d, k))
    X = (U @ V.T + 0.05 * rng.normal(size=(n, d))).astype(np.float32)
    if na_frac:
        mask = rng.random(X.shape) < na_frac
        X = X.copy()
        X[mask] = np.nan
    return h2o.Frame.from_arrays({f"c{i}": X[:, i] for i in range(d)}), X


def test_glrm_matches_svd_reconstruction(mesh8):
    fr, X = _lowrank_frame()
    m = GLRM(k=2, transform="DEMEAN", max_iterations=500, seed=1).train(
        training_frame=fr)
    rec = m.reconstruct(fr)
    Xc = X - X.mean(axis=0)
    got = np.stack([rec[f"reconstr_c{i}"].to_numpy()
                    for i in range(X.shape[1])], axis=1)
    glrm_mse = float(np.mean((got - Xc) ** 2))
    from sklearn.decomposition import TruncatedSVD

    svd = TruncatedSVD(n_components=2, random_state=0).fit(Xc)
    svd_mse = float(np.mean(
        (svd.inverse_transform(svd.transform(Xc)) - Xc) ** 2))
    # alternating minimization should land near the SVD optimum
    assert glrm_mse < svd_mse * 1.25 + 1e-4, (glrm_mse, svd_mse)
    assert m.archetypes().shape == (2, X.shape[1])
    assert m.x_frame().shape == (fr.nrows, 2)


def test_glrm_missing_cells_imputed(mesh8):
    fr, X = _lowrank_frame(na_frac=0.15, seed=3)
    m = GLRM(k=2, transform="NONE", max_iterations=500, seed=1).train(
        training_frame=fr)
    # objective only counts observed cells; reconstruction must still
    # correlate with the (unseen) complete structure
    _, Xfull = _lowrank_frame(na_frac=0.0, seed=3)
    rec = m.reconstruct(fr)
    got = np.stack([rec[f"reconstr_c{i}"].to_numpy()
                    for i in range(X.shape[1])], axis=1)
    miss = np.isnan(X)
    assert miss.sum() > 100
    corr = np.corrcoef(got[miss], Xfull[miss])[0, 1]
    assert corr > 0.9, corr


def test_glrm_non_negative_regularizer(mesh8):
    rng = np.random.default_rng(5)
    X = rng.random((200, 4)).astype(np.float32)      # non-negative data
    fr = h2o.Frame.from_arrays({f"c{i}": X[:, i] for i in range(4)})
    m = GLRM(k=2, transform="NONE", regularization_x="non_negative",
             regularization_y="non_negative", max_iterations=300).train(
        training_frame=fr)
    assert np.all(np.asarray(m.U) >= 0)
    assert np.all(np.asarray(m.V) >= 0)


# -- CoxPH -------------------------------------------------------------------

def _survival_frame(n=3000, beta=(0.8, -0.5), censor_rate=0.3, seed=7):
    rng = np.random.default_rng(seed)
    X = rng.normal(size=(n, len(beta)))
    lam = np.exp(X @ np.asarray(beta))
    t_event = rng.exponential(1.0 / lam)
    t_cens = rng.exponential(1.0 / (censor_rate * lam.mean()))
    t = np.minimum(t_event, t_cens)
    e = (t_event <= t_cens).astype(np.float64)
    fr = h2o.Frame.from_arrays({
        "x0": X[:, 0].astype(np.float32),
        "x1": X[:, 1].astype(np.float32),
        "stop": t.astype(np.float32), "event": e})
    return fr, X, t, e


def test_coxph_recovers_coefficients(mesh8):
    fr, X, t, e = _survival_frame()
    m = CoxPH(stop_column="stop", event_column="event").train(
        training_frame=fr)
    coef = m.coef()
    np.testing.assert_allclose(coef["x0"], 0.8, atol=0.1)
    np.testing.assert_allclose(coef["x1"], -0.5, atol=0.1)
    assert m.loglik > m.loglik_null       # fitted beats null
    assert m.concordance(fr) > 0.6
    hr = m.hazard_ratios()
    np.testing.assert_allclose(hr["x0"], np.exp(coef["x0"]), rtol=1e-6)


def test_coxph_hand_checked_no_ties(mesh8):
    # 3 subjects, times 1<2<3, all events, covariate x=[0,1,0]: the
    # partial likelihood -log(e^b+2) + b - log(e^b+1) has the closed-
    # form maximizer e^b = sqrt(2) (set the score to zero) — a finite,
    # hand-derivable optimum
    fr = h2o.Frame.from_arrays({
        "x": np.array([0.0, 1.0, 0.0], dtype=np.float32),
        "stop": np.array([1.0, 2.0, 3.0], dtype=np.float32),
        "event": np.array([1.0, 1.0, 1.0], dtype=np.float32)})
    m = CoxPH(stop_column="stop", event_column="event",
              max_iterations=50).train(training_frame=fr)
    np.testing.assert_allclose(m.coef()["x"], np.log(np.sqrt(2.0)),
                               atol=2e-2)


def test_coxph_breslow_close_to_efron_few_ties(mesh8):
    fr, *_ = _survival_frame(n=800, seed=11)
    me = CoxPH(stop_column="stop", event_column="event",
               ties="efron").train(training_frame=fr)
    mb = CoxPH(stop_column="stop", event_column="event",
               ties="breslow").train(training_frame=fr)
    # continuous times → almost no ties → the two agree closely
    np.testing.assert_allclose(me.coef()["x0"], mb.coef()["x0"],
                               rtol=2e-2)


def test_coxph_requires_columns(mesh8):
    fr = h2o.Frame.from_arrays({"x": np.arange(5.0)})
    with pytest.raises(ValueError):
        CoxPH().train(training_frame=fr)


# -- Aggregator --------------------------------------------------------------

def test_aggregator_reduces_to_target(mesh8):
    rng = np.random.default_rng(13)
    n = 3000
    X = np.concatenate([rng.normal(loc=c, scale=0.3, size=(n // 3, 2))
                        for c in (-3, 0, 3)]).astype(np.float32)
    fr = h2o.Frame.from_arrays({"a": X[:, 0], "b": X[:, 1]})
    m = Aggregator(target_num_exemplars=50).train(training_frame=fr)
    agg = m.aggregated_frame
    assert "counts" in agg.names
    counts = agg["counts"].to_numpy()
    assert counts.sum() == n              # every row accounted for
    # within the rel_tol band around the target
    assert 25 <= m.num_exemplars() <= 75, m.num_exemplars()
    # exemplars span all three clusters
    a = agg["a"].to_numpy()
    assert (a < -1.5).any() and (np.abs(a) < 1.5).any() and \
        (a > 1.5).any()
