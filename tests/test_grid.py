"""GridSearch (H2OGridSearch analog) tests — SURVEY.md §2b C16/C19."""

import numpy as np
import pytest

import h2o_kubernetes_tpu as h2o
from h2o_kubernetes_tpu import GridSearch
from h2o_kubernetes_tpu.models import GBM, GLM

# long-running tier: deselect locally with -m 'not slow'
pytestmark = pytest.mark.slow


@pytest.fixture(scope="module")
def binom_frame():
    rng = np.random.default_rng(7)
    n = 600
    x0 = rng.normal(size=n).astype(np.float32)
    x1 = rng.normal(size=n).astype(np.float32)
    logit = 1.5 * x0 - x1 + rng.normal(scale=0.3, size=n)
    return h2o.Frame.from_arrays({
        "x0": x0, "x1": x1,
        "y": np.where(logit > 0, "yes", "no")})


def test_cartesian_walks_full_product(binom_frame):
    grid = GridSearch(GBM, {"ntrees": [3, 5], "max_depth": [2, 3]})
    grid.train(y="y", training_frame=binom_frame)
    assert len(grid.model_ids) == 4
    # every hyper combo appears exactly once
    combos = {(m.grid_params["ntrees"], m.grid_params["max_depth"])
              for m in grid.models}
    assert combos == {(3, 2), (3, 3), (5, 2), (5, 3)}


def test_models_ranked_by_metric(binom_frame):
    grid = GridSearch(GBM, {"ntrees": [2, 10]})
    grid.train(y="y", training_frame=binom_frame)
    rows = grid.get_grid()
    assert grid.sort_metric == "auc"
    aucs = [r["auc"] for r in rows]
    assert aucs == sorted(aucs, reverse=True)
    assert grid.leader is grid.models[0]


def test_random_discrete_respects_max_models(binom_frame):
    grid = GridSearch(
        GBM, {"ntrees": [2, 3, 4], "max_depth": [2, 3], "learn_rate":
              [0.1, 0.3]},
        search_criteria={"strategy": "RandomDiscrete", "max_models": 3,
                         "seed": 42})
    grid.train(y="y", training_frame=binom_frame)
    assert len(grid.model_ids) == 3
    # draws are distinct
    seen = [tuple(sorted(m.grid_params.items())) for m in grid.models]
    assert len(set(seen)) == 3


def test_random_discrete_deterministic_seed(binom_frame):
    def run():
        g = GridSearch(GBM, {"ntrees": [2, 3, 4, 5]},
                       search_criteria={"strategy": "RandomDiscrete",
                                        "max_models": 2, "seed": 9})
        g.train(y="y", training_frame=binom_frame)
        return sorted(m.grid_params["ntrees"] for m in g.models)

    assert run() == run()


def test_base_params_from_instance(binom_frame):
    base = GBM(learn_rate=0.4, seed=5)
    grid = GridSearch(base, {"ntrees": [2, 3]})
    grid.train(y="y", training_frame=binom_frame)
    assert all(m.params.learn_rate == 0.4 for m in grid.models)
    assert all(m.params.seed == 5 for m in grid.models)


def test_failed_combo_recorded_not_fatal(binom_frame):
    grid = GridSearch(GBM, {"ntrees": [-1, 3]})   # -1 invalid
    grid.train(y="y", training_frame=binom_frame)
    assert len(grid.model_ids) == 1
    assert len(grid.failed_params) == 1
    assert grid.failed_params[0]["ntrees"] == -1


def test_grid_with_validation_frame_and_glm(binom_frame):
    rng = np.random.default_rng(11)
    n = 300
    x0 = rng.normal(size=n).astype(np.float32)
    x1 = rng.normal(size=n).astype(np.float32)
    logit = 1.5 * x0 - x1
    valid = h2o.Frame.from_arrays({
        "x0": x0, "x1": x1,
        "y": np.where(logit > 0, "yes", "no")})
    grid = GridSearch(GLM(family="binomial"), {"alpha": [0.0, 0.5]},
                      search_criteria={"strategy": "Cartesian"})
    grid.train(y="y", training_frame=binom_frame,
               validation_frame=valid)
    assert len(grid.model_ids) == 2
    assert all("auc" in r for r in grid.get_grid())


def test_get_grid_sort_by_override(binom_frame):
    grid = GridSearch(GBM, {"ntrees": [2, 8]})
    grid.train(y="y", training_frame=binom_frame)
    rows = grid.get_grid(sort_by="logloss")
    lls = [r["logloss"] for r in rows]
    assert lls == sorted(lls)


def test_unknown_strategy_rejected():
    with pytest.raises(ValueError, match="strategy"):
        GridSearch(GBM, {"ntrees": [1]},
                   search_criteria={"strategy": "Bayesian"})


def test_empty_hyper_params_rejected():
    with pytest.raises(ValueError, match="hyper_params"):
        GridSearch(GBM, {})


def test_grid_registers_job(binom_frame):
    from h2o_kubernetes_tpu.automl import JOBS

    grid = GridSearch(GBM, {"ntrees": [2]}, grid_id="grid_job_test")
    grid.train(y="y", training_frame=binom_frame)
    assert JOBS["grid_job_test"].status == "DONE"


def test_instance_cv_args_carried_into_grid(binom_frame):
    grid = GridSearch(GBM(ntrees=3, nfolds=3), {"max_depth": [2, 3]})
    grid.train(y="y", training_frame=binom_frame)
    assert len(grid.models) == 2
    # grid models must actually cross-validate (ranking uses CV metrics)
    assert all(m.cv is not None for m in grid.models)


def test_bad_response_column_recorded_not_fatal(binom_frame):
    """A missing y fails every combo (inside the per-combo try), so the
    grid finishes DONE with zero models and the errors recorded."""
    from h2o_kubernetes_tpu.automl import JOBS

    grid = GridSearch(GBM, {"ntrees": [2]}, grid_id="grid_bad_y_test")
    grid.train(y="no_such_column", training_frame=binom_frame)
    assert grid.model_ids == []
    assert len(grid.failed_params) == 1
    assert JOBS["grid_bad_y_test"].status == "DONE"


def test_job_failed_on_grid_crash(binom_frame):
    """A BaseException (user interrupt) escapes the per-combo guard and
    must mark the Job FAILED instead of leaving it RUNNING forever."""
    from h2o_kubernetes_tpu.automl import JOBS

    class Interrupting:
        def __init__(self, **kw):
            pass

        def train(self, **kw):
            raise KeyboardInterrupt

    grid = GridSearch(Interrupting, {"ntrees": [2]},
                      grid_id="grid_crash_test")
    with pytest.raises(KeyboardInterrupt):
        grid.train(y="y", training_frame=binom_frame)
    assert JOBS["grid_crash_test"].status == "FAILED"
