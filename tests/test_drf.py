import numpy as np
import pytest

from h2o_kubernetes_tpu import Frame
from h2o_kubernetes_tpu import metrics as M
from h2o_kubernetes_tpu.models import DRF


def test_drf_binary(mesh8):
    rng = np.random.default_rng(0)
    n = 4000
    x1 = rng.normal(size=n)
    x2 = rng.normal(size=n)
    y = ((1.2 * x1 - 0.8 * x2 + rng.normal(scale=0.4, size=n)) > 0).astype(int)
    fr = Frame.from_arrays({"x1": x1, "x2": x2,
                            "y": np.array(["n", "p"])[y]})
    m = DRF(ntrees=30, max_depth=8, seed=1).train(y="y", training_frame=fr)
    perf = m.model_performance(fr, "y")
    assert perf["auc"] > 0.95

    from sklearn.ensemble import RandomForestClassifier
    sk = RandomForestClassifier(n_estimators=30, max_depth=8,
                                random_state=0).fit(
        np.stack([x1, x2], 1), y)
    sk_auc = M.roc_auc(y, sk.predict_proba(np.stack([x1, x2], 1))[:, 1])
    assert perf["auc"] > sk_auc - 0.035  # parity band vs sklearn RF


@pytest.mark.slow
def test_drf_regression(mesh8):
    rng = np.random.default_rng(2)
    n = 3000
    x1 = rng.normal(size=n)
    x2 = rng.uniform(-2, 2, size=n)
    y = 2.0 * x1 + x2 ** 2 + rng.normal(scale=0.2, size=n)
    fr = Frame.from_arrays({"x1": x1, "x2": x2, "y": y})
    m = DRF(ntrees=40, max_depth=10, seed=3).train(y="y", training_frame=fr)
    perf = m.model_performance(fr, "y")
    assert perf["r2"] > 0.85


@pytest.mark.slow
def test_drf_multiclass_probs_sum_to_one(mesh8):
    rng = np.random.default_rng(4)
    n = 2000
    x1 = rng.normal(size=n)
    x2 = rng.normal(size=n)
    cls = np.where(x1 > 0.5, 2, np.where(x2 > 0, 1, 0))
    fr = Frame.from_arrays({"x1": x1, "x2": x2,
                            "y": np.array(["a", "b", "c"])[cls]})
    m = DRF(ntrees=20, max_depth=6, seed=5).train(y="y", training_frame=fr)
    out = m.predict_raw(fr)
    np.testing.assert_allclose(out.sum(axis=1), 1.0, atol=1e-5)
    assert m.model_performance(fr, "y")["accuracy"] > 0.9


def test_deep_tree_budget_validation(mesh8):
    """Depth past 12 trains when the level histograms fit the memory
    budget and fails with sizing guidance when they cannot — the
    reference reaches depth 20 via dynamic row partitions; the dense
    heap's answer is a validated budget (models/gbm.py)."""
    import pytest

    rng = np.random.default_rng(9)
    n = 4096
    cols = {f"x{i}": rng.normal(size=n).astype(np.float32)
            for i in range(4)}
    cols["y"] = np.where(cols["x0"] + 0.5 * cols["x1"] > 0, "p", "n")
    fr = Frame.from_arrays(cols)
    # depth 16, 4 features x 16 bins: ~25 MiB of level histograms —
    # must TRAIN, not error (depth itself is not capped)
    m = DRF(ntrees=2, max_depth=16, nbins=16, min_rows=1,
            seed=1).train(y="y", training_frame=fr)
    assert m.model_performance(fr, "y")["auc"] > 0.8
    # many features x 64 bins at depth 16 blows the budget: the error
    # must name the knobs (max_depth / nbins / budget)
    wide = {f"x{i}": rng.normal(size=256).astype(np.float32)
            for i in range(30)}
    wide["y"] = np.where(wide["x0"] > 0, "p", "n")
    fr_wide = Frame.from_arrays(wide)
    with pytest.raises(ValueError, match="max_depth.*nbins|nbins.*budget"):
        DRF(ntrees=1, max_depth=16, nbins=64, seed=1).train(
            y="y", training_frame=fr_wide)
