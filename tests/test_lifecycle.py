"""Lifecycle, overload control and circuit breaking (ISSUE 4).

The Kubernetes-grade serving envelope: STARTING→SERVING→DRAINING→
TERMINATED with a drain path that settles jobs and flushes the
micro-batcher; `/healthz` vs `/readyz` probe semantics; the bounded
admission queue's 429 load shedding; per-request deadlines rejected
before any dispatch; and the dispatch circuit breaker's
trip → open → half-open probe → closed round trip.
"""

import json
import socket
import threading
import time
import types
import urllib.error
import urllib.request

import numpy as np
import pytest

import h2o_kubernetes_tpu as h2o
from h2o_kubernetes_tpu import rest
from h2o_kubernetes_tpu.automl import JOBS, Job
from h2o_kubernetes_tpu.runtime import faults, health, lifecycle, retry

pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _fresh_state():
    faults.reset()
    health.reset()
    lifecycle.reset()
    rest.BATCHER.reset()
    yield
    faults.reset()
    health.reset()
    lifecycle.reset()
    rest.BATCHER.reset()


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.fixture
def server(mesh8):
    port = _free_port()
    srv = rest.start_server(port)
    yield f"http://127.0.0.1:{port}"
    srv.shutdown()
    rest.FRAMES.clear()
    rest.MODELS.clear()


@pytest.fixture
def gbm_server(server, mesh8):
    """Server + a small registered GBM for scoring-path tests."""
    rng = np.random.default_rng(5)
    x = rng.normal(size=200).astype(np.float32)
    y = np.where(x > 0, "p", "n")
    fr = h2o.Frame.from_arrays({"x": x, "y": y})
    from h2o_kubernetes_tpu.models import GBM

    rest.MODELS["lc_gbm"] = GBM(ntrees=3, max_depth=2, seed=0).train(
        y="y", training_frame=fr)
    yield server
    rest.MODELS.pop("lc_gbm", None)


def _get(base, path):
    """(status, body) without raising on 4xx/5xx."""
    try:
        with urllib.request.urlopen(base + path, timeout=30) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _score(base, headers=None, n=2):
    req = urllib.request.Request(
        base + "/3/Predictions/models/lc_gbm",
        data=json.dumps({"rows": [{"x": 0.3}] * n}).encode(),
        method="POST",
        headers={"Content-Type": "application/json", **(headers or {})})
    with urllib.request.urlopen(req, timeout=30) as r:
        return json.loads(r.read())


# -- circuit breaker ---------------------------------------------------------


def test_breaker_trip_halfopen_reset_round_trip(monkeypatch):
    monkeypatch.setenv("H2O_TPU_BREAKER_FAILURES", "2")
    monkeypatch.setenv("H2O_TPU_BREAKER_COOLDOWN", "0.15")
    b = lifecycle.CircuitBreaker("test")
    assert b.state() == "closed"
    b.record_failure("boom 1")
    assert b.state() == "closed"        # one failure is not a pattern
    b.record_failure("boom 2")
    assert b.state() == "open" and b.stats["trips"] == 1
    with pytest.raises(lifecycle.CircuitOpenError) as e:
        b.allow()
    assert e.value.retry_after > 0
    assert b.stats["short_circuited"] == 1
    # cooldown elapses -> half-open; ONE probe slot, the rest rejected
    time.sleep(0.2)
    assert b.state() == "half-open"
    b.allow()                           # claims the probe
    with pytest.raises(lifecycle.CircuitOpenError):
        b.allow()
    # failed probe -> back to open with a fresh cooldown
    b.record_failure("probe failed")
    assert b.state() == "open"
    time.sleep(0.2)
    b.allow()
    b.record_success()                  # probe succeeds -> closed
    assert b.state() == "closed"
    assert b.stats["closes"] == 1
    # a success resets the consecutive count entirely
    b.record_failure("x")
    b.record_success()
    b.record_failure("y")
    assert b.state() == "closed"


def test_breaker_probe_slot_released_on_non_device_error(monkeypatch):
    """A non-device exception during the half-open probe must RELEASE
    the claimed probe slot (not count against the device): without the
    release the breaker would stay wedged half-open forever, rejecting
    every dispatch on a healthy device until a manual reset."""
    monkeypatch.setenv("H2O_TPU_BREAKER_FAILURES", "1")
    monkeypatch.setenv("H2O_TPU_BREAKER_COOLDOWN", "0.1")
    b = lifecycle.BREAKER
    with pytest.raises(health.ClusterHealthError):
        with lifecycle.breaker_guard("t"):
            raise health.ClusterHealthError("device gone")
    assert b.state() == "open"
    time.sleep(0.15)
    assert b.state() == "half-open"
    # the probe dispatch dies on a CALLER bug: slot freed, still open
    with pytest.raises(TypeError):
        with lifecycle.breaker_guard("t"):
            raise TypeError("bad tracer")
    assert b.state() == "half-open"     # cooldown already elapsed
    # the NEXT dispatch becomes the probe and can close the breaker
    with lifecycle.breaker_guard("t"):
        pass
    assert b.state() == "closed"


def test_real_device_error_in_scoring_feeds_breaker_without_lock(
        mesh8, monkeypatch):
    """A REAL (non-injected) device runtime error in score_numpy is
    breaker food, not a locked cloud: serving auto-recovers through the
    half-open probe instead of demanding a manual health.reset()."""
    monkeypatch.setenv("H2O_TPU_BREAKER_FAILURES", "2")
    monkeypatch.setenv("H2O_TPU_BREAKER_COOLDOWN", "0.15")
    from jax.errors import JaxRuntimeError

    rng = np.random.default_rng(7)
    x = rng.normal(size=160).astype(np.float32)
    fr = h2o.Frame.from_arrays({"x": x, "y": np.where(x > 0, "p", "n")})
    from h2o_kubernetes_tpu.models import GBM

    m = GBM(ntrees=2, max_depth=2, seed=0).train(y="y", training_frame=fr)
    X = np.array([[0.5]], np.float32)

    def boom(*a, **k):
        raise JaxRuntimeError("INTERNAL: halted chip")

    m._cached_score = boom              # instance attr shadows the method
    for _ in range(2):
        with pytest.raises(health.ClusterHealthError):
            m.score_numpy(X)
    assert health.healthy()             # NOT locked — no manual reset due
    assert lifecycle.BREAKER.state() == "open"
    del m.__dict__["_cached_score"]
    time.sleep(0.2)
    out = m.score_numpy(X)              # half-open probe closes it
    assert out.shape[0] == 1
    assert lifecycle.BREAKER.state() == "closed"


def test_breaker_guard_counts_device_shaped_errors_only(monkeypatch):
    monkeypatch.setenv("H2O_TPU_BREAKER_FAILURES", "1")
    b_before = lifecycle.BREAKER.status()["consecutive_failures"]
    # a caller's bad input says nothing about the device
    with pytest.raises(ValueError):
        with lifecycle.breaker_guard("t"):
            raise ValueError("bad payload")
    assert lifecycle.BREAKER.state() == "closed"
    assert lifecycle.BREAKER.status()["consecutive_failures"] == b_before
    # a ClusterHealthError (what device_dispatch converts runtime
    # errors into) trips at threshold 1
    with pytest.raises(health.ClusterHealthError):
        with lifecycle.breaker_guard("t"):
            raise health.ClusterHealthError("device gone")
    assert lifecycle.BREAKER.state() == "open"


def test_breaker_trips_on_injected_dispatch_errors(mesh8, monkeypatch):
    """score.dispatch:dispatch_error feeds the breaker WITHOUT locking
    the cloud, and an open breaker rejects without any device call."""
    monkeypatch.setenv("H2O_TPU_BREAKER_FAILURES", "2")
    monkeypatch.setenv("H2O_TPU_BREAKER_COOLDOWN", "0.2")
    rng = np.random.default_rng(3)
    x = rng.normal(size=160).astype(np.float32)
    fr = h2o.Frame.from_arrays(
        {"x": x, "y": np.where(x > 0, "p", "n")})
    from h2o_kubernetes_tpu.models import GBM

    m = GBM(ntrees=2, max_depth=2, seed=0).train(
        y="y", training_frame=fr)
    X = np.array([[0.5]], np.float32)
    with faults.inject("score.dispatch:dispatch_error*2"):
        for _ in range(2):
            with pytest.raises(health.ClusterHealthError):
                m.score_numpy(X)
    assert health.healthy()             # NOT locked — breaker food only
    assert lifecycle.BREAKER.state() == "open"
    # open: instant rejection, the armed fault is NOT consumed (finite
    # count — inf - 1 == inf would make this assertion vacuous)
    with faults.inject("score.dispatch:dispatch_error*5") as armed:
        before = armed[0].count
        with pytest.raises(lifecycle.CircuitOpenError):
            m.score_numpy(X)
        assert armed[0].count == before
    # cooldown over + faults clear: the half-open probe closes it
    time.sleep(0.25)
    out = m.score_numpy(X)
    assert out.shape[0] == 1
    assert lifecycle.BREAKER.state() == "closed"


# -- drain path --------------------------------------------------------------


def test_lifecycle_states_and_admission():
    assert lifecycle.state() == lifecycle.STARTING
    assert lifecycle.accepting()
    lifecycle.mark_serving()
    assert lifecycle.state() == lifecycle.SERVING
    lifecycle.begin_drain(reason="test", timeout=1.0)
    assert not lifecycle.accepting()
    assert lifecycle.wait_terminated(10.0)
    assert lifecycle.state() == lifecycle.TERMINATED
    # draining twice is idempotent, not a second drain
    lifecycle.begin_drain(reason="again")
    assert lifecycle.state() == lifecycle.TERMINATED


def test_drain_waits_for_running_job(monkeypatch):
    monkeypatch.setenv("H2O_TPU_DRAIN_TIMEOUT", "5")
    job = Job(dest="drain_ok", description="finishes in time").start()

    def worker():
        time.sleep(0.3)
        job.done()

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    job._thread = t
    try:
        t0 = time.monotonic()
        lifecycle.drain(reason="test")
        assert job.status == "DONE"     # drain waited, did not kill it
        assert time.monotonic() - t0 < 5.0
        assert lifecycle.state() == lifecycle.TERMINATED
    finally:
        JOBS.pop("drain_ok", None)


def test_drain_fails_job_exceeding_timeout(monkeypatch):
    monkeypatch.setenv("H2O_TPU_DRAIN_TIMEOUT", "0.3")
    job = Job(dest="drain_slow", description="outlives the drain").start()

    def worker():
        time.sleep(5.0)
        job.done()                      # too late: FAILED is terminal

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    job._thread = t
    try:
        lifecycle.drain(reason="test")
        assert job.status == "FAILED"
        assert "drain" in job.msg.lower()
        assert lifecycle.state() == lifecycle.TERMINATED
    finally:
        JOBS.pop("drain_slow", None)


def test_stop_fails_waiters_in_wedged_inflight_batch():
    """A batch the dispatcher already POPPED when the dispatch wedges
    must be failed by stop() too — those waiters are invisible to the
    pending-queue flush and would otherwise sit out their full timeout
    while the drain os._exits around them."""
    class _Wedge:
        def score_numpy(self, X, offset=None):
            time.sleep(3.0)
            return np.zeros((len(X), 1), np.float32)

    got = {}

    def client():
        try:
            rest.BATCHER.submit(_Wedge(), np.zeros((1, 1), np.float32))
            got["out"] = True
        except Exception as e:  # noqa: BLE001
            got["err"] = e

    t = threading.Thread(target=client, daemon=True)
    t.start()
    time.sleep(0.3)             # batch popped, dispatch wedged in sleep
    t0 = time.monotonic()
    rest.BATCHER.stop(timeout=0.2)
    t.join(2.0)
    assert not t.is_alive()
    assert isinstance(got.get("err"), rest.NodeDrainingError)
    assert time.monotonic() - t0 < 2.0


def test_sigterm_handler_safe_with_lifecycle_lock_held(monkeypatch):
    """The SIGTERM handler must not take the lifecycle lock in signal
    context: delivery while the main thread holds it (a status() call
    mid-flight) would self-deadlock and the drain would never start."""
    import os as _os
    import signal as _signal

    monkeypatch.setenv("H2O_TPU_DRAIN_TIMEOUT", "5")
    assert lifecycle.install_sigterm(exit_on_drain=False)
    with lifecycle.LIFECYCLE._lock:     # main thread IS the lock holder
        _os.kill(_os.getpid(), _signal.SIGTERM)
        time.sleep(0.1)                 # handler runs here; must return
    assert lifecycle.wait_terminated(10.0)
    assert lifecycle.state() == lifecycle.TERMINATED


def test_reset_abandons_in_flight_drain(monkeypatch):
    """reset() mid-drain (the in-process restart flow) bumps the epoch:
    the stale drain thread must abandon, NOT force TERMINATED over the
    restarted node's SERVING, set its terminated event, or run the new
    epoch's shutdown hooks."""
    monkeypatch.setenv("H2O_TPU_DRAIN_TIMEOUT", "10")
    job = Job(dest="stale_drain", description="holds the drain").start()
    t = threading.Thread(target=lambda: (time.sleep(0.8), job.done()),
                         daemon=True)
    t.start()
    job._thread = t
    try:
        dt = lifecycle.begin_drain(reason="old epoch")
        # deadline published atomically with the DRAINING flip
        assert lifecycle.remaining_drain_budget() is not None
        lifecycle.reset()               # restart while drain in flight
        lifecycle.mark_serving()
        dt.join(15.0)
        assert not dt.is_alive()
        assert lifecycle.state() == lifecycle.SERVING
        assert not lifecycle.terminated()
    finally:
        JOBS.pop("stale_drain", None)


def test_drain_stops_batcher_and_refuses_new_submits(mesh8):
    model = types.SimpleNamespace(score_numpy=lambda X, offset=None:
                                  np.zeros(X.shape[0], np.float32))
    X = np.zeros((2, 1), np.float32)
    assert rest.BATCHER.submit(model, X).shape == (2,)
    lifecycle.drain(reason="test", timeout=2.0)
    with pytest.raises(health.ClusterHealthError, match="drain"):
        rest.BATCHER.submit(model, X)
    # restart path: reset revives admission and the dispatcher thread
    lifecycle.reset()
    rest.BATCHER.reset()
    assert rest.BATCHER.submit(model, X).shape == (2,)


def test_shutdown_hooks_do_not_accumulate_across_server_restarts():
    """start_server registers ONE module-level drain hook over the live
    servers, idempotently — a process that restarts its REST server N
    times must not replay N stale shutdowns (or leak N server objects
    pinned by the callback list) at drain time."""
    calls = []
    lifecycle.register_shutdown(calls.append)
    lifecycle.register_shutdown(calls.append)   # same identity: deduped
    assert lifecycle.LIFECYCLE._callbacks.count(calls.append) <= 1
    base = len(lifecycle.LIFECYCLE._callbacks)
    s1 = rest.start_server(_free_port())
    s1.shutdown()
    s1.server_close()
    s2 = rest.start_server(_free_port())
    try:
        assert len(lifecycle.LIFECYCLE._callbacks) == base + 1
    finally:
        s2.shutdown()
        s2.server_close()


def test_drain_joins_heartbeat_thread():
    health.start_heartbeat(interval=0.05, timeout=5.0)
    t = health._thread
    assert t is not None and t.is_alive()
    lifecycle.drain(reason="test", timeout=2.0)
    t.join(timeout=5.0)
    assert not t.is_alive()


def test_drain_fault_point_does_not_block_drain():
    with faults.inject("lifecycle.drain:error"):
        lifecycle.drain(reason="test", timeout=1.0)
    assert lifecycle.state() == lifecycle.TERMINATED


# -- probe endpoints ---------------------------------------------------------


def test_probe_endpoints_healthy(server):
    code, body = _get(server, "/healthz")
    assert code == 200 and body["alive"] and body["state"] == "SERVING"
    code, body = _get(server, "/readyz")
    assert code == 200 and body["ready"]
    assert body["breaker"]["state"] == "closed"


def test_readyz_flips_before_healthz_during_drain(server, monkeypatch):
    monkeypatch.setenv("H2O_TPU_DRAIN_TIMEOUT", "10")
    # a RUNNING job holds DRAINING open long enough to probe it
    job = Job(dest="drain_probe", description="holds the drain").start()

    def worker():
        time.sleep(1.0)
        job.done()

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    job._thread = t
    try:
        assert _get(server, "/readyz")[0] == 200
        lifecycle.begin_drain(reason="test")
        deadline = time.monotonic() + 5.0
        while _get(server, "/readyz")[0] != 503 and \
                time.monotonic() < deadline:
            time.sleep(0.01)
        code, body = _get(server, "/readyz")
        assert code == 503 and "state=DRAINING" in body["reasons"]
        # liveness must NOT flip: the kubelet would kill the drain
        code, body = _get(server, "/healthz")
        assert code == 200 and body["alive"]
        assert lifecycle.wait_terminated(10.0)
        assert job.status == "DONE"
    finally:
        JOBS.pop("drain_probe", None)


def test_readyz_unready_on_unhealthy_cloud(server):
    health.mark_unhealthy("test outage")
    code, body = _get(server, "/readyz")
    assert code == 503 and "cloud unhealthy" in body["reasons"]
    assert _get(server, "/healthz")[0] == 200   # alive, just not ready
    health.reset()
    assert _get(server, "/readyz")[0] == 200


def test_post_rejected_while_draining(gbm_server, monkeypatch):
    monkeypatch.setenv("H2O_TPU_DRAIN_TIMEOUT", "10")
    job = Job(dest="drain_post", description="holds the drain").start()

    def worker():
        time.sleep(0.8)
        job.done()

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    job._thread = t
    try:
        lifecycle.begin_drain(reason="test")
        with pytest.raises(urllib.error.HTTPError) as e:
            _score(gbm_server)
        assert e.value.code == 503
        assert "draining" in json.loads(e.value.read())["msg"].lower()
        assert lifecycle.wait_terminated(10.0)
    finally:
        JOBS.pop("drain_post", None)


# -- overload control --------------------------------------------------------


def test_admission_queue_full_sheds_with_429(gbm_server, monkeypatch):
    monkeypatch.setenv("H2O_TPU_SCORE_QUEUE_MAX", "1")
    # fill the queue directly (no notify: the dispatcher stays parked,
    # nothing consumes the fake entry while we probe the front door)
    fake = rest._ScoreJob(None, np.zeros((1, 1), np.float32), None)
    with rest.BATCHER._cond:
        rest.BATCHER._pending.append(fake)
    try:
        shed0 = rest.BATCHER.stats["shed"]
        with pytest.raises(urllib.error.HTTPError) as e:
            _score(gbm_server)
        assert e.value.code == 429
        assert int(e.value.headers["Retry-After"]) >= 1
        assert rest.BATCHER.stats["shed"] == shed0 + 1
    finally:
        with rest.BATCHER._cond:
            if fake in rest.BATCHER._pending:
                rest.BATCHER._pending.remove(fake)
    # queue freed: same request admits and scores
    assert _score(gbm_server)["rows"] == 2


def test_expired_deadline_rejected_without_dispatch(gbm_server):
    r0 = rest.BATCHER.stats["requests"]
    with pytest.raises(urllib.error.HTTPError) as e:
        _score(gbm_server, headers={"X-H2O-Deadline-Ms": "0"})
    assert e.value.code == 504
    assert rest.BATCHER.stats["requests"] == r0   # never reached the queue
    # an unparseable deadline is the client's bug: 400
    with pytest.raises(urllib.error.HTTPError) as e:
        _score(gbm_server, headers={"X-H2O-Deadline-Ms": "soon"})
    assert e.value.code == 400
    # a live deadline scores normally
    out = _score(gbm_server, headers={"X-H2O-Deadline-Ms": "60000"})
    assert out["rows"] == 2
    assert rest.BATCHER.stats["requests"] == r0 + 1


def test_deadline_expiring_in_queue_is_504_shaped():
    """A budget that runs out WHILE QUEUED answers like the
    pre-admission rejection (504 via _DeadlineExpired), not a
    retryable-looking 503 — either side of admission, a spent budget
    means the same thing."""
    class _Slow:
        def score_numpy(self, X, offset=None):
            time.sleep(0.6)                  # holds the dispatcher busy
            return np.zeros((len(X), 1), np.float32)

    with pytest.raises(rest._DeadlineExpired):
        rest.BATCHER.submit(_Slow(), np.zeros((1, 1), np.float32),
                            deadline=time.monotonic() + 0.15)


def test_breaker_open_rejects_over_rest(gbm_server, monkeypatch):
    monkeypatch.setenv("H2O_TPU_BREAKER_FAILURES", "2")
    monkeypatch.setenv("H2O_TPU_BREAKER_COOLDOWN", "0.2")
    with faults.inject("score.dispatch:dispatch_error*2"):
        for _ in range(2):
            with pytest.raises(urllib.error.HTTPError) as e:
                _score(gbm_server)
            assert e.value.code == 503
    assert _get(gbm_server, "/readyz")[0] == 503
    t0 = time.monotonic()
    with pytest.raises(urllib.error.HTTPError) as e:
        _score(gbm_server)
    assert e.value.code == 503
    assert time.monotonic() - t0 < 2.0
    assert e.value.headers["Retry-After"] is not None
    time.sleep(0.25)
    assert _score(gbm_server)["rows"] == 2        # half-open probe
    assert _get(gbm_server, "/readyz")[0] == 200


# -- retry caps --------------------------------------------------------------


def test_retry_max_elapsed_cap(monkeypatch):
    monkeypatch.setenv("H2O_TPU_RETRY_MAX_ELAPSED_S", "0.2")
    calls = []

    def fn():
        calls.append(1)
        time.sleep(0.08)
        raise retry.TransientError("always down")

    t0 = time.monotonic()
    with pytest.raises(retry.TransientError):
        retry.call(fn, retry.policy_from_env(attempts=50, base=0.05))
    assert time.monotonic() - t0 < 1.5
    assert 1 < len(calls) < 10          # retried some, capped well short


def test_retry_gives_up_inside_drain_window(monkeypatch):
    """A retried persist write on a DRAINING node must not outlive the
    drain: a backoff sleep past the drain deadline is skipped and the
    last transient error surfaces instead."""
    monkeypatch.setenv("H2O_TPU_DRAIN_TIMEOUT", "10")
    job = Job(dest="drain_retry", description="holds the drain").start()

    def worker():
        time.sleep(1.0)
        job.done()

    t = threading.Thread(target=worker, daemon=True)
    t.start()
    job._thread = t
    try:
        lifecycle.begin_drain(reason="test")
        assert lifecycle.remaining_drain_budget() is not None

        def fn():
            raise retry.TransientError("still down")

        t0 = time.monotonic()
        with pytest.raises(retry.TransientError):
            # base=30: the first backoff alone would exceed the 10s
            # drain budget, so the loop must give up immediately
            retry.call(fn, retry.RetryPolicy(attempts=5, base=30.0,
                                             max_delay=30.0))
        assert time.monotonic() - t0 < 2.0
        assert lifecycle.wait_terminated(15.0)
    finally:
        JOBS.pop("drain_retry", None)
