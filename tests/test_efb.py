"""Exclusive Feature Bundling (models/tree/efb.py) — tier-1.

Parity discipline (same as PR 5's fused-binning tests): with zero
bundle conflicts the bundled path must produce IDENTICAL splits and
bitwise-identical predictions.  Full bitwise equality (values, gains,
covers, flat artifacts, predictions) is asserted on exact-sum fixtures
— a DRF forest on a 0/1 response (dyadic gradients every tree) and a
single gaussian round on a dyadic response — where the default-bin
remainder reconstruction is exactly associative; multi-round bernoulli
asserts identical structure per-round-1 plus float-tolerance
predictions (the ooc.py chunk-boundary caveat, documented in
docs/SCALING.md "Wide sparse frames").
"""

import os

import numpy as np
import pytest

import h2o_kubernetes_tpu as h2o
from h2o_kubernetes_tpu.models import DRF, GBM
from h2o_kubernetes_tpu.models.tree import efb as E
from h2o_kubernetes_tpu.models.tree.binning import apply_bins_jit, fit_bins


def _wide_frame(n=4096, n_groups=6, card=8, seed=0, with_na=True,
                with_enum=True, dyadic_y=True):
    """One-hot groups (mutually exclusive within a group) + dense
    numerics + an enum sparse column + NAs: the rich EFB fixture."""
    rng = np.random.default_rng(seed)
    cols = {}
    cats = []
    for g in range(n_groups):
        cat = rng.integers(0, card, size=n)
        cats.append(cat)
        for k in range(card):
            v = (cat == k).astype(np.float32)
            if with_na and g == 0 and k == 0:
                v[::37] = np.nan
            cols[f"g{g}_{k}"] = v
    cols["d0"] = rng.normal(size=n).astype(np.float32)
    cols["d1"] = rng.gamma(2.0, 1.0, size=n).astype(np.float32)
    domains = {}
    if with_enum:
        e = rng.integers(0, 3, size=n).astype(np.float32)
        e[rng.random(n) > 0.06] = 0.0
        if with_na:
            e[1::53] = np.nan
        cols["e0"] = e
        domains["e0"] = ["a", "b", "c"]
    if dyadic_y:
        # y in {0, 1} and n a power of two: the gaussian prior and the
        # first-round gradients are dyadic, every histogram sum exact
        y = ((cats[0] == 1) | ((cols["d0"] > 0) & (cats[1] == 2)))
        cols["y"] = y.astype(np.float32)
    else:
        cols["y"] = (cols["d0"] + (cats[0] == 1)
                     - (cats[1] == 2)).astype(np.float32)
    return h2o.Frame.from_arrays(cols, domains=domains)


def _masked_tree_fields(trees):
    isp = np.asarray(trees.is_split)
    out = {"is_split": isp}
    for f in ("split_feat", "split_bin", "na_left"):
        out[f] = np.where(isp, np.asarray(getattr(trees, f)), -9)
    for f in ("value", "gain", "cover"):
        out[f] = np.asarray(getattr(trees, f))
    return out


def _assert_trees_equal(ta, tb, bitwise_leaves=True):
    a, b = _masked_tree_fields(ta), _masked_tree_fields(tb)
    for f in ("is_split", "split_feat", "split_bin", "na_left"):
        assert np.array_equal(a[f], b[f]), f"{f} differs"
    if bitwise_leaves:
        for f in ("value", "gain", "cover"):
            assert np.array_equal(a[f], b[f]), f"{f} differs"


def _train(algo_cls, env, fr, **kw):
    old = os.environ.get("H2O_TPU_EFB")
    os.environ["H2O_TPU_EFB"] = env
    try:
        return algo_cls(**kw).train(y="y", training_frame=fr)
    finally:
        if old is None:
            os.environ.pop("H2O_TPU_EFB", None)
        else:
            os.environ["H2O_TPU_EFB"] = old


class TestBundlePlan:
    def test_plan_exclusive_sets_and_decode(self):
        """Every bundle's members are mutually exclusive on the data,
        and the LUT decode of the bundled matrix reproduces the
        original bin code of EVERY (row, feature) — the invariant the
        grower's row descent rides."""
        fr = _wide_frame()
        names = [n for n in fr.names if n != "y"]
        os.environ["H2O_TPU_EFB"] = "1"
        try:
            spec = fit_bins(fr, names)
            plan = E.plan_bundles(fr, spec)
        finally:
            os.environ.pop("H2O_TPU_EFB", None)
        assert plan is not None and plan.fb < len(names)
        assert plan.conflicts == 0
        import jax.numpy as jnp

        full = np.asarray(apply_bins_jit(
            fr.to_matrix(names), jnp.asarray(spec.edges_matrix()),
            jnp.asarray(np.array(spec.is_enum)),
            spec.na_bin))[: fr.nrows]
        B = spec.n_bins
        luts = plan.device_luts()
        feat_col = np.asarray(luts.feat_col)
        slot_feat = np.asarray(luts.slot_feat)
        slot_bin = np.asarray(luts.slot_bin)
        feat_default = np.asarray(luts.feat_default)
        bundled = plan.binned_host[: fr.nrows]
        # decode every feature back through the LUTs
        for f in range(len(names)):
            s = bundled[:, feat_col[f]]
            sf, sb = slot_feat[feat_col[f], s], slot_bin[feat_col[f], s]
            decoded = np.where(sf == f, sb, feat_default[f])
            assert np.array_equal(decoded, full[:, f]), names[f]
        # mutual exclusivity: inside a bundle, at most one member
        # non-default per row
        for kind, payload in plan.cols:
            if kind != "bundle":
                continue
            nnd = np.zeros(fr.nrows, dtype=np.int64)
            for m in payload:
                nnd += (full[:, m.feat] != m.default_bin)
            assert int(nnd.max()) <= 1
        # bundles never use bin B-1 (the node-total formula relies on
        # it) and per-member slots are contiguous ascending bins
        assert bundled.max() <= B - 2 or any(
            k == "pass" for k, _ in plan.cols)

    def test_conflict_budget(self, monkeypatch):
        """Budget 0 keeps overlapping features apart; a generous
        budget bundles them with first-member-wins resolution."""
        n = 2048
        rng = np.random.default_rng(1)
        a = (rng.random(n) < 0.05).astype(np.float32)
        b = (rng.random(n) < 0.05).astype(np.float32)
        both = (a > 0) & (b > 0)
        assert both.sum() > 0          # real conflicts exist
        cols = {"a": a, "b": b,
                "c": (rng.random(n) < 0.04).astype(np.float32),
                "y": (a + rng.normal(size=n)).astype(np.float32)}
        fr = h2o.Frame.from_arrays(cols)
        names = ["a", "b", "c"]
        spec = fit_bins(fr, names)
        monkeypatch.setenv("H2O_TPU_EFB", "1")
        monkeypatch.setenv("H2O_TPU_EFB_CONFLICT", "0")
        p0 = E.plan_bundles(fr, spec)
        for kind, payload in (p0.cols if p0 else []):
            if kind == "bundle":
                feats = {m.feat for m in payload}
                assert not {0, 1} <= feats      # a+b never together
        monkeypatch.setenv("H2O_TPU_EFB_CONFLICT", "0.5")
        p1 = E.plan_bundles(fr, spec)
        assert p1 is not None
        together = any(kind == "bundle" and
                       {0, 1} <= {m.feat for m in payload}
                       for kind, payload in p1.cols)
        assert together
        assert p1.conflicts > 0

    def test_kill_switch_and_auto_gate(self, monkeypatch):
        """H2O_TPU_EFB=0 never plans; auto skips narrow frames."""
        fr = _wide_frame(n=1024, n_groups=2, card=4)
        names = [nm for nm in fr.names if nm != "y"]
        monkeypatch.setenv("H2O_TPU_EFB", "0")
        assert not E.efb_eligible(len(names), None)
        monkeypatch.setenv("H2O_TPU_EFB", "auto")
        assert not E.efb_eligible(11, None)      # < MIN_F floor
        assert E.efb_eligible(64, None)
        assert not E.efb_eligible(64, object())  # checkpoint blocked


class TestZeroConflictParity:
    def test_drf_forest_bitwise(self):
        """DRF on a 0/1 response: dyadic gradients for EVERY tree, so
        the full forest — splits, leaf values, gains, covers, flat
        artifacts, predictions — is bitwise-identical bundled vs
        unbundled, NAs + enums + per-node mtries included."""
        fr = _wide_frame()
        kw = dict(ntrees=8, max_depth=5, seed=3, mtries=10)
        m_b = _train(DRF, "1", fr, **kw)
        m_u = _train(DRF, "0", fr, **kw)
        _assert_trees_equal(m_b.trees, m_u.trees)
        # flat serving artifacts (the MOJO-v2 wire format) bitwise
        fa, fb_ = m_b._flat(), m_u._flat()
        for x, yv in zip(fa, fb_):
            assert np.array_equal(np.asarray(x), np.asarray(yv))
        X = m_b._design_matrix(fr)
        assert np.array_equal(np.asarray(m_b._margins(X)),
                              np.asarray(m_u._margins(X)))
        assert np.array_equal(np.asarray(m_b.predict_raw(fr)),
                              np.asarray(m_u.predict_raw(fr)))

    def test_gbm_gaussian_single_round_bitwise(self):
        """One gaussian round on a dyadic response: every histogram
        sum is exact, so bundled == unbundled to the last bit."""
        fr = _wide_frame(dyadic_y=True)
        kw = dict(ntrees=1, max_depth=6, seed=1, distribution="gaussian")
        m_b = _train(GBM, "1", fr, **kw)
        m_u = _train(GBM, "0", fr, **kw)
        _assert_trees_equal(m_b.trees, m_u.trees)
        assert np.array_equal(np.asarray(m_b.predict_raw(fr)),
                              np.asarray(m_u.predict_raw(fr)))

    def test_gbm_bernoulli_multiround_structure(self):
        """Multi-round bernoulli: non-dyadic gradients make the
        remainder reconstruction reassociate f32 sums, so the contract
        is identical split STRUCTURE modulo exact-gain ties and
        float-tolerance predictions (the documented ooc.py-style
        caveat)."""
        fr = _wide_frame(seed=5)
        kw = dict(ntrees=3, max_depth=4, seed=2)
        m_b = _train(GBM, "1", fr, **kw)
        m_u = _train(GBM, "0", fr, **kw)
        p_b = np.asarray(m_b.predict_raw(fr))
        p_u = np.asarray(m_u.predict_raw(fr))
        assert np.allclose(p_b, p_u, atol=1e-5)
        # round 1 is exact-sum-free of margins only in its argmax
        # inputs' magnitudes — still assert the first tree's structure
        isp_b = np.asarray(m_b.trees.is_split)[0]
        isp_u = np.asarray(m_u.trees.is_split)[0]
        assert np.array_equal(isp_b, isp_u)

    def test_multinomial_parity(self):
        """K-class trees ride the same bundled grower via vmap."""
        fr = _wide_frame(seed=7, dyadic_y=True)
        rng = np.random.default_rng(7)
        y3 = rng.integers(0, 3, size=fr.nrows).astype(np.float32)
        cols = {nm: fr.vec(nm).to_numpy() for nm in fr.names
                if nm != "y"}
        cols["y"] = y3
        fr3 = h2o.Frame.from_arrays(
            cols, domains={"y": ["a", "b", "c"],
                           "e0": ["a", "b", "c"]})
        kw = dict(ntrees=2, max_depth=3, seed=4)
        m_b = _train(GBM, "1", fr3, **kw)
        m_u = _train(GBM, "0", fr3, **kw)
        isp_b = np.asarray(m_b.trees.is_split)
        isp_u = np.asarray(m_u.trees.is_split)
        assert np.array_equal(isp_b, isp_u)
        assert np.allclose(np.asarray(m_b.predict_raw(fr3)),
                           np.asarray(m_u.predict_raw(fr3)), atol=1e-5)


class TestOocParity:
    def test_ooc_bundled_bitwise(self, monkeypatch):
        """Out-of-core chunk streaming over the BUNDLED layout:
        bitwise vs the in-HBM bundled path AND vs fully-unbundled on
        an exact-sum fixture (single gaussian round, dyadic y)."""
        fr = _wide_frame(n=4096, dyadic_y=True)
        kw = dict(ntrees=1, max_depth=4, seed=1,
                  distribution="gaussian")
        monkeypatch.setenv("H2O_TPU_OOC_CHUNK_ROWS", "1024")
        monkeypatch.setenv("H2O_TPU_OOC", "1")
        m_ooc = _train(GBM, "1", fr, **kw)
        monkeypatch.setenv("H2O_TPU_OOC", "0")
        m_hbm = _train(GBM, "1", fr, **kw)
        m_ref = _train(GBM, "0", fr, **kw)
        _assert_trees_equal(m_ooc.trees, m_hbm.trees)
        _assert_trees_equal(m_ooc.trees, m_ref.trees)
        p = [np.asarray(m.predict_raw(fr)) for m in
             (m_ooc, m_hbm, m_ref)]
        assert np.array_equal(p[0], p[1])
        assert np.array_equal(p[0], p[2])


class TestServingUntouched:
    def test_artifact_roundtrip_and_binned_scorer(self, tmp_path):
        """A bundled-trained model's MOJO artifact + legacy binned
        scorer work exactly like an unbundled model's — serving never
        sees a bundle."""
        fr = _wide_frame(dyadic_y=True)
        m = _train(GBM, "1", fr, ntrees=2, max_depth=4, seed=1,
                   distribution="gaussian")
        X = m._design_matrix(fr)
        assert np.array_equal(np.asarray(m._margins(X)),
                              np.asarray(m._margins_binned(X)))
        from h2o_kubernetes_tpu.mojo import export_mojo, import_mojo

        path = str(tmp_path / "m.mojo")
        export_mojo(m, path)
        m2 = import_mojo(path)
        assert np.allclose(
            np.asarray(m2.predict(fr)),
            np.asarray(m.predict_raw(fr))[: fr.nrows], atol=0)
