"""Histogram kernel tests: the Pallas one-hot-matmul implementation
(interpret mode on CPU) must match the segment_sum reference exactly
(SURVEY.md §7 'Pallas histogram kernel quality')."""

import numpy as np
import pytest

import jax.numpy as jnp

from h2o_kubernetes_tpu.ops.histogram import build_histogram


def _random_case(r, F, n_nodes, n_bins, seed, dead_frac=0.2):
    rng = np.random.default_rng(seed)
    binned = rng.integers(0, n_bins, size=(r, F)).astype(np.uint8)
    rel = rng.integers(0, n_nodes, size=r).astype(np.int32)
    rel[rng.random(r) < dead_frac] = -1
    g = rng.normal(size=r).astype(np.float32)
    h = rng.random(r).astype(np.float32)
    w = (rng.random(r) < 0.9).astype(np.float32)
    # dead rows may carry NaN gradients — must not poison sums
    g[rel < 0] = np.nan
    return (jnp.asarray(binned), jnp.asarray(rel), jnp.asarray(g),
            jnp.asarray(h), jnp.asarray(w))


@pytest.mark.parametrize("r,F,n_nodes,n_bins", [
    (300, 4, 1, 16),
    (1000, 3, 4, 64),
    (513, 2, 32, 17),       # odd bin count, rows not tile-aligned
    (128, 5, 8, 32),
])
def test_pallas_matches_segment(r, F, n_nodes, n_bins):
    binned, rel, g, h, w = _random_case(r, F, n_nodes, n_bins, seed=r)
    ref = build_histogram(binned, rel, g, h, w, n_nodes, n_bins,
                          impl="segment")
    got = build_histogram(binned, rel, g, h, w, n_nodes, n_bins,
                          impl="pallas")
    assert got.shape == (n_nodes, F, n_bins, 3)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_blocked_kernel_matches_segment(monkeypatch):
    """The bin-blocked fallback kernel (taken when the factorized A
    operand would blow VMEM) stays parity-tested even though small
    trees now route to the factorized path."""
    import h2o_kubernetes_tpu.ops.histogram as H

    monkeypatch.setattr(H, "_FACT_MAX_NHI", 0)   # force the fallback
    binned, rel, g, h, w = _random_case(1000, 3, 4, 64, seed=5)
    ref = build_histogram(binned, rel, g, h, w, 4, 64, impl="segment")
    got = build_histogram(binned, rel, g, h, w, 4, 64, impl="pallas")
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_factorized_vs_blocked_agree(monkeypatch):
    """The two Pallas formulations agree on a shape the blocked kernel
    actually tiles (n_nodes*n_bins = 2048 = one full bin block)."""
    import h2o_kubernetes_tpu.ops.histogram as H

    binned, rel, g, h, w = _random_case(777, 2, 16, 128, seed=9)
    live = (np.asarray(rel) >= 0) & (np.asarray(w) > 0)
    vals = jnp.where(jnp.asarray(live)[:, None],
                     jnp.stack([g * w, h * w, w], axis=1), 0.0)
    rel_live = jnp.where(jnp.asarray(live), rel, -1)
    fact = H._hist_pallas_fact(binned, rel_live, vals, 16, 128)
    monkeypatch.setattr(H, "_FACT_MAX_NHI", 0)
    blocked = H._hist_pallas(binned, rel_live, vals, 16, 128)
    np.testing.assert_allclose(np.asarray(fact), np.asarray(blocked),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("impl", ["segment", "pallas"])
def test_unit_hess_two_channel_matches_three(impl):
    """h ≡ 1: the 2-channel accumulation (expanded back to 3) must
    equal the full 3-channel build with h = ones."""
    from h2o_kubernetes_tpu.ops.histogram import expand_unit_hess

    binned, rel, g, _, w = _random_case(900, 4, 8, 32, seed=11)
    ones = jnp.ones_like(w)
    ref = build_histogram(binned, rel, g, ones, w, 8, 32, impl=impl)
    got2 = build_histogram(binned, rel, g, ones, w, 8, 32, impl=impl,
                           unit_hess=True)
    assert got2.shape == (8, 4, 32, 2)
    got = expand_unit_hess(got2)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_gaussian_gbm_unit_hess_matches_full_channels(mesh8):
    """End to end: a gaussian GBM (unit_hess path) must predict the
    same as a build forced through the 3-channel kernels."""
    import h2o_kubernetes_tpu as h2o
    from h2o_kubernetes_tpu.models import GBM
    from h2o_kubernetes_tpu.models.tree import core as C

    rng = np.random.default_rng(12)
    n = 600
    x = rng.normal(size=n).astype(np.float32)
    y = np.sin(2 * x) + rng.normal(scale=0.2, size=n)
    fr = h2o.Frame.from_arrays({"x": x, "y": y})
    m2 = GBM(ntrees=3, max_depth=3, nbins=32, seed=0).train(
        y="y", training_frame=fr)
    orig = C.TreeParams.__new__.__defaults__
    m3 = None
    try:
        # forcing unit_hess=False exercises the 3-channel path on the
        # same data (TreeParams is a NamedTuple: patch the default)
        import h2o_kubernetes_tpu.models.gbm as G

        real_tp = C.TreeParams

        def no_unit(*a, **kw):
            kw["unit_hess"] = False
            return real_tp(*a, **kw)

        G.TreeParams = no_unit
        m3 = GBM(ntrees=3, max_depth=3, nbins=32, seed=0).train(
            y="y", training_frame=fr)
    finally:
        import h2o_kubernetes_tpu.models.gbm as G

        G.TreeParams = C.TreeParams
        del orig
    np.testing.assert_allclose(m2.predict_raw(fr), m3.predict_raw(fr),
                               rtol=1e-6)


def test_vmapped_batch_matches_loop():
    """vmap over a class axis (the fused multinomial scan's shape) must
    equal per-class builds. The custom_vmap rule lowers the batch into
    the node axis instead of batching the Pallas kernel — Mosaic
    rejects vmapped rank-1 block specs (round-4 on-chip gate)."""
    import jax

    K, rows, F, n_nodes, n_bins = 3, 1500, 4, 8, 32
    rng = np.random.default_rng(21)
    binned = jnp.asarray(
        rng.integers(0, n_bins, size=(rows, F)).astype(np.uint8))
    relK = jnp.asarray(np.where(
        rng.random((K, rows)) < 0.85,
        rng.integers(0, n_nodes, size=(K, rows)), -1).astype(np.int32))
    gK = jnp.asarray(rng.normal(size=(K, rows)).astype(np.float32))
    hK = jnp.asarray(rng.random((K, rows)).astype(np.float32))
    w = jnp.asarray((rng.random(rows) < 0.9).astype(np.float32))

    for impl in ("segment", "pallas"):
        got = jax.vmap(
            lambda rel, g, h: build_histogram(
                binned, rel, g, h, w, n_nodes, n_bins, impl))(
            relK, gK, hK)
        assert got.shape == (K, n_nodes, F, n_bins, 3)
        for k in range(K):
            want = build_histogram(binned, relK[k], gK[k], hK[k], w,
                                   n_nodes, n_bins, "segment")
            np.testing.assert_allclose(
                np.asarray(got[k]), np.asarray(want),
                rtol=1e-5, atol=1e-5, err_msg=f"{impl} class {k}")


def test_mosaic_lowering_for_tpu_target():
    """AOT-lower the vmapped pallas build for a TPU target FROM CPU —
    catches Mosaic block-spec rejections (the round-4 gate failure:
    vmap prepends a squeezed batch dim that Mosaic refuses on rank-1
    operands) without needing a chip."""
    import unittest.mock as mock

    import jax

    rng = np.random.default_rng(7)
    rows, F, n_nodes, n_bins, K = 2048, 3, 8, 64, 3
    binned = jnp.asarray(
        rng.integers(0, n_bins, size=(rows, F)).astype(np.uint8))
    relK = jnp.asarray(
        rng.integers(0, n_nodes, size=(K, rows)).astype(np.int32))
    gK = jnp.asarray(rng.normal(size=(K, rows)).astype(np.float32))
    hK = jnp.asarray(np.ones((K, rows), np.float32))
    w = jnp.ones(rows, jnp.float32)

    with mock.patch("jax.default_backend", lambda: "tpu"):
        def one(rel, g, h):
            return build_histogram(binned, rel, g, h, w, n_nodes,
                                   n_bins, "pallas")

        # single (rank-1 specs) and vmapped (batched) forms both lower
        jax.jit(one).trace(relK[0], gK[0], hK[0]).lower(
            lowering_platforms=("tpu",))
        jax.jit(jax.vmap(one)).trace(relK, gK, hK).lower(
            lowering_platforms=("tpu",))


def test_mosaic_lowering_bench_shape_paths():
    """AOT-lower the fact kernel's OTHER configurations from CPU: the
    wide 4096 row tile (rows >= 8192 — the production bench shape; the
    small-rows case above stays at rt=1024) and the feature-group
    SPLIT path (F_pad > F grid), which needs _OUT_BUDGET forced down
    since hitting it naturally takes F > 64."""
    import unittest.mock as mock

    import jax

    from h2o_kubernetes_tpu.ops import histogram as H

    rng = np.random.default_rng(11)
    rows, n_nodes, n_bins = 8192, 16, 256
    w = jnp.ones(rows, jnp.float32)
    g = jnp.asarray(rng.normal(size=rows).astype(np.float32))
    h = jnp.asarray(rng.random(rows).astype(np.float32))
    rel = jnp.asarray(
        rng.integers(0, n_nodes, size=rows).astype(np.int32))

    with mock.patch("jax.default_backend", lambda: "tpu"):
        # rt=4096 path (n_hi = 32 <= 64, rows >= 8192)
        binned = jnp.asarray(
            rng.integers(0, n_bins, size=(rows, 10)).astype(np.uint8))
        jax.jit(lambda r: build_histogram(
            binned, r, g, h, w, n_nodes, n_bins, "pallas")).trace(
            rel).lower(lowering_platforms=("tpu",))
        # feature-group split: budget forced to one feature's out block
        per_f = 3 * 32 * 128 * 4
        binned_wide = jnp.asarray(
            rng.integers(0, n_bins, size=(rows, 18)).astype(np.uint8))
        with mock.patch.object(H, "_OUT_BUDGET", per_f * 8):
            jax.jit(lambda r: build_histogram(
                binned_wide, r, g, h, w, n_nodes, n_bins,
                "pallas")).trace(rel).lower(lowering_platforms=("tpu",))


def test_feature_group_split_parity():
    """Interpret-mode parity through the F_pad > F split path (padded
    feature columns must histogram into junk rows that are sliced
    away, not into real features)."""
    import unittest.mock as mock

    from h2o_kubernetes_tpu.ops import histogram as H

    binned, rel, g, h, w = _random_case(3000, 18, 8, 64, seed=13)
    want = build_histogram(binned, rel, g, h, w, 8, 64, impl="segment")
    per_f = 3 * (-(-8 * 64 // 128)) * 128 * 4
    with mock.patch.object(H, "_OUT_BUDGET", per_f * 8):
        got = build_histogram(binned, rel, g, h, w, 8, 64,
                              impl="pallas")
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


def test_totals_preserved():
    binned, rel, g, h, w = _random_case(700, 3, 8, 32, seed=1)
    hist = build_histogram(binned, rel, g, h, w, 8, 32, impl="pallas")
    live = (np.asarray(rel) >= 0) & (np.asarray(w) > 0)
    want_w = np.asarray(w)[live].sum()
    # per-feature totals all equal the live weight mass
    tot = np.asarray(hist).sum(axis=(0, 2))[:, 2]
    np.testing.assert_allclose(tot, want_w, rtol=1e-5)


def test_tree_with_pallas_impl(mesh8):
    """Whole GBM trained with the pallas histogram (interpret mode)
    predicts identically to the segment_sum build."""
    import h2o_kubernetes_tpu as h2o
    from h2o_kubernetes_tpu.models import GBM

    rng = np.random.default_rng(3)
    n = 500
    x = rng.normal(size=n).astype(np.float32)
    y = np.where(x + rng.normal(scale=0.3, size=n) > 0, "a", "b")
    fr = h2o.Frame.from_arrays({"x": x, "y": y})
    m_seg = GBM(ntrees=3, max_depth=3, nbins=32, seed=0).train(
        y="y", training_frame=fr)
    m_pal = GBM(ntrees=3, max_depth=3, nbins=32, seed=0,
                _hist_impl="pallas").train(y="y", training_frame=fr)
    np.testing.assert_allclose(m_pal.predict_raw(fr),
                               m_seg.predict_raw(fr), rtol=1e-5)


def test_histogram_auc_matches_exact():
    from h2o_kubernetes_tpu import metrics as M

    rng = np.random.default_rng(2)
    n = 30_000
    y = (rng.random(n) < 0.4).astype(np.float32)
    s = np.clip(y * 0.3 + rng.normal(scale=0.35, size=n) + 0.35, 0, 1)
    s = s.astype(np.float32)
    w = (rng.random(n) < 0.9).astype(np.float32)
    exact = M.roc_auc(y, s, w=w, exact=True)
    hist = M.roc_auc(y, s, w=w, exact=False)
    assert abs(exact - hist) < 2e-3, (exact, hist)
    # NaN on a live row surfaces through the histogram path too
    s2 = s.copy(); s2[17] = np.nan
    assert np.isnan(M.roc_auc(y, s2, w=w, exact=False))


def test_histogram_auc_inf_scores_pinned():
    from h2o_kubernetes_tpu import metrics as M

    rng = np.random.default_rng(4)
    n = 20_000
    y = (rng.random(n) < 0.5).astype(np.float32)
    s = (y * 0.5 + rng.normal(scale=0.3, size=n)).astype(np.float32)
    exact = M.roc_auc(y, s, exact=True)
    s_inf = s.copy(); s_inf[0] = np.inf; s_inf[1] = -np.inf
    hist = M.roc_auc(y, s_inf, exact=False)
    # one +inf / one -inf row must not collapse the binning
    assert abs(exact - hist) < 5e-3, (exact, hist)


def test_two_term_mode_close_to_segment(monkeypatch):
    """H2O_TPU_HIST_TERMS=2 (throughput mode): products carry ~2^-16
    relative error — the histogram must match segment to single-
    precision-histogram tolerance, far inside split-decision noise."""
    import h2o_kubernetes_tpu.ops.histogram as H

    monkeypatch.setattr(H, "_TERMS", 2)
    binned, rel, g, h, w = _random_case(2000, 4, 8, 64, seed=7)
    ref = build_histogram(binned, rel, g, h, w, 8, 64, impl="segment")
    got = build_histogram(binned, rel, g, h, w, 8, 64, impl="pallas")
    ref_np, got_np = np.asarray(ref), np.asarray(got)
    # near-zero cells make pointwise relative error meaningless —
    # normalize by the histogram's scale (what split gains compare
    # against); 2-term products are ~2^-16, so scale-relative error
    # stays well under 1e-5
    scale = np.abs(ref_np).max()
    assert np.max(np.abs(got_np - ref_np)) < 1e-4 * scale
