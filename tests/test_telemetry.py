"""Fleet telemetry (ISSUE 14 tentpole): ONE process-wide metrics
registry behind every stats surface, Prometheus exposition everywhere,
end-to-end request tracing.

Contracts pinned here:

- the registry is exact under thread fire (N threads x M increments
  across counters/histograms -> exact totals, no lost updates);
- tenant-label cardinality is BOUNDED: 1000 distinct model labels
  produce at most top-K + 1 (`other`) series, with the rollup
  conserving the total;
- `/3/Stats` keeps its byte-shape-compatible JSON (golden key-shape
  test) while being assembled from the registry snapshot, plus the
  sanctioned `build` block;
- every counter `/3/Stats` reports appears on `GET /metrics` under the
  shared naming rule (inventory-diff test — the two surfaces cannot
  drift);
- a traced request decomposes into admission/queue/assemble/dispatch/
  total spans at `GET /3/Trace/{id}` and echoes its X-H2O-Trace-Id;
- a LOST router hedge never double-counts the tenant's forwarded
  counter, and every fired hedge settles to exactly one of
  won/lost/cancelled on the hedge shard.
"""

import json
import socket
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

import h2o_kubernetes_tpu as h2o
from h2o_kubernetes_tpu import rest
from h2o_kubernetes_tpu.models import GBM
from h2o_kubernetes_tpu.operator.router import start_router
from h2o_kubernetes_tpu.runtime import telemetry
from h2o_kubernetes_tpu.runtime.telemetry import (
    ALLOWED_LABELS, REGISTRY, MetricsRegistry, build_info,
    metric_name, parse_prometheus_text)

pytestmark = pytest.mark.chaos


# ---------------------------------------------------------------------------
# Registry primitives
# ---------------------------------------------------------------------------


def test_label_allowlist_enforced():
    r = MetricsRegistry()
    with pytest.raises(ValueError, match="allowlist"):
        r.counter("h2o_bad_total", "x", label="tenant_name")
    # allowed labels pass
    for lab in ("model", "shard", "phase"):
        assert lab in ALLOWED_LABELS
        r.counter(f"h2o_ok_{lab}_total", "x", label=lab)


def test_registry_hammer_no_lost_updates():
    """N threads x M increments across counters + a histogram ->
    exact totals. A lost update would silently corrupt autoscale
    signals fleet-wide, so this is the registry's core contract."""
    r = MetricsRegistry()
    c_plain = r.counter("h2o_plain_total", "")
    c_model = r.counter("h2o_bymodel_total", "", label="model")
    g = r.gauge("h2o_gauge", "")
    h = r.histogram("h2o_lat_seconds", "", label="phase")
    threads, per = 8, 5000
    errs = []

    def work(tid):
        try:
            for i in range(per):
                c_plain.inc()
                c_model.inc(label_value=f"m{i % 30}")
                h.observe(0.001 * (i % 7), label_value="total")
                g.set(float(tid))
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    ts = [threading.Thread(target=work, args=(t,))
          for t in range(threads)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errs
    assert c_plain.value() == threads * per
    assert sum(v for _, _, v in c_model.samples()) == threads * per
    snap = h.snapshot("total")
    assert snap["count"] == threads * per


def test_model_label_cardinality_bounded():
    """1000 distinct model labels -> at most top-K + 1 series, the
    rollup conserves the total, and the hot labels keep their own
    series."""
    r = MetricsRegistry()
    c = r.counter("h2o_req_total", "", label="model")
    k = telemetry._topk()
    # hot tenants first (real traffic rank), then the long tail
    for hot in range(5):
        for _ in range(200):
            c.inc(label_value=f"hot{hot}")
    for i in range(1000):
        c.inc(label_value=f"tail{i:04d}")
    assert c.series_count() <= k + 1
    samples = {tuple(sorted(lbl.items())): v
               for _, lbl, v in c.samples()}
    total = sum(samples.values())
    assert total == 5 * 200 + 1000          # nothing lost to the cap
    for hot in range(5):                     # hot series survive
        assert ((("model", f"hot{hot}"),)) in samples
    assert samples.get((("model", "other"),), 0) > 0


def test_histogram_buckets_and_quantile():
    r = MetricsRegistry()
    h = r.histogram("h2o_x_seconds", "", buckets=(0.01, 0.1, 1.0))
    for v in (0.005, 0.05, 0.05, 0.5, 5.0):
        h.observe(v)
    snap = h.snapshot()
    assert snap["count"] == 5
    assert snap["buckets"][0.01] == 1
    assert snap["buckets"][0.1] == 3
    assert snap["buckets"][1.0] == 4
    q50 = h.quantile(0.5)
    assert 0.01 <= q50 <= 0.1
    # exposition carries cumulative buckets + +Inf + sum + count
    text = r.prometheus_text()
    p = parse_prometheus_text(text)
    assert p[("h2o_x_seconds_bucket", (("le", "0.1"),))] == 3
    assert p[("h2o_x_seconds_bucket", (("le", "+Inf"),))] == 5
    assert p[("h2o_x_seconds_count", ())] == 5


def test_prometheus_text_roundtrip_and_groups():
    r = MetricsRegistry()
    r.counter("h2o_a_total", "help a").inc(3)
    r.register_group("grp", lambda: {
        "n": 7, "flag": True, "state": "open",
        "nested": {"x": 1.5}, "skipped": [1, 2]})
    r.register_group("per_model", lambda: {
        "m1": {"requests": 4}, "m2": {"requests": 2}},
        labeled="model")
    p = parse_prometheus_text(r.prometheus_text())
    assert p[("h2o_a_total", ())] == 3
    assert p[(metric_name("grp", "n"), ())] == 7
    assert p[(metric_name("grp", "flag"), ())] == 1
    assert p[(metric_name("grp", "state"), (("value", "open"),))] == 1
    assert p[(metric_name("grp", "nested", "x"), ())] == 1.5
    assert p[(metric_name("per_model", "requests"),
              (("model", "m1"),))] == 4
    assert p[(metric_name("per_model", "requests"),
              (("model", "m2"),))] == 2


def test_labeled_group_topk_rollup():
    """The scrape-time top-K + `other` rollup for labeled groups:
    1000 tenants on /3/Stats expose <= K + 1 series per counter on
    /metrics, hottest kept, mass conserved."""
    r = MetricsRegistry()
    k = telemetry._topk()
    data = {f"t{i:04d}": {"requests": i} for i in range(1000)}
    r.register_group("models", lambda: data, labeled="model")
    p = parse_prometheus_text(r.prometheus_text())
    series = [(lbls, v) for (n, lbls), v in p.items()
              if n == metric_name("models", "requests")]
    assert len(series) <= k + 1
    assert sum(v for _, v in series) == sum(i for i in range(1000))
    labels = {dict(lbls)["model"] for lbls, _ in series}
    assert "t0999" in labels            # hottest kept by traffic
    assert "other" in labels


def test_group_registration_idempotent():
    r = MetricsRegistry()
    r.register_group("g", lambda: {"v": 1})
    r.register_group("g", lambda: {"v": 2})     # last wins
    assert r.group_snapshot()["g"] == {"v": 2}
    # a raising group yields an error marker, never a dead scrape
    r.register_group("boom", lambda: 1 / 0)
    snap = r.group_snapshot()
    assert "error" in snap["boom"]
    assert snap["g"] == {"v": 2}


def test_trace_id_sanitize():
    assert telemetry.trace_id_from({"X-H2O-Trace-Id": "ab-C_9"}) \
        == "ab-C_9"
    # header injection / garbage mints a fresh id instead
    bad = telemetry.trace_id_from(
        {"X-H2O-Trace-Id": 'x"\r\nSet-Cookie: p'})
    assert bad and all(c.isalnum() or c in "-_" for c in bad)
    assert telemetry.trace_id_from({})


def test_phase_span_feeds_histogram_and_timeline():
    from h2o_kubernetes_tpu.diagnostics import timeline

    hist = telemetry.train_phase_histogram()
    before = hist.snapshot("unit_test_phase")["count"]
    with telemetry.phase_span("unit_test_phase"):
        time.sleep(0.002)
    assert hist.snapshot("unit_test_phase")["count"] == before + 1
    evs = [e for e in timeline.events("phase")
           if e.get("phase") == "unit_test_phase"]
    assert evs and evs[-1]["dur_ms"] >= 1.0


def test_build_info_fields():
    b = build_info()
    assert b["version"]
    assert b["pid"]
    assert b["uptime_s"] >= 0
    assert b["hostfp"]
    # jax versions come from package metadata, never an import
    assert "jax" in b and "jaxlib" in b


def test_status_listener_serves_metrics():
    srv = telemetry.start_status_listener(0, extra_groups=lambda: {
        "operator": {"pool": "p", "n": 3}})
    try:
        port = srv.server_address[1]
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10) as r:
            assert r.status == 200
            assert "text/plain" in r.headers["Content-Type"]
            p = parse_prometheus_text(r.read().decode())
        assert p[(metric_name("operator", "n"), ())] == 3
        assert any(k[0] == "h2o_build_info" for k in p)
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=10) as r:
            hz = json.loads(r.read())
        assert hz["alive"] and hz["build"]["pid"]
    finally:
        srv.shutdown()
        srv.server_close()


# ---------------------------------------------------------------------------
# REST surface: golden shape, inventory diff, request tracing
# ---------------------------------------------------------------------------


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _get(base, path):
    try:
        with urllib.request.urlopen(base + path, timeout=30) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


def _post(base, path, payload, headers=None):
    req = urllib.request.Request(
        base + path, data=json.dumps(payload).encode(), method="POST",
        headers={"Content-Type": "application/json", **(headers or {})})
    try:
        with urllib.request.urlopen(req, timeout=120) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


def _train_tiny(seed=5):
    rng = np.random.default_rng(seed)
    n = 300
    cols = {f"x{i}": rng.normal(size=n).astype(np.float32)
            for i in range(4)}
    cols["y"] = np.where(cols["x0"] - cols["x1"] > 0, "late", "ontime")
    fr = h2o.Frame.from_arrays(cols)
    return GBM(ntrees=2, max_depth=2, seed=seed).train(
        y="y", training_frame=fr)


@pytest.fixture(scope="module")
def stats_server(mesh8):
    # module-scoped: one GBM train + one server for the three REST
    # surface tests below (they only READ /3/Stats//metrics or add
    # traffic, which every assertion tolerates)
    port = _free_port()
    rest.MODELS["telem_pm"] = _train_tiny()
    srv = rest.start_server(port)
    yield f"http://127.0.0.1:{port}"
    srv.shutdown()
    rest.MODELS.pop("telem_pm", None)
    rest.READINESS_GATES.clear()
    with rest._STATS_LOCK:
        rest.MODEL_STATS.pop("telem_pm", None)


def _shape(obj):
    """Recursive key-shape of a JSON payload (dict keys only — values
    and list contents are data, not shape)."""
    if isinstance(obj, dict):
        return {k: _shape(v) for k, v in sorted(obj.items())}
    return type(obj).__name__


# The golden /3/Stats key-shape: the PRE-registry sections verbatim
# (ready/reasons + lifecycle spread + identity/scorer_cache/batcher/
# counters/models/fairness/compiles/registry) plus the ONE sanctioned
# addition, `build`. If this test fails, either a surface broke its
# JSON contract or a new key needs to be added HERE deliberately.
GOLDEN_TOP_KEYS = {
    "ready", "reasons", "state", "healthy", "breaker", "cordoned",
    "drain_budget_s", "identity", "scorer_cache", "batcher",
    "counters", "models", "fairness", "compiles", "registry", "build",
}
GOLDEN_SECTIONS = {
    "counters": {"deadline_504", "scored_while_unready",
                 "rate_limited"},
    "batcher": {"requests", "batches", "batched_rows",
                "max_batch_requests", "shed", "fairness_shed",
                "queue_depth"},
    "scorer_cache": {"hits", "misses", "promotions", "evictions",
                     "models", "resident", "resident_bytes",
                     "budget_bytes"},
    "breaker": {"state", "consecutive_failures",
                "cooldown_remaining_s", "trips", "short_circuited",
                "probes", "closes", "failures"},
    "compiles": {"compiles", "compile_s", "pcache_hits",
                 "pcache_misses"},
    "build": {"version", "jax", "jaxlib", "hostfp", "pid",
              "started_at", "uptime_s"},
}


def test_stats_golden_json_shape(stats_server):
    code, st, _ = _get(stats_server, "/3/Stats")
    assert code == 200
    assert set(st.keys()) == GOLDEN_TOP_KEYS, (
        f"/3/Stats top-level shape drifted: "
        f"{sorted(set(st) ^ GOLDEN_TOP_KEYS)}")
    for section, keys in GOLDEN_SECTIONS.items():
        got = set(st[section].keys())
        assert got >= keys, (
            f"/3/Stats[{section}] lost keys: {sorted(keys - got)}")
        if section in ("counters", "batcher", "build"):
            # these sections are EXACT: a stray key is a shape change
            # clients (autoscaler scrapes) would start depending on
            assert got == keys, (
                f"/3/Stats[{section}] gained keys: "
                f"{sorted(got - keys)}")


def test_metrics_inventory_covers_stats(stats_server):
    """THE acceptance diff: every numeric counter on /3/Stats appears
    in the /metrics exposition under the shared naming rule — the two
    surfaces render one registry and cannot drift."""
    # traffic first so per-model series exist
    rows = [{f"x{i}": 0.2 for i in range(4)}]
    code, _, _ = _post(stats_server,
                       "/3/Predictions/models/telem_pm",
                       {"rows": rows})
    assert code == 200
    code, st, _ = _get(stats_server, "/3/Stats")
    assert code == 200
    with urllib.request.urlopen(stats_server + "/metrics",
                                timeout=30) as r:
        assert "text/plain" in r.headers["Content-Type"]
        exposed = parse_prometheus_text(r.read().decode())
    names = {k[0] for k in exposed}

    def leaves(prefix, obj, out):
        for k, v in obj.items():
            if isinstance(v, bool) or isinstance(v, (int, float)):
                out.append(prefix + (str(k),))
            elif isinstance(v, dict):
                leaves(prefix + (str(k),), v, out)

    missing = []
    # plain sections -> h2o_stats_<section>_<leaf...>
    for section, group in (("counters", "counters"),
                           ("batcher", "batcher"),
                           ("scorer_cache", "scorer_cache"),
                           ("compiles", "compiles"),
                           ("breaker", "lifecycle")):
        flat: list = []
        src = st[section]
        pre = (group, "breaker") if section == "breaker" else (group,)
        leaves(pre, src, flat)
        for path in flat:
            if metric_name(*path) not in names:
                missing.append("/".join(path))
    # per-model section -> h2o_stats_models_<counter>{model=...}
    for mkey, rec in st["models"].items():
        for k, v in rec.items():
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                want = (metric_name("models", k),
                        (("model", mkey),))
                if want not in exposed:
                    missing.append(f"models/{mkey}/{k}")
    assert not missing, (
        f"counters on /3/Stats absent from /metrics: {missing}")
    # and the request-phase histograms the registry owns directly
    assert "h2o_request_phase_seconds_bucket" in names


def test_request_trace_spans_and_echo(stats_server):
    rows = [{f"x{i}": 0.1 for i in range(4)}] * 5
    tid = "trace-test-0001"
    code, _, hdrs = _post(stats_server,
                          "/3/Predictions/models/telem_pm",
                          {"rows": rows},
                          headers={"X-H2O-Trace-Id": tid})
    assert code == 200
    low = {k.lower(): v for k, v in hdrs.items()}
    assert low.get("x-h2o-trace-id") == tid
    code, tr, _ = _get(stats_server, f"/3/Trace/{tid}")
    assert code == 200
    assert tr["trace_id"] == tid and tr["model"] == "telem_pm"
    names = [s["name"] for s in tr["spans"]]
    for want in ("admission", "queue", "assemble", "dispatch",
                 "total"):
        assert want in names, f"span '{want}' missing: {names}"
    assert names.count("dispatch") == 1
    total = next(s for s in tr["spans"] if s["name"] == "total")
    disp = next(s for s in tr["spans"] if s["name"] == "dispatch")
    assert 0 <= disp["ms"] <= total["ms"]
    # a request WITHOUT the header gets a minted id echoed back
    code, _, hdrs = _post(stats_server,
                          "/3/Predictions/models/telem_pm",
                          {"rows": rows})
    low = {k.lower(): v for k, v in hdrs.items()}
    minted = low.get("x-h2o-trace-id")
    assert code == 200 and minted and minted != tid
    # unknown id: clean 404
    code, _, _ = _get(stats_server, "/3/Trace/doesnotexist")
    assert code == 404


def test_trace_ring_bounded(monkeypatch):
    monkeypatch.setenv("H2O_TPU_TRACE_RING", "16")
    ring = telemetry.TraceRing()
    for i in range(200):
        ring.record(f"t{i}", [{"name": "total", "ms": 1.0}])
    assert ring.get("t0") is None           # aged out
    assert ring.get("t199") is not None     # newest kept
    with ring._lock:
        assert len(ring._ring) <= 16


# ---------------------------------------------------------------------------
# Router hedging: lost/cancelled races never double-count
# ---------------------------------------------------------------------------


class _Stub:
    """Scriptable replica (the test_router idiom, trimmed)."""

    def __init__(self, name, on_post):
        stub = self

        class H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                body = json.dumps({"ready": True,
                                   "name": stub.name}).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                n = int(self.headers.get("Content-Length") or 0)
                if n:
                    self.rfile.read(n)
                stub.posts.append(dict(self.headers))
                code, payload, hdrs = stub.on_post()
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (hdrs or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

        self.name = name
        self.posts: list = []
        self.on_post = on_post
        self.srv = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.url = f"http://127.0.0.1:{self.srv.server_address[1]}"
        threading.Thread(target=self.srv.serve_forever,
                         daemon=True).start()

    def close(self):
        self.srv.shutdown()
        self.srv.server_close()


def _fwd_count(model):
    """The tenant's slice of the global forwarded counter — summed
    with `other` because earlier tests in the same process may have
    filled the capped top-K label set (the per-instance by_model
    assertion is the exact one; this diff just proves the registry
    moved by 1 total)."""
    c = REGISTRY.counter(
        "h2o_router_forwarded_total",
        "requests relayed with a non-5xx answer, per tenant "
        "(top-K + other)", label="model")
    return c.value(model) + c.value("other")


def test_hedge_lost_settles_and_never_double_counts(monkeypatch):
    """The satellite fix: a hedge that LOSES the race (hedge leg
    answered, primary's answer relayed) must settle as hedge_lost on
    the hedge shard and add exactly ONE to the tenant's forwarded
    counter — and a hedge still in flight when the primary wins
    settles as hedge_cancelled."""
    monkeypatch.setenv("H2O_TPU_ROUTER_HEALTH_INTERVAL", "30")
    monkeypatch.setenv("H2O_TPU_ROUTER_HEDGE_MS", "30")

    def slow_ok():
        time.sleep(0.15)
        return 200, {"predict": ["ok"], "served_by": "primary"}, None

    def fast_503():
        return 503, {"msg": "draining"}, None

    hold = threading.Event()

    def hung_ok():
        hold.wait(2.0)
        return 200, {"predict": ["ok"], "served_by": "hedge"}, None

    a = _Stub("primary", slow_ok)
    b = _Stub("hedge503", fast_503)
    c = _Stub("hedgehang", hung_ok)
    key_lost, key_cxl = "tlost", "tcxl"
    table = {"keys": {key_lost: ["s0", "s1"], key_cxl: ["s0", "s2"]},
             "shards": {"s0": [a.url], "s1": [b.url], "s2": [c.url]}}
    srv, router = start_router(table)
    url = f"http://127.0.0.1:{srv.server_address[1]}"
    try:
        base_lost = _fwd_count(key_lost)
        # LOST race: hedge (fast 503) answers first and fails, slow
        # primary's 200 is relayed
        code, out, hdrs = _post(url, f"/3/Predictions/models/"
                                f"{key_lost}", {"rows": [[1.0]]},
                                headers={"X-H2O-SLO": "interactive"})
        assert code == 200 and out["served_by"] == "primary"
        st = router.snapshot()
        assert st["stats"]["hedges"] == 1
        assert st["stats"]["hedge_wins"] == 0
        assert st["by_shard"]["s1"]["hedge_lost"] == 1
        assert st["by_shard"]["s1"]["hedge_won"] == 0
        assert st["by_shard"]["s1"]["hedge_cancelled"] == 0
        # exactly ONE relayed request for the tenant — the lost hedge
        # did not double-count
        assert st["stats"]["forwarded"] == 1
        assert st["by_model"][key_lost] == 1
        assert _fwd_count(key_lost) - base_lost == 1
        # the trace id survives hedging: both legs carried the SAME id
        tid = {k.lower(): v for k, v in hdrs.items()}[
            "x-h2o-trace-id"]
        leg_tids = {h.get("X-H2O-Trace-Id")
                    for h in a.posts + b.posts}
        assert leg_tids == {tid}
        # CANCELLED race: hedge still hanging when the primary's 200
        # lands
        code, out, _ = _post(url, f"/3/Predictions/models/{key_cxl}",
                             {"rows": [[1.0]]},
                             headers={"X-H2O-SLO": "interactive"})
        assert code == 200 and out["served_by"] == "primary"
        st = router.snapshot()
        assert st["stats"]["hedges"] == 2
        assert st["by_shard"]["s2"]["hedge_cancelled"] == 1
        assert st["by_model"][key_cxl] == 1
        # every fired hedge settled to exactly one outcome
        settled = sum(r["hedge_won"] + r["hedge_lost"]
                      + r["hedge_cancelled"]
                      for r in st["by_shard"].values())
        assert settled == st["stats"]["hedges"]
    finally:
        hold.set()
        router.stop()
        srv.shutdown()
        srv.server_close()
        a.close()
        b.close()
        c.close()


def test_router_metrics_exposition(monkeypatch):
    monkeypatch.setenv("H2O_TPU_ROUTER_HEALTH_INTERVAL", "30")
    a = _Stub("a", lambda: (200, {"predict": ["ok"]}, None))
    table = {"keys": {"pm": ["s0"]}, "shards": {"s0": [a.url]}}
    srv, router = start_router(table)
    url = f"http://127.0.0.1:{srv.server_address[1]}"
    try:
        code, _, _ = _post(url, "/3/Predictions/models/pm",
                           {"rows": [[1.0]]})
        assert code == 200
        with urllib.request.urlopen(url + "/metrics",
                                    timeout=30) as r:
            p = parse_prometheus_text(r.read().decode())
        assert p[(metric_name("router", "stats", "requests"),
                  ())] >= 1
        assert p[(metric_name("router", "stats", "forwarded"),
                  ())] >= 1
        # tenant keys never become metric NAMES (capped labels only)
        assert not any("by_model" in k[0] for k in p)
        assert any(k[0] == "h2o_build_info" for k in p)
        assert any(k[0] == "h2o_router_route_seconds_bucket"
                   for k in p)
    finally:
        router.stop()
        srv.shutdown()
        srv.server_close()
        a.close()
