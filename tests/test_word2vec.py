import numpy as np
import pytest

from h2o_kubernetes_tpu import Frame
from h2o_kubernetes_tpu.models.word2vec import Word2Vec


def _synthetic_corpus(n_sent=800, seed=0):
    """Two topic clusters: {cat,dog,pet} and {car,road,drive} words
    co-occur within topics, so embeddings must cluster by topic."""
    rng = np.random.default_rng(seed)
    topics = [["cat", "dog", "pet", "fur", "paw"],
              ["car", "road", "drive", "wheel", "fuel"]]
    words = []
    for _ in range(n_sent):
        topic = topics[rng.integers(0, 2)]
        length = rng.integers(4, 9)
        words += list(rng.choice(topic, size=length)) + [None]
    return Frame.from_arrays({"words": np.array(words, dtype=object)})


@pytest.mark.slow
def test_word2vec_topic_clustering(mesh8):
    fr = _synthetic_corpus()
    m = Word2Vec(vec_size=16, epochs=30, min_word_freq=5, seed=1).train(fr)
    assert set(m.vocab) == {"cat", "dog", "pet", "fur", "paw",
                            "car", "road", "drive", "wheel", "fuel"}
    syn = m.find_synonyms("cat", count=4)
    assert set(syn) <= {"dog", "pet", "fur", "paw"}, syn


def test_word2vec_transform(mesh8):
    fr = _synthetic_corpus(n_sent=200, seed=2)
    m = Word2Vec(vec_size=8, epochs=5, min_word_freq=2, seed=3).train(fr)
    doc = Frame.from_arrays({"words": np.array(
        ["cat", "dog", None, "car", "road"], dtype=object)})
    none_vecs = m.transform(doc, aggregate_method="NONE")
    assert none_vecs.shape == (5, 8)
    assert np.isnan(none_vecs[2]).all()       # NA row has no vector
    avg = m.transform(doc, aggregate_method="AVERAGE")
    assert avg.shape == (2, 8)                # two sentences
    assert not np.isnan(avg).any()


def test_word2vec_to_frame(mesh8):
    fr = _synthetic_corpus(n_sent=150, seed=4)
    m = Word2Vec(vec_size=4, epochs=2, min_word_freq=2, seed=5).train(fr)
    wf = m.to_frame()
    assert wf.names[0] == "Word"
    assert wf.ncols == 5
