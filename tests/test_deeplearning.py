import numpy as np
import pytest

from h2o_kubernetes_tpu import Frame
from h2o_kubernetes_tpu.models.deeplearning import DeepLearning


def test_dl_binary_classification(mesh8):
    rng = np.random.default_rng(0)
    n = 4000
    x1 = rng.normal(size=n)
    x2 = rng.normal(size=n)
    y = ((x1 ** 2 + x2 ** 2) < 1.2).astype(int)   # nonlinear boundary
    fr = Frame.from_arrays({"x1": x1, "x2": x2,
                            "y": np.array(["out", "in"])[y]})
    m = DeepLearning(hidden=(32, 32), epochs=60, seed=1).train(
        y="y", training_frame=fr)
    perf = m.model_performance(fr, "y")
    assert perf["auc"] > 0.97      # MLP must learn the circle


def test_dl_regression(mesh8):
    rng = np.random.default_rng(1)
    n = 4000
    x = rng.uniform(-2, 2, size=n)
    y = np.sin(2 * x) + rng.normal(scale=0.05, size=n)
    fr = Frame.from_arrays({"x": x, "y": y})
    m = DeepLearning(hidden=(64, 64), epochs=80, seed=2).train(
        y="y", training_frame=fr)
    assert m.model_performance(fr, "y")["rmse"] < 0.15


def test_dl_multiclass(mesh8):
    rng = np.random.default_rng(2)
    n = 3000
    x1 = rng.normal(size=n)
    x2 = rng.normal(size=n)
    cls = (x1 > 0).astype(int) + (x2 > 0).astype(int)
    fr = Frame.from_arrays({"x1": x1, "x2": x2,
                            "y": np.array(["a", "b", "c"])[cls]})
    m = DeepLearning(hidden=(32,), epochs=40, seed=3).train(
        y="y", training_frame=fr)
    assert m.model_performance(fr, "y")["accuracy"] > 0.9


def test_dl_autoencoder_anomaly(mesh8):
    rng = np.random.default_rng(3)
    n = 3000
    # normal data on a line; anomalies off it
    t = rng.normal(size=n)
    X = np.stack([t, 2 * t, -t], axis=1) + rng.normal(scale=0.05,
                                                      size=(n, 3))
    fr = Frame.from_arrays({f"x{i}": X[:, i] for i in range(3)})
    m = DeepLearning(hidden=(2,), epochs=60, autoencoder=True,
                     seed=4).train(training_frame=fr)
    scores_normal = m.anomaly(fr)
    anomalies = Frame.from_arrays(
        {"x0": np.array([3.0, -2.0]), "x1": np.array([-4.0, 5.0]),
         "x2": np.array([3.0, 2.0])})
    scores_anom = m.anomaly(anomalies)
    assert scores_anom.min() > np.quantile(scores_normal, 0.99)


def test_dl_deepfeatures_shape(mesh8):
    rng = np.random.default_rng(4)
    fr = Frame.from_arrays({"x1": rng.normal(size=500),
                            "x2": rng.normal(size=500),
                            "y": rng.normal(size=500)})
    m = DeepLearning(hidden=(16, 8), epochs=2, seed=5).train(
        y="y", training_frame=fr)
    feats = m.deepfeatures(fr, layer=1)
    assert feats.shape == (500, 8)


def test_dl_autoencoder_predict_reconstruction_frame(mesh8):
    rng = np.random.default_rng(5)
    fr = Frame.from_arrays({f"x{i}": rng.normal(size=300) for i in range(3)})
    m = DeepLearning(hidden=(2,), epochs=3, autoencoder=True, seed=0).train(
        training_frame=fr)
    rec = m.predict(fr)
    assert rec.names == ["reconstr_x0", "reconstr_x1", "reconstr_x2"]
    assert rec.nrows == 300
    perf = m.model_performance(fr)
    assert "mse" in perf


def test_dl_checkpoint_epochs_total(mesh8):
    rng = np.random.default_rng(5)
    n = 256
    x = rng.normal(size=(n, 3)).astype(np.float32)
    y = np.where(x[:, 0] + x[:, 1] > 0, "a", "b")
    fr = Frame.from_arrays({"x0": x[:, 0], "x1": x[:, 1], "x2": x[:, 2],
                            "y": y})
    m1 = DeepLearning(hidden=(8,), epochs=2, seed=0).train(
        y="y", training_frame=fr)
    # epochs is the TOTAL target (like GBM ntrees): <= checkpoint rejected
    with pytest.raises(ValueError, match="must exceed"):
        DeepLearning(hidden=(8,), epochs=2, seed=0,
                     checkpoint=m1).train(y="y", training_frame=fr)
    m2 = DeepLearning(hidden=(8,), epochs=4, seed=0,
                      checkpoint=m1).train(y="y", training_frame=fr)
    assert m2 is not None


def test_dl_scoring_history(mesh8):
    rng = np.random.default_rng(7)
    n = 1200
    x = rng.normal(size=n).astype(np.float32)
    y = np.where(x + rng.normal(scale=0.5, size=n) > 0, "p", "n")
    fr = Frame.from_arrays({"x": x, "y": y})
    m = DeepLearning(hidden=[8], epochs=3, seed=1).train(
        y="y", training_frame=fr)
    assert len(m.scoring_history) == 1
    row = m.scoring_history[0]
    assert row["epochs"] == 3 and 0.5 <= row["train_auc"] <= 1.0
