import jax
import jax.numpy as jnp
import numpy as np
import pytest

from h2o_kubernetes_tpu.runtime import (ROWS, doall, make_mesh, n_row_shards,
                                        shard_rows, use_mesh)


def test_mesh_shape(mesh8):
    assert n_row_shards(mesh8) == 8
    assert len(jax.devices()) == 8


def test_doall_sum_matches_numpy(mesh8):
    rng = np.random.default_rng(0)
    x = rng.normal(size=1600).astype(np.float32)
    xs = shard_rows(x)
    out = doall(lambda s: dict(total=jnp.sum(s), sq=jnp.sum(s * s)), xs)
    np.testing.assert_allclose(float(out["total"]), x.sum(), rtol=1e-4)
    np.testing.assert_allclose(float(out["sq"]), (x * x).sum(), rtol=1e-4)


def test_doall_min_max(mesh8):
    x = np.arange(64, dtype=np.float32) - 17
    xs = shard_rows(x)
    out = doall(lambda s: dict(lo=jnp.min(s), hi=jnp.max(s)),
                xs, reduce=dict(lo="min", hi="max"))
    assert float(out["lo"]) == -17.0
    assert float(out["hi"]) == 46.0


def test_doall_multiple_inputs(mesh8):
    x = np.arange(80, dtype=np.float32)
    w = np.full(80, 0.5, dtype=np.float32)
    out = doall(lambda a, b: jnp.sum(a * b), shard_rows(x), shard_rows(w))
    np.testing.assert_allclose(float(out), (x * 0.5).sum())


def test_shard_rows_pads_to_multiple(mesh8):
    x = np.ones(13, dtype=np.float32)
    xs = shard_rows(x)
    assert xs.shape[0] == 16
    assert np.isnan(np.asarray(xs)[13:]).all()


def test_submesh(mesh8):
    with use_mesh(make_mesh(n_rows=4, devices=jax.devices()[:4])) as m:
        assert n_row_shards(m) == 4
        x = np.arange(8, dtype=np.float32)
        out = doall(lambda s: jnp.sum(s), shard_rows(x))
        assert float(out) == 28.0


# -- config tiers ------------------------------------------------------------

def test_config_env_and_programmatic(monkeypatch):
    import importlib

    import h2o_kubernetes_tpu.config as C

    monkeypatch.setenv("H2O_TPU_NBINS", "64")
    monkeypatch.setenv("H2O_TPU_LOG_LEVEL", "INFO")
    C.CONFIG.clear()
    C._load()
    assert C.get_config("nbins") == 64
    assert C.get_config("log_level") == "INFO"
    # programmatic tier wins
    C.set_config("nbins", 32)
    assert C.get_config("nbins") == 32
    with pytest.raises(KeyError):
        C.get_config("no_such_key")
    with pytest.raises(ValueError):
        C.set_config("hist_impl", "cuda")
    with pytest.raises(ValueError):
        C.set_config("nbins", 3)
    # restore defaults for the rest of the suite
    monkeypatch.delenv("H2O_TPU_NBINS")
    monkeypatch.delenv("H2O_TPU_LOG_LEVEL")
    C.CONFIG.clear()
    C._load()


def test_config_nbins_flows_into_gbm(monkeypatch):
    import h2o_kubernetes_tpu.config as C
    from h2o_kubernetes_tpu.models import GBM

    C.set_config("nbins", 32)
    try:
        assert GBM(ntrees=1).params.nbins == 32
        assert GBM(ntrees=1, nbins=16).params.nbins == 16   # explicit wins
    finally:
        C.set_config("nbins", 256)


def test_config_hist_impl_flows_into_resolver():
    import h2o_kubernetes_tpu.config as C
    from h2o_kubernetes_tpu.ops.histogram import resolve_impl

    C.set_config("hist_impl", "segment")
    try:
        assert resolve_impl("auto") == "segment"
        assert resolve_impl("pallas") == "pallas"   # explicit wins
    finally:
        C.set_config("hist_impl", "auto")


def test_bad_env_hist_impl_is_loud():
    import h2o_kubernetes_tpu.config as C
    from h2o_kubernetes_tpu.ops.histogram import resolve_impl

    C.CONFIG["hist_impl"] = "pallsa"       # env tier typo
    try:
        with pytest.raises(ValueError, match="pallsa"):
            resolve_impl("auto")
    finally:
        C.CONFIG["hist_impl"] = "auto"


def test_bad_log_level_rejected_before_assignment():
    import h2o_kubernetes_tpu.config as C

    before = C.get_config("log_level")
    with pytest.raises(ValueError, match="log level"):
        C.set_config("log_level", "verbose")
    assert C.get_config("log_level") == before


def test_env_config_validation(monkeypatch):
    """A typo'd H2O_TPU_NBINS must give a clear error, not a bare
    int() traceback at import (r2 ADVICE)."""
    from h2o_kubernetes_tpu import config as C

    monkeypatch.setenv("H2O_TPU_NBINS", "lots")
    with pytest.raises(ValueError, match="bad H2O_TPU_NBINS"):
        C._load()
    monkeypatch.setenv("H2O_TPU_NBINS", "3")
    with pytest.raises(ValueError, match=r"\[4, 256\]"):
        C._load()
    monkeypatch.setenv("H2O_TPU_NBINS", "64")
    C._load()
    assert C.CONFIG["nbins"] == 64
    monkeypatch.delenv("H2O_TPU_NBINS")
    C.CONFIG["nbins"] = 256          # restore the default for the suite


def test_doall_cache_key_reuses_jit(mesh8):
    """cache_key makes repeated same-computation doall calls reuse one
    jitted callable — rollups across CV fold frames must not recompile
    (an AutoML run paid ~25 warm recompiles before this)."""
    import logging

    import jax

    from h2o_kubernetes_tpu.frame.frame import Frame

    rng = np.random.default_rng(0)
    fr1 = Frame.from_arrays({"a": rng.normal(size=500).astype(np.float32)})
    fr1.vec("a").rollups()            # warm the cached callable

    msgs = []

    class H(logging.Handler):
        def emit(self, record):
            if "Compiling" in record.getMessage():
                msgs.append(record.getMessage())

    h = H()
    jax.config.update("jax_log_compiles", True)
    logging.getLogger("jax").addHandler(h)
    try:
        # same shape, different Vec object: zero new compiles
        fr2 = Frame.from_arrays(
            {"b": rng.normal(size=500).astype(np.float32)})
        r = fr2.vec("b").rollups()
    finally:
        jax.config.update("jax_log_compiles", False)
        logging.getLogger("jax").removeHandler(h)
    assert msgs == [], msgs
    assert np.isfinite(r["mean"])


def test_host_features_fingerprint(tmp_path):
    """The persistent-XLA-cache dir is keyed by a host CPU feature
    fingerprint: a cache copied from an +amx/+avx512 build host can
    never serve a mismatched AOT binary (SIGILL class, BENCH_r05)."""
    from h2o_kubernetes_tpu.runtime.backend import (
        host_features_fingerprint)

    fp = host_features_fingerprint()
    assert len(fp) == 10 and all(c in "0123456789abcdef" for c in fp)
    assert fp == host_features_fingerprint()          # deterministic
    # flag-set keyed: different features -> different fingerprint,
    # flag ORDER does not matter (kernel ordering isn't stable)
    a = tmp_path / "a"
    a.write_text("flags\t\t: fpu avx2 avx512f amx-tile\n")
    b = tmp_path / "b"
    b.write_text("flags\t\t: fpu avx2\n")
    c = tmp_path / "c"
    c.write_text("flags\t\t: amx-tile avx512f avx2 fpu\n")
    fa = host_features_fingerprint(str(a))
    fb = host_features_fingerprint(str(b))
    fc = host_features_fingerprint(str(c))
    assert fa != fb
    assert fa == fc
    # arm64 spelling
    d = tmp_path / "d"
    d.write_text("Features\t: fp asimd sve\n")
    assert host_features_fingerprint(str(d)) != fa
    # unreadable cpuinfo still fingerprints (platform fallback)
    assert len(host_features_fingerprint(str(tmp_path / "nope"))) == 10


def test_compile_cache_dir_keyed_by_host_features(monkeypatch):
    from h2o_kubernetes_tpu.runtime import backend

    monkeypatch.delenv("JAX_COMPILATION_CACHE_DIR", raising=False)
    backend.enable_persistent_compile_cache()
    got = __import__("os").environ.get("JAX_COMPILATION_CACHE_DIR", "")
    assert f"hostfp-{backend.host_features_fingerprint()}" in got
