import jax
import jax.numpy as jnp
import numpy as np
import pytest

from h2o_kubernetes_tpu.runtime import (ROWS, doall, make_mesh, n_row_shards,
                                        shard_rows, use_mesh)


def test_mesh_shape(mesh8):
    assert n_row_shards(mesh8) == 8
    assert len(jax.devices()) == 8


def test_doall_sum_matches_numpy(mesh8):
    rng = np.random.default_rng(0)
    x = rng.normal(size=1600).astype(np.float32)
    xs = shard_rows(x)
    out = doall(lambda s: dict(total=jnp.sum(s), sq=jnp.sum(s * s)), xs)
    np.testing.assert_allclose(float(out["total"]), x.sum(), rtol=1e-4)
    np.testing.assert_allclose(float(out["sq"]), (x * x).sum(), rtol=1e-4)


def test_doall_min_max(mesh8):
    x = np.arange(64, dtype=np.float32) - 17
    xs = shard_rows(x)
    out = doall(lambda s: dict(lo=jnp.min(s), hi=jnp.max(s)),
                xs, reduce=dict(lo="min", hi="max"))
    assert float(out["lo"]) == -17.0
    assert float(out["hi"]) == 46.0


def test_doall_multiple_inputs(mesh8):
    x = np.arange(80, dtype=np.float32)
    w = np.full(80, 0.5, dtype=np.float32)
    out = doall(lambda a, b: jnp.sum(a * b), shard_rows(x), shard_rows(w))
    np.testing.assert_allclose(float(out), (x * 0.5).sum())


def test_shard_rows_pads_to_multiple(mesh8):
    x = np.ones(13, dtype=np.float32)
    xs = shard_rows(x)
    assert xs.shape[0] == 16
    assert np.isnan(np.asarray(xs)[13:]).all()


def test_submesh(mesh8):
    with use_mesh(make_mesh(n_rows=4, devices=jax.devices()[:4])) as m:
        assert n_row_shards(m) == 4
        x = np.arange(8, dtype=np.float32)
        out = doall(lambda s: jnp.sum(s), shard_rows(x))
        assert float(out) == 28.0
