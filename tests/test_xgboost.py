"""XGBoost-hist estimator tests (config #3: hist + lambdarank)."""

import numpy as np
import pytest

import h2o_kubernetes_tpu as h2o
from h2o_kubernetes_tpu import metrics as M
from h2o_kubernetes_tpu.models import XGBoost


def _binary_frame(n=4000, seed=0):
    rng = np.random.default_rng(seed)
    x = {f"x{i}": rng.normal(size=n).astype(np.float32) for i in range(5)}
    logit = 1.5 * x["x0"] - 1.0 * x["x1"] + 0.5 * x["x2"] * x["x3"]
    y = (logit + rng.normal(scale=0.7, size=n)) > 0
    x["y"] = np.where(y, "yes", "no")
    return h2o.Frame.from_arrays(x)


def _rank_frame(n_groups=60, docs=25, seed=0):
    """Synthetic LTR data: relevance 0-4 driven by two features."""
    rng = np.random.default_rng(seed)
    n = n_groups * docs
    f1 = rng.normal(size=n).astype(np.float32)
    f2 = rng.normal(size=n).astype(np.float32)
    f3 = rng.normal(size=n).astype(np.float32)  # noise
    raw = 1.2 * f1 - 0.8 * f2 + rng.normal(scale=0.4, size=n)
    rel = np.clip(np.digitize(raw, [-1.5, -0.5, 0.5, 1.5]), 0, 4)
    group = np.repeat(np.arange(n_groups), docs)
    fr = h2o.Frame.from_arrays({
        "f1": f1, "f2": f2, "f3": f3,
        "rel": rel.astype(np.float32), "qid": group.astype(np.float32)})
    return fr, rel, group


def test_binary_classification(mesh8):
    fr = _binary_frame()
    m = XGBoost(ntrees=20, max_depth=4, learn_rate=0.3, seed=1).train(
        y="y", training_frame=fr)
    perf = m.model_performance(fr, "y")
    assert perf["auc"] > 0.9
    assert m.algo == "xgboost"


def test_objective_aliases(mesh8):
    fr = _binary_frame(n=1000)
    m = XGBoost(ntrees=5, objective="binary:logistic").train(
        y="y", training_frame=fr)
    assert m.distribution == "bernoulli"
    with pytest.raises(ValueError):
        XGBoost(objective="nope:nope")
    with pytest.raises(ValueError):
        XGBoost(booster="dart")


def test_regression_squarederror(mesh8):
    rng = np.random.default_rng(2)
    n = 3000
    x0 = rng.normal(size=n).astype(np.float32)
    x1 = rng.uniform(-2, 2, size=n).astype(np.float32)
    y = 3.0 * x0 + np.sin(2 * x1) + rng.normal(scale=0.1, size=n)
    fr = h2o.Frame.from_arrays({"x0": x0, "x1": x1, "y": y})
    m = XGBoost(ntrees=40, max_depth=5, learn_rate=0.3,
                objective="reg:squarederror").train(y="y", training_frame=fr)
    perf = m.model_performance(fr, "y")
    assert perf["r2"] > 0.95


@pytest.mark.slow
def test_min_child_weight_regularizes(mesh8):
    """High hessian floor must forbid tiny leaves (fewer splits)."""
    fr = _binary_frame(n=600, seed=3)
    loose = XGBoost(ntrees=5, max_depth=6, min_child_weight=0.0,
                    seed=1).train(y="y", training_frame=fr)
    tight = XGBoost(ntrees=5, max_depth=6, min_child_weight=30.0,
                    seed=1).train(y="y", training_frame=fr)
    n_loose = int(np.asarray(loose.trees.is_split).sum())
    n_tight = int(np.asarray(tight.trees.is_split).sum())
    assert n_tight < n_loose


def test_lambdarank_ndcg_improves(mesh8):
    fr, rel, group = _rank_frame()
    m = XGBoost(ntrees=30, max_depth=4, learn_rate=0.3,
                objective="rank:ndcg", seed=0).train(
        y="rel", training_frame=fr, group_column="qid")
    score = m.predict_raw(fr)
    got = M.ndcg(rel, score, group, k=10)
    random_ndcg = M.ndcg(rel, np.random.default_rng(0).normal(size=len(rel)),
                         group, k=10)
    ideal_on_f1 = M.ndcg(rel, fr.vec("f1").to_numpy(), group, k=10)
    assert got > random_ndcg + 0.1
    assert got > ideal_on_f1           # beats the single best raw feature
    perf = m.model_performance(fr, "rel")
    assert perf["ndcg@10"] == pytest.approx(got, abs=1e-6)


def test_rank_pairwise_runs(mesh8):
    fr, rel, group = _rank_frame(n_groups=20, docs=10, seed=5)
    m = XGBoost(ntrees=10, objective="rank:pairwise", seed=0).train(
        y="rel", training_frame=fr, group_column="qid")
    score = m.predict_raw(fr)
    assert M.ndcg(rel, score, group) > M.ndcg(
        rel, np.zeros_like(rel), group) - 1e-9
    # group column must not leak into features
    assert "qid" not in m.feature_names


def test_rank_with_enum_relevance(mesh8):
    """Graded relevance stored as a categorical must still rank (and
    score) as a single-output model, not take the multinomial path."""
    fr, rel, group = _rank_frame(n_groups=15, docs=8, seed=7)
    fr["rel_cat"] = h2o.Vec.from_numpy(
        rel.astype(np.int32), domain=[str(i) for i in range(5)])
    m = XGBoost(ntrees=3, objective="rank:ndcg", seed=0).train(
        y="rel_cat", training_frame=fr, x=["f1", "f2", "f3"],
        group_column="qid")
    score = m.predict_raw(fr)          # crashed before nclasses fix
    assert score.shape == (fr.nrows,)


def test_h2o_param_aliases(mesh8):
    """H2O spellings (min_rows, sample_rate, …) map to XGBoost params."""
    m = XGBoost(ntrees=2, min_rows=5.0, sample_rate=0.8,
                col_sample_rate_per_tree=0.9)
    assert m.params.min_child_weight == 5.0
    assert m.params.sample_rate == 0.8
    assert m.params.col_sample_rate_per_tree == 0.9


def test_rank_requires_group(mesh8):
    fr, _, _ = _rank_frame(n_groups=5, docs=5)
    with pytest.raises(ValueError, match="group_column"):
        XGBoost(ntrees=2, objective="rank:ndcg").train(
            y="rel", training_frame=fr)


def test_ndcg_metric_known_answer():
    # two groups; perfect ordering in g0, inverted in g1
    y = np.array([2, 1, 0, 0, 1, 2])
    s = np.array([3.0, 2.0, 1.0, 3.0, 2.0, 1.0])
    g = np.array([0, 0, 0, 1, 1, 1])
    perfect = M.ndcg(y[:3], s[:3], g[:3])
    assert perfect == pytest.approx(1.0)
    mixed = M.ndcg(y, s, g)
    assert 0.5 < mixed < 1.0
