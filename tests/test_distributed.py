"""Multi-host (DCN) proof: 2 real processes, one global mesh, one psum.

The reference scales across hosts with one JVM per pod gossiping over
TCP (SURVEY.md §2d multi-host row, §5.8); the TPU-native equivalent is
`jax.distributed.initialize` + collectives that ride DCN. This test is
the localhost-scale version of that claim — the same trick the
reference's own multi-JVM localhost tests use (§4b): no mocks, a real
2-process cluster.
"""

import os
import socket
import subprocess
import sys

import pytest

_WORKER = os.path.join(os.path.dirname(__file__), "dcn_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def test_two_process_dcn_psum():  # bounded by communicate(timeout=)
    port = _free_port()
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(_WORKER)) + \
        os.pathsep + env.get("PYTHONPATH", "")
    procs = [subprocess.Popen(
        [sys.executable, _WORKER, str(port), str(i)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
        text=True) for i in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=240)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail(f"DCN workers hung; partial output: {outs}")
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} rc={p.returncode}:\n{out}"
        assert "DCN_OK" in out, f"worker {i} output:\n{out}"
