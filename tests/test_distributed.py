"""Multi-host (DCN) proof: 2 real processes, one global mesh, one psum.

The reference scales across hosts with one JVM per pod gossiping over
TCP (SURVEY.md §2d multi-host row, §5.8); the TPU-native equivalent is
`jax.distributed.initialize` + collectives that ride DCN. This test is
the localhost-scale version of that claim — the same trick the
reference's own multi-JVM localhost tests use (§4b): no mocks, a real
2-process cluster.
"""

import os
import socket
import subprocess
import sys

import pytest

_WORKER = os.path.join(os.path.dirname(__file__), "dcn_worker.py")


def _cpu_multiprocess_supported() -> bool:
    """jax < 0.5 CPU backends reject multi-process computations
    outright ("Multiprocess computations aren't implemented on the CPU
    backend") — the cross-host CPU collective transport landed later.
    The DCN tests are then unrunnable on this toolchain, not broken."""
    import jax

    ver = tuple(int(x) for x in jax.__version__.split(".")[:2])
    return ver >= (0, 5)


pytestmark = pytest.mark.skipif(
    not _cpu_multiprocess_supported(),
    reason="this jax's CPU backend cannot run multi-process "
           "computations (needs jax >= 0.5 cross-host CPU collectives)")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _run_workers(mode: str, timeout: float = 240,
                 expect_rc=(0, 0)) -> list[str]:
    port = _free_port()
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(_WORKER)) + \
        os.pathsep + env.get("PYTHONPATH", "")
    procs = [subprocess.Popen(
        [sys.executable, _WORKER, str(port), str(i), mode],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, env=env,
        text=True) for i in range(2)]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail(f"DCN {mode} workers hung; partial output: {outs}")
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == expect_rc[i], \
            f"worker {i} rc={p.returncode} (want {expect_rc[i]}):\n{out}"
    return outs


def test_two_process_dcn_psum():  # bounded by communicate(timeout=)
    outs = _run_workers("psum")
    for i, out in enumerate(outs):
        assert "DCN_OK" in out, f"worker {i} output:\n{out}"


@pytest.mark.slow
def test_two_process_gbm_train():
    """A FULL fused-scan GBM train across 2 jax.distributed processes:
    every tree level's histogram psum crosses the process boundary, and
    both controllers must end with the identical reduced model (the
    round-2 DRF worker-crash class of defect lives on this path, which
    the virtual single-process mesh cannot reach)."""
    outs = _run_workers("gbm", timeout=600)
    aucs = set()
    for i, out in enumerate(outs):
        assert "DCN_GBM_OK" in out, f"worker {i} output:\n{out}"
        aucs.add(out.split("auc=")[1].split()[0])
    assert len(aucs) == 1, f"processes disagree on the model: {aucs}"


@pytest.mark.slow
def test_two_process_glm_irlsm():
    """Binomial IRLSM across 2 processes: the distributed Gram
    accumulation (XᵀWX psum) rides DCN every iteration and the solved
    coefficients must recover the generating model."""
    outs = _run_workers("glm", timeout=600)
    x1s = set()
    for i, out in enumerate(outs):
        assert "DCN_GLM_OK" in out, f"worker {i} output:\n{out}"
        x1s.add(out.split("x1=")[1].split()[0])
    assert len(x1s) == 1, f"processes disagree on beta: {x1s}"


@pytest.mark.slow
def test_process_drop_fails_fast():
    """Member loss mid-session: process 1 dies after cloud formation;
    process 0's heartbeat must flip unhealthy and the next train must
    raise ClusterHealthError (reference semantics: the locked cloud
    becomes unusable, jobs fail cleanly — SURVEY.md §5.3)."""
    outs = _run_workers("drop", timeout=600, expect_rc=(0, 17))
    assert "DCN_DROP_OK" in outs[0], f"worker 0 output:\n{outs[0]}"
    assert "DCN_DROP_EXITING" in outs[1], f"worker 1 output:\n{outs[1]}"
