"""REST v3 adapter (SURVEY.md §2b C9): the full client loop over HTTP —
import → inspect → build → poll → predict — against a live server, the
way h2o-py drives the reference's RequestServer."""

import json
import socket
import urllib.parse
import urllib.request

import numpy as np
import pytest

import h2o_kubernetes_tpu as h2o
from h2o_kubernetes_tpu import rest


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.fixture
def server(mesh8):
    port = _free_port()
    srv = rest.start_server(port)
    yield f"http://127.0.0.1:{port}"
    srv.shutdown()
    rest.FRAMES.clear()
    rest.MODELS.clear()


def _get(base, path):
    with urllib.request.urlopen(base + path, timeout=60) as r:
        return json.loads(r.read())


def _post(base, route, **params):
    data = urllib.parse.urlencode(params).encode()
    req = urllib.request.Request(base + route, data=data, method="POST")
    with urllib.request.urlopen(req, timeout=600) as r:
        return json.loads(r.read())


def test_cloud_and_jobs(server):
    cloud = _get(server, "/3/Cloud")
    assert cloud["cloud_size"] == 8 and cloud["cloud_healthy"]
    jobs = _get(server, "/3/Jobs")
    assert "jobs" in jobs


def test_full_rest_loop(server, tmp_path):
    rng = np.random.default_rng(3)
    n = 400
    x = rng.normal(size=n)
    y = np.where(x + rng.normal(scale=0.5, size=n) > 0, "p", "n")
    fr = h2o.Frame.from_arrays({"x": x.astype(np.float32), "y": y})
    csv = tmp_path / "train.csv"
    h2o.export_file(fr, str(csv))

    # import → frame appears with schema
    imp = _post(server, "/3/ImportFiles", path=str(csv),
                destination_frame="train")
    assert imp["rows"] == n
    frames = _get(server, "/3/Frames")
    assert any(f["frame_id"]["name"] == "train"
               for f in frames["frames"])
    summ = _get(server, "/3/Frames/train/summary")
    assert "x" in summ["summary"]

    # build a GBM over REST; the call returns when the job finishes
    job = _post(server, "/3/ModelBuilders/gbm", training_frame="train",
                response_column="y", ntrees="10", max_depth="3",
                model_id="gbm_rest")
    assert job["job"]["status"] == "DONE", job
    models = _get(server, "/3/Models")
    assert any(m["model_id"]["name"] == "gbm_rest"
               for m in models["models"])

    # score over REST → prediction frame registered
    pred = _post(server, "/3/Predictions/models/gbm_rest/frames/train")
    assert pred["rows"] == n
    pname = pred["predictions_frame"]["name"]
    assert _get(server, f"/3/Frames/{pname}")["rows"] == n


def test_rest_errors(server):
    with pytest.raises(urllib.error.HTTPError) as e:
        _get(server, "/3/Frames/nope")
    assert e.value.code == 404
    with pytest.raises(urllib.error.HTTPError) as e:
        _post(server, "/3/ModelBuilders/notanalgo", training_frame="x")
    assert e.value.code == 404
