"""REST v3 adapter (SURVEY.md §2b C9): the full client loop over HTTP —
import → inspect → build → poll → predict — against a live server, the
way h2o-py drives the reference's RequestServer."""

import json
import socket
import urllib.parse
import urllib.request

import numpy as np
import pytest

import h2o_kubernetes_tpu as h2o
from h2o_kubernetes_tpu import rest


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.fixture
def server(mesh8):
    port = _free_port()
    srv = rest.start_server(port)
    yield f"http://127.0.0.1:{port}"
    srv.shutdown()
    rest.FRAMES.clear()
    rest.MODELS.clear()
    rest.AUTOML.clear()
    rest.GRIDS.clear()


def _get(base, path):
    with urllib.request.urlopen(base + path, timeout=60) as r:
        return json.loads(r.read())


def _post(base, route, **params):
    data = urllib.parse.urlencode(params).encode()
    req = urllib.request.Request(base + route, data=data, method="POST")
    with urllib.request.urlopen(req, timeout=600) as r:
        return json.loads(r.read())


def test_cloud_and_jobs(server):
    cloud = _get(server, "/3/Cloud")
    assert cloud["cloud_size"] == 8 and cloud["cloud_healthy"]
    jobs = _get(server, "/3/Jobs")
    assert "jobs" in jobs


def test_full_rest_loop(server, tmp_path):
    rng = np.random.default_rng(3)
    n = 400
    x = rng.normal(size=n)
    y = np.where(x + rng.normal(scale=0.5, size=n) > 0, "p", "n")
    fr = h2o.Frame.from_arrays({"x": x.astype(np.float32), "y": y})
    csv = tmp_path / "train.csv"
    h2o.export_file(fr, str(csv))

    # import → frame appears with schema
    imp = _post(server, "/3/ImportFiles", path=str(csv),
                destination_frame="train")
    assert imp["rows"] == n
    frames = _get(server, "/3/Frames")
    assert any(f["frame_id"]["name"] == "train"
               for f in frames["frames"])
    summ = _get(server, "/3/Frames/train/summary")
    assert "x" in summ["summary"]

    # build a GBM over REST; the call returns when the job finishes
    job = _post(server, "/3/ModelBuilders/gbm", training_frame="train",
                response_column="y", ntrees="10", max_depth="3",
                model_id="gbm_rest")
    assert job["job"]["status"] == "DONE", job
    models = _get(server, "/3/Models")
    assert any(m["model_id"]["name"] == "gbm_rest"
               for m in models["models"])

    # score over REST → prediction frame registered
    pred = _post(server, "/3/Predictions/models/gbm_rest/frames/train")
    assert pred["rows"] == n
    pname = pred["predictions_frame"]["name"]
    assert _get(server, f"/3/Frames/{pname}")["rows"] == n


def test_rest_errors(server):
    with pytest.raises(urllib.error.HTTPError) as e:
        _get(server, "/3/Frames/nope")
    assert e.value.code == 404
    with pytest.raises(urllib.error.HTTPError) as e:
        _post(server, "/3/ModelBuilders/notanalgo", training_frame="x")
    assert e.value.code == 404


def _post_json(base, route, payload):
    data = json.dumps(payload).encode()
    req = urllib.request.Request(
        base + route, data=data, method="POST",
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=600) as r:
        return json.loads(r.read())


def _delete(base, path):
    req = urllib.request.Request(base + path, method="DELETE")
    with urllib.request.urlopen(req, timeout=60) as r:
        return json.loads(r.read())


def _mkframe(server, tmp_path, n=300, seed=3, name="train"):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=n)
    y = np.where(x + rng.normal(scale=0.5, size=n) > 0, "p", "n")
    fr = h2o.Frame.from_arrays({"x": x.astype(np.float32), "y": y})
    csv = tmp_path / f"{name}.csv"
    h2o.export_file(fr, str(csv))
    _post(server, "/3/ImportFiles", path=str(csv),
          destination_frame=name)
    return fr


def test_leader_readiness(server, monkeypatch):
    assert _get(server, "/kubernetes/isLeaderNode")["leader"] is True
    assert _get(server, "/3/IsLeaderNode")["leader"] is True
    monkeypatch.setenv("H2O_TPU_PROCESS_ID", "2")
    with pytest.raises(urllib.error.HTTPError) as e:
        _get(server, "/kubernetes/isLeaderNode")
    assert e.value.code == 503      # non-leader pods must NOT go Ready


def test_leader_env_runtime_crosscheck(monkeypatch):
    """A pod whose env CLAIMS leadership but whose runtime process
    index disagrees (or vice versa) must fail readiness + log the
    mismatch — pod metadata alone cannot make a non-leader Ready."""
    from h2o_kubernetes_tpu import rest
    from h2o_kubernetes_tpu.diagnostics import timeline

    # runtime not initialized: env alone decides (single-process cloud)
    monkeypatch.setattr(rest, "_runtime_process_index", lambda: None)
    monkeypatch.setenv("H2O_TPU_PROCESS_ID", "0")
    assert rest._is_leader() is True

    # env says leader, runtime says process 3: spoofed pod -> 503 path
    monkeypatch.setattr(rest, "_runtime_process_index", lambda: 3)
    assert rest._is_leader() is False
    assert any(e["kind"] == "leader_mismatch"
               for e in timeline.events())

    # env says non-leader but runtime IS process 0: also a mismatch
    monkeypatch.setenv("H2O_TPU_PROCESS_ID", "1")
    monkeypatch.setattr(rest, "_runtime_process_index", lambda: 0)
    assert rest._is_leader() is False

    # agreement on leadership passes
    monkeypatch.setenv("H2O_TPU_PROCESS_ID", "0")
    assert rest._is_leader() is True
    # agreement on NON-leadership still 503s
    monkeypatch.setenv("H2O_TPU_PROCESS_ID", "2")
    monkeypatch.setattr(rest, "_runtime_process_index", lambda: 2)
    assert rest._is_leader() is False


def test_runtime_process_index_without_distributed():
    # in-process truth: no jax.distributed here, so the probe must
    # report None (and never initialize a backend to find out)
    from h2o_kubernetes_tpu import rest

    assert rest._runtime_process_index() is None


def test_timeline(server):
    from h2o_kubernetes_tpu.diagnostics import timeline

    timeline.record("test_event", msg="hello")
    ev = _get(server, "/3/Timeline")["events"]
    assert any(e["kind"] == "test_event" for e in ev)


def test_delete_verbs(server, tmp_path):
    _mkframe(server, tmp_path, name="delme")
    _post(server, "/3/ModelBuilders/gbm", training_frame="delme",
          response_column="y", ntrees="3", max_depth="2",
          model_id="gbm_del")
    assert _delete(server, "/3/Frames/delme")["removed"]
    with pytest.raises(urllib.error.HTTPError):
        _get(server, "/3/Frames/delme")
    assert _delete(server, "/3/Models/gbm_del")["removed"]
    with pytest.raises(urllib.error.HTTPError):
        _delete(server, "/3/Models/gbm_del")     # already gone -> 404


@pytest.mark.slow
def test_automl_over_rest(server, tmp_path):
    """VERDICT r2 item 5: a REST client drives an AutoML build to
    completion over HTTP and reads the leaderboard."""
    _mkframe(server, tmp_path, n=300, name="amltrain")
    out = _post_json(server, "/3/AutoML", {
        "training_frame": "amltrain", "response_column": "y",
        "max_models": 2, "nfolds": 3, "seed": 0,
        "project_name": "rest_aml"})
    assert out["job"]["status"] == "DONE", out
    got = _get(server, "/3/AutoML/rest_aml")
    assert got["leaderboard"], got
    leader = got["leader"]["name"]
    assert leader
    # the leader is queryable and scoreable like any model
    models = _get(server, "/3/Models")
    assert any(m["model_id"]["name"] == leader for m in models["models"])
    pred = _post(server, f"/3/Predictions/models/{leader}/frames/amltrain")
    assert pred["rows"] == 300


@pytest.mark.slow
def test_grid_over_rest(server, tmp_path):
    _mkframe(server, tmp_path, n=300, name="gridtrain")
    out = _post_json(server, "/99/Grid/gbm", {
        "training_frame": "gridtrain", "response_column": "y",
        "grid_id": "g1", "ntrees": 4, "max_depth": 2,
        "hyper_parameters": {"learn_rate": [0.1, 0.3]}})
    assert out["job"]["status"] == "DONE", out
    got = _get(server, "/99/Grids/g1")
    assert len(got["model_ids"]) == 2
    assert got["summary"][0]["model_id"]


def test_flow_ui_served(server):
    """The root path serves the self-contained Flow page (the h2o-web
    analog) with no external asset references (air-gapped pods)."""
    for route in ("/", "/flow"):
        with urllib.request.urlopen(server + route, timeout=30) as r:
            assert r.headers["Content-Type"].startswith("text/html")
            body = r.read().decode()
        assert "H2O-TPU Flow" in body
        # self-contained: no external script/style/font loads
        assert "http://" not in body.replace(server, "")
        assert "https://" not in body
        for verb in ("/3/Cloud", "/3/Frames", "/3/ModelBuilders/",
                     "/99/AutoMLBuilder", "/3/Jobs", "/3/Timeline"):
            assert verb in body, f"Flow page lost the {verb} flow"


def test_model_detail_fields(server, tmp_path):
    """GET /3/Models/{key} carries scoring history, varimp and CV
    metrics — what the Flow model page renders."""
    _mkframe(server, tmp_path, n=300, name="detailtrain")
    _post_json(server, "/3/ModelBuilders/gbm", {
        "training_frame": "detailtrain", "response_column": "y",
        "model_id": "detail_gbm", "ntrees": 3, "max_depth": 3,
        "nfolds": 3})
    got = _get(server, "/3/Models/detail_gbm")
    assert got["algo"] == "gbm" and got["nclasses"] == 2
    assert len(got["scoring_history"]) >= 1
    assert got["variable_importances"]["x"] == 1.0
    cv = got["cross_validation_metrics"]
    assert cv and 0.5 <= cv["auc"] <= 1.0


def test_nan_metrics_serialize_as_null(server):
    """Non-finite metric values must reach clients as JSON null —
    json.dumps' bare NaN is rejected by strict parsers (fetch,
    jsonlite) and would blank the Flow model page."""
    rest.MODELS["nan_model"] = type("M", (), {
        "algo": "gbm", "nclasses": 2,
        "scoring_history": [{"ntrees": 1, "train_auc": float("nan")}],
        "validation_metrics": {"auc": float("inf")},
    })()
    try:
        raw = urllib.request.urlopen(
            server + "/3/Models/nan_model", timeout=30).read().decode()
        assert "NaN" not in raw and "Infinity" not in raw
        got = json.loads(raw)       # strict parse must succeed
        assert got["scoring_history"][0]["train_auc"] is None
        assert got["validation_metrics"]["auc"] is None
    finally:
        rest.MODELS.pop("nan_model", None)


def test_mojo_download_route(server, tmp_path):
    """GET /3/Models/{id}/mojo streams a loadable artifact (h2o-py's
    download_mojo surface)."""
    import urllib.error

    _mkframe(server, tmp_path, n=300, name="mojotrain")
    _post_json(server, "/3/ModelBuilders/gbm", {
        "training_frame": "mojotrain", "response_column": "y",
        "model_id": "mojo_gbm", "ntrees": 3, "max_depth": 3})
    with urllib.request.urlopen(
            server + "/3/Models/mojo_gbm/mojo", timeout=120) as r:
        assert r.headers["Content-Type"] == "application/octet-stream"
        blob = r.read()
    assert len(blob) > 100
    p = tmp_path / "dl.mojo"
    p.write_bytes(blob)
    mj = h2o.import_mojo(str(p))
    assert mj.predict is not None
    # unknown sub-verb stays a clean 404
    try:
        urllib.request.urlopen(server + "/3/Models/mojo_gbm/nope",
                               timeout=30)
        raise AssertionError("expected 404")
    except urllib.error.HTTPError as e:
        assert e.code == 404


def test_inline_scoring_row_cap(server, tmp_path, monkeypatch):
    """H2O_TPU_SCORE_MAX_ROWS: an oversized inline payload is a clean
    413, never a device dispatch that could trip the locked cloud."""
    _mkframe(server, tmp_path, n=300, name="captrain")
    _post(server, "/3/ModelBuilders/gbm", training_frame="captrain",
          response_column="y", ntrees="3", max_depth="2",
          model_id="cap_gbm")
    monkeypatch.setenv("H2O_TPU_SCORE_MAX_ROWS", "2")
    with pytest.raises(urllib.error.HTTPError) as e:
        _post_json(server, "/3/Predictions/models/cap_gbm",
                   {"rows": [{"x": 0.1}, {"x": 0.2}, {"x": 0.3}]})
    assert e.value.code == 413
    out = _post_json(server, "/3/Predictions/models/cap_gbm",
                     {"rows": [{"x": 0.1}, {"x": 0.2}]})
    assert out["rows"] == 2
    # 0 / inf / garbage read as UNCAPPED, never a dead dispatcher
    for raw in ("0", "inf", "-3"):
        monkeypatch.setenv("H2O_TPU_SCORE_MAX_ROWS", raw)
        out = _post_json(server, "/3/Predictions/models/cap_gbm",
                         {"rows": [{"x": 0.1}, {"x": 0.2}, {"x": 0.3}]})
        assert out["rows"] == 3, raw


def test_inline_scoring_route(server, tmp_path):
    """POST /3/Predictions/models/{key} with JSON rows: the serving
    fast path (no frame registration) — predictions match
    score_numpy, unseen levels/nulls read as NA."""
    _mkframe(server, tmp_path, n=300, name="srvtrain")
    _post(server, "/3/ModelBuilders/gbm", training_frame="srvtrain",
          response_column="y", ntrees="4", max_depth="3",
          model_id="srv_gbm")
    out = _post_json(server, "/3/Predictions/models/srv_gbm", {
        "rows": [{"x": 0.5}, {"x": -1.0}, {"x": None}]})
    assert out["rows"] == 3
    assert set(out["predict"]) <= {"p", "n"}
    m = rest.MODELS["srv_gbm"]
    want = m.score_numpy(
        np.array([[0.5], [-1.0], [np.nan]], np.float32))
    np.testing.assert_allclose(out["pp"], want[:, 1], rtol=1e-6)
    # list-shaped rows with explicit column order
    out2 = _post_json(server, "/3/Predictions/models/srv_gbm", {
        "rows": [[0.5], [-1.0]], "columns": ["x"]})
    assert out2["predict"] == out["predict"][:2]
    # malformed payloads stay clean 400s
    with pytest.raises(urllib.error.HTTPError) as e:
        _post_json(server, "/3/Predictions/models/srv_gbm", {})
    assert e.value.code == 400
    with pytest.raises(urllib.error.HTTPError) as e:
        _post_json(server, "/3/Predictions/models/srv_gbm",
                   {"rows": [[1.0]]})     # list rows, no columns
    assert e.value.code == 400
    with pytest.raises(urllib.error.HTTPError) as e:
        # a LATER row omitting a feature: 400, not silent NA scoring
        _post_json(server, "/3/Predictions/models/srv_gbm",
                   {"rows": [{"x": 1.0}, {}]})
    assert e.value.code == 400
    # models without the raw-matrix serving contract: clean 400
    rest.MODELS["noserve"] = type("M", (), {"algo": "kmeans"})()
    try:
        with pytest.raises(urllib.error.HTTPError) as e:
            _post_json(server, "/3/Predictions/models/noserve",
                       {"rows": [{"x": 1.0}]})
        assert e.value.code == 400
    finally:
        rest.MODELS.pop("noserve", None)


def test_concurrent_predictions_smoke(server, tmp_path):
    """Tier-1 micro-batcher smoke: a threaded server serving 2+
    concurrent predict requests through the batching path."""
    import threading

    _mkframe(server, tmp_path, n=300, name="conctrain")
    _post(server, "/3/ModelBuilders/gbm", training_frame="conctrain",
          response_column="y", ntrees="3", max_depth="2",
          model_id="conc_gbm")
    s0 = dict(rest.BATCHER.stats)
    results = [None, None]

    def hit(i):
        results[i] = _post_json(
            server, "/3/Predictions/models/conc_gbm",
            {"rows": [{"x": float(i)}, {"x": -float(i)}]})

    ts = [threading.Thread(target=hit, args=(i,)) for i in range(2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert all(r is not None and r["rows"] == 2 for r in results)
    s1 = rest.BATCHER.stats
    assert s1["requests"] >= s0["requests"] + 2
    assert s1["batches"] >= s0["batches"] + 1
    # per-request results are the per-request slices, not the batch
    m = rest.MODELS["conc_gbm"]
    for i, r in enumerate(results):
        want = m.score_numpy(
            np.array([[float(i)], [-float(i)]], np.float32))
        np.testing.assert_allclose(r["pp"], want[:, 1], rtol=1e-6)


def test_job_poll_reaps_dead_worker(server):
    """A worker thread that dies without reporting must read as FAILED
    on the next /3/Jobs poll — clients can never hang forever."""
    import threading

    from h2o_kubernetes_tpu.automl import JOBS, Job

    job = Job(dest="reap_dead", description="doomed worker").start()
    t = threading.Thread(target=lambda: None)
    t.start()
    t.join()
    job._thread = t                  # dead thread, job still RUNNING
    try:
        jobs = _get(server, "/3/Jobs")["jobs"]
        mine = [j for j in jobs if j["dest"] == "reap_dead"]
        assert mine and mine[0]["status"] == "FAILED"
        assert "died" in mine[0]["msg"]
    finally:
        JOBS.pop("reap_dead", None)


def test_job_poll_timeout(server, monkeypatch):
    """H2O_TPU_JOB_TIMEOUT: a RUNNING job older than the timeout is
    terminally FAILED on poll (worker unaccounted for)."""
    import time as _time

    from h2o_kubernetes_tpu.automl import JOBS, Job

    job = Job(dest="reap_old", description="stuck").start()
    job.start_time = _time.time() - 3600
    try:
        # no timeout configured: stays RUNNING
        jobs = _get(server, "/3/Jobs")["jobs"]
        assert [j for j in jobs
                if j["dest"] == "reap_old"][0]["status"] == "RUNNING"
        monkeypatch.setenv("H2O_TPU_JOB_TIMEOUT", "60")
        jobs = _get(server, "/3/Jobs")["jobs"]
        mine = [j for j in jobs if j["dest"] == "reap_old"]
        assert mine[0]["status"] == "FAILED"
        assert "timeout" in mine[0]["msg"]
        # FAILED is terminal: the (still live) worker finishing later
        # must not resurrect the job to DONE under pollers' feet
        job.done()
        assert job.status == "FAILED"
    finally:
        JOBS.pop("reap_old", None)


@pytest.mark.slow
def test_rest_scoring_load(server, tmp_path):
    """Closed-loop REST scoring load (tools/score_load.py) against a
    live server: no errors, and concurrent requests coalesce into
    fewer micro-batches than requests."""
    from tools.score_load import run_load

    _mkframe(server, tmp_path, n=500, name="loadtrain")
    _post(server, "/3/ModelBuilders/gbm", training_frame="loadtrain",
          response_column="y", ntrees="5", max_depth="3",
          model_id="load_gbm")
    s0 = dict(rest.BATCHER.stats)
    out = run_load(server, "load_gbm", ["x"], concurrency=6,
                   rows_per_request=16, seconds=2.0)
    assert out["errors"] == 0, out
    assert out["requests"] > 0
    s1 = rest.BATCHER.stats
    new_req = s1["requests"] - s0["requests"]
    new_bat = s1["batches"] - s0["batches"]
    assert new_req > new_bat, (new_req, new_bat)   # coalescing happened


def test_encoded_keys_across_routes(server):
    """Registry keys are percent-decoded on the Frames GET/summary/
    DELETE routes and the Models detail route — clients URL-encode ids
    (the R client always does)."""
    rng = np.random.default_rng(1)
    fr = h2o.Frame.from_arrays(
        {"x": rng.normal(size=100).astype(np.float32)})
    rest.FRAMES["my frame.hex"] = fr
    got = _get(server, "/3/Frames/my%20frame.hex")
    assert got["frame_id"]["name"] == "my frame.hex"
    got = _get(server, "/3/Frames/my%20frame.hex/summary")
    assert "x" in got["summary"]
    _delete(server, "/3/Frames/my%20frame.hex")
    assert "my frame.hex" not in rest.FRAMES
    rest.MODELS["enc model"] = type("M", (), {
        "algo": "gbm", "nclasses": 2, "scoring_history": [],
        "validation_metrics": None})()
    try:
        got = _get(server, "/3/Models/enc%20model")
        assert got["model_id"]["name"] == "enc model"
    finally:
        rest.MODELS.pop("enc model", None)
