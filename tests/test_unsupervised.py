"""KMeans / PCA / NaiveBayes / IsolationForest tests (SURVEY.md §2b C17),
known-answer checked against sklearn on small data (the reference's
accuracy-suite approach, SURVEY.md §4b)."""

import numpy as np
import pytest

import h2o_kubernetes_tpu as h2o
from h2o_kubernetes_tpu.models import (PCA, IsolationForest, KMeans,
                                       NaiveBayes)


def _blobs(n=400, seed=0):
    rng = np.random.default_rng(seed)
    c = rng.integers(0, 3, size=n)
    centers = np.array([[0, 0], [6, 0], [0, 6]], dtype=np.float32)
    X = centers[c] + rng.normal(scale=0.6, size=(n, 2)).astype(np.float32)
    return X, c


class TestKMeans:
    def test_recovers_blobs(self, mesh8):
        X, c = _blobs()
        fr = h2o.Frame.from_arrays({"x0": X[:, 0], "x1": X[:, 1]})
        m = KMeans(k=3, max_iterations=20, seed=1,
                   standardize=False).train(training_frame=fr)
        assert m.iterations <= 20
        pred = m.predict(fr)["predict"].to_numpy().astype(int)
        # each true blob maps to one distinct cluster
        maps = [np.bincount(pred[c == j], minlength=3).argmax()
                for j in range(3)]
        assert len(set(maps)) == 3
        acc = np.mean([maps[cj] == pj for cj, pj in zip(c, pred)])
        assert acc > 0.95
        # centers land near the true blob centers
        C = m.centers()
        got = sorted(np.round(C).tolist())
        assert sorted(np.round(np.array(
            [[0, 0], [6, 0], [0, 6]], dtype=float)).tolist()) == got

    def test_withinss_vs_sklearn(self, mesh8):
        from sklearn.cluster import KMeans as SK

        X, _ = _blobs(300, seed=2)
        fr = h2o.Frame.from_arrays({"a": X[:, 0], "b": X[:, 1]})
        m = KMeans(k=3, max_iterations=30, seed=3,
                   standardize=False).train(training_frame=fr)
        sk = SK(n_clusters=3, n_init=5, random_state=0).fit(X)
        assert m.tot_withinss < sk.inertia_ * 1.15

    def test_categorical_onehot(self, mesh8):
        rng = np.random.default_rng(4)
        g = np.array(["a", "b"])[rng.integers(0, 2, 200)]
        x = rng.normal(size=200).astype(np.float32)
        fr = h2o.Frame.from_arrays({"g": g, "x": x})
        m = KMeans(k=2, seed=0).train(training_frame=fr)
        assert m.predict(fr).nrows == 200


class TestPCA:
    def test_matches_sklearn(self, mesh8):
        from sklearn.decomposition import PCA as SK

        rng = np.random.default_rng(5)
        z = rng.normal(size=(500, 2)).astype(np.float32)
        A = np.array([[2.0, 0.3, 0.1], [0.1, 1.0, -0.5]], dtype=np.float32)
        X = z @ A
        fr = h2o.Frame.from_arrays({f"x{i}": X[:, i] for i in range(3)})
        m = PCA(k=2, transform="DEMEAN").train(training_frame=fr)
        sk = SK(n_components=2).fit(X)
        # eigenvalue spectrum matches
        np.testing.assert_allclose(np.asarray(m.eigenvalues),
                                   sk.explained_variance_, rtol=0.05)
        # loadings match up to sign
        V = np.asarray(m.eigenvectors)
        for j in range(2):
            dot = abs(float(V[:, j] @ sk.components_[j]))
            assert dot > 0.99
        scores = m.predict(fr)
        assert scores.names == ["PC1", "PC2"]

    def test_pve_sums_below_one(self, mesh8):
        rng = np.random.default_rng(6)
        X = rng.normal(size=(200, 4)).astype(np.float32)
        fr = h2o.Frame.from_arrays({f"x{i}": X[:, i] for i in range(4)})
        m = PCA(k=2, transform="STANDARDIZE").train(training_frame=fr)
        pve = m.pve()
        assert 0 < pve.sum() <= 1.0 + 1e-6


class TestNaiveBayes:
    def test_matches_sklearn_gaussian(self, mesh8):
        from sklearn.naive_bayes import GaussianNB

        rng = np.random.default_rng(7)
        n = 600
        c = rng.integers(0, 2, n)
        X = rng.normal(size=(n, 3)).astype(np.float32) + \
            c[:, None].astype(np.float32) * 1.5
        yl = np.array(["neg", "pos"])[c]
        fr = h2o.Frame.from_arrays({"x0": X[:, 0], "x1": X[:, 1],
                                    "x2": X[:, 2], "y": yl})
        m = NaiveBayes().train(y="y", training_frame=fr)
        sk = GaussianNB().fit(X, c)
        p = m.predict_raw(fr)[:, 1]
        psk = sk.predict_proba(X)[:, 1]
        assert np.corrcoef(p, psk)[0, 1] > 0.99
        assert ((p > 0.5) == c).mean() > 0.85

    def test_categorical_laplace(self, mesh8):
        rng = np.random.default_rng(8)
        n = 400
        c = rng.integers(0, 2, n)
        g = np.where(c == 1,
                     np.array(["u", "v"])[rng.integers(0, 2, n)],
                     np.array(["v", "w"])[rng.integers(0, 2, n)])
        fr = h2o.Frame.from_arrays({"g": g,
                                    "y": np.array(["a", "b"])[c]})
        m = NaiveBayes(laplace=1.0).train(y="y", training_frame=fr)
        acc = (m.predict_raw(fr).argmax(1) == c).mean()
        assert acc > 0.6

    def test_nb_with_cv(self, mesh8):
        rng = np.random.default_rng(9)
        n = 300
        x = rng.normal(size=n).astype(np.float32)
        yl = np.where(x + rng.normal(scale=0.5, size=n) > 0, "p", "n")
        fr = h2o.Frame.from_arrays({"x": x, "y": yl})
        m = NaiveBayes(nfolds=3).train(y="y", training_frame=fr)
        assert m.cross_validation_metrics()["auc"] > 0.8


class TestIsolationForest:
    def test_outliers_score_higher(self, mesh8):
        rng = np.random.default_rng(10)
        X = rng.normal(size=(500, 2)).astype(np.float32)
        out = np.array([[8, 8], [-9, 7], [10, -8]], dtype=np.float32)
        Xall = np.vstack([X, out])
        fr = h2o.Frame.from_arrays({"a": Xall[:, 0], "b": Xall[:, 1]})
        m = IsolationForest(ntrees=30, sample_size=128, seed=1).train(
            training_frame=fr)
        pred = m.predict(fr)
        s = pred["predict"].to_numpy()
        assert s[-3:].min() > np.median(s[:-3])
        # anomaly scores live in (0, 1]
        assert 0 < s.min() and s.max() <= 1.0
        # mean path length of outliers is shorter
        ln = pred["mean_length"].to_numpy()
        assert ln[-3:].max() < np.median(ln[:-3])
