import numpy as np
import pytest

from h2o_kubernetes_tpu import Frame
from h2o_kubernetes_tpu import metrics as M
from h2o_kubernetes_tpu.models import GBM


def _binary_data(n=4000, seed=0):
    rng = np.random.default_rng(seed)
    x1 = rng.normal(size=n)
    x2 = rng.normal(size=n)
    x3 = rng.integers(0, 4, size=n)
    logit = 1.5 * x1 - 2.0 * (x2 ** 2) + 1.2 * (x3 == 2) + \
        rng.normal(scale=0.3, size=n)
    y = (logit > 0).astype(int)
    fr = Frame.from_arrays({
        "x1": x1, "x2": x2,
        "x3": np.array(["a", "b", "c", "d"])[x3],
        "y": np.array(["no", "yes"])[y],
    })
    X = np.stack([x1, x2, x3.astype(float)], axis=1)
    return fr, X, y


def test_gbm_binary_auc_beats_sklearn_parity(mesh8):
    fr, X, y = _binary_data()
    m = GBM(ntrees=40, max_depth=4, learn_rate=0.2, seed=1).train(
        y="y", training_frame=fr)
    perf = m.model_performance(fr, "y")
    assert perf["auc"] > 0.97
    assert perf["logloss"] < 0.25

    from sklearn.ensemble import HistGradientBoostingClassifier
    sk = HistGradientBoostingClassifier(
        max_iter=40, max_depth=4, learning_rate=0.2,
        categorical_features=[2]).fit(X, y)
    sk_auc = M.roc_auc(y, sk.predict_proba(X)[:, 1])
    assert perf["auc"] > sk_auc - 0.01  # parity with sklearn hist-GBM


def test_gbm_regression(mesh8):
    rng = np.random.default_rng(3)
    n = 3000
    x1 = rng.normal(size=n)
    x2 = rng.uniform(-2, 2, size=n)
    y = 3.0 * x1 + np.sin(2 * x2) * 2 + rng.normal(scale=0.1, size=n)
    fr = Frame.from_arrays({"x1": x1, "x2": x2, "y": y})
    m = GBM(ntrees=60, max_depth=4, learn_rate=0.2, seed=2).train(
        y="y", training_frame=fr)
    perf = m.model_performance(fr, "y")
    assert perf["rmse"] < 0.4
    assert perf["r2"] > 0.97


def test_gbm_multinomial(mesh8):
    rng = np.random.default_rng(4)
    n = 3000
    x1 = rng.normal(size=n)
    x2 = rng.normal(size=n)
    cls = np.where(x1 + x2 > 0.7, 2, np.where(x1 - x2 > 0.3, 1, 0))
    fr = Frame.from_arrays({
        "x1": x1, "x2": x2,
        "y": np.array(["lo", "mid", "hi"])[cls]})
    m = GBM(ntrees=20, max_depth=4, learn_rate=0.3, seed=5).train(
        y="y", training_frame=fr)
    perf = m.model_performance(fr, "y")
    assert perf["accuracy"] > 0.93
    pred = m.predict(fr)
    assert set(pred.names) == {"predict", "plo", "pmid", "phi"}
    probs = np.stack([pred[c].to_numpy() for c in ("plo", "pmid", "phi")], 1)
    np.testing.assert_allclose(probs.sum(axis=1), 1.0, atol=1e-5)


def test_gbm_na_handling(mesh8):
    rng = np.random.default_rng(6)
    n = 3000
    x1 = rng.normal(size=n)
    # y depends on whether x1 is missing — the learned NA direction must
    # pick this up
    miss = rng.uniform(size=n) < 0.3
    y = np.where(miss, 1, (x1 > 0).astype(int))
    x1 = np.where(miss, np.nan, x1)
    fr = Frame.from_arrays({"x1": x1, "noise": rng.normal(size=n),
                            "y": np.array(["n", "p"])[y]})
    m = GBM(ntrees=20, max_depth=3, learn_rate=0.3, seed=7).train(
        y="y", training_frame=fr)
    assert m.model_performance(fr, "y")["auc"] > 0.98


def test_gbm_sampling_reproducible(mesh8):
    fr, X, y = _binary_data(n=2000, seed=8)
    kw = dict(ntrees=15, max_depth=3, sample_rate=0.7,
              col_sample_rate_per_tree=0.8, seed=42)
    a = GBM(**kw).train(y="y", training_frame=fr)
    b = GBM(**kw).train(y="y", training_frame=fr)
    np.testing.assert_array_equal(a.predict_raw(fr), b.predict_raw(fr))


def test_gbm_weights_column(mesh8):
    rng = np.random.default_rng(9)
    n = 2000
    x = rng.normal(size=n)
    y = (x > 0).astype(int)
    w = np.where(np.arange(n) < 1000, 1.0, 0.0)  # second half ignored
    y2 = y.copy()
    y2[1000:] = 1 - y2[1000:]  # corrupt ignored rows
    fr = Frame.from_arrays({"x": x, "w": w,
                            "y": np.array(["a", "b"])[y2]})
    m = GBM(ntrees=10, max_depth=2, seed=1).train(
        y="y", training_frame=fr, weights_column="w")
    sub = Frame.from_arrays({"x": x[:1000],
                             "y": np.array(["a", "b"])[y[:1000]]})
    assert m.model_performance(sub, "y")["auc"] > 0.99


def test_varimp_ranks_signal_over_noise(mesh8):
    rng = np.random.default_rng(10)
    n = 3000
    sig = rng.normal(size=n)
    noise = rng.normal(size=n)
    y = (sig > 0).astype(int)
    fr = Frame.from_arrays({"sig": sig, "noise": noise,
                            "y": np.array(["n", "p"])[y]})
    m = GBM(ntrees=10, max_depth=3, seed=2).train(y="y", training_frame=fr)
    vi = m.varimp()
    assert vi["sig"] == 1.0
    assert vi["noise"] < 0.05


def test_predict_remaps_enum_domains(mesh8):
    rng = np.random.default_rng(11)
    n = 3000
    c = np.array(["a", "b", "c", "d"])[rng.integers(0, 4, size=n)]
    y = np.where(np.isin(c, ["c", "d"]), "p", "n")  # y determined by c
    fr = Frame.from_arrays({"c": c, "noise": rng.normal(size=n), "y": y})
    m = GBM(ntrees=10, max_depth=2, seed=0).train(y="y", training_frame=fr)
    # scoring frame whose enum only contains b, d: local codes differ
    c2 = np.array(["b", "d"])[rng.integers(0, 2, size=200)]
    fr2 = Frame.from_arrays({"c": c2, "noise": rng.normal(size=200)})
    out = m.predict_raw(fr2)
    # all 'd' rows must score high, all 'b' rows low
    assert out[c2 == "d", 1].min() > 0.8
    assert out[c2 == "b", 1].max() < 0.2


def test_nbins_validation(mesh8):
    fr = Frame.from_arrays({"x": np.arange(100.0),
                            "y": np.arange(100.0)})
    with pytest.raises(ValueError, match="n_bins"):
        GBM(ntrees=2, nbins=512).train(y="y", training_frame=fr)


def test_scoring_history(mesh8):
    fr, X, y = _binary_data(n=2000, seed=12)
    m = GBM(ntrees=10, max_depth=3, score_every=5, seed=0).train(
        y="y", training_frame=fr)
    # @5 and @10; the final row IS the @10 row (no duplicate append)
    assert len(m.scoring_history) == 2
    assert [h["ntrees"] for h in m.scoring_history] == [5, 10]
    assert m.scoring_history[0]["train_logloss"] > \
        m.scoring_history[-1]["train_logloss"]


def test_time_feature_binning_consistent(mesh8):
    rng = np.random.default_rng(13)
    n = 2000
    base = np.datetime64("2026-01-01T00:00:00", "ms")
    offs = rng.integers(0, 90 * 86400_000, size=n)
    t = base + offs.astype("timedelta64[ms]")
    y = np.where(offs > 45 * 86400_000, "late", "early")  # split on time
    fr = Frame.from_arrays({"t": t, "y": y})
    m = GBM(ntrees=5, max_depth=2, seed=0).train(y="y", training_frame=fr)
    assert m.model_performance(fr, "y")["auc"] > 0.99


# -- round-2 distribution breadth (hex/genmodel DistributionFamily) ----------

def test_gbm_gamma_distribution(mesh8):
    rng = np.random.default_rng(31)
    n = 3000
    x = rng.normal(size=n)
    mu = np.exp(0.6 * x + 1.0)
    y = rng.gamma(shape=3.0, scale=mu / 3.0)
    fr = Frame.from_arrays({"x": x.astype(np.float32), "y": y})
    m = GBM(ntrees=40, max_depth=3, learn_rate=0.2,
            distribution="gamma", seed=1).train(y="y", training_frame=fr)
    pred = m.predict_raw(fr)
    assert np.all(np.asarray(pred)[:n] > 0)       # log link → positive
    corr = np.corrcoef(np.asarray(pred)[:n], mu)[0, 1]
    assert corr > 0.9, corr


def test_gbm_tweedie_distribution(mesh8):
    rng = np.random.default_rng(32)
    n = 3000
    x = rng.normal(size=n)
    mu = np.exp(0.5 * x)
    npois = rng.poisson(mu)
    y = np.array([rng.gamma(s, 1.0) if s > 0 else 0.0 for s in npois])
    fr = Frame.from_arrays({"x": x.astype(np.float32), "y": y})
    m = GBM(ntrees=40, max_depth=3, learn_rate=0.2,
            distribution="tweedie", seed=1).train(y="y",
                                                  training_frame=fr)
    pred = np.asarray(m.predict_raw(fr))[:n]
    assert np.all(pred > 0)
    assert np.corrcoef(pred, mu)[0, 1] > 0.8


def test_gbm_laplace_robust_to_outliers(mesh8):
    rng = np.random.default_rng(33)
    n = 3000
    x = rng.normal(size=n)
    y = 2.0 * x + rng.normal(scale=0.1, size=n)
    y[::50] += 100.0                              # gross outliers
    fr = Frame.from_arrays({"x": x.astype(np.float32),
                            "y": y.astype(np.float32)})
    m_l1 = GBM(ntrees=40, max_depth=3, learn_rate=0.3,
               distribution="laplace", seed=1).train(
        y="y", training_frame=fr)
    clean = np.ones(n, dtype=bool); clean[::50] = False
    pred = np.asarray(m_l1.predict_raw(fr))[:n]
    mae_clean = float(np.mean(np.abs(pred[clean] - y[clean])))
    assert mae_clean < 0.5, mae_clean             # outliers ignored


def test_gbm_laplace_large_scale_response(mesh8):
    # leaf steps are bounded by learn_rate, so without the internal
    # median/MAD scaling a y spanning thousands could never be fit
    rng = np.random.default_rng(34)
    n = 2000
    x = rng.normal(size=n)
    y = 1000.0 * x + rng.normal(scale=10.0, size=n)
    fr = Frame.from_arrays({"x": x.astype(np.float32),
                            "y": y.astype(np.float32)})
    m = GBM(ntrees=40, max_depth=3, learn_rate=0.3,
            distribution="laplace", seed=1).train(
        y="y", training_frame=fr)
    pred = np.asarray(m.predict_raw(fr))[:n]
    assert float(np.mean(np.abs(pred - y))) < 150.0
    assert pred.std() > 500.0             # predictions span the range


def test_gbm_gamma_rejects_nonpositive(mesh8):
    fr = Frame.from_arrays({"x": np.arange(10.0),
                            "y": np.arange(10.0) - 5.0})
    with pytest.raises(ValueError, match="positive"):
        GBM(distribution="gamma").train(y="y", training_frame=fr)


def test_gbm_laplace_zero_inflated_mad(mesh8):
    # 70% of y at exactly 0 → MAD = 0; the scale must fall back to std
    # instead of collapsing to 1e-8 (which froze predictions at 0)
    rng = np.random.default_rng(35)
    n = 2000
    y = np.where(rng.random(n) < 0.7, 0.0, rng.uniform(100, 1000, n))
    x = y + rng.normal(scale=20.0, size=n)
    fr = Frame.from_arrays({"x": x.astype(np.float32),
                            "y": y.astype(np.float32)})
    m = GBM(ntrees=30, max_depth=3, learn_rate=0.3,
            distribution="laplace", seed=1).train(
        y="y", training_frame=fr)
    pred = np.asarray(m.predict_raw(fr))[:n]
    assert pred.std() > 50.0


def test_zero_weight_frame_raises(mesh8):
    """All-zero effective weight (every response NA) must raise, not
    return a silently-NaN model."""
    fr = Frame.from_arrays(
        {"x": np.arange(64, dtype=np.float32),
         "y": np.full(64, np.nan, dtype=np.float32)})
    with pytest.raises(ValueError, match="positive weight"):
        GBM(ntrees=2, max_depth=2, seed=0).train(y="y", training_frame=fr)


def test_sampled_quantile_binning_parity(mesh8, monkeypatch):
    """Past _QUANTILE_SAMPLE rows fit_bins sketches edges from a fixed
    uniform sample (the reference's hist path also bins from
    approximate sketches). Forced onto the sampled path, edges must
    stay monotone and the model must match the exact-edge model's AUC
    to within noise."""
    from h2o_kubernetes_tpu.models.tree import binning as B

    fr, _, _ = _binary_data(n=6000, seed=9)
    m_exact = GBM(ntrees=5, max_depth=4, seed=1).train(
        y="y", training_frame=fr)
    auc_exact = m_exact.scoring_history[-1]["train_auc"]

    monkeypatch.setattr(B, "_QUANTILE_SAMPLE", 1024)
    B._device_quantiles.clear_cache()
    try:
        spec = B.fit_bins(fr, ["x1", "x2", "x3"], n_bins=64)
        edges = np.asarray(spec.edges_matrix())[0]
        finite = edges[np.isfinite(edges)]
        assert len(finite) > 10
        assert np.all(np.diff(finite) >= 0), "sampled edges not sorted"
        m_s = GBM(ntrees=5, max_depth=4, seed=1).train(
            y="y", training_frame=fr)
        auc_s = m_s.scoring_history[-1]["train_auc"]
        assert abs(auc_s - auc_exact) < 0.02, (auc_s, auc_exact)
    finally:
        B._device_quantiles.clear_cache()
