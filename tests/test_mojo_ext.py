"""MOJO coverage for the round-3 additions: StackedEnsemble (the
AutoML-leader case), CoxPH, GLRM, TargetEncoder (reference:
h2o-genmodel writers cover every algo — SURVEY.md §2b C18)."""

import numpy as np
import pytest

import h2o_kubernetes_tpu as h2o
from h2o_kubernetes_tpu.models import GBM, GLM, CoxPH, GLRM, StackedEnsemble
from h2o_kubernetes_tpu.models.targetencoder import TargetEncoder


def _frame(n=400, seed=21):
    rng = np.random.default_rng(seed)
    x0 = rng.normal(size=n).astype(np.float32)
    x1 = rng.normal(size=n).astype(np.float32)
    x0[::31] = np.nan
    g = np.array(["u", "v", "w"])[rng.integers(0, 3, n)]
    y = np.where(x1 + (g == "u") + rng.normal(scale=0.4, size=n) > 0,
                 "p", "n")
    return h2o.Frame.from_arrays({"x0": x0, "x1": x1, "g": g, "y": y})


@pytest.mark.slow
def test_stackedensemble_mojo_matches(tmp_path, mesh8):
    fr = _frame(500, seed=3)
    common = dict(nfolds=3, fold_assignment="modulo",
                  keep_cross_validation_predictions=True)
    base = [GBM(ntrees=5, max_depth=3, seed=1, **common).train(
                y="y", training_frame=fr),
            GLM(family="binomial", **common).train(
                y="y", training_frame=fr)]
    se = StackedEnsemble(base_models=base).train(y="y", training_frame=fr)
    p = str(tmp_path / "se.mojo")
    h2o.export_mojo(se, p)
    mj = h2o.import_mojo(p)
    got = mj.predict(fr)
    want = np.asarray(se.predict_raw(fr))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-6)


@pytest.mark.slow
def test_automl_leader_mojo_matches(tmp_path, mesh8):
    """The flagship serve-the-leaderboard flow: AutoML end-to-end, the
    leader (often a StackedEnsemble) exports and scores identically."""
    fr = _frame(400, seed=5)
    aml = h2o.AutoML(max_models=3, nfolds=3, seed=0)
    aml.train(y="y", training_frame=fr)
    p = str(tmp_path / "leader.mojo")
    h2o.export_mojo(aml.leader, p)
    mj = h2o.import_mojo(p)
    got = mj.predict(fr)
    want = np.asarray(aml.leader.predict_raw(fr))
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-6)


def test_coxph_mojo_matches(tmp_path, mesh8):
    rng = np.random.default_rng(11)
    n = 300
    x0 = rng.normal(size=n).astype(np.float32)
    g = np.array(["a", "b"])[rng.integers(0, 2, n)]
    t = rng.exponential(np.exp(-0.5 * x0)).astype(np.float32) + 0.01
    e = (rng.uniform(size=n) < 0.7).astype(np.float32)
    fr = h2o.Frame.from_arrays({"x0": x0, "g": g, "stop": t, "event": e})
    m = CoxPH(stop_column="stop", event_column="event").train(
        training_frame=fr)
    p = str(tmp_path / "cox.mojo")
    h2o.export_mojo(m, p)
    mj = h2o.import_mojo(p)
    got = mj.predict(fr)
    want = np.asarray(m.predict_raw(fr))[: fr.nrows]
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_glrm_mojo_matches(tmp_path, mesh8):
    rng = np.random.default_rng(13)
    n = 200
    base = rng.normal(size=(n, 2)).astype(np.float32)
    cols = {f"c{i}": (base @ rng.normal(size=2) +
                      0.05 * rng.normal(size=n)).astype(np.float32)
            for i in range(4)}
    cols["c0"][::17] = np.nan        # missing cells drop from the loss
    fr = h2o.Frame.from_arrays(cols)
    m = GLRM(k=2, max_iterations=50, seed=1).train(training_frame=fr)
    p = str(tmp_path / "glrm.mojo")
    h2o.export_mojo(m, p)
    mj = h2o.import_mojo(p)
    got = mj.predict(fr)
    want = np.asarray(m.predict_raw(fr))[: fr.nrows]
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=1e-4)
    rec = mj.reconstruct(fr)
    want_rec = m.reconstruct(fr)
    for name in rec:
        np.testing.assert_allclose(
            rec[name], want_rec[name].to_numpy(), rtol=1e-3, atol=1e-4)


def test_targetencoder_mojo_transform(tmp_path, mesh8):
    rng = np.random.default_rng(17)
    n = 500
    g = np.array(["a", "b", "c", "d"])[rng.integers(0, 4, n)]
    y = (rng.uniform(size=n) < (0.2 + 0.15 * (g == "a"))).astype(
        np.float32)
    fr = h2o.Frame.from_arrays({"g": g, "y": y})
    te = TargetEncoder(blending=True, inflection_point=5.0,
                       smoothing=10.0).train(y="y", training_frame=fr,
                                             x=["g"])
    p = str(tmp_path / "te.mojo")
    h2o.export_mojo(te, p)
    mj = h2o.import_mojo(p)
    got = mj.transform(fr)["g_te"]
    want = te.transform(fr, as_training=False).vec("g_te").to_numpy()
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    # dict input with an unseen level falls back to the prior
    got2 = mj.transform({"g": np.array(["a", "zzz"], dtype=object)})
    assert abs(got2["g_te"][1] - mj.meta["prior"]) < 1e-6
