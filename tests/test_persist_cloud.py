"""Cloud persist backends (s3:// gs:// hdfs://) against local fake
servers — no network, no SDKs (reference: water/persist/{PersistS3,
PersistGcs,PersistHdfs}, SURVEY.md §2b C20)."""

import os
import threading
from http.server import BaseHTTPRequestHandler, HTTPServer

import numpy as np
import pytest

import h2o_kubernetes_tpu as h2o
from h2o_kubernetes_tpu.models import GBM


def _server_side_sigv4(method: str, path_qs: str, headers,
                       payload: bytes, secret: str) -> str | None:
    """Recompute the SigV4 signature from the request AS THE SERVER SAW
    IT (the verification minio/localstack perform), written from the
    AWS spec: canonical request -> string-to-sign -> signing key chain.
    Returns the expected hex signature, or None if unsigned."""
    import hashlib
    import hmac as hm

    auth = headers.get("Authorization")
    if not auth or not auth.startswith("AWS4-HMAC-SHA256"):
        return None
    cred = auth.split("Credential=")[1].split(",")[0]
    signed = auth.split("SignedHeaders=")[1].split(",")[0]
    _akid, datestamp, region, service, _term = cred.split("/")
    path = path_qs.split("?", 1)[0]
    query = path_qs.split("?", 1)[1] if "?" in path_qs else ""
    canon_headers = "".join(
        f"{h}:{headers.get(h).strip()}\n" for h in signed.split(";"))
    payload_hash = hashlib.sha256(payload).hexdigest()
    canonical = "\n".join([method, path, query, canon_headers, signed,
                           payload_hash])
    amz_date = headers["x-amz-date"]
    scope = f"{datestamp}/{region}/{service}/aws4_request"
    to_sign = "\n".join([
        "AWS4-HMAC-SHA256", amz_date, scope,
        hashlib.sha256(canonical.encode()).hexdigest()])

    def _k(key, msg):
        return hm.new(key, msg.encode(), hashlib.sha256).digest()

    k = _k(_k(_k(_k(b"AWS4" + secret.encode(), datestamp), region),
               service), "aws4_request")
    return hm.new(k, to_sign.encode(), hashlib.sha256).hexdigest()


class _FakeStore(BaseHTTPRequestHandler):
    """One handler serves all three protocols: plain GET/PUT object
    paths (S3 path-style + WebHDFS), and the GCS JSON media endpoints.
    Signed S3 requests are VERIFIED server-side (recomputed signature
    must match) — a signer defect 403s here like it would on minio."""

    store: dict[str, bytes] = {}
    auth_headers: list[dict] = []
    sigv4_checked: int = 0

    def log_message(self, *a):        # silence test output
        pass

    def _verify_sig(self, payload: bytes) -> bool:
        auth = self.headers.get("Authorization", "")
        if not auth.startswith("AWS4-HMAC-SHA256"):
            return True          # unsigned (gs/hdfs/anonymous) is fine
        expect = _server_side_sigv4(self.command, self.path,
                                    self.headers, payload, "secret")
        got = auth.split("Signature=")[1]
        type(self).sigv4_checked += 1
        return expect == got

    def _key(self) -> str:
        path = self.path.split("?", 1)[0]
        if path.startswith("/upload/storage/v1/b/"):      # GCS upload
            from urllib.parse import parse_qs, urlparse

            q = parse_qs(urlparse(self.path).query)
            bucket = path.split("/")[5]
            return f"/{bucket}/{q['name'][0]}"
        if path.startswith("/storage/v1/b/"):             # GCS download
            parts = path.split("/")
            from urllib.parse import unquote

            return f"/{parts[4]}/{unquote(parts[6])}"
        return path                                        # S3 / WebHDFS

    def do_GET(self):
        self.auth_headers.append(dict(self.headers))
        if not self._verify_sig(b""):
            self.send_response(403)
            self.end_headers()
            return
        key = self._key()
        if key not in self.store:
            self.send_response(404)
            self.end_headers()
            return
        body = self.store[key]
        self.send_response(200)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_PUT(self):
        self.auth_headers.append(dict(self.headers))
        n = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(n)
        if not self._verify_sig(body):
            self.send_response(403)
            self.end_headers()
            return
        self.store[self._key()] = body
        self.send_response(200)
        self.end_headers()

    do_POST = do_PUT


@pytest.fixture()
def fake_store():
    _FakeStore.store = {}
    _FakeStore.auth_headers = []
    _FakeStore.sigv4_checked = 0
    srv = HTTPServer(("127.0.0.1", 0), _FakeStore)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    url = f"http://127.0.0.1:{srv.server_port}"
    saved = {k: os.environ.get(k) for k in
             ("AWS_ENDPOINT_URL", "STORAGE_EMULATOR_HOST",
              "H2O_TPU_WEBHDFS", "AWS_ACCESS_KEY_ID",
              "AWS_SECRET_ACCESS_KEY")}
    os.environ["AWS_ENDPOINT_URL"] = url
    os.environ["STORAGE_EMULATOR_HOST"] = url
    os.environ["H2O_TPU_WEBHDFS"] = url
    os.environ["AWS_ACCESS_KEY_ID"] = "AKIDEXAMPLE"
    os.environ["AWS_SECRET_ACCESS_KEY"] = "secret"
    yield url
    for k, v in saved.items():
        os.environ.pop(k, None)
        if v is not None:
            os.environ[k] = v
    srv.shutdown()


def _frame(n=200, seed=4):
    rng = np.random.default_rng(seed)
    x = rng.normal(size=n).astype(np.float32)
    y = np.where(x + rng.normal(scale=0.3, size=n) > 0, "p", "n")
    return h2o.Frame.from_arrays({"x": x, "y": y})


@pytest.mark.parametrize("scheme,prefix", [
    ("s3", "s3://bkt/dir/frame.h2f"),
    ("gs", "gs://bkt/dir/frame.h2f"),
    ("hdfs", "hdfs:///dir/frame.h2f"),
])
def test_frame_roundtrip(fake_store, mesh8, scheme, prefix):
    fr = _frame()
    h2o.save_frame(fr, prefix)
    fr2 = h2o.load_frame(prefix)
    np.testing.assert_allclose(fr["x"].to_numpy(), fr2["x"].to_numpy())
    assert fr2["y"].domain == fr["y"].domain


def test_s3_requests_are_sigv4_signed(fake_store, mesh8):
    fr = _frame(50)
    h2o.export_file(fr, "s3://bkt/export.csv")
    auth = [h for h in _FakeStore.auth_headers
            if "Authorization" in h or "authorization" in h]
    assert auth, "S3 write sent no Authorization header"
    a = auth[-1].get("Authorization", auth[-1].get("authorization"))
    assert a.startswith("AWS4-HMAC-SHA256 Credential=AKIDEXAMPLE/")
    assert "Signature=" in a
    # the fake 403s on signature mismatch, so landing in the store means
    # the server-side recomputation verified the signature
    assert _FakeStore.sigv4_checked > 0
    body = _FakeStore.store["/bkt/export.csv"].decode()
    assert body.splitlines()[0] == "x,y"


def test_model_roundtrip_s3(fake_store, mesh8, tmp_path):
    fr = _frame()
    m = GBM(ntrees=3, max_depth=3, seed=1).train(y="y", training_frame=fr)
    path = h2o.save_model(m, "s3://bkt/models/gbm.model")
    m2 = h2o.load_model(path)
    np.testing.assert_allclose(np.asarray(m.predict_raw(fr)),
                               np.asarray(m2.predict_raw(fr)), rtol=1e-6)


def test_gs_object_names_with_slashes(fake_store, mesh8):
    fr = _frame(30)
    h2o.export_file(fr, "gs://bkt/a/b/c.csv")
    # GCS JSON API carries the full object name (slash-encoded) — the
    # fake decodes it back, so the key keeps its path shape
    assert "/bkt/a/b/c.csv" in _FakeStore.store
    got = h2o.persist._read_bytes("gs://bkt/a/b/c.csv")
    assert got == _FakeStore.store["/bkt/a/b/c.csv"]


def test_missing_object_raises(fake_store, mesh8):
    with pytest.raises(IOError):
        h2o.load_frame("s3://bkt/nope.h2f")


@pytest.mark.slow
def test_automl_checkpoint_dir_on_s3(fake_store, mesh8):
    """Mid-run resume manifest lives on the object store: first run
    populates it, second run resumes from it without retraining."""
    fr = _frame(300, seed=9)
    aml = h2o.AutoML(max_models=2, nfolds=3, seed=0,
                     checkpoint_dir="s3://bkt/run1")
    aml.train(y="y", training_frame=fr)
    assert "/bkt/run1/automl_manifest.json" in _FakeStore.store
    import json

    manifest = json.loads(_FakeStore.store["/bkt/run1/automl_manifest.json"])
    assert manifest, "manifest is empty"
    aml2 = h2o.AutoML(max_models=2, nfolds=3, seed=0,
                      checkpoint_dir="s3://bkt/run1")
    aml2.train(y="y", training_frame=fr)
    assert aml2.leaderboard is not None
    assert len(aml2.leaderboard.rows) >= len(manifest)


def test_hdfs_create_follows_307_redirect(mesh8):
    """A real namenode 307-redirects CREATE to a datanode URL; the
    write must do the two-step PUT dance explicitly (urllib refuses to
    follow redirects for PUT)."""

    class _NameNode(BaseHTTPRequestHandler):
        store: dict[str, bytes] = {}

        def log_message(self, *a):
            pass

        def do_PUT(self):
            path = self.path.split("?", 1)[0]
            if "dn=1" not in self.path:            # namenode: redirect
                self.send_response(307)
                self.send_header(
                    "Location",
                    f"http://127.0.0.1:{self.server.server_port}"
                    f"{self.path}&dn=1")
                self.end_headers()
                return
            n = int(self.headers.get("Content-Length", 0))
            self.store[path] = self.rfile.read(n)   # datanode: accept
            self.send_response(201)
            self.end_headers()

        def do_GET(self):
            path = self.path.split("?", 1)[0]
            body = self.store.get(path, b"")
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    srv = HTTPServer(("127.0.0.1", 0), _NameNode)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    old = os.environ.get("H2O_TPU_WEBHDFS")
    os.environ["H2O_TPU_WEBHDFS"] = f"http://127.0.0.1:{srv.server_port}"
    try:
        h2o.persist.write_bytes("hdfs:///data/x.bin", b"payload")
        assert _NameNode.store["/webhdfs/v1/data/x.bin"] == b"payload"
        assert h2o.persist.read_bytes("hdfs:///data/x.bin") == b"payload"
    finally:
        os.environ.pop("H2O_TPU_WEBHDFS", None)
        if old is not None:
            os.environ["H2O_TPU_WEBHDFS"] = old
        srv.shutdown()


def test_hdfs_needs_namenode(mesh8):
    old = os.environ.pop("H2O_TPU_WEBHDFS", None)
    try:
        with pytest.raises(ValueError, match="H2O_TPU_WEBHDFS"):
            h2o.persist._read_bytes("hdfs:///x")
    finally:
        if old is not None:
            os.environ["H2O_TPU_WEBHDFS"] = old
