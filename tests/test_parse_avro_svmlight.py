"""Avro + SVMLight ingest round-trips (h2o-parsers analogs [U3]).

The Avro files are written by an inline stdlib encoder (zig-zag varints
+ container framing) so the reader is exercised against independently
constructed bytes, not its own output.
"""

import json
import struct
import zlib

import numpy as np
import pytest

from h2o_kubernetes_tpu.frame.parse import import_file


# -- minimal avro writer ------------------------------------------------------

def _zz(n: int) -> bytes:
    u = (n << 1) ^ (n >> 63)
    out = bytearray()
    while True:
        b = u & 0x7F
        u >>= 7
        if u:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _avro_str(s: str) -> bytes:
    b = s.encode()
    return _zz(len(b)) + b


def _write_avro(path, schema: dict, rows: list[dict], codec="null"):
    body = bytearray()
    for rec in rows:
        for fld in schema["fields"]:
            body += _encode_value(fld["type"], rec[fld["name"]])
    blk = bytes(body)
    if codec == "deflate":
        c = zlib.compressobj(wbits=-15)
        blk = c.compress(blk) + c.flush()
    sync = b"S" * 16
    meta = {"avro.schema": json.dumps(schema).encode(),
            "avro.codec": codec.encode()}
    out = bytearray(b"Obj\x01")
    out += _zz(len(meta))
    for k, v in meta.items():
        out += _avro_str(k) + _zz(len(v)) + v
    out += _zz(0)
    out += sync
    out += _zz(len(rows)) + _zz(len(blk)) + blk + sync
    with open(path, "wb") as f:
        f.write(out)


def _encode_value(ftype, v) -> bytes:
    if isinstance(ftype, list):                      # nullable union
        if v is None:
            return _zz(ftype.index("null"))
        branch = [b for b in ftype if b != "null"][0]
        return _zz(ftype.index(branch)) + _encode_value(branch, v)
    if isinstance(ftype, dict):
        if ftype["type"] == "enum":
            return _zz(ftype["symbols"].index(v))
        if ftype.get("logicalType"):
            return _zz(int(v))
        return _encode_value(ftype["type"], v)
    if ftype in ("int", "long"):
        return _zz(int(v))
    if ftype == "double":
        return struct.pack("<d", v)
    if ftype == "float":
        return struct.pack("<f", v)
    if ftype == "boolean":
        return b"\x01" if v else b"\x00"
    if ftype == "string":
        return _avro_str(v)
    raise AssertionError(ftype)


_SCHEMA = {
    "type": "record", "name": "r", "fields": [
        {"name": "xd", "type": "double"},
        {"name": "xi", "type": "long"},
        {"name": "flag", "type": "boolean"},
        {"name": "cat", "type": {"type": "enum", "name": "c",
                                 "symbols": ["low", "mid", "high"]}},
        {"name": "s", "type": "string"},
        {"name": "maybe", "type": ["null", "double"]},
        {"name": "ts", "type": {"type": "long",
                                "logicalType": "timestamp-millis"}},
    ]}


def _rows(n=50, seed=0):
    rng = np.random.default_rng(seed)
    syms = ["low", "mid", "high"]
    return [{"xd": float(rng.normal()),
             "xi": int(rng.integers(-5, 100)),
             "flag": bool(rng.integers(0, 2)),
             "cat": syms[int(rng.integers(0, 3))],
             "s": f"tok{int(rng.integers(0, 4))}",
             "maybe": None if i % 7 == 0 else float(i),
             "ts": 1_700_000_000_000 + i * 1000}
            for i in range(n)]


@pytest.mark.parametrize("codec", ["null", "deflate"])
def test_avro_roundtrip(tmp_path, codec, mesh8):
    rows = _rows()
    p = tmp_path / "t.avro"
    _write_avro(p, _SCHEMA, rows, codec=codec)
    fr = import_file(str(p))
    assert fr.nrows == len(rows)
    assert fr.names == ["xd", "xi", "flag", "cat", "s", "maybe", "ts"]
    np.testing.assert_allclose(
        np.asarray(fr.vec("xd").as_float())[: fr.nrows],
        [r["xd"] for r in rows], rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(fr.vec("xi").as_float())[: fr.nrows],
        [r["xi"] for r in rows])
    np.testing.assert_allclose(
        np.asarray(fr.vec("flag").as_float())[: fr.nrows],
        [float(r["flag"]) for r in rows])
    v = fr.vec("cat")
    assert v.is_enum() and v.domain == ["low", "mid", "high"]
    got = [v.domain[c] for c in v.to_numpy()[: fr.nrows]]
    assert got == [r["cat"] for r in rows]
    # nullable union: None -> NA
    m = np.asarray(fr.vec("maybe").as_float())[: fr.nrows]
    for i, r in enumerate(rows):
        if r["maybe"] is None:
            assert np.isnan(m[i])
        else:
            assert m[i] == r["maybe"]
    assert fr.vec("ts").kind == "time"


def test_avro_multifile_and_schema_mismatch(tmp_path, mesh8):
    _write_avro(tmp_path / "a1.avro", _SCHEMA, _rows(20, seed=1))
    _write_avro(tmp_path / "a2.avro", _SCHEMA, _rows(30, seed=2))
    fr = import_file(str(tmp_path / "a*.avro"))
    assert fr.nrows == 50
    other = dict(_SCHEMA)
    other["fields"] = _SCHEMA["fields"][:3]
    _write_avro(tmp_path / "b1.avro", _SCHEMA, _rows(5))
    _write_avro(tmp_path / "b2.avro", other,
                [{k: r[k] for k in ("xd", "xi", "flag")}
                 for r in _rows(5)])
    with pytest.raises(ValueError, match="schema differs"):
        import_file([str(tmp_path / "b1.avro"),
                     str(tmp_path / "b2.avro")])


def test_svmlight_roundtrip(tmp_path, mesh8):
    p = tmp_path / "t.svm"
    p.write_text(
        "1 1:0.5 3:2.0 # trailing comment\n"
        "0 2:1.5\n"
        "-1 1:-1.0 2:0.25 3:3.5\n"
        "\n")
    fr = import_file(str(p))
    assert fr.names == ["C1", "C2", "C3", "C4"]
    assert fr.nrows == 3
    lab = np.asarray(fr.vec("C1").as_float())[:3]
    np.testing.assert_allclose(lab, [1, 0, -1])
    X = np.stack([np.asarray(fr.vec(f"C{j}").as_float())[:3]
                  for j in (2, 3, 4)], axis=1)
    want = np.array([[0.5, 0.0, 2.0],
                     [0.0, 1.5, 0.0],
                     [-1.0, 0.25, 3.5]])
    np.testing.assert_allclose(X, want)   # absent entries are 0, not NA


def test_svmlight_qid_and_sniff(tmp_path, mesh8):
    # extension-free file must be detected by content, qid kept
    p = tmp_path / "ranktrain"
    p.write_text("2 qid:1 1:1.0\n1 qid:1 2:2.0\n0 qid:2 1:0.5 2:0.5\n")
    fr = import_file(str(p))
    assert "qid" in fr.names
    np.testing.assert_allclose(
        np.asarray(fr.vec("qid").as_float())[:3], [1, 1, 2])


def test_svmlight_rejects_disorder(tmp_path, mesh8):
    p = tmp_path / "bad.svm"
    p.write_text("1 3:1.0 2:0.5\n")
    with pytest.raises(ValueError, match="non-increasing"):
        import_file(str(p))


def test_avro_rejects_type_mismatch_across_files(tmp_path, mesh8):
    # same field NAMES but different types: decoding file2 with file1's
    # schema would read varints as doubles — must refuse
    s1 = {"type": "record", "name": "r",
          "fields": [{"name": "x", "type": "long"}]}
    s2 = {"type": "record", "name": "r",
          "fields": [{"name": "x", "type": "double"}]}
    _write_avro(tmp_path / "c1.avro", s1, [{"x": 1}, {"x": 2}])
    _write_avro(tmp_path / "c2.avro", s2, [{"x": 1.5}])
    with pytest.raises(ValueError, match="schema differs"):
        import_file([str(tmp_path / "c1.avro"),
                     str(tmp_path / "c2.avro")])


def test_avro_truncated_file_errors_cleanly(tmp_path, mesh8):
    p = tmp_path / "t.avro"
    _write_avro(p, _SCHEMA, _rows(10))
    blob = p.read_bytes()
    p.write_bytes(blob[: len(blob) - 7])    # chop mid-block
    with pytest.raises(ValueError, match="truncated|sync"):
        import_file(str(p))


def test_svmlight_dense_budget(tmp_path, monkeypatch, mesh8):
    p = tmp_path / "wide.svm"
    p.write_text("1 1:1.0 1000000:2.0\n")
    monkeypatch.setenv("H2O_TPU_SVMLIGHT_DENSE_BUDGET", "1000")
    with pytest.raises(ValueError, match="densify"):
        import_file(str(p))


def test_svmlight_sniff_does_not_eat_csv(tmp_path, mesh8):
    # a CSV with colon-bearing strings must stay CSV
    p = tmp_path / "t.csv"
    p.write_text("a,b\n1,x:1\n2,y:2\n")
    fr = import_file(str(p))
    assert fr.names == ["a", "b"]
    assert fr.vec("b").is_enum()
    # space-separated count + clock-time rows LOOK like one-pair
    # svmlight lines; the sniff requires a >= 2-pair line, so this
    # stays CSV (an extensionless real 1-pair file needs .svm)
    p2 = tmp_path / "times"
    p2.write_text("3 08:30\n4 09:15\n5 10:45\n")
    fr2 = import_file(str(p2))
    assert "qid" not in fr2.names
    assert len(fr2.names) == 2


def test_avro_nullable_string_keeps_empty_level(tmp_path, mesh8):
    # union [null, string] with BOTH None and genuine "" values: ""
    # must stay a level, None must be NA
    schema = {"type": "record", "name": "r", "fields": [
        {"name": "s", "type": ["null", "string"]}]}
    rows = [{"s": "a"}, {"s": ""}, {"s": None}, {"s": ""}, {"s": "b"}]
    _write_avro(tmp_path / "n.avro", schema, rows)
    fr = import_file(str(tmp_path / "n.avro"))
    v = fr.vec("s")
    assert v.domain == ["", "a", "b"]
    codes = v.to_numpy()[:5]
    assert codes[2] == -1                       # None -> NA
    assert codes[1] == 0 and codes[3] == 0      # "" is a real level


def test_offset_cannot_also_be_feature(tmp_path, mesh8):
    import numpy as np

    from h2o_kubernetes_tpu import Frame
    from h2o_kubernetes_tpu.models import GBM

    rng = np.random.default_rng(0)
    n = 200
    fr = Frame.from_arrays({"x": rng.normal(size=n),
                            "off": rng.normal(size=n),
                            "y": rng.normal(size=n)})
    with pytest.raises(ValueError, match="cannot also be features"):
        GBM(ntrees=2).train(y="y", training_frame=fr,
                            x=["x", "off"], offset_column="off")
