"""Cross-validation infrastructure tests (reference: ModelBuilder CV,
SURVEY.md §2b C16 — fold assignment, holdout predictions, CV metrics)."""

import numpy as np
import pytest

import h2o_kubernetes_tpu as h2o
from h2o_kubernetes_tpu.models import GBM, GLM
from h2o_kubernetes_tpu.models.cv import fold_ids


def _binary_frame(n=600, seed=3):
    rng = np.random.default_rng(seed)
    x0 = rng.normal(size=n).astype(np.float32)
    x1 = rng.normal(size=n).astype(np.float32)
    y = np.where(x0 + 0.5 * x1 + rng.normal(scale=0.3, size=n) > 0,
                 "yes", "no")
    return h2o.Frame.from_arrays({"x0": x0, "x1": x1, "y": y})


class TestFoldIds:
    def test_modulo(self):
        f = fold_ids(10, 3, "modulo")
        assert list(f[:6]) == [0, 1, 2, 0, 1, 2]

    def test_random_covers_all_folds(self):
        f = fold_ids(1000, 5, "random", seed=1)
        assert set(f) == {0, 1, 2, 3, 4}

    def test_stratified_balances_classes(self):
        y = np.array([0] * 90 + [1] * 10)
        f = fold_ids(100, 5, "stratified", y=y, seed=1)
        # every fold gets exactly 2 of the rare class and 18 of the common
        for k in range(5):
            assert (y[f == k] == 1).sum() == 2
            assert (y[f == k] == 0).sum() == 18


class TestGBMCV:
    def test_nfolds_attaches_cv(self, mesh8):
        fr = _binary_frame()
        m = GBM(ntrees=5, max_depth=3, nfolds=3, seed=7,
                fold_assignment="modulo").train(y="y", training_frame=fr)
        assert m.cv is not None
        assert len(m.cross_validation_models()) == 3
        preds = m.cross_validation_holdout_predictions()
        assert preds.shape == (fr.nrows, 2)
        # every row was predicted by exactly one holdout model
        assert (preds.sum(axis=1) > 0.99).all()
        cvm = m.cross_validation_metrics()
        assert cvm["auc"] > 0.8
        summ = m.cross_validation_metrics_summary()
        assert set(summ) >= {"auc", "logloss"}
        assert summ["auc"]["std"] >= 0.0

    def test_fold_column(self, mesh8):
        fr = _binary_frame()
        folds = (np.arange(fr.nrows) % 4).astype(np.float32)
        fr["fold"] = h2o.Vec.from_numpy(folds)
        m = GBM(ntrees=4, max_depth=3, fold_column="fold", seed=1).train(
            y="y", training_frame=fr)
        assert len(m.cross_validation_models()) == 4
        # fold column must not be used as a feature
        assert "fold" not in m.feature_names

    def test_validation_frame(self, mesh8):
        fr = _binary_frame()
        tr, va = fr.split_frame(ratios=[0.8], seed=5)
        m = GBM(ntrees=5, max_depth=3, seed=1).train(
            y="y", training_frame=tr, validation_frame=va)
        assert m.validation_metrics is not None
        assert m.validation_metrics["auc"] > 0.7


class TestGLMCV:
    def test_glm_cv_binomial(self, mesh8):
        fr = _binary_frame()
        m = GLM(family="binomial", nfolds=3, seed=2).train(
            y="y", training_frame=fr)
        assert len(m.cross_validation_models()) == 3
        assert m.cross_validation_metrics()["auc"] > 0.8

    def test_stratified_needs_enum(self, mesh8):
        rng = np.random.default_rng(0)
        fr = h2o.Frame.from_arrays({
            "x0": rng.normal(size=100).astype(np.float32),
            "y": rng.normal(size=100).astype(np.float32)})
        with pytest.raises(ValueError, match="stratified"):
            GLM(family="gaussian", nfolds=3,
                fold_assignment="stratified").train(
                y="y", training_frame=fr)


def test_shape_shared_cv_matches_classic(mesh8, monkeypatch):
    """The weights-masked (shape-shared) fold path must produce CV
    metrics equivalent to the classic sliced-frame path: same fold
    assignment, same holdout rows, w=0 masking instead of slicing.
    Small quantile-edge differences (bins fit on all rows vs the
    fold's rows) may move individual predictions slightly — the
    combined AUC must agree closely."""
    from h2o_kubernetes_tpu.models import GBM

    fr = _binary_frame()
    monkeypatch.setenv("H2O_TPU_CV_SHAPE_SHARE_ROWS", "0")
    classic = GBM(ntrees=5, max_depth=3, seed=3, nfolds=3,
                  fold_assignment="modulo").train(
        y="y", training_frame=fr)
    monkeypatch.setenv("H2O_TPU_CV_SHAPE_SHARE_ROWS", "1000000")
    shared = GBM(ntrees=5, max_depth=3, seed=3, nfolds=3,
                 fold_assignment="modulo").train(
        y="y", training_frame=fr)
    a = classic.cross_validation_metrics()["auc"]
    b = shared.cross_validation_metrics()["auc"]
    assert abs(a - b) < 0.02, (a, b)
    # every fold model trained (and holdout rows were truly held out:
    # metrics are not training-resubstitution numbers)
    assert len(shared.cross_validation_models()) == 3
